"""Propagation-plane smoke: record the geo scenario, fit, and gate.

The local/CI acceptance harness for the propagation-topology plane
(docs/OBSERVABILITY.md "Propagation plane"): runs the fixed-seed
WAN/geo churned scenario with the propagation observables on, derives
the ``corro-epidemic/1`` report, asserts the hard identities —

- on-device accounting reconciles (link mass == msgs, rumor mass ==
  first deliveries, useful + dup == msgs),
- the SI fit stands with a positive spread exponent bounded above by
  the push-gossip theory beta = ln(1 + F),

— and, when a committed ``EPIDEMIC_BASELINE.json`` exists next to the
repo root, diffs the fresh report against it at the CI tolerance. Exit
0 = all green; 1 = a broken identity, a failed fit, or a baseline
regression.

Modes (docs/PERFORMANCE.md "Adaptive dissemination"):

- default: legacy push-only run, gated against EPIDEMIC_BASELINE.json.
- ``--adaptive``: same scenario with the adaptive-dissemination plane
  on (``health.ADAPTIVE_GOSSIP``), gated against
  EPIDEMIC_BASELINE_ADAPTIVE.json.
- ``--compare``: BOTH runs back to back, additionally gated against
  the ``dissemination`` entry of ``bench_budget.json`` — the adaptive
  redundancy ceiling, the convergence requirement, and the
  equal-or-better time-to-convergence bound. Those three are hard
  product claims and are NEVER scaled by ``--tolerance`` (which only
  loosens the per-metric baseline diffs).

Usage: python scripts/epidemic_smoke.py [--out REPORT.json]
       [--nodes N] [--rounds R] [--tolerance T] [--adaptive]
       [--compare]
"""

from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(_sys.argv[0] or ".")))
)

import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _arg(flag: str, default, cast):
    for i, a in enumerate(sys.argv):
        if a == flag and i + 1 < len(sys.argv):
            return cast(sys.argv[i + 1])
        if a.startswith(flag + "="):
            return cast(a.split("=", 1)[1])
    return default


def _run(nodes: int, rounds: int, adaptive: bool):
    """One fixed-seed geo/churn recording -> (facts, corro-epidemic/1)."""
    from corrosion_tpu.obs import epidemic
    from corrosion_tpu.sim import health

    with tempfile.TemporaryDirectory() as tmp:
        flight = os.path.join(tmp, "epidemic_smoke.jsonl")
        facts = health.record_demo_flight(
            flight, nodes=nodes, rounds=rounds, churn=True, seed=0,
            progress=sys.stderr, geo=True, adaptive=adaptive,
        )
        rep = epidemic.report_from_flight(
            flight, fanout=facts["fanout"], nodes=nodes,
            geo_regions=facts["regions"],
        )
    return facts, rep


def _check_one(facts, rep, tolerance: float, baseline_name: str):
    """The per-run identity + fit + baseline-diff failures."""
    from corrosion_tpu.obs import epidemic

    failures: list[str] = []
    if not rep["checks_ok"]:
        failures += [f"accounting: {p}" for p in rep["check_problems"]]
    if not rep["fit"]["fitted"]:
        failures.append("SI fit abstained on the geo scenario")
    else:
        beta = rep["spread_exponent"]
        theory = rep["theory"]["spread_exponent"]
        if not 0.0 < beta <= 1.1 * theory:
            failures.append(
                f"spread exponent {beta:.4f} outside (0, 1.1*theory="
                f"{1.1 * theory:.4f}] — theory is an upper bound"
            )
    baseline = os.path.join(REPO, baseline_name)
    diff = None
    if os.path.exists(baseline):
        base = epidemic.load_report(baseline)
        diff = epidemic.diff_reports(base, rep, tolerance=tolerance)
        failures += [
            f"baseline({baseline_name}): {r}" for r in diff["regressions"]
        ]
    return failures, diff


def _dissemination_gate(push_facts, push_rep, ada_facts, ada_rep):
    """The bench_budget.json ``dissemination`` gate: the adaptive
    plane's product claims. None of these bounds are tolerance-scaled
    — a dup-share ceiling or a TTC regression is a real regression at
    any jitter level (the same never-scaled rule the accounting and
    fit checks follow)."""
    failures: list[str] = []
    path = os.path.join(REPO, "bench_budget.json")
    with open(path) as f:
        budget = json.load(f).get("dissemination")
    if budget is None:
        return ["bench_budget.json has no 'dissemination' entry"], None

    dup_max = float(budget["dup_share_max"])
    ttc_slack = int(budget.get("ttc_slack_rounds", 0))
    dup = ada_rep["redundancy_ratio"]
    if dup > dup_max:
        failures.append(
            f"adaptive redundancy_ratio {dup:.4f} > dup_share_max "
            f"{dup_max:.2f} (never tolerance-scaled)"
        )
    push_ttc = push_facts.get("converged_round")
    ada_ttc = ada_facts.get("converged_round")
    if budget.get("require_converged"):
        if push_ttc is None:
            failures.append("push run did not converge (need_last != 0)")
        if ada_ttc is None:
            failures.append(
                "adaptive run did not converge (need_last != 0)"
            )
        for name, facts in (("push", push_facts), ("adaptive", ada_facts)):
            if facts.get("mismatches_last", 0):
                failures.append(
                    f"{name} run ended with "
                    f"{facts['mismatches_last']} cell mismatches"
                )
    if push_ttc is not None and ada_ttc is not None:
        if ada_ttc > push_ttc + ttc_slack:
            failures.append(
                f"adaptive time-to-convergence {ada_ttc} > push "
                f"{push_ttc} + slack {ttc_slack} (never "
                f"tolerance-scaled)"
            )
    summary = {
        "dup_share": {"push": push_rep["redundancy_ratio"],
                      "adaptive": dup, "max": dup_max},
        "converged_round": {"push": push_ttc, "adaptive": ada_ttc,
                            "slack_rounds": ttc_slack},
        "msgs_total": {"push": push_rep["msgs_total"],
                       "adaptive": ada_rep["msgs_total"]},
        "spread_exponent": {"push": push_rep["spread_exponent"],
                            "adaptive": ada_rep["spread_exponent"]},
        "effective_fanout": {"push": push_rep["effective_fanout"],
                             "adaptive": ada_rep["effective_fanout"]},
    }
    return failures, summary


def main() -> int:
    from corrosion_tpu.obs import epidemic

    nodes = _arg("--nodes", 96, int)
    rounds = _arg("--rounds", 48, int)
    tolerance = _arg("--tolerance", 0.35, float)
    out = _arg("--out", None, str)
    adaptive = "--adaptive" in sys.argv
    compare = "--compare" in sys.argv

    if compare:
        push_facts, push_rep = _run(nodes, rounds, adaptive=False)
        ada_facts, ada_rep = _run(nodes, rounds, adaptive=True)
        failures, push_diff = _check_one(
            push_facts, push_rep, tolerance, "EPIDEMIC_BASELINE.json"
        )
        ada_failures, ada_diff = _check_one(
            ada_facts, ada_rep, tolerance,
            "EPIDEMIC_BASELINE_ADAPTIVE.json",
        )
        failures += [f"adaptive: {m}" for m in ada_failures]
        gate_failures, summary = _dissemination_gate(
            push_facts, push_rep, ada_facts, ada_rep
        )
        failures += gate_failures
        report = {
            "ok": not failures,
            "failures": failures,
            "dissemination": summary,
            "push": {"facts": push_facts, "report": push_rep,
                     "baseline_diff": push_diff},
            "adaptive": {"facts": ada_facts, "report": ada_rep,
                         "baseline_diff": ada_diff},
        }
        rendered = [epidemic.render_report(push_rep),
                    epidemic.render_report(ada_rep)]
        if summary:
            rendered.append(
                "dissemination gate: dup {push:.4f} -> {adaptive:.4f} "
                "(max {max:.2f})".format(**summary["dup_share"])
                + ", ttc {push} -> {adaptive} (+{slack_rounds})".format(
                    **summary["converged_round"]
                )
                + ", msgs {push} -> {adaptive}".format(
                    **summary["msgs_total"]
                )
            )
        body = "\n".join(rendered)
    else:
        facts, rep = _run(nodes, rounds, adaptive=adaptive)
        failures, diff = _check_one(
            facts, rep, tolerance,
            "EPIDEMIC_BASELINE_ADAPTIVE.json" if adaptive
            else "EPIDEMIC_BASELINE.json",
        )
        report = {
            "ok": not failures,
            "failures": failures,
            "facts": facts,
            "report": rep,
            "baseline_diff": diff,
        }
        body = epidemic.render_report(rep)

    if out:
        with open(out, "w") as f:
            json.dump(report, f, indent=2)
    print(body)
    for fmsg in failures:
        print(f"epidemic_smoke: FAIL {fmsg}", file=sys.stderr)
    print(f"epidemic_smoke: {'OK' if not failures else 'FAILED'}",
          file=sys.stderr)
    return 0 if not failures else 1


if __name__ == "__main__":
    raise SystemExit(main())
