"""Propagation-plane smoke: record the geo scenario, fit, and gate.

The local/CI acceptance harness for the propagation-topology plane
(docs/OBSERVABILITY.md "Propagation plane"): runs the fixed-seed
WAN/geo churned scenario with the propagation observables on, derives
the ``corro-epidemic/1`` report, asserts the hard identities —

- on-device accounting reconciles (link mass == msgs, rumor mass ==
  first deliveries, useful + dup == msgs),
- the SI fit stands with a positive spread exponent bounded above by
  the push-gossip theory beta = ln(1 + F),

— and, when a committed ``EPIDEMIC_BASELINE.json`` exists next to the
repo root, diffs the fresh report against it at the CI tolerance. Exit
0 = all green; 1 = a broken identity, a failed fit, or a baseline
regression.

Usage: python scripts/epidemic_smoke.py [--out REPORT.json]
       [--nodes N] [--rounds R] [--tolerance T]
"""

from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(_sys.argv[0] or ".")))
)

import json
import os
import sys
import tempfile


def _arg(flag: str, default, cast):
    for i, a in enumerate(sys.argv):
        if a == flag and i + 1 < len(sys.argv):
            return cast(sys.argv[i + 1])
        if a.startswith(flag + "="):
            return cast(a.split("=", 1)[1])
    return default


def main() -> int:
    from corrosion_tpu.obs import epidemic
    from corrosion_tpu.sim import health

    nodes = _arg("--nodes", 96, int)
    rounds = _arg("--rounds", 48, int)
    tolerance = _arg("--tolerance", 0.35, float)
    out = _arg("--out", None, str)

    with tempfile.TemporaryDirectory() as tmp:
        flight = os.path.join(tmp, "epidemic_smoke.jsonl")
        facts = health.record_demo_flight(
            flight, nodes=nodes, rounds=rounds, churn=True, seed=0,
            progress=sys.stderr, geo=True,
        )
        rep = epidemic.report_from_flight(
            flight, fanout=facts["fanout"], nodes=nodes,
            geo_regions=facts["regions"],
        )
    failures: list[str] = []
    if not rep["checks_ok"]:
        failures += [f"accounting: {p}" for p in rep["check_problems"]]
    if not rep["fit"]["fitted"]:
        failures.append("SI fit abstained on the geo scenario")
    else:
        beta = rep["spread_exponent"]
        theory = rep["theory"]["spread_exponent"]
        if not 0.0 < beta <= 1.1 * theory:
            failures.append(
                f"spread exponent {beta:.4f} outside (0, 1.1*theory="
                f"{1.1 * theory:.4f}] — theory is an upper bound"
            )
    baseline = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "EPIDEMIC_BASELINE.json",
    )
    diff = None
    if os.path.exists(baseline):
        base = epidemic.load_report(baseline)
        diff = epidemic.diff_reports(base, rep, tolerance=tolerance)
        failures += [f"baseline: {r}" for r in diff["regressions"]]

    report = {
        "ok": not failures,
        "failures": failures,
        "facts": facts,
        "report": rep,
        "baseline_diff": diff,
    }
    if out:
        with open(out, "w") as f:
            json.dump(report, f, indent=2)
    print(epidemic.render_report(rep))
    for fmsg in failures:
        print(f"epidemic_smoke: FAIL {fmsg}", file=sys.stderr)
    print(f"epidemic_smoke: {'OK' if not failures else 'FAILED'}",
          file=sys.stderr)
    return 0 if not failures else 1


if __name__ == "__main__":
    raise SystemExit(main())
