"""Measure every BASELINE scenario and print one JSON line per config.

Configs 1-3 (3-node, 32-node churn, 1k anti-entropy) run here; config 4 is
bench.py's headline and config 5 is scripts/wan100k_smoke.py — run those
separately (they take minutes at full scale). Each line reports
convergence + visibility so the five-scenario story is reproducible with
three commands.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import numpy as np

sys.path.insert(0, ".")

from corrosion_tpu import models  # noqa: E402
from corrosion_tpu.ops import gossip  # noqa: E402
from corrosion_tpu.sim import simulate, visibility_latencies  # noqa: E402


def run(name, builder, **kw):
    cfg, topo, sched = builder(**kw)
    t0 = time.perf_counter()
    final, curves = simulate(cfg, topo, sched, seed=0, max_chunk=40)
    jax.block_until_ready(final.data.contig)
    wall = time.perf_counter() - t0
    alive = np.asarray(final.swim.alive)
    contig = np.asarray(final.data.contig)[alive]
    heads = np.asarray(final.data.head)
    lat = visibility_latencies(final, sched, cfg)
    out = {
        "config": name,
        "nodes": cfg.n_nodes,
        "rounds": sched.rounds,
        "converged": bool((contig == heads[None, :]).all()),
        "cells_converged": (
            bool(gossip.cells_agree(final.data, cfg.gossip))
            if cfg.gossip.n_cells else None
        ),
        "p50_s": round(lat["p50_s"], 2),
        "p99_s": round(lat["p99_s"], 2),
        "unseen": lat["unseen"],
        "mismatches_final": int(curves["mismatches"][-1]),
        "wall_s": round(wall, 1),
    }
    print(json.dumps(out), flush=True)


def main() -> None:
    from corrosion_tpu.utils.cache import enable_persistent_cache

    enable_persistent_cache()
    print(
        f"# platform={jax.devices()[0].platform}", file=sys.stderr, flush=True
    )
    run("1_three_node_1k_inserts", models.three_node)
    run("2_churn_32", models.churn_32)
    run("3_anti_entropy_1k", models.anti_entropy_1k)
    run_chunks("3b_anti_entropy_chunks")


def run_chunks(name) -> None:
    """Config 3b: the seq-chunk plane at scale (multi-chunk transactions,
    partial-need sync) via sim.chunk_engine."""
    from corrosion_tpu.sim.chunk_engine import simulate_chunks

    cfg, origin, last_seq, rounds = models.anti_entropy_chunks()
    t0 = time.perf_counter()
    _, m = simulate_chunks(cfg, origin, last_seq, rounds)
    wall = time.perf_counter() - t0
    print(
        json.dumps(
            {
                "config": name,
                "nodes": cfg.n_nodes,
                "streams": cfg.n_streams,
                "seqs_per_stream": int(last_seq[0]) + 1,
                "rounds": rounds,
                "converged": m["unapplied"] == 0,
                "p50_s": round(m["p50_s"], 2),
                "p99_s": round(m["p99_s"], 2),
                "unapplied": m["unapplied"],
                "seqs_granted": m["seqs_granted"],
                "wall_s": round(wall, 1),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
