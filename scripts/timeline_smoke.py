"""CI timeline-smoke gate: causal write tracing, end to end.

Runs a small loadgen fan-out storm WITH tracing enabled (2 agents so the
gossip-hop stage is exercised, client-minted trace ids on every write),
builds the ``corro-timeline/1`` artifact with ``obs``'s correlator, and
asserts the PR-11 acceptance invariants hard (no budget entry — these
are absolute correctness properties, not tolerance-scaled ceilings):

- **coverage**: >= 99% of acked (sampled) writes reconstruct end-to-end
  — ingest -> commit -> fan-out, with span + oracle evidence joined;
- **reconciliation**: every reconstructed write's stage-latency sum
  (send-wait + ingest + commit + gossip + fan-out) equals the
  independently measured wall latency within the stated tolerance, and
  the span cuts are causally ordered against the oracle's timestamps.

The emitted report goes through ``telemetry.check_bench_invariants``
(via the serving plane's provenance context) like every other artifact:
platform, nodes, device_count, config fingerprint, scenario — a
timeline can no more be published without provenance than a bench.

Usage:
    python scripts/timeline_smoke.py [--out timeline_smoke_report.json]
"""

from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
)

import argparse
import asyncio
import json
import sys
import tempfile

SCENARIO = "timeline_smoke"
SUBS = 32
WRITES = 48
WRITE_RATE = 24.0
AGENTS = 2
MIN_COVERAGE = 0.99
TOLERANCE_MS = 100.0


def measure() -> dict:
    from corrosion_tpu.loadgen import scenarios
    from corrosion_tpu.loadgen.report import emit_serving_report, serving_context
    from corrosion_tpu.obs.timeline import timeline_from_run, timeline_ok

    async def go():
        with tempfile.TemporaryDirectory() as tmp:
            run = await scenarios.fanout_storm(
                _os.path.join(tmp, "run"),
                subs=SUBS, writes=WRITES, write_rate=WRITE_RATE,
                read_rate=5.0, pg_rate=2.0, n_agents=AGENTS,
                trace_dir=_os.path.join(tmp, "trace"),
                progress=sys.stderr,
            )
            # Build INSIDE the tempdir scope: the span files live there.
            return run, timeline_from_run(run, tolerance_ms=TOLERANCE_MS)

    run, timeline = asyncio.run(go())
    ok, problems = timeline_ok(timeline, min_coverage=MIN_COVERAGE)
    rec = timeline["reconcile"]
    if rec["independent_walls"] < rec["checked"]:
        # The smoke must exercise the NON-tautological reconcile path:
        # every wall measured on the monotonic clock, not the epoch
        # fallback.
        ok = False
        problems = list(problems) + [
            f"only {rec['independent_walls']}/{rec['checked']} walls "
            f"measured on the independent monotonic clock"
        ]
    report = {
        **serving_context(SCENARIO, AGENTS, SUBS, WRITES, WRITE_RATE),
        "subs": SUBS,
        "oracle": run["oracle"],
        "timeline": timeline,
        "min_coverage": MIN_COVERAGE,
        "ok": ok and run["oracle"]["violations"] == 0,
        "problems": problems,
    }
    return emit_serving_report(report)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="timeline_smoke_report.json")
    args = ap.parse_args(argv)

    report = measure()
    with open(args.out, "w") as f:
        f.write(json.dumps(report, indent=2) + "\n")
    tl = report["timeline"]
    print(json.dumps({
        k: tl[k] for k in (
            "coverage", "writes_reconstructed", "writes_expected",
            "hops", "stages_ms", "wall_ms", "reconcile",
        )
    }, indent=2))
    if not report["ok"]:
        for p in report["problems"]:
            print(f"[timeline-smoke] FAIL {p}", file=sys.stderr)
        if report["oracle"]["violations"]:
            print(
                f"[timeline-smoke] FAIL oracle violations: "
                f"{report['oracle']['violation_examples']}",
                file=sys.stderr,
            )
        return 1
    print(
        f"[timeline-smoke] ok: {tl['writes_reconstructed']}/"
        f"{tl['writes_expected']} writes reconstructed, max reconcile "
        f"err {tl['reconcile']['max_abs_err_ms']} ms"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
