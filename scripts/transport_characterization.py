"""Measured characterization of the 3-plane transport split
(VERDICT r4 missing #3).

The reference multiplexes datagrams + uni + bi streams over ONE QUIC
connection (corro-agent/src/api/peer.rs:215-313, transport.rs:26-63);
this framework deliberately splits planes — SWIM on UDP datagrams, sync
and broadcast frames on pooled TCP — because no production QUIC stack
ships in the environment and the failure isolation is better. The
divergence that matters is head-of-line behavior: on one QUIC
connection, a bulk sync stream and the failure detector share a
congestion controller and loss-recovery state; on the split design the
probe plane is structurally isolated. This script MEASURES that:

1. Baseline: two idle agents; sample SWIM probe RTT (UDP ping->ack).
2. Bulk-transfer phase: agent B catches up a large table from A over
   the pooled TCP sync plane (thousands of rows in flight) while the
   probe plane keeps sampling.
3. Reconnect churn: the TCP pool's endpoints are torn down mid-run;
   measures time for the next sync frame to re-establish and complete
   (pool re-dial + circuit-breaker behavior).

Output: one SELF-DESCRIBING JSON line (platform, nodes, device_count,
config fingerprint, scenario — asserted by
``telemetry.check_bench_invariants``, the PR 6 emit-site rule) with
probe RTT percentiles idle vs under bulk sync, bulk throughput, and
reconnect latency. The claim checked: probe p99 under bulk load stays
within ~2x idle (no cross-plane head-of-line coupling), which a
shared-connection design cannot guarantee under loss. Documented in
docs/SCALING.md "Transport split".

This artifact is also a CALIBRATION INPUT: ``corrosion fidelity
calibrate --from-characterization`` derives a round model from the
under-bulk probe percentiles and loss tail
(``fidelity.calibrate.from_characterization``) — which is why its
provenance is now held to the same standard as the outputs it feeds.

``--netem wan80`` re-runs the whole characterization under the
deterministic impairment shim (agent/netem.py): 40 ms one-way delay ±
8 ms jitter on every plane + 1 % probe/bcast loss — an ~80 ms-RTT WAN
instead of clean loopback. The emitted artifact (scenario
``transport_characterization_wan80``) feeds ``fidelity calibrate`` a
genuinely-impaired RoundModel (docs/FIDELITY.md "Impaired calibration"),
closing the "calibration inputs are loopback RTTs" gap.
"""

from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import argparse
import asyncio
import json
import tempfile
import time

import numpy as np

from corrosion_tpu.agent.testing import launch_test_agent, poll_until

SCHEMA = (
    "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY,"
    " text TEXT NOT NULL DEFAULT '')"
)

NETEM_SEED = 0


def wan80_plan() -> dict:
    """The standing impaired-characterization plan: ~80 ms RTT + 1% loss."""
    from corrosion_tpu.agent.netem import HostFault, HostFaultPlan

    return HostFaultPlan(
        name="wan80",
        faults=(
            HostFault(kind="delay", delay_ms=40.0, jitter_ms=8.0),
            HostFault(kind="loss", prob=0.01, planes=("probe", "bcast")),
        ),
    ).to_json_obj()


NETEM_PLANS = {"none": None, "wan80": wan80_plan}


async def sample_probe_rtts(a, peer_addr, n=60, gap=0.02):
    """Direct UDP ping->ack round trips through the real SWIM plane."""
    rtts = []
    for _ in range(n):
        t0 = time.perf_counter()
        ok = await a.agent.swim._probe(peer_addr)
        if ok:
            rtts.append((time.perf_counter() - t0) * 1000.0)
        await asyncio.sleep(gap)
    return rtts


async def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("rows", nargs="?", type=int, default=20_000)
    ap.add_argument(
        "--netem", choices=sorted(NETEM_PLANS), default="none",
        help="run under a deterministic impairment plan (agent/netem.py)",
    )
    args = ap.parse_args()
    rows = args.rows
    plan = NETEM_PLANS[args.netem]
    netem_kw: dict = {}
    scenario = "transport_characterization"
    if plan is not None:
        plan = plan()
        scenario = f"transport_characterization_{args.netem}"
    with tempfile.TemporaryDirectory() as d:
        if plan is not None:
            netem_kw = dict(netem_plan=plan, netem_seed=NETEM_SEED)
        a = await launch_test_agent(
            d + "/a", schema=SCHEMA,
            **({**netem_kw, "netem_node": "a"} if plan is not None else {}),
        )
        # Seed A BEFORE B exists: B's whole catch-up must flow through
        # the anti-entropy sync plane (pooled TCP), not live broadcast.
        t0 = time.perf_counter()
        for i in range(0, rows, 500):
            await a.client.execute(
                [
                    ["INSERT INTO tests (id, text) VALUES (?, ?)",
                     [j, f"row-{j}-{'x' * 64}"]]
                    for j in range(i, i + 500)
                ]
            )
        seed_s = time.perf_counter() - t0
        b = await launch_test_agent(
            d + "/b", schema=SCHEMA, bootstrap=[a.gossip_addr],
            **({**netem_kw, "netem_node": "b"} if plan is not None else {}),
        )
        if plan is not None:
            # Both directions impaired: a's shim delays pings/frames
            # toward b, b's shim the replies — 2x one-way = the RTT.
            a.agent.netem.register_peer(b.gossip_addr, "b")
            b.agent.netem.register_peer(a.gossip_addr, "a")
            a.agent.netem.arm()
            b.agent.netem.arm()
        try:
            await poll_until(
                lambda: asyncio.sleep(0, len(b.agent.members.alive()) > 0),
                timeout=10.0,
            )
            idle = await sample_probe_rtts(b, a.gossip_addr)

            # Bulk catch-up: sample probe RTTs WHILE the sync plane moves
            # the backlog over pooled TCP.
            t1 = time.perf_counter()
            probe_task = asyncio.create_task(
                sample_probe_rtts(b, a.gossip_addr, n=200, gap=0.01)
            )

            async def caught_up():
                _, r = await b.client.query("SELECT count(*) FROM tests")
                return r[0][0] >= rows

            await poll_until(caught_up, timeout=120.0)
            bulk_s = time.perf_counter() - t1
            under_load = await probe_task

            # Reconnect churn: kill B's pooled TCP endpoints; time the
            # next completed sync round trip.
            for _reader, wtr in list(b.agent.transport._pool.values()):
                try:
                    wtr.close()
                except Exception:
                    pass
            b.agent.transport._pool.clear()
            for _reader, wtr in list(a.agent.transport._pool.values()):
                try:
                    wtr.close()
                except Exception:
                    pass
            a.agent.transport._pool.clear()
            t2 = time.perf_counter()
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (?, 'late')",
                  [rows + 1]]]
            )

            async def saw_late():
                from corrosion_tpu.core.values import Statement

                _, r = await b.client.query(Statement(
                    "SELECT count(*) FROM tests WHERE id = ?",
                    params=[rows + 1],
                ))
                return r[0][0] == 1

            await poll_until(saw_late, timeout=30.0)
            reconnect_s = time.perf_counter() - t2

            def pct(xs, q):
                return round(float(np.percentile(xs, q)), 2) if xs else None

            # The one self-describing emit site: provenance asserted
            # exactly like every bench/serving/fidelity JSON, so the
            # calibration's input measurement is as trustworthy as the
            # divergence gate it feeds.
            from corrosion_tpu.sim import benchlib, telemetry

            report = telemetry.check_bench_invariants({
                **benchlib.bench_context(
                    scenario, rows, a.agent.cfg.fanout,
                ),
                "scenario": scenario,
                "netem": plan,
                "netem_seed": NETEM_SEED if plan is not None else None,
                "nodes": 2,
                "rows": rows,
                "seed_s": round(seed_s, 1),
                "bulk_catchup_s": round(bulk_s, 1),
                "bulk_changes_per_s": round(rows / bulk_s, 0),
                "probe_rtt_idle_ms": {
                    "p50": pct(idle, 50), "p99": pct(idle, 99),
                    "n": len(idle),
                },
                "probe_rtt_under_bulk_ms": {
                    "p50": pct(under_load, 50), "p99": pct(under_load, 99),
                    "n": len(under_load),
                },
                "probe_loss_under_bulk": round(
                    1.0 - len(under_load) / 200.0, 3
                ),
                "reconnect_to_delivery_s": round(reconnect_s, 2),
            }, extra_provenance=("scenario",))
            print(json.dumps(report))
        finally:
            await b.stop()
            await a.stop()


if __name__ == "__main__":
    asyncio.run(main())
