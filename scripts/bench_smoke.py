"""CI bench-smoke gate: a small fixed-seed step-time bench vs a budget.

Runs a scaled-down merge-storm config (fixed seed, fixed shapes), measures
warm per-round step time plus the plane breakdown on the SAME
cumulative-prefix composite the headline bench uses (sim/benchlib.py),
writes the full report as a JSON artifact, and exits 1 when ``step_ms``
or any plane exceeds its committed budget (bench_budget.json) by the
budget's tolerance — so the r04→r05 class of silent step-time regression
fails the PR that introduces it instead of surfacing rounds later.

A second, smaller measurement runs the SAME engine under the ``pallas``
kernel backend (interpret mode off-TPU — the identical kernel math as
XLA ops) and gates against the budget's ``interpret`` entry, so a
regression in the fused delivery kernels' structure is caught on CPU CI
without a TPU in the loop.

Every emitted report is self-describing (platform, device_count, nodes,
config fingerprint — asserted by ``telemetry.check_bench_invariants``)
so a CPU-fallback run can never be mistaken for an accelerator artifact,
and the gate refuses to compare across platforms or kernel backends.

Usage:
    python scripts/bench_smoke.py [--out report.json] [--budget FILE]
    python scripts/bench_smoke.py --update   # refresh the budget file

``--update`` rewrites the budget from the current measurement with the
documented headroom (x3 — absorbs slower CI runners; the gate's job is
catching multi-x structural regressions, not 10%% noise). How to read and
refresh the budget: docs/PERFORMANCE.md.
"""

from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
)

import argparse
import dataclasses
import json
import sys
import time
from pathlib import Path

# Fixed shape: small enough for a CI runner's 2 vCPUs (compile included),
# big enough that the broadcast/sync planes dominate like the flagship.
NODES = 128
ROUNDS = 48
SAMPLES = 64
SEED = 0
# Interpret-mode (pallas kernel) shape: interpret runs the kernel math
# as XLA ops with per-call overhead, so the config is smaller — the gate
# watches for structural multi-x regressions in the fused kernels, not
# absolute speed.
INTERP_NODES = 32
INTERP_ROUNDS = 12
INTERP_SAMPLES = 16
# --update headroom: budget = measured * this.
UPDATE_HEADROOM = 3.0
# Per-plane ceiling floor for --update: cumulative-prefix increments at
# this scale are ~1 ms, can measure 0 under timer noise (a 0 ms ceiling
# would make ANY later nonzero measurement a breach), and spike to tens
# of ms on a contended runner. step_ms is the stable primary gate; the
# plane ceilings are coarse attribution guards, floored high enough that
# only the multi-x structural class (the r05 sync plane was ~390 ms at
# the flagship shape) can breach them.
UPDATE_PLANE_FLOOR_MS = 30.0


def measure() -> dict:
    import jax

    from corrosion_tpu import models
    from corrosion_tpu.obs import costs as costs_mod
    from corrosion_tpu.obs import ledger as ledger_mod
    from corrosion_tpu.ops import onehot
    from corrosion_tpu.sim import benchlib, simulate, telemetry

    cfg, topo, sched = models.merge_10k(
        n=NODES, rounds=ROUNDS, samples=SAMPLES
    )
    chunk = 24
    # The compile ledger splits the warm-up blob into compile vs run and
    # ARMS the timed measurement: a steady-state recompile raises
    # RetraceError (and a nonzero steady_compiles would refuse to emit),
    # so CI's zero-recompile assertion is the measurement itself.
    led = ledger_mod.CompileLedger().watch_engines(("dense",)).install()
    # Warm-up compiles the chunked scan; the timed run re-executes the
    # SAME seed, so the reported seed is exactly the run that produced
    # the gated number (reproducible from the artifact alone).
    t0 = time.perf_counter()
    with led.window("first_run") as cold:
        final, _ = simulate(cfg, topo, sched, seed=SEED, max_chunk=chunk)
        jax.block_until_ready(final.data.contig)
    first_run_s = time.perf_counter() - t0
    led.arm("bench-smoke timed run")
    t0 = time.perf_counter()
    # The timed run rides its own ledger window: the window-exit
    # cache-growth check catches steady-state retraces even when a
    # persistent compilation cache swallows the backend_compile event
    # the armed monitoring tap listens for.
    with led.window("timed_run"):
        final, _ = simulate(cfg, topo, sched, seed=SEED, max_chunk=chunk)
        jax.block_until_ready(final.data.contig)
    step_ms = (time.perf_counter() - t0) / ROUNDS * 1000.0
    led.disarm()
    led.uninstall()

    composite, stages, carry0 = benchlib.plane_composite(
        cfg, topo, sched, final
    )
    # More iterations than the headline bench: per-stage increments are
    # ~1 ms at this scale, so the default 10 leaves the plane split
    # timer-noise-bound on a loaded runner.
    attr = telemetry.attribute_planes(composite, stages, carry0, iters=20)
    plane, _ = attr.scale(step_ms)
    step_rep = benchlib.rounded_step_report(step_ms, plane)
    report = {
        # Self-describing provenance (check_bench_invariants asserts it).
        **benchlib.bench_context(cfg, NODES, ROUNDS, SAMPLES, SEED),
        "kernels": onehot.resolve_backend(cfg.gossip.kernel_backend),
        "nodes": NODES,
        "rounds": ROUNDS,
        "seed": SEED,
        # Shared emit-site rounding (benchlib) — the headline bench and
        # this gate must publish invariant-satisfying numbers the same
        # way or they drift.
        **step_rep,
        # Ledger split of the warm-up blob + the zero-recompile verdict
        # (check_bench_invariants refuses steady_compiles != 0).
        **benchlib.compile_split_report(first_run_s, cold.compile_ms),
        "steady_compiles": led.armed_compiles,
        # Per-plane roofline from the SAME composite prefixes (AOT
        # cost_analysis), joined with the measured plane split.
        "roofline": benchlib.roofline_report(
            costs_mod.roofline_stage_costs(composite, stages, carry0),
            step_rep["plane_ms"],
        ),
        "attrib_composite_ms": round(attr.full_ms, 1),
    }
    # Same emitted-report invariants as the headline bench.
    return telemetry.check_bench_invariants(report)


def measure_interpret() -> dict:
    """The interpret-mode kernel gate: the same engine, the ``pallas``
    kernel backend (fused delivery kernels under
    ``pallas_call(..., interpret=True)`` off-TPU). Warm step time only —
    plane attribution at this shape is timer-noise."""
    import jax

    from corrosion_tpu import models
    from corrosion_tpu.sim import benchlib, simulate, telemetry

    cfg, topo, sched = models.merge_10k(
        n=INTERP_NODES, rounds=INTERP_ROUNDS, samples=INTERP_SAMPLES
    )
    cfg = dataclasses.replace(
        cfg,
        gossip=dataclasses.replace(cfg.gossip, kernel_backend="pallas"),
    )
    chunk = 6
    final, _ = simulate(cfg, topo, sched, seed=SEED, max_chunk=chunk)
    jax.block_until_ready(final.data.contig)
    t0 = time.perf_counter()
    final, _ = simulate(cfg, topo, sched, seed=SEED, max_chunk=chunk)
    jax.block_until_ready(final.data.contig)
    step_ms = (time.perf_counter() - t0) / INTERP_ROUNDS * 1000.0
    report = {
        **benchlib.bench_context(
            cfg, INTERP_NODES, INTERP_ROUNDS, INTERP_SAMPLES, SEED
        ),
        "kernels": "pallas",
        "nodes": INTERP_NODES,
        "rounds": INTERP_ROUNDS,
        "seed": SEED,
        "step_ms": round(step_ms, 1),
    }
    return telemetry.check_bench_invariants(report)


def main(argv=None) -> int:
    repo = Path(__file__).resolve().parent.parent
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--budget", default=str(repo / "bench_budget.json"))
    ap.add_argument("--out", default="bench_smoke_report.json")
    ap.add_argument(
        "--update", action="store_true",
        help="rewrite the budget file from this measurement "
        f"(x{UPDATE_HEADROOM} headroom) instead of gating",
    )
    ap.add_argument(
        "--skip-interpret", action="store_true",
        help="skip the interpret-mode (pallas kernel) measurement",
    )
    args = ap.parse_args(argv)

    from corrosion_tpu.sim import benchlib

    measured = measure()
    interp = None if args.skip_interpret else measure_interpret()
    budget_path = Path(args.budget)
    if args.update:
        old = (
            json.loads(budget_path.read_text())
            if budget_path.exists() else {}
        )
        budget = {
            "_comment": (
                "Per-round step-time budget for scripts/bench_smoke.py "
                "(docs/PERFORMANCE.md). Ceilings are measured-on-refresh "
                f"x{UPDATE_HEADROOM} headroom; the gate additionally "
                "multiplies by `tolerance`. `interpret` is the pallas-"
                "kernel interpret-mode entry (same headroom)."
            ),
            "platform": measured["platform"],
            "kernels": measured["kernels"],
            "nodes": NODES,
            "rounds": ROUNDS,
            "tolerance": old.get("tolerance", benchlib.DEFAULT_TOLERANCE),
            "step_ms": round(measured["step_ms"] * UPDATE_HEADROOM, 1),
            "plane_ms": {
                k: round(
                    max(v * UPDATE_HEADROOM, UPDATE_PLANE_FLOOR_MS), 1
                )
                for k, v in measured["plane_ms"].items()
            },
        }
        if interp is not None:
            budget["interpret"] = {
                "platform": interp["platform"],
                "kernels": "pallas",
                "nodes": INTERP_NODES,
                "rounds": INTERP_ROUNDS,
                "step_ms": round(
                    interp["step_ms"] * UPDATE_HEADROOM, 1
                ),
            }
        elif "interpret" in old:
            # --skip-interpret must not silently DELETE the interpret
            # gate: carry the previous ceilings forward unchanged.
            budget["interpret"] = old["interpret"]
        budget_path.write_text(json.dumps(budget, indent=2) + "\n")
        print(f"[bench-smoke] budget refreshed: {budget_path}")
        print(json.dumps(measured))
        if interp is not None:
            print(json.dumps({"interpret": interp}))
        return 0

    budget = json.loads(budget_path.read_text())
    ok, breaches = benchlib.check_budget(measured, budget)
    if interp is not None:
        if "interpret" in budget:
            ok_i, br_i = benchlib.check_budget(
                interp,
                {
                    "tolerance": budget.get(
                        "tolerance", benchlib.DEFAULT_TOLERANCE
                    ),
                    **budget["interpret"],
                },
            )
            ok = ok and ok_i
            breaches = breaches + [f"interpret.{b}" for b in br_i]
        else:
            # Measuring without gating is how regressions pass silently:
            # a budget file predating the interpret entry must breach,
            # not skip.
            ok = False
            breaches = breaches + [
                "interpret: entry missing from budget — rerun with "
                "--update"
            ]
    report = {
        **measured,
        "interpret": interp,
        "budget": {k: v for k, v in budget.items() if k != "_comment"},
        "ok": ok,
        "breaches": breaches,
    }
    Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    print(json.dumps(report))
    if not ok:
        for b in breaches:
            print(f"[bench-smoke] BREACH {b}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
