"""Ablate broadcast_round features at N to locate residual step cost."""

from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import dataclasses
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu import models
from corrosion_tpu.ops import gossip as gossip_ops


def timed(label, fn):
    out = fn()
    jax.block_until_ready(jax.tree.leaves(out)[0])
    t1 = time.perf_counter()
    for _ in range(3):
        out = fn()
    jax.block_until_ready(jax.tree.leaves(out)[0])
    t2 = time.perf_counter()
    print(f"[{label}] step={(t2 - t1) / 3 * 1000:.0f}ms", flush=True)


def main() -> None:
    from corrosion_tpu.utils.cache import enable_persistent_cache

    enable_persistent_cache()
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    cfg, topo, sched = models.wan_100k(n=n, rounds=4, samples=16)
    key = jax.random.PRNGKey(0)
    alive = jnp.ones(cfg.n_nodes, bool)
    n_regions = int(np.asarray(topo.region).max()) + 1
    part = jnp.zeros((n_regions, n_regions), bool)
    writes = jnp.asarray(sched.writes[0], jnp.uint32)
    print(f"platform={jax.devices()[0].platform} n={n}", flush=True)

    variants = {
        "full": cfg.gossip,
        "no_cells": dataclasses.replace(cfg.gossip, n_cells=0),
        "no_loss_rng": dataclasses.replace(cfg.gossip, loss_prob=0.0),
        "queue16": dataclasses.replace(cfg.gossip, queue=16),
        "no_intake": dataclasses.replace(cfg.gossip, rebroadcast_intake=6),
    }
    for label, g in variants.items():
        data = gossip_ops.init_data(g)
        timed(
            label,
            lambda g=g, data=data: gossip_ops.broadcast_round(
                data, topo, alive, part, writes, key, g
            ),
        )


if __name__ == "__main__":
    main()
