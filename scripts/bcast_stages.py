"""Cumulative stage timing of the fast-path delivery at N (TPU).

Rebuilds broadcast_round's delta-packed one-hot delivery stage by stage on
realistic state so per-stage cost = difference of consecutive cumulative
times (isolated micro-benches mismeasured: in-context fusion differs).
"""

from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import sys
import time

import jax
import jax.numpy as jnp

from corrosion_tpu.ops import crdt, routing
from corrosion_tpu.ops.gossip import _onehot_rowgather


def timed(label, fn, *args):
    f = jax.jit(fn)
    out = f(*args)
    jax.block_until_ready(jax.tree.leaves(out)[0])
    t1 = time.perf_counter()
    for _ in range(3):
        out = f(*args)
    jax.block_until_ready(jax.tree.leaves(out)[0])
    t2 = time.perf_counter()
    print(f"[{label}] step={(t2 - t1) / 3 * 1000:.0f}ms", flush=True)


def main() -> None:
    from corrosion_tpu.utils.cache import enable_persistent_cache

    enable_persistent_cache()
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    w_count, q_cap, f, n_cells, k_in = 512, 48, 3, 256, 26
    kk = f * q_cap
    k2 = kk + 3
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 8)
    print(f"platform={jax.devices()[0].platform} n={n} kk={kk}", flush=True)

    contig = jax.random.randint(ks[0], (n, w_count), 0, 50).astype(jnp.uint32)
    seen0 = contig + jax.random.randint(ks[1], (n, w_count), 0, 5).astype(jnp.uint32)
    q_writer = jax.random.randint(ks[2], (n, q_cap), -1, w_count).astype(jnp.int32)
    q_ver = jax.random.randint(ks[3], (n, q_cap), 1, 60).astype(jnp.uint32)
    src = jax.random.randint(ks[4], (n, f), 0, n)
    link_ok = jax.random.uniform(ks[5], (n, f)) < 0.9
    cells = crdt.make_cells(n * n_cells)

    def stage_gather(contig, src, q_writer, q_ver, link_ok):
        m_w = q_writer[src].reshape(n, kk)
        m_v = q_ver[src].reshape(n, kk)
        m_ok = (
            jnp.repeat(link_ok[:, :, None], q_cap, axis=2).reshape(n, kk)
            & (m_w >= 0)
        )
        return m_w, m_v, m_ok

    def stage_base(contig, src, q_writer, q_ver, link_ok):
        m_w, m_v, m_ok = stage_gather(contig, src, q_writer, q_ver, link_ok)
        base_m = _onehot_rowgather(contig, jnp.maximum(m_w, 0))
        return base_m, m_w, m_v, m_ok

    def stage_sort(contig, src, q_writer, q_ver, link_ok):
        base_m, m_w, m_v, m_ok = stage_base(contig, src, q_writer, q_ver, link_ok)
        useful = m_ok & (m_v > base_m)
        d_raw = jnp.where(useful, m_v - base_m, 0)
        dc = jnp.minimum(d_raw, jnp.uint32(kk + 1))
        sent_key = jnp.uint32(w_count * k2)
        pkd = jnp.where(useful, m_w.astype(jnp.uint32) * k2 + dc, sent_key)
        skey, v2 = jax.lax.sort((pkd, m_v), dimension=1, num_keys=1, is_stable=False)
        return skey, v2

    def stage_run(contig, src, q_writer, q_ver, link_ok):
        skey, v2 = stage_sort(contig, src, q_writer, q_ver, link_ok)
        sent_key = jnp.uint32(w_count * k2)
        valid2 = skey < sent_key
        w2 = jnp.minimum((skey // k2).astype(jnp.int32), w_count - 1)
        d2 = (skey % k2).astype(jnp.uint32)
        seg_start = jnp.concatenate(
            [jnp.ones((n, 1), bool), w2[:, 1:] != w2[:, :-1]], axis=1
        )
        prev_d = jnp.concatenate([jnp.zeros((n, 1), d2.dtype), d2[:, :-1]], axis=1)
        ok_link = jnp.where(seg_start, d2 == 1, d2 <= prev_d + 1) & (d2 <= kk)
        run = routing.segmented_prefix_and_rows(ok_link & valid2, seg_start)
        return run, valid2, w2, d2, v2, seg_start, prev_d

    def stage_reduce(contig, seen, src, q_writer, q_ver, link_ok):
        run, valid2, w2, d2, v2, seg_start, prev_d = stage_run(
            contig, src, q_writer, q_ver, link_ok
        )
        applied = run & valid2
        ids = jnp.arange(w_count, dtype=w2.dtype)
        hit = w2[:, :, None] == ids[None, None, :]
        contig2 = contig + jnp.max(
            jnp.where(hit & applied[:, :, None], d2[:, :, None], 0), axis=1
        )
        seen2 = jnp.maximum(
            seen,
            jnp.max(jnp.where(hit & valid2[:, :, None], v2[:, :, None], 0), axis=1),
        )
        return contig2, seen2, applied, w2, v2, seg_start, d2, prev_d

    def stage_crdt(cells, contig, seen, src, q_writer, q_ver, link_ok):
        contig2, seen2, applied, w2, v2, seg_start, d2, prev_d = stage_reduce(
            contig, seen, src, q_writer, q_ver, link_ok
        )
        fresh = applied & ~((~seg_start) & (d2 == prev_d))
        from corrosion_tpu.ops.gossip import GossipConfig, _merge_versions_dense

        cfg = GossipConfig(n_nodes=n, n_writers=w_count, n_cells=n_cells)
        cells2, _ = _merge_versions_dense(
            cells, None, w2, v2, fresh, None, n, cfg
        )
        return contig2, seen2, cells2, fresh, w2, v2

    def stage_intake(cells, contig, seen, src, q_writer, q_ver, link_ok):
        contig2, seen2, cells2, fresh, w2, v2 = stage_crdt(
            cells, contig, seen, src, q_writer, q_ver, link_ok
        )
        in_mask, (in_w, in_v) = routing.rebuild_bounded_queue(
            fresh, -v2.astype(jnp.int32), (w2, v2), k_in
        )
        return contig2, seen2, cells2, in_mask, in_w, in_v

    timed("A_gather", stage_gather, contig, src, q_writer, q_ver, link_ok)
    timed("B_base", stage_base, contig, src, q_writer, q_ver, link_ok)
    timed("C_sort", stage_sort, contig, src, q_writer, q_ver, link_ok)
    timed("D_run", stage_run, contig, src, q_writer, q_ver, link_ok)
    timed("E_reduce", stage_reduce, contig, seen0, src, q_writer, q_ver, link_ok)
    timed("F_crdt", stage_crdt, cells, contig, seen0, src, q_writer, q_ver, link_ok)
    timed("G_intake", stage_intake, cells, contig, seen0, src, q_writer, q_ver, link_ok)


if __name__ == "__main__":
    main()
