"""Decompose churn_32's tail visibility latency (VERDICT weak #5).

For every (sample, node) visibility pair, splits the latency into:

- **downtime**: rounds the observer spent dead between the write's commit
  and its revive (scenario-defined — the node cannot possibly see the
  write while its process is down);
- **heal**: rounds from the relevant start (commit, or revive when the
  observer was down) to first visibility — the recovery path the
  framework actually controls (rejoin + sync catch-up).

If heal-p99 is small while raw-p99 is large, the tail is the 60-round
scheduled outage, not a recovery-path weakness. Runs on CPU (32 nodes).

Prints one JSON line.
"""

from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import json

import numpy as np

from corrosion_tpu import models
from corrosion_tpu.sim import simulate, visibility_latencies


def main() -> None:
    cfg, topo, sched = models.churn_32()
    final, curves = simulate(cfg, topo, sched, seed=0)
    vis = np.asarray(final.vis_round)  # [S, N] first-visible round
    n = cfg.n_nodes
    rounds = sched.rounds

    # Per-round alive matrix from the kill/revive script.
    alive = np.ones((rounds, n), bool)
    cur = np.ones(n, bool)
    for r in range(rounds):
        cur = (cur & ~sched.kill[r]) | sched.revive[r]
        alive[r] = cur

    raw, heal, downtime = [], [], []
    for s in range(vis.shape[0]):
        commit = int(sched.sample_round[s])
        for node in range(n):
            v = int(vis[s, node])
            if v < 0:
                continue  # never seen (final-alive check covers this)
            lat = v - commit
            # Rounds in [commit, v) the observer was dead.
            dead_rounds = int((~alive[commit:v, node]).sum()) if v > commit else 0
            raw.append(lat)
            downtime.append(dead_rounds)
            heal.append(lat - dead_rounds)

    raw = np.array(raw, float) * cfg.round_ms / 1000.0
    heal = np.array(heal, float) * cfg.round_ms / 1000.0
    downtime = np.array(downtime, float) * cfg.round_ms / 1000.0
    lat = visibility_latencies(final, sched, cfg)
    affected = downtime > 0
    out = {
        "config": "churn_32_decomposition",
        "pairs": len(raw),
        "raw_p50_s": round(float(np.percentile(raw, 50)), 2),
        "raw_p99_s": round(float(np.percentile(raw, 99)), 2),
        "heal_p50_s": round(float(np.percentile(heal, 50)), 2),
        "heal_p99_s": round(float(np.percentile(heal, 99)), 2),
        "downtime_pairs": int(affected.sum()),
        "downtime_p99_s": round(
            float(np.percentile(downtime[affected], 99)) if affected.any() else 0.0, 2
        ),
        "heal_p99_downtime_pairs_s": round(
            float(np.percentile(heal[affected], 99)) if affected.any() else 0.0, 2
        ),
        "unseen": lat["unseen"],
        "mismatches_final": int(curves["mismatches"][-1]),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
