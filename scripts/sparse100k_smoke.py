"""On-chip validation of the any-node-writes sparse writer plane at 100k.

BASELINE-5 variant (VERDICT r4 missing #1 / next-round #2): every node is
write-eligible; cohorts of fresh writers rotate through w_hot hot slots
each epoch (ops/sparse_writers.py). Reports the north-star visibility
metric, convergence over watermarks AND CRDT cells vs the serial-merge
ground truth, per-node state bytes, and rotation stats.

The run is instrumented by the kernel telemetry plane (sim/telemetry.py):
every epoch prints a progress line to stderr (long 100k runs no longer go
dark for minutes), and ``--flight PATH`` additionally streams per-round
curves to a replayable JSONL flight record.

Usage: python scripts/sparse100k_smoke.py [rounds] [--cells-check]
       [--flight[=PATH]]
"""

from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import json
import sys
import time

import jax
import numpy as np

from corrosion_tpu import models
from corrosion_tpu.sim import health, sparse_engine
from corrosion_tpu.sim.telemetry import (
    FlightRecorder,
    KernelTelemetry,
    flight_path_from_argv,
)


def main() -> None:
    from corrosion_tpu.utils.cache import (
        enable_persistent_cache,
        ensure_live_backend,
    )

    ensure_live_backend()
    enable_persistent_cache()
    flight = flight_path_from_argv(sys.argv)
    nums = [a for a in sys.argv[1:] if not a.startswith("-")]
    rounds = int(nums[0]) if nums else 240
    cells_check = "--cells-check" in sys.argv
    on_accel = jax.devices()[0].platform not in ("cpu",)
    if on_accel:
        cfg, topo, sched = models.anywrite_sparse(rounds=rounds)
    else:
        cfg, topo, sched = models.anywrite_sparse(
            n=512, w_hot=64, n_regions=4, rounds=min(rounds, 96),
            cohort=24, k_dev=16, samples=128,
        )

    tele = KernelTelemetry(
        engine="sparse",
        progress=sys.stderr,
        recorder=(
            FlightRecorder(flight, engine="sparse") if flight else None
        ),
    )
    t0 = time.perf_counter()
    sstate, swim_state, vis_round, curves, info = (
        sparse_engine.simulate_sparse(
            cfg, topo, sched, seed=0, telemetry=tele
        )
    )
    jax.block_until_ready(sstate.data.contig)
    wall = time.perf_counter() - t0
    if tele.recorder is not None:
        tele.recorder.close()

    lat_rounds = np.asarray(vis_round) - sched.sample_round[:, None]
    seen = np.asarray(vis_round) >= 0
    lat_s = lat_rounds[seen].astype(np.float64) * (cfg.round_ms / 1000.0)
    state_bytes = sum(
        x.size * x.dtype.itemsize
        for x in jax.tree.leaves((sstate, swim_state))
    )
    distinct_writers = int((sched.writes.sum(axis=0) > 0).sum())
    from corrosion_tpu.sim import benchlib

    out = {
        **benchlib.bench_context(cfg, rounds),
        "nodes": cfg.n_nodes,
        "w_hot": cfg.w_hot,
        "distinct_writers": distinct_writers,
        "rounds": rounds,
        "epochs": info["epochs"],
        "retired": info["retired"],
        "promoted": info["promoted"],
        "max_dev_entries": info["max_dev_entries"],
        "wall_s": round(wall, 2),
        "step_ms": round(wall / rounds * 1000.0, 1),
        "step_inner_ms": round(tele.device_step_ms, 1),
        "state_mib": round(state_bytes / 2**20, 1),
        "state_bytes_per_node": int(state_bytes / cfg.n_nodes),
        "applied": int(
            curves["applied_broadcast"].sum() + curves["applied_sync"].sum()
        ),
        "cold_healed": int(curves["cold_healed"].sum()),
        "window_degraded": int(curves["window_degraded"].sum()),
        "converged": sparse_engine.converged_sparse(sstate),
        "vis_p50_s": round(float(np.percentile(lat_s, 50)), 2),
        "vis_p99_s": round(float(np.percentile(lat_s, 99)), 2),
        "unseen_pairs": int((~seen).sum()),
    }
    # Convergence health plane (hot-slot staleness; cold residue rides
    # `need`). Same derivation as `obs report` on the --flight record.
    rep = health.report_from_curves(
        curves, engine="sparse", round_ms=cfg.round_ms
    )
    out.update({
        "converged_round": rep.converged_round,
        "staleness_p99": round(rep.staleness_p99, 1),
        "staleness_peak_node": rep.staleness_max_peak,
        # JSON-safe serializer: overflow percentiles render "inf".
        "vis_hist_p50_s": rep.to_dict()["vis_p50_s"],
        "vis_hist_p99_s": rep.to_dict()["vis_p99_s"],
        "queue_backlog_peak": rep.queue_backlog_peak,
        "swim_false_alarms": int(rep.false_alarms_total),
    })
    if cells_check:
        from corrosion_tpu.ops import gossip as gossip_ops
        from corrosion_tpu.ops import sparse_writers as sw_ops
        import jax.numpy as jnp

        hf = sparse_engine.final_head_full(sstate)
        ref = sw_ops.serial_merge_reference_sparse(hf, cfg.gossip)
        pc = gossip_ops.node_cells(sstate.data, cfg.gossip)
        out["cells_converged"] = bool(
            jnp.all(pc.cl == ref.cl[None, :])
            & jnp.all(pc.col_version == ref.col_version[None, :])
            & jnp.all(pc.value_rank == ref.value_rank[None, :])
        )
    from corrosion_tpu.sim import telemetry as telemetry_mod

    print(json.dumps(telemetry_mod.check_bench_invariants(out)))


if __name__ == "__main__":
    main()
