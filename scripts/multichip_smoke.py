"""CI multichip gate: the standing sharded bench lane vs its budget.

Runs the dense + sparse planes under the explicit shard_map round driver
(parallel/shard_driver.py) at device_count ∈ {1, 2, 4, 8} on the
8-virtual-device CPU mesh (or real chips when the host has >= 8), and
records the lane's whole evidence chain in one self-describing JSON
artifact:

- warm per-round ``step_ms`` per device count for BOTH planes (the D=1
  anchor runs the same driver with identity collectives);
- the per-plane step split at D=8, measured on the SHARDED composite
  (broadcast = the shard_map delivery chain incl. its queue exchange);
- cross-shard bytes per round (measured curves, asserted equal to the
  static ``traffic_model``) split by mesh axis (ici vs dcn);
- max per-device live-state MiB per device count, with the O(N/D)
  acceptance bound (D=8 holds <= 1/6 of the D=1 state bytes) enforced;
- bit-identity of final state and curves across every device count —
  the lane refuses to publish numbers from diverged runs.

The D=8 dense ``step_ms`` / ``plane_ms`` gate against the ``multichip``
entry in ``bench_budget.json`` exactly like the bench-smoke gate
(``benchlib.check_budget``; a missing entry is a breach, not a skip).
NOTE on reading the curve: on the VIRTUAL CPU mesh D>1 is slower than
D=1 — eight shards of one host CPU plus real collectives — so the gate
bounds regression of the sharded step itself; the D-scaling *speedup*
story belongs to real-chip runs of this same lane (docs/SCALING.md
"Multi-chip").

Usage:
    python scripts/multichip_smoke.py [--out report.json] [--budget FILE]
    python scripts/multichip_smoke.py --update     # refresh budget entry
    python scripts/multichip_smoke.py --large N [--large-rounds R]
        # append the "largest sharded run the host can hold" tail
"""

from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
)
# Must run before jax initializes a backend: the lane needs >= 8 devices,
# which off real multi-chip hardware means the virtual CPU mesh.
_flags = _os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import argparse
import json
import sys
from pathlib import Path

UPDATE_HEADROOM = 3.0  # budget = measured * this (docs/PERFORMANCE.md)
UPDATE_PLANE_FLOOR_MS = 30.0  # same floor rationale as bench_smoke.py


def main(argv=None) -> int:
    repo = Path(__file__).resolve().parent.parent
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--budget", default=str(repo / "bench_budget.json"))
    ap.add_argument("--out", default="multichip_report.json")
    ap.add_argument(
        "--update", action="store_true",
        help="rewrite the budget file's `multichip` entry from this "
        f"measurement (x{UPDATE_HEADROOM} headroom) instead of gating",
    )
    ap.add_argument(
        "--large", type=int, default=None, metavar="NODES",
        help="append a sharded convergence run at NODES nodes (the "
        "largest-run tail; not gated — evidence, recorded in the "
        "artifact)",
    )
    ap.add_argument("--large-rounds", type=int, default=96)
    args = ap.parse_args(argv)

    import jax

    if jax.config.jax_platforms and "axon" in jax.config.jax_platforms:
        jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < 8:
        print(
            f"[multichip] need 8 devices, have {len(jax.devices())}",
            file=sys.stderr,
        )
        return 2

    from corrosion_tpu.sim import benchlib, telemetry

    measured = telemetry.check_bench_invariants(
        benchlib.measure_multichip(
            large_nodes=args.large, large_rounds=args.large_rounds,
            progress=sys.stderr,
        )
    )

    budget_path = Path(args.budget)
    if args.update:
        budget = (
            json.loads(budget_path.read_text())
            if budget_path.exists() else {}
        )
        budget["multichip"] = {
            "platform": measured["platform"],
            "kernels": measured["kernels"],
            "nodes": measured["nodes"],
            "rounds": measured["rounds"],
            "device_count": measured["device_count"],
            "step_ms": round(
                measured["step_ms"] * UPDATE_HEADROOM, 1
            ),
            "plane_ms": {
                k: round(
                    max(v * UPDATE_HEADROOM, UPDATE_PLANE_FLOOR_MS), 1
                )
                for k, v in measured["plane_ms"].items()
            },
        }
        budget_path.write_text(json.dumps(budget, indent=2) + "\n")
        print(f"[multichip] budget entry refreshed: {budget_path}")
        print(json.dumps(measured))
        return 0

    budget = json.loads(budget_path.read_text())
    if "multichip" not in budget:
        # Measuring without gating is how regressions pass silently.
        ok, breaches = False, [
            "multichip: entry missing from bench_budget.json — rerun "
            "with --update"
        ]
    else:
        ok, breaches = benchlib.check_budget(
            measured,
            {
                "tolerance": budget.get(
                    "tolerance", benchlib.DEFAULT_TOLERANCE
                ),
                **budget["multichip"],
            },
        )
    report = {
        **measured,
        "budget": budget.get("multichip"),
        "ok": ok,
        "breaches": breaches,
    }
    Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    print(json.dumps(report))
    if not ok:
        for b in breaches:
            print(f"[multichip] BREACH {b}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
