"""Micro-bisection: compile each sparse-SWIM sub-operation at N on TPU."""

from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import sys
import time

import jax
import jax.numpy as jnp

from corrosion_tpu.ops import routing, swim_sparse
from corrosion_tpu.ops.swim import SwimConfig


def timed(label, fn):
    t0 = time.perf_counter()
    out = fn()
    jax.block_until_ready(jax.tree.leaves(out)[0])
    t1 = time.perf_counter()
    print(f"[{label}] compile+first={t1 - t0:.1f}s", flush=True)


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    which = sys.argv[2] if len(sys.argv) > 2 else "all"
    cfg = SwimConfig(n_nodes=n, view_capacity=64)
    g, u, k = cfg.gossip_fanout, cfg.backlog, cfg.view_capacity
    m = n * g * u
    key = jax.random.PRNGKey(0)
    print(f"platform={jax.devices()[0].platform} n={n} m={m}", flush=True)

    if which in ("intake", "all"):
        recv = jax.random.randint(key, (m,), 0, n)
        valid = jnp.ones((m,), bool)
        tgt = jax.random.randint(key, (m,), 0, n)
        pkd = jax.random.randint(key, (m,), 0, 1000).astype(jnp.uint32)
        f = jax.jit(
            lambda r, v, t, p: routing.bounded_intake(r, v, (t, p), n, g * u)
        )
        timed("bounded_intake", lambda: f(recv, valid, tgt, pkd))

    if which in ("merge", "all"):
        st = swim_sparse.init_state(cfg)
        tgts = jax.random.randint(key, (n, g * u), 0, n)
        pkds = jax.random.randint(key, (n, g * u), 0, 1000).astype(jnp.uint32)
        valids = jnp.ones((n, g * u), bool)
        f = jax.jit(swim_sparse._merge_scan)
        timed(
            "merge_scan48",
            lambda: f(st.exc_tgt, st.exc_pkd, tgts, pkds, valids),
        )

    if which in ("one", "all"):
        st = swim_sparse.init_state(cfg)
        t1 = jax.random.randint(key, (n,), 0, n)
        p1 = jax.random.randint(key, (n,), 0, 1000).astype(jnp.uint32)
        f = jax.jit(swim_sparse._merge_one)
        timed(
            "merge_one",
            lambda: f(st.exc_tgt, st.exc_pkd, t1, p1, jnp.ones((n,), bool)),
        )

    if which in ("rebuild", "all"):
        c = k + 60
        co = jnp.ones((n, c), bool)
        cx = jax.random.randint(key, (n, c), 0, 6)
        ct = jax.random.randint(key, (n, c), 0, n)
        cp = jax.random.randint(key, (n, c), 0, 1000).astype(jnp.uint32)
        f = jax.jit(
            lambda co, cx, ct, cp: routing.rebuild_bounded_queue(
                co, cx, (ct, cp, cx), u
            )
        )
        timed("rebuild_queue", lambda: f(co, cx, ct, cp))


if __name__ == "__main__":
    main()
