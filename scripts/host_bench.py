"""Host-agent benchmark: quick-start scenario + N-agent fan-out stress.

The only throughput number the reference publishes is a quick-start log
excerpt: 2 changes synced in 0.0128 s ≈ 156 changes/s across a 2-node
cluster (doc/quick-start.md:119, BASELINE.md). The default mode reproduces
that scenario with REAL agents — two in-process nodes over real TCP
loopback, writes on A via the HTTP API, convergence polled on B.

``--agents N`` runs the stress_test shape instead (agent.rs:3009-3224):
N agents, writes fired at random agents in batches under a sustained
concurrent read load, convergence asserted everywhere; reports end-to-end
replicated change-APPLICATIONS per second (writes × (N-1) receivers).

Usage: python scripts/host_bench.py [n_changes] [batch] [--agents N]
Prints one JSON line.
"""

from __future__ import annotations

import asyncio
import json
import random
import sys
import tempfile
import time

sys.path.insert(0, ".")

from corrosion_tpu.agent.testing import launch_test_agent, poll_until  # noqa: E402
from corrosion_tpu.core.values import Statement  # noqa: E402


async def main(n_changes: int, batch: int) -> None:
    with tempfile.TemporaryDirectory() as d1, tempfile.TemporaryDirectory() as d2:
        a = await launch_test_agent(d1, sync_interval=0.5)
        b = await launch_test_agent(
            d2, bootstrap=[a.gossip_addr], sync_interval=0.5
        )
        try:
            # Warm the links + schema caches.
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (-1, 'warm')"]]
            )

            async def warm():
                _, rows = b.agent.store.query(
                    Statement("SELECT count(*) FROM tests")
                )
                return rows[0][0] == 1

            await poll_until(warm, timeout=15)

            t0 = time.monotonic()
            for base in range(0, n_changes, batch):
                stmts = [
                    ["INSERT INTO tests (id, text) VALUES (?, ?)",
                     [base + j, f"v{base + j}"]]
                    for j in range(min(batch, n_changes - base))
                ]
                await a.client.execute(stmts)
            write_done = time.monotonic()

            async def converged():
                _, rows = b.agent.store.query(
                    Statement("SELECT count(*) FROM tests WHERE id >= 0")
                )
                return rows[0][0] == n_changes

            await poll_until(converged, timeout=120, interval=0.02)
            total = time.monotonic() - t0
            print(
                json.dumps(
                    {
                        "metric": "host_2node_replicated_changes_per_s",
                        "value": round(n_changes / total, 1),
                        "unit": "changes/s",
                        # 156 changes/s = the reference's quick-start log
                        # excerpt (doc/quick-start.md:119), its only
                        # published throughput figure.
                        "vs_baseline": round(n_changes / total / 156.0, 1),
                        "n_changes": n_changes,
                        "write_s": round(write_done - t0, 3),
                        "end_to_end_s": round(total, 3),
                    }
                )
            )
        finally:
            await b.stop()
            await a.stop()


async def main_fanout(n_changes: int, batch: int, n_agents: int) -> None:
    """N-agent mixed-load stress (the stress_test harness shape,
    agent.rs:3009-3224): batched writes to random agents + a sustained
    concurrent read load, then cluster-wide convergence."""
    rng = random.Random(7)
    with tempfile.TemporaryDirectory() as root:
        agents = [await launch_test_agent(f"{root}/a0")]
        for i in range(1, n_agents):
            agents.append(
                await launch_test_agent(
                    f"{root}/a{i}", bootstrap=[agents[0].gossip_addr]
                )
            )
        try:
            async def joined():
                return all(
                    len(t.agent.members.alive()) >= n_agents - 1
                    for t in agents
                )

            await poll_until(joined, timeout=30)

            reads = 0
            stop_reads = asyncio.Event()

            async def read_load():
                nonlocal reads
                while not stop_reads.is_set():
                    t = rng.choice(agents)
                    await t.client.query("SELECT count(*) FROM tests")
                    reads += 1

            readers = [asyncio.ensure_future(read_load()) for _ in range(4)]

            t0 = time.monotonic()
            for base in range(0, n_changes, batch):
                stmts = [
                    ["INSERT INTO tests (id, text) VALUES (?, ?)",
                     [base + j, f"v{base + j}"]]
                    for j in range(min(batch, n_changes - base))
                ]
                await rng.choice(agents).client.execute(stmts)
            write_done = time.monotonic()

            async def converged():
                for t in agents:
                    _, rows = t.agent.store.query(
                        Statement("SELECT count(*) FROM tests")
                    )
                    if rows[0][0] != n_changes:
                        return False
                return True

            await poll_until(converged, timeout=300, interval=0.05)
            total = time.monotonic() - t0
            stop_reads.set()
            for r in readers:
                r.cancel()
            applications = n_changes * (n_agents - 1)
            print(
                json.dumps(
                    {
                        "metric": "host_fanout_replicated_applications_per_s",
                        "value": round(applications / total, 1),
                        "unit": "applications/s",
                        "agents": n_agents,
                        "n_changes": n_changes,
                        "writes_per_s": round(n_changes / total, 1),
                        "reads_completed": reads,
                        "write_s": round(write_done - t0, 3),
                        "end_to_end_s": round(total, 3),
                    }
                )
            )
        finally:
            for t in agents:
                await t.stop()


if __name__ == "__main__":
    argv = sys.argv[1:]
    n_agents = 0
    if "--agents" in argv:
        i = argv.index("--agents")
        n_agents = int(argv[i + 1])
        del argv[i:i + 2]
    n = int(argv[0]) if argv else 10000
    batch = int(argv[1]) if len(argv) > 1 else 200
    if n_agents > 2:
        asyncio.run(main_fanout(n, batch, n_agents))
    else:
        asyncio.run(main(n, batch))
