"""Host-agent benchmark: the reference's 2-node quick-start scenario.

The only throughput number the reference publishes is a quick-start log
excerpt: 2 changes synced in 0.0128 s ≈ 156 changes/s across a 2-node
cluster (doc/quick-start.md:119, BASELINE.md). This script reproduces that
scenario with REAL agents — two in-process nodes over real TCP loopback,
writes on A via the HTTP API, convergence polled on B — and reports
end-to-end replicated changes/s.

Usage: python scripts/host_bench.py [n_changes] [batch]
Prints one JSON line.
"""

from __future__ import annotations

import asyncio
import json
import sys
import tempfile
import time

sys.path.insert(0, ".")

from corrosion_tpu.agent.testing import launch_test_agent, poll_until  # noqa: E402
from corrosion_tpu.core.values import Statement  # noqa: E402


async def main(n_changes: int, batch: int) -> None:
    with tempfile.TemporaryDirectory() as d1, tempfile.TemporaryDirectory() as d2:
        a = await launch_test_agent(d1, sync_interval=0.5)
        b = await launch_test_agent(
            d2, bootstrap=[a.gossip_addr], sync_interval=0.5
        )
        try:
            # Warm the links + schema caches.
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (-1, 'warm')"]]
            )

            async def warm():
                _, rows = b.agent.store.query(
                    Statement("SELECT count(*) FROM tests")
                )
                return rows[0][0] == 1

            await poll_until(warm, timeout=15)

            t0 = time.monotonic()
            for base in range(0, n_changes, batch):
                stmts = [
                    ["INSERT INTO tests (id, text) VALUES (?, ?)",
                     [base + j, f"v{base + j}"]]
                    for j in range(min(batch, n_changes - base))
                ]
                await a.client.execute(stmts)
            write_done = time.monotonic()

            async def converged():
                _, rows = b.agent.store.query(
                    Statement("SELECT count(*) FROM tests WHERE id >= 0")
                )
                return rows[0][0] == n_changes

            await poll_until(converged, timeout=120, interval=0.02)
            total = time.monotonic() - t0
            print(
                json.dumps(
                    {
                        "metric": "host_2node_replicated_changes_per_s",
                        "value": round(n_changes / total, 1),
                        "unit": "changes/s",
                        # 156 changes/s = the reference's quick-start log
                        # excerpt (doc/quick-start.md:119), its only
                        # published throughput figure.
                        "vs_baseline": round(n_changes / total / 156.0, 1),
                        "n_changes": n_changes,
                        "write_s": round(write_done - t0, 3),
                        "end_to_end_s": round(total, 3),
                    }
                )
            )
        finally:
            await b.stop()
            await a.stop()


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 10000
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 200
    asyncio.run(main(n, batch))
