"""CI loadgen-smoke gate: the serving plane at reduced scale vs a budget.

Runs the standing serving scenarios at CI-feasible scale — the
subscription fan-out storm (~200 streams + a sustained open-loop write
storm + pooled HTTP/PG reads) and the saturation sweep (arrival ramp
past a deliberately small ``api_concurrency``) — through the fan-out
correctness oracle, emits ONE self-describing report (platform, config
fingerprint, scenario — ``loadgen.report.emit_serving_report``), writes
it as a JSON artifact, and exits 1 when the ``serving`` entry of
bench_budget.json is breached:

- any oracle violation (exactly-once delivery, monotonic change ids) —
  never tolerance-scaled;
- a sweep that failed to engage load-shed, or whose client-side 503
  count disagrees with the server's own ``corro_api_shed_total``;
- a latency ceiling (tolerance-scaled): admitted-transaction p99,
  fan-out delivery-lag p99, sweep admitted p99.

Usage:
    python scripts/loadgen_smoke.py [--out report.json] [--budget FILE]
    python scripts/loadgen_smoke.py --update   # refresh the budget entry

``--update`` rewrites ONLY the ``serving`` entry of the budget file from
the current measurement with x3 headroom (the same policy as
bench_smoke.py; docs/SERVING.md documents the workflow). Latency
ceilings get a floor so a 0 ms loopback measurement can't make any later
nonzero one a breach.
"""

from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
)

import argparse
import asyncio
import json
import sys
import tempfile
from pathlib import Path

# Reduced CI scale (the heavy 2k/10k-stream storms are `loadgen run`
# territory and slow-marked tests, not the tier-1 gate).
SUBS = 200
WRITES = 120
WRITE_RATE = 20.0
SCENARIO = "ci_smoke"
UPDATE_HEADROOM = 3.0
# Ceiling floor for --update: loopback latencies (fan-out lag
# especially) can measure ~0 ms; a 0 ms ceiling would make ANY later
# nonzero measurement a breach.
UPDATE_FLOOR_MS = 500.0

CEILING_PATHS = (
    "run.routes.transactions.latency_ms.p99",
    "run.oracle.fanout_lag_ms.p99",
    "sweep.admitted_p99_ms_max",
)


def measure() -> dict:
    from corrosion_tpu.loadgen import scenarios
    from corrosion_tpu.loadgen.report import emit_serving_report

    async def go():
        with tempfile.TemporaryDirectory() as tmp:
            return await scenarios.full_report(
                tmp, subs=SUBS, writes=WRITES, write_rate=WRITE_RATE,
                scenario=SCENARIO, progress=sys.stderr,
            )

    return emit_serving_report(asyncio.run(go()))


def main(argv=None) -> int:
    repo = Path(__file__).resolve().parent.parent
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--budget", default=str(repo / "bench_budget.json"))
    ap.add_argument("--out", default="loadgen_smoke_report.json")
    ap.add_argument(
        "--update", action="store_true",
        help="rewrite the budget's `serving` entry from this measurement "
        f"(x{UPDATE_HEADROOM} headroom) instead of gating",
    )
    args = ap.parse_args(argv)

    from corrosion_tpu.loadgen.report import check_serving_budget
    from corrosion_tpu.sim import benchlib

    measured = measure()
    budget_path = Path(args.budget)
    full_budget = (
        json.loads(budget_path.read_text()) if budget_path.exists() else {}
    )
    if args.update:
        from corrosion_tpu.loadgen.report import _get

        def ceiling(path: str) -> float:
            cur = _get(measured, path)
            if cur is None:
                # e.g. every transaction timed out, so latency_ms never
                # materialized — refuse to write a budget from a broken
                # measurement, and say which surface vanished.
                raise SystemExit(
                    f"[loadgen-smoke] --update: measurement is missing "
                    f"{path!r} — cannot refresh the budget from it"
                )
            return round(
                max(float(cur) * UPDATE_HEADROOM, UPDATE_FLOOR_MS), 1
            )

        full_budget["serving"] = {
            "platform": measured["platform"],
            "scenario": SCENARIO,
            "subs": SUBS,
            "tolerance": full_budget.get("serving", {}).get(
                "tolerance", benchlib.DEFAULT_TOLERANCE
            ),
            "ceilings_ms": {p: ceiling(p) for p in CEILING_PATHS},
            "oracle_violations_max": 0,
            "require_shed_engaged": True,
        }
        budget_path.write_text(
            json.dumps(full_budget, indent=2) + "\n"
        )
        print(f"[loadgen-smoke] serving budget refreshed: {budget_path}")
        print(json.dumps(measured))
        return 0

    if "serving" not in full_budget:
        # Measuring without gating is how regressions pass silently.
        ok, breaches = False, [
            "serving: entry missing from budget — rerun with --update"
        ]
    else:
        ok, breaches = check_serving_budget(
            measured, full_budget["serving"]
        )
    report = {
        **measured,
        "budget": full_budget.get("serving"),
        "ok": ok,
        "breaches": breaches,
    }
    Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    print(json.dumps(report))
    if not ok:
        for b in breaches:
            print(f"[loadgen-smoke] BREACH {b}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
