"""CI endurance-soak gate: kernel + host metric series through the
leak/wedge/stall/SLO detectors vs a budget (docs/OBSERVABILITY.md
"Endurance plane").

Two CI-sized lanes, one self-describing ``corro-soak/1`` report:

- **kernel**: the seeded churned demo cluster run chunked with a
  clock-less :class:`~corrosion_tpu.obs.series.MetricSeriesRecorder` on
  ``KernelTelemetry`` (t = absolute round index) — run TWICE, and the
  two series files must be byte-identical (``determinism_ok``: replay
  determinism of the record itself is part of the gate);
- **host**: the ``soak_churn`` hostchaos scenario (WAN netem + link
  flap + SIGKILL-restart churn + write storm) with every agent
  streaming one registry snapshot per tick; the killed agent's series
  continues ``mode="a"`` across its restart, exercising the
  counter-reset rebase for real.

The ``soak`` entry of bench_budget.json gates the report
(obs/endurance.check_soak_budget): leak-slope ceilings and the wall
ceiling are tolerance-scaled; wedge/SLO/stall maxima (0), the
detectors-armed rule (a soak passing with detectors never armed is a
harness failure), and kernel series determinism are NEVER
tolerance-scaled. ``--update`` refreshes the entry with x3 headroom on
the measured leak slopes (with absolute floors so a flat run doesn't
make any later nonzero slope a breach) and rewrites SOAK_BASELINE.json
— the slim committed baseline ``obs soak diff`` gates PRs against.

The multi-minute variant is slow-marked pytest territory
(tests/test_endurance.py), not this gate.

Usage:
    python scripts/soak_smoke.py [--out report.json] [--budget FILE]
    python scripts/soak_smoke.py --update   # refresh budget + baseline
"""

from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
)

import argparse
import asyncio
import json
import sys
import tempfile
import time
from pathlib import Path

SEED = 0
UPDATE_HEADROOM = 3.0

# Kernel lane shape (CI-sized: seconds on a 2-vCPU box).
K_NODES, K_ROUNDS, K_CHUNK = 16, 48, 8

# Absolute per-hour floors for --update: a flat measured slope must not
# make any later nonzero (but harmless) slope a breach.
UPDATE_SLOPE_FLOORS = {
    "host:corro_runtime_rss_bytes": 256 * 2 ** 20,  # 256 MiB/h
    "host:corro_runtime_open_fds": 120.0,
    "host:corro_broadcast_pending": 20000.0,
    "kernel:corro_kernel_health_queue_backlog_last": 20000.0,
}
WALL_FLOOR_S = 60.0

# Host detector tuning for a CI-sized (seconds-long) churn window:
# - wedge needs a 6 s+ flat-while-offered run (fault windows last ~3 s);
# - loop-lag on a loaded CI box spikes past 0.5 s legitimately, stall
#   runs need 3 ticks > 0.75 s;
# - the in-report leak ceilings tolerate the startup allocation/socket
#   ramp a 9 s window extrapolates to hours (~0.7 GiB/h rss, ~3k fds/h
#   measured); genuine leaks (the positive controls inject 10x that)
#   still flag, and cross-PR drift is bounded by the budget's measured
#   x3 ceilings, not these;
# - fan-out lag p99 at the 10 s bucket edge / 0.9 objective: changesets
#   legitimately age seconds across flap+partition windows, but a clean
#   lane drains well under 10 s — only a genuine slow-burn pushes
#   deliveries past it;
# - probe false alarms (member removals) budgeted at 3600/h ~ 1 per
#   soak-sized window beyond the scheduled kill.
HOST_ENDURANCE_KW = dict(
    wedge_min_span_s=6.0,
    stall_threshold_s=0.75,
    leak_ceilings={
        "corro_runtime_rss_bytes": 4 * 2 ** 30,
        "corro_runtime_open_fds": 20000.0,
    },
    slos=(
        {
            "name": "fanout_lag_p99",
            "kind": "histogram",
            "series": "corro_broadcast_recv_lag_seconds",
            "threshold_s": 10.0,
            "objective": 0.90,
        },
        {
            "name": "convergence_staleness",
            "kind": "gauge",
            "series": "corro_sync_needs",
            "ceiling": 500.0,
            "objective": 0.90,
        },
        {
            "name": "probe_false_alarm_budget",
            "kind": "counter_budget",
            "series": "corro_gossip_member_removed",
            "allowed_per_hour": 3600.0,
        },
    ),
)

# Kernel lane detectors: leaks on the level-gauge watermarks + SLO burn
# on convergence staleness (gauge ceiling scaled to cluster size) and
# the SWIM false-alarm budget (t is in ROUNDS; treat a round as a
# second for rate purposes — the ceilings are calibrated in the same
# unit by --update, so the scale cancels).
KERNEL_SLOS = (
    {
        "name": "convergence_staleness",
        "kind": "gauge",
        "series": "corro_kernel_health_staleness_sum_last",
        "ceiling": 40.0 * K_NODES,
        "objective": 0.80,
    },
    {
        "name": "probe_false_alarm_budget",
        "kind": "counter_budget",
        "series": "corro_kernel_health_swim_false_alarms_last",
        "allowed_per_hour": 3600.0 * K_NODES,
    },
)

LEAK_CEILING_PATHS = tuple(UPDATE_SLOPE_FLOORS)


def run_kernel_lane(tmp: str, progress) -> dict:
    from corrosion_tpu.obs import endurance
    from corrosion_tpu.obs.series import MetricSeriesRecorder, replay_series
    from corrosion_tpu.sim import health
    from corrosion_tpu.sim.engine import simulate
    from corrosion_tpu.sim.telemetry import KernelTelemetry
    from corrosion_tpu.utils.metrics import MetricsRegistry

    def one(path: str) -> None:
        cfg, topo, sched, _kills = health.churned_demo_cluster(
            K_NODES, K_ROUNDS, churn=True, seed=SEED
        )
        reg = MetricsRegistry()
        with MetricSeriesRecorder(
            path, source="kernel", mode="w", clock=None
        ) as rec:
            tele = KernelTelemetry(
                engine="dense", registry=reg, series=rec,
                progress=progress,
            )
            simulate(
                cfg, topo, sched, seed=SEED, max_chunk=K_CHUNK,
                telemetry=tele,
            )

    p1 = _os.path.join(tmp, "kernel.series.jsonl")
    p2 = _os.path.join(tmp, "kernel.rerun.series.jsonl")
    one(p1)
    one(p2)
    with open(p1, "rb") as f:
        b1 = f.read()
    with open(p2, "rb") as f:
        b2 = f.read()
    samples = replay_series(p1)["samples"]
    end = endurance.build_report(
        samples, label="kernel", t_scale_s=1.0,
        wedge_pairs=(),  # per-chunk movement is gauge-only
        slos=KERNEL_SLOS,
    )
    return {
        "nodes": K_NODES,
        "rounds": K_ROUNDS,
        "samples": len(samples),
        "series_bytes": len(b1),
        "determinism_ok": b1 == b2,
        "endurance": end,
    }


async def run_host_lane(tmp: str, progress) -> dict:
    from corrosion_tpu.hostchaos import get_scenario, run_scenario

    spec = get_scenario("soak_churn")
    series_dir = _os.path.join(tmp, "host-series")
    _os.makedirs(series_dir, exist_ok=True)
    with tempfile.TemporaryDirectory() as d:
        # sub_costs rides along so the standing soak lane also records
        # the serving query-cost ledger (docs/SERVING.md "Query-cost
        # plane") — the leak detectors stay the gate; the ledger is
        # artifact visibility for slow cost drift across the soak.
        return await run_scenario(
            spec, d, seed=SEED, progress=progress,
            series_dir=series_dir, series_interval=0.2,
            endurance_kw=dict(HOST_ENDURANCE_KW),
            sub_costs=True,
        )


def measure(progress) -> dict:
    from corrosion_tpu.obs.endurance import SOAK_SCHEMA
    from corrosion_tpu.sim import benchlib, telemetry

    t0 = time.monotonic()
    with tempfile.TemporaryDirectory() as tmp:
        kernel = run_kernel_lane(tmp, progress)
        host = asyncio.run(run_host_lane(tmp, progress))
    report = {
        **benchlib.bench_context(
            "soak_smoke", K_NODES, K_ROUNDS, "soak_churn", SEED
        ),
        "schema": SOAK_SCHEMA,
        "scenario": "soak_smoke",
        "nodes": K_NODES,
        "seed": SEED,
        "wall_s": round(time.monotonic() - t0, 2),
        "kernel": kernel,
        "host": host,
    }
    return telemetry.check_bench_invariants(
        report, extra_provenance=("scenario",)
    )


def slim_baseline(report: dict) -> dict:
    """The committed SOAK_BASELINE.json: provenance + everything
    diff_soak reads (endurance blocks, determinism, samples), without
    the netem traces / routes / heads bulk."""
    host = report["host"]
    return {
        k: report[k]
        for k in (
            "schema", "platform", "device_count", "config_fingerprint",
            "scenario", "nodes", "seed", "wall_s",
        )
    } | {
        "kernel": {
            k: report["kernel"][k]
            for k in (
                "nodes", "rounds", "samples", "series_bytes",
                "determinism_ok", "endurance",
            )
        },
        "host": {
            "scenario": host["scenario"],
            "agents": host["agents"],
            "ok": host["ok"],
            "machinery_ok": host["machinery_ok"],
            "endurance": host["endurance"],
        },
    }


def max_slope(report: dict, path: str) -> float:
    """Largest measured slope for a ``prefix:stem`` budget path."""
    from corrosion_tpu.obs.endurance import endurance_blocks

    prefix, _, stem = path.partition(":")
    best = 0.0
    for label, blk in endurance_blocks(report).items():
        if not (label == prefix or label.startswith(prefix + ".")):
            continue
        e = blk["leaks"].get(stem)
        if e and e.get("slope_per_hour") is not None:
            best = max(best, e["slope_per_hour"])
    return best


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None, help="report JSON path")
    ap.add_argument(
        "--budget", default=str(Path(__file__).parent.parent
                                / "bench_budget.json")
    )
    ap.add_argument(
        "--baseline", default=str(Path(__file__).parent.parent
                                  / "SOAK_BASELINE.json")
    )
    ap.add_argument(
        "--update", action="store_true",
        help="rewrite the budget's `soak` entry (x3 headroom + floors) "
        "and SOAK_BASELINE.json from this measurement",
    )
    args = ap.parse_args()

    report = measure(sys.stderr)

    from corrosion_tpu.obs.endurance import check_soak_budget

    budget_path = Path(args.budget)
    budget_all = json.loads(budget_path.read_text())

    if args.update:
        entry = {
            "platform": report["platform"],
            "scenario": "soak_smoke",
            "tolerance": 3.0,
            "leak_ceilings_per_hour": {
                p: round(
                    max(
                        max_slope(report, p) * UPDATE_HEADROOM,
                        UPDATE_SLOPE_FLOORS[p],
                    ), 1,
                )
                for p in LEAK_CEILING_PATHS
            },
            "wedge_max": 0,
            "slo_breach_max": 0,
            "stall_runs_max": 0,
            "require_detectors_armed": True,
            "require_determinism": True,
            "wall_ceiling_s": round(
                max(report["wall_s"] * UPDATE_HEADROOM, WALL_FLOOR_S), 1
            ),
        }
        budget_all["soak"] = entry
        budget_path.write_text(json.dumps(budget_all, indent=2) + "\n")
        Path(args.baseline).write_text(
            json.dumps(slim_baseline(report), indent=1) + "\n"
        )
        print(f"refreshed `soak` entry in {budget_path} and "
              f"{args.baseline}")

    budget = budget_all.get("soak")
    if budget is None:
        print("bench_budget.json has no `soak` entry (run with "
              "--update)", file=sys.stderr)
        return 2
    ok, breaches = check_soak_budget(report, budget)
    report["budget_gate"] = {"ok": ok, "breaches": breaches}

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
        print(f"wrote {args.out}", file=sys.stderr)

    k = report["kernel"]
    print(
        f"kernel: samples={k['samples']} determinism="
        f"{k['determinism_ok']} endurance_ok={k['endurance']['ok']}"
    )
    h = report["host"]
    harmed = {
        name: blk["detectors_armed"]
        for name, blk in (h["endurance"] or {}).get("agents", {}).items()
    }
    print(
        f"host[{h['scenario']}]: ok={h['ok']} machinery={h['machinery']} "
        f"endurance_armed={harmed}"
    )
    if not ok:
        print("SOAK BUDGET BREACHED:", file=sys.stderr)
        for b in breaches:
            print(f"  {b}", file=sys.stderr)
        return 1
    print("soak gate ok=true breaches=[]")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
