"""CI serving-cost gate: the query-cost plane under a storm vs a budget.

Runs ONE cost-armed subscription fan-out storm at CI-feasible scale —
448 plain streams over 4 incremental-capable queries plus 64
deliberately fallback-bound window-function streams over 2 queries (512
streams total, past the 500-stream acceptance floor) — with the
per-subscription cost ledger enabled, joins the ledger with the fan-out
oracle's delivery records into the ``corro-serving-cost/1`` heatmap
report (``obs.serving.build_serving_report``), emits it through the one
self-describing path (``loadgen.report.emit_serving_report``), writes
the report + the raw ``corro-sub-cost/1`` ledger JSONL as artifacts, and
exits 1 when:

- the ``serving_cost`` entry of bench_budget.json is breached — eval/lag
  ceilings (tolerance-scaled), the fallback-share ceiling, any oracle
  violation (never scaled), a ledger that fails to reconcile exactly
  against oracle delivery counts, or the machinery-fired rule (a storm
  where no fallback-bound subscription was ever observed evaluating is a
  test-harness failure, not a pass);
- the report regresses against the committed SERVING_COST_BASELINE.json
  (``obs.serving.diff_serving_reports``).

Usage:
    python scripts/serving_cost_smoke.py [--out report.json]
    python scripts/serving_cost_smoke.py --update   # refresh budget+baseline

``--update`` rewrites ONLY the ``serving_cost`` entry of the budget file
(x3 headroom, 500 ms latency floor — same policy as loadgen_smoke.py)
AND SERVING_COST_BASELINE.json from the current measurement
(docs/SERVING.md "Query-cost plane" documents the workflow).
"""

from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
)

import argparse
import asyncio
import json
import sys
import tempfile
from pathlib import Path

# Reduced CI scale; the acceptance floor is >= 500 total streams with a
# deliberately fallback-bound window population.
SUBS = 448
SUB_GROUPS = 4
FALLBACK_SUBS = 64
FALLBACK_GROUPS = 2
WRITES = 60
WRITE_RATE = 30.0
SCENARIO = "serving_cost_smoke"
UPDATE_HEADROOM = 3.0
UPDATE_FLOOR_MS = 500.0
# Fallback share is a ratio, not a latency: headroom is additive with a
# hard sub-1.0 cap (1.0 would accept "all eval burn is fallback").
SHARE_HEADROOM = 0.2
SHARE_CAP = 0.97

CEILING_PATHS = (
    "serving.eval_ms.total",
    "serving.eval_ms.fallback",
    "serving.classes.window.lag_ms.p99",
    "serving.classes.simple.lag_ms.p99",
    "run.oracle.fanout_lag_ms.p99",
)


def measure() -> dict:
    from corrosion_tpu.loadgen import scenarios
    from corrosion_tpu.loadgen.report import (
        emit_serving_report,
        serving_context,
    )
    from corrosion_tpu.obs import serving

    async def go():
        with tempfile.TemporaryDirectory() as tmp:
            return await scenarios.fanout_storm(
                tmp, subs=SUBS, sub_groups=SUB_GROUPS,
                writes=WRITES, write_rate=WRITE_RATE,
                sub_costs=True, fallback_subs=FALLBACK_SUBS,
                fallback_groups=FALLBACK_GROUPS, progress=sys.stderr,
            )

    run = asyncio.run(go())
    rep = serving.build_serving_report(run)
    return emit_serving_report({
        **serving_context(
            SCENARIO, 1, SUBS, SUB_GROUPS, FALLBACK_SUBS, FALLBACK_GROUPS,
            WRITES,
        ),
        "streams": rep["streams"],
        "run": run,
        "serving": rep,
    })


def main(argv=None) -> int:
    repo = Path(__file__).resolve().parent.parent
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--budget", default=str(repo / "bench_budget.json"))
    ap.add_argument(
        "--baseline", default=str(repo / "SERVING_COST_BASELINE.json")
    )
    ap.add_argument("--out", default="serving_cost_report.json")
    ap.add_argument(
        "--ledger-out", default="serving_cost_ledger.jsonl",
        help="raw corro-sub-cost/1 ledger artifact path",
    )
    ap.add_argument(
        "--update", action="store_true",
        help="rewrite the budget's `serving_cost` entry "
        f"(x{UPDATE_HEADROOM} headroom) AND the committed baseline from "
        "this measurement instead of gating",
    )
    args = ap.parse_args(argv)

    from corrosion_tpu.obs import serving
    from corrosion_tpu.sim import benchlib

    measured = measure()
    # The raw ledger rides along as a self-describing artifact so a CI
    # run's per-sub counters are inspectable without re-running.
    serving.write_cost_ledger(
        args.ledger_out,
        measured["run"]["sub_costs"]["ledger"],
        context={"scenario": SCENARIO, "platform": measured["platform"]},
    )
    budget_path = Path(args.budget)
    full_budget = (
        json.loads(budget_path.read_text()) if budget_path.exists() else {}
    )
    if args.update:
        def ceiling(path: str) -> float:
            cur = benchlib.get_path(measured, path)
            if cur is None:
                raise SystemExit(
                    f"[serving-cost] --update: measurement is missing "
                    f"{path!r} — cannot refresh the budget from it"
                )
            return round(
                max(float(cur) * UPDATE_HEADROOM, UPDATE_FLOOR_MS), 1
            )

        share = measured["serving"]["fallback"]["share_of_eval_seconds"]
        full_budget["serving_cost"] = {
            "platform": measured["platform"],
            "scenario": SCENARIO,
            "streams": measured["streams"],
            "tolerance": full_budget.get("serving_cost", {}).get(
                "tolerance", benchlib.DEFAULT_TOLERANCE
            ),
            "ceilings_ms": {p: ceiling(p) for p in CEILING_PATHS},
            "fallback_share_max": round(
                min(SHARE_CAP, share + SHARE_HEADROOM), 3
            ),
            "oracle_violations_max": 0,
            "require_fallback_observed": True,
            "require_mass_reconciled": True,
        }
        budget_path.write_text(
            json.dumps(full_budget, indent=2) + "\n"
        )
        Path(args.baseline).write_text(json.dumps({
            "platform": measured["platform"],
            "scenario": SCENARIO,
            "streams": measured["streams"],
            "serving": measured["serving"],
        }, indent=2) + "\n")
        print(
            f"[serving-cost] budget + baseline refreshed: {budget_path}, "
            f"{args.baseline}"
        )
        print(json.dumps(measured))
        return 0

    if "serving_cost" not in full_budget:
        ok, breaches = False, [
            "serving_cost: entry missing from budget — rerun with --update"
        ]
    else:
        ok, breaches = serving.check_serving_cost_budget(
            measured, full_budget["serving_cost"]
        )
    base_path = Path(args.baseline)
    diff_rows: list = []
    if not base_path.exists():
        ok = False
        breaches.append(
            f"{base_path.name} missing — rerun with --update"
        )
    else:
        base = json.loads(base_path.read_text())
        diff_ok, diff_rows = serving.diff_serving_reports(
            base.get("serving", base), measured["serving"],
            tolerance=float(
                full_budget.get("serving_cost", {}).get("tolerance", 1.5)
            ),
        )
        if not diff_ok:
            ok = False
            breaches.extend(
                f"baseline regression: {r['path']} {r['base']} -> "
                f"{r['cand']} (limit {r['limit']})"
                for r in diff_rows if not r["ok"]
            )
    report = {
        **measured,
        "budget": full_budget.get("serving_cost"),
        "baseline_diff": diff_rows,
        "ok": ok,
        "breaches": breaches,
    }
    Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    print(serving.render_serving_report(measured["serving"]))
    if not ok:
        for b in breaches:
            print(f"[serving-cost] BREACH {b}", file=sys.stderr)
        return 1
    print("[serving-cost] gate ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
