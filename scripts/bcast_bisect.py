"""Micro-bisection of the broadcast-plane sub-ops at N (TPU timing).

Times each structural piece of ops/gossip.broadcast_round in isolation at
wan_100k-like shapes so optimization effort lands where the time is:
source-queue gather, base gather, 1-key vs 3-key delivery sort, watermark
scatters, CRDT merge scatter, intake/queue rebuilds.
"""

from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import sys
import time

import jax
import jax.numpy as jnp

from corrosion_tpu.ops import crdt, routing


def timed(label, fn, *args):
    f = jax.jit(fn)
    out = f(*args)
    jax.block_until_ready(jax.tree.leaves(out)[0])
    t1 = time.perf_counter()
    for _ in range(3):
        out = f(*args)
    jax.block_until_ready(jax.tree.leaves(out)[0])
    t2 = time.perf_counter()
    print(f"[{label}] step={(t2 - t1) / 3 * 1000:.0f}ms", flush=True)


def main() -> None:
    from corrosion_tpu.utils.cache import enable_persistent_cache

    enable_persistent_cache()
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    w_count, q_cap, f, n_cells, k_in = 512, 48, 3, 256, 26
    kk = f * q_cap
    key = jax.random.PRNGKey(0)
    k1, k2, k3, k4 = jax.random.split(key, 4)
    print(f"platform={jax.devices()[0].platform} n={n} kk={kk}", flush=True)

    src = jax.random.randint(k1, (n, f), 0, n)
    q_writer = jax.random.randint(k2, (n, q_cap), -1, w_count).astype(jnp.int32)
    q_ver = jax.random.randint(k3, (n, q_cap), 1, 100).astype(jnp.uint32)
    q_tx = jax.random.randint(k4, (n, q_cap), 0, 6).astype(jnp.int32)
    contig = jnp.zeros((n, w_count), jnp.uint32)
    m_w = jax.random.randint(k1, (n, kk), 0, w_count).astype(jnp.int32)
    m_v = jax.random.randint(k2, (n, kk), 1, 100).astype(jnp.uint32)
    m_tx = jax.random.randint(k3, (n, kk), 0, 6).astype(jnp.int32)
    m_ok = jax.random.uniform(k4, (n, kk)) < 0.5
    pkd = jnp.where(
        m_ok, m_w.astype(jnp.uint32) * (kk + 2) + (m_v % (kk + 1) + 1),
        jnp.uint32(w_count * (kk + 2)),
    )
    nodes = jnp.arange(n)

    timed("gather_src_queues", lambda s: (q_writer[s], q_ver[s], q_tx[s]), src)
    timed(
        "gather_base",
        lambda c, w: jnp.take_along_axis(c, jnp.maximum(w, 0), axis=1),
        contig, m_w,
    )
    timed(
        "sort3",
        lambda a, b, c: jax.lax.sort(
            (a, b, c), dimension=1, num_keys=3, is_stable=False
        ),
        jnp.where(m_ok, m_w, w_count), m_v, -m_tx,
    )
    timed("sort1", lambda a: jax.lax.sort(a, dimension=1, is_stable=False), pkd)
    rw = nodes[:, None] * w_count + m_w
    timed(
        "scatter_contig",
        lambda c, idx, v: c.reshape(-1).at[idx.reshape(-1)].max(v.reshape(-1)).reshape(n, w_count),
        contig, rw, m_v,
    )

    def crdt_merge(cells, w, v, mask):
        k, cl, cv, vr = crdt.derive_change(
            w.reshape(-1).astype(jnp.uint32), v.reshape(-1), jnp.uint32(0),
            n_cells,
        )
        flat = jnp.where(mask.reshape(-1), nodes.repeat(kk) * n_cells + k, 0)
        return crdt.apply_changes(
            cells,
            crdt.ChangeBatch(key=flat, cl=cl, col_version=cv, value_rank=vr,
                             mask=mask.reshape(-1)),
        )

    cells = crdt.make_cells(n * n_cells)
    timed("crdt_scatter", crdt_merge, cells, m_w, m_v, m_ok)
    timed(
        "intake_rebuild",
        lambda ok, v, w: routing.rebuild_bounded_queue(
            ok, -v.astype(jnp.int32), (w, v), k_in
        ),
        m_ok, m_v, m_w,
    )
    cand = q_cap + 4 + k_in
    cw = jax.random.randint(k1, (n, cand), -1, w_count).astype(jnp.int32)
    cv_ = jax.random.randint(k2, (n, cand), 1, 100).astype(jnp.uint32)
    ct = jax.random.randint(k3, (n, cand), 0, 6).astype(jnp.int32)
    timed(
        "queue_rebuild",
        lambda w, v, t: routing.rebuild_bounded_queue(
            (w >= 0) & (t > 0), t, (w, v, t), q_cap
        ),
        cw, cv_, ct,
    )
    timed(
        "seg_prefix",
        lambda fl, ss: routing.segmented_prefix_and_rows(fl, ss),
        m_ok, jnp.concatenate(
            [jnp.ones((n, 1), bool), m_w[:, 1:] != m_w[:, :-1]], axis=1
        ),
    )


if __name__ == "__main__" and (len(sys.argv) <= 2 or sys.argv[2] != "onehot"):
    main()


def onehot_bench() -> None:
    """Dense one-hot reductions vs sparse gather/scatter at wan_100k shapes:
    scatters serialize per element on TPU (~70M elem/s measured); a dense
    compare+max-reduce over the writer axis is pure VPU work."""
    from corrosion_tpu.utils.cache import enable_persistent_cache

    enable_persistent_cache()
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    w_count, q_cap, f, n_cells = 512, 48, 3, 256
    kk = f * q_cap
    key = jax.random.PRNGKey(0)
    k1, k2, k3, k4 = jax.random.split(key, 4)
    print(f"platform={jax.devices()[0].platform} n={n} kk={kk}", flush=True)
    contig = jnp.zeros((n, w_count), jnp.uint32)
    m_w = jax.random.randint(k1, (n, kk), 0, w_count).astype(jnp.int32)
    m_v = jax.random.randint(k2, (n, kk), 1, 100).astype(jnp.uint32)
    m_ok = jax.random.uniform(k4, (n, kk)) < 0.5

    def onehot_scatter_max(c, w, v, ok):
        # c[n, x] = max(c[n, x], max_k where(w[n,k]==x & ok, v, 0))
        wids = jnp.arange(w_count, dtype=jnp.int32)
        hit = (w[:, :, None] == wids[None, None, :]) & ok[:, :, None]
        return jnp.maximum(c, jnp.max(jnp.where(hit, v[:, :, None], 0), axis=1))

    timed("onehot_scatter_contig", onehot_scatter_max, contig, m_w, m_v, m_ok)

    def onehot_gather(c, w):
        wids = jnp.arange(w_count, dtype=jnp.int32)
        hit = w[:, :, None] == wids[None, None, :]
        return jnp.max(jnp.where(hit, c[:, None, :], 0), axis=2)

    timed("onehot_gather_base", onehot_gather, contig, m_w)

    # CRDT pass over 256 hashed cell keys.
    cellsN = jnp.zeros((n, n_cells), jnp.uint32)
    ckey = jax.random.randint(k3, (n, kk), 0, n_cells).astype(jnp.int32)
    pkd_in = jax.random.randint(k2, (n, kk), 1, 1 << 25).astype(jnp.uint32)

    def onehot_crdt(cells, ck, pk, ok):
        cids = jnp.arange(n_cells, dtype=jnp.int32)
        hit = (ck[:, :, None] == cids[None, None, :]) & ok[:, :, None]
        return jnp.maximum(
            cells, jnp.max(jnp.where(hit, pk[:, :, None], 0), axis=1)
        )

    timed("onehot_crdt_pass", onehot_crdt, cellsN, ckey, pkd_in, m_ok)


if __name__ == "__main__" and len(sys.argv) > 2 and sys.argv[2] == "onehot":
    onehot_bench()
