"""Per-kernel compile/step timing at a given N (bisection for the 100k path).

Usage: compile_bisect.py [n] [stage]
  stage: swim | bcast | sync | all (default all)
"""

from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu import models
from corrosion_tpu.ops import gossip as gossip_ops
from corrosion_tpu.ops import swim as swim_ops


def timed(label, fn):
    t0 = time.perf_counter()
    out = fn()
    jax.block_until_ready(jax.tree.leaves(out)[0])
    t1 = time.perf_counter()
    out = fn()
    jax.block_until_ready(jax.tree.leaves(out)[0])
    t2 = time.perf_counter()
    print(
        f"[{label}] compile+first={t1 - t0:.1f}s step={(t2 - t1) * 1000:.0f}ms",
        flush=True,
    )


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    stage = sys.argv[2] if len(sys.argv) > 2 else "all"
    midrun = "--midrun" in sys.argv
    rounds = 40 if midrun else 4
    cfg, topo, sched = models.wan_100k(n=n, rounds=rounds, samples=16)
    print(f"platform={jax.devices()[0].platform} n={n} midrun={midrun}",
          flush=True)
    key = jax.random.PRNGKey(0)

    mid_state = None
    if midrun:
        # Build realistic mid-run state (queues populated, grants flowing)
        # so plane timings reflect steady-state work, not empty-state
        # short-circuits.
        from corrosion_tpu.sim import simulate
        from corrosion_tpu.utils.cache import enable_persistent_cache

        enable_persistent_cache()
        mid_state, _ = simulate(cfg, topo, sched, seed=0, max_chunk=8)
        jax.block_until_ready(mid_state.data.contig)

    if stage in ("swim", "all"):
        impl = swim_ops.impl(cfg.swim)
        sw = impl.init_state(cfg.swim) if mid_state is None else mid_state.swim
        timed("swim", lambda: impl.swim_round(sw, key, jnp.int32(41), cfg.swim))

    if stage in ("bcast", "sync", "all"):
        data = (
            gossip_ops.init_data(cfg.gossip)
            if mid_state is None else mid_state.data
        )
        alive = jnp.ones(cfg.n_nodes, bool)
        n_regions = int(np.asarray(topo.region).max()) + 1
        part = jnp.zeros((n_regions, n_regions), bool)
        if stage in ("bcast", "all"):
            writes = jnp.asarray(sched.writes[0], jnp.uint32)
            timed(
                "bcast",
                lambda: gossip_ops.broadcast_round(
                    data, topo, alive, part, writes, key, cfg.gossip
                ),
            )
        if stage in ("sync", "all"):
            timed(
                "sync",
                lambda: gossip_ops.sync_round(
                    data, topo, alive, part, jnp.int32(0), key, cfg.gossip
                ),
            )


if __name__ == "__main__":
    main()
