"""Micro-harness for the one-hot primitive variants at data-plane shapes.

Times rowmax/rowgather/rowsum at the broadcast plane's real shapes —
inside a scanned loop so per-call dispatch does not pollute the numbers
(memory: isolated microbenches LIE on axon) — for:

- the jnp minor-most-reduce forms (production default),
- the Pallas VMEM-tiled kernels (CORRO_ONEHOT_PALLAS=1 route),

at both the wan_100k (W=512, M=144) and anywrite_sparse (W=2048, M=320)
operating points. Feeds the SCALING.md roofline iteration (VERDICT r4
next #3). Usage: python scripts/onehot_bench.py [rows]
"""

from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import json
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp


def time_scanned(fn, args, iters=20):
    @partial(jax.jit, static_argnames=("n",))
    def scan(args, n):
        def body(c, _):
            out = fn(*c)
            # Fold the output back into a carry input so the loop cannot
            # be collapsed; idx/val stay constant.
            idx, val, mask, table = c[0], c[1], c[2], c[3]
            table = table ^ out
            return (idx, val, mask, table), ()

        c, _ = jax.lax.scan(body, args, None, length=n)
        return c

    out = scan(args, iters)
    jax.block_until_ready(jax.tree.leaves(out))
    t0 = time.perf_counter()
    out = scan(args, iters)
    jax.block_until_ready(jax.tree.leaves(out))
    return (time.perf_counter() - t0) / iters * 1000.0


def main():
    from corrosion_tpu.ops import onehot
    from corrosion_tpu.utils.cache import (
        enable_persistent_cache,
        ensure_live_backend,
    )

    ensure_live_backend()
    enable_persistent_cache()
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    results = {}
    for w, m in ((512, 144), (2048, 320)):
        key = jax.random.PRNGKey(0)
        k1, k2 = jax.random.split(key)
        idx = jax.random.randint(k1, (rows, m), 0, w)
        val = jax.random.randint(k2, (rows, m), 0, 1 << 20).astype(
            jnp.uint32
        )
        mask = idx < (w - 1)
        table = jnp.zeros((rows, w), jnp.uint32)

        def f_rowmax(idx, val, mask, table):
            return onehot.rowmax(idx, val, mask, w) | table * 0

        def f_rowsum(idx, val, mask, table):
            return onehot.rowsum(idx, val, mask, w) | table * 0

        args = (idx, val, mask, table)
        for name, f in (("rowmax", f_rowmax), ("rowsum", f_rowsum)):
            ms = time_scanned(f, args)
            results[f"{name}_w{w}_m{m}"] = round(ms, 2)

        def f_gather(idx, val, mask, table):
            g = onehot.rowgather(table, idx)
            return (
                jnp.zeros((rows, w), jnp.uint32)
                .at[:, 0]
                .set(g.sum(axis=1, dtype=jnp.uint32))
            )

        results[f"rowgather_w{w}_m{m}"] = round(
            time_scanned(f_gather, args), 2
        )
        def f_gather_wide(idx, val, mask, table):
            g = onehot.rowgather_wide(table, idx)
            return (
                jnp.zeros((rows, w), jnp.uint32)
                .at[:, 0]
                .set(g.sum(axis=1, dtype=jnp.uint32))
            )

        results[f"rowgather_wide_w{w}_m{m}"] = round(
            time_scanned(f_gather_wide, args), 2
        )
    results["pallas"] = _os.environ.get("CORRO_ONEHOT_PALLAS", "0")
    results["rows"] = rows
    print(json.dumps(results))


if __name__ == "__main__":
    main()
