"""CI fidelity-smoke gate: the mixed-mode divergence report vs a budget.

Runs the three standing fidelity scenarios at CI-feasible scale — the
steady-load and burst-drain mixed-mode comparisons (a live 3-agent
loopback cluster vs the kernel replay of its recorded workload,
calibrated and uncalibrated) and the DCN-scale partition-and-heal
kernel scenario — emits ONE self-describing report (platform, nodes,
config fingerprint, scenario, trace fingerprint —
``fidelity.report.emit_fidelity_report``), writes it as a JSON artifact,
and exits 1 when the ``fidelity`` entry of bench_budget.json is
breached:

- the calibrated replay failing to land STRICTLY closer to the live
  visibility CDF than the uncalibrated replay (per scenario) — never
  tolerance-scaled: this ordering is the subsystem's reason to exist;
- the DCN scenario's chaos-invariant cross-check failing — never
  tolerance-scaled;
- any (live or calibrated-replay) write that never became visible;
- a divergence ceiling (tolerance-scaled): calibrated CDF distance and
  p99 bucket delta per mixed-mode scenario, the DCN recovery delta.

Usage:
    python scripts/fidelity_smoke.py [--out report.json] [--budget FILE]
    python scripts/fidelity_smoke.py --update   # refresh the budget entry

``--update`` rewrites ONLY the ``fidelity`` entry of the budget file
from the current measurement with x3 headroom (the same policy as
bench_smoke.py / loadgen_smoke.py; docs/FIDELITY.md documents the
workflow). Ceilings get floors so a quiet-box measurement can't make any
later noisier one a breach.
"""

from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
)

import argparse
import asyncio
import json
import sys
import tempfile
from pathlib import Path

# Reduced CI scale.
STEADY_WRITES = 24
BURST_WRITES = 24
DCN_ROUNDS = 64
SCENARIO = "ci_smoke"
UPDATE_HEADROOM = 3.0
# Ceiling floors for --update: a quiet loopback box can measure tiny
# divergences; a near-zero ceiling would make ANY later run a breach.
FLOOR = {
    "cdf_distance": 1.0,  # EMD in bucket units (compare.divergence_verdict)
    "p99_bucket_delta": 1.0,
    "recovery_delta_rounds": 4.0,
}

CEILING_PATHS = (
    ("scenarios.steady.calibrated.cdf_distance", "cdf_distance"),
    ("scenarios.steady.calibrated.p99_bucket_delta", "p99_bucket_delta"),
    ("scenarios.burst.calibrated.cdf_distance", "cdf_distance"),
    ("scenarios.burst.calibrated.p99_bucket_delta", "p99_bucket_delta"),
    ("scenarios.dcn.recovery_delta_rounds", "recovery_delta_rounds"),
)


def measure() -> dict:
    from corrosion_tpu.fidelity import scenarios
    from corrosion_tpu.fidelity.report import emit_fidelity_report

    async def go():
        with tempfile.TemporaryDirectory() as tmp:
            return await scenarios.full_report(
                tmp, scenario=SCENARIO, steady_writes=STEADY_WRITES,
                burst_writes=BURST_WRITES, dcn_rounds=DCN_ROUNDS,
                progress=sys.stderr,
            )

    return emit_fidelity_report(asyncio.run(go()))


def main(argv=None) -> int:
    repo = Path(__file__).resolve().parent.parent
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--budget", default=str(repo / "bench_budget.json"))
    ap.add_argument("--out", default="fidelity_smoke_report.json")
    ap.add_argument(
        "--update", action="store_true",
        help="rewrite the budget's `fidelity` entry from this measurement "
        f"(x{UPDATE_HEADROOM} headroom) instead of gating",
    )
    args = ap.parse_args(argv)

    from corrosion_tpu.fidelity.report import _get, check_fidelity_budget
    from corrosion_tpu.sim import benchlib

    measured = measure()
    budget_path = Path(args.budget)
    full_budget = (
        json.loads(budget_path.read_text()) if budget_path.exists() else {}
    )
    if args.update:

        def ceiling(path: str, kind: str) -> float:
            cur = _get(measured, path)
            if cur is None:
                # e.g. a replay never converged so the metric never
                # materialized — refuse to write a budget from a broken
                # measurement, and say which surface vanished.
                raise SystemExit(
                    f"[fidelity-smoke] --update: measurement is missing "
                    f"{path!r} — cannot refresh the budget from it"
                )
            return round(
                max(abs(float(cur)) * UPDATE_HEADROOM, FLOOR[kind]), 4
            )

        full_budget["fidelity"] = {
            "platform": measured["platform"],
            "scenario": SCENARIO,
            "tolerance": full_budget.get("fidelity", {}).get(
                "tolerance", benchlib.DEFAULT_TOLERANCE
            ),
            "ceilings": {p: ceiling(p, k) for p, k in CEILING_PATHS},
            # The ordering and correctness keys are ABSOLUTE — --update
            # must never loosen them.
            "require_calibrated_closer": True,
            "require_invariants_ok": True,
            "unseen_max": 0,
        }
        budget_path.write_text(
            json.dumps(full_budget, indent=2) + "\n"
        )
        print(f"[fidelity-smoke] fidelity budget refreshed: {budget_path}")
        print(json.dumps(measured))
        return 0

    if "fidelity" not in full_budget:
        # Measuring without gating is how regressions pass silently.
        ok, breaches = False, [
            "fidelity: entry missing from budget — rerun with --update"
        ]
    else:
        ok, breaches = check_fidelity_budget(
            measured, full_budget["fidelity"]
        )
    report = {
        **measured,
        "budget": full_budget.get("fidelity"),
        "ok": ok,
        "breaches": breaches,
    }
    Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    print(json.dumps(report))
    if not ok:
        for b in breaches:
            print(f"[fidelity-smoke] BREACH {b}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
