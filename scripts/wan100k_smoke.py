"""On-chip validation for the 100k-node WAN config (BASELINE config 5).

Initializes the full wan_100k cluster (sparse SWIM kernel) on the real
device, runs a bounded number of rounds, and prints state size + step time.
This is the memory-plan check: 100k nodes must fit and run on one chip.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import numpy as np

from corrosion_tpu import models
from corrosion_tpu.ops import swim_sparse
from corrosion_tpu.sim import simulate


def main() -> None:
    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    cfg, topo, sched = models.wan_100k(rounds=rounds, samples=64)
    t0 = time.perf_counter()
    final, curves = simulate(cfg, topo, sched, seed=0, max_chunk=8)
    jax.block_until_ready(final.data.contig)
    wall = time.perf_counter() - t0

    state_bytes = sum(
        x.size * x.dtype.itemsize
        for x in jax.tree.leaves((final.swim, final.data))
    )
    print(
        json.dumps(
            {
                "platform": jax.devices()[0].platform,
                "nodes": cfg.n_nodes,
                "rounds": rounds,
                "wall_s": round(wall, 2),
                "step_ms": round(wall / rounds * 1000.0, 1),
                "state_mib": round(state_bytes / 2**20, 1),
                "swim_bytes_per_node": swim_sparse.state_bytes_per_node(
                    cfg.swim
                ),
                "applied": int(
                    curves["applied_broadcast"].sum()
                    + curves["applied_sync"].sum()
                ),
                "mismatches_last": int(curves["mismatches"][-1]),
            }
        )
    )


if __name__ == "__main__":
    main()
