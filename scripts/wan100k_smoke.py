"""On-chip validation for the 100k-node WAN config (BASELINE config 5).

Initializes the full wan_100k cluster (sparse SWIM kernel) on the real
device, runs the scheduled rounds (mid-run partition of region 0 included),
and prints state size, step time, and the north-star metric: p99 change
visibility in simulated seconds (BASELINE.md: < 10 s at 100k nodes).

Visibility is reported twice: over ALL sampled writes, and over the writes
not affected by the scheduled partition (steady-state) — a write originating
in a region that is cut off for 30 simulated seconds cannot be visible
elsewhere before the heal, so the overall p99 measures partition recovery,
not propagation speed.

The run is instrumented by the kernel telemetry plane (sim/telemetry.py):
every chunk execution prints a progress line to stderr (long 100k runs no
longer go dark for minutes), and ``--flight PATH`` additionally streams
per-round curves to a replayable JSONL flight record.

Usage: python scripts/wan100k_smoke.py [rounds] [--steady] [--steptime]
       [--flight[=PATH]]
"""

from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import json
import sys
import time

import jax
import numpy as np

from corrosion_tpu import models
from corrosion_tpu.ops import swim_sparse
from corrosion_tpu.sim import health, simulate, visibility_latencies
from corrosion_tpu.sim.telemetry import (
    FlightRecorder,
    KernelTelemetry,
    flight_path_from_argv,
)

# Device executions per dispatch (watchdog-safe at current step times;
# the steptime warm slice must equal this so the timed window never
# compiles).
CHUNK = 16


def main() -> None:
    from corrosion_tpu.utils.cache import (
        enable_persistent_cache,
        ensure_live_backend,
    )

    ensure_live_backend()
    enable_persistent_cache()
    steady = "--steady" in sys.argv  # no partition: pure propagation p99
    steptime = "--steptime" in sys.argv  # warm-chunk step timing only
    flight = flight_path_from_argv(sys.argv)
    nums = [a for a in sys.argv[1:] if not a.startswith("-")]
    rounds = int(nums[0]) if nums else 16
    cfg, topo, sched = models.wan_100k(
        rounds=rounds, samples=256, partition=not steady
    )
    if steptime:
        # Warm-up one full-size chunk (compile), then time the SAME
        # compiled scan over the next chunks: per-round time without
        # compile skew. The warm slice must match max_chunk, or the timed
        # window compiles a different scan length.
        import dataclasses

        if flight:
            print(
                "[wan100k] --flight is ignored with --steptime: the "
                "recorder's per-chunk JSONL flush would skew the timed "
                "window",
                file=sys.stderr,
            )

        ck = CHUNK
        if rounds - ck <= 0 or (rounds - ck) % ck != 0:
            raise SystemExit(
                f"--steptime needs rounds = warm({ck}) + k*{ck} timed "
                f"(e.g. 48); got {rounds} — the timed window would "
                f"compile a differently-sized scan and skew step_ms"
            )
        warm = dataclasses.replace(
            sched, writes=sched.writes[:ck],
            partition=None if sched.partition is None else sched.partition[:ck],
        )
        state, _ = simulate(cfg, topo, warm, seed=0, max_chunk=ck)
        jax.block_until_ready(state.data.contig)
        rest = dataclasses.replace(
            sched, writes=sched.writes[ck:],
            partition=None if sched.partition is None else sched.partition[ck:],
        )
        t0 = time.perf_counter()
        state, _ = simulate(cfg, topo, rest, seed=0, state=state, max_chunk=ck)
        jax.block_until_ready(state.data.contig)
        wall = time.perf_counter() - t0
        from corrosion_tpu.sim import benchlib, telemetry

        print(json.dumps(telemetry.check_bench_invariants({
            **benchlib.bench_context(cfg, rounds, ck),
            "nodes": cfg.n_nodes,
            "mode": "steptime",
            "rounds_timed": rounds - ck,
            "step_ms": round(wall / max(rounds - ck, 1) * 1000.0, 1),
        })))
        return
    tele = KernelTelemetry(
        engine="dense",
        progress=sys.stderr,
        recorder=(
            FlightRecorder(flight, engine="dense") if flight else None
        ),
    )
    t0 = time.perf_counter()
    final, curves = simulate(
        cfg, topo, sched, seed=0, max_chunk=CHUNK, telemetry=tele
    )
    jax.block_until_ready(final.data.contig)
    wall = time.perf_counter() - t0
    if tele.recorder is not None:
        tele.recorder.close()

    state_bytes = sum(
        x.size * x.dtype.itemsize
        for x in jax.tree.leaves((final.swim, final.data))
    )
    lat = visibility_latencies(final, sched, cfg)

    # Steady-state split: samples whose write round overlaps the partition
    # window AND whose writer sits in the cut-off region (or whose
    # observers include it before the heal) measure partition recovery.
    # wan_100k cuts region 0 for rounds [60, 120).
    from corrosion_tpu.sim import benchlib

    out = {
        **benchlib.bench_context(cfg, rounds, steady),
        "steady": steady,
        "nodes": cfg.n_nodes,
        "rounds": rounds,
        "wall_s": round(wall, 2),
        "step_ms": round(wall / rounds * 1000.0, 1),
        "step_inner_ms": round(tele.device_step_ms, 1),
        "state_mib": round(state_bytes / 2**20, 1),
        "swim_bytes_per_node": swim_sparse.state_bytes_per_node(cfg.swim),
        "applied": int(
            curves["applied_broadcast"].sum() + curves["applied_sync"].sum()
        ),
        "mismatches_last": int(curves["mismatches"][-1]),
        # Window saturation instrumentation (VERDICT r4 weak #4 / ADVICE
        # #2): arrivals that degraded to seen-only (beyond window_k), and
        # sync budget spent re-granting window-possessed versions.
        "window_degraded": int(curves["window_degraded"].sum()),
        "sync_regrant": int(curves["sync_regrant"].sum()),
        "converged": bool(
            (np.asarray(final.data.contig)
             == np.asarray(final.data.head)[None, :]).all()
        ),
        "vis_p50_s": round(lat["p50_s"], 2),
        "vis_p99_s": round(lat["p99_s"], 2),
        "unseen_pairs": lat["unseen"],
    }
    # Convergence health plane: run-level verdicts from the round curves
    # (identical derivation to `obs report` on the --flight record).
    rep = health.report_from_curves(
        curves, engine="dense", round_ms=cfg.round_ms
    )
    out.update({
        "converged_round": rep.converged_round,
        "staleness_p99": round(rep.staleness_p99, 1),
        "staleness_peak_node": rep.staleness_max_peak,
        # JSON-safe serializer: overflow percentiles render "inf".
        "vis_hist_p50_s": rep.to_dict()["vis_p50_s"],
        "vis_hist_p99_s": rep.to_dict()["vis_p99_s"],
        "queue_backlog_peak": rep.queue_backlog_peak,
        "swim_false_alarms": int(rep.false_alarms_total),
    })
    if rounds >= 120 and sched.partition is not None:
        # Every write committed while region 0 is cut (rounds [60, 120)) has
        # unreachable observers until the heal — and writes up to ~2 sync
        # intervals BEFORE the cut may not have drained into region 0 yet.
        # Those samples measure partition recovery, not propagation.
        affected = (sched.sample_round >= 36) & (sched.sample_round < 120)
        steady = ~affected

        def _sub(mask):
            import dataclasses

            sub = dataclasses.replace(
                sched,
                sample_writer=sched.sample_writer[mask],
                sample_ver=sched.sample_ver[mask],
                sample_round=sched.sample_round[mask],
            )
            vis = np.asarray(final.vis_round)[mask]
            fake = final._replace(vis_round=vis)
            return visibility_latencies(fake, sub, cfg)

        lat_steady = _sub(steady)
        lat_part = _sub(affected)
        out["vis_steady_p50_s"] = round(lat_steady["p50_s"], 2)
        out["vis_steady_p99_s"] = round(lat_steady["p99_s"], 2)
        out["steady_samples"] = int(steady.sum())
        out["vis_partition_p99_s"] = round(lat_part["p99_s"], 2)
        out["partition_samples"] = int(affected.sum())
    from corrosion_tpu.sim import telemetry as telemetry_mod

    print(json.dumps(telemetry_mod.check_bench_invariants(out)))


if __name__ == "__main__":
    main()
