"""CI elastic-survival gate: reshard + preemption drills vs a budget
(docs/SCALING.md "Elastic ops").

The CI-sized lane runs four standing drills from the elastic scenario
catalog (corrosion_tpu/elastic/scenarios.py) on the 8-virtual-device
CPU mesh and wraps them in one self-describing ``corro-elastic-smoke/1``
report:

- **reshard_dense_4to8** / **reshard_dense_8to4**: mid-run checkpoint
  at a chunk boundary, re-place through the mesh spec builders onto the
  other device count (with a byte-exact ``predicted_per_device_bytes``
  reconcile before resume), and pin the resumed run BIT-IDENTICAL to
  the uninterrupted same-seed run on the target mesh;
- **preempt_dense_churn**: hard device-shard kills mid-run under an
  active churn/loss fault plan, recovery from the last checkpoint +
  deterministic gap replay, gated by the full dense invariant suite AND
  the machinery-fired rule (idle recovery counters = harness failure);
- **soak_preempt**: the same preempted run streamed through the
  endurance metric-series recorder — the counter-reset classifier must
  label every recovery a ``restart`` (not a leak/wedge fake) and the
  detectors must stay armed across the events.

The ``elastic`` entry of bench_budget.json gates the report
(elastic/report.check_elastic_budget): per-scenario wall ceilings are
tolerance-scaled; bit-identity, the byte-exact reconcile, zero oracle
violations, and the machinery-fired rule are NEVER tolerance-scaled.
``--update`` refreshes the entry with x3 headroom on the measured wall
times. The full (D -> D') x engine matrix is `corrosion_tpu elastic
matrix` / slow-marked pytest territory (tests/test_elastic.py), not
this gate.

Usage:
    python scripts/elastic_smoke.py [--out report.json] [--budget FILE]
    python scripts/elastic_smoke.py --update   # refresh budget entry
"""

from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
)
# Must run before jax initializes a backend: the drills need >= 8
# devices, which off real multi-chip hardware means the virtual CPU mesh.
_flags = _os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import argparse
import json
import sys
import tempfile
import time
from pathlib import Path

SEED = 0
UPDATE_HEADROOM = 3.0
# Absolute floor for --update wall ceilings: a fast measured run on an
# idle box must not make a normal run on a loaded CI host a breach.
WALL_FLOOR_S = 30.0

# The CI drill set. The dense 4->8 / 8->4 pair is the acceptance bar's
# hard bit-identity assertion; the rest of the reshard matrix (8->2,
# 1->8, sparse/chunk/mixed) runs in the multichip job's full-matrix
# step and the slow-marked tests.
SCENARIOS = (
    "reshard_dense_4to8",
    "reshard_dense_8to4",
    "preempt_dense_churn",
    "soak_preempt",
)

# Node count of the dense reshard drills (models.wan_100k CI shape);
# preempt drills run at invariants.STD_NODES and carry their own count.
NODES = 64


def measure(log=sys.stderr) -> dict:
    from corrosion_tpu.elastic import report as report_mod
    from corrosion_tpu.elastic import scenarios as scenarios_mod
    from corrosion_tpu.sim import benchlib, telemetry

    t0 = time.monotonic()
    scens = []
    with tempfile.TemporaryDirectory(prefix="elastic_smoke_") as td:
        for name in SCENARIOS:
            t = time.monotonic()
            scen = scenarios_mod.run_scenario(
                name, seed=SEED,
                checkpoint_dir=str(Path(td) / name),
                series_path=str(Path(td) / f"{name}.series.jsonl"),
            )
            scens.append(scen)
            print(
                f"  {name}: ok={scen['ok']} "
                f"bit_identical={scen.get('bit_identical')} "
                f"wall={report_mod.wall_total(scen):.1f}s "
                f"({time.monotonic() - t:.1f}s incl. reference)",
                file=log,
            )

    report = {
        **benchlib.bench_context("elastic_smoke", SCENARIOS, SEED),
        "schema": "corro-elastic-smoke/1",
        "scenario": "elastic_smoke",
        "nodes": NODES,
        "seed": SEED,
        "scenarios": scens,
        "ok": all(s["ok"] for s in scens),
        "wall_s": round(time.monotonic() - t0, 2),
    }
    return telemetry.check_bench_invariants(
        report, extra_provenance=("scenario",)
    )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None, help="report JSON path")
    ap.add_argument(
        "--budget", default=str(Path(__file__).parent.parent
                                / "bench_budget.json")
    )
    ap.add_argument(
        "--update", action="store_true",
        help="rewrite the budget's `elastic` entry "
        f"(x{UPDATE_HEADROOM} headroom on wall ceilings) from this "
        "measurement instead of just gating",
    )
    args = ap.parse_args()

    report = measure(sys.stderr)

    from corrosion_tpu.elastic.report import (
        check_elastic_budget, wall_total,
    )

    budget_path = Path(args.budget)
    budget_all = json.loads(budget_path.read_text())

    if args.update:
        entry = {
            "platform": report["platform"],
            "scenario": "elastic_smoke",
            "tolerance": 3.0,
            # Survival invariants: NEVER tolerance-scaled.
            "require_bit_identical": 1,
            "require_reconcile": 1,
            "require_machinery_fired": 1,
            "oracle_violations_max": 0,
            "scenarios": {
                s["scenario"]: {
                    "wall_ceiling_s": round(
                        max(
                            wall_total(s) * UPDATE_HEADROOM,
                            WALL_FLOOR_S,
                        ), 1,
                    )
                }
                for s in report["scenarios"]
            },
        }
        budget_all["elastic"] = entry
        budget_path.write_text(json.dumps(budget_all, indent=2) + "\n")
        print(f"refreshed `elastic` entry in {budget_path}")

    budget = budget_all.get("elastic")
    if budget is None:
        print("bench_budget.json has no `elastic` entry (run with "
              "--update)", file=sys.stderr)
        return 2
    gate = check_elastic_budget(report, budget)
    report["budget_gate"] = gate

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1, default=str)
        print(f"wrote {args.out}", file=sys.stderr)

    for s in report["scenarios"]:
        mach = s.get("machinery")
        print(
            f"{s['scenario']}: ok={s['ok']} "
            f"bit_identical={s.get('bit_identical')} "
            f"reconcile={(s.get('reconcile') or {}).get('ok')} "
            f"violations={len(s.get('violations') or [])}"
            + (f" machinery_fired={mach.get('fired')}" if mach else "")
        )
    if not gate["ok"]:
        print("ELASTIC BUDGET BREACHED:", file=sys.stderr)
        for b in gate["breaches"]:
            print(f"  {b}", file=sys.stderr)
        return 1
    print("elastic gate ok=true breaches=[]")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
