"""CI host-chaos smoke gate: WAN steady-state + SIGKILL-restart vs a
budget (docs/CHAOS.md "Host plane").

Runs the two CI-scale standing scenarios — ``wan_steady`` (80 ms RTT ±
jitter + 1 % loss over real loopback agents, oracle-checked fan-out) and
``kill_restart`` (SIGKILL mid-storm, same-dir restart, durable-sub
resume) — emits ONE self-describing report
(``hostchaos.report.emit_hostchaos_report``), writes it as a JSON
artifact, and exits 1 when the ``hostchaos`` entry of bench_budget.json
is breached:

- any fan-out-oracle violation — never tolerance-scaled;
- a scenario whose REQUIRED defensive machinery never fired (the
  mechanical "the defenses actually engaged" proof) — never
  tolerance-scaled;
- failed post-heal invariants (CRDT agreement, bookkeeping contiguity,
  convergence) — never tolerance-scaled;
- a drain/convergence wall-time ceiling (tolerance-scaled).

The long flap/partition soak (``flap_soak``) and the full acceptance
scenario (``wan_full``) are slow-marked pytest territory (the chaos CI
job), not this gate.

Usage:
    python scripts/hostchaos_smoke.py [--out report.json] [--budget FILE]
    python scripts/hostchaos_smoke.py --update   # refresh the budget entry
"""

from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
)

import argparse
import asyncio
import json
import sys
import tempfile
from pathlib import Path

SCENARIOS = ("wan_steady", "kill_restart")
SEED = 0
UPDATE_HEADROOM = 3.0
# Floor for --update: a fast loopback drain must not make any later
# nonzero drain a breach.
UPDATE_FLOOR_S = 10.0

CEILING_PATHS = tuple(
    f"scenarios.{name}.{key}"
    for name in SCENARIOS
    for key in ("drain_s", "convergence_s")
)


async def measure(progress) -> dict:
    from corrosion_tpu.hostchaos import get_scenario, run_scenario
    from corrosion_tpu.hostchaos.report import (
        emit_hostchaos_report,
        hostchaos_context,
    )

    blocks: dict[str, dict] = {}
    for name in SCENARIOS:
        spec = get_scenario(name)
        with tempfile.TemporaryDirectory() as d:
            blocks[name] = await run_scenario(
                spec, d, seed=SEED, progress=progress
            )
    nodes = max(b["agents"] for b in blocks.values())
    report = {
        **hostchaos_context(nodes, *SCENARIOS, SEED),
        "seed": SEED,
        "scenarios": blocks,
    }
    return emit_hostchaos_report(report)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None, help="report JSON path")
    ap.add_argument(
        "--budget", default=str(Path(__file__).parent.parent
                                / "bench_budget.json")
    )
    ap.add_argument(
        "--update", action="store_true",
        help="rewrite the budget's `hostchaos` entry from this "
        "measurement with x3 headroom",
    )
    args = ap.parse_args()

    report = asyncio.run(measure(sys.stderr))

    from corrosion_tpu.hostchaos.report import check_hostchaos_budget
    from corrosion_tpu.sim import benchlib

    budget_path = Path(args.budget)
    budget_all = json.loads(budget_path.read_text())

    if args.update:
        entry = {
            "platform": report["platform"],
            "scenario": "host_chaos_smoke",
            "scenarios": list(SCENARIOS),
            "tolerance": 3.0,
            "ceilings_s": {
                p: round(
                    max(
                        float(benchlib.get_path(report, p) or 0.0)
                        * UPDATE_HEADROOM,
                        UPDATE_FLOOR_S,
                    ), 1,
                )
                for p in CEILING_PATHS
            },
            "oracle_violations_max": 0,
            "require_machinery_fired": True,
            "require_converged": True,
        }
        budget_all["hostchaos"] = entry
        budget_path.write_text(json.dumps(budget_all, indent=2) + "\n")
        print(f"refreshed `hostchaos` entry in {budget_path}")

    budget = budget_all.get("hostchaos")
    if budget is None:
        print("bench_budget.json has no `hostchaos` entry "
              "(run with --update)", file=sys.stderr)
        return 2
    ok, breaches = check_hostchaos_budget(report, budget)
    report["budget_gate"] = {"ok": ok, "breaches": breaches}

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
        print(f"wrote {args.out}", file=sys.stderr)

    for name in SCENARIOS:
        blk = report["scenarios"][name]
        print(
            f"{name}: ok={blk['ok']} violations="
            f"{blk['oracle']['violations']} machinery={blk['machinery']} "
            f"drain={blk['drain_s']}s"
        )
    if not ok:
        print("HOSTCHAOS BUDGET BREACHED:", file=sys.stderr)
        for b in breaches:
            print(f"  {b}", file=sys.stderr)
        return 1
    print("hostchaos gate ok=true breaches=[]")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
