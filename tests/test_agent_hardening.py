"""Hardening behaviors of the host agent's background loops.

- Pending-broadcast byte budget (the reference's 64 KiB buffer cutoff,
  broadcast/mod.rs:357): a member-less agent under sustained writes holds
  bounded memory, and a late-joining peer still converges via sync.
- Streak-dampened failure logging in the SWIM and sync loops (one WARNING
  per failure streak, DEBUG thereafter — the _compact_loop pattern).
"""

import asyncio
import logging

from corrosion_tpu.agent.testing import launch_test_agent, poll_until


def run(coro):
    return asyncio.run(coro)


def test_memberless_buffer_bounded_then_peer_converges(tmp_path):
    async def main():
        a = await launch_test_agent(
            str(tmp_path / "a"), broadcast_buffer_bytes=2048
        )
        try:
            # 1k writes with no peer: frames accumulate; never-sent local
            # frames survive to 8x the soft budget, then shed oldest —
            # memory stays bounded either way.
            for i in range(0, 1000, 50):
                stmts = [
                    ["INSERT INTO tests (id, text) VALUES (?, ?)",
                     [j, f"row-{j}"]]
                    for j in range(i, i + 50)
                ]
                await a.client.execute(stmts)
            # Let at least one flush tick observe the member-less state.
            await asyncio.sleep(a.agent.cfg.broadcast_interval * 3)
            assert a.agent._pending_bytes <= 8 * 2048
            assert len(a.agent._pending) < 1000
            assert a.agent._m_bcast_dropped.get() > 0
            assert a.agent._m_bcast_pending_bytes.get() == (
                a.agent._pending_bytes
            )

            # A peer that joins NOW recovers everything: the surviving
            # buffered frames via broadcast, the dropped ones via sync.
            b = await launch_test_agent(
                str(tmp_path / "b"), bootstrap=[a.gossip_addr]
            )
            try:
                async def caught_up():
                    _, rows = await b.client.query(
                        "SELECT count(*) FROM tests"
                    )
                    return rows[0][0] == 1000

                await poll_until(caught_up, timeout=30.0)
            finally:
                await b.stop()
        finally:
            await a.stop()

    run(main())


def test_fallback_full_diff_is_rate_limited(tmp_path):
    """An aggregate subscription over a large table must not re-scan per
    change batch: once an evaluation proves expensive, intervening batches
    coalesce into one deferred re-snapshot per interval (VERDICT r3 #9;
    the reference's candidate path never full-scans, pubsub.rs:1303-1570)."""
    from corrosion_tpu.agent.store import Store
    from corrosion_tpu.agent.subs import MatcherHandle
    from corrosion_tpu.core.values import Change, pack_columns

    store = Store(str(tmp_path / "big.db"), bytes(range(16)))
    store.apply_schema(
        "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY,"
        " text TEXT NOT NULL DEFAULT '')"
    )
    # 30k rows: the table only needs to be big enough that a wrongly
    # re-run scan would be visible — "expensive" classification itself
    # is FORCED below (FALLBACK_EVAL_BUDGET = 0), so the row count buys
    # no extra coverage, and the 100k it used to be cost ~50 s of
    # trigger-driven insert time per test in the tier-1 budget.
    store.conn.executemany(
        "INSERT INTO tests (id, text) VALUES (?, ?)",
        [(i, f"r{i}") for i in range(30_000)],
    )
    store.conn.commit()

    h = MatcherHandle(store, "SELECT count(*), sum(id) FROM tests")
    try:
        # Aggregates have no PK identity: every batch is a fallback.
        assert h._pk_prefix == 0
        # Force the "expensive" classification deterministically (the
        # default budget is wall-clock based).
        h.FALLBACK_EVAL_BUDGET = 0.0
        h.FALLBACK_MIN_INTERVAL = 60.0

        evals = 0
        orig = h._evaluate

        def counting():
            nonlocal evals
            evals += 1
            return orig()

        h._evaluate = counting
        ch = Change(
            table="tests", pk=pack_columns((1,)), cid="text", val="x",
            col_version=2, db_version=1, seq=0, site_id=bytes(16), cl=1,
        )
        # First fallback runs (and flags the sub expensive)...
        h.process([ch])
        assert evals == 1 and h._full_expensive
        # ...then 50 further batches coalesce: zero evaluations.
        for _ in range(50):
            h.process([ch])
        assert evals == 1
        assert h._dirty
        # The deferred flush (here: explicit, as no loop runs) emits the
        # events that accumulated.
        store.conn.execute("DELETE FROM tests WHERE id >= 15000")
        store.conn.commit()
        h._dirty = False
        events = h.process(None)  # what _flush_deferred runs
        assert evals == 2
        assert any(ev.cells == [15000, 112492500] for ev in events)
    finally:
        h.close()
        store.close()


def test_fallback_scan_runs_off_the_event_loop(tmp_path):
    """VERDICT r4 weak #6: when the rate-limited re-snapshot of an
    expensive (aggregate) sub DOES fire inside a running event loop, the
    table scan must not stall the match loop — it runs on a worker
    thread with its own read connection, and process() stays fast. The
    final stream is still correct once the background pass lands."""
    import time as _time

    from corrosion_tpu.agent.store import Store
    from corrosion_tpu.agent.subs import MatcherHandle
    from corrosion_tpu.core.values import Change, pack_columns

    store = Store(str(tmp_path / "big.db"), bytes(range(16)))
    store.apply_schema(
        "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY,"
        " text TEXT NOT NULL DEFAULT '')"
    )
    # 30k rows (down from 100k; see the rate-limit test above): the
    # stall assertion below is tightened to match — an INLINE 30k-row
    # aggregate scan still takes well over the bound on any box, so a
    # regression to on-loop scanning keeps failing loudly.
    store.conn.executemany(
        "INSERT INTO tests (id, text) VALUES (?, ?)",
        [(i, f"r{i}") for i in range(30_000)],
    )
    store.conn.commit()

    async def main():
        h = MatcherHandle(store, "SELECT count(*), sum(id) FROM tests")
        try:
            h.FALLBACK_EVAL_BUDGET = 0.0  # everything counts as expensive
            h.FALLBACK_MIN_INTERVAL = 0.05
            ch = Change(
                table="tests", pk=pack_columns((1,)), cid="text", val="x",
                col_version=2, db_version=1, seq=0, site_id=bytes(16),
                cl=1,
            )
            h.process([ch])  # initial sync pass flags the sub expensive
            assert h._full_expensive
            store.conn.execute("DELETE FROM tests WHERE id >= 15000")
            store.conn.commit()
            h.process([ch])  # within interval: defers
            await asyncio.sleep(0.06)
            # Overdue now: this call must hand off to the background scan
            # and return immediately — bounded loop time even though the
            # full evaluation scans the whole table.
            t0 = _time.monotonic()
            out = h.process([ch])
            took = _time.monotonic() - t0
            assert out == [] and took < 0.02, (
                f"process() stalled the loop for {took:.3f}s"
            )
            # The re-snapshot ran OFF the loop: either the bg task is
            # still in flight, or the timer-armed deferred flush (due at
            # FALLBACK_MIN_INTERVAL, i.e. during the sleep above) already
            # started-and-landed it on a slow/loaded machine — in which
            # case the result is in history and the call above correctly
            # deferred. Pinning `_bg_task is not None` alone raced.
            assert h._bg_task is not None or any(
                ev.cells == [15000, 112492500] for ev in list(h.history)
            )

            async def landed():
                return any(
                    ev.cells == [15000, 112492500]
                    for ev in list(h.history)
                )

            await poll_until(landed, timeout=10.0)
        finally:
            h.close()

    run(main())
    store.close()


def test_graceful_leave_announces_down(tmp_path):
    """Clean shutdown announces DOWN immediately (foca.leave_cluster,
    broadcast/mod.rs:306): the survivor marks the peer down without
    waiting out a probe-timeout + suspect window."""

    async def main():
        a = await launch_test_agent(
            str(tmp_path / "a"), probe_interval=30.0
        )
        b = await launch_test_agent(
            str(tmp_path / "b"), bootstrap=[a.gossip_addr],
            probe_interval=30.0,
        )
        try:
            async def joined():
                return len(a.agent.members.alive()) == 1

            await poll_until(joined)
            b_id = b.agent.actor_id
            await b.stop()
            # Probes are effectively off (30 s interval): only the leave
            # announcement can flip the state.
            from corrosion_tpu.agent.membership import DOWN

            async def b_down_on_a():
                m = a.agent.members.states.get(b_id)
                return m is not None and m.state == DOWN

            await poll_until(b_down_on_a, timeout=5.0)
        finally:
            await a.stop()

    run(main())


def test_restart_after_graceful_leave_rejoins_immediately(tmp_path):
    """A clean leave makes DOWN durable on peers; the restarted node must
    beat it — its persisted own-incarnation row seeds the next life one
    higher, so ALIVE@n+1 wins even after the leave rumor's retransmission
    budget is long spent."""

    async def main():
        from corrosion_tpu.agent.membership import ALIVE

        b_dir = str(tmp_path / "b")
        a = await launch_test_agent(
            str(tmp_path / "a"), probe_interval=30.0
        )
        try:
            b = await launch_test_agent(
                b_dir, bootstrap=[a.gossip_addr], probe_interval=30.0
            )
            b_id = b.agent.actor_id

            async def joined():
                return len(a.agent.members.alive()) == 1

            await poll_until(joined)
            await b.stop()

            async def b_down():
                m = a.agent.members.states.get(b_id)
                return m is not None and m.state != ALIVE

            await poll_until(b_down, timeout=5.0)
            # Model a LATE restart: the survivor's leave rumor budget is
            # spent, so only the incarnation bump can win the rejoin.
            a.agent.swim.rumors = []

            b2 = await launch_test_agent(
                b_dir, bootstrap=[a.gossip_addr], probe_interval=30.0
            )
            try:
                assert b2.agent.actor_id == b_id
                assert b2.agent.swim.incarnation >= 1, (
                    "restart must seed a fresher incarnation"
                )

                async def b_alive_again():
                    m = a.agent.members.states.get(b_id)
                    return m is not None and m.state == ALIVE

                await poll_until(b_alive_again, timeout=10.0)
            finally:
                await b2.stop()
        finally:
            await a.stop()

    run(main())


def test_normalize_sql_token_level():
    """Reuse-key normalization (VERDICT r3 #4): spelling-insensitive for
    SQL structure, but literal-preserving — two queries differing only in
    literal case must get DISTINCT matchers."""
    from corrosion_tpu.agent.subs import normalize_sql

    assert normalize_sql("SELECT  id\nFROM Tests WHERE x = 1;") == (
        normalize_sql("select id from tests where x=1")
    )
    # Same statement, different identifier case / whitespace / comments.
    a = normalize_sql("SELECT id FROM tests -- c\n WHERE x = 'A'")
    b = normalize_sql("select id\n from TESTS where x = 'A'")
    assert a == b
    # Different literal case: DIFFERENT keys.
    c = normalize_sql("select id from tests where x = 'a'")
    assert a != c
    # Trailing semicolons and comments never affect the key.
    assert normalize_sql("SELECT 1;") == normalize_sql("SELECT 1")


def test_swim_and_sync_loops_warn_once_per_streak(tmp_path, caplog):
    async def main():
        a = await launch_test_agent(
            str(tmp_path / "a"), probe_interval=0.02, sync_interval=0.02
        )
        try:
            async def boom(*args, **kwargs):
                raise RuntimeError("induced failure")

            a.agent.swim.probe_round = boom
            a.agent._sync_once = boom
            with caplog.at_level(
                logging.DEBUG, logger="corrosion_tpu.agent.agent"
            ):
                await asyncio.sleep(0.3)
            for needle in ("SWIM probe round failed", "sync session failed"):
                recs = [
                    r for r in caplog.records if needle in r.getMessage()
                ]
                warns = [
                    r for r in recs if r.levelno == logging.WARNING
                ]
                debugs = [r for r in recs if r.levelno == logging.DEBUG]
                assert len(warns) == 1, (
                    f"{needle}: one WARNING per streak, got {len(warns)}"
                )
                assert len(debugs) >= 1, (
                    f"{needle}: repeats must land at DEBUG"
                )
        finally:
            await a.stop()

    run(main())


def test_header_count_cap_responds_431(tmp_path):
    """agent/api.py::_read_request regression: a client streaming headers
    forever must get 431 + connection close, not buffer unbounded server
    memory — and the agent must stay healthy for the next client."""

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        try:
            host, port = a.agent.api_addr
            reader, writer = await asyncio.open_connection(host, port)
            payload = b"GET /v1/queries HTTP/1.1\r\n" + b"".join(
                b"x-h%d: v\r\n" % i for i in range(300)
            ) + b"\r\n"
            writer.write(payload)
            try:
                await writer.drain()
            except (ConnectionError, OSError):
                pass
            status = await reader.readline()
            assert b"431" in status, status
            writer.close()
            # Agent still healthy: a normal (many-but-bounded-header)
            # request on a fresh connection succeeds.
            resp = await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (1, 'ok')"]]
            )
            assert resp["results"][0]["rows_affected"] == 1
        finally:
            await a.stop()

    run(main())


def test_header_total_bytes_cap_responds_431(tmp_path):
    """Few headers but huge total: the byte cap (not just the count cap)
    must trip."""

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        try:
            host, port = a.agent.api_addr
            reader, writer = await asyncio.open_connection(host, port)
            # 50 headers x ~1 KiB = ~50 KiB > MAX_HEADER_BYTES, while
            # staying under both the per-line stream limit and the
            # header-count cap.
            writer.write(
                b"GET /v1/queries HTTP/1.1\r\n" + b"".join(
                    b"x-h%d: " % i + b"a" * 1024 + b"\r\n"
                    for i in range(50)
                ) + b"\r\n"
            )
            try:
                await writer.drain()
            except (ConnectionError, OSError):
                pass
            status = await reader.readline()
            assert b"431" in status, status
            writer.close()
        finally:
            await a.stop()

    run(main())


def test_oversized_header_line_responds_431(tmp_path):
    """A single header line past asyncio's 64 KiB stream limit must be
    answered 431 (the ValueError path), not crash the connection task."""

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        try:
            host, port = a.agent.api_addr
            reader, writer = await asyncio.open_connection(host, port)
            writer.write(
                b"POST /v1/transactions HTTP/1.1\r\n"
                b"x-big: " + b"a" * (80 * 1024) + b"\r\n\r\n"
            )
            try:
                await writer.drain()
            except (ConnectionError, OSError):
                pass
            status = await reader.readline()
            assert b"431" in status, status
            writer.close()
        finally:
            await a.stop()

    run(main())


def test_shed_and_inflight_metrics_on_route_limit(tmp_path):
    """RouteLimit satellite: load-shed is no longer invisible —
    corro_api_shed_total/corro_api_inflight are exposed per route and
    match what a client actually observed."""

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"), api_concurrency=1)
        try:
            from corrosion_tpu.client import ApiError

            async def one(i):
                try:
                    await a.client.execute(
                        [["INSERT INTO tests (id, text) VALUES (?, 'x')",
                          [i]]]
                    )
                    return "ok"
                except ApiError as e:
                    assert e.status == 503
                    return "shed"

            outcomes = await asyncio.gather(*(one(i) for i in range(12)))
            shed = outcomes.count("shed")
            assert shed > 0, "12 concurrent writes vs limit 1 must shed"
            ctr = a.agent.metrics.counter("corro_api_shed_total")
            assert ctr.get(route="/v1/transactions") == shed
            # All slots released after the burst.
            g = a.agent.metrics.gauge("corro_api_inflight")
            assert g.get(route="/v1/transactions") == 0
        finally:
            await a.stop()

    run(main())
