"""Propagation-topology plane tests (sim/telemetry.PROP_CURVE_KEYS +
obs/epidemic.py).

Covers the on-device observables' conservation identities (link-matrix
mass == msgs, rumor-age mass == first deliveries, useful + dup == msgs)
under clean and churn+loss schedules, the static zero-cost-skip pin
(disabled propagation leaves every other curve and the final state
bit-identical), CT010 static parity of the new keys across all four
engines, shard-count invariance of the link matrix plus the
traffic_model cross-check, the SI/logit fit, the corro-epidemic/1
report + diff gate, and the host-oracle cross-validation path.
"""

import json
import math

import jax
import numpy as np
import pytest

from corrosion_tpu.obs import epidemic
from corrosion_tpu.sim import health, simulate
from corrosion_tpu.sim import telemetry as T
from corrosion_tpu.sim.engine import Schedule


def _geo_run(nodes=64, rounds=32, seed=0, **sched_kw):
    cfg, topo, sched, kills = health.churned_demo_cluster(
        nodes=nodes, rounds=rounds, samples=32, churn=True, seed=seed,
        geo=True,
    )
    for k, v in sched_kw.items():
        setattr(sched, k, v)
    final, curves = simulate(cfg, topo, sched, seed=seed)
    return cfg, topo, sched, final, curves


@pytest.fixture(scope="module")
def geo_run():
    return _geo_run()


def _mass(curves, keys):
    return sum(np.asarray(curves[k], np.float64) for k in keys)


def test_conservation_identities_geo(geo_run):
    """Per round: the link matrix partitions msgs, the rumor-age
    histogram partitions first deliveries, useful+dup partitions the
    delivered copies."""
    *_, curves = geo_run
    np.testing.assert_array_equal(
        _mass(curves, T.LINK_CURVE_KEYS), curves["msgs"]
    )
    np.testing.assert_array_equal(
        _mass(curves, T.RUMOR_AGE_KEYS), curves["vis_count"]
    )
    np.testing.assert_array_equal(
        curves["prop_useful_msgs"] + curves["prop_dup_msgs"],
        curves["msgs"],
    )
    ok, problems = epidemic.conservation_checks(curves)
    assert ok, problems
    # The geo geography actually exercises cross-region links.
    m = epidemic.link_matrix(curves)
    assert np.trace(m) > 0 and m.sum() > np.trace(m)


def test_rumor_mass_conserved_under_churn_and_loss():
    """Satellite property: mass conservation must survive the chaos
    axes — injected per-region loss, probe loss, and the scenario's
    kill/revive wave all composing in one schedule."""
    rng = np.random.default_rng(7)
    rounds = 32
    loss = (rng.random((rounds, health.GEO_REGIONS)) * 0.4).astype(
        np.float32
    )
    probe = (rng.random(rounds) * 0.3).astype(np.float32)
    *_, curves = _geo_run(
        rounds=rounds, seed=7, loss=loss, probe_loss=probe
    )
    np.testing.assert_array_equal(
        _mass(curves, T.RUMOR_AGE_KEYS), curves["vis_count"]
    )
    np.testing.assert_array_equal(
        _mass(curves, T.LINK_CURVE_KEYS), curves["msgs"]
    )
    np.testing.assert_array_equal(
        curves["prop_useful_msgs"] + curves["prop_dup_msgs"],
        curves["msgs"],
    )
    assert curves["chaos_lost_msgs"].sum() > 0  # the loss really fired


def test_disabled_prop_is_bit_identical(geo_run):
    """The static-skip pin, applied to this plane: observation must
    change nothing. The same schedule with prop_observe off produces
    bit-identical non-propagation curves and final state (no RNG is
    consumed, no protocol work reordered), and the propagation keys
    zero-fill."""
    from dataclasses import replace

    cfg, topo, sched, final, curves = geo_run
    cfg_off = replace(cfg, gossip=replace(cfg.gossip, prop_observe=False))
    final_off, curves_off = simulate(cfg_off, topo, sched, seed=0)
    for k in T.ROUND_CURVE_KEYS:
        if k in T.PROP_CURVE_KEYS:
            assert (np.asarray(curves_off[k]) == 0).all(), k
        else:
            np.testing.assert_array_equal(
                np.asarray(curves[k]), np.asarray(curves_off[k]), err_msg=k
            )
    for a, b in zip(
        jax.tree.leaves((final.data, final.swim, final.vis_round)),
        jax.tree.leaves((final_off.data, final_off.swim,
                         final_off.vis_round)),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_prop_keys_statically_emitted_by_all_engines():
    """CT010 parity: every engine's round_curves call site resolves the
    propagation keys statically (the ``**prop_curves(...)`` expansion),
    so an engine dropping the plane fails the lint, not a run."""
    import os

    from corrosion_tpu.analysis import schema
    from corrosion_tpu.analysis.source import SourceModule

    pkg = os.path.dirname(
        os.path.dirname(os.path.abspath(T.__file__))
    )
    canonical = schema.extract_canonical(
        os.path.join(pkg, "sim", "telemetry.py")
    )
    assert canonical["PROP_CURVE_KEYS"] == T.PROP_CURVE_KEYS
    assert canonical["LINK_CURVE_KEYS"] == T.LINK_CURVE_KEYS
    assert canonical["RUMOR_AGE_KEYS"] == T.RUMOR_AGE_KEYS
    for eng in ("engine.py", "sparse_engine.py", "chunk_engine.py",
                "mixed_engine.py"):
        path = os.path.join(pkg, "sim", eng)
        mod = SourceModule(path, open(path).read())
        keys, findings = schema.emitted_keys(mod, canonical)
        assert not findings, (eng, [f.message for f in findings])
        assert set(T.PROP_CURVE_KEYS) <= set(keys), eng


def test_link_matrix_shard_invariant_and_traffic_model():
    """Acceptance: on a sharded run the kernel link matrix equals the
    unsharded one bit-for-bit and the measured exchange bytes equal
    shard_driver.traffic_model per round."""
    from dataclasses import replace

    from jax.sharding import Mesh

    from corrosion_tpu import models, parallel

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    cfg, topo, sched = models.wan_100k(
        n=32, n_regions=4, n_writers=8, rounds=10, samples=8,
        partition=False,
    )
    sched.writes[:, :] = 0
    sched.writes[:4, :] = 1
    sched = sched.make_samples(8)
    cfg = replace(cfg, gossip=replace(cfg.gossip, prop_observe=True))
    _, ref = simulate(cfg, topo, sched, seed=0)
    mesh = Mesh(np.array(jax.devices()[:2]), ("node",))
    _, got = parallel.shard_driver.simulate_sharded(
        cfg, topo, sched, mesh, seed=0
    )
    for k in T.PROP_CURVE_KEYS:
        np.testing.assert_array_equal(
            np.asarray(ref[k]), np.asarray(got[k]), err_msg=k
        )
    ok, problems = epidemic.xshard_model_check(got, cfg.gossip, mesh)
    assert ok, problems
    ok_ref, _ = epidemic.conservation_checks(got)
    assert ok_ref


def test_fit_si_recovers_logistic_beta():
    """The logit fit on exact logistic coverage recovers beta and the
    half-coverage point to float precision."""
    beta, n = 0.9, 500.0
    pts = []
    for t in T.RUMOR_AGE_EDGES:
        s = n / (1.0 + (n - 1.0) * math.exp(-beta * t))
        pts.append((float(t), s / n))
    fit = epidemic.fit_si(pts)
    assert fit["fitted"]
    assert abs(fit["spread_exponent"] - beta) < 1e-6
    assert abs(fit["half_coverage_round"] - math.log(n - 1.0) / beta) < 1e-4
    assert fit["r2"] > 0.999999


def test_fit_abstains_on_degenerate_curve():
    fit = epidemic.fit_si([(1.0, 1.0), (2.0, 1.0), (4.0, 1.0)])
    assert not fit["fitted"]
    assert fit["spread_exponent"] is None


def test_epidemic_report_fits_geo_scenario(geo_run):
    """Acceptance: the fixed-seed geo scenario's report fits the SI
    model with a positive spread exponent bounded above by the
    push-gossip theory (theory assumes zero redundancy, so measured
    must sit below it but within the same order)."""
    cfg, topo, sched, _final, curves = geo_run
    rep = epidemic.build_report(
        curves, engine="dense", fanout=cfg.gossip.fanout, nodes=64,
        geo_regions=health.GEO_REGIONS,
    )
    assert rep["checks_ok"], rep["check_problems"]
    assert rep["fit"]["fitted"]
    beta = rep["spread_exponent"]
    theory = rep["theory"]["spread_exponent"]
    assert 0.15 * theory < beta <= 1.1 * theory, (beta, theory)
    assert rep["fit_r2"] > 0.5
    assert 0.0 < rep["redundancy_ratio"] < 1.0
    assert rep["half_coverage_round"] is not None
    assert rep["traffic"]["cross_region_share"] > 0
    assert "ring_shares" in rep["traffic"]
    # Renders without error and mentions the verdict surface.
    text = epidemic.render_report(rep)
    assert "spread:" in text and "accounting: OK" in text


def test_epidemic_diff_clean_and_regression(geo_run):
    cfg, *_rest, curves = geo_run
    rep = epidemic.build_report(curves, fanout=cfg.gossip.fanout)
    clean = epidemic.diff_reports(rep, rep, tolerance=0.25)
    assert not clean["regressions"]
    worse = dict(rep)
    worse["spread_exponent"] = rep["spread_exponent"] * 0.4
    worse["effective_fanout"] = rep["effective_fanout"] * 0.3
    diff = epidemic.diff_reports(rep, worse, tolerance=0.25)
    assert any("spread_exponent" in r for r in diff["regressions"])
    assert any("effective_fanout" in r for r in diff["regressions"])
    broken = dict(rep)
    broken["checks_ok"] = False
    broken["check_problems"] = ["synthetic"]
    assert epidemic.diff_reports(rep, broken)["regressions"]


def test_report_from_flight_and_cli_roundtrip(tmp_path, geo_run):
    """Flight JSONL -> report -> load_report round-trips; a flight
    recorded without the plane is refused loudly."""
    cfg, topo, sched, _final, _curves = geo_run
    path = str(tmp_path / "geo.jsonl")
    tele = T.KernelTelemetry(
        engine="dense", recorder=T.FlightRecorder(path, engine="dense")
    )
    simulate(cfg, topo, sched, seed=0, max_chunk=16, telemetry=tele)
    tele.recorder.close()
    rep = epidemic.report_from_flight(
        path, fanout=cfg.gossip.fanout, nodes=64, geo_regions=4
    )
    assert rep["checks_ok"] and rep["engine"] == "dense"
    out = tmp_path / "rep.json"
    out.write_text(json.dumps(rep))
    loaded = epidemic.load_report(str(out))
    assert loaded["spread_exponent"] == rep["spread_exponent"]
    # load_report also accepts the raw flight.
    from_flight = epidemic.load_report(
        path, fanout=cfg.gossip.fanout, nodes=64, geo_regions=4
    )
    assert from_flight["spread_exponent"] == rep["spread_exponent"]
    # A prop-less flight is refused with a pointed error.
    cfg2, topo2, sched2, _k = health.churned_demo_cluster(
        nodes=32, rounds=16, samples=8, churn=False, seed=1
    )
    p2 = str(tmp_path / "flat.jsonl")
    tele2 = T.KernelTelemetry(
        engine="dense", recorder=T.FlightRecorder(p2, engine="dense")
    )
    simulate(cfg2, topo2, sched2, seed=1, max_chunk=8, telemetry=tele2)
    tele2.recorder.close()
    with pytest.raises(ValueError, match="prop_observe off"):
        epidemic.report_from_flight(p2)


def test_committed_baseline_schema_and_self_diff():
    """The committed EPIDEMIC_BASELINE.json is a valid corro-epidemic/1
    report whose accounting reconciled and whose fit stood — the CI
    gate's left-hand side can never be a broken instrument."""
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base = epidemic.load_report(
        os.path.join(root, "EPIDEMIC_BASELINE.json")
    )
    assert base["schema"] == epidemic.EPIDEMIC_SCHEMA
    assert base["checks_ok"] and base["fit"]["fitted"]
    assert not epidemic.diff_reports(base, base)["regressions"]


def test_oracle_coverage_cross_validation():
    """Host-plane path: synthetic oracle delivery records whose ages
    follow a logistic spread land on the same bucket axis and fit."""
    rng = np.random.default_rng(3)
    round_s = 0.5
    writes, deliveries = [], []
    beta, n = 0.8, 64
    for w in range(40):
        ack = 100.0 + w * 0.3
        writes.append({"key": w, "t_ack_wall": ack})
        # Inverse-CDF sample of the logistic first-delivery age.
        for _ in range(16):
            u = rng.uniform(1.0 / n, 1.0 - 1e-3)
            age = max(
                (math.log(u / (1 - u)) + math.log(n - 1.0)) / beta, 0.05
            )
            deliveries.append({
                "kind": "change", "key": w,
                "t_wall": ack + age * round_s,
            })
    block = epidemic.oracle_coverage(
        {"writes": writes, "deliveries": deliveries}, round_ms=500.0
    )
    assert block["events"] == len(deliveries)
    assert block["fit"]["fitted"]
    assert abs(block["spread_exponent"] - beta) < 0.35 * beta
    assert sum(block["rumor_age_hist"]) == block["events"]


def test_publish_epidemic_and_curve_aggregates(geo_run):
    from corrosion_tpu.utils.metrics import MetricsRegistry

    cfg, *_rest, curves = geo_run
    reg = MetricsRegistry()
    T.publish_curves(reg, curves, engine="dense")
    same = reg.counter(
        "corro_kernel_prop_link_same_region_total"
    ).get(engine="dense")
    cross = reg.counter(
        "corro_kernel_prop_link_cross_region_total"
    ).get(engine="dense")
    assert same + cross == float(np.asarray(curves["msgs"]).sum())
    assert reg.counter(
        "corro_kernel_prop_rumor_events_total"
    ).get(engine="dense") == float(
        np.asarray(curves["vis_count"]).sum()
    )
    rep = epidemic.build_report(curves, fanout=cfg.gossip.fanout)
    epidemic.publish_epidemic(reg, rep, engine="dense")
    got = reg.gauge("corro_kernel_epidemic_spread_exponent").get(
        engine="dense"
    )
    assert got == pytest.approx(rep["spread_exponent"])


def test_geo_scenario_variant_shape():
    """The geo family: 4 contiguous regions, ring classes spanning the
    synthetic circle's range, writers spread across regions, prop plane
    on — while the default (flat) variant is untouched (writers 0..W-1,
    single region, prop off)."""
    cfg, topo, sched, kills = health.churned_demo_cluster(
        nodes=64, rounds=16, samples=8, churn=True, seed=0, geo=True
    )
    assert cfg.gossip.prop_observe
    region = np.asarray(topo.region)
    assert region.max() == health.GEO_REGIONS - 1
    rtt = np.asarray(topo.region_rtt)
    assert rtt.max() == 5 and rtt.min() == 0
    writer_regions = set(region[np.asarray(topo.writer_nodes)].tolist())
    assert len(writer_regions) == health.GEO_REGIONS
    # Kill victims never host writers (sampled-write bookkeeping).
    kill_nodes = np.nonzero(np.asarray(sched.kill).any(axis=0))[0]
    assert not set(kill_nodes) & set(np.asarray(topo.writer_nodes))
    cfg2, topo2, *_ = health.churned_demo_cluster(
        nodes=64, rounds=16, samples=8, churn=True, seed=0
    )
    assert not cfg2.gossip.prop_observe
    assert np.asarray(topo2.region).max() == 0
    np.testing.assert_array_equal(
        np.asarray(topo2.writer_nodes), np.arange(8)
    )
