"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run against
``--xla_force_host_platform_device_count=8`` exactly like the driver's
multi-chip dry run. The environment's sitecustomize grabs the real TPU chip
(platform "axon") at interpreter start, so env vars alone are not enough —
the platform is overridden via jax.config before any backend is touched.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Build the native runtime artifacts (codec + SQLite CRDT extension) once
# per session so the host-agent tests exercise the native path; everything
# they cover also runs pure-Python when the toolchain is absent.
from corrosion_tpu import native as _native  # noqa: E402

_native.build()
