"""Test configuration: force an 8-device virtual CPU mesh before jax imports.

Multi-chip hardware is not available in CI; sharding tests run against
``--xla_force_host_platform_device_count=8`` exactly like the driver's
multi-chip dry run.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
