"""SplitPool + online restore + restart identity tests.

Covers the reference's SplitPool discipline (corro-types/src/agent.rs:
353-578: serialized prioritized writes, pooled snapshot reads), the
sqlite3-restore online swap, and the restart-identity regression (a
reopened store must adopt the persisted site_id or it can no longer read
back its own local writes for broadcast).
"""

import asyncio
import os

import pytest

from corrosion_tpu.agent.backup import backup, online_restore
from corrosion_tpu.agent.pool import HIGH, LOW, NORMAL, SplitPool
from corrosion_tpu.agent.store import Store
from corrosion_tpu.agent.testing import launch_test_agent, poll_until
from corrosion_tpu.core.values import Statement


def run(coro):
    return asyncio.run(coro)


def test_restart_adopts_persisted_identity(tmp_path):
    p = str(tmp_path / "x.db")
    s1 = Store(p, b"\x01" * 16)
    s1.apply_schema("CREATE TABLE t (id INTEGER PRIMARY KEY, v TEXT);")
    _, dbv, _, ch = s1.execute_transaction(
        [Statement("INSERT INTO t VALUES (1, 'a')")]
    )
    assert len(ch) >= 1
    site1 = s1.site_id
    s1.close()

    # Reopen with a DIFFERENT passed site_id (what a restarted agent does):
    # the store must keep the persisted identity and still read back its
    # own local writes for broadcast.
    s2 = Store(p, b"\x02" * 16)
    assert s2.site_id == site1
    _, dbv, _, ch = s2.execute_transaction(
        [Statement("INSERT INTO t VALUES (2, 'b')")]
    )
    assert len(ch) >= 1, "restarted node must see its own changes"
    s2.close()


def test_pool_priority_and_serialization(tmp_path):
    async def main():
        store = Store(str(tmp_path / "p.db"), b"\x03" * 16)
        store.apply_schema("CREATE TABLE t (id INTEGER PRIMARY KEY, v TEXT);")
        pool = SplitPool(store, read_conns=4)
        order: list[str] = []

        async def submit_when_busy():
            # Occupy the writer with a slow job, then enqueue one job per
            # class; drain order must be high, normal, low regardless of
            # submission order.
            import time as _t

            block = pool.write(lambda: _t.sleep(0.15), NORMAL)
            blocked = asyncio.ensure_future(block)
            await asyncio.sleep(0.03)
            jobs = [
                asyncio.ensure_future(
                    pool.write(lambda n=name: order.append(n), prio)
                )
                for name, prio in (
                    ("low", LOW), ("normal", NORMAL), ("high", HIGH),
                )
            ]
            await asyncio.gather(blocked, *jobs)

        pool.start()
        await submit_when_busy()
        assert order == ["high", "normal", "low"]

        # Writes are serialized: concurrent increments never lose updates.
        store.execute_transaction(
            [Statement("INSERT INTO t VALUES (1, '0')")]
        )

        def bump():
            c = store.conn
            with store._wlock("bump"):
                (v,) = c.execute("SELECT v FROM t WHERE id = 1").fetchone()
                c.execute(
                    "UPDATE t SET v = ? WHERE id = 1", (str(int(v) + 1),)
                )

        await asyncio.gather(*[pool.write(bump) for _ in range(25)])
        _, rows = await pool.query(Statement("SELECT v FROM t WHERE id=1"))
        assert rows == [("25",)]  # all 25 bumps applied, none lost

        # Pooled reads run concurrently and see committed state.
        results = await asyncio.gather(
            *[pool.query(Statement("SELECT count(*) FROM t")) for _ in range(8)]
        )
        assert all(r[1] == [(1,)] for r in results)

        # Errors propagate to the caller without killing the writer.
        with pytest.raises(RuntimeError):
            await pool.write(_raise)
        await pool.write(lambda: order.append("after-error"))
        assert order[-1] == "after-error"

        await pool.close()
        store.close()

    run(main())


def _raise():
    raise RuntimeError("boom")


def test_pool_close_waits_for_inflight_writer(tmp_path):
    """Shutdown race regression: close() must drain the writer THREAD —
    cancelling the awaiting task leaves the job running, and closing the
    store connection under a mid-transaction job segfaults in sqlite3
    (observed as a flaky teardown crash in the host bench)."""
    import time

    async def main():
        store = Store(str(tmp_path / "s.db"), os.urandom(16))
        store.apply_schema(
            "CREATE TABLE t (id INTEGER NOT NULL PRIMARY KEY, v INTEGER)"
        )
        pool = SplitPool(store)
        pool.start()
        import threading

        started = threading.Event()
        state = {"done": False}

        def slow_job():
            started.set()
            time.sleep(0.3)
            # Touch the connection late: if close() freed it, this is the
            # use-after-free the old code hit.
            store.conn.execute("SELECT count(*) FROM t").fetchone()
            state["done"] = True

        fut = asyncio.ensure_future(pool.write(slow_job))
        # Deterministic: wait until the job is RUNNING on the writer
        # thread (a fixed sleep can miss on a loaded machine).
        assert await asyncio.to_thread(started.wait, 5.0), "job never started"
        await pool.close()
        assert state["done"], "close returned before the in-flight job"
        # The caller's future was failed, not left hanging.
        with pytest.raises(RuntimeError):
            await fut
        store.close()

    run(main())


def test_online_restore_same_inode(tmp_path):
    # Build a source DB, back it up, then restore it into a LIVE store.
    src = Store(str(tmp_path / "src.db"), b"\x04" * 16)
    src.apply_schema("CREATE TABLE t (id INTEGER PRIMARY KEY, v TEXT);")
    src.execute_transaction([Statement("INSERT INTO t VALUES (7, 'seed')")])
    src.close()
    backup(str(tmp_path / "src.db"), str(tmp_path / "bk.db"))

    live = Store(str(tmp_path / "live.db"), b"\x05" * 16)
    live.apply_schema("CREATE TABLE u (id INTEGER PRIMARY KEY);")
    live.execute_transaction([Statement("INSERT INTO u VALUES (1)")])
    ino_before = os.stat(live.path).st_ino

    online_restore(str(tmp_path / "bk.db"), live.path, self_actor_id=False)
    assert os.stat(live.path).st_ino == ino_before, "same inode (live FDs)"
    live.reload_after_restore()

    # The live connections now serve the restored content.
    _, rows = live.query(Statement("SELECT v FROM t WHERE id = 7"))
    assert rows == [("seed",)]
    assert "u" not in live.tables() and "t" in live.tables()
    # Fresh identity by default (not the backup's origin).
    assert live.site_id != b"\x04" * 16
    # And the restored store accepts new writes with change tracking.
    _, dbv, _, ch = live.execute_transaction(
        [Statement("INSERT INTO t VALUES (8, 'post')")]
    )
    assert len(ch) >= 1
    live.close()


def test_agent_online_restore_via_admin(tmp_path):
    async def main():
        # Seed agent writes data; its backup is restored into agent B while
        # B is live; B must serve the data and keep replicating afterward.
        seed = await launch_test_agent(str(tmp_path / "seed"))
        await seed.client.execute(
            [["INSERT INTO tests (id, text) VALUES (1, 'from-backup')"]]
        )
        await seed.stop()
        backup(
            str(tmp_path / "seed" / "state.db"), str(tmp_path / "bk.db")
        )

        a = await launch_test_agent(
            str(tmp_path / "a"), admin_uds=str(tmp_path / "a.sock")
        )
        b = await launch_test_agent(
            str(tmp_path / "b"), bootstrap=[a.gossip_addr]
        )
        try:
            old_actor = a.agent.actor_id
            from corrosion_tpu.agent.admin import AdminClient

            (frame,) = await AdminClient(str(tmp_path / "a.sock")).call(
                {"c": "restore", "path": str(tmp_path / "bk.db")}
            )
            assert frame["restored"] and frame["actor_id"] != old_actor

            _, rows = a.agent.store.query(
                Statement("SELECT text FROM tests WHERE id = 1")
            )
            assert rows == [("from-backup",)]

            # Replication still works after the restore: a new write on A
            # reaches B (including the restored row via sync/broadcast).
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (2, 'post-restore')"]]
            )

            async def converged():
                _, r = b.agent.store.query(
                    Statement("SELECT text FROM tests WHERE id = 2")
                )
                return r == [("post-restore",)]

            await poll_until(converged, timeout=20)
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_write_queue_full_blocks_deterministically(tmp_path):
    """agent/pool.py backpressure contract: a full priority queue BLOCKS
    the producer in ``put`` — never sheds, drops, or reorders within the
    class — and drains FIFO once the writer frees slots. (Load-shed is
    the API layer's job; the pool's is deterministic backpressure.)"""
    import threading

    async def main():
        store = Store(str(tmp_path / "bp.db"), b"\x02" * 16)
        pool = SplitPool(
            store, queue_depths={HIGH: 1, NORMAL: 2, LOW: 1}
        )
        pool.start()
        gate = threading.Event()
        started = threading.Event()
        results = []

        def slow():
            started.set()
            assert gate.wait(10), "test gate never opened"
            return "slow"

        t_slow = asyncio.ensure_future(pool.write(slow))
        await asyncio.to_thread(started.wait, 5)  # writer thread is busy
        t1 = asyncio.ensure_future(
            pool.write(lambda: results.append(1) or 1)
        )
        t2 = asyncio.ensure_future(
            pool.write(lambda: results.append(2) or 2)
        )
        await asyncio.sleep(0.05)
        assert pool.queue_depths()["normal"] == 2  # class queue is FULL
        t3 = asyncio.ensure_future(
            pool.write(lambda: results.append(3) or 3)
        )
        await asyncio.sleep(0.2)
        # The third write is neither failed nor executed nor enqueued —
        # it is BLOCKED in put (deterministic backpressure, no shed).
        assert not t3.done()
        assert pool.queue_depths()["normal"] == 2
        assert results == []
        gate.set()
        assert await t_slow == "slow"
        assert [await t1, await t2, await t3] == [1, 2, 3]
        assert results == [1, 2, 3]  # FIFO within the priority class
        await pool.close()
        store.close()

    run(main())
