"""Elastic survival plane: reshard + preemption pins (docs/SCALING.md
"Elastic ops").

Fast lanes (tier-1): the self-describing checkpoint header contract
(round-trip + refusals), the sparse resume fault-axis fix, the
schedule-window slicer, preempt faults on the fault plane, the
mesh-spec reshard contract (gather → re-place is a bijection with
byte-exact ``predicted_per_device_bytes`` on every (D, D′) pair), the
shard poisoner, the budget gate, and the endurance restart classifier
across an in-process re-``attach()``.

Slow lanes (multichip CI job, unfiltered): the full dense reshard
matrix {4→8, 8→4, 8→2, 1→8} plus sparse/chunk/mixed 4→8 — each pinned
BIT-identical to the uninterrupted same-seed run — and the preemption
scenarios with the machinery-fired rule.
"""

import dataclasses
import itertools

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from corrosion_tpu import models
from corrosion_tpu.elastic import report as el_report
from corrosion_tpu.elastic import reshard, scenarios
from corrosion_tpu.elastic.preempt import poison_lost_shard
from corrosion_tpu.parallel import mesh as mesh_mod
from corrosion_tpu.parallel import shard_driver
from corrosion_tpu.sim import checkpoint
from corrosion_tpu.sim.engine import init_cluster
from corrosion_tpu.sim.faults import Fault, FaultPlan

needs8 = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def _tiny_dense(n=16, rounds=8):
    cfg, topo, sched = models.wan_100k(
        n=n, n_regions=2, n_writers=4, rounds=rounds, samples=4
    )
    sched.writes[:2, :] = 1
    sched = sched.make_samples(4)
    return cfg, topo, sched


# -- checkpoint self-description (corro-checkpoint/1) -------------------------


def test_checkpoint_header_roundtrip(tmp_path):
    cfg, _topo, sched = _tiny_dense()
    state = init_cluster(cfg, len(sched.sample_writer))
    path = str(tmp_path / "c.npz")
    checkpoint.save_state(path, state, fingerprint="fp-1", mesh_shape=(2, 4))
    header = checkpoint.read_header(path)
    assert header == {
        "schema": "corro-checkpoint/1",
        "kind": "state",
        "config_fingerprint": "fp-1",
        "mesh": [2, 4],
        "round": 0,
    }
    restored = checkpoint.load_state(
        path, cfg, len(sched.sample_writer), expect_fingerprint="fp-1"
    )
    assert el_report.diff_trees(state, restored) == []


def test_checkpoint_refuses_mismatched_fingerprint(tmp_path):
    cfg, _topo, sched = _tiny_dense()
    state = init_cluster(cfg, len(sched.sample_writer))
    path = str(tmp_path / "c.npz")
    checkpoint.save_state(path, state, fingerprint="fp-1")
    with pytest.raises(ValueError, match="fingerprint"):
        checkpoint.load_state(
            path, cfg, len(sched.sample_writer), expect_fingerprint="other"
        )


def test_checkpoint_refuses_wrong_kind(tmp_path):
    """A state snapshot must not load through the generic tree loader —
    the header's kind field binds each file to its loader."""
    cfg, _topo, sched = _tiny_dense()
    state = init_cluster(cfg, len(sched.sample_writer))
    path = str(tmp_path / "c.npz")
    checkpoint.save_state(path, state, fingerprint="fp-1")
    with pytest.raises(ValueError, match="kind"):
        checkpoint.load_tree(path, state, expect_fingerprint="fp-1")


def test_headerless_checkpoint_needs_no_fingerprint(tmp_path):
    """Pre-header (v0) snapshots still load — but only when the caller
    does not demand fingerprint verification."""
    cfg, _topo, sched = _tiny_dense()
    state = init_cluster(cfg, len(sched.sample_writer))
    path = str(tmp_path / "c.npz")
    checkpoint.save_state(path, state)
    with np.load(path) as data:
        arrays = {k: data[k] for k in data.files if k != "__header__"}
    legacy = str(tmp_path / "legacy.npz")
    np.savez_compressed(legacy, **arrays)
    assert checkpoint.read_header(legacy) is None
    restored = checkpoint.load_state(legacy, cfg, len(sched.sample_writer))
    assert el_report.diff_trees(state, restored) == []
    with pytest.raises(ValueError, match="header"):
        checkpoint.load_state(
            legacy, cfg, len(sched.sample_writer), expect_fingerprint="fp"
        )


def test_checkpoint_refuses_shape_mismatch(tmp_path):
    cfg, _topo, sched = _tiny_dense(n=16)
    state = init_cluster(cfg, len(sched.sample_writer))
    path = str(tmp_path / "c.npz")
    checkpoint.save_state(path, state)
    cfg32, _t, sched32 = _tiny_dense(n=32)
    with pytest.raises(ValueError):
        checkpoint.load_state(path, cfg32, len(sched32.sample_writer))


# -- sparse resume fault-axis persistence (the asymmetry fix) -----------------


def _strip_fault_axes(sched):
    return dataclasses.replace(
        sched, **{name: None for name in checkpoint.FAULT_AXES}
    )


def test_attach_resume_faults_restores_and_refuses():
    _cfg, _topo, sched = _tiny_dense(rounds=8)
    loss = np.zeros((8, 2), np.float32)
    loss[3, :] = 0.5
    sched = dataclasses.replace(sched, loss=loss)
    bare = _strip_fault_axes(sched)
    assert bare.loss is None

    restored = checkpoint.attach_resume_faults(bare, {"faults": {"loss": loss}})
    np.testing.assert_array_equal(restored.loss, loss)
    # Re-attaching over an identical axis is a no-op, not a conflict.
    again = checkpoint.attach_resume_faults(sched, {"faults": {"loss": loss}})
    np.testing.assert_array_equal(again.loss, loss)

    other = loss.copy()
    other[5, :] = 0.9
    with pytest.raises(ValueError, match="different"):
        checkpoint.attach_resume_faults(sched, {"faults": {"loss": other}})
    with pytest.raises(ValueError, match="unknown"):
        checkpoint.attach_resume_faults(bare, {"faults": {"writes": loss}})
    with pytest.raises(ValueError, match="rounds"):
        checkpoint.attach_resume_faults(bare, {"faults": {"loss": loss[:4]}})


@pytest.mark.slow  # tier-1 budget; the multichip CI job runs this file unfiltered
def test_sparse_resume_bit_identical_under_active_plan(tmp_path):
    """Satellite pin for the resume asymmetry: a sparse run under an
    active fault plan, persisted mid-run WITH its fault axes and resumed
    against a schedule rebuilt WITHOUT them, must end bit-identical to
    the uninterrupted run. Before the fix the resume point silently
    dropped the plan and diverged."""
    from corrosion_tpu.models.baselines import anywrite_sparse

    cfg, topo, sched = anywrite_sparse(
        n=32, w_hot=8, rounds=16, n_regions=4, epoch_rounds=8, cohort=4,
        burst_writes=2, samples=16, k_dev=8, partition=True, seed=3,
    )
    assert sched.partition is not None  # the plan must actually be active
    mesh = reshard.virtual_mesh(1)
    n_samples = len(sched.sample_writer)

    *ref_state, ref_curves, _info = shard_driver.simulate_sparse_sharded(
        cfg, topo, sched, mesh, seed=0
    )

    *_pre, prefix_curves, info = shard_driver.simulate_sparse_sharded(
        cfg, topo, sched, mesh, seed=0, stop_after_epoch=0
    )
    resume = info["resume"]
    path = str(tmp_path / "sparse.npz")
    checkpoint.save_sparse_resume(
        path,
        {
            "sstate": jax.device_get(resume["sstate"]),
            "swim": jax.device_get(resume["swim"]),
            "vis_round": jax.device_get(resume["vis_round"]),
            "planner": resume["planner"],
            "next_epoch": int(resume["next_epoch"]),
        },
        schedule=sched, fingerprint="sp-1",
    )
    loaded = checkpoint.load_sparse_resume(
        path, cfg, n_samples, expect_fingerprint="sp-1"
    )
    assert "partition" in loaded["faults"]

    bare = _strip_fault_axes(sched)
    restored_sched = checkpoint.attach_resume_faults(bare, loaded)
    np.testing.assert_array_equal(restored_sched.partition, sched.partition)

    node = shard_driver.node_spec_entry(mesh)
    tree = (loaded["sstate"], loaded["swim"], loaded["vis_round"])
    specs = (
        mesh_mod.sparse_state_specs(loaded["sstate"], mesh),
        mesh_mod.node_major_specs(loaded["swim"], mesh),
        P(None, node),
    )
    placed, _rec = reshard.place_reconciled(tree, specs, mesh)
    *fin_state, tail_curves, _info2 = shard_driver.simulate_sparse_sharded(
        cfg, topo, restored_sched, mesh, seed=0,
        resume={
            "sstate": placed[0], "swim": placed[1], "vis_round": placed[2],
            "planner": loaded["planner"],
            "next_epoch": loaded["next_epoch"],
        },
    )
    assert el_report.diff_trees(tuple(fin_state), tuple(ref_state)) == []
    split = 8  # one epoch
    assert el_report.diff_curves(
        prefix_curves, el_report.slice_curves(ref_curves, 0, split)
    ) == []
    assert el_report.diff_curves(
        tail_curves, el_report.slice_curves(ref_curves, split)
    ) == []


# -- schedule windowing -------------------------------------------------------


def test_schedule_slice_windows_faults_keeps_samples_absolute():
    _cfg, _topo, sched = _tiny_dense(rounds=8)
    loss = np.linspace(0, 1, 8 * 2, dtype=np.float32).reshape(8, 2)
    sched = dataclasses.replace(sched, loss=loss)
    sl = reshard.schedule_slice(sched, 2, 6)
    assert sl.rounds == 4
    np.testing.assert_array_equal(sl.writes, sched.writes[2:6])
    np.testing.assert_array_equal(sl.loss, loss[2:6])
    assert sl.kill is None  # None-safe: absent axes stay absent
    # Visibility samples are tracked in ABSOLUTE rounds by the engines.
    np.testing.assert_array_equal(sl.sample_round, sched.sample_round)
    np.testing.assert_array_equal(sl.sample_writer, sched.sample_writer)


# -- preempt on the fault plane -----------------------------------------------


def test_preempt_fault_validation_and_plan_split():
    f = Fault("preempt", 3, 4, device=2)
    assert f.clears_at == 4
    assert Fault.from_dict(f.to_dict()) == f
    with pytest.raises(ValueError, match="device"):
        Fault("preempt", 3, 4)
    with pytest.raises(ValueError, match="instantaneous"):
        Fault("preempt", 3, 9, device=2)
    with pytest.raises(ValueError, match="preempt-only"):
        Fault("churn", 3, 4, nodes=(1,), device=2)

    plan = FaultPlan(
        rounds=12,
        faults=(
            Fault("preempt", 7, 8, device=1),
            Fault("loss", 2, 5, prob=0.5),
            Fault("preempt", 3, 4, device=6),
        ),
    )
    assert plan.preempt_events() == ((3, 6), (7, 1))  # sorted worklist
    assert all(f.kind != "preempt" for f in plan.kernel_plan().faults)
    # compile() lowers only kernel faults — a preempt is host-side — but
    # the heal horizon still covers it.
    compiled = plan.compile(16, 2)
    assert compiled.loss is not None and compiled.kill is None
    assert plan.heal_round >= 8
    assert FaultPlan.from_json(plan.to_json()).faults == plan.faults


@needs8
def test_poison_lost_shard_destroys_exactly_one_block():
    cfg, _topo, sched = _tiny_dense(n=16)
    host = jax.device_get(init_cluster(cfg, len(sched.sample_writer)))
    mesh = reshard.virtual_mesh(8)
    specs = mesh_mod.cluster_state_specs(host, mesh)
    poisoned, n_leaves = poison_lost_shard(host, specs, mesh, 3)
    assert n_leaves > 0
    # Node-major leaves: rows [6, 8) belong to device 3 on a 16-node
    # 8-device mesh; every other row must be untouched.
    a, b = np.asarray(host.data.contig), np.asarray(poisoned.data.contig)
    assert not np.array_equal(a[6:8], b[6:8])
    np.testing.assert_array_equal(np.delete(a, [6, 7], axis=0),
                                  np.delete(b, [6, 7], axis=0))
    # Replicated leaves (the writer heads) survive the kill intact.
    np.testing.assert_array_equal(
        np.asarray(host.data.head), np.asarray(poisoned.data.head)
    )
    with pytest.raises(ValueError, match="outside"):
        poison_lost_shard(host, specs, mesh, 8)


# -- the mesh-spec reshard contract (satellite: property over builders) -------


def _contract_states():
    """(engine, host_tree, specs_fn) for every engine state family. All
    node counts divide every device count in the matrix."""
    from corrosion_tpu.models.baselines import anywrite_sparse
    from corrosion_tpu.ops import sparse_writers as sw_ops
    from corrosion_tpu.ops import swim as swim_ops
    from corrosion_tpu.ops.chunks import ChunkConfig, init_chunks
    from corrosion_tpu.sim import invariants as inv
    from corrosion_tpu.sim import mixed_engine

    out = []
    cfg, _topo, sched = _tiny_dense(n=16)
    dense = jax.device_get(init_cluster(cfg, len(sched.sample_writer)))
    out.append(("dense", dense, mesh_mod.cluster_state_specs))

    scfg, _st, ssched = anywrite_sparse(
        n=32, w_hot=8, rounds=16, n_regions=4, epoch_rounds=8, cohort=4,
        burst_writes=2, samples=16, k_dev=8, seed=3,
    )
    sparse = jax.device_get((
        sw_ops.init_sparse(scfg.gossip, scfg.sparse),
        swim_ops.impl(scfg.swim).init_state(scfg.swim),
        np.zeros((len(ssched.sample_writer), scfg.n_nodes), np.int32),
    ))

    def sparse_specs(tree, mesh):
        return (
            mesh_mod.sparse_state_specs(tree[0], mesh),
            mesh_mod.node_major_specs(tree[1], mesh),
            P(None, shard_driver.node_spec_entry(mesh)),
        )

    out.append(("sparse", sparse, sparse_specs))

    ccfg = ChunkConfig(
        n_nodes=16, n_streams=2, cap=8, chunk_len=64, fanout=2, k_in=4,
        sync_interval=2, gap_requests=2, sync_seq_budget=256,
    )
    chunk = jax.device_get((
        init_chunks(
            ccfg, np.asarray([0, 7], np.int32),
            np.asarray([255, 255], np.int32),
        ),
        np.full((ccfg.n_nodes, ccfg.n_streams), -1, np.int32),
    ))

    def chunk_specs(tree, mesh):
        return (
            mesh_mod.node_major_specs(tree[0], mesh),
            P(shard_driver.node_spec_entry(mesh), None),
        )

    out.append(("chunk", chunk, chunk_specs))

    mcfg, mccfg, mtopo, msched, mstreams = inv._mixed_scenario(
        FaultPlan(rounds=24, name="contract"), 0
    )
    mixed = jax.device_get(mixed_engine.init_mixed_state(
        mcfg, mccfg, mtopo, msched, mstreams
    ))
    out.append(("mixed", mixed, mesh_mod.mixed_state_specs))
    return out


@needs8
def test_mesh_specs_are_a_reshard_bijection():
    """The reshard contract on the ONE spec source: for every engine
    state and every (D, D′) ∈ {1,2,4,8}², place → gather → re-place
    loses nothing (bit-exact round trip, no silent truncation or
    padding) and ``predicted_per_device_bytes`` matches the live shards
    byte-exact on BOTH meshes (place_reconciled raises otherwise)."""
    meshes = {d: reshard.virtual_mesh(d) for d in (1, 2, 4, 8)}
    for engine_name, host, specs_fn in _contract_states():
        for d_a, d_b in itertools.product((1, 2, 4, 8), repeat=2):
            placed_a, rec_a = reshard.place_reconciled(
                host, specs_fn(host, meshes[d_a]), meshes[d_a]
            )
            host_a = jax.device_get(placed_a)
            assert el_report.diff_trees(
                host, host_a, f"{engine_name} D={d_a}: "
            ) == []
            placed_b, rec_b = reshard.place_reconciled(
                host_a, specs_fn(host_a, meshes[d_b]), meshes[d_b]
            )
            assert el_report.diff_trees(
                host, jax.device_get(placed_b),
                f"{engine_name} {d_a}->{d_b}: ",
            ) == []
            assert rec_a["ok"] and rec_b["ok"]
            assert rec_a["devices"] == d_a and rec_b["devices"] == d_b


# -- chunk-engine resume seam (single device, tier-1 sized) -------------------


def test_chunk_resume_bit_identical_single_device():
    from corrosion_tpu.ops.chunks import ChunkConfig

    ccfg = ChunkConfig(
        n_nodes=16, n_streams=2, cap=8, chunk_len=64, fanout=2, k_in=4,
        sync_interval=2, gap_requests=2, sync_seq_budget=256,
    )
    origin = np.asarray([0, 7], np.int32)
    last_seq = np.asarray([255, 255], np.int32)
    mesh = reshard.virtual_mesh(1)

    ref_state, ref_m = shard_driver.simulate_chunks_sharded(
        ccfg, origin, last_seq, 8, mesh, seed=0
    )
    state, m1 = shard_driver.simulate_chunks_sharded(
        ccfg, origin, last_seq, 4, mesh, seed=0
    )
    final, m2 = shard_driver.simulate_chunks_sharded(
        ccfg, origin, last_seq, 4, mesh, seed=0,
        state=state, vis=m1["vis"], start_round=4,
    )
    assert el_report.diff_trees(
        jax.device_get((final, m2["vis"])),
        jax.device_get((ref_state, ref_m["vis"])),
    ) == []
    stitched = {
        k: np.concatenate([np.asarray(m1["curves"][k]), np.asarray(v)])
        for k, v in m2["curves"].items()
    }
    assert el_report.diff_curves(stitched, ref_m["curves"]) == []


# -- budget gate --------------------------------------------------------------


def _gate_scenario(**over):
    s = {
        "scenario": "drill", "bit_identical": True, "mismatches": [],
        "reconcile": {"ok": True}, "violations": [],
        "machinery": {"fired": True}, "wall_s": {"run": 1.0}, "ok": True,
    }
    s.update(over)
    return s


def _gate_budget(**over):
    b = {
        "tolerance": 2.0, "require_bit_identical": 1, "require_reconcile": 1,
        "require_machinery_fired": 1, "oracle_violations_max": 0,
        "scenarios": {"drill": {"wall_ceiling_s": 1.0}},
    }
    b.update(over)
    return b


def test_elastic_budget_gate_scales_only_wall():
    report = {"scenarios": [_gate_scenario()]}
    gate = el_report.check_elastic_budget(report, _gate_budget())
    assert gate["ok"] and gate["breaches"] == []
    # wall 1.0 passes only because the 2x tolerance scales the 1.0
    # ceiling; the same wall breaches at tolerance 0.5.
    gate = el_report.check_elastic_budget(
        report, _gate_budget(tolerance=0.5)
    )
    assert not gate["ok"] and "wall" in gate["breaches"][0]


@pytest.mark.parametrize(
    "over, needle",
    [
        ({"bit_identical": False}, "bit-identical"),
        ({"reconcile": {"ok": False}}, "reconcile"),
        ({"violations": ["x"], "ok": False}, "violation"),
        ({"machinery": {"fired": False}}, "machinery"),
        ({"scenario": "other"}, "missing"),
    ],
)
def test_elastic_budget_gate_never_scales_survival(over, needle):
    """The survival invariants breach at ANY tolerance."""
    report = {"scenarios": [_gate_scenario(**over)]}
    gate = el_report.check_elastic_budget(
        report, _gate_budget(tolerance=1e9)
    )
    assert not gate["ok"]
    assert any(needle in b for b in gate["breaches"])


# -- endurance tie-in: restart classification across re-attach ----------------


def test_series_attach_adopts_and_classifies_restart(tmp_path):
    """An in-process reshard/preemption re-attaches the recorder (same
    path) and restarts its counters from zero; the replayed series must
    show ONE header and the reset classified `restart` — not a wedge or
    leak fake (the soak_preempt scenario pins the same end to end)."""
    from corrosion_tpu.obs import endurance
    from corrosion_tpu.obs import series as series_mod
    from corrosion_tpu.utils.metrics import MetricsRegistry

    path = str(tmp_path / "series.jsonl")
    rec = series_mod.MetricSeriesRecorder.attach(
        path, clock=None, source="t", mode="w"
    )
    try:
        reg = MetricsRegistry()
        for t in range(20):
            if t == 10:  # the preempted process relaunches
                reg = MetricsRegistry()
                rec2 = series_mod.MetricSeriesRecorder.attach(path)
                assert rec2 is rec  # adopted, not reopened
            reg.counter("corro_changes_committed").inc(10.0)
            reg.counter("corro_changes_applied").inc(10.0)
            reg.gauge("corro_sync_needs").set(0.0)
            rec.sample(reg, t=float(t))
    finally:
        rec.close()
        rec.close()  # one close per attach (refcounted)

    data = series_mod.replay_series(path)
    assert len(data["headers"]) == 1
    rep = endurance.build_report(
        data["samples"], t_scale_s=1.0, label="attach-pin"
    )
    resets = rep["resets"]["corro_changes_committed"]
    assert resets["events"] == 1 and set(resets["kinds"]) == {"restart"}
    assert not any(w["wedged"] for w in rep["wedges"].values())
    assert rep["ok"], rep["breaches"]


# -- the standing drills (multichip CI job) -----------------------------------


@needs8
@pytest.mark.slow  # tier-1 budget; the multichip CI job runs this file unfiltered
@pytest.mark.parametrize("d_from, d_to", scenarios.RESHARD_MATRIX)
def test_reshard_dense_matrix_bit_identical(tmp_path, d_from, d_to):
    rep = scenarios.run_reshard_scenario(
        "dense", d_from, d_to, checkpoint_dir=str(tmp_path)
    )
    assert rep["bit_identical"], rep["mismatches"]
    assert rep["reconcile"]["ok"]
    assert rep["checkpoint"]["schema"] == "corro-checkpoint/1"
    assert rep["checkpoint"]["mesh"] == list(
        reshard.mesh_dims(reshard.virtual_mesh(d_from))
    )
    assert rep["ok"]


@needs8
@pytest.mark.slow  # tier-1 budget; the multichip CI job runs this file unfiltered
@pytest.mark.parametrize("engine", ["sparse", "chunk", "mixed"])
def test_reshard_other_engines_bit_identical(tmp_path, engine):
    rep = scenarios.run_reshard_scenario(
        engine, 4, 8, checkpoint_dir=str(tmp_path)
    )
    assert rep["bit_identical"], rep["mismatches"]
    assert rep["reconcile"]["ok"] and rep["ok"]


@needs8
@pytest.mark.slow  # tier-1 budget; the multichip CI job runs this file unfiltered
def test_preempt_scenario_survives_with_machinery_fired(tmp_path):
    rep = scenarios.run_preempt_scenario(checkpoint_dir=str(tmp_path))
    assert rep["violations"] == []
    assert rep["bit_identical"], rep["mismatches"]
    mach = rep["machinery"]
    assert mach["fired"] and mach["preempts_fired"] == 2
    assert mach["poison_changed"] and mach["replay_identical"]
    assert mach["gap_rounds_replayed"] > 0
    assert rep["reconcile"]["ok"] and rep["reconcile"]["count"] == 2
    assert rep["ok"]


@needs8
@pytest.mark.slow  # tier-1 budget; the multichip CI job runs this file unfiltered
def test_soak_preempt_classifies_recoveries_as_restarts(tmp_path):
    rep = scenarios.run_soak_preempt_scenario(
        str(tmp_path / "series.jsonl")
    )
    assert rep["violations"] == []
    e = rep["endurance"]
    assert e["ok"] and e["detectors_armed"]["wedge"]
    for stem in ("corro_changes_committed", "corro_changes_applied"):
        assert set(e["resets"][stem]["kinds"]) == {"restart"}
    assert rep["ok"]
