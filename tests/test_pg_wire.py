"""PostgreSQL wire server (corro-pg analogue): protocol-level test.

The reference's test_pg drives a real pg client against the in-process
server (corro-pg/src/lib.rs test_pg). No pg driver ships in this
environment, so this speaks protocol v3 directly over a socket: startup,
simple query, write-path parity with the agent's bookkeeping.
"""

import asyncio
import struct

from corrosion_tpu.agent.testing import launch_test_agent


def run(coro):
    return asyncio.run(coro)


class MiniPg:
    """Tiny protocol-v3 client (simple query flow only)."""

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer

    @classmethod
    async def connect(cls, host, port):
        reader, writer = await asyncio.open_connection(host, port)
        params = b"user\x00test\x00database\x00main\x00\x00"
        payload = struct.pack(">I", 196608) + params
        writer.write(struct.pack(">I", len(payload) + 4) + payload)
        await writer.drain()
        self = cls(reader, writer)
        msgs = await self.read_until(b"Z")
        assert any(t == b"R" for t, _ in msgs), "AuthenticationOk expected"
        return self

    async def read_msg(self):
        header = await self.reader.readexactly(5)
        tag = header[0:1]
        (length,) = struct.unpack(">I", header[1:5])
        return tag, await self.reader.readexactly(length - 4)

    async def read_until(self, end_tag):
        out = []
        while True:
            tag, payload = await self.read_msg()
            out.append((tag, payload))
            if tag == end_tag:
                return out

    async def query(self, sql):
        body = sql.encode() + b"\x00"
        self.writer.write(b"Q" + struct.pack(">I", len(body) + 4) + body)
        await self.writer.drain()
        return await self.read_until(b"Z")

    def close(self):
        self.writer.close()


def _rows(msgs):
    rows = []
    for tag, payload in msgs:
        if tag != b"D":
            continue
        (n,) = struct.unpack(">H", payload[:2])
        off = 2
        row = []
        for _ in range(n):
            (ln,) = struct.unpack(">i", payload[off:off + 4])
            off += 4
            if ln == -1:
                row.append(None)
            else:
                row.append(payload[off:off + ln].decode())
                off += ln
        rows.append(row)
    return rows


def test_pg_select_insert_and_parity(tmp_path):
    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        from corrosion_tpu.agent.pg import serve_pg

        server, (host, port) = await serve_pg(a.agent)
        try:
            pg = await MiniPg.connect(host, port)
            # Write through the pg path.
            msgs = await pg.query(
                "INSERT INTO tests (id, text) VALUES (1, 'via-pg')"
            )
            tags = [t for t, _ in msgs]
            assert b"C" in tags and b"E" not in tags
            # The write went through agent bookkeeping (broadcast parity).
            assert a.agent.bookie.get(a.agent.actor_id).last() == 1
            # Read back.
            msgs = await pg.query("SELECT id, text FROM tests ORDER BY id")
            assert _rows(msgs) == [["1", "via-pg"]]
            # Multi-statement + transaction noise like psql sends.
            msgs = await pg.query(
                "BEGIN; INSERT INTO tests (id, text) VALUES (2, 'two'); COMMIT"
            )
            assert b"E" not in [t for t, _ in msgs]
            msgs = await pg.query("SELECT count(*) FROM tests")
            assert _rows(msgs) == [["2"]]
            # Errors surface as ErrorResponse, connection stays usable.
            msgs = await pg.query("SELECT * FROM nosuch")
            assert b"E" in [t for t, _ in msgs]
            msgs = await pg.query("SELECT version()")
            assert "corrosion-tpu" in _rows(msgs)[0][0]
            pg.close()
        finally:
            server.close()
            await a.stop()

    run(main())


def test_pg_catalog_introspection(tmp_path):
    """Catalog queries ORMs/psql issue at connect: pg_class/pg_attribute/
    pg_namespace reflect the live schema; session shims answer
    current_database()/current_schema() (the reference's pg_catalog vtabs,
    corro-pg/src/vtab/*)."""

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        from corrosion_tpu.agent.pg import serve_pg

        server, (host, port) = await serve_pg(a.agent)
        try:
            pg = await MiniPg.connect(host, port)
            msgs = await pg.query(
                "SELECT relname FROM pg_catalog.pg_class"
                " WHERE relkind = 'r' ORDER BY relname"
            )
            names = [r[0] for r in _rows(msgs)]
            assert names == ["tests", "tests2", "testsblob"]
            # Columns with type + pk flag, the \d backbone.
            msgs = await pg.query(
                "SELECT a.attname, t.typname, a.attnotnull"
                " FROM pg_attribute a"
                " JOIN pg_class c ON c.oid = a.attrelid"
                " JOIN pg_type t ON t.oid = a.atttypid"
                " WHERE c.relname = 'tests' ORDER BY a.attnum"
            )
            assert _rows(msgs) == [
                ["id", "int8", "1"], ["text", "text", "0"],
            ]
            msgs = await pg.query(
                "SELECT nspname FROM pg_namespace ORDER BY oid"
            )
            assert [r[0] for r in _rows(msgs)] == ["pg_catalog", "public"]
            msgs = await pg.query(
                "SELECT current_database(), current_schema(), current_user"
            )
            assert _rows(msgs) == [["corrosion", "public", "corrosion"]]
            # A schema migration shows up in the next catalog snapshot.
            from corrosion_tpu.agent.testing import TEST_SCHEMA

            await a.client.schema(
                [TEST_SCHEMA
                 + "CREATE TABLE newt (id INTEGER NOT NULL PRIMARY KEY);"]
            )
            msgs = await pg.query(
                "SELECT tablename FROM pg_tables WHERE tablename = 'newt'"
            )
            assert _rows(msgs) == [["newt"]]
            # Catalog names INSIDE string literals must not reroute the
            # query away from user tables...
            msgs = await pg.query(
                "SELECT count(*) FROM tests WHERE text = 'pg_class'"
            )
            assert _rows(msgs) == [["0"]]
            # ...session keywords inside literals must pass through
            # unrewritten...
            msgs = await pg.query(
                "INSERT INTO tests (id, text) VALUES (7, 'current_user')"
            )
            assert b"E" not in [t for t, _ in msgs]
            msgs = await pg.query("SELECT text FROM tests WHERE id = 7")
            assert _rows(msgs) == [["current_user"]]
            # ...and catalog queries can JOIN user tables (the reference's
            # vtabs share the connection with user data).
            msgs = await pg.query(
                "SELECT c.relname, count(t.id) FROM pg_class c"
                " LEFT JOIN tests t ON c.relname = 'tests'"
                " WHERE c.relname = 'tests' GROUP BY c.relname"
            )
            assert len(_rows(msgs)) == 1
            pg.close()
        finally:
            server.close()
            await a.stop()

    run(main())


def test_split_statements_quote_aware():
    from corrosion_tpu.agent.pg import _split_statements

    assert _split_statements("SELECT 1; SELECT 2;") == ["SELECT 1", "SELECT 2"]
    # ';' inside string literals must not split (real PG accepts these).
    assert _split_statements(
        "INSERT INTO t VALUES (1, 'a;b'); SELECT 'x;''y;' ;"
    ) == ["INSERT INTO t VALUES (1, 'a;b')", "SELECT 'x;''y;'"]
    assert _split_statements('SELECT ";" AS "a;b"') == ['SELECT ";" AS "a;b"']
    assert _split_statements("  ;;  ") == []


def _pg_msg(tag: bytes, payload: bytes) -> bytes:
    import struct

    return tag + struct.pack(">I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


def test_pg_extended_protocol(tmp_path):
    """Parse/Bind/Describe/Execute/Sync — the libpq PQexecParams flow —
    against a live agent, at the byte level (no PG client libs in-image)."""
    import struct

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        from corrosion_tpu.agent.pg import serve_pg

        server, (host, port) = await serve_pg(a.agent)
        try:
            reader, writer = await asyncio.open_connection(host, port)
            # Startup
            startup = struct.pack(">I", 196608) + _cstr("user") + _cstr("t") + b"\x00"
            writer.write(struct.pack(">I", len(startup) + 4) + startup)
            await writer.drain()

            async def read_msg():
                header = await reader.readexactly(5)
                tag = header[0:1]
                (length,) = struct.unpack(">I", header[1:5])
                return tag, await reader.readexactly(length - 4)

            # Drain until ReadyForQuery
            while (await read_msg())[0] != b"Z":
                pass

            # INSERT via extended flow with $1/$2 params (oids: int4, text)
            parse = (_cstr("st1")
                     + _cstr("INSERT INTO tests (id, text) VALUES ($1, $2)")
                     + struct.pack(">H", 2)
                     + struct.pack(">II", 23, 25))
            bind = (_cstr("") + _cstr("st1")
                    + struct.pack(">H", 1) + struct.pack(">H", 0)  # all text
                    + struct.pack(">H", 2)
                    + struct.pack(">i", 1) + b"7"
                    + struct.pack(">i", 3) + b"ext"
                    + struct.pack(">H", 0))
            execute = _cstr("") + struct.pack(">i", 0)
            writer.write(_pg_msg(b"P", parse) + _pg_msg(b"B", bind)
                         + _pg_msg(b"E", execute) + _pg_msg(b"S", b""))
            await writer.drain()
            tags = []
            while True:
                tag, payload = await read_msg()
                tags.append(tag)
                if tag == b"C":
                    assert payload.startswith(b"INSERT 0 1")
                if tag == b"Z":
                    break
            assert tags[:3] == [b"1", b"2", b"C"]  # Parse/Bind/CommandComplete

            # SELECT it back with a $1 param + Describe(portal)
            parse = (_cstr("st2")
                     + _cstr("SELECT id, text FROM tests WHERE id = $1")
                     + struct.pack(">H", 1) + struct.pack(">I", 23))
            bind = (_cstr("p2") + _cstr("st2")
                    + struct.pack(">H", 0)  # default text format
                    + struct.pack(">H", 1)
                    + struct.pack(">i", 1) + b"7"
                    + struct.pack(">H", 0))
            describe = b"P" + _cstr("p2")
            execute = _cstr("p2") + struct.pack(">i", 0)
            writer.write(_pg_msg(b"P", parse) + _pg_msg(b"B", bind)
                         + _pg_msg(b"D", describe) + _pg_msg(b"E", execute)
                         + _pg_msg(b"S", b""))
            await writer.drain()
            saw = {}
            while True:
                tag, payload = await read_msg()
                saw.setdefault(tag, payload)
                if tag == b"Z":
                    break
            assert b"T" in saw  # RowDescription names the columns
            assert b"id" in saw[b"T"] and b"text" in saw[b"T"]
            assert b"D" in saw and b"ext" in saw[b"D"]  # the row came back
            assert saw[b"C"].startswith(b"SELECT 1")

            # Describe(statement) reports parameter oids; errors recover at Sync.
            writer.write(_pg_msg(b"D", b"S" + _cstr("st2")) + _pg_msg(b"S", b""))
            await writer.drain()
            saw = {}
            while True:
                tag, payload = await read_msg()
                saw.setdefault(tag, payload)
                if tag == b"Z":
                    break
            assert b"t" in saw  # ParameterDescription
            (n_oids,) = struct.unpack_from(">H", saw[b"t"], 0)
            assert n_oids == 1

            # Unknown statement -> error, then recovery after Sync.
            bad_bind = (_cstr("") + _cstr("nope") + struct.pack(">H", 0)
                        + struct.pack(">H", 0) + struct.pack(">H", 0))
            writer.write(_pg_msg(b"B", bad_bind)
                         + _pg_msg(b"E", _cstr("") + struct.pack(">i", 0))
                         + _pg_msg(b"S", b""))
            await writer.drain()
            tags = []
            while True:
                tag, _ = await read_msg()
                tags.append(tag)
                if tag == b"Z":
                    break
            assert b"E" in tags  # ErrorResponse, Execute discarded
            assert tags.count(b"E") == 1

            writer.write(_pg_msg(b"X", b""))
            writer.close()
        finally:
            server.close()
            await a.stop()

    run(main())


def test_translate_placeholders():
    from corrosion_tpu.agent.pg import translate_placeholders

    assert translate_placeholders("SELECT $1, $2") == "SELECT ?1, ?2"
    # $ inside literals must survive.
    assert translate_placeholders("SELECT '$1', \"a$2\", $3") == (
        "SELECT '$1', \"a$2\", ?3"
    )
    assert translate_placeholders("SELECT 1") == "SELECT 1"


def test_pg_dialect_translation():
    """PG-isms → SQLite (corro-pg's sqlparser translation, lib.rs:306-472):
    ``::`` casts, boolean literals, ILIKE, E'...' escape strings."""
    from corrosion_tpu.agent.pg import translate_pg_sql

    assert (
        translate_pg_sql("SELECT id::text FROM t WHERE ok = true")
        == "SELECT CAST(id AS TEXT) FROM t WHERE ok = 1"
    )
    assert (
        translate_pg_sql("SELECT '5'::int4, 1.5::float8")
        == "SELECT CAST('5' AS INTEGER), CAST(1.5 AS REAL)"
    )
    # Parenthesized expressions keep the cast (the token-level pass wraps
    # the whole parenthesized run; the old regex pass had to drop these).
    assert (
        translate_pg_sql("SELECT (id + 1)::bigint FROM t")
        == "SELECT CAST((id + 1) AS INTEGER) FROM t"
    )
    # varchar(32)-style length qualifiers are consumed with the cast.
    assert (
        translate_pg_sql("SELECT name::varchar(32) FROM t")
        == "SELECT CAST(name AS TEXT) FROM t"
    )
    assert (
        translate_pg_sql("SELECT * FROM t WHERE a ILIKE 'x%' AND b = false")
        == "SELECT * FROM t WHERE a LIKE 'x%' AND b = 0"
    )
    # E-strings decode backslash escapes into standard literals.
    assert (
        translate_pg_sql(r"INSERT INTO t VALUES (E'a\nb\'c')")
        == "INSERT INTO t VALUES ('a\nb''c')"
    )
    # Literals stay untouched: 'true' inside a string is data.
    assert (
        translate_pg_sql("INSERT INTO t VALUES ('true::int4')")
        == "INSERT INTO t VALUES ('true::int4')"
    )
    # Dollar-quoted blocks are opaque.
    assert (
        translate_pg_sql("SELECT $$true::x$$")
        == "SELECT $$true::x$$"
    )


def test_pg_sqlstate_mapping():
    from corrosion_tpu.agent.pg import sqlstate_for

    assert sqlstate_for("no such table: nope") == "42P01"
    assert sqlstate_for("no such column: z") == "42703"
    assert sqlstate_for('near "FRM": syntax error') == "42601"
    assert sqlstate_for("UNIQUE constraint failed: tests.id") == "23505"
    assert sqlstate_for("NOT NULL constraint failed: t.x") == "23502"
    assert sqlstate_for("whatever else") == "XX000"


def test_pg_binary_formats(tmp_path):
    """Binary Bind parameters + binary result formats (the PQexecParams
    paramFormats=1 / resultFormat=1 flow real drivers use)."""
    import struct

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        from corrosion_tpu.agent.pg import serve_pg

        server, (host, port) = await serve_pg(a.agent)
        try:
            reader, writer = await asyncio.open_connection(host, port)
            startup = struct.pack(">I", 196608) + _cstr("user") + _cstr("t") + b"\x00"
            writer.write(struct.pack(">I", len(startup) + 4) + startup)
            await writer.drain()

            async def read_msg():
                header = await reader.readexactly(5)
                tag = header[0:1]
                (length,) = struct.unpack(">I", header[1:5])
                return tag, await reader.readexactly(length - 4)

            while (await read_msg())[0] != b"Z":
                pass

            # INSERT with BINARY int4 + text params.
            parse = (_cstr("s")
                     + _cstr("INSERT INTO tests (id, text) VALUES ($1, $2)")
                     + struct.pack(">H", 2) + struct.pack(">II", 23, 25))
            bind = (_cstr("") + _cstr("s")
                    + struct.pack(">HHH", 2, 1, 0)  # fmts: binary, text
                    + struct.pack(">H", 2)
                    + struct.pack(">i", 4) + struct.pack(">i", 99)  # binary int4
                    + struct.pack(">i", 3) + b"bin"
                    + struct.pack(">H", 0))
            writer.write(_pg_msg(b"P", parse) + _pg_msg(b"B", bind)
                         + _pg_msg(b"E", _cstr("") + struct.pack(">i", 0))
                         + _pg_msg(b"S", b""))
            await writer.drain()
            while True:
                tag, payload = await read_msg()
                if tag == b"C":
                    assert payload.startswith(b"INSERT 0 1")
                if tag == b"Z":
                    break

            # SELECT it back asking for BINARY results.
            parse = (_cstr("q") + _cstr("SELECT id, text FROM tests WHERE id = $1")
                     + struct.pack(">H", 1) + struct.pack(">I", 23))
            bind = (_cstr("p") + _cstr("q")
                    + struct.pack(">HH", 1, 1)  # one param fmt: binary
                    + struct.pack(">H", 1)
                    + struct.pack(">i", 4) + struct.pack(">i", 99)
                    + struct.pack(">H", 1) + struct.pack(">H", 1))  # results binary
            describe = b"P" + _cstr("p")
            writer.write(_pg_msg(b"P", parse) + _pg_msg(b"B", bind)
                         + _pg_msg(b"D", describe)
                         + _pg_msg(b"E", _cstr("p") + struct.pack(">i", 0))
                         + _pg_msg(b"S", b""))
            await writer.drain()
            saw = {}
            while True:
                tag, payload = await read_msg()
                saw.setdefault(tag, payload)
                if tag == b"Z":
                    break
            # RowDescription: id column typed int8 with binary format code.
            t = saw[b"T"]
            (ncols,) = struct.unpack_from(">H", t, 0)
            assert ncols == 2
            off = 2
            metas = []
            for _ in range(ncols):
                end = t.index(b"\x00", off)
                name = t[off:end].decode()
                tbl, attnum, oid, tlen, tmod, fmt = struct.unpack_from(
                    ">IhIhih", t, end + 1
                )
                metas.append((name, oid, fmt))
                off = end + 1 + 18
            assert metas[0] == ("id", 20, 1)  # int8, binary
            # DataRow: binary int8 99 + binary text.
            d = saw[b"D"]
            (n,) = struct.unpack_from(">H", d, 0)
            (ln,) = struct.unpack_from(">i", d, 2)
            assert ln == 8
            (val,) = struct.unpack_from(">q", d, 6)
            assert val == 99
            (ln2,) = struct.unpack_from(">i", d, 14)
            assert d[18:18 + ln2] == b"bin"

            # SQLSTATE travels on errors: undefined table → 42P01.
            parse = (_cstr("bad") + _cstr("SELECT * FROM nope_table")
                     + struct.pack(">H", 0))
            bind = (_cstr("pb") + _cstr("bad") + struct.pack(">H", 0)
                    + struct.pack(">H", 0) + struct.pack(">H", 0))
            writer.write(_pg_msg(b"P", parse) + _pg_msg(b"B", bind)
                         + _pg_msg(b"E", _cstr("pb") + struct.pack(">i", 0))
                         + _pg_msg(b"S", b""))
            await writer.drain()
            err_payload = None
            while True:
                tag, payload = await read_msg()
                if tag == b"E":
                    err_payload = payload
                if tag == b"Z":
                    break
            assert err_payload is not None and b"C42P01\x00" in err_payload

            writer.write(_pg_msg(b"X", b""))
            writer.close()
        finally:
            server.close()
            await a.stop()

    run(main())


# ---------------------------------------------------------------------------
# Statement routing: write-verb tokens vs the replace() SQL function
# (ADVICE r5: a read-only query using replace(...) must not be misrouted
# to the write path, and a WITH-headed write's CommandComplete tag must
# name the real top-level DML verb).


def test_replace_function_routes_as_read():
    from corrosion_tpu.agent import pg

    # replace() as a function: pure reads, even under WITH.
    assert pg._is_query(
        "WITH x AS (SELECT replace(name, 'a', 'b') AS n FROM t) "
        "SELECT * FROM x"
    )
    assert pg._is_query("SELECT replace(col, 'x', 'y') FROM t")
    # Real write verbs still route as writes.
    assert not pg._is_query(
        "WITH x AS (SELECT 1) INSERT INTO t SELECT * FROM x"
    )
    assert not pg._is_query("WITH x AS (SELECT 1) REPLACE INTO t VALUES (1)")
    # Verb words inside strings/comments never count (lexer tokens).
    assert pg._is_query(
        "WITH x AS (SELECT 'insert into y' AS s) SELECT * FROM x"
    )


def test_dml_word_skips_function_calls():
    from corrosion_tpu.agent import pg

    assert pg._dml_word(
        "WITH x AS (SELECT replace(n, 'a', 'b') FROM t) "
        "UPDATE u SET v = 1"
    ) == "UPDATE"
    assert pg._dml_word(
        "WITH x AS (SELECT replace(n, 'a', 'b') FROM t) "
        "INSERT INTO u SELECT * FROM x"
    ) == "INSERT"
    # Plain-headed statements keep their head verb.
    assert pg._dml_word("REPLACE INTO t VALUES (1)") == "REPLACE"
    assert pg._dml_word("DELETE FROM t WHERE a = 1") == "DELETE"
