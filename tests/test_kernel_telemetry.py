"""Kernel telemetry plane tests (sim/telemetry.py).

Covers the RoundCurves schema parity across all three engines, the flight
recorder (chunk-boundary streaming, crash tolerance, resume), the
corro_kernel_* metrics bridge, the kernel_chunk tracer span, and the
plane-attribution telescoping invariant.
"""

import json

import numpy as np
import pytest

from corrosion_tpu import models
from corrosion_tpu.sim import simulate
from corrosion_tpu.sim import telemetry as T
from corrosion_tpu.sim.engine import Schedule
from corrosion_tpu.utils import metrics as M
from corrosion_tpu.utils import tracing as TR


def _dense_run(**kw):
    cfg, topo, sched = models.merge_10k(n=64, rounds=24, samples=16)
    return simulate(cfg, topo, sched, seed=5, **kw)


def test_round_curves_schema_rejects_unknown_keys():
    with pytest.raises(ValueError):
        T.round_curves(msgs=1, not_a_curve=2)
    full = T.round_curves(msgs=1)
    assert tuple(full) == T.ROUND_CURVE_KEYS


def test_engines_emit_identical_round_curve_keys():
    """The unify-and-assert parity check: dense, sparse, chunk, AND mixed
    engines must emit exactly the canonical RoundCurves key set
    (health-plane keys included)."""
    _, dense_curves = _dense_run()

    from corrosion_tpu.sim import sparse_engine

    s_cfg, s_topo, s_sched = models.anywrite_sparse(
        n=96, w_hot=16, n_regions=4, rounds=24, cohort=8, epoch_rounds=8,
        k_dev=8, samples=16,
    )
    *_, sparse_curves, _info = sparse_engine.simulate_sparse(
        s_cfg, s_topo, s_sched, seed=0
    )

    from corrosion_tpu.ops.chunks import ChunkConfig
    from corrosion_tpu.sim.chunk_engine import simulate_chunks

    c_cfg = ChunkConfig(
        n_nodes=16, n_streams=2, chunk_len=64, fanout=3, sync_interval=4,
        gap_requests=4,
    )
    _, m = simulate_chunks(c_cfg, [0, 5], [511, 255], rounds=24, seed=1)
    chunk_curves = m["curves"]

    from corrosion_tpu.models.baselines import mixed_storm
    from corrosion_tpu.sim import mixed_engine

    m_cfg, m_ccfg, m_topo, m_sched, m_spec = mixed_storm(
        n=64, streams=2, last_seq=255, rounds=24, samples=16, n_cells=0
    )
    _, mixed_curves = mixed_engine.simulate_mixed(
        m_cfg, m_ccfg, m_topo, m_sched, m_spec, seed=0
    )

    want = set(T.ROUND_CURVE_KEYS)
    assert set(dense_curves) == want
    assert set(sparse_curves) == want
    assert set(chunk_curves) - {"round"} == want
    assert set(mixed_curves) == want
    for curves in (dense_curves, sparse_curves, mixed_curves):
        for k in T.ROUND_CURVE_KEYS:
            assert curves[k].shape == (24,), k


def test_vis_count_totals_match_final_visibility():
    final, curves = _dense_run()
    assert int(curves["vis_count"].sum()) == int(
        (np.asarray(final.vis_round) >= 0).sum()
    )


def test_flight_recorder_chunked_run_and_metrics_bridge(tmp_path):
    """A chunked run with the recorder writes per-round JSONL at each
    chunk boundary; the registry afterwards carries corro_kernel_* series
    whose totals equal the summed curves; the tracer holds one
    kernel_chunk span per chunk."""
    path = str(tmp_path / "flight.jsonl")
    reg = M.MetricsRegistry()
    tracer = TR.Tracer()
    tele = T.KernelTelemetry(
        engine="dense",
        recorder=T.FlightRecorder(path, engine="dense"),
        registry=reg,
        tracer=tracer,
    )
    final, curves = _dense_run(max_chunk=8, telemetry=tele)
    tele.recorder.close()

    # Chunk boundaries: 24 rounds / 8 = 3 chunks, timed and spanned.
    assert len(tele.chunk_walls) == 3
    assert all(n == 8 for n, _ in tele.chunk_walls)
    assert tele.device_step_ms > 0
    spans = tracer.recent(name="kernel_chunk")
    assert len(spans) == 3
    assert [s["attrs"]["start_round"] for s in spans] == [0, 8, 16]

    # JSONL replay reproduces the returned curves exactly.
    rec, chunk_markers = T.replay_flight(path)
    assert rec["round"].tolist() == list(range(24))
    assert len(chunk_markers) == 3
    assert all("wall_s" in c for c in chunk_markers)
    for k in T.ROUND_CURVE_KEYS:
        np.testing.assert_array_equal(
            rec[k].astype(np.float64), curves[k].astype(np.float64), err_msg=k
        )

    # Metrics bridge: totals equal summed curves, on the same renderer
    # the agent plane uses. Health-plane keys render under the
    # corro_kernel_health_ prefix (T.series_name). The propagation
    # plane's per-link/per-bucket curves stay flight-record-only: the
    # bridge carries their aggregates instead (publish_curves
    # docstring; the aggregate identities are pinned in
    # tests/test_epidemic.py).
    text = reg.render()
    per_key_agg = set(T.LINK_CURVE_KEYS) | set(T.RUMOR_AGE_KEYS)
    for k in T.ROUND_CURVE_KEYS:
        if k in per_key_agg:
            assert f"{T.series_name(k)}_total" not in text, k
            continue
        got = reg.counter(f"{T.series_name(k)}_total").get(engine="dense")
        assert got == float(curves[k].astype(np.float64).sum()), k
        assert f"{T.series_name(k)}_total" in text
    for agg in (
        "corro_kernel_prop_link_same_region_total",
        "corro_kernel_prop_link_cross_region_total",
        "corro_kernel_prop_rumor_events_total",
    ):
        assert agg in text
    assert reg.counter("corro_kernel_rounds_total").get(engine="dense") == 24
    assert reg.gauge("corro_kernel_need_last").get(engine="dense") == float(
        curves["need"][-1]
    )
    assert reg.gauge("corro_kernel_health_staleness_sum_last").get(
        engine="dense"
    ) == float(curves["staleness_sum"][-1])
    assert reg.histogram("corro_kernel_chunk_seconds").count(engine="dense") == 3


def test_flight_recorder_crash_resume(tmp_path):
    """Kill mid-run (simulated: first half recorded, then a torn partial
    line from the crash), resume from carried state appending to the same
    record: replay must match a clean uninterrupted run exactly."""
    cfg, topo, sched = models.merge_10k(n=64, rounds=24, samples=16)
    clean_final, clean_curves = simulate(cfg, topo, sched, seed=7)

    path = str(tmp_path / "flight.jsonl")
    first = Schedule(
        writes=sched.writes[:12], sample_writer=sched.sample_writer,
        sample_ver=sched.sample_ver, sample_round=sched.sample_round,
    )
    second = Schedule(
        writes=sched.writes[12:], sample_writer=sched.sample_writer,
        sample_ver=sched.sample_ver, sample_round=sched.sample_round,
    )
    tele1 = T.KernelTelemetry(
        engine="dense", recorder=T.FlightRecorder(path, engine="dense")
    )
    mid, _ = simulate(cfg, topo, first, seed=7, max_chunk=6, telemetry=tele1)
    tele1.recorder.close()
    # The crash: a round record torn mid-write.
    with open(path, "a") as f:
        f.write('{"kind": "round", "round": 12, "msgs": 31')

    tele2 = T.KernelTelemetry(
        engine="dense", recorder=T.FlightRecorder(path, engine="dense")
    )
    final, _ = simulate(
        cfg, topo, second, seed=7, state=mid, max_chunk=6, telemetry=tele2
    )
    tele2.recorder.close()

    rec, _ = T.replay_flight(path)
    assert rec["round"].tolist() == list(range(24))
    for k in T.ROUND_CURVE_KEYS:
        np.testing.assert_array_equal(
            rec[k].astype(np.float64),
            clean_curves[k].astype(np.float64),
            err_msg=k,
        )
    for a, b in zip(
        np.asarray(final.vis_round), np.asarray(clean_final.vis_round)
    ):
        np.testing.assert_array_equal(a, b)


def test_replay_flight_skips_garbage_lines(tmp_path):
    path = str(tmp_path / "f.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "flight", "version": 1}) + "\n")
        f.write(json.dumps({"kind": "round", "round": 0, "msgs": 3}) + "\n")
        f.write("{\"kind\": \"round\", \"round\": 1, \"msg")  # torn tail
    rec, chunks = T.replay_flight(path)
    assert rec["round"].tolist() == [0]
    assert rec["msgs"].tolist() == [3]
    assert chunks == []


def test_progress_stream_emits_per_chunk_lines(tmp_path):
    import io

    out = io.StringIO()
    tele = T.KernelTelemetry(engine="dense", progress=out)
    _dense_run(max_chunk=8, telemetry=tele)
    lines = [ln for ln in out.getvalue().splitlines() if ln]
    assert len(lines) == 3
    assert lines[0].startswith("[flight:dense] rounds 0..7 ")
    assert lines[-1].startswith("[flight:dense] rounds 16..23 ")


def test_plane_attribution_telescopes_and_scales():
    """Cumulative-prefix attribution on a toy composite: increments plus
    overhead telescope exactly to the full composite, and scaling onto a
    run wall keeps sum(plane_ms) + residual_ms == step_ms."""
    import jax.numpy as jnp

    def make_step(enabled):
        def step(carry, i):
            x = carry
            if "a" in enabled:
                x = x + jnp.float32(1.0)
            if "b" in enabled:
                x = x * jnp.float32(1.0001)
            return x

        return step

    attr = T.attribute_planes(
        make_step, ("a", "b"), jnp.zeros((64,), jnp.float32), iters=3
    )
    attr.check()  # overhead + sum(increments) == full, exact
    assert attr.full_ms > 0
    plane, residual = attr.scale(100.0)
    assert set(plane) == {"a", "b"}
    assert all(v >= 0 for v in plane.values())
    assert abs(sum(plane.values()) + residual - 100.0) < 1e-9


def test_flight_path_from_argv_never_swallows_positionals():
    f = T.flight_path_from_argv
    assert f(["prog", "300"]) is None
    assert f(["prog", "--flight", "300"]) == "flight.jsonl"  # 300 = rounds
    assert f(["prog", "--flight=/tmp/x.jsonl", "300"]) == "/tmp/x.jsonl"
    assert f(["prog", "--flight="]) == "flight.jsonl"
    assert f(["prog"], default="d.jsonl") is None


def test_simulate_chunks_zero_rounds_returns_empty_curves():
    from corrosion_tpu.ops.chunks import ChunkConfig
    from corrosion_tpu.sim.chunk_engine import simulate_chunks

    cfg = ChunkConfig(n_nodes=8, n_streams=1, chunk_len=64, fanout=2)
    _, m = simulate_chunks(cfg, [0], [63], rounds=0)
    assert set(m["curves"]) == set(T.ROUND_CURVE_KEYS)
    assert all(v.shape == (0,) for v in m["curves"].values())
    assert m["chunks_sent"] == 0 and m["unapplied"] == 8


def test_publish_curves_handles_partial_dicts():
    reg = M.MetricsRegistry()
    T.publish_curves(
        reg, {"msgs": np.asarray([2, 3]), "need": np.asarray([5, 1])},
        engine="chunk",
    )
    assert reg.counter("corro_kernel_msgs_total").get(engine="chunk") == 5
    assert reg.gauge("corro_kernel_need_last").get(engine="chunk") == 1
    assert reg.counter("corro_kernel_rounds_total").get(engine="chunk") == 2
    # Keys absent from the curves emit nothing for that engine label.
    assert (
        reg.counter("corro_kernel_sessions_total").get(engine="chunk") == 0
    )


def test_chunk_engine_chunked_run_with_recorder(tmp_path):
    """simulate_chunks(max_chunk=...) carries state/visibility across
    device executions (identical results), and the recorder streams at
    each boundary under engine="chunk"."""
    from corrosion_tpu.ops.chunks import ChunkConfig
    from corrosion_tpu.sim.chunk_engine import simulate_chunks

    cfg = ChunkConfig(
        n_nodes=16, n_streams=2, chunk_len=64, fanout=3, sync_interval=4,
        gap_requests=4,
    )
    _, plain = simulate_chunks(cfg, [0, 5], [511, 255], rounds=24, seed=2)

    path = str(tmp_path / "chunk.jsonl")
    reg = M.MetricsRegistry()
    tele = T.KernelTelemetry(
        engine="chunk",
        recorder=T.FlightRecorder(path, engine="chunk"),
        registry=reg,
    )
    _, chunked = simulate_chunks(
        cfg, [0, 5], [511, 255], rounds=24, seed=2, max_chunk=8,
        telemetry=tele,
    )
    tele.recorder.close()

    # Chunked == unchunked (RNG folds the absolute round index).
    for k in T.ROUND_CURVE_KEYS:
        np.testing.assert_array_equal(
            plain["curves"][k], chunked["curves"][k], err_msg=k
        )
    assert chunked["p99_s"] == plain["p99_s"]
    assert len(tele.chunk_walls) == 3
    rec, markers = T.replay_flight(path)
    assert rec["round"].tolist() == list(range(24))
    assert [m["start"] for m in markers] == [0, 8, 16]
    assert reg.counter("corro_kernel_applied_sync_total").get(
        engine="chunk"
    ) == float(chunked["curves"]["applied_sync"].astype(np.float64).sum())


def test_sparse_engine_flight_recorder_per_epoch(tmp_path):
    """Sparse runs flush at epoch boundaries and publish under
    engine="sparse"."""
    from corrosion_tpu.sim import sparse_engine

    cfg, topo, sched = models.anywrite_sparse(
        n=96, w_hot=16, n_regions=4, rounds=24, cohort=8, epoch_rounds=8,
        k_dev=8, samples=16,
    )
    path = str(tmp_path / "sparse.jsonl")
    reg = M.MetricsRegistry()
    tele = T.KernelTelemetry(
        engine="sparse",
        recorder=T.FlightRecorder(path, engine="sparse"),
        registry=reg,
    )
    *_, curves, info = sparse_engine.simulate_sparse(
        cfg, topo, sched, seed=0, telemetry=tele
    )
    tele.recorder.close()
    assert len(tele.chunk_walls) == info["epochs"] == 3
    rec, markers = T.replay_flight(path)
    assert rec["round"].tolist() == list(range(24))
    assert [m["start"] for m in markers] == [0, 8, 16]
    np.testing.assert_array_equal(rec["cold_healed"], curves["cold_healed"])
    assert reg.counter("corro_kernel_msgs_total").get(engine="sparse") == float(
        curves["msgs"].astype(np.float64).sum()
    )
