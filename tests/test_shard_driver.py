"""Shard-count invariance for the explicit shard_map round driver.

tests/test_parallel_mesh.py pins the GSPMD placements; this module pins
the multi-chip plane built on top of them (parallel/shard_driver.py):

- All FOUR engine planes (dense, sparse, chunk, mixed) are bit-identical
  across device_count ∈ {1, 2, 4, 8} under the sharded entries — the
  explicit broadcast queue exchange and the GSPMD-placed remainder must
  not change semantics on any mesh shape (1-D node mesh at D ≤ 2, the
  2-D (dcn, ici) WAN mesh from D = 4 so the coalesced outer hop is
  exercised too).
- The measured cross-shard curves equal the static ``traffic_model``
  exactly (and stay zero at D=1 / in unsharded runs) — the traffic
  accounting the bench artifact publishes is the arithmetic the driver
  actually runs, not an estimate.
- Per-device live-state bytes scale O(N/D): the D=8 shard holds ≤ 1/6
  of the D=1 state (docs/SCALING.md "Multi-chip").
- The donated entry points from PR 5 keep their contract when state is
  node-sharded: donated rounds release the sharded input buffers, and
  the chunked engine run (which scans through the donated twins) stays
  bit-identical under the shard_map broadcast driver.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu import models, parallel
from corrosion_tpu.models.baselines import (
    anti_entropy_chunks,
    anywrite_sparse,
    mixed_storm,
)
from corrosion_tpu.sim import benchlib, chunk_engine, engine, mixed_engine
from corrosion_tpu.sim import simulate
from corrosion_tpu.sim.sparse_engine import simulate_sparse
from corrosion_tpu.sim.telemetry import XSHARD_CURVE_KEYS

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)

DEVICE_COUNTS = (1, 2, 4, 8)


def _dense_setup(n=64, rounds=24):
    cfg, topo, sched = models.wan_100k(
        n=n, n_regions=4, n_writers=16, rounds=rounds, samples=16,
        partition=False,
    )
    sched.writes[:, :] = 0
    sched.writes[:8, :] = 1
    return cfg, topo, sched.make_samples(16)


def _assert_curves_equal(ref: dict, got: dict, plane: str):
    for k in ref:
        if k in XSHARD_CURVE_KEYS:
            continue
        np.testing.assert_array_equal(
            np.asarray(ref[k]), np.asarray(got[k]),
            err_msg=f"{plane} curve {k}",
        )


def _assert_xshard_matches_model(curves: dict, cfg_gossip, mesh):
    """The emitted cross-shard curves are the static model, constant
    every round — measured-vs-arithmetic agreement is the accounting
    invariant the bench artifact publishes."""
    tm = parallel.traffic_model(cfg_gossip, mesh)
    for key in XSHARD_CURVE_KEYS:
        got = np.asarray(curves[key], np.float64)
        np.testing.assert_array_equal(
            got, np.full_like(got, tm[key]), err_msg=key
        )


# The 4-device-count whole-run pins cost ~40-60 s of compiles each;
# dense/sparse/mixed run outside the tier-1 870 s budget in the CI
# `multichip` job (the chunk pin stays in-lane as the cheap
# representative, alongside the traffic/memory/donation contracts).
@pytest.mark.slow
def test_dense_bit_identical_across_device_counts():
    cfg, topo, sched = _dense_setup()
    ref_final, ref_curves = simulate(cfg, topo, sched, seed=5)
    for k in XSHARD_CURVE_KEYS:  # unsharded runs report zero traffic
        assert float(np.asarray(ref_curves[k]).sum()) == 0.0
    for d in DEVICE_COUNTS:
        mesh = benchlib.multichip_mesh(d)
        final, curves = parallel.simulate_sharded(
            cfg, topo, sched, mesh, seed=5
        )
        for name in ("head", "contig", "seen", "q_writer", "q_ver"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ref_final.data, name)),
                np.asarray(getattr(final.data, name)),
                err_msg=f"dense D={d} {name}",
            )
        for u, s in zip(
            jax.tree.leaves(ref_final.swim), jax.tree.leaves(final.swim)
        ):
            np.testing.assert_array_equal(np.asarray(u), np.asarray(s))
        _assert_curves_equal(ref_curves, curves, f"dense D={d}")
        _assert_xshard_matches_model(curves, cfg.gossip, mesh)
        if d > 1:
            assert float(np.asarray(curves["xshard_bytes_ici"][0])) > 0


@pytest.mark.slow  # see test_dense_bit_identical_across_device_counts
def test_sparse_bit_identical_across_device_counts():
    cfg, topo, sched = anywrite_sparse(
        n=64, w_hot=8, rounds=16, n_regions=4, epoch_rounds=8,
        cohort=10, burst_writes=2, samples=16, k_dev=8,
    )
    ref = simulate_sparse(cfg, topo, sched, seed=0)
    for d in DEVICE_COUNTS:
        mesh = benchlib.multichip_mesh(d)
        got = parallel.simulate_sparse_sharded(
            cfg, topo, sched, mesh, seed=0
        )
        for name in ("contig", "seen", "q_writer", "q_ver"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ref[0].data, name)),
                np.asarray(getattr(got[0].data, name)),
                err_msg=f"sparse D={d} {name}",
            )
        np.testing.assert_array_equal(
            np.asarray(ref[0].head_full), np.asarray(got[0].head_full),
            err_msg=f"sparse D={d} head_full",
        )
        _assert_curves_equal(ref[3], got[3], f"sparse D={d}")
        _assert_xshard_matches_model(got[3], cfg.gossip, mesh)


@pytest.mark.slow  # tier-1 budget; the multichip CI job runs this file unfiltered
def test_chunk_bit_identical_across_device_counts():
    ccfg, origin, last_seq, _ = anti_entropy_chunks(
        n=64, streams=2, last_seq=127, rounds=0
    )
    _, ref_metrics = chunk_engine.simulate_chunks(
        ccfg, origin, last_seq, 24, seed=3
    )
    for d in DEVICE_COUNTS:
        mesh = benchlib.multichip_mesh(d)
        _, metrics = parallel.simulate_chunks_sharded(
            ccfg, origin, last_seq, 24, mesh, seed=3
        )
        assert metrics["applied_frac"] == ref_metrics["applied_frac"]
        _assert_curves_equal(
            ref_metrics["curves"], metrics["curves"], f"chunk D={d}"
        )


@pytest.mark.slow  # see test_dense_bit_identical_across_device_counts
def test_mixed_bit_identical_across_device_counts():
    cfg, ccfg, topo, sched, spec = mixed_storm(
        n=64, streams=2, last_seq=63, rounds=48, samples=16, n_cells=64
    )
    ref_final, ref_curves = mixed_engine.simulate_mixed(
        cfg, ccfg, topo, sched, spec, seed=0
    )
    for d in DEVICE_COUNTS:
        mesh = benchlib.multichip_mesh(d)
        final, curves = parallel.simulate_mixed_sharded(
            cfg, ccfg, topo, sched, spec, mesh, seed=0
        )
        for name in ("head", "contig", "seen"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ref_final.data, name)),
                np.asarray(getattr(final.data, name)),
                err_msg=f"mixed D={d} {name}",
            )
        np.testing.assert_array_equal(
            np.asarray(ref_final.applied_before),
            np.asarray(final.applied_before),
            err_msg=f"mixed D={d} applied_before",
        )
        _assert_curves_equal(ref_curves, curves, f"mixed D={d}")
        _assert_xshard_matches_model(curves, cfg.gossip, mesh)


def test_traffic_model_arithmetic():
    """Hand-checked exchange volume on the (2, 4) mesh: the inner hop
    gathers each device's block across its 4-wide ici group, the outer
    hop moves the 4x-grown block across the 2 dcn groups."""
    cfg, _, _ = _dense_setup(n=512)
    g = cfg.gossip
    mesh = benchlib.multichip_mesh(8)
    tm = parallel.traffic_model(g, mesh)
    per_entry = 12 + (4 if g.track_writer_ids else 0)
    block = (512 // 8) * g.queue * per_entry
    assert tm["xshard_bytes_ici"] == 8 * 3 * block
    assert tm["xshard_bytes_dcn"] == 8 * 1 * (block * 4)
    one = parallel.traffic_model(g, benchlib.multichip_mesh(1))
    assert one["xshard_bytes_ici"] == one["xshard_bytes_dcn"] == 0.0


def test_per_device_state_scales_o_n_over_d():
    cfg, _, sched = _dense_setup(n=512)
    mib = {}
    for d in (1, 8):
        state = engine.init_cluster(cfg, len(sched.sample_writer))
        state = parallel.shard_cluster_state(
            state, benchlib.multichip_mesh(d)
        )
        per_dev = parallel.per_device_state_bytes(state)
        assert len(per_dev) == d
        mib[d] = max(per_dev.values())
    assert mib[8] <= mib[1] * benchlib.MULTICHIP_STATE_FRACTION, (
        f"D=8 shard holds {mib[8] / mib[1]:.3f} of the D=1 state — "
        f"per-device memory must scale O(N/D)"
    )


@pytest.mark.slow  # tier-1 budget; the multichip CI job runs this file unfiltered
def test_donated_rounds_release_sharded_buffers():
    """The PR 5 donation contract survives sharding: a donated round on
    a node-sharded ClusterState releases the (sharded) input buffers and
    matches the plain entry bit-for-bit."""
    cfg, topo, sched = _dense_setup(rounds=6)
    mesh = benchlib.multichip_mesh(8)
    topo_r = parallel.replicate(topo, mesh)
    n_regions = int(np.asarray(topo.region).max()) + 1
    part = jnp.zeros((n_regions, n_regions), bool)
    kill = jnp.zeros((1,), bool)
    writes = jnp.asarray(sched.writes[0], jnp.uint32)
    s_w = jnp.asarray(sched.sample_writer)
    s_v = jnp.asarray(sched.sample_ver)
    s_r = jnp.asarray(sched.sample_round)
    key = jax.random.PRNGKey(7)

    state0 = engine.init_cluster(cfg, len(sched.sample_writer))
    state0 = parallel.shard_cluster_state(state0, mesh)
    # One plain round first: donation requires a device-execution output
    # (a fresh init may share constant buffers between zero leaves).
    state1, _ = engine.cluster_round(
        state0, topo_r, writes, part, kill, kill, s_w, s_v, s_r, key,
        cfg, False,
    )
    plain, _ = engine.cluster_round(
        state1, topo_r, writes, part, kill, kill, s_w, s_v, s_r, key,
        cfg, False,
    )
    donated, _ = engine.cluster_round_donated(
        state1, topo_r, writes, part, kill, kill, s_w, s_v, s_r, key,
        cfg, False,
    )
    for name in ("head", "contig", "seen"):
        np.testing.assert_array_equal(
            np.asarray(getattr(plain.data, name)),
            np.asarray(getattr(donated.data, name)),
            err_msg=name,
        )
    # The donated input's shards are gone; the output stays sharded.
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(state1.data.contig)
    assert len(parallel.per_device_state_bytes(donated)) == 8


@pytest.mark.slow  # tier-1 budget; the multichip CI job runs this file unfiltered
def test_donated_scan_under_sharding_bit_identical():
    """The chunked engine run scans through the _donated twins; under
    the shard_map broadcast driver it must still match the unsharded
    run (and leave the caller's sharded init readable — the
    copy-once-donate-always ownership rule)."""
    cfg, topo, sched = _dense_setup()
    ref_final, ref_curves = simulate(cfg, topo, sched, seed=5, max_chunk=8)
    mesh = benchlib.multichip_mesh(8)
    state0 = engine.init_cluster(cfg, len(sched.sample_writer))
    state0 = parallel.shard_cluster_state(state0, mesh)
    final, curves = simulate(
        cfg, parallel.replicate(topo, mesh), sched, seed=5,
        state=state0, max_chunk=8,
        bcast_fn=parallel.make_sharded_broadcast(mesh),
    )
    for name in ("head", "contig", "seen"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ref_final.data, name)),
            np.asarray(getattr(final.data, name)),
            err_msg=name,
        )
    _assert_curves_equal(ref_curves, curves, "donated scan")
    np.asarray(state0.data.contig)  # caller state survives donation
