"""CRDT SQLite store: local change capture + remote merge semantics.

Mirrors the reference's CRDT behavior spec (doc/crdts.md:11-28) and the
write/merge paths (public/mod.rs:33-191, agent.rs:1809-2231), exercised on
real SQLite files like corro-tests does.
"""

import itertools

import pytest

from corrosion_tpu.agent.store import SchemaError, Store
from corrosion_tpu.core.values import Change, Statement, pack_columns

SCHEMA = """
CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT NOT NULL DEFAULT '');
CREATE TABLE tests2 (id INTEGER NOT NULL PRIMARY KEY, text TEXT NOT NULL DEFAULT '');
CREATE TABLE testsblob (id BLOB NOT NULL PRIMARY KEY, text TEXT NOT NULL DEFAULT '');
"""


def mk_store(tmp_path, n=0):
    site = bytes([n] * 16)
    s = Store(str(tmp_path / f"node{n}.db"), site)
    s.apply_schema(SCHEMA)
    return s


def ins(s, i, text, table="tests"):
    return s.execute_transaction(
        [Statement(f"INSERT INTO {table} (id, text) VALUES (?, ?)"
                   " ON CONFLICT (id) DO UPDATE SET text = excluded.text",
                   params=[i, text])]
    )


def test_local_write_records_changes(tmp_path):
    s = mk_store(tmp_path)
    results, dbv, last_seq, changes = ins(s, 1, "hello")
    assert dbv == 1
    assert results[0].rows_affected == 1
    assert [c.cid for c in changes] == ["text"]
    ch = changes[0]
    assert ch.table == "tests" and ch.val == "hello"
    assert ch.col_version == 1 and ch.cl == 1 and ch.seq == 0
    assert ch.site_id == s.site_id
    # Update bumps col_version, allocates a new db_version.
    _, dbv2, _, changes2 = ins(s, 1, "world")
    assert dbv2 == 2 and changes2[0].col_version == 2
    # No-op write allocates nothing (has_changes check).
    _, dbv3, _, ch3 = s.execute_transaction(
        [Statement("UPDATE tests SET text='world' WHERE id=1")]
    )
    assert dbv3 == 0 and ch3 == []
    assert s.db_version() == 2


def test_delete_emits_sentinel_and_even_cl(tmp_path):
    s = mk_store(tmp_path)
    ins(s, 5, "x")
    _, dbv, _, changes = s.execute_transaction(
        [Statement("DELETE FROM tests WHERE id = 5")]
    )
    assert len(changes) == 1
    ch = changes[0]
    assert ch.cid == Change.DELETE_CID and ch.cl == 2
    # Reinsert: cl goes odd again (resurrection epoch).
    _, _, _, changes2 = ins(s, 5, "back")
    assert changes2[0].cl == 3


def test_two_stores_converge_bidirectionally(tmp_path):
    a, b = mk_store(tmp_path, 0), mk_store(tmp_path, 1)
    _, _, _, ca = ins(a, 1, "from-a")
    _, _, _, cb = ins(b, 2, "from-b")
    assert b.apply_changes(ca) == 1
    assert a.apply_changes(cb) == 1
    qa = a.query(Statement("SELECT id, text FROM tests ORDER BY id"))[1]
    qb = b.query(Statement("SELECT id, text FROM tests ORDER BY id"))[1]
    assert qa == qb == [(1, "from-a"), (2, "from-b")]


def test_lww_conflict_resolution_is_order_independent(tmp_path):
    # Concurrent writes to the same cell: same col_version, so the bigger
    # value wins (doc/crdts.md:15-16), in any application order.
    a, b = mk_store(tmp_path, 0), mk_store(tmp_path, 1)
    _, _, _, ca = ins(a, 1, "aaa")
    _, _, _, cb = ins(b, 1, "zzz")
    b.apply_changes(ca)
    a.apply_changes(cb)
    va = a.query(Statement("SELECT text FROM tests WHERE id=1"))[1][0][0]
    vb = b.query(Statement("SELECT text FROM tests WHERE id=1"))[1][0][0]
    assert va == vb == "zzz"


def test_higher_col_version_beats_bigger_value(tmp_path):
    a, b = mk_store(tmp_path, 0), mk_store(tmp_path, 1)
    ins(a, 1, "zzz")           # a: col_version 1, value zzz
    ins(b, 1, "aaa")
    _, _, _, cb2 = ins(b, 1, "mmm")  # b: col_version 2
    a.apply_changes(cb2)
    va = a.query(Statement("SELECT text FROM tests WHERE id=1"))[1][0][0]
    assert va == "mmm", "col_version dominates value ordering"


def test_delete_beats_concurrent_update(tmp_path):
    # Causal length precedence: a delete (cl 2) wins over concurrent cl-1
    # updates regardless of col_version (doc/crdts.md:19-24).
    a, b = mk_store(tmp_path, 0), mk_store(tmp_path, 1)
    _, _, _, c0 = ins(a, 1, "v1")
    b.apply_changes(c0)
    _, _, _, c_del = a.execute_transaction(
        [Statement("DELETE FROM tests WHERE id=1")]
    )
    for _ in range(5):
        b.execute_transaction(
            [Statement("UPDATE tests SET text = text || 'x' WHERE id=1")]
        )
    assert b.apply_changes(c_del) == 1
    assert b.query(Statement("SELECT count(*) FROM tests"))[1][0][0] == 0


def test_resurrection_beats_delete(tmp_path):
    a, b = mk_store(tmp_path, 0), mk_store(tmp_path, 1)
    _, _, _, c0 = ins(a, 1, "v1")
    b.apply_changes(c0)
    a.execute_transaction([Statement("DELETE FROM tests WHERE id=1")])
    _, _, _, c_res = ins(a, 1, "reborn")  # cl 3
    # b sees only the resurrection (delete lost in transit): applies cleanly.
    assert b.apply_changes(c_res) >= 1
    assert b.query(Statement("SELECT text FROM tests WHERE id=1"))[1] == [("reborn",)]


def test_convergence_under_any_interleaving(tmp_path):
    # Three writers, overlapping keys; apply each other's changesets in
    # every permutation — all replicas end identical (CRDT law check on the
    # full store, matching tests/test_ops_crdt.py's kernel laws).
    # Coverage shape: EXHAUSTIVE over the first 4 changesets (24 orders —
    # the pairwise/triple-wise commutativity the law lives on) plus 48
    # seeded random orders of all 6; the former all-720-permutations
    # sweep re-proved the same pairwise swaps hundreds of times over and
    # cost ~40 s of the tier-1 budget in fresh-store setup alone.
    import random

    stores = [mk_store(tmp_path, i) for i in range(3)]
    sets = []
    for i, s in enumerate(stores):
        for k in (1, 2):
            _, _, _, ch = ins(s, k, f"w{i}k{k}")
            sets.append(ch)
    rng = random.Random(0)
    perms = [
        p + (4, 5) for p in itertools.permutations(range(4))
    ]
    for _ in range(48):
        p = list(range(len(sets)))
        rng.shuffle(p)
        perms.append(tuple(p))
    finals = []
    for n, perm in enumerate(perms):
        s = Store(str(tmp_path / f"merge{n}.db"), bytes([9] * 16))
        s.apply_schema(SCHEMA)
        for idx in perm:
            s.apply_changes(sets[idx])
        finals.append(s.query(Statement("SELECT * FROM tests ORDER BY id"))[1])
        s.close()
    assert all(f == finals[0] for f in finals)


def test_blob_pk_and_multi_table(tmp_path):
    a, b = mk_store(tmp_path, 0), mk_store(tmp_path, 1)
    _, _, _, ch = a.execute_transaction(
        [Statement("INSERT INTO testsblob (id, text) VALUES (?, ?)",
                   params=[b"\x01\x02", "blobby"])]
    )
    assert ch[0].pk == pack_columns([b"\x01\x02"])
    b.apply_changes(ch)
    assert b.query(Statement("SELECT id, text FROM testsblob"))[1] == [
        (b"\x01\x02", "blobby")
    ]


def test_schema_migration_add_column_and_table(tmp_path):
    s = mk_store(tmp_path)
    changed = s.apply_schema(SCHEMA + """
CREATE TABLE newt (id INTEGER NOT NULL PRIMARY KEY, a TEXT);
""")
    assert changed == ["newt"]
    s2 = s.apply_schema(SCHEMA.replace(
        "CREATE TABLE tests2 (id INTEGER NOT NULL PRIMARY KEY, text TEXT NOT NULL DEFAULT '');",
        "CREATE TABLE tests2 (id INTEGER NOT NULL PRIMARY KEY, text TEXT NOT NULL DEFAULT '', extra INTEGER DEFAULT 0);",
    ) + "CREATE TABLE newt (id INTEGER NOT NULL PRIMARY KEY, a TEXT);")
    assert s2 == ["tests2"]
    _, _, _, ch = s.execute_transaction(
        [Statement("INSERT INTO tests2 (id, text, extra) VALUES (1, 'x', 7)")]
    )
    assert {c.cid for c in ch} == {"text", "extra"}


def test_destructive_schema_rejected(tmp_path):
    s = mk_store(tmp_path)
    with pytest.raises(SchemaError):
        s.apply_schema("CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT NOT NULL DEFAULT '');\nCREATE TABLE tests2 (id INTEGER NOT NULL PRIMARY KEY, text TEXT NOT NULL DEFAULT '');")  # drops testsblob
    with pytest.raises(SchemaError):
        s.apply_schema(SCHEMA.replace(
            "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT NOT NULL DEFAULT '');",
            "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY);",
        ))  # drops a column
    with pytest.raises(SchemaError):
        s.apply_schema("CREATE TABLE nopk (x INTEGER);" + SCHEMA)


def test_changes_for_serves_by_site_and_dbv(tmp_path):
    a, b = mk_store(tmp_path, 0), mk_store(tmp_path, 1)
    _, dbv, _, ch = ins(a, 1, "x")
    b.apply_changes(ch)
    served = b.changes_for(a.site_id, dbv)
    assert [c.to_tuple() for c in served] == [c.to_tuple() for c in ch]
    # Third store syncs a's write from b.
    c3 = mk_store(tmp_path, 2)
    c3.apply_changes(served)
    assert c3.query(Statement("SELECT text FROM tests WHERE id=1"))[1] == [("x",)]
