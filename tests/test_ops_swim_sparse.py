"""Sparse (exception-table) SWIM kernel tests.

Re-runs the dense kernel's churn scenarios against the O(N·K) kernel and
adds sparse-specific coverage: bounded-table eviction priority, the merge
invariants, and the 100k memory budget the kernel exists for.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.ops import swim, swim_sparse


def cfg_for(n, **kw):
    kw.setdefault("view_capacity", 16)
    return swim.SwimConfig(n_nodes=n, **kw)


def run_rounds(state, cfg, start, count, seed=0):
    key = jax.random.PRNGKey(seed)
    for r in range(start, start + count):
        key, sub = jax.random.split(key)
        state = swim_sparse.swim_round(state, sub, jnp.int32(r), cfg)
    return state


def test_impl_dispatch():
    assert swim.impl(swim.SwimConfig(n_nodes=4)) is swim
    assert swim.impl(cfg_for(4)) is swim_sparse


def test_stable_cluster_stays_accurate_and_empty():
    cfg = cfg_for(16)
    state = swim_sparse.init_state(cfg)
    state = run_rounds(state, cfg, 0, 10)
    assert int(swim_sparse.mismatches(state)) == 0
    assert int(jnp.max(state.incarnation)) == 0
    # A quiet cluster gossips only alive@inc0 == baseline: no exceptions.
    assert int(jnp.sum(state.exc_tgt >= 0)) == 0


def test_dead_node_detected_and_spread():
    cfg = cfg_for(24, suspect_rounds=2, gossip_fanout=3)
    state = swim_sparse.init_state(cfg)
    state = run_rounds(state, cfg, 0, 4)
    kill = jnp.zeros(24, bool).at[5].set(True)
    state = swim_sparse.apply_churn(state, kill, jnp.zeros(24, bool))
    state = run_rounds(state, cfg, 4, 30, seed=1)
    sev = swim.packed_sev(swim_sparse.beliefs_about(state, 5))
    live = np.asarray(state.alive)
    believed_down = np.asarray(sev == swim.SEV_DOWN)
    assert believed_down[live].all(), "all live nodes must see node 5 as down"
    assert int(swim_sparse.mismatches(state)) == 0


def test_revived_node_rejoins_with_bumped_incarnation():
    cfg = cfg_for(16, suspect_rounds=2)
    state = swim_sparse.init_state(cfg)
    kill = jnp.zeros(16, bool).at[3].set(True)
    state = swim_sparse.apply_churn(state, kill, jnp.zeros(16, bool))
    state = run_rounds(state, cfg, 0, 25, seed=2)
    assert int(
        swim.packed_sev(swim_sparse.beliefs_about(state, 3))[0]
    ) == swim.SEV_DOWN
    revive = jnp.zeros(16, bool).at[3].set(True)
    state = swim_sparse.apply_churn(
        state, jnp.zeros(16, bool), revive, jax.random.PRNGKey(9)
    )
    assert int(state.incarnation[3]) == 1
    state = run_rounds(state, cfg, 25, 30, seed=3)
    sev = swim.packed_sev(swim_sparse.beliefs_about(state, 3))
    live = np.asarray(state.alive)
    assert np.asarray(sev < swim.SEV_DOWN)[live].all(), "rejoin must spread"
    assert int(swim_sparse.mismatches(state)) == 0


def test_false_suspicion_refuted_under_loss():
    cfg = cfg_for(16, suspect_rounds=4, loss_prob=0.3)
    state = swim_sparse.init_state(cfg)
    state = run_rounds(state, cfg, 0, 20, seed=4)
    calm = cfg_for(16, suspect_rounds=4, loss_prob=0.0)
    state = run_rounds(state, calm, 20, 20, seed=5)
    assert int(swim_sparse.mismatches(state)) == 0
    assert bool(state.alive.all())


def test_matches_dense_on_churn_storm():
    """Same scenario on both kernels: both must converge to the same truth.

    Bit-identical views are not required (the sparse kernel caps per-round
    view intake), but post-storm both must reach zero mismatches and agree
    on which nodes are down.
    """
    n = 32
    dense_cfg = swim.SwimConfig(n_nodes=n, suspect_rounds=2)
    sparse_cfg = cfg_for(n, suspect_rounds=2)
    ds = swim.init_state(dense_cfg)
    ss = swim_sparse.init_state(sparse_cfg)
    key = jax.random.PRNGKey(7)
    rng = np.random.default_rng(7)
    r = 0
    for burst in range(3):
        kill_np = rng.random(n) < 0.15
        kill = jnp.asarray(kill_np)
        none = jnp.zeros(n, bool)
        key, kc = jax.random.split(key)
        ds = swim.apply_churn(ds, kill, none, kc)
        ss = swim_sparse.apply_churn(ss, kill, none, kc)
        for _ in range(25):
            key, sub = jax.random.split(key)
            ds = swim.swim_round(ds, sub, jnp.int32(r), dense_cfg)
            ss = swim_sparse.swim_round(ss, sub, jnp.int32(r), sparse_cfg)
            r += 1
    assert int(swim.mismatches(ds)) == 0
    assert int(swim_sparse.mismatches(ss)) == 0
    assert np.array_equal(np.asarray(ds.alive), np.asarray(ss.alive))


def test_merge_one_invariants():
    # Unique target per row; max-merge on hit; eviction keeps severe entries.
    et = jnp.array([[0, 2, -1]], jnp.int32)
    ep = jnp.array(
        [[swim.pack(jnp.uint32(1), swim.SEV_ALIVE),
          swim.pack(jnp.uint32(0), swim.SEV_DOWN), 0]], jnp.uint32
    )
    # Hit: raise belief about 0.
    t = jnp.array([0], jnp.int32)
    p = jnp.array([int(swim.pack(jnp.uint32(3), swim.SEV_ALIVE))], jnp.uint32)
    et2, ep2, raised = swim_sparse._merge_one(
        et, ep, t, p, jnp.array([True])
    )
    assert bool(raised[0])
    assert int(swim_sparse._lookup(et2, ep2, t)[0]) == int(p[0])
    assert int(jnp.sum(et2 == 0)) == 1  # no duplicate slot
    # Insert into the free slot.
    t3 = jnp.array([5], jnp.int32)
    p3 = jnp.array([int(swim.pack(jnp.uint32(0), swim.SEV_SUSPECT))], jnp.uint32)
    et3, ep3, raised3 = swim_sparse._merge_one(
        et2, ep2, t3, p3, jnp.array([True])
    )
    assert bool(raised3[0]) and int(swim_sparse._lookup(et3, ep3, t3)[0]) == int(p3[0])
    # Table now full: an alive entry must be evicted before suspect/down.
    t4 = jnp.array([7], jnp.int32)
    p4 = jnp.array([int(swim.pack(jnp.uint32(0), swim.SEV_DOWN))], jnp.uint32)
    et4, ep4, raised4 = swim_sparse._merge_one(
        et3, ep3, t4, p4, jnp.array([True])
    )
    assert bool(raised4[0])
    kept = set(np.asarray(et4[0]).tolist())
    assert 7 in kept and 2 in kept and 5 in kept  # down/suspect survive
    assert 0 not in kept  # the alive@inc3 exception was the evictee
    # Weakest incoming vs full severe table: dropped, not evicted.
    t5 = jnp.array([9], jnp.int32)
    p5 = jnp.array([int(swim.pack(jnp.uint32(0), swim.SEV_ALIVE))], jnp.uint32)
    _, _, raised5 = swim_sparse._merge_one(
        et4, ep4, t5, p5, jnp.array([True])
    )
    assert not bool(raised5[0])


def test_memory_budget_100k():
    # The point of the kernel: the membership plane at 100k nodes must fit
    # in a fraction of one chip's HBM. ~0.5 KiB/node at K=64.
    cfg = swim.SwimConfig(n_nodes=100_000, view_capacity=64)
    per_node = swim_sparse.state_bytes_per_node(cfg)
    assert per_node <= 1024
    assert per_node * cfg.n_nodes <= 110 * 2**20  # ≤ ~105 MiB total


def test_engine_integration_sparse():
    """The full cluster engine (all three planes) over the sparse kernel:
    the dense churn_32 scenario must converge identically in outcome."""
    import dataclasses

    from corrosion_tpu.models import baselines
    from corrosion_tpu.sim import simulate

    cfg, topo, sched = baselines.churn_32(rounds=200, samples=32)
    cfg = dataclasses.replace(
        cfg, swim=dataclasses.replace(cfg.swim, view_capacity=16)
    )
    final, curves = simulate(cfg, topo, sched, seed=1)
    m = curves["mismatches"]
    assert m.max() > 0, "churn must actually cause belief divergence"
    assert m[-1] == 0, "membership converges after the storm"
    alive = np.asarray(final.swim.alive)
    contig = np.asarray(final.data.contig)[alive]
    heads = np.asarray(final.data.head)
    assert (contig == heads[None, :]).all()
