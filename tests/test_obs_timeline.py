"""Causal write tracing + the obs timeline/journey correlators (PR 11).

Units for the sampling knob (deterministic trace-id-keyed decisions,
hop consistency), structured span export (nesting, service field,
Span.start/finish for non-LIFO batches), the Histogram edge cases the
latency surfaces lean on, the flight-recorder rotation satellite, the
timeline join + latency budget + reconciliation invariant, and the
kernel write-journey reconstructor — plus live end-to-end pins: a
DEFAULT agent's write path allocates zero spans (the tracing-off-costs-
nothing acceptance bar) and a traced 2-agent storm reconstructs every
write with the gossip hop attributed.
"""

import asyncio
import json
import math
import os
import threading

import pytest

from corrosion_tpu.utils import tracing as T
from corrosion_tpu.utils.metrics import (
    Histogram,
    MetricsRegistry,
    process_open_fds,
    process_rss_bytes,
    process_stats,
    register_process_gauges,
)


def run(coro):
    return asyncio.run(coro)


# -- sampling ----------------------------------------------------------------


def test_trace_sampled_deterministic_and_bounded():
    tid = "ab" * 16
    assert T.trace_sampled(tid, 1.0)
    assert not T.trace_sampled(tid, 0.0)
    # Same id, same rate -> same decision, every time (hop consistency).
    for rate in (0.1, 0.5, 0.9):
        first = T.trace_sampled(tid, rate)
        assert all(
            T.trace_sampled(tid, rate) == first for _ in range(10)
        )
    # Rate roughly honored over many ids.
    kept = sum(
        T.trace_sampled(os.urandom(16).hex(), 0.5) for _ in range(2000)
    )
    assert 800 < kept < 1200


def test_maybe_span_unsampled_returns_none_and_hops_agree():
    tr = T.Tracer(sample=0.5)
    # Walk until we find one kept and one dropped id.
    kept = dropped = None
    while kept is None or dropped is None:
        tid = os.urandom(16).hex()
        if T.trace_sampled(tid, 0.5):
            kept = kept or tid
        else:
            dropped = dropped or tid
    tp_kept = f"00-{kept}-{os.urandom(8).hex()}-01"
    tp_dropped = f"00-{dropped}-{os.urandom(8).hex()}-01"
    s = tr.maybe_span("x", traceparent=tp_kept)
    assert s is not None and s.trace_id == kept
    assert tr.maybe_span("x", traceparent=tp_dropped) is None
    # A second "hop" tracer at the same rate agrees on both.
    tr2 = T.Tracer(sample=0.5)
    assert tr2.maybe_span("hop", traceparent=tp_kept) is not None
    assert tr2.maybe_span("hop", traceparent=tp_dropped) is None


def test_maybe_span_sampled_root_decision_matches_carried_id():
    # The decision must be made on the id the span CARRIES: at rate 0.5
    # every returned root span's id must itself pass trace_sampled.
    tr = T.Tracer(sample=0.5)
    got = 0
    for _ in range(200):
        s = tr.maybe_span("root")
        if s is not None:
            got += 1
            assert T.trace_sampled(s.trace_id, 0.5)
    assert 0 < got < 200


# -- structured export + span nesting ---------------------------------------


def test_nested_spans_export_structured_jsonl(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    tr = T.Tracer(service="svc-a", export_path=path)
    with tr.span("outer") as outer:
        with tr.span("inner", depth=2) as inner:
            assert T.current_span() is inner
        assert T.current_span() is outer
    assert T.current_span() is None
    tr.close()
    rows = [json.loads(line) for line in open(path)]
    by_name = {r["name"]: r for r in rows}
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    assert by_name["inner"]["trace_id"] == by_name["outer"]["trace_id"]
    assert all(r["service"] == "svc-a" for r in rows)
    assert by_name["inner"]["attrs"] == {"depth": 2}
    assert by_name["outer"]["duration_us"] >= by_name["inner"]["duration_us"]


def test_span_start_finish_non_lifo_overlap():
    # The batched-ingest shape: spans opened together, closed together —
    # contextvars would reject this; start()/finish() must not touch the
    # ambient span.
    tr = T.Tracer()
    a = tr.span("a").start()
    b = tr.span("b").start()
    assert T.current_span() is None
    a.finish()
    b.finish()
    names = [s["name"] for s in tr.recent()]
    assert names == ["a", "b"]
    assert all(s["duration_us"] >= 0 for s in tr.recent())


# -- Histogram edge cases (satellite) ---------------------------------------


def test_histogram_empty_quantile_is_nan():
    h = Histogram("h")
    assert math.isnan(h.quantile(0.5))
    assert h.count() == 0


def test_histogram_single_bucket_quantile_interpolates_from_zero():
    h = Histogram("h", buckets=(1.0,))
    h.observe(0.5)
    h.observe(0.5)
    # All mass in the only bucket: interpolation spans [0, 1].
    assert 0.0 <= h.quantile(0.5) <= 1.0
    # Past the last edge -> +inf.
    h2 = Histogram("h2", buckets=(1.0,))
    h2.observe(5.0)
    assert math.isinf(h2.quantile(0.99))


def test_histogram_concurrent_observe_vs_snapshot():
    h = Histogram("h")
    reg = MetricsRegistry()
    reg._metrics["h"] = h
    stop = threading.Event()
    errors = []

    def hammer():
        i = 0
        try:
            while not stop.is_set():
                h.observe(0.001 * (i % 500), worker="w")
                i += 1
        except Exception as e:  # pragma: no cover
            errors.append(e)

    t = threading.Thread(target=hammer)
    t.start()
    try:
        for _ in range(200):
            snap = reg.snapshot()
            h.render()
            h.quantile(0.9, worker="w")
            for k, v in snap.items():
                assert v >= 0
    finally:
        stop.set()
        t.join()
    assert not errors
    # Totals consistent after the dust settles.
    assert h.count(worker="w") > 0
    assert h._counts[(("worker", "w"),)][-1] <= h.count(worker="w")


def test_process_stats_helpers():
    rss = process_rss_bytes()
    fds = process_open_fds()
    assert rss is None or rss > 1 << 20  # a python process is > 1 MiB
    assert fds is None or fds > 0
    stats = process_stats()
    assert set(stats) == {"rss_bytes", "open_fds"}
    reg = MetricsRegistry()
    rss_g, fds_g, lag_g = register_process_gauges(reg)
    rss_g.set(123.0)
    assert "corro_runtime_rss_bytes 123" in reg.render()
    assert "corro_runtime_open_fds" in reg.render()
    assert "corro_runtime_loop_lag_last_seconds" in reg.render()


# -- flight recorder rotation (satellite) ------------------------------------


def _fake_curves(start, n):
    import numpy as np

    return {
        "msgs": np.arange(start, start + n, dtype=np.uint32),
        "queue_backlog": np.full(n, 7, dtype=np.uint32),
    }


def test_flight_recorder_rotation_and_replay(tmp_path):
    from corrosion_tpu.sim import telemetry as tm

    path = str(tmp_path / "flight.jsonl")
    rec = tm.FlightRecorder(path, engine="dense", mode="w", max_bytes=2048)
    r = 0
    for _ in range(12):
        rec.record_chunk(r, _fake_curves(r, 8))
        r += 8
    rec.close()
    segs = tm.flight_segments(path)
    assert len(segs) > 2, "cap must have forced rotation"
    assert segs[-1] == path
    # Every segment self-describes.
    for seg in segs:
        head = json.loads(open(seg).readline())
        assert head["schema"] == tm.FLIGHT_SCHEMA
        assert head["kind"] == "flight"
    # Segment indices in the headers are monotonically increasing.
    seg_ids = [json.loads(open(s).readline())["segment"] for s in segs]
    assert seg_ids == sorted(seg_ids)
    # Replay stitches the whole chain: every round, in order, correct.
    curves, chunks = tm.replay_flight(path)
    assert list(curves["round"]) == list(range(96))
    assert list(curves["msgs"]) == list(range(96))
    assert len(chunks) == 12


def test_flight_recorder_resume_append_continues_segments(tmp_path):
    from corrosion_tpu.sim import telemetry as tm

    path = str(tmp_path / "flight.jsonl")
    rec = tm.FlightRecorder(path, mode="w", max_bytes=1024)
    for i in range(8):
        rec.record_chunk(i * 4, _fake_curves(i * 4, 4))
    rec.close()
    n_before = len(tm.flight_segments(path))
    assert n_before > 1
    # A resumed run appends and keeps rotating WITHOUT clobbering the
    # existing segments.
    rec2 = tm.FlightRecorder(path, mode="a", max_bytes=1024)
    for i in range(8, 16):
        rec2.record_chunk(i * 4, _fake_curves(i * 4, 4))
    rec2.close()
    assert len(tm.flight_segments(path)) > n_before
    curves, _ = tm.replay_flight(path)
    assert list(curves["round"]) == list(range(64))


def test_flight_recorder_no_cap_never_rotates(tmp_path):
    from corrosion_tpu.sim import telemetry as tm

    path = str(tmp_path / "flight.jsonl")
    rec = tm.FlightRecorder(path, mode="w")
    for i in range(6):
        rec.record_chunk(i * 8, _fake_curves(i * 8, 8))
    rec.close()
    assert tm.flight_segments(path) == [path]
    curves, _ = tm.replay_flight(path)
    assert len(curves["round"]) == 48


# -- timeline correlator (units) --------------------------------------------


def _mk_span(name, trace_id, start_s, dur_s, parent=None, service="a",
             span_id=None):
    return {
        "name": name, "trace_id": trace_id,
        "span_id": span_id or os.urandom(8).hex(),
        "parent_id": parent, "service": service,
        "start_ns": int(start_s * 1e9),
        "duration_us": int(dur_s * 1e6), "attrs": {},
    }


def _mk_write(key, tid, t_send, t_ack):
    return {"key": key, "group": None, "trace_id": tid,
            "t_send_wall": t_send, "t_ack_wall": t_ack}


def test_timeline_local_write_stages_and_reconcile():
    from corrosion_tpu.obs.timeline import build_timeline, timeline_ok

    tid = "11" * 16
    t0 = 1000.0
    spans = [
        _mk_span("api_write", tid, t0 + 0.002, 0.010),
        _mk_span("commit", tid, t0 + 0.003, 0.006),
    ]
    records = {
        "writes": [_mk_write(1, tid, t0, t0 + 0.013)],
        "deliveries": [
            {"kind": "change", "sid": 0, "key": 1, "change_id": 1,
             "t_wall": t0 + 0.008},
        ],
    }
    tl = build_timeline(spans, records)
    assert tl["writes_reconstructed"] == 1
    assert tl["coverage"] == 1.0
    st = tl["writes_detail"][0]["stages_ms"]
    assert st["send_wait"] == pytest.approx(2.0, abs=0.01)
    assert st["ingest"] == pytest.approx(1.0, abs=0.01)
    assert st["commit"] == pytest.approx(6.0, abs=0.01)
    assert st["gossip"] == 0.0  # no hop span: local fan-out
    # Stage sum telescopes to the measured wall exactly.
    assert sum(st.values()) == pytest.approx(
        tl["writes_detail"][0]["wall_ms"], abs=0.01
    )
    assert tl["reconcile"]["ok"] == 1
    ok, problems = timeline_ok(tl)
    assert ok, problems


def test_timeline_remote_hop_attributed_to_serving_hop_only():
    from corrosion_tpu.obs.timeline import build_timeline

    tid = "22" * 16
    t0 = 2000.0
    commit = _mk_span("commit", tid, t0 + 0.002, 0.004)
    # Serving hop (on the subs agent) contains the delivery; a second,
    # delivery-irrelevant relay hop ends much later and must NOT be
    # charged to the gossip stage.
    serving = _mk_span("ingest_apply", tid, t0 + 0.050, 0.010,
                       parent=commit["span_id"], service="b")
    # Second hop chains on the first (multi-hop rebroadcast re-stamp) —
    # deepens the chain but must not be charged to the gossip stage.
    relay = _mk_span("ingest_apply", tid, t0 + 0.150, 0.020,
                     parent=serving["span_id"], service="c")
    spans = [
        _mk_span("api_write", tid, t0 + 0.001, 0.008),
        commit, serving, relay,
    ]
    records = {
        "writes": [_mk_write(7, tid, t0, t0 + 0.010)],
        "deliveries": [
            {"kind": "change", "sid": 3, "key": 7, "change_id": 9,
             "t_wall": t0 + 0.055},
        ],
    }
    tl = build_timeline(spans, records)
    st = tl["writes_detail"][0]["stages_ms"]
    # gossip = commit end (t0+6ms) -> serving hop start (t0+50ms).
    assert st["gossip"] == pytest.approx(44.0, abs=0.01)
    assert st["fanout"] == pytest.approx(5.0, abs=0.01)
    assert tl["hops"]["writes_with_remote_hop"] == 1
    assert tl["hops"]["max_chain_depth"] == 2
    assert tl["reconcile"]["ok"] == 1


def test_timeline_missing_span_lowers_coverage_and_fails_verdict():
    from corrosion_tpu.obs.timeline import build_timeline, timeline_ok

    t0 = 3000.0
    tids = ["a" * 31 + str(i) for i in range(4)]
    spans, writes, dels = [], [], []
    for i, tid in enumerate(tids):
        writes.append(_mk_write(i, tid, t0, t0 + 0.01))
        dels.append({"kind": "change", "sid": 0, "key": i,
                     "change_id": i + 1, "t_wall": t0 + 0.008})
        if i != 2:  # write 2's spans went missing
            spans.append(_mk_span("api_write", tid, t0 + 0.001, 0.008))
            spans.append(_mk_span("commit", tid, t0 + 0.002, 0.005))
    tl = build_timeline(spans, {"writes": writes, "deliveries": dels})
    assert tl["writes_reconstructed"] == 3
    assert tl["coverage"] == 0.75
    ok, problems = timeline_ok(tl, min_coverage=0.99)
    assert not ok and "coverage" in problems[0]


def test_timeline_clock_skew_fails_reconciliation():
    from corrosion_tpu.obs.timeline import build_timeline, timeline_ok

    tid = "33" * 16
    t0 = 4000.0
    spans = [
        # Server clock skewed 5 s into the future: ordering invariant
        # (commit end <= ack) must flag it.
        _mk_span("api_write", tid, t0 + 5.0, 0.004),
        _mk_span("commit", tid, t0 + 5.001, 0.002),
    ]
    records = {
        "writes": [_mk_write(1, tid, t0, t0 + 0.01)],
        "deliveries": [
            {"kind": "change", "sid": 0, "key": 1, "change_id": 1,
             "t_wall": t0 + 0.008},
        ],
    }
    tl = build_timeline(spans, records)
    rec = tl["reconcile"]
    assert rec["ok"] == 0
    assert rec["ordering_violations"] == 1
    ok, problems = timeline_ok(tl)
    assert not ok and any("reconciliation" in p for p in problems)


def test_timeline_independent_wall_catches_epoch_clock_step():
    from corrosion_tpu.obs.timeline import build_timeline, timeline_ok

    tid = "44" * 16
    t0 = 6000.0
    spans = [
        _mk_span("api_write", tid, t0 + 0.002, 0.010),
        _mk_span("commit", tid, t0 + 0.003, 0.006),
    ]

    def records(mono_wall_s):
        w = _mk_write(1, tid, t0, t0 + 0.013)
        # Monotonic stamps: send at 100.0, delivery defines the wall.
        w["t_send_mono"] = 100.0
        w["t_ack_mono"] = 100.0 + mono_wall_s
        return {
            "writes": [w],
            "deliveries": [
                {"kind": "change", "sid": 0, "key": 1, "change_id": 1,
                 "t_wall": t0 + 0.008, "t_mono": 100.0 + mono_wall_s},
            ],
        }

    # Consistent clocks: mono wall == epoch window (13 ms) -> exact.
    tl = build_timeline(spans, records(0.013))
    assert tl["reconcile"]["independent_walls"] == 1
    assert tl["reconcile"]["ok"] == 1
    assert timeline_ok(tl)[0]
    # Epoch clock stepped mid-write: stage sum still telescopes to
    # 13 ms but the monotonic wall says 500 ms — the cross-clock check
    # must fail where the old epoch-vs-epoch tautology could not.
    tl2 = build_timeline(spans, records(0.5))
    assert tl2["reconcile"]["independent_walls"] == 1
    assert tl2["reconcile"]["ok"] == 0
    assert tl2["reconcile"]["max_abs_err_ms"] == pytest.approx(
        487.0, abs=1.0
    )
    assert not timeline_ok(tl2)[0]


def test_timeline_sampling_judges_only_kept_writes():
    from corrosion_tpu.obs.timeline import build_timeline
    from corrosion_tpu.utils.tracing import trace_sampled

    t0 = 5000.0
    rate = 0.5
    writes, spans, dels = [], [], []
    for i in range(40):
        tid = os.urandom(16).hex()
        writes.append(_mk_write(i, tid, t0, t0 + 0.01))
        dels.append({"kind": "change", "sid": 0, "key": i,
                     "change_id": i + 1, "t_wall": t0 + 0.008})
        if trace_sampled(tid, rate):  # only kept traces have spans
            spans.append(_mk_span("api_write", tid, t0 + 0.001, 0.008))
            spans.append(_mk_span("commit", tid, t0 + 0.002, 0.005))
    tl = build_timeline(spans, {"writes": writes, "deliveries": dels},
                        sample=rate)
    assert tl["writes_traced"] == 40
    assert tl["writes_expected"] == len(spans) // 2
    assert tl["coverage"] == 1.0  # every KEPT write reconstructed


# -- kernel write-journey reconstructor --------------------------------------


def _write_synthetic_flight(path, rows):
    """rows: list of dicts keyed by curve name, one per round."""
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "flight", "engine": "dense"}) + "\n")
        for i, row in enumerate(rows):
            f.write(json.dumps({"kind": "round", "round": i, **row}) + "\n")
        f.write(json.dumps(
            {"kind": "chunk", "start": 0, "rounds": len(rows)}
        ) + "\n")


def test_journey_reconstruction_attributes_and_reconciles(tmp_path):
    from corrosion_tpu.obs.journey import reconstruct_write_journeys
    from corrosion_tpu.sim.trace import Trace

    # 2 actors commit one write each in round 0 (t=0ms) and round 2
    # (t=1000ms) at 500 ms rounds.
    actor_a, actor_b = "aa" * 16, "bb" * 16
    tr = Trace(events=[
        (0, actor_a, 1), (5, actor_b, 1),
        (1000, actor_a, 2), (1005, actor_b, 2),
    ])
    # Flight: 6 events visible at round 1 with latency <=1 (bucket 0 ->
    # commits in rounds 0..1 -> all round 0), 4 events at round 3 with
    # latency in (1,2] (bucket 1 -> commit round 1..? (3-2..3-2)= round
    # 1... no writes at 1) — place them at latency <=1 instead: round 3
    # bucket 0 -> commits rounds 2..3 -> round 2.
    rows = [
        {"queue_backlog": 8, "msgs": 4},
        {"vis_lat_b0": 6, "msgs": 2},
        {"queue_backlog": 3, "msgs": 3},
        {"vis_lat_b0": 4, "msgs": 1},
    ]
    path = str(tmp_path / "flight.jsonl")
    _write_synthetic_flight(path, rows)
    j = reconstruct_write_journeys(path, tr, round_ms=500.0)
    assert j["schema"] == "corro-write-journey/1"
    assert j["trace_writes"] == 4
    # Total attribution reconciles exactly: 10 events, all attributable.
    assert j["totals"]["vis_events"] == 10.0
    assert j["totals"]["attributed"] == pytest.approx(10.0)
    assert j["totals"]["attribution_fraction"] == pytest.approx(1.0)
    by = {(w["actor"], w["version"]): w for w in j["writes"]}
    w_a1 = by[(actor_a[:8], 1)]
    assert w_a1["commit_round"] == 0
    # Round 0's 2 writes split round 1's 6 events evenly.
    assert w_a1["expected_deliveries"] == pytest.approx(3.0)
    assert w_a1["delivery_rounds"] == {1: 3.0}
    assert w_a1["latency_rounds_mean"] == pytest.approx(1.0)
    # Little's-law dwell at commit round 0: backlog 8 / msgs 4.
    assert w_a1["queue_dwell_rounds"] == pytest.approx(2.0)
    w_b2 = by[(actor_b[:8], 2)]
    assert w_b2["commit_round"] == 2
    assert w_b2["expected_deliveries"] == pytest.approx(2.0)
    assert w_b2["queue_dwell_rounds"] == pytest.approx(1.0)


def test_journey_unattributable_mass_reported(tmp_path):
    from corrosion_tpu.obs.journey import reconstruct_write_journeys
    from corrosion_tpu.sim.trace import Trace

    tr = Trace(events=[(0, "cc" * 16, 1)])
    # Visibility at round 0 latency bucket 2 (lat in (2,4]) — commits
    # would predate the trace entirely.
    path = str(tmp_path / "flight.jsonl")
    _write_synthetic_flight(path, [{"vis_lat_b2": 5, "msgs": 1}])
    j = reconstruct_write_journeys(path, tr, round_ms=500.0)
    assert j["totals"]["vis_events"] == 5.0
    assert j["totals"]["attributed"] == 0.0
    assert j["totals"]["unattributed"] == 5.0


# -- live agent pins ---------------------------------------------------------


def test_default_agent_write_path_allocates_no_spans(tmp_path):
    """The tracing-off acceptance bar: a DEFAULT-config agent's write +
    ingest path must create zero Span objects and stamp no trace header
    on broadcast frames."""
    from corrosion_tpu.agent.testing import launch_test_agent
    from corrosion_tpu.agent.transport import TRACE_KEY
    from corrosion_tpu.utils.tracing import Tracer

    async def go():
        ta = await launch_test_agent(str(tmp_path))
        calls = []
        orig_span, orig_maybe = Tracer.span, Tracer.maybe_span

        def counting_span(self, name, *a, **kw):
            calls.append(name)
            return orig_span(self, name, *a, **kw)

        def counting_maybe(self, name, *a, **kw):
            calls.append(name)
            return orig_maybe(self, name, *a, **kw)

        Tracer.span, Tracer.maybe_span = counting_span, counting_maybe
        try:
            tid = os.urandom(16).hex()
            await ta.client.execute(
                [["INSERT INTO tests (id, text) VALUES (?, ?)", [1, "x"]]],
                traceparent=f"00-{tid}-{os.urandom(8).hex()}-01",
            )
            # Simulated inbound broadcast WITH a trace header: the
            # ingest path must not open a hop span either.
            await ta.agent._process_changes([(
                {
                    "t": "bcast", "actor": "ee" * 16, "version": 1,
                    "changes": [], "seqs": [0, 0], "last_seq": 0,
                    "ts": 1, TRACE_KEY: f"00-{tid}-{'ab' * 8}-01",
                },
                "broadcast",
            )])
        finally:
            Tracer.span, Tracer.maybe_span = orig_span, orig_maybe
        write_spans = [
            c for c in calls
            if c in ("api_write", "commit", "ingest_apply", "sub_fanout")
        ]
        frames = [pb.frame for pb in ta.agent._pending]
        own_actor = ta.agent.actor_id
        await ta.stop()
        return write_spans, frames, own_actor

    write_spans, frames, own_actor = run(go())
    assert write_spans == [], (
        f"disabled tracing allocated write-path spans: {write_spans}"
    )
    own_frames = [f for f in frames if f["actor"] == own_actor]
    relayed = [f for f in frames if f["actor"] != own_actor]
    assert own_frames, "the write must still have queued broadcast frames"
    # Locally-originated frames carry no trace header when tracing is
    # off; a RELAYED frame keeps the upstream header untouched
    # (pass-through by design — the chain skips untraced relays but
    # stays connected by trace id).
    assert all(TRACE_KEY not in f for f in own_frames)
    assert all(TRACE_KEY in f for f in relayed)


def test_traced_cluster_end_to_end_timeline(tmp_path):
    """2-agent traced storm: every write reconstructs, remote writes get
    the gossip hop, reconciliation is exact (same-process clocks)."""
    from corrosion_tpu.loadgen import scenarios
    from corrosion_tpu.obs.timeline import timeline_from_run, timeline_ok

    async def go():
        run_blk = await scenarios.fanout_storm(
            str(tmp_path / "run"),
            subs=8, writes=10, write_rate=20.0, read_rate=2.0,
            pg_rate=1.0, sub_groups=2, n_agents=2,
            trace_dir=str(tmp_path / "trace"),
        )
        return run_blk, timeline_from_run(run_blk)

    run_blk, tl = run(go())
    assert run_blk["oracle"]["violations"] == 0
    assert tl["coverage"] == 1.0
    assert tl["writes_reconstructed"] == 10
    assert tl["reconcile"]["ok"] == tl["reconcile"]["checked"] == 10
    # Every wall must come from the independent monotonic clock (the
    # scenario records t_send_mono + per-delivery t_mono).
    assert tl["reconcile"]["independent_walls"] == 10
    assert tl["reconcile"]["ordering_violations"] == 0
    # Writes round-robin 2 agents; subs live on agent 0 — the agent-1
    # half must show a remote gossip hop.
    assert tl["hops"]["writes_with_remote_hop"] >= 3
    for stage in ("send_wait", "ingest", "commit", "gossip", "fanout"):
        assert tl["stages_ms"][stage]["count"] == 10
    ok, problems = timeline_ok(tl)
    assert ok, problems


def test_obs_timeline_cli_from_run(tmp_path, capsys):
    """The CLI surface: `obs timeline --from-run report.json` exits 0 on
    a good run and emits the corro-timeline/1 artifact."""
    from corrosion_tpu.loadgen import scenarios

    async def go():
        return await scenarios.fanout_storm(
            str(tmp_path / "run"),
            subs=4, writes=6, write_rate=20.0, read_rate=1.0,
            pg_rate=1.0, sub_groups=2, n_agents=1,
            trace_dir=str(tmp_path / "trace"),
        )

    run_blk = run(go())
    report_path = str(tmp_path / "report.json")
    with open(report_path, "w") as f:
        json.dump({"run": run_blk}, f)
    out_path = str(tmp_path / "timeline.json")
    from corrosion_tpu.cli import main as cli_main

    rc = cli_main([
        "obs", "timeline", "--from-run", report_path, "--out", out_path,
    ])
    assert rc == 0
    artifact = json.load(open(out_path))
    assert artifact["schema"] == "corro-timeline/1"
    assert artifact["coverage"] == 1.0
    capsys.readouterr()
