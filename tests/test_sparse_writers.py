"""Sparse writer axis (rotating hot slots + deviation tables).

Covers VERDICT r4 missing #1: any-node-writes beyond a dense writer axis.

- steady rotation: cohorts of fresh writers flow through the slots;
  zero-lag demotion; convergence over watermarks AND the CRDT cell plane
  against the order-independent serial-merge ground truth (cells are
  keyed by GLOBAL writer id, so slot reuse across epochs must not
  collide — this is the test that would catch it).
- forced demotion: slot pressure during a partition creates deviation
  entries for the cut-off nodes; cold_sync heals them from the origin
  after the heal; nothing is ever silently dropped.
- differential bookkeeping: delivery + rotation traces replayed against
  the host BookedVersions bookie (core/bookkeeping.py), possession
  compared version by version.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.models.baselines import anywrite_sparse
from corrosion_tpu.ops import crdt, gossip
from corrosion_tpu.ops import sparse_writers as sw
from corrosion_tpu.sim import sparse_engine
from corrosion_tpu.sim.engine import Schedule


def _small(n=96, w_hot=16, rounds=48, cohort=6, partition=False, k_dev=8,
           **kw):
    return anywrite_sparse(
        n=n, w_hot=w_hot, rounds=rounds, n_regions=4, epoch_rounds=8,
        cohort=cohort, burst_writes=2, samples=64, k_dev=k_dev,
        partition=partition, **kw,
    )


def test_steady_rotation_converges_and_cells_match_ground_truth():
    cfg, topo, sched = _small()
    sstate, swim_state, vis_round, curves, info = (
        sparse_engine.simulate_sparse(cfg, topo, sched, seed=0)
    )
    assert info["retired"] > 0, "rotation must actually demote slots"
    assert info["promoted"] > cfg.w_hot, (
        "more distinct writers than slots must have flowed through"
    )
    assert sparse_engine.converged_sparse(sstate)
    # Every sampled write became visible at every node.
    assert int((np.asarray(vis_round) < 0).sum()) == 0
    # Cell plane: every node's registers equal the serial merge of ALL
    # committed versions keyed by global writer id.
    hf = sparse_engine.final_head_full(sstate)
    ref = sw.serial_merge_reference_sparse(hf, cfg.gossip)
    pc = gossip.node_cells(sstate.data, cfg.gossip)
    assert bool(jnp.all(pc.cl == ref.cl[None, :]))
    assert bool(jnp.all(pc.col_version == ref.col_version[None, :]))
    assert bool(jnp.all(pc.value_rank == ref.value_rank[None, :]))


def test_visibility_latencies_reasonable():
    cfg, topo, sched = _small()
    _, _, vis_round, _, _ = sparse_engine.simulate_sparse(
        cfg, topo, sched, seed=1
    )
    lat = np.asarray(vis_round) - sched.sample_round[:, None]
    assert (lat >= 0).all()
    # Propagation should be epidemic-fast, not epoch-bound: the p99 over
    # (sample, node) pairs stays well under two epochs.
    assert np.percentile(lat, 99) <= 2 * cfg.sparse.epoch_rounds


def test_forced_demotion_creates_and_heals_deviation_entries():
    # Region 0 is cut off while early cohorts write and demote under slot
    # pressure (w_hot too small for the active set without forcing).
    cfg, topo, sched = _small(
        n=96, w_hot=8, rounds=96, cohort=4, partition=True, k_dev=16,
    )
    sstate, _, vis_round, curves, info = sparse_engine.simulate_sparse(
        cfg, topo, sched, seed=2
    )
    assert info["max_dev_entries"] > 0, (
        "partition + slot pressure must force lagging demotions"
    )
    assert int(curves["cold_healed"].sum()) > 0, (
        "cold_sync must heal the deviation entries"
    )
    assert sparse_engine.converged_sparse(sstate)
    assert int((np.asarray(vis_round) < 0).sum()) == 0
    hf = sparse_engine.final_head_full(sstate)
    ref = sw.serial_merge_reference_sparse(hf, cfg.gossip)
    pc = gossip.node_cells(sstate.data, cfg.gossip)
    assert bool(jnp.all(pc.cl == ref.cl[None, :]))
    assert bool(jnp.all(pc.col_version == ref.col_version[None, :]))
    assert bool(jnp.all(pc.value_rank == ref.value_rank[None, :]))


def test_rotate_refuses_to_drop_deviation_entries():
    # Direct kernel-level check: forcing more laggards than table capacity
    # reports dev_dropped > 0 (the engine raises on it); demote_report's
    # maxload predicts the overflow so the planner never commits such a
    # plan.
    n, w_hot, k_dev = 8, 4, 2
    g = gossip.GossipConfig(
        n_nodes=n, n_writers=w_hot, track_writer_ids=True, n_cells=0,
    )
    sp = sw.SparseConfig(epoch_rounds=4, k_dev=k_dev, d_max=4, p_max=4)
    st = sw.init_sparse(g, sp)
    # Slots 0..2 held by writers 1..3, every node far behind their heads.
    st = st._replace(
        slot_writer=jnp.asarray([1, 2, 3, -1], jnp.int32),
        data=st.data._replace(
            head=jnp.asarray([5, 5, 5, 0], jnp.uint32),
        ),
    )
    cand = jnp.asarray([0, 1, 2, 0], jnp.int32)
    ok = jnp.asarray([True, True, True, False])
    caught, maxload = sw.demote_report(st, cand, ok)
    assert not bool(caught[0]) and not bool(caught[2])
    # Forcing all three would need 3 entries/node > k_dev=2.
    assert int(maxload[2]) > k_dev >= int(maxload[1])
    _, stats = sw.rotate(
        st, cand, ok,
        jnp.zeros(4, jnp.int32), jnp.zeros(4, jnp.int32),
        jnp.zeros(4, bool), g,
    )
    assert int(stats["dev_dropped"]) > 0


def test_cold_visibility_and_need():
    n, w_hot, k_dev = 6, 2, 4
    g = gossip.GossipConfig(
        n_nodes=n, n_writers=w_hot, track_writer_ids=True, n_cells=0,
    )
    sp = sw.SparseConfig(epoch_rounds=4, k_dev=k_dev)
    st = sw.init_sparse(g, sp)
    hf = np.zeros(n, np.uint32)
    hf[3] = 7
    dev_w = np.full((n, k_dev), -1, np.int32)
    dev_c = np.zeros((n, k_dev), np.uint32)
    dev_w[2, 1] = 3  # node 2 lags on writer 3 at contig 4
    dev_c[2, 1] = 4
    st = st._replace(
        head_full=jnp.asarray(hf),
        dev_writer=jnp.asarray(dev_w),
        dev_contig=jnp.asarray(dev_c),
        dev_any=jnp.array(True),
    )
    vis = np.asarray(sw.cold_visibility(
        st, jnp.asarray([3, 3], jnp.int32), jnp.asarray([4, 5], jnp.uint32)
    ))
    assert vis[0].all()  # v4 held everywhere (node 2 reached 4)
    assert not vis[1][2] and vis[1][[0, 1, 3, 4, 5]].all()
    assert int(sw.cold_need(st)) == 3  # versions 5..7 at node 2

    # cold_sync pulls from the origin and clears the entry.
    region = jnp.zeros(n, jnp.int32)
    alive = jnp.ones(n, bool)
    part = jnp.zeros((1, 1), bool)
    st2, stats = sw.cold_sync(st, region, alive, part, g, sp)
    assert int(stats["cold_healed"]) == 3
    assert not bool(st2.dev_any)
    assert int(sw.cold_need(st2)) == 0


# -- differential: rotation bookkeeping vs the host bookie --------------------
#
# BookedVersions (core/bookkeeping.py, vector-tested against the
# reference's own sync.rs cases) is per (node, actor) and PERSISTENT —
# demotion/promotion must be an identity transformation on possession
# claims. The trace drives the real kernels (rotate / cold_sync /
# broadcast_round) and mirrors every possession event into bookies,
# comparing claims version by version after every step.


def _claims(sstate, writer, n):
    """Possession claim per node for global ``writer`` from sparse state:
    hot slot contig, else deviation entry, else head_full."""
    slot_writer = np.asarray(sstate.slot_writer)
    hot = np.nonzero(slot_writer == writer)[0]
    if len(hot):
        return np.asarray(sstate.data.contig)[:, hot[0]].copy()
    hf = int(np.asarray(sstate.head_full)[writer])
    out = np.full(n, hf, np.uint32)
    dev_w = np.asarray(sstate.dev_writer)
    dev_c = np.asarray(sstate.dev_contig)
    for i in range(n):
        hit = np.nonzero(dev_w[i] == writer)[0]
        if len(hit):
            out[i] = dev_c[i, hit[0]]
    return out


def _assert_claims_match(sstate, bookies, writers, n):
    for w in writers:
        claim = _claims(sstate, w, n)
        for i in range(n):
            bv = bookies[i][w]
            last = bv.last() or 0
            assert last == int(claim[i]), (
                f"node {i} writer {w}: kernel claims {int(claim[i])}, "
                f"bookie has {last}"
            )
            # Contiguity: every version 1..claim possessed, none above.
            for v in range(1, int(claim[i]) + 1):
                assert bv.contains_version(v)
            assert not bv.contains_version(int(claim[i]) + 1)


def test_rotation_bookkeeping_differential_vs_bookie():
    from corrosion_tpu.core.bookkeeping import BookedVersions, Current

    n, w_hot = 4, 2
    g = gossip.GossipConfig(
        n_nodes=n, n_writers=w_hot, track_writer_ids=True, n_cells=0,
        queue=4, fanout_near=2, fanout_far=1, sync_interval=4,
    )
    sp = sw.SparseConfig(epoch_rounds=4, k_dev=4, d_max=2, p_max=2)
    st = sw.init_sparse(g, sp)
    bookies = [
        {w: BookedVersions() for w in range(n)} for _ in range(n)
    ]

    def record(node, writer, start, end):
        # Current applies to a single version (agent.rs:1009-1047) —
        # insert each delivered version like the ingest path does.
        for v in range(start, end + 1):
            bookies[node][writer].insert(
                v, Current(db_version=v, last_seq=0, ts=0)
            )

    zeros2 = jnp.zeros(2, jnp.int32)
    false2 = jnp.zeros(2, bool)

    # Epoch 0: promote writers 1 and 2 into slots 0 and 1.
    st, stats = sw.rotate(
        st, zeros2, false2,
        jnp.asarray([0, 1], jnp.int32), jnp.asarray([1, 2], jnp.int32),
        jnp.asarray([True, True]), g,
    )
    _assert_claims_match(st, bookies, [1, 2, 3], n)

    # Delivery surgery: writer 1 commits 6 versions; nodes 0..2 fully
    # caught up, node 3 only to 2. Mirror into the bookies.
    contig = np.asarray(st.data.contig).copy()
    contig[:, 0] = [6, 6, 6, 2]
    head = np.asarray(st.data.head).copy()
    head[0] = 6
    st = st._replace(
        data=st.data._replace(
            contig=jnp.asarray(contig), head=jnp.asarray(head),
            seen=jnp.asarray(contig),
        )
    )
    for i, c in enumerate([6, 6, 6, 2]):
        record(i, 1, 1, c)
    _assert_claims_match(st, bookies, [1, 2, 3], n)

    # Forced demotion of slot 0 (node 3 lags) + promote writer 3 there.
    caught, maxload = sw.demote_report(
        st, jnp.asarray([0, 0], jnp.int32), jnp.asarray([True, False])
    )
    assert not bool(caught[0]) and int(maxload[0]) <= sp.k_dev
    st, stats = sw.rotate(
        st,
        jnp.asarray([0, 0], jnp.int32), jnp.asarray([True, False]),
        jnp.asarray([0, 0], jnp.int32), jnp.asarray([3, 0], jnp.int32),
        jnp.asarray([True, False]), g,
    )
    assert int(stats["dev_dropped"]) == 0
    assert int(stats["dev_entries"]) == 1  # node 3's lag on writer 1
    # Rotation changed NO possession: bookies untouched, claims must agree.
    _assert_claims_match(st, bookies, [1, 2, 3], n)

    # cold_sync heals node 3 from writer 1 (the origin). Mirror the grant.
    region = jnp.zeros(n, jnp.int32)
    st, cstats = sw.cold_sync(
        st, region, jnp.ones(n, bool), jnp.zeros((1, 1), bool), g, sp
    )
    assert int(cstats["cold_healed"]) == 4
    record(3, 1, 3, 6)
    _assert_claims_match(st, bookies, [1, 2, 3], n)
    assert not bool(st.dev_any)

    # Writer 3 (now hot in slot 0) commits via the REAL broadcast path;
    # in-order deliveries mirror into the bookies from the contig deltas.
    topo = gossip.make_topology([n], np.array([3, 2], np.int32))
    topo = topo._replace(
        writer_ids=jnp.asarray([3, 2], jnp.uint32),
        writer_of_node=jnp.asarray([-1, -1, 1, 0], jnp.int32),
    )
    alive = jnp.ones(n, bool)
    part = jnp.zeros((1, 1), bool)
    key = jax.random.PRNGKey(5)
    for r in range(6):
        key, k = jax.random.split(key)
        writes = jnp.asarray([1 if r < 2 else 0, 0], jnp.uint32)
        before = np.asarray(st.data.contig).copy()
        data, _ = gossip.broadcast_round(
            st.data, topo, alive, part, writes, k, g
        )
        st = st._replace(data=data)
        after = np.asarray(st.data.contig)
        for i in range(n):
            for s, w in ((0, 3), (1, 2)):
                if after[i, s] > before[i, s]:
                    record(i, w, int(before[i, s]) + 1, int(after[i, s]))
    _assert_claims_match(st, bookies, [1, 2, 3], n)


def test_sparse_engine_with_churn():
    """Nodes dying and rejoining mid-run: dead nodes block zero-lag
    demotion (they cannot catch up), forcing entries; revive_sync heals
    the hot plane on rejoin and cold_sync heals deviations — the run
    still converges on watermarks and cells."""
    cfg, topo, sched = _small(n=96, w_hot=12, rounds=96, cohort=5,
                              k_dev=24)
    rng = np.random.default_rng(9)
    rounds, n = sched.writes.shape[0], cfg.n_nodes
    kill = np.zeros((rounds, n), bool)
    revive = np.zeros((rounds, n), bool)
    # Six non-writer nodes flap for ~3 epochs mid-run (writers must stay
    # alive: a dead origin cannot serve cold pulls).
    writers = set(np.nonzero(sched.writes.sum(axis=0))[0].tolist())
    flappers = [i for i in range(n) if i not in writers][:6]
    for j, node in enumerate(flappers):
        down = 16 + 2 * j
        up = down + 24
        kill[down, node] = True
        if up < rounds:
            revive[up, node] = True
    sched.kill, sched.revive = kill, revive
    sstate, _, vis_round, curves, info = sparse_engine.simulate_sparse(
        cfg, topo, sched, seed=3
    )
    assert sparse_engine.converged_sparse(sstate)
    hf = sparse_engine.final_head_full(sstate)
    ref = sw.serial_merge_reference_sparse(hf, cfg.gossip)
    pc = gossip.node_cells(sstate.data, cfg.gossip)
    assert bool(jnp.all(pc.cl == ref.cl[None, :]))
    assert bool(jnp.all(pc.col_version == ref.col_version[None, :]))
    # Visibility: only pairs where the observer was dead at commit may
    # resolve late; all must resolve by the end.
    assert int((np.asarray(vis_round) < 0).sum()) == 0


def test_sparse_checkpoint_resume_bit_identical(tmp_path):
    """Save after 3 epochs, reload, run the rest: bit-identical to the
    uninterrupted run (the sparse plane's checkpoint/resume parity —
    sim/checkpoint.py save/load_sparse_resume)."""
    from corrosion_tpu.sim import checkpoint

    cfg, topo, sched = _small(rounds=48)
    full = sparse_engine.simulate_sparse(cfg, topo, sched, seed=5)

    part1 = sparse_engine.simulate_sparse(
        cfg, topo, sched, seed=5, stop_after_epoch=2
    )
    p = str(tmp_path / "sparse.npz")
    checkpoint.save_sparse_resume(p, part1[4]["resume"])
    resume = checkpoint.load_sparse_resume(
        p, cfg, len(sched.sample_writer)
    )
    part2 = sparse_engine.simulate_sparse(
        cfg, topo, sched, seed=5, resume=resume
    )
    assert (
        np.asarray(full[0].data.contig)
        == np.asarray(part2[0].data.contig)
    ).all()
    assert (
        np.asarray(full[0].data.cells.cl)
        == np.asarray(part2[0].data.cells.cl)
    ).all()
    assert (
        np.asarray(full[0].head_full) == np.asarray(part2[0].head_full)
    ).all()
    assert (np.asarray(full[2]) == np.asarray(part2[2])).all()  # vis


def test_sparse_zero_epoch_resume_returns_empty_curves():
    """A resume whose cursor is already at/past the schedule end (or a
    rounds==0 schedule) runs zero epochs: the resumed state comes back
    unchanged with EMPTY curves instead of an IndexError on the curve
    merge (ADVICE r5)."""
    cfg, topo, sched = _small(rounds=48)
    out = sparse_engine.simulate_sparse(cfg, topo, sched, seed=7)
    resume = out[4]["resume"]
    assert resume["next_epoch"] * cfg.sparse.epoch_rounds >= sched.rounds
    sstate, swim_state, vis_round, curves, info = (
        sparse_engine.simulate_sparse(
            cfg, topo, sched, seed=7, resume=resume
        )
    )
    assert curves == {}
    assert info["epochs"] == 0
    np.testing.assert_array_equal(
        np.asarray(sstate.data.contig), np.asarray(out[0].data.contig)
    )
    np.testing.assert_array_equal(np.asarray(vis_round), np.asarray(out[2]))
    assert info["resume"]["next_epoch"] == resume["next_epoch"]
