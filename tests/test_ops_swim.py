"""SWIM kernel behavior tests.

Drives the batched membership kernel the way the reference's stress/churn
scenarios drive foca (SURVEY.md §4): kill nodes, assert the cluster converges
to the truth within a bounded number of protocol periods; revive them and
assert refutation/rejoin works via incarnation bumps.
"""

import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.ops import swim


def run_rounds(state, cfg, start, count, seed=0):
    key = jax.random.PRNGKey(seed)
    for r in range(start, start + count):
        key, sub = jax.random.split(key)
        state = swim.swim_round(state, sub, jnp.int32(r), cfg)
    return state


def test_stable_cluster_stays_accurate():
    cfg = swim.SwimConfig(n_nodes=16)
    state = swim.init_state(cfg)
    state = run_rounds(state, cfg, 0, 10)
    assert int(swim.mismatches(state)) == 0
    # Nobody should have bumped incarnation in a quiet cluster.
    assert int(jnp.max(state.incarnation)) == 0


def test_dead_node_detected_and_spread():
    cfg = swim.SwimConfig(n_nodes=24, suspect_rounds=2, gossip_fanout=3)
    state = swim.init_state(cfg)
    state = run_rounds(state, cfg, 0, 4)
    kill = jnp.zeros(24, bool).at[5].set(True)
    state = swim.apply_churn(state, kill, jnp.zeros(24, bool))
    # Probe interval ~1 round, suspect->down 2 rounds, dissemination ~log N:
    # give it 30 rounds to be safe, then everyone must know node 5 is down.
    state = run_rounds(state, cfg, 4, 30, seed=1)
    sev = swim.packed_sev(state.view[:, 5])
    live = np.asarray(state.alive)
    believed_down = np.asarray(sev == swim.SEV_DOWN)
    assert believed_down[live].all(), "all live nodes must see node 5 as down"
    assert int(swim.mismatches(state)) == 0


def test_revived_node_rejoins_with_bumped_incarnation():
    cfg = swim.SwimConfig(n_nodes=16, suspect_rounds=2)
    state = swim.init_state(cfg)
    kill = jnp.zeros(16, bool).at[3].set(True)
    state = swim.apply_churn(state, kill, jnp.zeros(16, bool))
    state = run_rounds(state, cfg, 0, 25, seed=2)
    assert bool((swim.packed_sev(state.view[:, 3]) == swim.SEV_DOWN)[0])
    # Revive: identity renews (incarnation bump) and the cluster re-learns it.
    revive = jnp.zeros(16, bool).at[3].set(True)
    state = swim.apply_churn(state, jnp.zeros(16, bool), revive)
    assert int(state.incarnation[3]) == 1
    state = run_rounds(state, cfg, 25, 30, seed=3)
    sev = swim.packed_sev(state.view[:, 3])
    live = np.asarray(state.alive)
    assert np.asarray(sev < swim.SEV_DOWN)[live].all(), "rejoin must spread"
    assert int(swim.mismatches(state)) == 0


def test_false_suspicion_refuted_under_loss():
    # With packet loss, live nodes get suspected; refutation must keep the
    # cluster converged on the truth (accuracy returns to 1 in calm rounds).
    cfg = swim.SwimConfig(n_nodes=16, suspect_rounds=4, loss_prob=0.3)
    state = swim.init_state(cfg)
    state = run_rounds(state, cfg, 0, 20, seed=4)
    calm = swim.SwimConfig(n_nodes=16, suspect_rounds=4, loss_prob=0.0)
    state = run_rounds(state, calm, 20, 20, seed=5)
    assert int(swim.mismatches(state)) == 0
    assert bool(state.alive.all())


def test_view_merge_is_scatter_max():
    # The packed encoding must give SWIM's merge rule by plain max.
    a = swim.pack(jnp.uint32(2), swim.SEV_ALIVE)
    s = swim.pack(jnp.uint32(2), swim.SEV_SUSPECT)
    d = swim.pack(jnp.uint32(1), swim.SEV_DOWN)
    assert int(jnp.maximum(a, s)) == int(s)  # same inc: worse state wins
    assert int(jnp.maximum(s, d)) == int(s)  # higher inc beats old down
    a3 = swim.pack(jnp.uint32(3), swim.SEV_ALIVE)
    assert int(jnp.maximum(a3, s)) == int(a3)  # refutation wins
