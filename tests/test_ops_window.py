"""Out-of-order possession window: unit, differential, and healing tests.

The window (ops/gossip.py `window_absorb` + the delivery integrations) is
the bounded-tensor form of the reference's apply-in-any-order bookkeeping
(corro-agent/src/agent.rs:1809-2060; gap ranges in corro-types/src/
agent.rs:1041-1046). The differential test here replays identical delivery
traces through the REAL kernel delivery path (driven via queue surgery on
a 3-node cluster) and through the host bookie (`BookedVersions`, itself
vector-tested against the reference's own sync.rs cases), asserting the
possession sets agree version by version.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.core.bookkeeping import BookedVersions, Current
from corrosion_tpu.ops import gossip


# -- window_absorb vs a big-int reference model -------------------------------


def _absorb_ref(contig: int, bits: int, adv: int, new_bits: int, nbits: int):
    """Python big-int model: shift by adv, OR, promote trailing ones."""
    mask = (1 << nbits) - 1
    bits = ((bits >> adv) | new_bits) & mask
    t = 0
    while bits & (1 << t):
        t += 1
    return contig + adv + t, (bits >> t) & mask


@pytest.mark.parametrize("words", [1, 2])
def test_window_absorb_matches_bigint_model(words):
    rng = np.random.default_rng(7)
    nbits = 32 * words
    n = 64
    contig = rng.integers(0, 1000, n).astype(np.uint32)
    adv = rng.integers(0, nbits + 1, n).astype(np.int32)
    raw = [int(rng.integers(0, 1 << 32)) for _ in range(n * words * 2)]
    bits = [
        sum(raw[i * words + b] << (32 * b) for b in range(words))
        for i in range(n)
    ]
    newb = [
        sum(raw[(n + i) * words + b] << (32 * b) for b in range(words))
        for i in range(n)
    ]
    oo = np.zeros((words, n), np.uint32)
    nb = np.zeros((words, n), np.uint32)
    for i in range(n):
        for b in range(words):
            oo[b, i] = (bits[i] >> (32 * b)) & 0xFFFFFFFF
            nb[b, i] = (newb[i] >> (32 * b)) & 0xFFFFFFFF
    c2, oo2 = jax.jit(gossip.window_absorb)(
        jnp.asarray(contig), jnp.asarray(oo), jnp.asarray(adv),
        jnp.asarray(nb),
    )
    c2 = np.asarray(c2)
    oo2 = np.asarray(oo2)
    for i in range(n):
        want_c, want_bits = _absorb_ref(
            int(contig[i]), bits[i], int(adv[i]), newb[i], nbits
        )
        got_bits = sum(int(oo2[b, i]) << (32 * b) for b in range(words))
        assert int(c2[i]) == want_c, f"row {i}"
        assert got_bits == want_bits, f"row {i}"


# -- differential trace replay: kernel delivery vs host bookie ----------------

# Writer 0 carries the trace; writer 1 is the per-round beacon that makes
# "did node 1 pull node 0 this round?" observable from `seen`.
_QUEUE = 8


def _mk_harness(window_k=32, **kw):
    cfg = gossip.GossipConfig(
        n_nodes=3,
        n_writers=2,
        queue=_QUEUE,
        fanout_near=0,
        fanout_far=4,
        max_transmissions=6,
        sync_interval=2,
        sync_budget=16,
        sync_chunk=16,
        window_k=window_k,
        **kw,
    )
    topo = gossip.make_topology([3], [0, 2])
    data = gossip.init_data(cfg)
    return cfg, topo, data


def _seed_queue(data, batch, head, rnd):
    """Surgery: node 0's queue holds ``batch`` of writer-0 versions plus the
    round beacon (writer 1, version rnd+1); node 0 possesses everything."""
    qw = np.full((3, _QUEUE), -1, np.int32)
    qv = np.zeros((3, _QUEUE), np.uint32)
    qt = np.zeros((3, _QUEUE), np.int32)
    for j, v in enumerate(batch):
        qw[0, j] = 0
        qv[0, j] = v
        qt[0, j] = 5
    qw[0, len(batch)] = 1
    qv[0, len(batch)] = rnd + 1
    qt[0, len(batch)] = 5
    contig = np.asarray(data.contig).copy()
    seen = np.asarray(data.seen).copy()
    contig[0, 0] = seen[0, 0] = head
    contig[0, 1] = seen[0, 1] = rnd + 1
    return data._replace(
        head=jnp.asarray(np.array([head, rnd + 1], np.uint32)),
        contig=jnp.asarray(contig),
        seen=jnp.asarray(seen),
        q_writer=jnp.asarray(qw),
        q_ver=jnp.asarray(qv),
        q_tx=jnp.asarray(qt),
    )


def _possessed(data, node, ver, wk):
    """Kernel possession of (writer 0, ver) at ``node``: at/below the
    watermark or bit-set in the window."""
    contig = int(np.asarray(data.contig)[node, 0])
    if ver <= contig:
        return True
    d = ver - contig - 1
    if wk and d < wk:
        word = int(np.asarray(data.oo)[d // 32, node, 0])
        return bool((word >> (d % 32)) & 1)
    return False


def _local_shuffle(h, disp, rng):
    """Versions 1..h in an order where element i lands within ``disp`` of
    its sorted position — bounds every transient gap below 2*disp."""
    keys = np.arange(1, h + 1) + rng.uniform(0, disp, h)
    return np.array(sorted(range(1, h + 1), key=lambda v: keys[v - 1]))


def _run_trace(order, batch_cap, window_k, seed=0, legacy=False):
    """Replay a delivery order through the real broadcast path and the
    bookie in lockstep; compare possession after every delivered round."""
    cfg, topo, data = _mk_harness(window_k=window_k)
    h = len(order)
    alive = jnp.ones(3, bool)
    part = jnp.zeros((1, 1), bool)
    zero_w = jnp.zeros(2, jnp.uint32)
    key = jax.random.PRNGKey(seed)
    book = BookedVersions()
    sent = 0
    rnd = 0
    if legacy:
        old = gossip._FAST_MAX_WRITERS
        gossip._FAST_MAX_WRITERS = 0
        _clear_jit_caches()
    try:
        while sent < h and rnd < 400:
            batch = order[sent : sent + batch_cap]
            data = _seed_queue(data, batch, h, rnd)
            key, k1 = jax.random.split(key)
            data, _ = gossip.broadcast_round(
                data, topo, alive, part, zero_w, k1, cfg
            )
            delivered = int(np.asarray(data.seen)[1, 1]) == rnd + 1
            if delivered:
                for v in batch:
                    book.insert_many(
                        int(v), int(v), Current(db_version=int(v), last_seq=0, ts=0)
                    )
                sent += len(batch)
            rnd += 1
    finally:
        if legacy:
            gossip._FAST_MAX_WRITERS = old
            _clear_jit_caches()
    assert sent == h, "trace did not finish (source never sampled?)"
    return cfg, topo, data, book


def _clear_jit_caches():
    for fn in (gossip.broadcast_round, gossip.sync_round):
        try:
            fn.clear_cache()
        except AttributeError:
            pass


@pytest.mark.parametrize("legacy", [False, True])
def test_differential_vs_bookie_bounded_gaps(legacy):
    """Gaps bounded below window_k: kernel possession == bookie possession
    after every round, including out-of-order visibility mid-heal."""
    rng = np.random.default_rng(3)
    order = _local_shuffle(60, disp=8.0, rng=rng)
    cfg, topo, data, book = _run_trace(
        order, batch_cap=5, window_k=32, legacy=legacy
    )
    for v in range(1, 61):
        assert _possessed(data, 1, v, 32) == book.contains_version(v), (
            f"version {v} possession diverges from bookie"
        )
    # The whole trace was delivered, so both must hold everything; node 1's
    # window drains fully (node 2, a bystander that missed one-round queue
    # snapshots, may legitimately keep bits).
    assert all(book.contains_version(v) for v in range(1, 61))
    assert int(np.asarray(data.contig)[1, 0]) == 60
    assert int(np.asarray(data.oo)[:, 1, 0].sum()) == 0


@pytest.mark.parametrize("legacy", [False, True])
def test_differential_mid_trace_and_need_sets(legacy):
    """Check possession and need agreement at a mid-trace cut, where the
    window is typically non-empty."""
    rng = np.random.default_rng(11)
    order = _local_shuffle(40, disp=10.0, rng=rng)
    # Replay only a prefix: the trailing displaced versions leave holes.
    prefix = order[:25]
    cfg, topo, data, book = _run_trace(
        np.asarray(prefix), batch_cap=4, window_k=32, legacy=legacy
    )
    kernel_poss = {v for v in range(1, 41) if _possessed(data, 1, v, 32)}
    bookie_poss = {v for v in range(1, 41) if book.contains_version(v)}
    assert kernel_poss == bookie_poss
    # Need sets (heard-of but not possessed) agree too.
    seen = int(np.asarray(data.seen)[1, 0])
    last = book.last() or 0
    assert seen == last
    kernel_need = {v for v in range(1, seen + 1) if v not in kernel_poss}
    bookie_need = set()
    for s, e in book.sync_need():
        bookie_need.update(range(s, e + 1))
    assert kernel_need == bookie_need


def test_window_overflow_underclaims_then_sync_heals():
    """Displacement beyond window_k: the kernel may under-claim (safety:
    kernel possession ⊆ bookie possession) and anti-entropy heals the
    difference, promoting the watermark through window-held versions."""
    h = 50
    # Adversarial order: the tail first, then the head — gaps of ~40 > 32.
    order = np.concatenate([np.arange(41, h + 1), np.arange(1, 41)])
    cfg, topo, data, book = _run_trace(order, batch_cap=5, window_k=32)
    kernel_poss = {v for v in range(1, h + 1) if _possessed(data, 1, v, 32)}
    bookie_poss = {v for v in range(1, h + 1) if book.contains_version(v)}
    assert kernel_poss <= bookie_poss
    assert bookie_poss == set(range(1, h + 1))
    # Sync against node 0 (which holds everything) heals the rest.
    alive = jnp.ones(3, bool)
    part = jnp.zeros((1, 1), bool)
    key = jax.random.PRNGKey(9)
    for r in range(40):
        key, k1 = jax.random.split(key)
        data, _ = gossip.sync_round(
            data, topo, alive, part, jnp.int32(r), k1, cfg
        )
    assert int(np.asarray(data.contig)[1, 0]) == h
    assert not bool(np.asarray(data.oo_any))


# -- engine-level behavior ----------------------------------------------------


def _mini_cluster(window_k, loss=0.35, n=16):
    cfg = gossip.GossipConfig(
        n_nodes=n,
        n_writers=1,
        queue=8,
        fanout_near=2,
        fanout_far=1,
        max_transmissions=5,
        loss_prob=loss,
        sync_interval=6,
        sync_budget=64,
        sync_chunk=64,
        window_k=window_k,
    )
    topo = gossip.make_topology([n], [0])
    return cfg, topo, gossip.init_data(cfg)


def test_lossy_run_exercises_window_and_converges():
    """Under heavy loss, some node must at some point hold a version
    out-of-order (visible above a gap) — the pessimism the window removes —
    and the run still converges with an empty window."""
    cfg, topo, data = _mini_cluster(window_k=32)
    alive = jnp.ones(16, bool)
    part = jnp.zeros((1, 1), bool)
    key = jax.random.PRNGKey(2)
    w = jnp.zeros(1, jnp.uint32)
    saw_window = False
    for r in range(60):
        key, k1, k2 = jax.random.split(key, 3)
        writes = w.at[0].set(2 if r < 15 else 0)
        data, _ = gossip.broadcast_round(
            data, topo, alive, part, writes, k1, cfg
        )
        if bool(np.asarray(data.oo_any)):
            saw_window = True
            # Out-of-order possession is *visible*: some (node, version)
            # with contig < version must report visible=True.
            oo = np.asarray(data.oo)
            contig = np.asarray(data.contig)
            rows = np.nonzero(oo.any(axis=0).any(axis=1))[0]
            node = int(rows[0])
            d = int(np.nonzero(
                [(int(oo[b, node, 0]) >> (i % 32)) & 1
                 for i in range(32) for b in [i // 32]]
            )[0][0])
            ver = int(contig[node, 0]) + 1 + d
            vis = gossip.visibility(
                data, jnp.array([0]), jnp.array([ver], jnp.uint32)
            )
            assert bool(np.asarray(vis)[0, node]), (
                "window-possessed version must be visible"
            )
        data, _ = gossip.sync_round(
            data, topo, alive, part, jnp.int32(r), k2, cfg
        )
    assert saw_window, "loss config never exercised the window"
    assert bool((np.asarray(data.contig)[:, 0] == 30).all())
    assert not bool(np.asarray(data.oo_any))
    assert int(gossip.total_need(data)) == 0


def test_fast_and_legacy_paths_agree_on_one_round():
    """From an identical mid-run state and RNG, one broadcast_round via the
    delta-packed one-hot path and via the sort+scatter path must produce
    identical possession state (contig/seen/oo) and cells — the two
    implementations encode ONE semantics."""
    cfg = gossip.GossipConfig(
        n_nodes=24, n_writers=6, queue=8, fanout_near=2, fanout_far=1,
        max_transmissions=5, loss_prob=0.3, window_k=32, n_cells=32,
        sync_interval=4,
    )
    topo = gossip.make_topology([12, 12], [0, 3, 7, 11, 15, 19])
    data = gossip.init_data(cfg)
    alive = jnp.ones(24, bool)
    part = jnp.zeros((2, 2), bool)
    key = jax.random.PRNGKey(4)
    w = jnp.full((6,), 2, jnp.uint32)
    # Build a messy mid-run state on the default (fast) path.
    for r in range(12):
        key, k1, k2 = jax.random.split(key, 3)
        data, _ = gossip.broadcast_round(data, topo, alive, part, w, k1, cfg)
        if r % 3 == 0:
            data, _ = gossip.sync_round(
                data, topo, alive, part, jnp.int32(r), k2, cfg
            )
    key, k1 = jax.random.split(key)
    out_fast, _ = gossip.broadcast_round(data, topo, alive, part, w, k1, cfg)
    old = gossip._FAST_MAX_WRITERS
    gossip._FAST_MAX_WRITERS = 0
    _clear_jit_caches()
    try:
        out_legacy, _ = gossip.broadcast_round(
            data, topo, alive, part, w, k1, cfg
        )
    finally:
        gossip._FAST_MAX_WRITERS = old
        _clear_jit_caches()
    for name in ("head", "contig", "seen", "oo"):
        a = np.asarray(getattr(out_fast, name))
        b = np.asarray(getattr(out_legacy, name))
        assert (a == b).all(), f"{name} diverges between delivery paths"
    for name in ("cl", "col_version", "value_rank"):
        a = np.asarray(getattr(out_fast.cells, name))
        b = np.asarray(getattr(out_legacy.cells, name))
        assert (a == b).all(), f"cells.{name} diverges between paths"


def test_lossy_engine_run_with_64bit_window():
    """window_k=64 (two words): full engine round loop under loss converges
    and drains the window — exercises the multi-word shift/absorb path at
    engine level, not just the unit model."""
    cfg, topo, data = _mini_cluster(window_k=64, loss=0.4)
    alive = jnp.ones(16, bool)
    part = jnp.zeros((1, 1), bool)
    key = jax.random.PRNGKey(6)
    for r in range(80):
        key, k1, k2 = jax.random.split(key, 3)
        writes = jnp.asarray([3 if r < 20 else 0], jnp.uint32)
        data, _ = gossip.broadcast_round(
            data, topo, alive, part, writes, k1, cfg
        )
        data, _ = gossip.sync_round(
            data, topo, alive, part, jnp.int32(r), k2, cfg
        )
    assert bool((np.asarray(data.contig)[:, 0] == 60).all())
    assert not bool(np.asarray(data.oo_any))
    assert int(gossip.total_need(data)) == 0


def test_window_off_matches_old_inorder_semantics():
    """window_k=0 keeps the strict in-order model: no oo state, converges
    the old way."""
    cfg, topo, data = _mini_cluster(window_k=0)
    assert data.oo.shape == (0, 16, 1)
    alive = jnp.ones(16, bool)
    part = jnp.zeros((1, 1), bool)
    key = jax.random.PRNGKey(2)
    for r in range(70):
        key, k1, k2 = jax.random.split(key, 3)
        writes = jnp.asarray([2 if r < 15 else 0], jnp.uint32)
        data, _ = gossip.broadcast_round(
            data, topo, alive, part, writes, k1, cfg
        )
        data, _ = gossip.sync_round(
            data, topo, alive, part, jnp.int32(r), k2, cfg
        )
    assert bool((np.asarray(data.contig)[:, 0] == 30).all())


def test_total_need_excludes_window_possession():
    cfg, topo, data = _mk_harness(window_k=32)
    contig = np.asarray(data.contig).copy()
    seen = np.asarray(data.seen).copy()
    oo = np.asarray(data.oo).copy()
    # Node 1 heard of 10 versions, holds 1..4 contiguous + {6, 8} windowed.
    contig[1, 0] = 4
    seen[1, 0] = 10
    oo[0, 1, 0] = 0b1010  # bits 1,3 -> versions 6 and 8
    data = data._replace(
        contig=jnp.asarray(contig),
        seen=jnp.asarray(seen),
        oo=jnp.asarray(oo),
        oo_any=jnp.array(True),
    )
    assert int(gossip.total_need(data)) == 6 - 2  # 5..10 minus {6, 8}
    assert np.asarray(gossip.window_possession(data))[1, 0] == 6


def test_config_validates_window():
    with pytest.raises(ValueError):
        gossip.GossipConfig(n_nodes=4, n_writers=1, window_k=31)
    gossip.GossipConfig(n_nodes=4, n_writers=1, window_k=64)


# -- window saturation instrumentation ----------------------------------------
#
# VERDICT r4 weak #4: a long outage accumulates far more versions than the
# window holds; affected nodes degrade to seen-only pessimism. The
# `window_degraded` counter makes that visible; `sync_regrant` measures the
# budget spent re-granting window-possessed versions (ADVICE r4 #2).


def _partition_cluster(rounds=40, cut=20, n=8):
    from corrosion_tpu.ops.swim import SwimConfig
    from corrosion_tpu.sim.engine import ClusterConfig, Schedule

    g = gossip.GossipConfig(
        n_nodes=n, n_writers=1, sync_interval=4, sync_budget=64,
        sync_chunk=64, window_k=32, queue=8, fanout_near=2, fanout_far=1,
        max_transmissions=4,
    )
    s = SwimConfig(
        n_nodes=n, max_transmissions=4, suspect_rounds=3, gossip_fanout=3
    )
    topo = gossip.make_topology(
        [n // 2, n - n // 2], [0], sync_interval=g.sync_interval
    )
    writes = np.zeros((rounds, 1), np.uint32)
    writes[: cut + 4, 0] = 4  # ~96 versions: far beyond the 32-bit window
    part = None
    if cut:
        part = np.zeros((rounds, 2, 2), bool)
        part[:cut, 0, 1] = True
        part[:cut, 1, 0] = True
    sched = Schedule(writes=writes, partition=part).make_samples(32)
    return ClusterConfig(swim=s, gossip=g), topo, sched


def test_degraded_counter_fires_after_partition_heal():
    from corrosion_tpu.sim.engine import simulate

    cfg, topo, sched = _partition_cluster(rounds=48, cut=20)
    final, curves = simulate(cfg, topo, sched, seed=0)
    # Post-heal, region-1 nodes see arrivals ~90 versions beyond their
    # watermark — far past window_k=32 — and must degrade.
    assert int(curves["window_degraded"][20:].sum()) > 0
    # The cluster still converges (sync heals the degraded tail).
    heads = np.asarray(final.data.head)
    assert (np.asarray(final.data.contig) == heads[None, :]).all()


def test_degraded_counter_zero_in_steady_state():
    from corrosion_tpu.sim.engine import simulate

    cfg, topo, sched = _partition_cluster(rounds=48, cut=0)
    final, curves = simulate(cfg, topo, sched, seed=0)
    assert int(curves["window_degraded"].sum()) == 0
    heads = np.asarray(final.data.head)
    assert (np.asarray(final.data.contig) == heads[None, :]).all()
