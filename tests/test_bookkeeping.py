"""BookedVersions + compute_available_needs against the reference's vectors.

test_compute_available_needs_reference_vectors is a direct translation of the
reference's own unit test (corro-types/src/sync.rs:376-491), stage by stage.
insert_many cases mirror agent.rs:1009-1047 and the in-tree compaction test
(agent.rs:3224 test_in_memory_versions_compaction's bookkeeping steps).
"""

import pytest

from corrosion_tpu.core.bookkeeping import (
    CLEARED,
    Bookie,
    BookedVersions,
    Current,
    FullNeed,
    Partial,
    PartialNeed,
    SyncState,
    generate_sync,
)
from corrosion_tpu.core.intervals import RangeSet


A1 = "actor-1"


def test_compute_available_needs_reference_vectors():
    our = SyncState(actor_id="us")
    our.heads[A1] = 10
    other = SyncState(actor_id="them")
    other.heads[A1] = 13

    # Stage 1: head gap only (sync.rs:385-400).
    assert our.compute_available_needs(other) == {A1: [FullNeed(11, 13)]}

    # Stage 2: full needs [2,5] and [7,7] (sync.rs:402-426).
    our.need.setdefault(A1, []).append((2, 5))
    our.need.setdefault(A1, []).append((7, 7))
    assert our.compute_available_needs(other) == {
        A1: [FullNeed(2, 5), FullNeed(7, 7), FullNeed(11, 13)]
    }

    # Stage 3: our partial v9 seqs [100,120],[130,132] (sync.rs:428-458).
    our.partial_need[A1] = {9: [(100, 120), (130, 132)]}
    assert our.compute_available_needs(other) == {
        A1: [
            FullNeed(2, 5),
            FullNeed(7, 7),
            PartialNeed(9, [(100, 120), (130, 132)]),
            FullNeed(11, 13),
        ]
    }

    # Stage 4: they're partial on v9 too — their partial_need lists THEIR
    # gaps [100,110],[130,130] — so we request only the overlap of our gaps
    # with what they actually hold (sync.rs:460-489).
    other.partial_need[A1] = {9: [(100, 110), (130, 130)]}
    assert our.compute_available_needs(other) == {
        A1: [
            FullNeed(2, 5),
            FullNeed(7, 7),
            PartialNeed(9, [(111, 120), (131, 132)]),
            FullNeed(11, 13),
        ]
    }


def test_zero_head_and_self_are_skipped():
    our = SyncState(actor_id="us")
    other = SyncState(actor_id="them")
    other.heads["us"] = 5  # our own id: skipped (sync.rs:129)
    other.heads[A1] = 0  # zero head: skipped (sync.rs:132-135)
    assert our.compute_available_needs(other) == {}


def test_insert_many_tracks_gaps_as_sync_need():
    bv = BookedVersions()
    bv.insert_many(1, 1, Current(db_version=1, last_seq=10, ts=0))
    assert list(bv.sync_need()) == []
    assert bv.last() == 1
    # Jump to version 5: versions 2..=5's start are needed (agent.rs:1038-43:
    # (old_last+1)..=start inserted, then the inserted range removed).
    bv.insert_many(5, 5, Current(db_version=2, last_seq=3, ts=0))
    assert list(bv.sync_need()) == [(2, 4)]
    assert bv.last() == 5
    bv.insert_many(3, 3, CLEARED)
    assert list(bv.sync_need()) == [(2, 2), (4, 4)]
    bv.insert_many(2, 2, Current(db_version=3, last_seq=0, ts=0))
    bv.insert_many(4, 4, Current(db_version=4, last_seq=0, ts=0))
    assert list(bv.sync_need()) == []


def test_insert_cleared_range_purges_current_and_partials():
    bv = BookedVersions()
    bv.insert(1, Current(db_version=1, last_seq=0, ts=0))
    bv.insert(2, Partial(seqs=RangeSet([(0, 3)]), last_seq=9, ts=0))
    bv.insert(3, Current(db_version=2, last_seq=0, ts=0))
    bv.insert_many(1, 3, CLEARED)
    assert bv.current == {}
    assert bv.partials == {}
    assert list(bv.cleared) == [(1, 3)]
    assert bv.contains_all((1, 3))


def test_partial_promotion_to_current():
    bv = BookedVersions()
    p = Partial(seqs=RangeSet([(0, 5)]), last_seq=9, ts=0)
    bv.insert(4, p)
    assert not p.is_complete()
    assert p.gaps() == [(6, 9)]
    assert bv.contains(4, (0, 5))
    assert not bv.contains(4, (0, 9))
    p.seqs.insert(6, 9)
    assert p.is_complete()
    bv.insert(4, Current(db_version=7, last_seq=9, ts=0))
    assert 4 not in bv.partials
    assert bv.contains(4, (0, 9))


def test_generate_sync_shape():
    bookie = Bookie()
    bv = bookie.for_actor(A1)
    bv.insert(1, Current(db_version=1, last_seq=0, ts=0))
    bv.insert(5, Current(db_version=2, last_seq=0, ts=0))
    bv.insert(7, Partial(seqs=RangeSet([(0, 2)]), last_seq=9, ts=0))
    state = generate_sync(bookie, "me")
    assert state.heads[A1] == 7
    assert state.need[A1] == [(2, 4), (6, 6)]
    assert state.partial_need[A1] == {7: [(3, 9)]}
    assert state.need_len_for_actor(A1) == 5
    # Round-trip: a fresh node computes needs against us.
    empty = SyncState(actor_id="newbie")
    needs = empty.compute_available_needs(state)
    assert needs == {A1: [FullNeed(1, 7)]}


def test_need_len_counts_partials_as_chunks():
    s = SyncState(actor_id="x")
    s.need["a"] = [(1, 10)]
    s.partial_need["a"] = {3: [(0, 99)]}
    assert s.need_len() == 10 + 100 // 50
