"""Concurrent multi-peer sync: parallel sessions, in-flight dedup,
adaptive chunk sizing, stall abort, server budget.

Mirrors the reference's parallel_sync machinery (peer.rs:925-1286:
FuturesUnordered sessions, 10-needs/turn scheduling with in-flight dedup
peer.rs:1108-1223, 8 KiB→1 KiB chunk halving past 500 ms sends
peer.rs:352-355,638-653, bounded server jobs peer.rs:675-686).
"""

from __future__ import annotations

import asyncio
import time

from corrosion_tpu.agent.agent import Agent, AgentConfig
from corrosion_tpu.agent.testing import TEST_SCHEMA, launch_test_agent, poll_until
from corrosion_tpu.core.bookkeeping import FullNeed, PartialNeed
from corrosion_tpu.core.changes import AdaptiveChunker
from corrosion_tpu.core.values import Statement


def run(coro):
    return asyncio.run(coro)


def make_agent(tmp_path) -> Agent:
    return Agent(AgentConfig(data_dir=str(tmp_path), schema_sql=TEST_SCHEMA))


class FakeSession:
    """Scripted server-side session: feeds frames to recv, records sends."""

    def __init__(self, script, send_delay: float = 0.0):
        self.script = list(script)
        self.frames = []
        self.send_delay = send_delay
        self.closed = False

    async def send(self, frame):
        if self.send_delay:
            await asyncio.sleep(self.send_delay)
        self.frames.append(frame)

    async def recv(self, timeout: float = 0.0):
        if self.script:
            return self.script.pop(0)
        return None

    def close(self):
        self.closed = True


def test_adaptive_chunker_halves_and_floors():
    c = AdaptiveChunker(max_bytes=8192, min_bytes=1024, threshold_s=0.5)
    c.record(0.1)
    assert c.max_bytes == 8192  # fast send: unchanged
    c.record(0.6)
    assert c.max_bytes == 4096  # slow send: halved
    for _ in range(10):
        c.record(1.0)
    assert c.max_bytes == 1024  # floored at the reference's 1 KiB minimum


def test_claim_needs_dedups_across_sessions(tmp_path):
    a = make_agent(tmp_path)
    try:
        in_flight: set = set()
        needs = {"aa" * 16: [FullNeed(1, 25)], "bb" * 16: [PartialNeed(3, [(0, 5)])]}
        wave1, keys1 = a._claim_needs(needs, in_flight, cap=2)
        # Grid-aligned blocks: [1,10], [11,20] claimed first.
        assert [
            (n.start, n.end) for n in wave1["aa" * 16]
        ] == [(1, 10), (11, 20)]
        # A concurrent session computing the SAME needs gets only what's
        # left — no overlap with session 1's claims.
        wave2, keys2 = a._claim_needs(needs, in_flight, cap=10)
        got2 = [(n.start, n.end) for n in wave2.get("aa" * 16, [])]
        assert got2 == [(21, 25)]
        assert any(isinstance(n, PartialNeed) for n in wave2["bb" * 16])
        assert not (set(keys1) & set(keys2))
        # Releasing session 1's claims makes its blocks requestable again.
        for k in keys1:
            in_flight.discard(k)
        wave3, _ = a._claim_needs(needs, in_flight, cap=10)
        assert [(n.start, n.end) for n in wave3["aa" * 16]] == [(1, 10), (11, 20)]
    finally:
        a.store.close()


def test_serve_need_shrinks_chunks_on_slow_sends(tmp_path):
    a = make_agent(tmp_path)
    try:
        # One big multi-row transaction so chunking has something to split.
        a.execute(
            [
                Statement(
                    "INSERT INTO tests (id, text) VALUES (?, ?)",
                    params=[i, "x" * 200],
                )
                for i in range(60)
            ]
        )
        booked = a.bookie.for_actor(a.actor_id)

        async def main():
            chunker = AdaptiveChunker(
                max_bytes=8192, min_bytes=1024, threshold_s=0.01
            )
            s = FakeSession([], send_delay=0.02)  # every send is "slow"
            await a._serve_need(
                s, a.actor_id, booked, FullNeed(1, 1), chunker=chunker
            )
            return chunker, s

        chunker, s = run(main())
        # The chunk target observably shrank below the 8 KiB start.
        assert chunker.max_bytes < 8192
        assert any(f["t"] == "sync_changes" for f in s.frames)
    finally:
        a.store.close()


def test_serve_sync_budget_bounds_a_wave(tmp_path):
    a = make_agent(tmp_path)
    a.cfg.sync_serve_budget = 5
    try:
        for i in range(12):
            a.execute(
                [Statement("INSERT INTO tests (id, text) VALUES (?, 'x')",
                           params=[i])]
            )
        wire_needs = {a.actor_id: [{"full": [1, 12]}]}

        async def main():
            s = FakeSession(
                [{"t": "sync_request", "needs": wire_needs},
                 {"t": "sync_finish"}]
            )
            await a._serve_sync(s, {"t": "sync_start", "actor": "cc" * 16})
            return s.frames

        frames = run(main())
        waves = [f for f in frames if f["t"] == "sync_wave_done"]
        assert waves and waves[0]["served"] == 5  # budget, not 12
        versions = {f["version"] for f in frames if f["t"] == "sync_changes"}
        assert len(versions) == 5  # a huge request cannot monopolize a wave
        assert frames[-1]["t"] == "sync_done"
    finally:
        a.store.close()


def test_slow_peer_does_not_delay_fast_peer(tmp_path):
    """The verdict's acceptance test: with sessions concurrent, a slow
    peer's sync cannot delay the data arriving from a fast peer."""

    async def main():
        # Dissemination via sync only: broadcasts effectively disabled.
        kw = dict(broadcast_interval=3600.0, sync_interval=0.3, sync_peers=3)
        a = await launch_test_agent(str(tmp_path / "a"), **kw)
        b = await launch_test_agent(
            str(tmp_path / "b"), bootstrap=[a.gossip_addr], **kw
        )
        c = await launch_test_agent(
            str(tmp_path / "c"), bootstrap=[a.gossip_addr], **kw
        )
        try:
            await poll_until(
                lambda: asyncio.sleep(
                    0, result=len(a.agent.members.alive()) >= 2
                )
            )
            # Both peers get data; C's sessions are slowed 1 s per frame.
            await b.client.execute(
                [[f"INSERT INTO tests (id, text) VALUES ({i}, 'fast')"]
                 for i in range(5)]
            )
            await c.client.execute(
                [[f"INSERT INTO tests2 (id, text) VALUES ({i}, 'slow')"]
                 for i in range(5)]
            )

            c_addr = c.agent.gossip_addr
            orig_open = a.agent.transport.open_session

            async def slow_open(addr, first, timeout=10.0):
                session = await orig_open(addr, first, timeout)
                if session is not None and addr == c_addr:
                    orig_recv = session.recv

                    async def slow_recv(timeout=30.0):
                        await asyncio.sleep(1.0)
                        return await orig_recv(timeout)

                    session.recv = slow_recv
                return session

            a.agent.transport.open_session = slow_open

            t0 = time.monotonic()

            async def fast_rows():
                _, rows = await a.client.query("SELECT count(*) FROM tests")
                return rows[0][0] == 5

            await poll_until(fast_rows, timeout=10.0)
            fast_t = time.monotonic() - t0
            # B's 5 versions must land well before C's 1 s/frame sessions
            # could have finished even one wave (state+5 waves ≥ 5 s).
            assert fast_t < 4.0

            async def slow_rows():
                _, rows = await a.client.query("SELECT count(*) FROM tests2")
                return rows[0][0] == 5

            await poll_until(slow_rows, timeout=30.0)  # C still completes
        finally:
            await c.stop()
            await b.stop()
            await a.stop()

    run(main())
