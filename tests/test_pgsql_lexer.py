"""Token-level PG SQL translation vectors (VERDICT r3 #4).

The reference parses before rewriting (sqlparser, corro-pg/src/
lib.rs:306,325-327); these vectors pin the properties a regex pass got
wrong: casts inside string literals, nested casts, comments, and
multi-statement splitting.
"""

from corrosion_tpu.agent import pgsql


def test_cast_inside_string_literal_untouched():
    assert pgsql.translate("SELECT 'a::b'") == "SELECT 'a::b'"
    assert (
        pgsql.translate("INSERT INTO t (x) VALUES ('n::int')")
        == "INSERT INTO t (x) VALUES ('n::int')"
    )
    # ...including doubled-quote literals and quoted identifiers.
    assert pgsql.translate("SELECT 'it''s::int'") == "SELECT 'it''s::int'"
    assert pgsql.translate('SELECT "a::b" FROM t') == 'SELECT "a::b" FROM t'


def test_simple_and_literal_casts():
    assert pgsql.translate("SELECT x::int4 FROM t") == (
        "SELECT CAST(x AS INTEGER) FROM t"
    )
    assert pgsql.translate("SELECT 'x'::text") == "SELECT CAST('x' AS TEXT)"
    assert pgsql.translate("SELECT $1::int8") == "SELECT CAST($1 AS INTEGER)"
    assert pgsql.translate("SELECT a.b.c::varchar(32)") == (
        "SELECT CAST(a.b.c AS TEXT)"
    )
    # Unknown type: cast dropped, value kept.
    assert pgsql.translate("SELECT x::tsvector FROM t") == (
        "SELECT x FROM t"
    )


def test_nested_casts_compose():
    assert pgsql.translate("SELECT x::int::text") == (
        "SELECT CAST(CAST(x AS INTEGER) AS TEXT)"
    )
    assert pgsql.translate("SELECT (a + b)::int8") == (
        "SELECT CAST((a + b) AS INTEGER)"
    )
    assert pgsql.translate("SELECT f(x)::text") == (
        "SELECT CAST(f(x) AS TEXT)"
    )


def test_multiword_type_casts():
    # Multi-word type names must be consumed whole — the second word used
    # to dangle after the rewrite (x::double precision ->
    # "CAST(x AS REAL) precision").
    assert pgsql.translate("SELECT x::double precision FROM t") == (
        "SELECT CAST(x AS REAL) FROM t"
    )
    assert pgsql.translate("SELECT x::character varying(12) FROM t") == (
        "SELECT CAST(x AS TEXT) FROM t"
    )
    # timestamp has no SQLite affinity: cast dropped, value kept, and the
    # with/without time zone suffix consumed (not left dangling).
    assert pgsql.translate(
        "SELECT x::timestamp with time zone FROM t"
    ) == "SELECT x FROM t"
    assert pgsql.translate(
        "SELECT x::time without time zone, y FROM t"
    ) == "SELECT x, y FROM t"
    # bit varying: unknown type, suffix still consumed.
    assert pgsql.translate("SELECT x::bit varying FROM t") == (
        "SELECT x FROM t"
    )


def test_array_casts():
    # A ']'-terminated value is the whole bracketed run plus what it
    # subscripts, not a one-token ']'.
    assert pgsql.translate("SELECT ARRAY[1,2]::text") == (
        "SELECT CAST(ARRAY[1,2] AS TEXT)"
    )
    assert pgsql.translate("SELECT a.b[1]::int8 FROM t") == (
        "SELECT CAST(a.b[1] AS INTEGER) FROM t"
    )
    assert pgsql.translate("SELECT f(x)[2]::text") == (
        "SELECT CAST(f(x)[2] AS TEXT)"
    )
    # Array TYPES have no SQLite affinity: brackets consumed, cast
    # dropped, value kept.
    assert pgsql.translate("SELECT x::text[] FROM t") == "SELECT x FROM t"
    assert pgsql.translate("SELECT x::int[3] FROM t") == "SELECT x FROM t"
    assert pgsql.translate("SELECT ARRAY[1,2]::int[] FROM t") == (
        "SELECT ARRAY[1,2] FROM t"
    )


def _norm(s):
    return " ".join(s.split())


def test_comments_stripped_and_inert():
    assert _norm(pgsql.translate(
        "SELECT x -- cast this? x::int\nFROM t"
    )) == "SELECT x FROM t"
    assert _norm(pgsql.translate(
        "SELECT /* true::int 'y */ x FROM t"
    )) == "SELECT x FROM t"
    # Nested block comments (PG nests; a naive scanner would end early).
    assert _norm(pgsql.translate(
        "SELECT /* a /* b */ still comment */ x FROM t"
    )) == "SELECT x FROM t"
    # A quote opened inside a comment must not swallow following SQL.
    assert _norm(pgsql.translate(
        "SELECT x /* don't */ , true FROM t"
    )) == "SELECT x , 1 FROM t"
    # Comment glue must not fuse adjacent identifiers.
    assert _norm(pgsql.translate("SELECT x--c\nFROM t")) == "SELECT x FROM t"


def test_multi_statement_split_is_token_aware():
    parts = pgsql.split_statements(
        "INSERT INTO t VALUES ('a;b'); -- c;d\nSELECT 1; SELECT ';';"
    )
    assert parts == [
        "INSERT INTO t VALUES ('a;b')",
        "-- c;d\nSELECT 1",
        "SELECT ';'",
    ]
    assert pgsql.split_statements("SELECT $$x;y$$") == ["SELECT $$x;y$$"]


def test_dialect_and_shims_skip_strings():
    assert pgsql.translate("SELECT true, false, x ILIKE 'A%' FROM t") == (
        "SELECT 1, 0, x LIKE 'A%' FROM t"
    )
    assert pgsql.translate("SELECT 'true ilike current_user'") == (
        "SELECT 'true ilike current_user'"
    )
    assert pgsql.translate("SELECT current_database()") == (
        "SELECT 'corrosion'"
    )
    assert pgsql.translate("SELECT current_user") == "SELECT 'corrosion'"
    # Qualified column named like a shim is NOT a shim.
    assert pgsql.translate("SELECT t.current_user FROM t") == (
        "SELECT t.current_user FROM t"
    )


def test_estring_decodes():
    assert pgsql.translate(r"SELECT E'a\nb'") == "SELECT 'a\nb'"
    assert pgsql.translate(r"SELECT E'it\'s'") == "SELECT 'it''s'"
    # A plain identifier ending in e followed by a string is NOT an
    # E-string.
    assert pgsql.translate("SELECT value 'x'") == "SELECT value 'x'"


def test_txn_and_session_statements_elide():
    assert pgsql.translate("BEGIN") == ""
    assert pgsql.translate("start transaction") == ""
    assert pgsql.translate("SET client_encoding = 'UTF8'") == ""
    assert pgsql.translate("SHOW server_version") == ""
    assert pgsql.translate("COMMIT;") == ""


def test_prepared_param_casts_and_many_casts():
    # The prepared-statement path rewrites $N -> ?N BEFORE translate; a
    # cast on a parameter must wrap the whole placeholder.
    assert pgsql.translate(
        pgsql.translate_placeholders("INSERT INTO t (a) VALUES ($1::int8)")
    ) == "INSERT INTO t (a) VALUES (CAST(?1 AS INTEGER))"
    # No cast-count ceiling: machine-generated statements with many casts
    # translate completely.
    many = "SELECT " + ", ".join(f"${i}::text" for i in range(1, 81))
    out = pgsql.translate(pgsql.translate_placeholders(many))
    assert "::" not in out
    assert out.count("CAST(") == 80


def test_tokenize_render_roundtrip_property():
    """The lexer is lossless: render(tokenize(s)) == s for ANY input —
    SQL-shaped or adversarial garbage (unterminated strings, stray
    dollar signs, partial comments). Translation safety rests on this."""
    import random
    import string

    rng = random.Random(7)
    pieces = [
        "select", "'a''b'", '"Q q"', "$$x;y$$", "$1", "--c\n", "/*x*/",
        "/* nested /* deep */ out */", "::", ";", " ", "\t\n", "1.5e3",
        "1.5e", r"E'\n'", "e'unterminated", "$tag$z$tag$", "$bad$never",
        "(", ")", ",", "ident_x", "'unterminated", '"open', "$", ".", "?3",
    ]
    for _ in range(300):
        s = "".join(
            rng.choice(pieces) for _ in range(rng.randint(0, 10))
        )
        assert pgsql.render(pgsql.tokenize(s)) == s, repr(s)
    for _ in range(300):
        s = "".join(
            rng.choice(string.printable) for _ in range(rng.randint(0, 40))
        )
        assert pgsql.render(pgsql.tokenize(s)) == s, repr(s)


def test_normalize_sql_idempotent():
    from corrosion_tpu.agent.subs import normalize_sql

    for s in (
        "SELECT  id FROM Tests -- c\n WHERE x = 'A';",
        "select 1", "", "  ;;  ",
    ):
        once = normalize_sql(s)
        assert normalize_sql(once) == once


def test_placeholders_and_catalog():
    assert pgsql.translate_placeholders("SELECT $1, '$2'") == (
        "SELECT ?1, '$2'"
    )
    assert pgsql.mentions_catalog("SELECT * FROM pg_catalog.pg_type")
    assert not pgsql.mentions_catalog("SELECT 'pg_type'")
    assert pgsql.strip_catalog_prefix(
        "SELECT * FROM pg_catalog.pg_type WHERE t = 'pg_catalog.x'"
    ) == "SELECT * FROM pg_type WHERE t = 'pg_catalog.x'"
