"""Property tests: JAX fixed-capacity interval sets vs host RangeSet.

Random op sequences are applied to both implementations; whenever the JAX set
has not hit its capacity bound, the two must agree exactly. Mirrors the
reference's rangemap-based bookkeeping tests (corro-types/src/agent.rs,
sync.rs:376-491 drive the same structures).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.core.intervals import RangeSet
from corrosion_tpu.ops import intervals as iv


CAP = 8


def as_list(s: iv.IntervalSet):
    return iv.to_host(s)


def test_insert_coalesce_basics():
    s = iv.make(CAP)
    s = iv.insert(s, 1, 3)
    s = iv.insert(s, 5, 7)
    assert as_list(s) == [(1, 3), (5, 7)]
    s = iv.insert(s, 4, 4)  # adjacent to both -> coalesce all
    assert as_list(s) == [(1, 7)]
    s = iv.insert(s, 10, 12)
    s = iv.insert(s, 0, 20)
    assert as_list(s) == [(0, 20)]


def test_remove_split():
    s = iv.from_ranges([(0, 10)], CAP)
    s = iv.remove(s, 3, 5)
    assert as_list(s) == [(0, 2), (6, 10)]
    s = iv.remove(s, 0, 0)
    assert as_list(s) == [(1, 2), (6, 10)]
    s = iv.remove(s, 0, 100)
    assert as_list(s) == []


def test_contains_and_total():
    s = iv.from_ranges([(1, 3), (7, 9)], CAP)
    assert bool(iv.contains(s, 2))
    assert not bool(iv.contains(s, 5))
    assert bool(iv.contains_range(s, 7, 9))
    assert not bool(iv.contains_range(s, 3, 7))
    assert int(iv.total(s)) == 6
    assert int(iv.max_end(s)) == 9


def test_gaps():
    s = iv.from_ranges([(2, 3), (6, 7)], CAP)
    g = iv.gaps(s, 0, 10)
    assert as_list(g) == [(0, 1), (4, 5), (8, 10)]
    g = iv.gaps(s, 2, 7)
    assert as_list(g) == [(4, 5)]
    empty = iv.make(CAP)
    assert as_list(iv.gaps(empty, 3, 5)) == [(3, 5)]
    full = iv.from_ranges([(0, 10)], CAP)
    assert as_list(iv.gaps(full, 2, 8)) == []


def test_contiguous_watermark():
    s = iv.from_ranges([(0, 4), (6, 9)], CAP)
    assert int(iv.contiguous_watermark(s, 0)) == 4
    s = iv.insert(s, 5, 5)
    assert int(iv.contiguous_watermark(s, 0)) == 9
    empty = iv.make(CAP)
    assert int(iv.contiguous_watermark(empty, 3)) == 2


def test_union():
    a = iv.from_ranges([(0, 2), (10, 12)], CAP)
    b = iv.from_ranges([(3, 5), (11, 20)], CAP)
    assert as_list(iv.union(a, b)) == [(0, 5), (10, 20)]


@pytest.mark.parametrize("seed", range(6))
def test_random_ops_match_rangeset(seed):
    rng = np.random.default_rng(seed)
    cap = 16
    js = iv.make(cap)
    hs = RangeSet()
    overflowed = False
    for _ in range(120):
        s = int(rng.integers(0, 120))
        e = s + int(rng.integers(0, 15))
        if rng.random() < 0.7:
            js = iv.insert(js, s, e)
            hs.insert(s, e)
        else:
            js = iv.remove(js, s, e)
            hs.remove(s, e)
        host = list(hs)
        overflowed = overflowed or len(host) > cap
        if not overflowed:
            assert as_list(js) == host, f"mismatch after ops (seed={seed})"
        else:
            # Ever-overflowed: JAX set must under-approximate coverage (safe).
            for gs, ge in as_list(js):
                assert hs.contains_range(gs, ge)


@pytest.mark.parametrize("seed", range(3))
def test_random_gaps_match(seed):
    rng = np.random.default_rng(100 + seed)
    cap = 16
    js = iv.make(cap)
    hs = RangeSet()
    for _ in range(40):
        s = int(rng.integers(0, 80))
        e = s + int(rng.integers(0, 10))
        js = iv.insert(js, s, e)
        hs.insert(s, e)
    if len(list(hs)) <= cap:
        lo, hi = 5, 75
        assert as_list(iv.gaps(js, lo, hi)) == list(hs.gaps(lo, hi))


def test_overflow_drops_smallest():
    cap = 4
    s = iv.make(cap)
    widths = [(0, 9), (20, 29), (40, 49), (60, 69)]
    for a, b in widths:
        s = iv.insert(s, a, b)
    s = iv.insert(s, 80, 80)  # 5th disjoint interval, width 1 -> dropped
    out = as_list(s)
    assert len(out) == cap
    assert (80, 80) not in out
    # coverage under-approximates: everything present was really inserted
    for a, b in out:
        assert (a, b) in widths


def test_vmapped_insert():
    import jax

    base = iv.make(CAP)
    batch = jax.tree.map(lambda x: jnp.stack([x] * 4), base)
    ss = jnp.array([0, 10, 20, 30], dtype=jnp.int32)
    es = ss + 5
    out = jax.vmap(iv.insert)(batch, ss, es)
    for i in range(4):
        row = iv.IntervalSet(out.starts[i], out.ends[i])
        assert as_list(row) == [(int(ss[i]), int(es[i]))]
