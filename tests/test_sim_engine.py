"""End-to-end engine tests on the small baseline configs.

The assertions mirror what the reference's integration tests check
(SURVEY.md §4): writes converge cluster-wide, bookkeeping need drains to
zero, churn is detected and healed, visibility latency is finite and sane.
"""

import numpy as np
import pytest

from corrosion_tpu import models
from corrosion_tpu.sim import simulate, visibility_latencies
from corrosion_tpu.sim.engine import Schedule


def test_three_node_1k_inserts_converges():
    cfg, topo, sched = models.three_node(n_inserts=100, samples=64)
    final, curves = simulate(cfg, topo, sched, seed=0)
    heads = np.asarray(final.data.head)
    assert heads.sum() == 100, "exactly the scheduled inserts commit"
    contig = np.asarray(final.data.contig)
    assert (contig == heads[None, :]).all(), "all 3 nodes hold every version"
    assert curves["need"][-1] == 0
    lat = visibility_latencies(final, sched, cfg)
    assert lat["unseen"] == 0
    assert lat["p99_s"] < 10.0
    assert curves["mismatches"][-1] == 0


def test_revive_syncs_immediately():
    """A rejoining node pulls anti-entropy the SAME round it comes back
    (the reference syncs on rejoin) instead of waiting out its sync-cohort
    slot — heal latency is bounded by the session budget, not the cadence."""
    from corrosion_tpu.ops.gossip import GossipConfig, make_topology
    from corrosion_tpu.ops.swim import SwimConfig
    from corrosion_tpu.sim.engine import ClusterConfig

    n = 16
    g = GossipConfig(
        n_nodes=n, n_writers=1, sync_interval=12, sync_budget=256,
        sync_chunk=256, fanout_near=2, fanout_far=1, max_transmissions=5,
    )
    cfg = ClusterConfig(
        swim=SwimConfig(n_nodes=n, max_transmissions=5), gossip=g
    )
    topo = make_topology([n], [0], sync_interval=g.sync_interval)
    rounds = 40
    writes = np.zeros((rounds, 1), np.uint32)
    writes[:20, 0] = 4  # 80 versions while node 9 is down
    kill = np.zeros((rounds, n), bool)
    revive = np.zeros((rounds, n), bool)
    kill[0, 9] = True
    revive[30, 9] = True
    sched = Schedule(writes=writes, kill=kill, revive=revive).make_samples(8)
    final, curves = simulate(cfg, topo, sched, seed=3)
    # 80 versions committed; with sync_interval=12 and revival at round 30,
    # a cohort-only node might not sync before round 40 at all. The
    # rejoin session (budget 256 > 80) must have caught it up immediately.
    contig = np.asarray(final.data.contig)
    assert int(np.asarray(final.data.head)[0]) == 80
    assert int(contig[9, 0]) == 80, "revived node must catch up on rejoin"


def test_churn_32_detects_and_heals():
    cfg, topo, sched = models.churn_32(rounds=200, samples=32)
    final, curves = simulate(cfg, topo, sched, seed=1)
    m = curves["mismatches"]
    assert m.max() > 0, "churn must actually cause belief divergence"
    assert m[-1] == 0, "membership converges after the storm"
    # Data plane: writes from live writers still converge to live nodes.
    alive = np.asarray(final.swim.alive)
    contig = np.asarray(final.data.contig)[alive]
    heads = np.asarray(final.data.head)
    assert (contig == heads[None, :]).all()


def test_anti_entropy_small_scale():
    # Scaled-down config 3: sync must do the heavy lifting once broadcast
    # budgets are exhausted.
    cfg, topo, sched = models.anti_entropy_1k(n=64, burst=400, samples=64)
    final, curves = simulate(cfg, topo, sched, seed=2)
    heads = np.asarray(final.data.head)
    contig = np.asarray(final.data.contig)
    assert (contig == heads[None, :]).all()
    assert curves["applied_sync"].sum() > 0, "sync plane must participate"
    lat = visibility_latencies(final, sched, cfg)
    assert lat["unseen"] == 0


def test_wan_partition_small_scale():
    cfg, topo, sched = models.wan_100k(
        n=80, n_regions=4, n_writers=8, rounds=160, samples=32)
    final, curves = simulate(cfg, topo, sched, seed=3)
    heads = np.asarray(final.data.head)
    contig = np.asarray(final.data.contig)
    assert (contig == heads[None, :]).all(), "heal catches every region up"
    lat = visibility_latencies(final, sched, cfg)
    assert lat["unseen"] == 0
    # Partitioned-era writes must show elevated tail latency vs the floor.
    assert lat["p99_s"] > lat["p50_s"]


def test_metrics_curves_shape():
    cfg, topo, sched = models.three_node(n_inserts=48, samples=16)
    final, curves = simulate(cfg, topo, sched)
    for k in ("mismatches", "need", "applied_broadcast", "applied_sync",
              "msgs", "sessions", "cell_merges", "window_degraded",
              "sync_regrant"):
        assert curves[k].shape == (sched.rounds,), k


def test_checkpoint_resume_bit_identical(tmp_path):
    """Save mid-run, resume from disk: final state must be bit-identical to
    the uninterrupted run (per-round RNG folds the absolute round index, so
    chunked/resumed runs replay exactly — the sim's checkpoint/resume)."""
    import jax

    from corrosion_tpu.sim import checkpoint

    cfg, topo, sched = models.merge_10k(n=256, rounds=60, samples=32)
    full, _ = simulate(cfg, topo, sched, seed=9)

    first = Schedule(
        writes=sched.writes[:30], sample_writer=sched.sample_writer,
        sample_ver=sched.sample_ver, sample_round=sched.sample_round,
    )
    second = Schedule(
        writes=sched.writes[30:], sample_writer=sched.sample_writer,
        sample_ver=sched.sample_ver, sample_round=sched.sample_round,
    )
    mid, _ = simulate(cfg, topo, first, seed=9)
    checkpoint.save_state(str(tmp_path / "ckpt.npz"), mid)
    checkpoint.save_schedule(str(tmp_path / "trace.npz"), second)

    restored = checkpoint.load_state(
        str(tmp_path / "ckpt.npz"), cfg, len(sched.sample_writer)
    )
    replay = checkpoint.load_schedule(str(tmp_path / "trace.npz"))
    resumed, _ = simulate(cfg, topo, replay, seed=9, state=restored)

    for a, b in zip(jax.tree.leaves(full), jax.tree.leaves(resumed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Mismatched config must fail loudly, not mis-zip leaves.
    import dataclasses

    import pytest

    bad = dataclasses.replace(
        cfg, swim=dataclasses.replace(cfg.swim, view_capacity=8)
    )
    with pytest.raises(ValueError):
        checkpoint.load_state(
            str(tmp_path / "ckpt.npz"), bad, len(sched.sample_writer)
        )


def test_checkpoint_resume_with_live_window_bits(tmp_path):
    """Checkpoint taken while the out-of-order window holds bits (lossy
    run, mid-heal): resume must be bit-identical — the oo words and flag
    are replication state, not scratch."""
    import dataclasses

    import jax
    import pytest

    from corrosion_tpu.sim import checkpoint

    cfg, topo, sched = models.merge_10k(n=128, rounds=48, samples=16)
    cfg = dataclasses.replace(
        cfg, gossip=dataclasses.replace(cfg.gossip, loss_prob=0.35)
    )

    first = Schedule(
        writes=sched.writes[:17], sample_writer=sched.sample_writer,
        sample_ver=sched.sample_ver, sample_round=sched.sample_round,
    )
    second = Schedule(
        writes=sched.writes[17:], sample_writer=sched.sample_writer,
        sample_ver=sched.sample_ver, sample_round=sched.sample_round,
    )
    # Whether window bits are live at the cut depends on the platform's
    # RNG stream (jax folds backend/version into key derivation), so a
    # hard-coded seed flakes across environments. Scan a few seeds for
    # one that satisfies the precondition — the seed is a traced
    # argument, so every probe after the first reuses the compile.
    mid = None
    for seed in range(16):
        cand, _ = simulate(cfg, topo, first, seed=seed)
        if np.asarray(cand.data.oo).sum() > 0:
            mid = cand
            break
    if mid is None:
        pytest.skip(
            "no seed in 0..15 leaves live window bits at the cut round "
            "on this platform's RNG stream (precondition, not a bug)"
        )
    full, _ = simulate(cfg, topo, sched, seed=seed)
    checkpoint.save_state(str(tmp_path / "w.npz"), mid)
    restored = checkpoint.load_state(
        str(tmp_path / "w.npz"), cfg, len(sched.sample_writer)
    )
    resumed, _ = simulate(cfg, topo, second, seed=seed, state=restored)
    for a, b in zip(jax.tree.leaves(full), jax.tree.leaves(resumed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
