"""In-process multi-agent cluster tests over real TCP loopback.

Analogues of the reference's integration tests (SURVEY.md §4):
insert_rows_and_gossip (agent.rs:2780), large_tx_sync (agent.rs:3340), the
subscription end-to-end test (public/pubsub.rs test_api_v1_subs), and
shutdown hygiene via the counted-task registry.
"""

import asyncio

import pytest

from corrosion_tpu.agent.testing import launch_test_agent, poll_until
from corrosion_tpu.core.values import Statement


def run(coro):
    return asyncio.run(coro)


async def _query_count(ta, table="tests"):
    _, rows = await ta.client.query(f"SELECT count(*) FROM {table}")
    return rows[0][0]


def test_insert_rows_and_gossip(tmp_path):
    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        b = await launch_test_agent(
            str(tmp_path / "b"), bootstrap=[a.gossip_addr]
        )
        try:
            resp = await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (?, ?)", [1, "hello"]]]
            )
            assert resp["results"][0]["rows_affected"] == 1

            async def visible_on_b():
                _, rows = await b.client.query(
                    "SELECT id, text FROM tests WHERE id = 1"
                )
                return rows == [[1, "hello"]]

            await poll_until(visible_on_b)
            # Bookkeeping recorded the remote version on B (agent.rs:2884+).
            booked = b.agent.bookie.get(a.agent.actor_id)
            assert booked is not None and booked.last() == 1
            # And the reverse direction.
            await b.client.execute(
                [["INSERT INTO tests (id, text) VALUES (?, ?)", [2, "world"]]]
            )

            async def visible_on_a():
                _, rows = await a.client.query(
                    "SELECT count(*) FROM tests"
                )
                return rows[0][0] == 2

            await poll_until(visible_on_a)
        finally:
            await a.stop()
            await b.stop()
        assert a.agent.tasks.pending == 0, "counted tasks drained"

    run(main())


def test_late_joiner_catches_up_via_sync(tmp_path):
    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        try:
            for i in range(20):
                await a.client.execute(
                    [["INSERT INTO tests (id, text) VALUES (?, ?)",
                      [i, f"row{i}"]]]
                )
            # B joins after the writes: broadcasts are long gone; only
            # anti-entropy sync can deliver (the late-joiner scenario).
            b = await launch_test_agent(
                str(tmp_path / "b"), bootstrap=[a.gossip_addr]
            )
            try:
                await poll_until(
                    lambda: _query_count_is(b, 20), timeout=20.0
                )
            finally:
                await b.stop()
        finally:
            await a.stop()

    async def _query_count_is(ta, n):
        return await _query_count(ta) == n

    run(main())


def test_large_tx_sync_chunked(tmp_path):
    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        b = await launch_test_agent(
            str(tmp_path / "b"), bootstrap=[a.gossip_addr]
        )
        try:
            # One transaction inserting 2000 rows -> multiple 8 KiB chunks
            # (large_tx_sync, agent.rs:3340).
            stmts = [
                ["INSERT INTO tests (id, text) VALUES (?, ?)",
                 [i, "payload-" + "x" * 50]]
                for i in range(2000)
            ]
            resp = await a.client.execute(stmts)
            assert sum(r["rows_affected"] for r in resp["results"]) == 2000
            version = a.agent.bookie.get(a.agent.actor_id).last()
            assert version == 1

            async def converged():
                return await _query_count(b) == 2000

            await poll_until(converged, timeout=30.0)
            booked = b.agent.bookie.get(a.agent.actor_id)
            assert booked.contains(1)
        finally:
            await a.stop()
            await b.stop()

    run(main())


def test_three_node_concurrent_writers(tmp_path):
    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        b = await launch_test_agent(
            str(tmp_path / "b"), bootstrap=[a.gossip_addr]
        )
        c = await launch_test_agent(
            str(tmp_path / "c"), bootstrap=[a.gossip_addr]
        )
        agents = [a, b, c]
        try:
            for i, ta in enumerate(agents):
                for k in range(10):
                    await ta.client.execute(
                        [["INSERT INTO tests (id, text) VALUES (?, ?)"
                          " ON CONFLICT (id) DO UPDATE SET text = excluded.text",
                          [k, f"from-{i}"]]]
                    )

            async def all_converged():
                vals = []
                for ta in agents:
                    _, rows = await ta.client.query(
                        "SELECT id, text FROM tests ORDER BY id"
                    )
                    vals.append(rows)
                return all(v == vals[0] for v in vals) and len(vals[0]) == 10

            await poll_until(all_converged, timeout=30.0)
        finally:
            for ta in agents:
                await ta.stop()

    run(main())


def test_subscription_stream_end_to_end(tmp_path):
    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        b = await launch_test_agent(
            str(tmp_path / "b"), bootstrap=[a.gossip_addr]
        )
        try:
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (1, 'pre')"]]
            )
            sub = await b.client.subscribe("SELECT id, text FROM tests")
            # Wait until the pre-existing row lands on b (snapshot or change).
            seen = {}
            got_eoq = asyncio.Event()

            async def reader():
                async for ev in sub:
                    if "row" in ev:
                        seen[ev["row"][1][0]] = ev["row"][1][1]
                    elif "change" in ev:
                        kind, _rowid, cells, _cid = ev["change"]
                        if kind in ("insert", "update"):
                            seen[cells[0]] = cells[1]
                        else:
                            seen.pop(cells[0], None)
                    elif "eoq" in ev:
                        got_eoq.set()

            task = asyncio.ensure_future(reader())
            # A remote write must flow: a -> gossip -> b -> matcher -> stream.
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (2, 'live')"]]
            )

            async def got_both():
                return seen.get(1) == "pre" and seen.get(2) == "live"

            await poll_until(got_both, timeout=20.0)
            assert got_eoq.is_set()
            assert sub.sub_id is not None
            task.cancel()
            sub.close()
        finally:
            await a.stop()
            await b.stop()

    run(main())


def test_subscription_catch_up_from_change_id(tmp_path):
    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        try:
            handle = a.agent.subs.subscribe("SELECT id, text FROM tests")
            sub_id = handle.id
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (1, 'one')"]]
            )
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (2, 'two')"]]
            )

            async def two_changes():
                return handle.change_id >= 2

            await poll_until(two_changes)
            # Exclusive resume: from=1 replays only events after change 1.
            sub = await a.client.resubscribe(sub_id, from_change=1)
            events = []
            async for ev in sub:
                events.append(ev)
                if "change" in ev and ev["change"][3] >= 2:
                    break
            sub.close()
            changes = [e for e in events if "change" in e]
            assert changes and all(c["change"][3] >= 2 for c in changes)
        finally:
            await a.stop()

    run(main())


def test_query_error_and_migration(tmp_path):
    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        try:
            from corrosion_tpu.client import ApiError

            with pytest.raises(ApiError):
                await a.client.execute([["INSERT INTO nosuch VALUES (1)"]])
            out = await a.client.schema(
                ["CREATE TABLE extra (id INTEGER NOT NULL PRIMARY KEY, v TEXT);",
                 TEST_SCHEMA]
            )
            assert out["changed"] == ["extra"]
            await a.client.execute(
                [["INSERT INTO extra (id, v) VALUES (1, 'x')"]]
            )
            _, rows = await a.client.query("SELECT v FROM extra")
            assert rows == [["x"]]
        finally:
            await a.stop()

    from corrosion_tpu.agent.testing import TEST_SCHEMA

    run(main())


def test_subscription_restored_after_restart(tmp_path):
    """Persisted subs are recreated at boot with their change-id watermark
    (agent.rs:373-419 + Matcher::restore); a subscriber resuming past the
    watermark gets a snapshot restart instead of silent event loss."""

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        handle_id = None
        try:
            handle = a.agent.subs.subscribe("SELECT id, text FROM tests")
            handle_id = handle.id
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (1, 'one')"]]
            )
            await poll_until(
                lambda: _ready(a, handle_id), timeout=10
            )
            assert a.agent.subs.get(handle_id).change_id >= 1
        finally:
            await a.stop()

        b = await launch_test_agent(str(tmp_path / "a"))
        try:
            restored = b.agent.subs.get(handle_id)
            assert restored is not None, "sub must survive restart"
            assert restored.sql == "SELECT id, text FROM tests"
            assert restored.change_id >= 1  # watermark restored
            assert restored.rows  # snapshot restored from the sub-db
            # Durable history: resume from 0 REPLAYS the pre-restart
            # events from the sub-db instead of a snapshot restart
            # (pubsub.rs:806-841 sub-db semantics).
            events = restored.backlog(from_change=0)
            kinds = [e.to_json_obj() for e in events]
            assert any("columns" in k for k in kinds)
            assert any(
                "change" in k and k["change"][0] == "insert" for k in kinds
            ), "pre-restart events must replay from the durable log"
            assert not any("eoq" in k for k in kinds)  # not a snapshot
            # New changes keep numbering past the restored watermark.
            before = restored.change_id
            await b.client.execute(
                [["INSERT INTO tests (id, text) VALUES (2, 'two')"]]
            )
            await poll_until(
                lambda: _past(b, handle_id, before), timeout=10
            )
        finally:
            await b.stop()

    async def _ready(agent, sid):
        h = agent.agent.subs.get(sid)
        return h is not None and h.change_id >= 1

    async def _past(agent, sid, before):
        return agent.agent.subs.get(sid).change_id > before

    run(main())


def test_stress_many_agents_randomized(tmp_path):
    """stress_test analogue (agent.rs:3009): a 10-agent cluster bootstrapped
    randomly, statements fired at random agents in concurrent chunks, then
    every agent polled until the cluster-wide CRDT state converges."""
    import random

    async def main():
        rng = random.Random(11)
        agents = []
        try:
            first = await launch_test_agent(str(tmp_path / "a0"))
            agents.append(first)
            for i in range(1, 10):
                peers = [rng.choice(agents).gossip_addr]
                agents.append(
                    await launch_test_agent(
                        str(tmp_path / f"a{i}"), bootstrap=peers,
                        sync_interval=0.4,
                    )
                )

            async def fire(stmt_id: int):
                target = rng.choice(agents)
                await target.client.execute(
                    [["INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)",
                      [stmt_id % 40, f"v{stmt_id}"]]]
                )

            # 150 statements in chunks of 10 concurrent.
            for base in range(0, 150, 10):
                await asyncio.gather(*[fire(base + j) for j in range(10)])

            async def converged():
                digests = set()
                for t in agents:
                    _, rows = t.agent.store.query(Statement(
                        "SELECT group_concat(id || '=' || text, ',') FROM"
                        " (SELECT id, text FROM tests ORDER BY id)"
                    ))
                    digests.add(rows[0][0])
                return len(digests) == 1 and rows[0][0] is not None

            await poll_until(converged, timeout=60, interval=0.5)
            # Convergence must be to the LWW winner per row, identically
            # everywhere — digest equality across 10 agents already implies
            # it; sanity-check row count too.
            _, rows = agents[0].agent.store.query(
                Statement("SELECT count(*) FROM tests")
            )
            assert rows[0][0] == 40
        finally:
            await asyncio.gather(
                *[t.stop() for t in agents], return_exceptions=True
            )

    run(main())


def test_subscription_semicolon_and_limit_membership(tmp_path):
    """Regression: a trailing ';' in the subscribed SQL must not break the
    candidate path, and LIMIT queries must keep full-diff semantics (a row
    evicted from the window without its own PK changing must be deleted)."""

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        try:
            # Trailing semicolon + WHERE tail → candidate path must work.
            h = a.agent.subs.subscribe("SELECT id, text FROM tests WHERE id > 0;")
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (1, 'x')"]]
            )

            async def got_insert():
                return any(
                    ev.kind == "insert" for ev in h.history
                )

            await poll_until(got_insert, timeout=10)

            # LIMIT window: inserting a smaller id evicts the old row; the
            # eviction must be emitted even though its PK never changed.
            h2 = a.agent.subs.subscribe(
                "SELECT id, text FROM tests2 ORDER BY id LIMIT 1"
            )
            assert not h2._local_membership
            await a.client.execute(
                [["INSERT INTO tests2 (id, text) VALUES (5, 'five')"]]
            )

            async def window_has_five():
                return list(h2.rows) == [(5,)]

            await poll_until(window_has_five, timeout=10)
            await a.client.execute(
                [["INSERT INTO tests2 (id, text) VALUES (2, 'two')"]]
            )

            async def window_swapped():
                return list(h2.rows) == [(2,)]

            await poll_until(window_swapped, timeout=10)
            kinds = [ev.kind for ev in h2.history]
            assert "delete" in kinds, kinds  # the evicted row was emitted
        finally:
            await a.stop()

    run(main())


def test_api_concurrency_load_shed(tmp_path):
    """P8 admission control: over-limit requests shed with 503 instead of
    queueing (the reference's per-route ConcurrencyLimit + load-shed,
    agent.rs:836-902; migrations get their own, smaller limit)."""

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"), api_concurrency=2)
        try:
            # Long-lived subscription STREAMS do not hold admission slots:
            # the reference's ConcurrencyLimitLayer releases its permit when
            # the handler returns, before the body streams — the N+1th
            # subscriber must work, not shed (tower semantics,
            # agent.rs:836-902).
            s1 = await a.client.subscribe("SELECT id FROM tests")
            s2 = await a.client.subscribe("SELECT text FROM tests")
            s3 = await a.client.subscribe("SELECT id, text FROM tests")
            from corrosion_tpu.client import ApiError

            # The limit bounds request SETUP: with both slots held by
            # in-flight setups, the next request sheds 503.
            limit = a.agent._api_limits["/v1/subscriptions"]
            limit.__enter__()
            limit.__enter__()
            try:
                await a.client.subscribe("SELECT text FROM tests2")
                raise AssertionError("over-limit setup should shed")
            except ApiError as e:
                assert e.status == 503
            finally:
                limit.__exit__()
                limit.__exit__()
            # Other routes have their own limits: writes still work even
            # while the subscriptions route is saturated.
            limit.__enter__()
            limit.__enter__()
            try:
                resp = await a.client.execute(
                    [["INSERT INTO tests (id, text) VALUES (1, 'x')"]]
                )
                assert resp["results"][0]["rows_affected"] == 1
            finally:
                limit.__exit__()
                limit.__exit__()
            s1.close()
            s2.close()
            s3.close()
        finally:
            await a.stop()

    run(main())


def test_subscription_window_function_full_diff(tmp_path):
    """A window function's value on UNCHANGED rows shifts when other rows
    change, so such queries must keep full-diff semantics — the candidate
    path would leave stale row_number values behind."""

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        try:
            h = a.agent.subs.subscribe(
                "SELECT id, row_number() OVER (ORDER BY id) FROM tests"
                " WHERE id > 0"
            )
            assert not h._local_membership
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (5, 'x')"],
                 ["INSERT INTO tests (id, text) VALUES (7, 'y')"]]
            )

            async def two_rows():
                return sorted(h.rows.values()) == [(5, 1), (7, 2)]

            await poll_until(two_rows, timeout=10)
            # Inserting a smaller id renumbers BOTH existing rows.
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (1, 'z')"]]
            )

            async def renumbered():
                return sorted(h.rows.values()) == [(1, 1), (5, 2), (7, 3)]

            await poll_until(renumbered, timeout=10)
        finally:
            await a.stop()

    run(main())


def test_bootstrap_announcer_retries_until_join(tmp_path):
    """A node whose seed name resolves only LATER must still join (the
    announcer loop re-resolves with backoff, agent.rs:726-768)."""
    import socket

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        real = a.gossip_addr
        published = False
        orig = socket.getaddrinfo

        def fake(host, port, type=0, *args, **kw):
            if host == "seed.later":
                if not published:
                    raise socket.gaierror("NXDOMAIN")
                return [(socket.AF_INET, socket.SOCK_STREAM, 6, "",
                         (real[0], port))]
            return orig(host, port, type, *args, **kw)

        socket.getaddrinfo = fake
        try:
            b = await launch_test_agent(
                str(tmp_path / "b"),
                bootstrap_raw=[f"seed.later:{real[1]}@dns"],
            )
            await asyncio.sleep(0.3)
            assert not b.agent.members.alive(), "must not join before DNS"
            published = True

            async def joined():
                return bool(b.agent.members.alive())

            await poll_until(joined, timeout=30)
            await b.stop()
        finally:
            socket.getaddrinfo = orig
            await a.stop()

    run(main())


def test_subscription_replays_events_missed_while_down(tmp_path):
    """The verdict's durable-history acceptance test: a subscriber that
    disconnects, misses writes across an agent RESTART, and reconnects
    with ?from= receives the missed events — not a snapshot restart
    (pubsub.rs:735-771 restore + 806-841 durable sub-db)."""

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        try:
            handle = a.agent.subs.subscribe("SELECT id, text FROM tests")
            handle_id = handle.id
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (1, 'one')"]]
            )

            async def seen():
                h = a.agent.subs.get(handle_id)
                return h is not None and h.change_id >= 1

            await poll_until(seen, timeout=10)
            resume_from = a.agent.subs.get(handle_id).change_id
        finally:
            await a.stop()

        # Mutate the data while "down" via a second agent instance on the
        # same dir (simulates changes the subscriber missed: an insert, an
        # update, and a delete).
        b = await launch_test_agent(str(tmp_path / "a"))
        try:
            # Separate transactions: same-batch insert+delete of one row
            # coalesces to no event (batch-level diffing, like the
            # reference's per-batch handle_candidates).
            await b.client.execute(
                [["INSERT INTO tests (id, text) VALUES (2, 'two')"]]
            )
            await b.client.execute(
                [["UPDATE tests SET text = 'ONE' WHERE id = 1"]]
            )
            await b.client.execute([["DELETE FROM tests WHERE id = 2"]])

            async def advanced():
                h = b.agent.subs.get(handle_id)
                return h is not None and h.change_id > resume_from

            await poll_until(advanced, timeout=10)
            restored = b.agent.subs.get(handle_id)
            events = restored.backlog(from_change=resume_from)
            objs = [e.to_json_obj() for e in events]
            changes = [o["change"] for o in objs if "change" in o]
            kinds = [c[0] for c in changes]
            # The missed insert/update/delete all replay, in order, with
            # monotonically increasing change ids after the resume point.
            assert "insert" in kinds and "update" in kinds and "delete" in kinds
            ids = [c[3] for c in changes]
            assert ids == sorted(ids) and ids[0] > resume_from
            assert not any("eoq" in o for o in objs), "must not snapshot-restart"
        finally:
            await b.stop()

    run(main())


def test_join_subscription_pk_identity(tmp_path):
    """Join subscriptions keep per-result-row PK identity (the Matcher's
    multi-table PK aliasing, pubsub.rs:566-661): a cell update on either
    side emits an UPDATE (not a delete+insert pair), one-to-many joins
    keep distinct row identities, and candidate diffing — not a full
    re-evaluation — serves join batches."""

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        try:
            h = a.agent.subs.subscribe(
                "SELECT a.text, b.text FROM tests a"
                " JOIN tests2 b ON a.id = b.id"
            )
            assert h._pk_segments is not None, "join PK aliasing must engage"
            assert h._local_membership

            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (1, 'l1')"]]
            )
            await a.client.execute(
                [["INSERT INTO tests2 (id, text) VALUES (1, 'r1')"]]
            )

            async def joined():
                return any(ev.kind == "insert" for ev in h.history)

            await poll_until(joined, timeout=10)

            # Cell update on the RIGHT side: must surface as an update of
            # the same row identity, not delete+insert.
            n_before = len(h.history)
            await a.client.execute(
                [["UPDATE tests2 SET text = 'r1b' WHERE id = 1"]]
            )

            async def updated():
                new = list(h.history)[n_before:]
                return any(ev.kind == "update" for ev in new)

            await poll_until(updated, timeout=10)
            new = list(h.history)[n_before:]
            assert not any(ev.kind == "delete" for ev in new), new
            assert [list(h.rows.values())[0]] == [("l1", "r1b")]

            # One-to-many: a second right-side row for the same left row
            # creates a NEW identity (insert), leaving the first row alone.
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (2, 'l2')"]]
            )
            await a.client.execute(
                [["INSERT INTO tests2 (id, text) VALUES (2, 'r2')"]]
            )

            async def two_rows():
                return len(h.rows) == 2

            await poll_until(two_rows, timeout=10)
            assert len(set(h.rowids.values())) == 2

            # Right-side delete removes exactly its join row.
            await a.client.execute([["DELETE FROM tests2 WHERE id = 1"]])

            async def one_left():
                return len(h.rows) == 1

            await poll_until(one_left, timeout=10)
            assert list(h.rows.values()) == [("l2", "r2")]
            kinds = [ev.kind for ev in h.history]
            assert "delete" in kinds
        finally:
            await a.stop()

    run(main())


def test_join_subscription_large_sub_uses_candidate_path(tmp_path):
    """Cost pin for joined subs (VERDICT weak #4): on a large joined
    result set, a small change batch must go through candidate diffing,
    never a full re-evaluation."""
    from corrosion_tpu.agent.agent import Agent, AgentConfig
    from corrosion_tpu.agent.subs import SubsManager
    from corrosion_tpu.agent.testing import TEST_SCHEMA

    a = Agent(AgentConfig(data_dir=str(tmp_path / "a"), schema_sql=TEST_SCHEMA))
    a.subs = SubsManager(a.store)
    try:
        stmts = []
        for i in range(500):
            stmts.append(
                Statement("INSERT INTO tests (id, text) VALUES (?, ?)",
                          params=[i, f"l{i}"])
            )
            stmts.append(
                Statement("INSERT INTO tests2 (id, text) VALUES (?, ?)",
                          params=[i, f"r{i}"])
            )
        a.execute(stmts)
        h = a.agent_subscribe = a.subs.subscribe(
            "SELECT a.id, b.text FROM tests a JOIN tests2 b ON a.id = b.id"
        )
        assert len(h.rows) == 500
        evals = 0
        orig = h._evaluate

        def counting():
            nonlocal evals
            evals += 1
            return orig()

        h._evaluate = counting
        a.execute(
            [Statement("UPDATE tests2 SET text = 'bump' WHERE id = 250")]
        )
        assert evals == 0, "small join batch must not full-re-evaluate"
        assert h.rows[(250, 250)] == (250, "bump")
        kinds = [ev.kind for ev in h.history]
        assert kinds[-1] == "update"
    finally:
        a.store.close()


def test_members_persist_and_rejoin_without_bootstrap(tmp_path):
    """diff_member_states parity (broadcast/mod.rs:570-702 + agent.rs:772-
    831): member states persist to __corro_members on a cadence, and a
    restarted agent rejoins its cluster from them with NO bootstrap seeds."""
    async def main():
        a = await launch_test_agent(
            str(tmp_path / "a"), probe_interval=0.1,
            member_persist_interval=0.2,
        )
        b = await launch_test_agent(
            str(tmp_path / "b"), bootstrap=[a.gossip_addr],
            probe_interval=0.1, member_persist_interval=0.2,
        )
        try:
            async def persisted():
                rows = b.agent.store.conn.execute(
                    "SELECT actor_id, state FROM __corro_members"
                ).fetchall()
                return any(r[0] == a.agent.actor_id for r in rows)

            await poll_until(persisted, timeout=10.0)
        finally:
            await b.stop()

        # Restart b with NO bootstrap: it must rejoin via the persisted
        # member table (a's gossip addr is stable here).
        b2 = await launch_test_agent(
            str(tmp_path / "b"), probe_interval=0.1,
            member_persist_interval=0.2,
        )
        try:
            assert b2.agent.cfg.bootstrap == []

            async def rejoined():
                return any(
                    m.actor_id == a.agent.actor_id
                    for m in b2.agent.members.alive()
                ) and any(
                    m.actor_id == b2.agent.actor_id
                    for m in a.agent.members.alive()
                )

            await poll_until(rejoined, timeout=10.0)
        finally:
            await b2.stop()
            await a.stop()

    run(main())


def test_subscription_reconnect_resumes_across_agent_restart(tmp_path):
    """client.py::SubscriptionStream.reconnect under a mid-stream agent
    restart: the durable sub-db makes ``?from=<last change id>`` valid
    across restarts, so the resumed stream carries on with no duplicate
    and no missed events and strictly monotonic change ids."""

    from corrosion_tpu.loadgen.oracle import FanoutOracle

    async def main():
        data = str(tmp_path / "a")
        a = await launch_test_agent(data)
        host, port = a.agent.api_addr
        oracle = FanoutOracle()
        sid = oracle.attach_stream()
        stream = await a.client.subscribe("SELECT id, text FROM tests")

        async def pull_until(pred, timeout=10.0):
            async def go():
                while True:
                    ev = await stream.__anext__()
                    if "change" in ev:
                        kind, _rowid, cells, cid = ev["change"]
                        oracle.change(
                            sid, kind, cells[0], tuple(cells[1:]), cid, 0.0
                        )
                    elif "row" in ev:
                        _rowid, cells = ev["row"]
                        oracle.snapshot_row(
                            sid, cells[0], tuple(cells[1:])
                        )
                    if pred(ev):
                        return ev
            return await asyncio.wait_for(go(), timeout)

        await pull_until(lambda ev: "eoq" in ev)
        oracle.snapshot_done(sid, 0.0)

        async def write(client, i):
            await client.execute(
                [["INSERT INTO tests (id, text) VALUES (?, ?)",
                  [i, f"w{i}"]]]
            )
            oracle.commit(i, (f"w{i}",), t_ack=0.0)

        for i in range(3):
            await write(a.client, i)
        await pull_until(
            lambda ev: "change" in ev and ev["change"][2][0] == 2
        )
        assert stream.last_change_id == 3
        await a.stop()

        # Restart on the SAME data dir and API port; the persisted
        # subscription (and its durable change log) must come back.
        b = await launch_test_agent(data, api_port=port)
        try:
            assert b.agent.api_addr[1] == port
            for i in range(3, 6):
                await write(b.client, i)
            await stream.reconnect(retries=20)
            await pull_until(
                lambda ev: "change" in ev and ev["change"][2][0] == 5
            )
            rep = oracle.finish()
            assert rep["violations"] == 0, rep["violation_examples"]
            assert rep["missing"] == 0
            # The resumed stream replayed EXACTLY the post-restart
            # events: ids kept climbing past the pre-restart watermark.
            assert stream.last_change_id == 6
        finally:
            stream.close()
            await b.stop()

    run(main())
