"""Unit tests for core pure types: intervals, HLC, values, chunker, backoff."""

import pytest

from corrosion_tpu.core.changes import chunk_changes
from corrosion_tpu.core.hlc import (
    HLC,
    ClockDriftError,
    make_ts,
    ts_from_string,
    ts_logical,
    ts_physical_ms,
    ts_to_string,
)
from corrosion_tpu.core.ids import Actor, ActorId
from corrosion_tpu.core.intervals import RangeMap, RangeSet
from corrosion_tpu.core.values import (
    Change,
    Statement,
    pack_columns,
    unpack_columns,
    value_cmp_key,
)
from corrosion_tpu.utils.backoff import Backoff


class TestRangeSet:
    def test_insert_coalesce(self):
        rs = RangeSet()
        rs.insert(1, 3)
        rs.insert(5, 7)
        assert list(rs) == [(1, 3), (5, 7)]
        rs.insert(4, 4)  # adjacent on both sides -> merge all
        assert list(rs) == [(1, 7)]

    def test_insert_overlap(self):
        rs = RangeSet([(1, 5), (10, 20)])
        rs.insert(3, 12)
        assert list(rs) == [(1, 20)]

    def test_contains_and_gaps(self):
        rs = RangeSet([(1, 3), (7, 9)])
        assert rs.contains(2) and rs.contains(7)
        assert not rs.contains(5)
        assert rs.contains_range(7, 9)
        assert not rs.contains_range(2, 8)
        assert list(rs.gaps(0, 12)) == [(0, 0), (4, 6), (10, 12)]
        assert list(rs.gaps(1, 3)) == []

    def test_remove_splits(self):
        rs = RangeSet([(1, 10)])
        rs.remove(4, 6)
        assert list(rs) == [(1, 3), (7, 10)]
        rs.remove(0, 2)
        assert list(rs) == [(3, 3), (7, 10)]
        rs.remove(3, 100)
        assert list(rs) == []

    def test_total(self):
        rs = RangeSet([(1, 3), (10, 10)])
        assert rs.total() == 4


class TestRangeMap:
    def test_insert_overwrites_overlap(self):
        rm = RangeMap()
        rm.insert(1, 10, "a")
        rm.insert(4, 6, "b")
        assert list(rm) == [(1, 3, "a"), (4, 6, "b"), (7, 10, "a")]

    def test_coalesce_equal_values(self):
        rm = RangeMap()
        rm.insert(1, 3, "a")
        rm.insert(4, 6, "a")
        assert list(rm) == [(1, 6, "a")]
        rm.insert(4, 5, "a")
        assert list(rm) == [(1, 6, "a")]

    def test_get(self):
        rm = RangeMap([(1, 5, "x"), (8, 9, "y")])
        assert rm.get(3) == "x"
        assert rm.get(6) is None
        assert rm.get_range(8) == (8, 9, "y")

    def test_overwrite_spanning_multiple(self):
        rm = RangeMap([(1, 2, "a"), (4, 5, "b"), (7, 8, "c")])
        rm.insert(2, 7, "z")
        assert list(rm) == [(1, 1, "a"), (2, 7, "z"), (8, 8, "c")]

    def test_remove(self):
        rm = RangeMap([(1, 10, "a")])
        rm.remove(3, 4)
        assert list(rm) == [(1, 2, "a"), (5, 10, "a")]


class TestHLC:
    def test_monotonic(self):
        clock = HLC()
        seen = [clock.new_timestamp() for _ in range(100)]
        assert seen == sorted(set(seen))

    def test_merge_remote(self):
        clock = HLC()
        t0 = clock.new_timestamp()
        remote = t0 + (50 << 20)  # 50ms ahead: within the 300ms drift bound
        clock.update_with_timestamp(remote)
        assert clock.new_timestamp() > remote

    def test_drift_rejected(self):
        clock = HLC(max_delta_ms=300)
        way_ahead = make_ts(ts_physical_ms(clock.new_timestamp()) + 10_000)
        with pytest.raises(ClockDriftError):
            clock.update_with_timestamp(way_ahead)

    def test_string_roundtrip(self):
        ts = make_ts(123456789, 42)
        assert ts_from_string(ts_to_string(ts)) == ts
        assert ts_logical(ts) == 42


class TestValues:
    def test_pack_roundtrip(self):
        cases = [
            (),
            (None,),
            (1, -1, 0, 2**40, -(2**40)),
            (3.14, -0.0),
            ("hello", "", "日本語"),
            (b"\x00\xff", b""),
            (None, 7, 2.5, "x", b"y"),
        ]
        for vals in cases:
            assert unpack_columns(pack_columns(vals)) == vals

    def test_pack_deterministic_key(self):
        assert pack_columns((1, "a")) == pack_columns((1, "a"))
        assert pack_columns((1, "a")) != pack_columns(("a", 1))

    def test_value_order(self):
        ordered = [None, -5, 1.5, 2, "a", "b", b"a"]
        keys = [value_cmp_key(v) for v in ordered]
        assert keys == sorted(keys)

    def test_statement_parse_forms(self):
        assert Statement.parse("SELECT 1").sql == "SELECT 1"
        s = Statement.parse(["INSERT INTO t VALUES (?)", [1]])
        assert s.params == [1]
        s = Statement.parse(["INSERT INTO t VALUES (:a)", {"a": 2}])
        assert s.named_params == {"a": 2}


def _mkchange(seq, val="v"):
    return Change(
        table="t",
        pk=pack_columns((seq,)),
        cid="c",
        val=val,
        col_version=1,
        db_version=1,
        seq=seq,
        site_id=b"\x00" * 16,
        cl=1,
    )


class TestChunker:
    def test_single_chunk(self):
        rows = [_mkchange(i) for i in range(3)]
        chunks = list(chunk_changes(rows, last_seq=2))
        assert len(chunks) == 1
        changes, (lo, hi) = chunks[0]
        assert len(changes) == 3 and (lo, hi) == (0, 2)

    def test_chunks_tile_seq_space(self):
        rows = [_mkchange(i, val="x" * 100) for i in range(100)]
        chunks = list(chunk_changes(rows, last_seq=99, max_bytes=500))
        # ranges must tile [0, 99] contiguously
        cursor = 0
        for _, (lo, hi) in chunks:
            assert lo == cursor
            cursor = hi + 1
        assert cursor == 100

    def test_empty_covers_range(self):
        chunks = list(chunk_changes([], last_seq=5))
        assert chunks == [([], (0, 5))]

    def test_sparse_seqs_no_holes(self):
        rows = [_mkchange(s, val="x" * 300) for s in (0, 5, 9)]
        chunks = list(chunk_changes(rows, last_seq=9, max_bytes=400))
        cursor = 0
        for _, (lo, hi) in chunks:
            assert lo == cursor
            cursor = hi + 1
        assert cursor == 10


class TestBackoff:
    def test_growth_and_cap(self):
        b = Backoff(min_wait=1, max_wait=8, factor=2, jitter=False, max_retries=6)
        assert list(b) == [1, 2, 4, 8, 8, 8]

    def test_jitter_bounds(self):
        b = Backoff(min_wait=1, max_wait=10, factor=2, jitter=True, max_retries=20)
        for w in b:
            assert 1 <= w <= 10


class TestIds:
    def test_actor_id(self):
        a = ActorId.random()
        assert len(a.bytes) == 16
        assert ActorId.from_hex(a.hex) == a
        assert 0 <= a.to_node_index(100) < 100

    def test_actor_renew_wins(self):
        a = Actor(ActorId.random(), ("127.0.0.1", 1000), ts=5)
        b = a.renew(ts=6)
        assert b.wins_over(a) and not a.wins_over(b)
        assert a.same_node(b)


class TestMalformedBlobs:
    def test_truncated_blob_raises(self):
        from corrosion_tpu.core.values import MalformedBlobError

        good = pack_columns(("hello world",))
        with pytest.raises(MalformedBlobError):
            unpack_columns(good[:-4])

    def test_truncated_varint_and_overflow(self):
        from corrosion_tpu.core.values import MalformedBlobError

        with pytest.raises(MalformedBlobError):
            unpack_columns(b"\x01\x80")
        with pytest.raises(MalformedBlobError):
            unpack_columns(b"\x03" + b"\x80" * 40 + b"\x01")
        with pytest.raises(MalformedBlobError):
            unpack_columns(b"\x02\x00")  # truncated real
        with pytest.raises(MalformedBlobError):
            unpack_columns(b"\x09")  # bad tag

    def test_out_of_i64_int_rejected(self):
        with pytest.raises(ValueError):
            pack_columns((2**100,))
        assert unpack_columns(pack_columns((2**63 - 1, -(2**63)))) == (
            2**63 - 1,
            -(2**63),
        )

    def test_statement_malformed_rejected(self):
        with pytest.raises(ValueError):
            Statement.parse(["sql", [1], "junk"])
        with pytest.raises(ValueError):
            Statement.parse(["sql", 42])

    def test_utf8_byte_size(self):
        c = _mkchange(0, val="日" * 100)
        assert c.estimated_byte_size() >= 300
