"""Adaptive dissemination plane tests (docs/PERFORMANCE.md "Adaptive
dissemination").

Pins the three config-gated mechanisms against the measured 97%
redundant-delivery waste (ISSUE 20 / ROADMAP item 2):

- (a) feedback-based rumor death (``rumor_kill_k``): the Demers counter
  kill's two feedback signals — receiver-side (a delivered copy matches
  the node's own pending entry) and sender-side (a redundant delivery
  scatters a hit back to the SOURCE's queue slot) — at deterministic
  two-node scale, plus the same-round slot-free regression: a kill must
  free its ``rebroadcast_intake`` slot in the SAME round's rebuild, not
  leak it for a round.
- (b) push->pull phase switching (``pull_switch_age``): saturated nodes
  stop pulling on their far slots and escalate through the sync plane;
  the mechanism stays inert (zero ``prop_pull_rounds``) while no node
  saturates.
- (c) age-targeted forwarding (``age_forward``): intake priority by the
  rumor-age bins — pinned to share the propagation plane's binning
  (AGE_FORWARD_EDGES == telemetry.RUMOR_AGE_EDGES).

Plus the plane-wide contracts: rumor-mass conservation (useful + dup ==
msgs, age-hist mass == vis_count, link mass == msgs) under each
mechanism alone and composed, under churn + injected loss; the
mechanism counters are exactly zero when disabled; a neutral-threshold
kill config is bit-identical to the off config (the machinery is
observation-only until the threshold); sparse and mixed engines thread
the counters with the same identities; and the measured geo win itself
(adaptive dup share well below push at preserved convergence).
"""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.obs import epidemic
from corrosion_tpu.ops import gossip
from corrosion_tpu.sim import health, simulate
from corrosion_tpu.sim import telemetry as T

ADAPTIVE = dict(health.ADAPTIVE_GOSSIP)

MECHS = {
    "kill": {"rumor_kill_k": 2},
    "pull": {"pull_switch_age": 2},
    "age": {"age_forward": True},
    "composed": ADAPTIVE,
    "composed_sketch": {**ADAPTIVE, "sync_sketch_buckets": 8},
}


def _geo_run(nodes=64, rounds=32, seed=0, gossip_kw=None, **sched_kw):
    cfg, topo, sched, _ = health.churned_demo_cluster(
        nodes=nodes, rounds=rounds, samples=32, churn=True, seed=seed,
        geo=True,
    )
    if gossip_kw:
        cfg = replace(cfg, gossip=replace(cfg.gossip, **gossip_kw))
    for k, v in sched_kw.items():
        setattr(sched, k, v)
    final, curves = simulate(cfg, topo, sched, seed=seed)
    return cfg, final, curves


def _mass(curves, keys):
    return sum(np.asarray(curves[k], np.float64) for k in keys)


# ---------------------------------------------------------------------------
# Conservation + counters under each mechanism, composed, churn + loss


@pytest.mark.parametrize("mech", sorted(MECHS), ids=sorted(MECHS))
def test_conservation_under_mechanism_with_churn_and_loss(mech):
    """The propagation plane's conservation identities are invariant
    under every adaptive mechanism alone and composed, with the churn
    wave AND injected per-region + probe loss in the same schedule —
    killing or suppressing rumors changes how many copies flow, never
    the accounting that partitions them."""
    rng = np.random.default_rng(3)
    rounds = 32
    loss = (rng.random((rounds, health.GEO_REGIONS)) * 0.35).astype(
        np.float32
    )
    probe = (rng.random(rounds) * 0.25).astype(np.float32)
    _, _, curves = _geo_run(
        rounds=rounds, seed=3, gossip_kw=MECHS[mech], loss=loss,
        probe_loss=probe,
    )
    np.testing.assert_array_equal(
        _mass(curves, T.LINK_CURVE_KEYS), curves["msgs"]
    )
    np.testing.assert_array_equal(
        _mass(curves, T.RUMOR_AGE_KEYS), curves["vis_count"]
    )
    np.testing.assert_array_equal(
        curves["prop_useful_msgs"] + curves["prop_dup_msgs"],
        curves["msgs"],
    )
    ok, problems = epidemic.conservation_checks(curves)
    assert ok, problems
    assert curves["chaos_lost_msgs"].sum() > 0  # the loss really fired
    # Mechanism counters fire iff their mechanism is on.
    kills = float(np.asarray(curves["prop_rumor_kills"]).sum())
    pulls = float(np.asarray(curves["prop_pull_rounds"]).sum())
    if MECHS[mech].get("rumor_kill_k"):
        assert kills > 0, "kill mechanism armed but never fired"
    else:
        assert kills == 0
    if not MECHS[mech].get("pull_switch_age"):
        assert pulls == 0
    elif not MECHS[mech].get("rumor_kill_k"):
        # Pure pull: saturation must fire. Composed runs may
        # legitimately never saturate — the kill retires entries
        # before they age past the switch threshold.
        assert pulls > 0, "pull switch armed but never fired"


def test_counters_zero_when_disabled():
    """Satellite pin: the new PROP_CURVE_KEYS counters exist on every
    run and are exactly zero under a default (non-adaptive) config."""
    _, _, curves = _geo_run()
    assert "prop_rumor_kills" in curves and "prop_pull_rounds" in curves
    assert float(np.asarray(curves["prop_rumor_kills"]).sum()) == 0
    assert float(np.asarray(curves["prop_pull_rounds"]).sum()) == 0


# ---------------------------------------------------------------------------
# The measured win, at test scale


def test_adaptive_cuts_redundancy_at_preserved_convergence():
    """The tentpole's claim at in-suite scale: on the geo churn
    scenario the committed ADAPTIVE_GOSSIP tuning removes a large
    share of redundant copies and still converges (need drains to
    zero). The full-size CI gate (96x48, dup <= 0.80, equal-or-better
    TTC) lives in scripts/epidemic_smoke.py --compare against the
    bench_budget.json ``dissemination`` entry."""
    _, _, push = _geo_run(seed=1)
    _, _, ada = _geo_run(seed=1, gossip_kw=ADAPTIVE)

    def _dup_share(curves):
        msgs = float(np.asarray(curves["msgs"], np.float64).sum())
        dup = float(np.asarray(curves["prop_dup_msgs"], np.float64).sum())
        return dup / msgs

    assert float(np.asarray(push["need"])[-1]) == 0
    assert float(np.asarray(ada["need"])[-1]) == 0
    assert float(np.asarray(ada["mismatches"])[-1]) == 0
    push_dup, ada_dup = _dup_share(push), _dup_share(ada)
    assert ada_dup < push_dup - 0.10, (push_dup, ada_dup)
    # Fewer copies overall, not just a better ratio.
    assert (
        float(np.asarray(ada["msgs"], np.float64).sum())
        < 0.5 * float(np.asarray(push["msgs"], np.float64).sum())
    )


def test_adaptive_halves_exchange_capacity():
    """The wire-bytes half of the tentpole (docs/PERFORMANCE.md
    "Adaptive dissemination"): the shard driver's queue exchange is
    capacity-shaped (``traffic_model``: block = n_local * queue *
    entry bytes), and the rumor kill keeps adaptive peak queue
    occupancy ~2/node where push needs >8 — so the adaptive geo config
    runs ``queue=8`` with EVERY round curve bit-identical to queue=16
    (the halved capacity never binds), which halves the D=8 exchange
    bytes by the model's exact arithmetic (measured==model is pinned
    per round by epidemic.xshard_model_check / test_shard_driver)."""
    _, _, push = _geo_run(seed=0)
    _, _, a16 = _geo_run(seed=0, gossip_kw=ADAPTIVE)
    _, _, a8 = _geo_run(seed=0, gossip_kw={**ADAPTIVE, "queue": 8})
    nodes = 64
    push_peak = float(np.asarray(push["queue_backlog"]).max()) / nodes
    ada_peak = float(np.asarray(a16["queue_backlog"]).max()) / nodes
    assert push_peak > 8, push_peak  # push can't drop to queue=8 freely
    assert ada_peak <= 2, ada_peak
    for k in a16:
        np.testing.assert_array_equal(
            np.asarray(a16[k]), np.asarray(a8[k]), err_msg=k
        )
    # The D=8 exchange-byte halving is exact model arithmetic on the
    # (dcn, ici) wan mesh shape — device-free: traffic_model is pure.
    from unittest import mock

    from corrosion_tpu import parallel

    mesh = mock.Mock()
    mesh.axis_names = ("dcn", "ici")
    mesh.shape = {"dcn": 2, "ici": 4}
    g16 = gossip.GossipConfig(n_nodes=96, n_writers=12, queue=16)
    g8 = replace(g16, queue=8)
    tm16 = parallel.traffic_model(g16, mesh)
    tm8 = parallel.traffic_model(g8, mesh)
    for k in ("xshard_bytes_ici", "xshard_bytes_dcn"):
        assert tm8[k] == tm16[k] / 2, k
        assert tm8[k] > 0


# ---------------------------------------------------------------------------
# Deterministic two-node mechanics of the Demers counter kill


def _mk2(**kw):
    """Two nodes, both writers, one-slot queues, near-only fanout wide
    enough that a cross pull happens on the pinned seed."""
    cfg = gossip.GossipConfig(
        n_nodes=2, n_writers=2, queue=1, max_writes_per_round=1,
        fanout_near=2, fanout_far=0, queue_priority="version",
        window_k=0, n_cells=0, prop_observe=True, **kw,
    )
    topo = gossip.make_topology([2], [0, 1])
    return cfg, topo


def _seed_queues(data, q_writer, q_ver, q_tx, contig, q_dup=None):
    kw = dict(
        head=jnp.asarray([1, 1], jnp.uint32),
        contig=jnp.asarray(contig, jnp.uint32),
        seen=jnp.asarray(contig, jnp.uint32),
        q_writer=jnp.asarray(q_writer, jnp.int32),
        q_ver=jnp.asarray(q_ver, jnp.uint32),
        q_tx=jnp.asarray(q_tx, jnp.int32),
    )
    if q_dup is not None:
        kw["q_dup"] = jnp.asarray(q_dup, jnp.int32)
    return data._replace(**kw)


def _one_round(cfg, topo, data, seed):
    alive = jnp.ones(2, bool)
    part = jnp.zeros((1, 1), bool)
    w = jnp.zeros(2, jnp.uint32)
    return gossip.broadcast_round(
        data, topo, alive, part, w, jax.random.PRNGKey(seed), cfg
    )


def _cross_pull_seed(cfg, topo, data):
    """First seed on which both nodes deliver to each other (the
    receiver-centric sampling may draw self, which is skipped)."""
    for seed in range(32):
        _, stats = _one_round(cfg, topo, data, seed)
        if int(stats["msgs"]) >= 2:
            return seed
    raise AssertionError("no cross-pull seed in 32 tries")


def test_sender_and_receiver_kill_feedback():
    """Both Demers feedback signals, isolated: both nodes hold the SAME
    (writer 0, v1) rumor both already possess. Any delivered copy is
    (i) redundant at the receiver — a sender-side hit scattered back to
    the source's slot — and (ii) a match of the receiver's own pending
    entry — a receiver-side hit. At k=1 one exchanged round retires the
    rumor from both queues."""
    cfg, topo = _mk2(rumor_kill_k=1)
    data = _seed_queues(
        gossip.init_data(cfg),
        q_writer=[[0], [0]], q_ver=[[1], [1]], q_tx=[[6], [6]],
        contig=[[1, 1], [1, 1]], q_dup=[[0], [0]],
    )
    seed = _cross_pull_seed(cfg, topo, data)
    out, stats = _one_round(cfg, topo, data, seed)
    assert int(stats["msgs"]) >= 2
    assert int(stats["prop_dup"]) == int(stats["msgs"])  # all redundant
    assert int(stats["prop_kills"]) == 2
    np.testing.assert_array_equal(np.asarray(out.q_writer), [[-1], [-1]])


def test_kill_threshold_not_reached_keeps_entry():
    """One duplicate receipt below k leaves the entry alive with its
    counter advanced — the kill is a threshold, not a latch."""
    cfg, topo = _mk2(rumor_kill_k=8)
    data = _seed_queues(
        gossip.init_data(cfg),
        q_writer=[[0], [0]], q_ver=[[1], [1]], q_tx=[[6], [6]],
        contig=[[1, 1], [1, 1]], q_dup=[[0], [0]],
    )
    seed = _cross_pull_seed(cfg, topo, data)
    out, stats = _one_round(cfg, topo, data, seed)
    assert int(stats["prop_kills"]) == 0
    np.testing.assert_array_equal(np.asarray(out.q_writer), [[0], [0]])
    assert int(np.asarray(out.q_dup).sum()) >= 2  # hits accumulated


def test_kill_frees_intake_slot_same_round():
    """The satellite regression (``rebroadcast_intake`` interaction):
    node 1's one-slot queue holds a saturated rumor at the kill
    threshold while node 0 delivers a version node 1 lacks. The kill
    must free the slot in the SAME round's rebuild so the fresh
    rumor's intake admission lands — without the kill the old entry
    wins the stable keep-priority tie and the fresh rumor is dropped
    (the slot would leak a full round)."""
    base = dict(
        q_writer=[[0], [1]], q_ver=[[1], [1]], q_tx=[[6], [6]],
        contig=[[1, 1], [0, 1]],
    )
    # With the kill: node 1's (writer 1, v1) entry dies, the freshly
    # delivered (writer 0, v1) takes its slot this round.
    cfg, topo = _mk2(rumor_kill_k=1)
    data = _seed_queues(
        gossip.init_data(cfg), q_dup=[[0], [1]], **base
    )
    seed = _cross_pull_seed(cfg, topo, data)
    out, stats = _one_round(cfg, topo, data, seed)
    assert int(stats["prop_useful"]) >= 1  # node 1 really got writer 0
    assert int(stats["prop_kills"]) == 1
    assert np.asarray(out.q_writer)[1].tolist() == [0]
    assert np.asarray(out.q_ver)[1].tolist() == [1]
    # Control (mechanism off): the old entry survives and the intake
    # admission is dropped at capacity.
    cfg0, topo0 = _mk2()
    data0 = _seed_queues(gossip.init_data(cfg0), **base)
    out0, stats0 = _one_round(cfg0, topo0, data0, seed)
    assert int(stats0["prop_useful"]) >= 1
    assert np.asarray(out0.q_writer)[1].tolist() == [1]


# ---------------------------------------------------------------------------
# Neutral thresholds: armed machinery below threshold changes nothing


def _run_rounds(cfg, topo, data, rounds, seed=0, all_writers=False):
    n = cfg.n_nodes
    alive = jnp.ones(n, bool)
    part = jnp.zeros((int(jnp.max(topo.region)) + 1,) * 2, bool)
    key = jax.random.PRNGKey(seed)
    stats = []
    for r in range(rounds):
        key, k1, k2 = jax.random.split(key, 3)
        if all_writers and r < 6:
            w = jnp.ones(cfg.n_writers, jnp.uint32)
        else:
            w = (
                jnp.zeros(cfg.n_writers, jnp.uint32).at[r % cfg.n_writers]
                .set(1)
                if r < 6 else jnp.zeros(cfg.n_writers, jnp.uint32)
            )
        data, b = gossip.broadcast_round(
            data, topo, alive, part, w, k1, cfg
        )
        data, s = gossip.sync_round(
            data, topo, alive, part, jnp.int32(r), k2, cfg
        )
        stats.append((b, s))
    return data, stats


def _mk24(**kw):
    cfg = gossip.GossipConfig(
        n_nodes=24, n_writers=8, queue=4, prop_observe=True,
        **{"sync_interval": 4, **kw},
    )
    topo = gossip.make_topology([6, 6, 6, 6], list(range(8)))
    return cfg, topo, gossip.init_data(cfg)


def test_unreachable_kill_threshold_bit_identical_to_off():
    """A huge ``rumor_kill_k`` arms the whole counter plane (q_dup
    tracking, feedback scatters, the extra rebuild payload) but can
    never fire — the protocol state must be bit-identical to the off
    config, every round stat equal, and the counter zero. This is the
    disabled-flag zero-cost contract tested from the inside."""
    cfg_off, topo, data0 = _mk24()
    ref, stats_ref = _run_rounds(cfg_off, topo, data0, 12)
    cfg_on, _, data1 = _mk24(rumor_kill_k=1 << 20)
    got, stats_got = _run_rounds(cfg_on, topo, data1, 12)
    for name in ref._fields:
        if name in ("q_dup", "cells"):
            continue
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, name)),
            np.asarray(getattr(got, name)), err_msg=name,
        )
    assert np.asarray(ref.q_dup).shape[1] == 0  # zero-width when off
    assert np.asarray(got.q_dup).shape[1] == cfg_on.queue
    for r, ((br, sr), (bg, sg)) in enumerate(zip(stats_ref, stats_got)):
        for k in br:
            if k in ("prop_kills", "prop_pulls"):
                continue
            np.testing.assert_array_equal(
                np.asarray(br[k]), np.asarray(bg[k]),
                err_msg=f"round {r} {k}",
            )
        assert int(bg["prop_kills"]) == 0
    for k in ("applied_sync", "sessions"):
        assert [int(s[k]) for _, s in stats_ref] == [
            int(s[k]) for _, s in stats_got
        ], k


def test_unreachable_pull_threshold_never_fires():
    """A ``pull_switch_age`` far above any rumor age keeps every queue
    entry "young": no node ever saturates, no far slot is suppressed,
    no escalation session runs (zero prop_pulls and unchanged sync
    session counts vs off)."""
    cfg_off, topo, data0 = _mk24()
    _, stats_ref = _run_rounds(cfg_off, topo, data0, 12)
    cfg_on, _, data1 = _mk24(pull_switch_age=1 << 20)
    _, stats_got = _run_rounds(cfg_on, topo, data1, 12)
    assert all(int(b["prop_pulls"]) == 0 for b, _ in stats_got)
    assert [int(s["sessions"]) for _, s in stats_ref] == [
        int(s["sessions"]) for _, s in stats_got
    ]


def test_pull_escalation_heals_through_sync_plane():
    """Mechanism (b) end to end at ops level: with an aggressive
    switch age the saturated nodes' escalation sessions run through
    the sync grant path and the cluster still fully converges. Every
    writer commits each of the first 6 rounds so queued rumors really
    age past the threshold (a single commit per writer pins every
    rumor at version-age 0 and nothing would ever saturate)."""
    cfg, topo, data = _mk24(pull_switch_age=1, sync_interval=6)
    data, stats = _run_rounds(cfg, topo, data, 18, all_writers=True)
    assert sum(int(b["prop_pulls"]) for b, _ in stats) > 0
    heads = np.asarray(data.head)
    assert (np.asarray(data.contig) == heads[None, :]).all()


# ---------------------------------------------------------------------------
# Age-targeted forwarding


def test_age_forward_edges_pinned_to_telemetry():
    """Mechanism (c) bins ages exactly like the rumor-age histogram
    that motivated it (ops cannot import sim, so the edge tuple is
    duplicated and pinned here)."""
    assert gossip.AGE_FORWARD_EDGES == T.RUMOR_AGE_EDGES


def test_age_forward_priority_orders_young_bins_first():
    """The packed intake priority keeps young age bins ahead of old
    ones and breaks ties inside a bin young-version-first, within i32."""
    head = jnp.asarray([100], jnp.uint32)
    w = jnp.zeros((1, 4), jnp.int32)
    v = jnp.asarray([[99, 97, 40, 3]], jnp.uint32)  # ages 1, 3, 60, 97
    cfg = gossip.GossipConfig(
        n_nodes=4, n_writers=1, age_forward=True,
        rebroadcast_stale=False,
    )
    prio = np.asarray(
        gossip._intake_priority(head, w, v, cfg, "native")
    )[0]
    assert prio[0] > prio[1] > prio[2] > prio[3]
    assert prio.dtype == np.int32


def test_config_validation():
    for bad in (
        {"rumor_kill_k": -1},
        {"pull_switch_age": -2},
        {"sync_sketch_buckets": -1},
        {"age_forward": True, "rebroadcast_stale": True},
    ):
        with pytest.raises(ValueError):
            gossip.GossipConfig(n_nodes=4, n_writers=2, **bad)


# ---------------------------------------------------------------------------
# Engine coverage beyond dense: sparse and mixed thread the counters


def test_sparse_engine_adaptive_counters_and_conservation():
    from corrosion_tpu import models
    from corrosion_tpu.sim import sparse_engine

    cfg, topo, sched = models.anywrite_sparse(
        n=96, w_hot=16, n_regions=4, rounds=24, cohort=8,
        epoch_rounds=8, k_dev=8, samples=16,
    )
    cfg = replace(
        cfg, gossip=replace(cfg.gossip, prop_observe=True, **ADAPTIVE)
    )
    *_, curves, _info = sparse_engine.simulate_sparse(
        cfg, topo, sched, seed=0
    )
    np.testing.assert_array_equal(
        curves["prop_useful_msgs"] + curves["prop_dup_msgs"],
        curves["msgs"],
    )
    np.testing.assert_array_equal(
        _mass(curves, T.LINK_CURVE_KEYS), curves["msgs"]
    )
    assert float(np.asarray(curves["prop_rumor_kills"]).sum()) > 0


def test_mixed_engine_adaptive_counters_and_conservation():
    from corrosion_tpu.models.baselines import mixed_storm
    from corrosion_tpu.sim import mixed_engine

    cfg, ccfg, topo, sched, spec = mixed_storm(
        n=64, streams=2, last_seq=255, rounds=24, samples=16, n_cells=0
    )
    cfg = replace(
        cfg, gossip=replace(cfg.gossip, prop_observe=True, **ADAPTIVE)
    )
    _, curves = mixed_engine.simulate_mixed(
        cfg, ccfg, topo, sched, spec, seed=0
    )
    np.testing.assert_array_equal(
        curves["prop_useful_msgs"] + curves["prop_dup_msgs"],
        curves["msgs"],
    )
    assert float(np.asarray(curves["prop_rumor_kills"]).sum()) > 0


# ---------------------------------------------------------------------------
# Shard-count invariance of the kill-feedback scatter + psum


def test_kill_feedback_shard_invariant():
    """The sender-side feedback is the one new cross-shard reduction
    (full-shape scatter + psum, like ``pulled``): a D=2 sharded
    adaptive run must match the unsharded run bit-for-bit on protocol
    state and every propagation curve — and q_dup must NOT join the
    queue gather (the pinned xshard byte model still reconciles)."""
    from jax.sharding import Mesh

    from corrosion_tpu import models, parallel

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    cfg, topo, sched = models.wan_100k(
        n=32, n_regions=4, n_writers=8, rounds=12, samples=8,
        partition=False,
    )
    sched.writes[:, :] = 0
    sched.writes[:4, :] = 1
    sched = sched.make_samples(8)
    cfg = replace(
        cfg, gossip=replace(cfg.gossip, prop_observe=True, **ADAPTIVE)
    )
    ref_final, ref = simulate(cfg, topo, sched, seed=0)
    mesh = Mesh(np.array(jax.devices()[:2]), ("node",))
    final, got = parallel.shard_driver.simulate_sharded(
        cfg, topo, sched, mesh, seed=0
    )
    assert float(np.asarray(ref["prop_rumor_kills"]).sum()) > 0
    for k in T.PROP_CURVE_KEYS:
        np.testing.assert_array_equal(
            np.asarray(ref[k]), np.asarray(got[k]), err_msg=k
        )
    for name in ("head", "contig", "seen", "q_writer", "q_ver", "q_dup"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ref_final.data, name)),
            np.asarray(getattr(final.data, name)), err_msg=name,
        )
    ok, problems = epidemic.xshard_model_check(got, cfg.gossip, mesh)
    assert ok, problems
