"""Host-plane chaos: the netem shim, the hardened defensive machinery,
and the standing scenarios (docs/CHAOS.md "Host plane").

Fast units pin the shim's determinism contract (same seed ⇒ identical
fault schedule, mechanically replayable), the plan schema, the
zero-impairment bit-identity promise, one-way blackhole asymmetry, and
the Breaker/Backoff/AdaptiveChunker defense primitives the counters now
make visible. The slow-marked tests launch real loopback clusters: the
SIGKILL-rehydrate-reconnect regression and the standing scenarios up to
the ``wan_full`` acceptance run (80 ms WAN + 1 % loss + partition-heal +
SIGKILL-restart, zero oracle violations, all three defenses fired, seed
replay identical) — they run unfiltered in the chaos CI job.
"""

from __future__ import annotations

import asyncio
import time

import pytest

from corrosion_tpu.agent.netem import (
    HostFault,
    HostFaultPlan,
    NetemShim,
    PLAN_SCHEMA,
    replay_schedule,
)
from corrosion_tpu.agent.testing import (
    hard_kill,
    launch_test_agent,
    relaunch_test_agent,
)
from corrosion_tpu.agent.transport import Breaker, Transport
from corrosion_tpu.core.changes import AdaptiveChunker
from corrosion_tpu.utils.backoff import Backoff


def run(coro):
    return asyncio.run(coro)


# -- plan schema --------------------------------------------------------------


def test_plan_json_round_trip():
    plan = HostFaultPlan(
        name="rt",
        faults=(
            HostFault(kind="delay", delay_ms=40.0, jitter_ms=10.0),
            HostFault(kind="loss", prob=0.02, planes=("probe", "bcast"),
                      start_s=1.0, stop_s=5.0),
            HostFault(kind="blackhole", src=("a",), dst=("b",),
                      stall_s=0.2, start_s=2.0, stop_s=3.0),
            HostFault(kind="partition", a=("n1",), one_way=True,
                      start_s=0.5, stop_s=4.0),
            HostFault(kind="flap", a=("n2",), b=("n0",), period_s=0.5,
                      start_s=0.0, stop_s=8.0),
            HostFault(kind="dup", prob=0.5, planes=("probe",)),
            HostFault(kind="reorder", prob=0.25, extra_ms=80.0),
        ),
    )
    again = HostFaultPlan.from_json(plan.to_json())
    assert again == plan
    obj = plan.to_json_obj()
    assert obj["schema"] == PLAN_SCHEMA
    with pytest.raises(ValueError, match="schema"):
        HostFaultPlan.from_json({"schema": "corro-fault-plan/1"})
    # JSON loading must not soften validation: a delay component whose
    # document lacks delay_ms is an error, not a ~0 ms impairment.
    with pytest.raises(ValueError, match="delay_ms"):
        HostFaultPlan.from_json(
            {"schema": PLAN_SCHEMA, "faults": [{"kind": "delay"}]}
        )


def test_plan_validation_rejects_nonsense():
    with pytest.raises(ValueError, match="kind"):
        HostFault(kind="gremlins", start_s=0)
    with pytest.raises(ValueError, match="start_s"):
        HostFault(kind="delay", delay_ms=1.0, start_s=5.0, stop_s=2.0)
    # Loss on the sync stream is a category error: TCP doesn't lose
    # frames — it gets slow (that's the delay kind's job).
    with pytest.raises(ValueError, match="unsupported"):
        HostFault(kind="loss", prob=0.1, planes=("sync",))
    with pytest.raises(ValueError, match="period_s"):
        HostFault(kind="flap", a=("n0",))
    with pytest.raises(ValueError, match="side"):
        HostFault(kind="partition")
    with pytest.raises(ValueError, match="prob"):
        HostFault(kind="loss", prob=1.5)
    with pytest.raises(ValueError, match="negative"):
        HostFault(kind="delay", delay_ms=5.0, jitter_ms=10.0)


def test_plan_horizon():
    always_on = HostFault(kind="delay", delay_ms=10.0)
    windowed = HostFault(kind="partition", a=("n0",), start_s=1.0,
                         stop_s=4.5)
    assert HostFaultPlan(faults=(always_on,)).horizon_s() == 0.0
    assert HostFaultPlan(faults=(always_on, windowed)).horizon_s() == 4.5


# -- shim determinism ---------------------------------------------------------


def _drive(shim: NetemShim, clock: list):
    """A fixed event sequence through a shim with an injected clock."""
    shim.register_peer(("10.0.0.2", 1), "n1")
    shim.register_peer(("10.0.0.3", 1), "n2")
    shim.arm()
    for i in range(40):
        clock[0] += 0.1
        shim.udp_fault(("10.0.0.2", 1))
        shim.stream_fault("bcast", ("10.0.0.3", 1))
        if i % 3 == 0:
            shim.stream_fault("sync", ("10.0.0.2", 1))


def _mixed_plan() -> HostFaultPlan:
    return HostFaultPlan(
        name="mixed",
        faults=(
            HostFault(kind="delay", delay_ms=30.0, jitter_ms=10.0),
            HostFault(kind="loss", prob=0.2, planes=("probe", "bcast")),
            HostFault(kind="dup", prob=0.1, planes=("probe",)),
            HostFault(kind="partition", a=("n2",), start_s=2.0,
                      stop_s=3.0),
        ),
    )


def test_same_seed_identical_schedule():
    traces = []
    for _ in range(2):
        clock = [0.0]
        shim = NetemShim(
            _mixed_plan(), seed=7, local="n0", clock=lambda: clock[0]
        )
        _drive(shim, clock)
        traces.append((shim.trace, shim.fingerprint()))
    assert traces[0][1] == traces[1][1]
    assert traces[0][0] == traces[1][0]
    # And a different seed yields a different schedule (the loss/dup
    # draws flip somewhere in 40 events at these probabilities).
    clock = [0.0]
    other = NetemShim(
        _mixed_plan(), seed=8, local="n0", clock=lambda: clock[0]
    )
    _drive(other, clock)
    assert other.fingerprint() != traces[0][1]


def test_replay_schedule_verifies_and_detects_tamper():
    clock = [0.0]
    shim = NetemShim(
        _mixed_plan(), seed=3, local="n0", clock=lambda: clock[0]
    )
    _drive(shim, clock)
    assert shim.trace, "the fixed drive must produce impaired events"
    ok, mismatches = replay_schedule(_mixed_plan(), 3, "n0", shim.trace)
    assert ok, mismatches
    # Tampering with one recorded decision must be caught.
    tampered = [dict(e) for e in shim.trace]
    tampered[5]["drop"] = not tampered[5]["drop"]
    ok, mismatches = replay_schedule(_mixed_plan(), 3, "n0", tampered)
    assert not ok and mismatches
    # Structural corruption (component index outside the plan, missing
    # keys) is a diagnosed mismatch, never a traceback.
    corrupt = [dict(e) for e in shim.trace]
    corrupt[0]["f"] = [99]
    del corrupt[1]["plane"]
    ok, mismatches = replay_schedule(_mixed_plan(), 3, "n0", corrupt)
    assert not ok
    assert sum("structurally invalid" in m for m in mismatches) == 2


def test_shim_windows_wait_for_arm():
    """Scheduled windows must not fire while the cluster is still
    launching: before arm() only always-on components apply."""
    clock = [10.0]  # construction-time origin far in the "past"
    plan = HostFaultPlan(faults=(
        HostFault(kind="partition", a=("n1",), start_s=0.0, stop_s=1e9),
        HostFault(kind="delay", delay_ms=20.0),
    ))
    shim = NetemShim(plan, seed=0, local="n0", clock=lambda: clock[0])
    shim.register_peer(("10.0.0.2", 1), "n1")
    clock[0] = 500.0
    v = shim.stream_fault("bcast", ("10.0.0.2", 1))
    assert v.block_s is None and v.delay_s > 0  # delay yes, partition no
    shim.arm()
    clock[0] += 0.1
    v = shim.stream_fault("bcast", ("10.0.0.2", 1))
    assert v.block_s is not None


def test_flap_half_cycles():
    f = HostFault(kind="flap", a=("n0",), start_s=1.0, stop_s=5.0,
                  period_s=1.0)
    assert f.active_at(1.5)       # first half-cycle: cut
    assert not f.active_at(2.5)   # second: up
    assert f.active_at(3.5)
    assert not f.active_at(0.5) and not f.active_at(5.5)
    assert f.cuts("n0", "n1") and f.cuts("n1", "n0")
    one_way = HostFault(kind="partition", a=("n0",), one_way=True,
                        start_s=0.0, stop_s=1.0)
    assert one_way.cuts("n0", "n1") and not one_way.cuts("n1", "n0")
    # Unresolved peers never sit inside a partition side.
    assert not f.cuts("n0", "?")


def test_forced_loss_dup_delay_verdicts():
    clock = [0.0]
    plan = HostFaultPlan(faults=(
        HostFault(kind="loss", prob=1.0, planes=("bcast",)),
        HostFault(kind="dup", prob=1.0, planes=("probe",)),
        HostFault(kind="delay", delay_ms=50.0, planes=("sync",)),
    ))
    shim = NetemShim(plan, seed=0, local="n0", clock=lambda: clock[0])
    shim.register_peer(("h", 1), "n1")
    shim.arm()
    u = shim.udp_fault(("h", 1))
    assert u.dup and not u.drop  # probe: duplicated, loss is bcast-only
    assert shim.stream_fault("bcast", ("h", 1)).drop
    assert shim.stream_fault("sync", ("h", 1)).delay_s == pytest.approx(
        0.05
    )
    # dup/delay stay on their declared planes
    assert shim.stream_fault("sync", ("h", 1)).drop is False
    # Duplication is datagram-shaped: declaring it on a stream plane is
    # a plan error, not a silent no-op.
    with pytest.raises(ValueError, match="unsupported"):
        HostFault(kind="dup", prob=0.5, planes=("bcast",))


def test_empty_plan_is_disabled():
    shim = NetemShim(HostFaultPlan(name="empty"), seed=0, local="n0")
    assert not shim.enabled
    assert HostFaultPlan.from_json(
        HostFaultPlan(name="empty").to_json()
    ).empty


# -- zero-impairment bit-identity + one-way blackhole -------------------------


async def _echo_transport(received: list):
    t = Transport()

    async def handler(_session, msg):
        received.append(msg)

    addr = await t.serve("127.0.0.1", 0, handler)
    return t, addr


def test_zero_impairment_transport_path_identical(tmp_path):
    """A shim whose components never match the current window leaves
    transport behavior and frame bytes identical — and records nothing."""

    async def main():
        received: list = []
        server, addr = await _echo_transport(received)
        msg = {"t": "bcast", "actor": "ff" * 16, "blob": b"\x01\x02"}

        plain = Transport()
        assert await plain.send_frame(addr, msg)

        future_only = HostFaultPlan(faults=(
            HostFault(kind="delay", delay_ms=500.0, start_s=1e6,
                      stop_s=2e6),
        ))
        shim = NetemShim(future_only, seed=0, local="a")
        shim.arm()
        impaired = Transport(netem=shim)
        t0 = time.monotonic()
        assert await impaired.send_frame(addr, msg)
        assert time.monotonic() - t0 < 0.4  # no delay applied
        assert shim.trace == [] and shim.stats["events"] == 0

        await asyncio.sleep(0.1)
        assert len(received) == 2
        assert received[0] == received[1] == msg  # byte-for-byte decode
        plain.close()
        impaired.close()
        server.close()

    run(main())


def test_one_way_blackhole_asymmetry(tmp_path):
    """The same plan installed on both endpoints cuts ONLY the a→b
    direction: locality + the src/dst filter do the asymmetry."""

    async def main():
        recv_a: list = []
        recv_b: list = []
        ta, addr_a = await _echo_transport(recv_a)
        tb, addr_b = await _echo_transport(recv_b)
        plan = HostFaultPlan(faults=(
            HostFault(kind="blackhole", src=("a",), dst=("b",),
                      stall_s=0.05),
        ))
        shim_a = NetemShim(plan, seed=0, local="a")
        shim_b = NetemShim(plan, seed=0, local="b")
        ta._netem = shim_a
        tb._netem = shim_b
        shim_a.register_peer(addr_b, "b")
        shim_b.register_peer(addr_a, "a")
        shim_a.arm()
        shim_b.arm()

        # a -> b: cut, and repeated failures trip a's breaker for b.
        for _ in range(ta._breaker_threshold):
            assert not await ta.send_frame(addr_b, {"t": "x"})
        assert not ta.breaker(addr_b).available()
        # b -> a: same plan, same window — flows untouched.
        assert await tb.send_frame(addr_a, {"t": "y"})
        await asyncio.sleep(0.05)
        assert recv_b == [] and recv_a == [{"t": "y"}]
        ta.close()
        tb.close()

    run(main())


# -- defense primitives -------------------------------------------------------


def test_breaker_trip_edge_and_recovery():
    br = Breaker(threshold=3, base_s=0.05, max_s=0.2)
    assert br.fail() is False
    assert br.fail() is False
    assert br.fail() is True  # the closed->open edge, exactly once
    assert br.fail() is False  # already open: no second trip edge
    assert not br.available()
    assert br.ok() is True  # recovery edge
    assert br.ok() is False  # already closed: no second recovery
    assert br.available() and br.fails == 0


def test_breaker_cooldown_expiry_and_retrip():
    br = Breaker(threshold=2, base_s=0.05, max_s=0.1)
    assert not br.fail()
    assert br.fail()  # trip, 0.05 s cooldown
    assert not br.available()
    time.sleep(0.12)
    assert br.available()  # cooldown expired without a success
    assert br.fail() is True  # failing again while cooled-down re-trips


def test_breaker_success_resets_count():
    br = Breaker(threshold=3)
    br.fail()
    br.fail()
    br.ok()
    assert br.fails == 0
    assert br.fail() is False  # streak restarted: 1/3, no trip


def test_backoff_growth_cap_and_stop():
    b = Backoff(min_wait=1.0, max_wait=8.0, factor=2.0, jitter=False,
                max_retries=5)
    assert list(b) == [1.0, 2.0, 4.0, 8.0, 8.0]  # growth then cap
    with pytest.raises(StopIteration):
        next(b)
    b.reset()
    assert next(b) == 1.0


def test_backoff_jitter_floor_and_seed_determinism():
    waits1 = list(Backoff(min_wait=0.5, max_wait=60.0, seed=42,
                          max_retries=50))
    waits2 = list(Backoff(min_wait=0.5, max_wait=60.0, seed=42,
                          max_retries=50))
    assert waits1 == waits2  # injectable seed pins the jitter
    assert all(w >= 0.5 for w in waits1)  # full jitter never dips below
    assert all(w <= 60.0 for w in waits1)
    assert waits1 != list(Backoff(min_wait=0.5, max_wait=60.0, seed=43,
                                  max_retries=50))


def test_backoff_on_wait_hook():
    ticks: list[float] = []
    b = Backoff(min_wait=1.0, jitter=False, max_retries=3,
                on_wait=ticks.append)
    list(b)
    assert ticks == [1.0, 2.0, 4.0]


def test_adaptive_chunker_halving_counter():
    c = AdaptiveChunker(max_bytes=8192, min_bytes=1024, threshold_s=0.5)
    assert c.record(0.4) is False  # fast send: no halving
    assert c.record(0.6) is True and c.max_bytes == 4096
    assert c.record(0.6) is True and c.max_bytes == 2048
    assert c.record(0.6) is True and c.max_bytes == 1024
    # At the floor a slow send has no smaller step left: NOT a halving.
    assert c.record(0.6) is False and c.max_bytes == 1024
    assert c.halvings == 3


def test_counter_total_sums_labeled_series():
    from corrosion_tpu.hostchaos.harness import _counter_total

    snaps = [
        {"corro_peer_breaker_trips_total{addr=\"h:1\"}": 2.0,
         "corro_peer_breaker_trips_total{addr=\"h:2\"}": 1.0,
         "corro_peer_breaker_trips_totally_not": 9.0},
        {"corro_peer_breaker_trips_total": 4.0},
    ]
    assert _counter_total(snaps, "corro_peer_breaker_trips_total") == 7.0


# -- crash-recovery regression (satellite 3) ---------------------------------


@pytest.mark.slow
def test_hard_kill_rehydrates_and_reconnect_replays_gap(tmp_path):
    """SIGKILL (no graceful leave, no final flushes) + same-dir restart:
    the bookie rehydrates from __corro_bookkeeping, and a client
    SubscriptionStream.reconnect replays EXACTLY the missed gap —
    oracle-clean, strictly monotonic change ids, no duplicates."""
    from corrosion_tpu.loadgen.oracle import FanoutOracle

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        oracle = FanoutOracle()
        sid = oracle.attach_stream()
        stream = await a.client.subscribe("SELECT id, text FROM tests")

        async def pull_until(pred, timeout=10.0):
            async def go():
                while True:
                    ev = await stream.__anext__()
                    if "change" in ev:
                        _k, _rid, cells, cid = ev["change"]
                        oracle.change(
                            sid, _k, cells[0], tuple(cells[1:]), cid, 0.0
                        )
                    elif "row" in ev:
                        _rid, cells = ev["row"]
                        oracle.snapshot_row(sid, cells[0], tuple(cells[1:]))
                    if pred(ev):
                        return ev
            return await asyncio.wait_for(go(), timeout)

        await pull_until(lambda ev: "eoq" in ev)
        oracle.snapshot_done(sid, 0.0)

        async def write(client, i):
            await client.execute(
                [["INSERT INTO tests (id, text) VALUES (?, ?)",
                  [i, f"w{i}"]]]
            )
            oracle.commit(i, (f"w{i}",), t_ack=0.0)

        for i in range(3):
            await write(a.client, i)
        await pull_until(
            lambda ev: "change" in ev and ev["change"][2][0] == 2
        )
        head_before = a.agent.bookie.for_actor(a.agent.actor_id).last()
        assert head_before == 3

        await hard_kill(a)
        b = await relaunch_test_agent(a)
        try:
            # Same ports, rehydrated bookkeeping: the next local write
            # continues the version sequence (no reuse, no gap).
            assert b.agent.api_addr == a.agent.api_addr
            assert b.agent.bookie.for_actor(
                b.agent.actor_id
            ).last() == head_before
            for i in range(3, 6):
                await write(b.client, i)
            await stream.reconnect(retries=25)
            await pull_until(
                lambda ev: "change" in ev and ev["change"][2][0] == 5
            )
            rep = oracle.finish()
            assert rep["violations"] == 0, rep["violation_examples"]
            assert rep["missing"] == 0
            assert stream.last_change_id == 6  # exactly the gap, no more
        finally:
            stream.close()
            await b.stop()

    run(main())


# -- standing scenarios (chaos CI job territory) ------------------------------


def _run_named(tmp_path, name: str, seed: int = 0) -> dict:
    from corrosion_tpu.hostchaos import get_scenario, run_scenario

    async def main():
        return await run_scenario(
            get_scenario(name), str(tmp_path), seed=seed
        )

    return run(main())


@pytest.mark.slow
def test_scenario_wan_steady(tmp_path):
    rep = _run_named(tmp_path, "wan_steady")
    assert rep["ok"], rep["failures"]
    assert rep["oracle"]["violations"] == 0
    assert rep["converged"] and rep["bookkeeping_contiguous"]
    # The WAN was genuinely present: impairment events were decided.
    stats = rep["netem"]["agents"]
    assert all(blk["stats"]["delayed"] > 0 for blk in stats.values())


@pytest.mark.slow
def test_scenario_kill_restart(tmp_path):
    rep = _run_named(tmp_path, "kill_restart")
    assert rep["ok"], rep["failures"]
    assert rep["machinery"]["breaker_trips"] >= 1
    assert rep["oracle"]["reconnects"] >= 1  # durable subs resumed
    assert rep["kill"]["agent"] == 0


@pytest.mark.slow
def test_scenario_link_flap(tmp_path):
    rep = _run_named(tmp_path, "link_flap")
    assert rep["ok"], rep["failures"]
    assert rep["machinery"]["breaker_trips"] >= 1
    assert rep["machinery"]["breaker_recoveries"] >= 1


@pytest.mark.slow
def test_scenario_partition_heal(tmp_path):
    rep = _run_named(tmp_path, "partition_heal")
    assert rep["ok"], rep["failures"]
    for key in ("breaker_trips", "chunk_halvings", "stall_aborts"):
        assert rep["machinery"][key] >= 1, (key, rep["machinery"])


@pytest.mark.slow
def test_wan_full_acceptance(tmp_path):
    """ISSUE 14 acceptance: the seeded 80 ms-WAN + 1 %-loss +
    partition-then-heal + SIGKILL-restart scenario completes with zero
    fan-out-oracle violations, post-heal CRDT agreement, metrics proving
    stall abort + chunk halving + breaker trip each fired, and a fault
    schedule that replays identically from the seed."""
    from corrosion_tpu.hostchaos.harness import verify_schedule_determinism

    rep = _run_named(tmp_path, "wan_full", seed=0)
    assert rep["ok"], rep["failures"]
    assert rep["oracle"]["violations"] == 0
    assert rep["converged"] and rep["bookkeeping_contiguous"]
    for key in ("breaker_trips", "chunk_halvings", "stall_aborts"):
        assert rep["machinery"][key] >= 1, (key, rep["machinery"])
    assert rep["kill"] and rep["kill"]["agent"] == 0
    ok, problems = verify_schedule_determinism(rep)
    assert ok, problems
    # And the budget-gate path accepts a green report.
    from corrosion_tpu.hostchaos.report import check_hostchaos_budget

    gate_ok, breaches = check_hostchaos_budget(
        {"platform": "cpu", "scenario": "host_chaos_smoke",
         "scenarios": {"wan_full": rep}},
        {"platform": "cpu", "scenario": "host_chaos_smoke",
         "scenarios": ["wan_full"], "oracle_violations_max": 0,
         "require_machinery_fired": True, "require_converged": True},
    )
    assert gate_ok, breaches


@pytest.mark.slow
def test_scenario_flap_soak(tmp_path):
    """The long flap/partition churn soak (slow-marked out of tier-1
    AND the smoke gate; the chaos job runs it unfiltered)."""
    rep = _run_named(tmp_path, "flap_soak")
    assert rep["ok"], rep["failures"]
    assert rep["machinery"]["breaker_trips"] >= 3
    assert rep["machinery"]["breaker_recoveries"] >= 1


def test_budget_gate_refuses_idle_machinery_and_violations():
    from corrosion_tpu.hostchaos.report import check_hostchaos_budget

    budget = {
        "platform": "cpu", "scenario": "host_chaos_smoke",
        "scenarios": ["s"], "oracle_violations_max": 0,
        "require_machinery_fired": True, "require_converged": True,
        "ceilings_s": {"scenarios.s.drain_s": 1.0}, "tolerance": 2.0,
    }
    good = {
        "oracle": {"violations": 0}, "machinery_ok": True,
        "machinery_required": ["breaker_trips"],
        "machinery": {"breaker_trips": 2},
        "converged": True, "bookkeeping_contiguous": True, "ok": True,
        "drain_s": 1.5,
    }
    ok, breaches = check_hostchaos_budget(
        {"platform": "cpu", "scenario": "host_chaos_smoke",
         "scenarios": {"s": good}}, budget,
    )
    assert ok, breaches  # 1.5 < 1.0 x2 tolerance

    idle = dict(good, machinery_ok=False)
    ok, breaches = check_hostchaos_budget(
        {"platform": "cpu", "scenario": "host_chaos_smoke",
         "scenarios": {"s": idle}}, budget,
    )
    assert not ok and any("never fired" in b for b in breaches)

    violating = dict(good, oracle={"violations": 1})
    ok, breaches = check_hostchaos_budget(
        {"platform": "cpu", "scenario": "host_chaos_smoke",
         "scenarios": {"s": violating}}, budget,
    )
    assert not ok and any("oracle violations" in b for b in breaches)

    slow = dict(good, drain_s=2.5)
    ok, breaches = check_hostchaos_budget(
        {"platform": "cpu", "scenario": "host_chaos_smoke",
         "scenarios": {"s": slow}}, budget,
    )
    assert not ok and any("drain_s" in b for b in breaches)

    missing = {"platform": "cpu", "scenario": "host_chaos_smoke",
               "scenarios": {}}
    ok, breaches = check_hostchaos_budget(missing, budget)
    assert not ok and any("missing" in b for b in breaches)
