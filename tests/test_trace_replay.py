"""Record real agent write traffic, replay it in the kernel simulator.

The dispatch-seam bridge (SURVEY §7 step 7): the scripted Schedule the
simulator consumes is generated from a transcript of actual host-agent
traffic, so kernel convergence/visibility numbers can be read for real
workloads.
"""

import asyncio

import numpy as np

from corrosion_tpu.agent.testing import launch_test_agent, poll_until
from corrosion_tpu.sim.trace import Trace, replay, schedule_from_trace


def run(coro):
    return asyncio.run(coro)


def test_trace_record_replay_end_to_end(tmp_path):
    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        b = await launch_test_agent(
            str(tmp_path / "b"), bootstrap=[a.gossip_addr]
        )
        trace = Trace()
        trace.record(a.agent)
        trace.record(b.agent)
        try:
            for i in range(6):
                await a.client.execute(
                    [["INSERT INTO tests (id, text) VALUES (?, 'a')", [i]]]
                )
            for i in range(4):
                await b.client.execute(
                    [["INSERT INTO tests2 (id, text) VALUES (?, 'b')", [i]]]
                )

            async def both_converged():
                _, ra = await a.client.query("SELECT count(*) FROM tests2")
                _, rb = await b.client.query("SELECT count(*) FROM tests")
                return ra[0][0] == 4 and rb[0][0] == 6

            await poll_until(both_converged, timeout=20.0)
        finally:
            actor_a, actor_b = a.agent.actor_id, b.agent.actor_id
            await a.stop()
            await b.stop()
        return trace, actor_a, actor_b

    trace, actor_a, actor_b = run(main())
    counts = {actor_a: 6, actor_b: 4}
    assert {a: sum(1 for _, x, _ in trace.events if x == a)
            for a in trace.actors} == counts

    # Persistence roundtrip.
    path = str(tmp_path / "trace.jsonl")
    trace.save(path)
    assert Trace.load(path).events == sorted(trace.events)

    # Replay the recorded workload in the kernel with 3 extra observers.
    actors, final, curves, lat = replay(trace, observers=3)
    heads = np.asarray(final.data.head)
    assert [counts[a] for a in actors] == list(heads)
    contig = np.asarray(final.data.contig)
    assert (contig == heads[None, :]).all(), "kernel replay converged"
    assert lat["unseen"] == 0


def test_schedule_from_trace_buckets_and_validates():
    t = Trace(events=[
        (1000, "aa", 1), (1200, "aa", 2), (1800, "aa", 3), (2600, "bb", 1),
    ])
    actors, sched = schedule_from_trace(t, round_ms=500, drain_rounds=2)
    assert actors == ["aa", "bb"]
    # Buckets: t0=1000 → rounds (1000,1200)->0, 1800->1, 2600->3.
    assert sched.writes[0].tolist() == [2, 0]
    assert sched.writes[1].tolist() == [1, 0]
    assert sched.writes[3].tolist() == [0, 1]
    assert sched.writes.shape == (4 + 2, 2)

    # A version gap is rejected loudly.
    bad = Trace(events=[(0, "aa", 1), (10, "aa", 3)])
    try:
        schedule_from_trace(bad)
        raise AssertionError("gap must raise")
    except ValueError as e:
        assert "gap" in str(e)
