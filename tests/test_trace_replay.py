"""Record real agent write traffic, replay it in the kernel simulator.

The dispatch-seam bridge (SURVEY §7 step 7): the scripted Schedule the
simulator consumes is generated from a transcript of actual host-agent
traffic, so kernel convergence/visibility numbers can be read for real
workloads.
"""

import asyncio

import numpy as np

from corrosion_tpu.agent.testing import launch_test_agent, poll_until
from corrosion_tpu.sim.trace import Trace, replay, schedule_from_trace


def run(coro):
    return asyncio.run(coro)


def test_trace_record_replay_end_to_end(tmp_path):
    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        b = await launch_test_agent(
            str(tmp_path / "b"), bootstrap=[a.gossip_addr]
        )
        trace = Trace()
        trace.record(a.agent)
        trace.record(b.agent)
        try:
            for i in range(6):
                await a.client.execute(
                    [["INSERT INTO tests (id, text) VALUES (?, 'a')", [i]]]
                )
            for i in range(4):
                await b.client.execute(
                    [["INSERT INTO tests2 (id, text) VALUES (?, 'b')", [i]]]
                )

            async def both_converged():
                _, ra = await a.client.query("SELECT count(*) FROM tests2")
                _, rb = await b.client.query("SELECT count(*) FROM tests")
                return ra[0][0] == 4 and rb[0][0] == 6

            await poll_until(both_converged, timeout=20.0)
        finally:
            actor_a, actor_b = a.agent.actor_id, b.agent.actor_id
            await a.stop()
            await b.stop()
        return trace, actor_a, actor_b

    trace, actor_a, actor_b = run(main())
    counts = {actor_a: 6, actor_b: 4}
    assert {a: sum(1 for _, x, _ in trace.events if x == a)
            for a in trace.actors} == counts

    # Persistence roundtrip.
    path = str(tmp_path / "trace.jsonl")
    trace.save(path)
    assert Trace.load(path).events == sorted(trace.events)

    # Replay the recorded workload in the kernel with 3 extra observers.
    actors, final, curves, lat = replay(trace, observers=3)
    heads = np.asarray(final.data.head)
    assert [counts[a] for a in actors] == list(heads)
    contig = np.asarray(final.data.contig)
    assert (contig == heads[None, :]).all(), "kernel replay converged"
    assert lat["unseen"] == 0


class _FakeAgent:
    """Just the hook attribute surface Trace.record touches."""

    def __init__(self):
        self.on_local_write = None


def test_two_recorders_chain_instead_of_clobbering():
    # Regression: Trace.record used to assign the hook wholesale, so a
    # second recorder (or any user hook) silently disabled the first.
    from corrosion_tpu.core.hlc import make_ts

    agent = _FakeAgent()
    user_calls = []
    agent.on_local_write = lambda a, v, ts: user_calls.append((a, v))
    t1, t2 = Trace(), Trace()
    t1.record(agent)
    t2.record(agent)
    agent.on_local_write("aa", 1, make_ts(1000))
    agent.on_local_write("aa", 2, make_ts(1500))
    assert t1.events == [(1000, "aa", 1), (1500, "aa", 2)]
    assert t2.events == t1.events, "both recorders must see every write"
    assert user_calls == [("aa", 1), ("aa", 2)], "user hook must survive"
    # unrecord unwinds LIFO: t2 detaches cleanly, then t1 is on top; a
    # trace NOT on top refuses to unwind (it would drop the newer hook).
    assert not t1.unrecord(agent), "t1 is not on top while t2 records"
    assert t2.unrecord(agent)
    assert t1.unrecord(agent)
    agent.on_local_write("aa", 3, make_ts(2000))
    assert user_calls[-1] == ("aa", 3), "original user hook restored"
    assert t1.events[-1][2] == 2, "detached recorders stop recording"


def test_unrecord_restores_chain_order():
    from corrosion_tpu.core.hlc import make_ts

    agent = _FakeAgent()
    t1, t2 = Trace(), Trace()
    t1.record(agent)
    t2.record(agent)
    assert t2.unrecord(agent)
    assert t1.unrecord(agent)
    assert agent.on_local_write is None
    t1.record(agent)
    agent.on_local_write("bb", 1, make_ts(7))
    assert t1.events[-1] == (7, "bb", 1)


def test_schedule_from_trace_zero_duration_trace():
    # Every event in one round_ms window: a valid 1-write-round schedule
    # (plus the drain tail), not a degenerate shape.
    t = Trace(events=[(5000, "aa", 1), (5000, "bb", 1), (5001, "aa", 2)])
    actors, sched = schedule_from_trace(t, round_ms=500, drain_rounds=3)
    assert sched.writes.shape == (1 + 3, 2)
    assert sched.writes[0].tolist() == [2, 1]
    assert sched.writes[1:].sum() == 0
    assert len(sched.sample_round) == 3


def test_schedule_from_trace_sub_ms_round():
    # Sub-ms round_ms: bucket arithmetic is float; the last event's
    # bucket must stay inside the array (rounds derives from the max
    # bucket index, not an independent duration division).
    t = Trace(events=[(0, "aa", 1), (1, "aa", 2), (999, "aa", 3)])
    actors, sched = schedule_from_trace(t, round_ms=0.333, drain_rounds=2)
    assert sched.writes.sum() == 3
    assert sched.writes.shape[1] == 1
    # And a plainly invalid round_ms is rejected loudly.
    for bad in (0.0, -5.0):
        try:
            schedule_from_trace(t, round_ms=bad)
            raise AssertionError("non-positive round_ms must raise")
        except ValueError as e:
            assert "round_ms" in str(e)


def test_schedule_from_trace_mid_life_attach_base_version():
    # A recorder attached mid-life of an agent starts at version k+1;
    # contiguity is required from the FIRST recorded version, not 1.
    t = Trace(events=[(0, "aa", 13), (10, "aa", 14), (700, "aa", 15)])
    actors, sched = schedule_from_trace(t, round_ms=500, drain_rounds=1)
    assert sched.writes[:, 0].tolist() == [2, 1, 0]


def test_schedule_from_trace_bucket_counts_preserve_version_order():
    # Property: for any trace, the count-per-bucket encoding preserves
    # each actor's version order — walking the buckets in round order
    # and numbering writes contiguously reproduces exactly the per-actor
    # version sequence of the sorted events, for any round_ms.
    rng = np.random.default_rng(0)
    for case in range(30):
        n_actors = int(rng.integers(1, 5))
        actors_in = [f"a{i}" for i in range(n_actors)]
        events = []
        t = 0
        heads = {a: 0 for a in actors_in}
        for _ in range(int(rng.integers(1, 40))):
            t += int(rng.integers(0, 700))
            a = actors_in[int(rng.integers(0, n_actors))]
            heads[a] += 1
            events.append((t, a, heads[a]))
        round_ms = float(rng.choice([0.4, 1.0, 250.0, 500.0, 1000.0]))
        trace = Trace(events=events)
        actors, sched = schedule_from_trace(
            trace, round_ms=round_ms, drain_rounds=1
        )
        # Total per actor preserved...
        for i, a in enumerate(actors):
            assert sched.writes[:, i].sum() == heads[a]
        # ...and bucket-order numbering reproduces the event order: the
        # k-th bucketed write of actor a IS version k (versions started
        # at 1 here), committed no later than its bucket's successors.
        for i, a in enumerate(actors):
            seq = []
            for r in range(sched.writes.shape[0]):
                seq.extend([r] * int(sched.writes[r, i]))
            ev_rounds = [
                int((tt - events[0][0]) // round_ms)
                for tt, aa, _v in sorted(events) if aa == a
            ]
            # sorted(events) orders ties by actor/version; per actor the
            # bucket sequence must match the event bucket sequence.
            assert seq == ev_rounds, (case, a, round_ms)


def test_schedule_from_trace_buckets_and_validates():
    t = Trace(events=[
        (1000, "aa", 1), (1200, "aa", 2), (1800, "aa", 3), (2600, "bb", 1),
    ])
    actors, sched = schedule_from_trace(t, round_ms=500, drain_rounds=2)
    assert actors == ["aa", "bb"]
    # Buckets: t0=1000 → rounds (1000,1200)->0, 1800->1, 2600->3.
    assert sched.writes[0].tolist() == [2, 0]
    assert sched.writes[1].tolist() == [1, 0]
    assert sched.writes[3].tolist() == [0, 1]
    assert sched.writes.shape == (4 + 2, 2)

    # A version gap is rejected loudly.
    bad = Trace(events=[(0, "aa", 1), (10, "aa", 3)])
    try:
        schedule_from_trace(bad)
        raise AssertionError("gap must raise")
    except ValueError as e:
        assert "gap" in str(e)
