"""RTT ring model: ring-aware sync peer ordering (members.rs:33,101-136).

Kernel-side: region_rtt ring classes break need ties toward low-RTT peers.
Host-side: per-member RTT samples bucket into the reference's ring edges.
"""

import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.agent.membership import MemberState, rtt_ring
from corrosion_tpu.ops import gossip


def test_geo_rings_span_buckets():
    topo = gossip.make_topology([4] * 10, [0], region_rtt="geo")
    rtt = np.asarray(topo.region_rtt)
    assert rtt.min() == 0 and rtt.max() == 5
    assert (np.diag(rtt) == 0).all()
    assert (rtt == rtt.T).all()


def test_sync_prefers_low_ring_peer_on_need_tie():
    # Ring-0 holders of writer 0 sit in node 0's own region; ring-5 holders
    # of writer 1 fill regions 1-2. With sync_peers=1 and equal need, the
    # tie must break toward ring 0 whenever a ring-0 holder is among the
    # candidates — which the half-ring-0 candidate sampling makes near
    # certain. (When no ring-0 holder is sampled there is no tie, so the
    # far peer may legitimately win; hence a margin, not an absolute.)
    rtt = np.array([[0, 5, 5], [5, 0, 5], [5, 5, 0]], np.int32)
    cfg = gossip.GossipConfig(
        n_nodes=9, n_writers=2, fanout_near=0, fanout_far=0,
        sync_interval=1, sync_budget=4, sync_chunk=4,
        sync_peers=1, sync_candidates=8,
    )
    topo = gossip.make_topology([3, 3, 3], [1, 3], region_rtt=rtt)
    data = gossip.init_data(cfg)
    contig = data.contig
    for holder in (1, 2):  # ring 0 relative to node 0
        contig = contig.at[holder, 0].set(4)
    for holder in (3, 4, 5, 6, 7, 8):  # ring 5
        contig = contig.at[holder, 1].set(4)
    data = data._replace(
        head=jnp.array([4, 4], jnp.uint32),
        contig=contig,
        seen=jnp.maximum(data.seen, contig),
    )
    alive = jnp.ones(9, bool)
    part = jnp.zeros((3, 3), bool)
    pulls_near = pulls_far = 0
    for seed in range(40):
        d, _ = gossip.sync_round(
            data, topo, alive, part, jnp.int32(0),
            jax.random.PRNGKey(seed), cfg,
        )
        got_near = int(d.contig[0, 0]) > 0
        got_far = int(d.contig[0, 1]) > 0
        pulls_near += got_near
        pulls_far += got_far and not got_near
    assert pulls_near >= 35, f"ring-0 should win almost always ({pulls_near})"
    assert pulls_far <= 3, f"ring-5 must only win when ring 0 unsampled ({pulls_far})"


def test_host_rtt_ring_exact_edges():
    # Exact ring-edge RTTs: edges are EXCLUSIVE upper bounds (rtt < edge
    # -> ring i, members.rs:101-136), so a sample AT an edge lands in
    # the next ring. The rings are now a fidelity-plane calibration
    # input (fidelity/calibrate.py), so these boundaries are pinned.
    from corrosion_tpu.agent.membership import RING_BUCKETS_MS

    for i, edge in enumerate(RING_BUCKETS_MS[:-1]):
        assert rtt_ring(edge) == i + 1, f"edge {edge} must open ring {i + 1}"
        assert rtt_ring(edge - 0.001) == i
    # The last edge (300) is inside the open-ended top ring.
    assert rtt_ring(RING_BUCKETS_MS[-1]) == len(RING_BUCKETS_MS) - 1
    assert rtt_ring(0.0) == 0


def test_member_empty_sample_buffer_and_churn_recalc():
    m = MemberState(actor_id="x", addr=("h", 1))
    # Empty sample buffer: no ring assignment yet (callers treat None as
    # "unknown", sorted last by Members.by_ring).
    assert m.rtts == [] and m.ring is None
    # Fill the 20-sample circular buffer with ring-0 RTTs.
    for _ in range(20):
        m.add_rtt(2.0)
    assert m.ring == 0 and len(m.rtts) == 20
    # Churn: the link degrades; new samples must ROTATE the old ones out
    # (cap 20) and the ring must recalculate from the surviving window,
    # not the all-time history.
    for _ in range(20):
        m.add_rtt(250.0)
    assert len(m.rtts) == 20
    assert all(r == 250.0 for r in m.rtts), "old samples must rotate out"
    assert m.ring == 5
    # Partial churn: a mixed window averages (members.rs keeps a mean
    # over the ring buffer) — 10x2.0 + 10x120.0 -> mean 61 -> ring 3.
    m2 = MemberState(actor_id="y", addr=("h", 2))
    for _ in range(10):
        m2.add_rtt(2.0)
    for _ in range(10):
        m2.add_rtt(120.0)
    assert m2.ring == 3


def test_ring_repr_table_matches_ring_edges():
    # The fidelity plane's representative-RTT table must stay consistent
    # with the host ring classifier: each representative must classify
    # into its own ring.
    from corrosion_tpu.fidelity.calibrate import RING_REPR_MS

    for ring, repr_ms in enumerate(RING_REPR_MS):
        assert rtt_ring(repr_ms) == ring, (
            f"RING_REPR_MS[{ring}]={repr_ms} classifies as "
            f"ring {rtt_ring(repr_ms)}"
        )


def test_host_rtt_buckets_match_reference_edges():
    assert rtt_ring(2.0) == 0
    assert rtt_ring(10.0) == 1
    assert rtt_ring(20.0) == 2
    assert rtt_ring(70.0) == 3
    assert rtt_ring(150.0) == 4
    assert rtt_ring(250.0) == 5
    assert rtt_ring(400.0) == 5
    m = MemberState(actor_id="x", addr=("h", 1))
    for _ in range(5):
        m.add_rtt(3.0)
    assert m.ring == 0
    for _ in range(30):
        m.add_rtt(120.0)
    assert m.ring == 4
