"""Native runtime (C) parity tests.

The C implementations must be byte-exact / order-exact with the pure-Python
ones: the PK codec round-trips identically, malformed blobs raise the same
exception type, the LWW value order agrees pairwise, the wire codec
round-trips agent frames, and the SQLite extension's SQL surface matches.
"""

import math
import sqlite3

import pytest

from corrosion_tpu import native
from corrosion_tpu.core import values as V

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)

SAMPLES = [
    (),
    (None,),
    (0,), (1,), (-1,), (63,), (64,), (-64,), (-65,),
    (2**62, -(2**62), 2**63 - 1, -(2**63)),
    (0.0, -0.0, 1.5, -2.75, 1e300, -1e300, math.inf, -math.inf),
    ("", "hi", "héllo wörld", "☃" * 100),
    (b"", b"\x00\xff", bytes(range(256))),
    (None, 42, 2.5, "mixed", b"blob", True, False),
]


@pytest.mark.parametrize("vals", SAMPLES)
def test_pack_roundtrip_parity(vals):
    nb = native.native.pack_columns(list(vals))
    pb = V._py_pack_columns(vals)
    assert nb == pb
    expect = tuple(int(v) if isinstance(v, bool) else v for v in vals)
    assert native.native.unpack_columns(nb) == expect
    assert V._py_unpack_columns(nb) == expect


def test_unpack_malformed_blob_rejected():
    good = V.pack_columns([1, "hi", b"xy"])
    for bad in (
        good[:-1],              # truncated payload
        b"\x01",                # truncated varint
        b"\x05",                # unknown tag
        b"\x03\x05ab",          # declared length overruns
        b"\x02\x00\x00",        # truncated real
        b"\x01" + b"\x80" * 10, # varint overflow
    ):
        with pytest.raises(V.MalformedBlobError):
            V.unpack_columns(bad)


def test_int_out_of_i64_range_rejected():
    with pytest.raises(ValueError):
        V.pack_columns([2**63])
    with pytest.raises(ValueError):
        native.native.pack_columns([-(2**63) - 1])


CMP_VALUES = [
    None, -(2**63), -5, -1, 0, 1, 5, 2**63 - 1,
    -1e300, -2.5, -0.5, 0.0, 0.5, 2.5, 1e300,
    2**53 + 1, float(2**53),  # exact int/float comparison past 2^53
    "", "a", "ab", "b", "é",
    b"", b"a", b"ab", b"b", b"\xff",
]


def test_value_cmp_matches_python_order():
    for a in CMP_VALUES:
        for b in CMP_VALUES:
            got = native.native.value_cmp(a, b)
            ka, kb = V.value_cmp_key(a), V.value_cmp_key(b)
            want = -1 if ka < kb else (1 if ka > kb else 0)
            assert got == want, f"value_cmp({a!r}, {b!r}) = {got}, want {want}"
            assert V.value_le(a, b) == (want <= 0)


def test_wire_codec_roundtrip():
    frames = [
        {"t": "bcast", "actor": b"\x01" * 16, "version": 7,
         "seqs": [0, 41], "last_seq": 41, "ts": 123456789,
         "changes": [["tbl", b"pk\x00", "col", None, 1, 2, 3, b"s" * 16, 1],
                     ["tbl", b"pk\x01", "col", 2.5, 1, 2, 4, b"s" * 16, 1]]},
        {"t": "sync_state", "state": {"heads": {"00ff": 3},
                                      "need": {}, "partial": []}},
        {"empty": {}, "list": [], "nested": [[[1], [True, False, None]]]},
    ]
    for f in frames:
        assert native.native.decode(native.native.encode(f)) == f


def test_wire_codec_rejects_garbage():
    with pytest.raises(ValueError):
        native.native.decode(b"\xffgarbage")
    with pytest.raises(ValueError):
        native.native.decode(native.native.encode({"a": 1}) + b"tail")
    with pytest.raises(ValueError):
        native.native.decode(b"\x07\xff\xff\xff\xff\x7f")  # huge list claim


def test_python_binary_decoder_parity():
    # Mixed clusters: a peer without the C module must decode binary frames
    # identically via the pure-Python decoder.
    from corrosion_tpu.agent import transport

    msgs = [
        {"t": "bcast", "actor": b"\x01" * 16, "version": -3,
         "changes": [["t", b"\x00", "c", 1.5, 1, 2, 3, b"s" * 16, 1]],
         "flags": [True, False, None], "nested": {"a": {"b": [2**62]}}},
        {},
        {"x": []},
    ]
    for m in msgs:
        payload = native.native.encode(m)
        obj, end = transport._py_wire_decode(payload)
        assert end == len(payload)
        assert obj == m
    with pytest.raises(ValueError):
        transport._py_wire_decode(b"\xff")
    with pytest.raises(ValueError):
        transport._py_wire_decode(native.native.encode({"a": 1})[:-1])


def test_transport_frames_binary_and_json():
    from corrosion_tpu.agent import transport

    msg = {"t": "bcast", "actor": b"\xab" * 16, "changes": [["t", b"p", "c",
           "v", 1, 2, 3, b"s" * 16, 1]], "ok": True}
    frame = transport.encode_frame(msg)
    assert frame[4] == transport.FRAME_BIN
    assert transport.decode_frame_body(frame[4:]) == msg
    # JSON frames remain decodable (non-native peer interop).
    import json

    body = bytes([transport.FRAME_JSON]) + json.dumps(
        transport.encode_value(msg), separators=(",", ":")
    ).encode()
    assert transport.decode_frame_body(body) == msg


@pytest.fixture
def ext_conn():
    if not native.crdt_ext_available():
        pytest.skip("crdt_ext.so not built")
    c = sqlite3.connect(":memory:")
    assert native.load_crdt_extension(c)
    yield c
    c.close()


def test_sqlite_ext_value_cmp(ext_conn):
    for a in CMP_VALUES:
        for b in CMP_VALUES:
            if isinstance(a, float) and not math.isfinite(a):
                continue  # SQLite binds inf fine but keep matrix modest
            if isinstance(b, float) and not math.isfinite(b):
                continue
            (got,) = ext_conn.execute(
                "SELECT crdt_value_cmp(?, ?)", (a, b)
            ).fetchone()
            ka, kb = V.value_cmp_key(a), V.value_cmp_key(b)
            want = -1 if ka < kb else (1 if ka > kb else 0)
            assert got == want, f"crdt_value_cmp({a!r}, {b!r})"


def test_sqlite_ext_pack_matches_python(ext_conn):
    (blob,) = ext_conn.execute(
        "SELECT crdt_pack_columns(?, ?, ?, ?)", (1, "hi", None, b"\x00")
    ).fetchone()
    assert blob == V.pack_columns([1, "hi", None, b"\x00"])
    (count,) = ext_conn.execute(
        "SELECT crdt_col_count(?)", (blob,)
    ).fetchone()
    assert count == 4
    row = ext_conn.execute(
        "SELECT crdt_unpack_col(?, 0), crdt_unpack_col(?, 1),"
        " crdt_unpack_col(?, 2), crdt_unpack_col(?, 3),"
        " crdt_unpack_col(?, 4)",
        (blob,) * 5,
    ).fetchone()
    assert row == (1, "hi", None, b"\x00", None)


def test_sqlite_ext_site_hex(ext_conn):
    (txt,) = ext_conn.execute(
        "SELECT crdt_site_hex(?)", (b"\x00\xab\xff",)
    ).fetchone()
    assert txt == "00abff"


def test_store_uses_native_merge(tmp_path):
    """The Store loads the extension and the native tie-break path agrees
    with the Python one on a col_version tie."""
    from corrosion_tpu.agent.store import Store
    from corrosion_tpu.core.values import Change

    if not native.crdt_ext_available():
        pytest.skip("crdt_ext.so not built")
    s = Store(str(tmp_path / "a.db"), b"\x01" * 16)
    assert s.native_crdt
    s.apply_schema("CREATE TABLE kv (k TEXT PRIMARY KEY, v TEXT);")
    pk = V.pack_columns(["key"])
    site_b = b"\x02" * 16
    site_c = b"\x03" * 16

    def mk(site, val, cv):
        return Change(
            table="kv", pk=pk, cid="v", val=val, col_version=cv,
            db_version=1, seq=0, site_id=site, cl=1,
        )

    assert s.apply_changes([mk(site_b, "bbb", 1)]) == 1
    # Tie on col_version: "aaa" < "bbb" loses, "zzz" wins.
    assert s.apply_changes([mk(site_c, "aaa", 1)]) == 0
    assert s.apply_changes([mk(site_c, "zzz", 1)]) == 1
    cols, rows = s.query(V.Statement("SELECT v FROM kv WHERE k = 'key'"))
    assert rows == [("zzz",)]
    s.close()
