"""Serving-plane load subsystem (corrosion_tpu/loadgen, docs/SERVING.md).

Units for the open-loop schedule and the fan-out oracle's violation
detection, the serving emit path + budget gate, and reduced-scale
end-to-end runs of the standing scenarios against real in-process
agents over TCP loopback: the fan-out storm (zero oracle violations),
the saturation sweep (shed engages at the configured api_concurrency,
client and server shed accounting agree, admitted p99 bounded), and —
slow-marked with the heavy storms — the 2k-subscription acceptance run
and the intake-policy collapse rule.
"""

import asyncio
import json

import pytest

from corrosion_tpu.loadgen import schedule as sched_mod
from corrosion_tpu.loadgen.oracle import FanoutOracle
from corrosion_tpu.loadgen.report import (
    check_serving_budget,
    emit_serving_report,
)


def run(coro):
    return asyncio.run(coro)


# -- schedule ----------------------------------------------------------------


def test_open_loop_schedule_deterministic_grid():
    a = sched_mod.open_loop(10.0, 25)
    b = sched_mod.open_loop(10.0, 25)
    assert a == b
    assert len(a) == 25
    assert a[0].t == 0.0
    assert a[1].t == pytest.approx(0.1)
    assert a[-1].t == pytest.approx(2.4)
    assert all(x.stage == 0 for x in a)


def test_open_loop_burst_packs_instants_at_same_rate():
    a = sched_mod.open_loop(100.0, 32, burst=16)
    # 16 arrivals share each instant; instants 0.16 s apart — the
    # long-run rate is still 100/s.
    assert [x.t for x in a[:16]] == [0.0] * 16
    assert a[16].t == pytest.approx(0.16)
    assert a[-1].t == pytest.approx(0.16)


def test_ramp_tags_stages():
    a = sched_mod.ramp([(10.0, 1.0), (20.0, 1.0)])
    assert sum(1 for x in a if x.stage == 0) == 10
    assert sum(1 for x in a if x.stage == 1) == 20
    # Stage 1 starts after stage 0's window.
    assert min(x.t for x in a if x.stage == 1) == pytest.approx(1.0)


def test_open_loop_rejects_bad_args():
    with pytest.raises(ValueError):
        sched_mod.open_loop(0.0, 5)
    with pytest.raises(ValueError):
        sched_mod.open_loop(10.0, 5, burst=0)


# -- oracle ------------------------------------------------------------------


def test_oracle_clean_exactly_once_pass():
    o = FanoutOracle()
    sid = o.attach_stream()
    o.snapshot_done(sid, t=0.0)
    o.commit(1, ("a",), t_ack=1.0)
    o.change(sid, "insert", 1, ("a",), change_id=1, t=1.01)
    rep = o.finish()
    assert rep["violations"] == 0 and rep["missing"] == 0
    assert rep["delivered_changes"] == 1
    assert rep["fanout_lag_ms"]["count"] == 1


def test_oracle_detects_duplicate_delivery():
    o = FanoutOracle()
    sid = o.attach_stream()
    o.snapshot_done(sid, t=0.0)
    o.commit(1, ("a",), t_ack=1.0)
    o.change(sid, "insert", 1, ("a",), change_id=1, t=1.0)
    o.change(sid, "insert", 1, ("a",), change_id=2, t=1.1)
    rep = o.finish()
    assert rep["violations"] == 1
    assert "duplicate" in rep["violation_examples"][0]


def test_oracle_detects_non_monotonic_change_ids():
    o = FanoutOracle()
    sid = o.attach_stream()
    o.snapshot_done(sid, t=0.0)
    o.change(sid, "insert", 1, ("a",), change_id=5, t=1.0)
    o.change(sid, "insert", 2, ("b",), change_id=3, t=1.1)
    assert any("non_monotonic" in v for v in o.violations)


def test_oracle_detects_missing_delivery():
    o = FanoutOracle()
    sid = o.attach_stream()
    o.snapshot_done(sid, t=0.0)
    o.commit(1, ("a",), t_ack=1.0)
    assert o.pending() == 1
    rep = o.finish()
    assert rep["missing"] == 1
    assert any("missing" in v for v in rep["violation_examples"])


def test_oracle_snapshot_covers_delivery_and_prior_commits_optional():
    o = FanoutOracle()
    sid = o.attach_stream()
    # Acked BEFORE the snapshot finished: no obligation either way.
    o.commit(1, ("early",), t_ack=0.5)
    o.snapshot_done(sid, t=1.0)
    # Acked after: must arrive, and a snapshot(-restart) row satisfies it.
    o.commit(2, ("late",), t_ack=2.0)
    assert o.pending() == 1
    o.snapshot_row(sid, 2, ("late",))
    assert o.pending() == 0
    assert o.finish()["violations"] == 0


def test_oracle_group_partitioning():
    o = FanoutOracle()
    sid = o.attach_stream(group=0)
    o.snapshot_done(sid, t=0.0)
    o.commit(1, ("a",), t_ack=1.0, group=1)  # other group: no obligation
    o.commit(2, ("b",), t_ack=1.0, group=0)
    assert o.pending() == 1
    o.change(sid, "insert", 2, ("b",), change_id=1, t=1.1)
    assert o.finish()["missing"] == 0


def test_oracle_early_delivery_resolves_lag_at_commit():
    # Fan-out regularly beats the writer's HTTP ack: the lag must still
    # be recorded (clamped at 0), not lost.
    o = FanoutOracle()
    sid = o.attach_stream()
    o.snapshot_done(sid, t=0.0)
    o.change(sid, "insert", 1, ("a",), change_id=1, t=0.9)
    assert o.lag_hist.count() == 0
    o.commit(1, ("a",), t_ack=1.0)
    assert o.lag_hist.count() == 1
    assert o.finish()["violations"] == 0


# -- emit path + budget gate -------------------------------------------------


def test_emit_serving_report_requires_scenario_provenance():
    base = {
        "platform": "cpu", "nodes": 1, "device_count": 1,
        "config_fingerprint": "abc123",
    }
    with pytest.raises(ValueError, match="scenario"):
        emit_serving_report(dict(base))
    out = emit_serving_report({**base, "scenario": "ci_smoke"})
    assert out["scenario"] == "ci_smoke"


def _measured(**over):
    m = {
        "platform": "cpu", "scenario": "ci_smoke", "subs": 200,
        "run": {
            "routes": {"transactions": {"latency_ms": {"p99": 100.0}}},
            "oracle": {
                "violations": 0, "fanout_lag_ms": {"p99": 1.0},
            },
        },
        "sweep": {
            "shed_engaged": True, "admitted_p99_ms_max": 500.0,
            "oracle": {"violations": 0},
        },
    }
    m.update(over)
    return m


_BUDGET = {
    "platform": "cpu", "scenario": "ci_smoke", "subs": 200,
    "tolerance": 1.5,
    "ceilings_ms": {
        "run.routes.transactions.latency_ms.p99": 200.0,
        "run.oracle.fanout_lag_ms.p99": 100.0,
        "sweep.admitted_p99_ms_max": 1000.0,
    },
    "oracle_violations_max": 0,
    "require_shed_engaged": True,
}


def test_serving_budget_clean_pass():
    ok, breaches = check_serving_budget(_measured(), _BUDGET)
    assert ok, breaches


def test_serving_budget_flags_dimension_mismatch():
    ok, breaches = check_serving_budget(_measured(subs=32), _BUDGET)
    assert not ok and any("subs" in b for b in breaches)


def test_serving_budget_flags_latency_ceiling_and_missing_key():
    m = _measured()
    m["run"]["routes"]["transactions"]["latency_ms"]["p99"] = 10_000.0
    del m["sweep"]["admitted_p99_ms_max"]
    ok, breaches = check_serving_budget(m, _BUDGET)
    assert not ok
    assert any("transactions" in b for b in breaches)
    assert any("missing from measurement" in b for b in breaches)


def test_serving_budget_oracle_violations_never_tolerated():
    m = _measured()
    m["run"]["oracle"]["violations"] = 1
    ok, breaches = check_serving_budget(m, _BUDGET)
    assert not ok and any("oracle violations" in b for b in breaches)


def test_serving_budget_requires_shed_engagement():
    m = _measured()
    m["sweep"]["shed_engaged"] = False
    ok, breaches = check_serving_budget(m, _BUDGET)
    assert not ok and any("shed_engaged" in b for b in breaches)


# -- scenarios end-to-end (reduced scale) ------------------------------------


def test_fanout_storm_small_zero_violations(tmp_path):
    from corrosion_tpu.loadgen import scenarios

    async def main():
        return await scenarios.fanout_storm(
            str(tmp_path), subs=32, writes=30, write_rate=30.0,
            read_rate=10.0, pg_rate=5.0, drain_timeout_s=15.0,
        )

    rep = run(main())
    o = rep["oracle"]
    assert o["streams"] == 32
    assert o["commits"] == 30
    assert o["violations"] == 0, o["violation_examples"]
    assert o["missing"] == 0
    # Every stream's group sees its quarter of the commits exactly once:
    # 30 commits spread over 4 groups x 8 streams each.
    assert o["delivered_changes"] + o["delivered_snapshot"] >= 30 * 8
    tx = rep["routes"]["transactions"]
    assert tx["ok"] == 30 and tx["shed"] == 0 and tx["error"] == 0
    assert rep["routes"]["queries"]["ok"] > 0
    assert rep["routes"]["pg"]["ok"] > 0
    # The whole block is emittable through the one self-describing path.
    from corrosion_tpu.loadgen.report import serving_context

    emit_serving_report(
        {**serving_context("fanout_storm", 1, 32), "run": rep}
    )


def test_saturation_sweep_shed_engages_and_accounts(tmp_path):
    from corrosion_tpu.loadgen import scenarios

    async def main():
        return await scenarios.saturation_sweep(
            str(tmp_path), api_concurrency=2, rates=(30.0, 300.0),
            stage_duration_s=1.0, burst=12,
        )

    rep = run(main())
    # The top stage packs 12 concurrent arrivals against a limit of 2:
    # shed MUST engage there, and the server's own accounting must agree
    # with the client's 503 count.
    assert rep["shed_engaged"], rep
    assert rep["stages"][1]["shed"] > 0
    assert rep["shed_accounting_consistent"], (
        rep["shed_total"], rep["server_shed_total"],
    )
    assert rep["admitted_p99_bounded"], rep["admitted_p99_ms_max"]
    # Shed is fast-fail: its p99 must sit well under the bound too.
    shed_ms = rep["stages"][1]["shed_latency_ms"]["p99"]
    assert shed_ms < rep["bounded_p99_ms"]
    json.dumps(rep)  # strict-JSON serializable


@pytest.mark.slow
def test_fanout_storm_2k_subscriptions(tmp_path):
    """The acceptance bar: >= 2k concurrent subscriptions + a sustained
    write storm, zero oracle violations. Slow-marked out of the tier-1
    lane; the loadgen-smoke CI job runs the same shape via the CLI."""
    from corrosion_tpu.loadgen import scenarios

    async def main():
        return await scenarios.fanout_storm(
            str(tmp_path), subs=2000, writes=60, write_rate=10.0,
            drain_timeout_s=60.0,
        )

    rep = run(main())
    o = rep["oracle"]
    assert o["streams"] == 2000
    assert o["violations"] == 0, o["violation_examples"]
    assert o["missing"] == 0
    assert o["delivered_changes"] >= 60 * (2000 // 4)


@pytest.mark.slow
def test_intake_policy_collapse_rule():
    """docs/SCALING.md queue-policy rule, measured: backlog bounded with
    intake sized to the write rate, divergent when starved below it."""
    from corrosion_tpu.loadgen import scenarios

    rep = scenarios.intake_policy()
    assert rep["collapse_rule_holds"], rep
    assert rep["divergence_ratio"] > 3.0
    assert (
        rep["starved"]["tail_slope_per_round"]
        > rep["write_rate_per_round"]
    )
    assert rep["sized"]["staleness_last"] < rep["bounded_ceiling"]


# -- listener-overflow eviction (agent/subs.py + api.py) ---------------------


def test_matcher_overflow_marks_queue_lossy(tmp_path):
    """A listener queue that overflows is marked lossy and counts its
    drops — silent event loss is no longer a legal outcome."""
    from corrosion_tpu.agent.store import Store
    from corrosion_tpu.agent.subs import MatcherHandle
    from corrosion_tpu.core.values import CHANGE_INSERT, QueryEventChange

    store = Store(str(tmp_path / "s.db"), b"\x03" * 16)
    store.apply_schema(
        "CREATE TABLE t (id INTEGER NOT NULL PRIMARY KEY, v TEXT);"
    )
    h = MatcherHandle(store, "SELECT id, v FROM t")
    q = h.attach()
    assert not h.lossy(q)
    h._publish([
        QueryEventChange(
            kind=CHANGE_INSERT, rowid=i, cells=[i, "x"], change_id=i + 1
        )
        for i in range(1030)
    ])
    assert h.lossy(q)
    assert h.dropped_events == 1030 - 1024
    h.detach(q)
    assert not h.lossy(q)
    h.close()
    store.close()


def test_lossy_stream_evicted_and_pump_resumes(tmp_path):
    """End-to-end eviction contract: once a stream's queue is lossy the
    server flushes what IS queued and ends the stream; the pump
    reconnects from the last change id and the oracle stays clean (no
    duplicate, no miss) — dropped events come back via the durable
    replay."""
    from corrosion_tpu.agent.testing import launch_test_agent
    from corrosion_tpu.loadgen.harness import SubscriptionPump

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        pump = None
        try:
            oracle = FanoutOracle()
            pump = SubscriptionPump(
                a.client, "SELECT id, text FROM tests", oracle
            )
            await pump.start()
            loop = asyncio.get_running_loop()

            async def write(i):
                await a.client.execute(
                    [["INSERT INTO tests (id, text) VALUES (?, ?)",
                      [i, f"w{i}"]]]
                )
                oracle.commit(i, (f"w{i}",), t_ack=loop.time())

            for i in range(5):
                await write(i)
            handle = a.agent.subs.get(pump.stream.sub_id)
            # Force the eviction condition (a real overflow needs >1024
            # undrained events — the MECHANISM under test is identical).
            handle._overflowed.add(handle._listeners[0])
            for i in range(5, 12):
                await write(i)
            deadline = loop.time() + 10.0
            while (
                oracle.pending(limit=1) or not oracle._streams[0].reconnects
            ) and loop.time() < deadline:
                await asyncio.sleep(0.05)
            rep = oracle.finish()
            assert rep["reconnects"] >= 1, "stream was never evicted"
            assert rep["violations"] == 0, rep["violation_examples"]
            assert rep["missing"] == 0
        finally:
            if pump is not None:
                await pump.stop()
            await a.stop()

    run(main())
