"""CRDT merge kernel tests: laws + agreement with a host-side model.

The host model folds changes one at a time with the documented rule
(doc/crdts.md: biggest col_version wins, tie -> biggest value; causal length
max governs row liveness). The batched scatter kernel must agree regardless
of batch order — that's the convergence guarantee the reference gets from
cr-sqlite.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.ops import crdt


def host_merge_one(state, key, tup):
    """Fold one (cl, cv, vr) change into dict state by lexicographic max."""
    cur = state.get(key, (0, 0, 0))
    state[key] = max(cur, tup)


def to_host(cells: crdt.CellState):
    cl, cv, vr = map(np.asarray, cells)
    return {
        i: (int(cl[i]), int(cv[i]), int(vr[i]))
        for i in range(len(cl))
        if (cl[i], cv[i], vr[i]) != (0, 0, 0)
    }


def rand_state(rng, k):
    return crdt.CellState(
        cl=jnp.asarray(rng.integers(0, 5, k), dtype=jnp.uint32),
        col_version=jnp.asarray(rng.integers(0, 10, k), dtype=jnp.uint32),
        value_rank=jnp.asarray(rng.integers(0, 100, k), dtype=jnp.uint32),
    )


@pytest.mark.parametrize("seed", range(4))
def test_merge_laws(seed):
    rng = np.random.default_rng(seed)
    k = 64
    a, b, c = (rand_state(rng, k) for _ in range(3))
    m = crdt.merge_cells
    # idempotence
    assert to_host(m(a, a)) == to_host(a)
    # commutativity
    assert to_host(m(a, b)) == to_host(m(b, a))
    # associativity
    assert to_host(m(m(a, b), c)) == to_host(m(a, m(b, c)))


@pytest.mark.parametrize("seed", range(4))
def test_apply_changes_matches_host_fold(seed):
    rng = np.random.default_rng(10 + seed)
    k, b = 32, 200
    state = rand_state(rng, k)
    keys = rng.integers(0, k, b)
    cls = rng.integers(0, 4, b)
    cvs = rng.integers(0, 8, b)
    vrs = rng.integers(0, 50, b)
    mask = rng.random(b) < 0.9

    host = to_host(state)
    for i in range(b):
        if mask[i]:
            host_merge_one(host, int(keys[i]), (int(cls[i]), int(cvs[i]), int(vrs[i])))
    host = {kk: v for kk, v in host.items() if v != (0, 0, 0)}

    batch = crdt.ChangeBatch(
        key=jnp.asarray(keys, dtype=jnp.int32),
        cl=jnp.asarray(cls, dtype=jnp.uint32),
        col_version=jnp.asarray(cvs, dtype=jnp.uint32),
        value_rank=jnp.asarray(vrs, dtype=jnp.uint32),
        mask=jnp.asarray(mask),
    )
    out = crdt.apply_changes(state, batch)
    assert to_host(out) == host


def test_apply_changes_batch_order_invariant():
    rng = np.random.default_rng(99)
    k, b = 16, 64
    state = crdt.make_cells(k)
    keys = rng.integers(0, k, b)
    cls = rng.integers(1, 4, b)
    cvs = rng.integers(1, 6, b)
    vrs = rng.integers(0, 30, b)
    perm = rng.permutation(b)

    def run(order):
        batch = crdt.ChangeBatch(
            key=jnp.asarray(keys[order], dtype=jnp.int32),
            cl=jnp.asarray(cls[order], dtype=jnp.uint32),
            col_version=jnp.asarray(cvs[order], dtype=jnp.uint32),
            value_rank=jnp.asarray(vrs[order], dtype=jnp.uint32),
            mask=jnp.ones(b, dtype=bool),
        )
        return to_host(crdt.apply_changes(state, batch))

    assert run(np.arange(b)) == run(perm)


def test_causal_length_delete_beats_concurrent_update():
    # Row cells live at cl=1. Replica A deletes (cl=2); replica B updates
    # (cl=1, higher col_version). Delete must win on both after exchange.
    base = crdt.CellState(
        cl=jnp.asarray([1], dtype=jnp.uint32),
        col_version=jnp.asarray([3], dtype=jnp.uint32),
        value_rank=jnp.asarray([7], dtype=jnp.uint32),
    )
    a = crdt.local_delete_row(base, jnp.asarray([0]))
    b = crdt.local_write(base, jnp.asarray(0), jnp.asarray(42, dtype=jnp.uint32))
    ab = crdt.merge_cells(a, b)
    ba = crdt.merge_cells(b, a)
    assert not bool(crdt.row_live(ab)[0])
    assert to_host(ab) == to_host(ba)
    # Re-insert resurrects over the delete.
    c = crdt.local_insert_row(ab, jnp.asarray([0]))
    merged = crdt.merge_cells(ab, c)
    assert bool(crdt.row_live(merged)[0])


def test_upsert_on_live_row_keeps_lww_monotonic():
    # Insert onto an already-live row must NOT rewind col_version: a stale
    # remote value would otherwise win the merge.
    base = crdt.CellState(
        cl=jnp.asarray([1], dtype=jnp.uint32),
        col_version=jnp.asarray([5], dtype=jnp.uint32),
        value_rank=jnp.asarray([9], dtype=jnp.uint32),
    )
    upserted = crdt.local_insert_row(base, jnp.asarray([0]))
    assert int(upserted.cl[0]) == 1  # still the same causal epoch
    assert int(upserted.col_version[0]) == 6  # bumped, not reset
    stale_remote = base._replace(col_version=jnp.asarray([3], dtype=jnp.uint32))
    merged = crdt.merge_cells(upserted, stale_remote)
    assert int(merged.col_version[0]) == 6, "stale remote must lose"


def test_lww_tiebreak_on_value_rank():
    a = crdt.CellState(
        cl=jnp.asarray([1], dtype=jnp.uint32),
        col_version=jnp.asarray([5], dtype=jnp.uint32),
        value_rank=jnp.asarray([10], dtype=jnp.uint32),
    )
    b = a._replace(value_rank=jnp.asarray([20], dtype=jnp.uint32))
    out = crdt.merge_cells(a, b)
    assert int(out.value_rank[0]) == 20  # biggest value wins the tie
