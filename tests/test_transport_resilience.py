"""Dissemination-path resilience: black-holing peers, circuit breaker,
datagram SWIM plane.

The reference never lets one unresponsive peer stall gossip: SWIM packets
ride unreliable QUIC datagrams (broadcast/mod.rs:710, transport.rs:66-90)
and broadcast transmits are spawned tasks (broadcast/mod.rs:741-756).
These tests pin the same properties on the host agent: a peer that
accepts nothing (SYN black hole, modeled as a connect that never
completes) must not affect probe cadence or broadcast latency to healthy
peers, and its repeated failures must trip a fail-fast breaker.
"""

from __future__ import annotations

import asyncio
import time

import pytest

from corrosion_tpu.agent.membership import ALIVE
from corrosion_tpu.agent.testing import launch_test_agent, poll_until
from corrosion_tpu.agent.transport import (
    BREAKER_THRESHOLD,
    MAX_DATAGRAM,
    Breaker,
    Transport,
)


def run(coro):
    return asyncio.run(coro)


BLACKHOLE = ("127.0.0.1", 1)


def _blackhole_conn(transport: Transport):
    """Patch ``transport`` so connects to BLACKHOLE behave like a dropped
    SYN (never refused, never completed): they burn the full connect
    timeout, then time out — the same behavior the real ``_conn`` has
    against an unroutable peer."""
    orig = transport._conn

    async def conn(addr, fresh=False):
        if addr == BLACKHOLE:
            await asyncio.sleep(transport.connect_timeout)
            raise asyncio.TimeoutError
        return await orig(addr, fresh)

    transport._conn = conn


def test_breaker_trips_and_recovers():
    br = Breaker()
    assert br.available()
    for _ in range(BREAKER_THRESHOLD - 1):
        br.fail()
    assert br.available()  # below threshold: still closed
    br.fail()
    assert not br.available()  # tripped
    br.ok()
    assert br.available()  # success resets


def test_send_frame_fails_fast_once_tripped(tmp_path):
    async def main():
        t = Transport(connect_timeout=0.3)
        _blackhole_conn(t)
        for _ in range(BREAKER_THRESHOLD):
            assert not await t.send_frame(BLACKHOLE, {"t": "x"})
        # Breaker open: the next send must not wait out the connect timeout.
        t0 = time.monotonic()
        assert not await t.send_frame(BLACKHOLE, {"t": "x"})
        assert time.monotonic() - t0 < 0.05
        # open_session consults the same breaker.
        assert await t.open_session(BLACKHOLE, {"t": "sync_start"}) is None
        t.close()

    run(main())


def test_blackhole_peer_does_not_stall_broadcast_or_probes(tmp_path):
    """The verdict's acceptance test: with a never-ACKing peer in the
    member list, broadcast latency to healthy peers and the SWIM probe
    cadence stay unaffected."""

    async def main():
        a = await launch_test_agent(
            str(tmp_path / "a"), probe_interval=0.1, broadcast_interval=0.05
        )
        b = await launch_test_agent(
            str(tmp_path / "b"), bootstrap=[a.gossip_addr],
            probe_interval=0.1, broadcast_interval=0.05,
        )
        try:
            await poll_until(
                lambda: asyncio.sleep(
                    0, result=len(a.agent.members.alive()) >= 1
                    and len(b.agent.members.alive()) >= 1
                )
            )
            # Inject the black hole: connects to it hang, datagrams vanish.
            _blackhole_conn(a.agent.transport)
            a.agent.transport._udp = None  # drop its datagram path too
            a.agent.members.apply_update("ff" * 16, BLACKHOLE, ALIVE, 0)

            # Broadcast latency to the healthy peer must stay sub-second
            # even though every pending entry also targets the black hole.
            t0 = time.monotonic()
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (42, 'bh')"]]
            )

            async def visible():
                cols, rows = await b.client.query("SELECT id FROM tests")
                return any(r[0] == 42 for r in rows)

            await poll_until(visible, timeout=5.0)
            assert time.monotonic() - t0 < 3.0

            # Probe cadence: b must stay ALIVE in a's view across several
            # probe intervals with the black hole present (no stalled SWIM
            # loop would let the suspect timer fire spuriously).
            await asyncio.sleep(1.0)
            states = {
                m.actor_id: m.state for m in a.agent.members.alive()
            }
            assert states.get(b.agent.actor_id) == ALIVE
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_swim_rides_datagrams(tmp_path):
    """Membership converges with the stream path disabled entirely —
    proving SWIM actually uses the UDP datagram plane."""

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"), probe_interval=0.1)
        b = await launch_test_agent(
            str(tmp_path / "b"), bootstrap=[a.gossip_addr],
            probe_interval=0.1,
        )
        assert a.agent.transport._udp is not None

        # Datagram-size budget: a ping with piggybacked rumors fits foca's
        # packet budget.
        from corrosion_tpu.agent.transport import encode_frame

        ping = {
            "t": "swim", "k": "ping", "seq": 1,
            "from": a.agent.actor_id,
            "from_addr": list(a.gossip_addr),
            "inc": 0,
            "updates": [
                {"id": "ab" * 16, "addr": ["10.0.0.1", 65535],
                 "state": "alive", "inc": 2**31}
                for _ in range(8)
            ],
        }
        assert len(encode_frame(ping)[4:]) <= MAX_DATAGRAM

        try:
            await poll_until(
                lambda: asyncio.sleep(
                    0, result=len(b.agent.members.alive()) >= 1
                )
            )
            # Cut the stream plane on both sides; probes must keep flowing
            # (b stays alive at a, rtts keep accumulating).
            for t in (a.agent.transport, b.agent.transport):
                async def no_stream(addr, msg, _t=t):
                    return False

                t.send_frame = no_stream
            m = a.agent.members.states.get(b.agent.actor_id)
            n0 = len(m.rtts) if m else 0

            async def rtts_grew():
                mm = a.agent.members.states.get(b.agent.actor_id)
                return mm is not None and len(mm.rtts) > n0

            await poll_until(rtts_grew, timeout=5.0)
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_transport_metrics_and_client_endpoints(tmp_path):
    """emit_metrics parity (transport.rs:225+): frame/datagram/byte
    counters and connection/breaker gauges tick under real traffic, and
    outbound datagrams spread over the addr-hashed client endpoints
    (transport.rs:54-57)."""

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"), probe_interval=0.1)
        b = await launch_test_agent(
            str(tmp_path / "b"), bootstrap=[a.gossip_addr],
            probe_interval=0.1,
        )
        try:
            assert len(a.agent.transport._client_udp) == \
                a.agent.transport.N_CLIENT_ENDPOINTS
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (1, 'm')"]]
            )

            async def traffic_counted():
                snap = a.agent.metrics.snapshot()
                return (
                    snap.get("corro_peer_datagrams_sent", 0) >= 1
                    and snap.get("corro_peer_bytes_sent", 0) > 0
                    and snap.get("corro_peer_streams_sent", 0) >= 1
                )

            await poll_until(traffic_counted, timeout=10.0)

            async def b_received():
                snap_b = b.agent.metrics.snapshot()
                return (
                    snap_b.get("corro_peer_datagrams_recv", 0) >= 1
                    and snap_b.get("corro_peer_streams_recv", 0) >= 1
                    and snap_b.get("corro_peer_bytes_recv", 0) > 0
                )

            await poll_until(b_received, timeout=10.0)
        finally:
            await b.stop()
            await a.stop()

    run(main())
