"""Chaos plane tests: declarative fault injection (sim/faults.py),
post-heal invariant checking (sim/invariants.py), and the seeded
fuzzer's shrink-to-minimal-repro loop.

Engine-level runs share two shapes on purpose — a 24-node/20-round tiny
cluster and the invariant suite's standard 48-node scenarios — so the
module pays a handful of compiles, not one per test.
"""

import json

import numpy as np
import pytest

from corrosion_tpu.sim import faults as F
from corrosion_tpu.sim import health as H
from corrosion_tpu.sim import invariants as I
from corrosion_tpu.sim import telemetry as T
from corrosion_tpu.sim.engine import Schedule, simulate
from corrosion_tpu.sim.faults import Fault, FaultPlan

SUITE_ROUNDS = 48  # one length for every standard-scenario run


# ---------------------------------------------------------------------------
# Plan schema: pure host, no engine.


def test_fault_plan_json_roundtrip():
    plan = FaultPlan(64, (
        Fault("loss", 8, 24, prob=0.35, regions=(1, 3)),
        Fault("partition", 10, 30, a=(0,), b=(2,), one_way=True),
        Fault("flap", 6, 26, a=(1,), period=4),
        Fault("churn", 9, 10, nodes=(7, 21), revive_at=28, wipe=True),
        Fault("probe_loss", 8, 24, prob=0.5),
    ), name="rt")
    back = FaultPlan.from_json(plan.to_json())
    assert back == plan
    assert back.heal_round == 30
    assert back.heals
    assert back.wipes() == (7, 21)
    # Validation bites: bad windows, probs, kinds, wipe on non-churn.
    with pytest.raises(ValueError):
        Fault("loss", 10, 10, prob=0.5)
    with pytest.raises(ValueError):
        Fault("loss", 0, 5, prob=0.0)
    with pytest.raises(ValueError):
        Fault("partition", 0, 5)
    with pytest.raises(ValueError):
        Fault("loss", 0, 5, prob=0.3, wipe=True)
    with pytest.raises(ValueError):
        FaultPlan(16, (Fault("loss", 8, 24, prob=0.5),))


def test_compile_semantics_oneway_flap_loss_churn():
    plan = FaultPlan(12, (
        Fault("partition", 2, 6, a=(0,), b=(1,), one_way=True),
        Fault("flap", 4, 10, a=(2,), b=(3,), period=2),
        Fault("loss", 3, 7, prob=0.5, regions=(1,)),
        Fault("loss", 5, 9, prob=0.2),
        Fault("churn", 4, 5, nodes=(6,), revive_at=8, wipe=True),
    ))
    c = plan.compile(n_nodes=10, n_regions=4)
    # One-way: region 1 can't hear region 0; the reverse stays open.
    assert c.partition[3, 1, 0] and not c.partition[3, 0, 1]
    assert not c.partition[1, 1, 0] and not c.partition[6, 1, 0]
    # Flap duty cycle: on for [4,6), off [6,8), on [8,10) — symmetric.
    assert c.partition[4, 3, 2] and c.partition[4, 2, 3]
    assert not c.partition[6, 3, 2]
    assert c.partition[8, 3, 2]
    # Loss: component max per (round, region); scalar view is row max.
    assert c.loss[4, 1] == np.float32(0.5)
    assert c.loss[6, 1] == np.float32(0.5)  # max(0.5, 0.2)
    assert c.loss[8, 1] == np.float32(0.2)
    assert c.loss[4, 0] == 0.0
    assert c.loss_scalar[4] == np.float32(0.5)
    # Churn + wipe masks and the liveness fold.
    assert c.kill[4, 6] and c.wipe[4, 6] and c.revive[8, 6]
    alive = c.alive_curve(10)
    assert not alive[4:8, 6].any() and alive[8, 6] and alive[3, 6]
    assert alive[:, 0].all()
    # Degrading wipe (sparse engine) drops only the wipe axis.
    assert plan.compile(10, 4, allow_wipe=False).wipe is None


def test_shrink_plan_greedy_drop_and_bisect():
    plan = FaultPlan(64, (
        Fault("loss", 4, 20, prob=0.3),
        Fault("partition", 8, 40, a=(0,)),
        Fault("probe_loss", 4, 20, prob=0.5),
    ))

    # Synthetic oracle: fails iff some partition component with side A
    # region 0 covers round 30.
    def still_fails(p):
        return any(
            f.kind == "partition" and 0 in f.a and f.start <= 30 < f.stop
            for f in p.faults
        )

    mini, evals = F.shrink_plan(plan, still_fails, max_evals=32)
    assert len(mini.faults) == 1
    (f,) = mini.faults
    assert f.kind == "partition" and f.start <= 30 < f.stop
    assert f.stop - f.start < 32  # bisection narrowed the window
    assert evals <= 32


def test_recovery_after_heal_helper():
    curves = {
        "need": np.asarray([5, 5, 3, 0, 0, 0]),
        "staleness_sum": np.asarray([2, 2, 0, 1, 0, 0]),
        "swim_undetected_deaths": np.asarray([0, 1, 1, 0, 0, 0]),
        "mismatches": np.asarray([0, 0, 0, 0, 9, 9]),
    }
    rec = H.recovery_after_heal(curves, heal_round=2, round_ms=500.0)
    assert rec["recovered_round"] == 4 and rec["recovery_rounds"] == 2
    assert rec["recovery_s"] == 1.0
    # Sticky mismatches only gate with require_membership.
    rec = H.recovery_after_heal(curves, 2, require_membership=True)
    assert rec["recovered_round"] is None
    # Never-quiet record.
    rec = H.recovery_after_heal({"need": np.asarray([1, 1])}, 0)
    assert rec["recovery_rounds"] is None


# ---------------------------------------------------------------------------
# Tiny engine runs (24 nodes, 2 regions, 20 rounds — one shared shape).


def _tiny(rounds=20, n_cells=16):
    from corrosion_tpu.models.baselines import _cfg

    cfg, topo = _cfg(
        24, writers=[0, 12], regions=[12, 12], sync_interval=4,
        sync_budget=256, sync_chunk=64, n_cells=n_cells,
    )
    writes = np.zeros((rounds, 2), np.uint32)
    writes[:10] = 1
    sched = Schedule(writes=writes).make_samples(8)
    return cfg, topo, sched


def _densified(plan, n=24, r=2):
    return I._densify(plan.compile(n, r), n, r)


def test_fault_free_plan_is_bit_identical():
    """The chaos plane's zero-cost contract: an EMPTY plan threads no
    fault axes and the run is bit-identical to one without a plan."""
    import jax

    cfg, topo, sched = _tiny()
    plain_final, plain_curves = simulate(cfg, topo, sched, seed=5)
    merged = F.apply_plan(sched, FaultPlan(20), n_nodes=24, n_regions=2)
    assert merged.loss is None and merged.wipe is None
    fp_final, fp_curves = simulate(cfg, topo, merged, seed=5)
    for a, b in zip(jax.tree.leaves(plain_final), jax.tree.leaves(fp_final)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in T.ROUND_CURVE_KEYS:
        np.testing.assert_array_equal(plain_curves[k], fp_curves[k], err_msg=k)
    assert fp_curves["chaos_lost_msgs"].sum() == 0


def test_one_way_partition_is_asymmetric():
    """A one-way cut a->b starves b of a's writes while a keeps
    receiving b's — the failure mode a symmetric mask cannot model."""
    cfg, topo, sched = _tiny()
    plan = FaultPlan(20, (
        Fault("partition", 2, 20, a=(0,), b=(1,), one_way=True),
    ))
    merged = F.apply_plan(sched, _densified(plan), n_nodes=24, n_regions=2)
    final, curves = simulate(cfg, topo, merged, seed=5)
    head = np.asarray(final.data.head)
    contig = np.asarray(final.data.contig)
    assert head[0] == 10 and head[1] == 10
    # Region 1 (nodes 12..23) never hears region 0's writer again...
    assert (contig[12:, 0] < head[0]).all()
    # ...while region 0 still converges on region 1's writer.
    assert (contig[:12, 1] == head[1]).all()


def test_loss_burst_drops_messages_only_in_window():
    cfg, topo, sched = _tiny()
    plan = FaultPlan(20, (Fault("loss", 4, 9, prob=0.6),))
    merged = F.apply_plan(sched, _densified(plan), n_nodes=24, n_regions=2)
    final, curves = simulate(cfg, topo, merged, seed=5)
    lost = np.asarray(curves["chaos_lost_msgs"])
    assert lost[4:9].sum() > 0
    assert lost[:4].sum() == 0 and lost[9:].sum() == 0
    # Loss delays but must not prevent convergence (sync heals).
    assert np.asarray(curves["need"])[-1] == 0


def test_probe_loss_hits_membership_not_data():
    cfg, topo, sched = _tiny()
    plan = FaultPlan(20, (Fault("probe_loss", 2, 12, prob=0.7),))
    merged = F.apply_plan(sched, _densified(plan), n_nodes=24, n_regions=2)
    final, curves = simulate(cfg, topo, merged, seed=5)
    assert curves["chaos_lost_msgs"].sum() == 0, "data plane untouched"
    assert curves["swim_false_alarms"].max() > 0, (
        "a probe/ack storm must raise false suspicions"
    )
    assert np.asarray(curves["need"])[-1] == 0


def test_wipe_vs_pause_kill_semantics():
    """Satellite: wipe-on-kill resets replica state (watermarks, queue,
    cells); the default pause-resume kill retains it."""
    cfg, topo, sched = _tiny()
    plan = FaultPlan(20, (
        Fault("churn", 12, 13, nodes=(5,), revive_at=None, wipe=True),
        Fault("churn", 12, 13, nodes=(7,), revive_at=None, wipe=False),
    ))
    merged = F.apply_plan(sched, _densified(plan), n_nodes=24, n_regions=2)
    final, curves = simulate(cfg, topo, merged, seed=5)
    contig = np.asarray(final.data.contig)
    # The wiped node restarted empty and, dead, never recovered anything.
    assert (contig[5] == 0).all()
    assert (np.asarray(final.data.q_writer)[5] == -1).all()
    cells_cl = np.asarray(final.data.cells.cl).reshape(24, -1)
    assert (cells_cl[5] == 0).all()
    # The paused node kept the replica state it died with.
    assert contig[7].sum() > 0
    assert cells_cl[7].sum() > 0
    assert int(curves["chaos_wiped"].sum()) == 1


def test_swim_wipe_units_dense_and_sparse():
    """apply_churn(wipe=...) clears the wiped node's beliefs/queues but
    keeps its incarnation monotonic, in both membership kernels."""
    import jax.numpy as jnp

    from corrosion_tpu.ops import swim, swim_sparse

    n = 8
    cfg = swim.SwimConfig(n_nodes=n)
    st = swim.init_state(cfg)
    st = st._replace(
        view=st.view.at[3, 1].set(swim.pack(jnp.uint32(2), swim.SEV_DOWN)),
        incarnation=st.incarnation.at[3].set(4),
        upd_target=st.upd_target.at[3, 0].set(1),
    )
    wipe = jnp.zeros(n, bool).at[3].set(True)
    out = swim.apply_churn(
        st, wipe, jnp.zeros(n, bool), wipe=wipe
    )
    assert int(np.asarray(out.view)[3].sum()) == 0
    assert (np.asarray(out.upd_target)[3] == -1).all()
    assert int(np.asarray(out.incarnation)[3]) == 4  # kept, not reset
    assert not bool(np.asarray(out.alive)[3])

    scfg = swim.SwimConfig(n_nodes=n, view_capacity=4)
    ss = swim_sparse.init_state(scfg)
    ss = ss._replace(
        exc_tgt=ss.exc_tgt.at[3, 0].set(1),
        exc_pkd=ss.exc_pkd.at[3, 0].set(9),
        incarnation=ss.incarnation.at[3].set(2),
    )
    out = swim_sparse.apply_churn(
        ss, wipe, jnp.zeros(n, bool), wipe=wipe
    )
    assert (np.asarray(out.exc_tgt)[3] == -1).all()
    assert int(np.asarray(out.incarnation)[3]) == 2


def test_determinism_identical_flight_records(tmp_path):
    """Satellite: identical seed + identical FaultPlan => identical
    flight records across two runs. Every protocol datum matches; only
    the wall-clock fields (header t_unix, chunk wall_s) may differ —
    this is what makes the fuzzer's JSON repros replayable."""
    cfg, topo, sched = _tiny()
    plan = FaultPlan(20, (
        Fault("loss", 3, 9, prob=0.4),
        Fault("churn", 5, 6, nodes=(9,), revive_at=12, wipe=True),
    ))
    merged = F.apply_plan(sched, _densified(plan), n_nodes=24, n_regions=2)

    def fly(path):
        tele = T.KernelTelemetry(
            engine="dense",
            recorder=T.FlightRecorder(path, engine="dense", mode="w"),
        )
        simulate(cfg, topo, merged, seed=7, telemetry=tele)
        tele.recorder.close()
        out = []
        with open(path) as f:
            for line in f:
                rec = json.loads(line)
                rec.pop("t_unix", None)
                rec.pop("wall_s", None)
                out.append(rec)
        return out

    a = fly(str(tmp_path / "a.jsonl"))
    b = fly(str(tmp_path / "b.jsonl"))
    assert a == b
    assert sum(1 for r in a if r["kind"] == "round") == 20


# ---------------------------------------------------------------------------
# Invariant suite on the standard scenarios (48 rounds shared).


@pytest.mark.slow  # tier-1 budget; the chaos CI job runs this file unfiltered
def test_invariant_suite_dense_crash_wipe_recovers():
    plans = F.named_scenarios(
        SUITE_ROUNDS, I.STD_REGIONS, I.STD_NODES, protect=I.PROTECTED
    )
    rep = I.run_dense(plans["crash-wipe"], seed=0)
    assert rep.ok, rep.violations
    assert rep.recovery["recovery_rounds"] is not None
    assert rep.facts["chaos_wiped"] > 0


@pytest.mark.slow  # tier-1 budget; the chaos CI job runs this file unfiltered
def test_partition_heal_sparse_engine():
    """Satellite: partition-heal convergence on the SPARSE engine is
    checked against the sparse serial-merge reference (previously only
    the dense plane was verified after a heal)."""
    plans = F.named_scenarios(
        SUITE_ROUNDS, I.STD_REGIONS, I.STD_NODES, protect=I.PROTECTED
    )
    rep = I.run_sparse(plans["partition-heal"], seed=0)
    assert rep.ok, rep.violations
    assert rep.recovery["recovery_rounds"] is not None


@pytest.mark.slow  # tier-1 budget; the chaos CI job runs this file unfiltered
def test_partition_heal_mixed_engine():
    """Satellite: partition-heal convergence on the MIXED engine —
    watermarks, CRDT cells (big versions included), and stream
    reassembly all verified after the cut clears."""
    plans = F.named_scenarios(
        SUITE_ROUNDS, I.STD_REGIONS, I.STD_NODES, protect=I.PROTECTED
    )
    rep = I.run_mixed(plans["partition-heal"], seed=0)
    assert rep.ok, rep.violations


@pytest.mark.slow  # tier-1 budget; the chaos CI job runs this file unfiltered
def test_chunk_engine_loss_and_wipe_recovers():
    plans = F.named_scenarios(
        SUITE_ROUNDS, I.STD_REGIONS, I.STD_NODES, protect=I.PROTECTED
    )
    rep = I.run_chunks(plans["crash-wipe"], seed=0)
    assert rep.ok, rep.violations
    rep = I.run_chunks(plans["loss-burst"], seed=0)
    assert rep.ok, rep.violations
    assert rep.facts["chaos_lost_msgs"] > 0


@pytest.mark.slow  # tier-1 budget; the chaos CI job runs this file unfiltered
def test_broken_plan_fails_and_shrinks_to_repro(tmp_path):
    """Acceptance: a deliberately non-healing plan fails the invariant
    suite, shrinks to a minimal JSON repro artifact, and the artifact
    replays to the same failure."""
    out = I.fuzz(
        seed=1, plans=1, engines=("dense",), rounds=SUITE_ROUNDS,
        out_dir=str(tmp_path), break_heal=True, shrink_evals=6,
    )
    assert out["failures"] == 1
    assert len(out["repros"]) == 1
    path = out["repros"][0]
    with open(path) as f:
        repro = json.load(f)
    assert repro["schema"] == I.REPRO_SCHEMA
    mini = FaultPlan.from_dict(repro["plan"])
    orig = FaultPlan.from_dict(repro["original_plan"])
    assert len(mini.faults) <= len(orig.faults)
    assert repro["violations"], "the minimal plan still states violations"
    # Round-trip: the artifact reproduces the failure.
    rep = I.replay_repro(path)
    assert not rep.ok


def test_chaos_cli_run_and_fuzz(tmp_path, capsys):
    from corrosion_tpu import cli

    assert cli.main(["chaos", "list"]) == 0
    text = capsys.readouterr().out
    assert "partition-heal" in text and "crash-wipe" in text

    # Named scenario on one engine (shares the suite's jit cache).
    rc = cli.main([
        "chaos", "run", "partition-heal", "--engines", "dense",
        "--rounds", str(SUITE_ROUNDS),
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "[dense] OK" in out

    # Broken fuzz: exit 1 + artifact (same shapes as the test above).
    rc = cli.main([
        "chaos", "fuzz", "--seed", "1", "--plans", "1", "--engines",
        "dense", "--rounds", str(SUITE_ROUNDS), "--broken",
        "--out", str(tmp_path), "--shrink-evals", "4",
    ])
    out = capsys.readouterr().out
    assert rc == 1
    assert "shrunk repro" in out
    repros = list(tmp_path.glob("chaos_repro_*.json"))
    assert len(repros) == 1
    # The artifact replays through the CLI too.
    assert cli.main(["chaos", "replay", str(repros[0])]) == 1


def test_schedule_checkpoint_roundtrips_fault_axes(tmp_path):
    """save_schedule/load_schedule must persist the chaos axes — a
    resumed run replays its fault plan, not a defanged one."""
    from corrosion_tpu.sim import checkpoint

    cfg, topo, sched = _tiny()
    plan = FaultPlan(20, (
        Fault("loss", 3, 9, prob=0.4, regions=(1,)),
        Fault("probe_loss", 2, 8, prob=0.5),
        Fault("churn", 5, 6, nodes=(9,), revive_at=12, wipe=True),
    ))
    merged = F.apply_plan(
        sched, plan.compile(24, 2), n_nodes=24, n_regions=2
    )
    path = str(tmp_path / "sched.npz")
    checkpoint.save_schedule(path, merged)
    back = checkpoint.load_schedule(path)
    for name in ("writes", "kill", "revive", "loss", "probe_loss", "wipe"):
        np.testing.assert_array_equal(
            getattr(back, name), getattr(merged, name), err_msg=name
        )
    # Fault-free schedules still round-trip with absent axes.
    checkpoint.save_schedule(path, sched)
    assert checkpoint.load_schedule(path).loss is None


def test_chaos_cli_usage_errors_exit_2(tmp_path, capsys):
    from corrosion_tpu import cli

    bad = tmp_path / "bad.json"
    bad.write_text('{"not_a_plan": true}')
    assert cli.main(["chaos", "run", str(bad), "--engines", "dense"]) == 2
    capsys.readouterr()
    assert cli.main(["chaos", "list", "--rounds", "20"]) == 2
    capsys.readouterr()
    # A plan addressing regions past the standard scenario's shape is a
    # usage error, not a traceback.
    oob = tmp_path / "oob.json"
    oob.write_text(FaultPlan(48, (
        Fault("loss", 2, 8, prob=0.3, regions=(7,)),
    )).to_json())
    assert cli.main(["chaos", "run", str(oob), "--engines", "dense"]) == 2
    capsys.readouterr()
    assert cli.main(["chaos", "replay", str(bad)]) == 2


def test_sparse_engine_rejects_wipe_loudly():
    from corrosion_tpu.sim.sparse_engine import simulate_sparse

    cfg, topo, sched = I._sparse_scenario(FaultPlan(16), seed=0)
    plan = FaultPlan(16, (
        Fault("churn", 2, 3, nodes=(40,), revive_at=8, wipe=True),
    ))
    bad = F.apply_plan(
        sched, plan.compile(I.STD_NODES, I.STD_REGIONS),
        I.STD_NODES, I.STD_REGIONS,
    )
    with pytest.raises(ValueError, match="wipe"):
        simulate_sparse(cfg, topo, bad, seed=0)
