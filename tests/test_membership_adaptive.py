"""Cluster-size-adaptive SWIM config + down-member GC.

The reference resizes foca's config on cluster-size notifications
(agent.rs:1345-1358 → make_foca_config, broadcast/mod.rs:704-713) and
forgets down members after remove_down_after (48 h WAN preset). Host and
kernel sides both implement the semantics.
"""

from __future__ import annotations

import asyncio
import time

import jax
import jax.numpy as jnp

from corrosion_tpu.agent.membership import ALIVE, DOWN, Members, Swim
from corrosion_tpu.ops import swim as swim_ops
from corrosion_tpu.ops import swim_sparse
from corrosion_tpu.ops.swim import SEV_DOWN, SwimConfig, pack, packed_sev


async def _noop_send(addr, msg):
    return True


def _swim(members) -> Swim:
    return Swim(members, ("127.0.0.1", 0), _noop_send, max_transmissions=4)


def test_config_adapts_3_to_32():
    members = Members("me")
    sw = _swim(members)
    for i in range(2):  # 3-node cluster (self + 2)
        members.apply_update(f"{i:032x}", ("10.0.0.1", i + 1), ALIVE, 0)
    asyncio.run(sw.probe_round())
    tx_small = sw.max_transmissions
    ind_small = sw.indirect_probes
    for i in range(2, 31):  # grow to 32
        members.apply_update(f"{i:032x}", ("10.0.0.1", i + 1), ALIVE, 0)
    asyncio.run(sw.probe_round())
    assert sw.max_transmissions > tx_small  # ~1.5·log2(n) growth
    assert sw.indirect_probes >= ind_small
    assert sw.max_transmissions >= 7  # ceil(1.5·log2(33))


def test_host_down_member_gc():
    members = Members("me")
    sw = _swim(members)
    sw.down_gc_s = 0.05
    members.apply_update("aa" * 16, ("10.0.0.1", 1), ALIVE, 0)
    members.apply_update("bb" * 16, ("10.0.0.2", 1), ALIVE, 0)
    members.apply_update("bb" * 16, ("10.0.0.2", 1), DOWN, 0)
    assert "bb" * 16 in members.states
    time.sleep(0.1)
    asyncio.run(sw.probe_round())
    assert "bb" * 16 not in members.states  # horizon passed: forgotten
    assert "aa" * 16 in members.states  # alive member untouched


def test_dense_kernel_down_gc():
    cfg = SwimConfig(n_nodes=8, down_gc_rounds=1)  # forget every round
    state = swim_ops.init_state(cfg)
    # Everyone believes node 3 is down at incarnation 2.
    view = state.view.at[:, 3].set(pack(jnp.uint32(2), SEV_DOWN))
    state = state._replace(view=view, alive=state.alive.at[3].set(False))
    state = swim_ops.swim_round(
        state, jax.random.PRNGKey(0), jnp.int32(0), cfg
    )
    assert not bool(
        jnp.any(packed_sev(state.view[:, 3]) == SEV_DOWN)
    ), "down beliefs must be forgotten at the GC horizon"


def test_sparse_kernel_down_gc_frees_slots():
    cfg = SwimConfig(n_nodes=8, view_capacity=4, down_gc_rounds=1)
    state = swim_sparse.init_state(cfg)
    exc_tgt = state.exc_tgt.at[:, 0].set(3)
    exc_pkd = state.exc_pkd.at[:, 0].set(pack(jnp.uint32(2), SEV_DOWN))
    state = state._replace(
        exc_tgt=exc_tgt, exc_pkd=exc_pkd, alive=state.alive.at[3].set(False)
    )
    state = swim_sparse.swim_round(
        state, jax.random.PRNGKey(0), jnp.int32(0), cfg
    )
    down_slots = (packed_sev(state.exc_pkd) == SEV_DOWN) & (state.exc_tgt == 3)
    assert not bool(jnp.any(down_slots)), "GC must free the down slots"


def test_sparse_gc_disabled_keeps_down():
    cfg = SwimConfig(n_nodes=8, view_capacity=4, down_gc_rounds=0)
    state = swim_sparse.init_state(cfg)
    exc_tgt = state.exc_tgt.at[:, 0].set(3)
    exc_pkd = state.exc_pkd.at[:, 0].set(pack(jnp.uint32(2), SEV_DOWN))
    state = state._replace(
        exc_tgt=exc_tgt, exc_pkd=exc_pkd, alive=state.alive.at[3].set(False)
    )
    state = swim_sparse.swim_round(
        state, jax.random.PRNGKey(0), jnp.int32(0), cfg
    )
    kept = (packed_sev(state.exc_pkd) == SEV_DOWN) & (state.exc_tgt == 3)
    assert bool(jnp.any(kept))
