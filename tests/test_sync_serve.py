"""Direct unit tests of the sync server's need-serving — the analogue of
test_handle_known_version (peer.rs:1529), which drives handle_known_version
with a channel-backed sender and no network: Current versions stream their
changesets, Cleared spans stream as cleared ranges, Partial versions serve
buffered seq ranges.
"""

import asyncio

from corrosion_tpu.agent.agent import Agent, AgentConfig
from corrosion_tpu.agent.testing import TEST_SCHEMA
from corrosion_tpu.core.bookkeeping import (
    CLEARED,
    Current,
    FullNeed,
    Partial,
    PartialNeed,
)
from corrosion_tpu.core.intervals import RangeSet
from corrosion_tpu.core.values import Change, Statement


class FakeSession:
    def __init__(self):
        self.frames = []

    async def send(self, frame):
        self.frames.append(frame)


def run(coro):
    return asyncio.run(coro)


def make_agent(tmp_path) -> Agent:
    return Agent(AgentConfig(data_dir=str(tmp_path), schema_sql=TEST_SCHEMA))


def test_serve_full_need_streams_current_versions(tmp_path):
    a = make_agent(tmp_path)
    try:
        for i in range(3):
            a.execute(
                [Statement("INSERT INTO tests (id, text) VALUES (?, ?)",
                           params=[i, f"row{i}"])]
            )
        booked = a.bookie.for_actor(a.actor_id)
        assert booked.last() == 3

        async def main():
            s = FakeSession()
            await a._serve_need(s, a.actor_id, booked, FullNeed(1, 3))
            return s.frames

        frames = run(main())
        assert [f["t"] for f in frames] == ["sync_changes"] * 3
        assert [f["version"] for f in frames] == [1, 2, 3]
        # Each frame is a complete changeset: seqs [0, last_seq].
        for f in frames:
            assert f["seqs"][0] == 0 and f["seqs"][1] == f["last_seq"]
            assert len(f["changes"]) >= 1
        # A need outside the held range serves nothing.
        async def none():
            s = FakeSession()
            await a._serve_need(s, a.actor_id, booked, FullNeed(7, 9))
            return s.frames

        assert run(none()) == []
    finally:
        a.store.close()


def test_serve_full_need_sends_cleared_spans(tmp_path):
    a = make_agent(tmp_path)
    try:
        for i in range(4):
            a.execute(
                [Statement("INSERT INTO tests (id, text) VALUES (?, ?)",
                           params=[10 + i, "x"])]
            )
        booked = a.bookie.for_actor(a.actor_id)
        booked.insert_many(1, 2, CLEARED)

        async def main():
            s = FakeSession()
            await a._serve_need(s, a.actor_id, booked, FullNeed(1, 4))
            return s.frames

        frames = run(main())
        kinds = [f["t"] for f in frames]
        assert kinds[0] == "sync_cleared"
        assert frames[0]["versions"] == [(1, 2)]
        # The still-current tail streams as changesets.
        assert [f["version"] for f in frames[1:]] == [3, 4]
        # Cleared range clipped to the need window (partial overlap).
        async def clipped():
            s = FakeSession()
            await a._serve_need(s, a.actor_id, booked, FullNeed(2, 3))
            return s.frames

        frames = run(clipped())
        assert frames[0]["t"] == "sync_cleared"
        assert frames[0]["versions"] == [(2, 2)]
    finally:
        a.store.close()


def test_serve_partial_need_serves_buffered_seq_ranges(tmp_path):
    a = make_agent(tmp_path)
    try:
        actor = "ab" * 16  # a remote actor
        site = bytes.fromhex(actor)
        booked = a.bookie.for_actor(actor)
        # Buffer seqs 0-1 and 4-5 of a 6-seq version (gap at 2-3), like
        # process_incomplete_version would (agent.rs:2063-2151).
        rows = []
        for seq in (0, 1, 4, 5):
            rows.append(Change(
                table="tests", pk=b"\x01", cid="text", val=f"s{seq}",
                col_version=1, db_version=9, seq=seq, site_id=site, cl=1,
            ))
        with a.store._wlock("test_seed"):
            for ch in rows:
                a.store.conn.execute(
                    "INSERT INTO __corro_buffered_changes VALUES"
                    " (?, 5, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
                    (site, ch.table, ch.pk, ch.cid, ch.val, ch.col_version,
                     ch.db_version, ch.seq, ch.site_id, ch.cl),
                )
        booked.insert(
            5, Partial(seqs=RangeSet([(0, 1), (4, 5)]), last_seq=6, ts=0)
        )

        async def main(need):
            s = FakeSession()
            await a._serve_need(s, actor, booked, need)
            return s.frames

        # Request exactly the buffered ranges.
        frames = run(main(PartialNeed(5, [(0, 1), (4, 5)])))
        assert [f["t"] for f in frames] == ["sync_changes"] * 2
        assert frames[0]["seqs"] == [0, 1] and frames[1]["seqs"] == [4, 5]
        assert [c[6] for c in frames[0]["changes"]] == [0, 1]  # seq column
        # A range covering the gap serves only what is buffered.
        frames = run(main(PartialNeed(5, [(0, 5)])))
        assert len(frames) == 1
        assert frames[0]["seqs"] == [0, 5]
        assert [c[6] for c in frames[0]["changes"]] == [0, 1, 4, 5]
        # Ranges entirely inside the gap serve nothing.
        assert run(main(PartialNeed(5, [(2, 3)]))) == []
        # A PartialNeed for a version we hold as Current is ignored (the
        # client's state was stale).
        booked2 = a.bookie.for_actor(a.actor_id)
        assert run(main(PartialNeed(99, [(0, 1)]))) == []
    finally:
        a.store.close()


def test_serve_partial_need_from_current_version(tmp_path):
    """A partial need against a holder of the COMPLETE version must be
    served from the applied changeset (sync.rs:248-266): the requester's
    gaps came from lossy dissemination, and once every peer compacted
    the version to Current, a Partial-only server would strand it
    forever (regression: a 2-node catch-up wedged at 39/40 versions
    permanently until this branch existed)."""
    a = make_agent(tmp_path)
    try:
        a.execute(
            [Statement(
                "INSERT INTO tests (id, text) VALUES (1, 'a'), (2, 'b'),"
                " (3, 'c')"
            )]
        )
        booked = a.bookie.for_actor(a.actor_id)
        known = booked.get(1)
        assert isinstance(known, Current) and known.last_seq == 2

        async def main(need):
            s = FakeSession()
            await a._serve_need(s, a.actor_id, booked, need)
            return s.frames

        # The requester holds seq 0 and lacks 1..2.
        frames = run(main(PartialNeed(1, [(1, 2)])))
        assert [f["t"] for f in frames] == ["sync_changes"]
        assert frames[0]["version"] == 1
        assert frames[0]["seqs"] == [1, 2]
        assert frames[0]["last_seq"] == 2
        assert [c[6] for c in frames[0]["changes"]] == [1, 2]
        # Ranges beyond the version's seqs serve nothing.
        assert run(main(PartialNeed(1, [(5, 9)]))) == []
    finally:
        a.store.close()
