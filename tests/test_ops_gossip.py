"""Data-plane kernel tests: broadcast dissemination + anti-entropy sync.

Scenarios mirror the reference's integration tests (SURVEY.md §4):
insert_rows_and_gossip (write → cluster-wide visibility), large_tx_sync
(late joiner catches up via sync), and partition healing.
"""

import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.ops import gossip


def mk(n, regions=None, writers=None, **kw):
    regions = regions or [n]
    writers = writers if writers is not None else list(range(n))
    cfg = gossip.GossipConfig(n_nodes=n, n_writers=len(writers), **kw)
    topo = gossip.make_topology(regions, writers)
    data = gossip.init_data(cfg)
    return cfg, topo, data


def no_partition(regions=1):
    return jnp.zeros((regions, regions), dtype=bool)


def run(cfg, topo, data, rounds, writes_fn=None, alive=None, part=None,
        seed=0, start=0, sync=True):
    n = cfg.n_nodes
    alive = jnp.ones(n, bool) if alive is None else alive
    part = no_partition(int(jnp.max(topo.region)) + 1) if part is None else part
    key = jax.random.PRNGKey(seed)
    for r in range(start, start + rounds):
        key, k1, k2 = jax.random.split(key, 3)
        w = writes_fn(r) if writes_fn else jnp.zeros(cfg.n_writers, jnp.uint32)
        data, _ = gossip.broadcast_round(data, topo, alive, part, w, k1, cfg)
        if sync:
            data, _ = gossip.sync_round(data, topo, alive, part, jnp.int32(r), k2, cfg)
    return data


def test_single_write_reaches_everyone():
    cfg, topo, data = mk(12)
    one = jnp.zeros(12, jnp.uint32).at[3].set(1)
    data = run(cfg, topo, data, 1, writes_fn=lambda r: one)
    assert int(data.head[3]) == 1
    data = run(cfg, topo, data, 15, start=1)
    # Everyone holds version 1 of writer 3.
    assert bool((data.contig[:, 3] >= 1).all())


def test_burst_stays_in_order_and_converges():
    cfg, topo, data = mk(10, max_writes_per_round=4)
    burst = jnp.zeros(10, jnp.uint32).at[0].set(4)
    data = run(cfg, topo, data, 3, writes_fn=lambda r: burst)  # 12 versions
    assert int(data.head[0]) == 12
    data = run(cfg, topo, data, 25, start=3)
    assert bool((data.contig[:, 0] == 12).all())
    # Invariants: contig <= seen <= head.
    assert bool((data.contig <= data.seen).all())
    assert bool((data.seen[:, 0] <= data.head[0]).all())


def test_broadcast_only_no_sync_mostly_converges():
    cfg, topo, data = mk(10)
    one = jnp.zeros(10, jnp.uint32).at[2].set(1)
    data = run(cfg, topo, data, 1, writes_fn=lambda r: one, sync=False)
    data = run(cfg, topo, data, 20, start=1, sync=False)
    # Epidemic fanout alone should reach everyone without loss.
    assert bool((data.contig[:, 2] >= 1).all())


def test_late_joiner_catches_up_via_sync():
    # Node 9 is down while writer 0 commits 40 versions; on revival,
    # anti-entropy (not broadcast — tx budgets are exhausted) catches it up.
    cfg, topo, data = mk(10, sync_interval=4, sync_budget=32, sync_chunk=32)
    alive = jnp.ones(10, bool).at[9].set(False)
    w = jnp.zeros(10, jnp.uint32).at[0].set(2)
    data = run(cfg, topo, data, 20, writes_fn=lambda r: w, alive=alive)
    assert int(data.head[0]) == 40
    assert int(data.contig[9, 0]) == 0
    data = run(cfg, topo, data, 30, start=20)
    assert int(data.contig[9, 0]) == 40, "late joiner must fully catch up"


def test_partition_blocks_then_heals():
    # Two regions; cut the link; writes in region 0 stay invisible to
    # region 1 until the partition heals (config 5's WAN scenario).
    cfg, topo, data = mk(12, regions=[6, 6], sync_interval=3)
    cut = jnp.array([[False, True], [True, False]])
    w = jnp.zeros(12, jnp.uint32).at[1].set(1)
    data = run(cfg, topo, data, 12, writes_fn=lambda r: w if r < 5 else jnp.zeros(12, jnp.uint32), part=cut)
    assert int(data.head[1]) == 5
    assert bool((data.contig[:6, 1] == 5).all()), "region 0 converges internally"
    assert int(jnp.max(data.contig[6:, 1])) == 0, "partition blocks region 1"
    data = run(cfg, topo, data, 25, start=12)  # healed
    assert bool((data.contig[6:, 1] == 5).all()), "heal lets region 1 catch up"


def test_sync_budget_caps_transfer():
    cfg, topo, data = mk(4, sync_interval=1, sync_budget=8, sync_chunk=8,
                         fanout_near=0, fanout_far=0)  # sync only
    w = jnp.zeros(4, jnp.uint32).at[0].set(4)
    # 10 rounds x 4 writes = 40 versions, no broadcast fanout at all.
    data = run(cfg, topo, data, 10, writes_fn=lambda r: w)
    # Per sync session a node can gain at most 8 versions of writer 0.
    # After enough rounds everyone still converges.
    data = run(cfg, topo, data, 30, start=10)
    assert bool((data.contig[:, 0] == 40).all())


def test_loss_is_healed():
    cfg, topo, data = mk(10, loss_prob=0.4, sync_interval=5)
    w = jnp.zeros(10, jnp.uint32).at[4].set(1)
    data = run(cfg, topo, data, 10, writes_fn=lambda r: w)
    data = run(cfg, topo, data, 40, start=10)
    assert bool((data.contig[:, 4] == 10).all())


def test_visibility_helper():
    cfg, topo, data = mk(6)
    one = jnp.zeros(6, jnp.uint32).at[0].set(1)
    data = run(cfg, topo, data, 12, writes_fn=lambda r: one if r == 0 else jnp.zeros(6, jnp.uint32))
    vis = gossip.visibility(data, jnp.array([0]), jnp.array([1], dtype=jnp.uint32))
    assert vis.shape == (1, 6)
    assert bool(vis.all())
