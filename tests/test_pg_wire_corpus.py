"""libpq/psycopg wire-corpus replay against serve_pg (VERDICT r4 weak #5).

No PostgreSQL driver ships in this environment, so each fixture is the
exact byte sequence libpq emits for the flow (framed per the v3 protocol
docs and psycopg's observable behavior): extended-protocol prepare/bind
with BINARY parameters, error-mid-transaction recovery (SQLSTATE 25P02,
ReadyForQuery status bytes I/T/E), Describe(statement) of a join, and
clean feature_not_supported (0A000) errors for COPY/LISTEN — the
connection stays usable after each.
"""

import asyncio
import struct
import tempfile

from corrosion_tpu.agent.testing import launch_test_agent


def run(coro):
    return asyncio.run(coro)


def _m(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack(">I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


def parse_msg(tag, name, query, oids=()):
    body = _cstr(name) + _cstr(query) + struct.pack(">H", len(oids))
    for o in oids:
        body += struct.pack(">I", o)
    return _m(b"P", body)


def bind_msg(portal, stmt, fmts, values, rfmts):
    body = _cstr(portal) + _cstr(stmt)
    body += struct.pack(">H", len(fmts))
    for f in fmts:
        body += struct.pack(">H", f)
    body += struct.pack(">H", len(values))
    for v in values:
        if v is None:
            body += struct.pack(">i", -1)
        else:
            body += struct.pack(">i", len(v)) + v
    body += struct.pack(">H", len(rfmts))
    for f in rfmts:
        body += struct.pack(">H", f)
    return _m(b"B", body)


def describe_msg(kind, name):
    return _m(b"D", kind + _cstr(name))


def execute_msg(portal, maxrows=0):
    return _m(b"E", _cstr(portal) + struct.pack(">I", maxrows))


SYNC = _m(b"S", b"")
QUERY = lambda sql: _m(b"Q", _cstr(sql))  # noqa: E731

# libpq startup: protocol 196608 + user/database/application_name (the
# parameter set psql/psycopg actually send).
STARTUP_PARAMS = (
    b"user\x00postgres\x00database\x00corrosion\x00"
    b"application_name\x00psql\x00client_encoding\x00UTF8\x00\x00"
)


class Conn:
    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer

    @classmethod
    async def connect(cls, host, port):
        reader, writer = await asyncio.open_connection(host, port)
        payload = struct.pack(">I", 196608) + STARTUP_PARAMS
        writer.write(struct.pack(">I", len(payload) + 4) + payload)
        await writer.drain()
        self = cls(reader, writer)
        msgs = await self.until_ready()
        assert any(t == b"R" for t, _ in msgs)
        return self

    async def send(self, raw: bytes):
        self.writer.write(raw)
        await self.writer.drain()

    async def read_msg(self):
        header = await self.reader.readexactly(5)
        (length,) = struct.unpack(">I", header[1:5])
        return header[0:1], await self.reader.readexactly(length - 4)

    async def until_ready(self):
        out = []
        while True:
            tag, payload = await self.read_msg()
            out.append((tag, payload))
            if tag == b"Z":
                return out

    def close(self):
        self.writer.close()


def tags(msgs):
    return [t for t, _ in msgs]


def ready_status(msgs):
    return [p for t, p in msgs if t == b"Z"][-1]


def sqlstate(msgs):
    for t, p in msgs:
        if t == b"E":
            fields = p.split(b"\x00")
            for f in fields:
                if f[:1] == b"C":
                    return f[1:].decode()
    return None


def command_tags(msgs):
    return [p.rstrip(b"\x00").decode() for t, p in msgs if t == b"C"]


def data_rows(msgs):
    out = []
    for t, p in msgs:
        if t != b"D":
            continue
        (n,) = struct.unpack_from(">H", p, 0)
        off = 2
        row = []
        for _ in range(n):
            (ln,) = struct.unpack_from(">i", p, off)
            off += 4
            if ln < 0:
                row.append(None)
            else:
                row.append(p[off : off + ln])
                off += ln
        out.append(row)
    return out


async def _with_agent(schema, fn):
    with tempfile.TemporaryDirectory() as d:
        a = await launch_test_agent(d, schema=schema)
        from corrosion_tpu.agent.pg import serve_pg

        server, (host, port) = await serve_pg(a.agent)
        try:
            conn = await Conn.connect(host, port)
            try:
                await fn(conn, a)
            finally:
                conn.close()
        finally:
            server.close()
            await server.wait_closed()
            await a.stop()


SCHEMA = (
    "CREATE TABLE t1 (id INTEGER PRIMARY KEY, name TEXT DEFAULT '');\n"
    "CREATE TABLE t2 (id INTEGER PRIMARY KEY, t1_id INTEGER DEFAULT 0,"
    " note TEXT DEFAULT '');"
)


def test_extended_flow_with_binary_params():
    """psycopg3 binary-parameter flow: Parse named stmt with OIDs,
    Describe(statement), Bind with format=1 int4/text params, Execute,
    Sync — then a binary-RESULT select reads the row back."""

    async def fn(conn, a):
        await conn.send(
            parse_msg(
                b"P", "s1",
                "INSERT INTO t1 (id, name) VALUES ($1, $2)",
                oids=(23, 25),  # int4, text
            )
            + describe_msg(b"S", "s1")
            + bind_msg(
                "", "s1", [1, 0],
                [struct.pack(">i", 41), b"bin-row"], [],
            )
            + execute_msg("")
            + SYNC
        )
        msgs = await conn.until_ready()
        ts = tags(msgs)
        # ParseComplete, ParameterDescription, NoData (a write),
        # BindComplete, CommandComplete, ReadyForQuery.
        assert ts[0] == b"1" and b"t" in ts and b"2" in ts
        assert "INSERT 0 1" in command_tags(msgs)
        assert ready_status(msgs) == b"I"
        # ParameterDescription carries the two declared OIDs.
        pd = [p for t, p in msgs if t == b"t"][0]
        assert struct.unpack_from(">H", pd, 0)[0] == 2
        assert struct.unpack_from(">I", pd, 2)[0] == 23
        assert struct.unpack_from(">I", pd, 6)[0] == 25

        # Binary RESULT format: int4 column comes back big-endian.
        await conn.send(
            parse_msg(b"P", "q1", "SELECT id, name FROM t1 WHERE id = $1",
                      oids=(23,))
            + bind_msg("", "q1", [1], [struct.pack(">i", 41)], [1, 0])
            + describe_msg(b"P", "")
            + execute_msg("")
            + SYNC
        )
        msgs = await conn.until_ready()
        rows = data_rows(msgs)
        assert len(rows) == 1
        # Column 1 binary (int8/int4 big-endian), column 2 text.
        assert int.from_bytes(rows[0][0], "big") == 41
        assert rows[0][1] == b"bin-row"

    run(_with_agent(SCHEMA, fn))


def test_error_mid_transaction_recovery():
    """libpq's failed-transaction flow: BEGIN (ready=T), failing
    statement (ready=E), subsequent statement refused with 25P02, COMMIT
    of a failed block reports ROLLBACK, and nothing was applied; a fresh
    BEGIN..COMMIT then lands atomically."""

    async def fn(conn, a):
        m = await conn.send(QUERY("BEGIN")) or await conn.until_ready()
        assert "BEGIN" in command_tags(m) and ready_status(m) == b"T"
        m = await conn.send(
            QUERY("INSERT INTO t1 (id, name) VALUES (1, 'a')")
        ) or await conn.until_ready()
        assert "INSERT 0 1" in command_tags(m)
        assert ready_status(m) == b"T"
        # Syntax error fails the block.
        m = await conn.send(
            QUERY("INSERT INTO t1 (id, nosuchcol) VALUES (2, 'b')")
        ) or await conn.until_ready()
        assert sqlstate(m) is not None and ready_status(m) == b"E"
        # Anything else now refuses with 25P02 until the block ends.
        m = await conn.send(
            QUERY("INSERT INTO t1 (id, name) VALUES (3, 'c')")
        ) or await conn.until_ready()
        assert sqlstate(m) == "25P02" and ready_status(m) == b"E"
        m = await conn.send(QUERY("SELECT 1")) or await conn.until_ready()
        assert sqlstate(m) == "25P02"
        # COMMIT of a failed block rolls back.
        m = await conn.send(QUERY("COMMIT")) or await conn.until_ready()
        assert "ROLLBACK" in command_tags(m) and ready_status(m) == b"I"
        m = await conn.send(
            QUERY("SELECT count(*) FROM t1")
        ) or await conn.until_ready()
        assert data_rows(m) == [[b"0"]], "failed txn must apply nothing"

        # Clean block applies atomically at COMMIT.
        m = await conn.send(
            QUERY("BEGIN")
        ) or await conn.until_ready()
        m = await conn.send(
            QUERY("INSERT INTO t1 (id, name) VALUES (10, 'x'), (11, 'y')")
        ) or await conn.until_ready()
        assert "INSERT 0 2" in command_tags(m)
        # Not visible before COMMIT (deferred-batch semantics).
        m = await conn.send(QUERY("COMMIT")) or await conn.until_ready()
        assert "COMMIT" in command_tags(m) and ready_status(m) == b"I"
        m = await conn.send(
            QUERY("SELECT count(*) FROM t1")
        ) or await conn.until_ready()
        assert data_rows(m) == [[b"2"]]
        # ROLLBACK of a clean block discards.
        m = await conn.send(QUERY("BEGIN")) or await conn.until_ready()
        m = await conn.send(
            QUERY("INSERT INTO t1 (id, name) VALUES (12, 'z')")
        ) or await conn.until_ready()
        m = await conn.send(QUERY("ROLLBACK")) or await conn.until_ready()
        assert "ROLLBACK" in command_tags(m)
        m = await conn.send(
            QUERY("SELECT count(*) FROM t1")
        ) or await conn.until_ready()
        assert data_rows(m) == [[b"2"]]

    run(_with_agent(SCHEMA, fn))


def test_describe_statement_of_join():
    """Describe(statement) of a two-table join returns the joined
    RowDescription before any Bind/Execute (what psql's \\gdesc and
    psycopg's .description rely on)."""

    async def fn(conn, a):
        await conn.send(
            parse_msg(
                b"P", "j1",
                "SELECT t1.id, t1.name, t2.note FROM t1 "
                "JOIN t2 ON t2.t1_id = t1.id WHERE t1.id = $1",
                oids=(23,),
            )
            + describe_msg(b"S", "j1")
            + SYNC
        )
        msgs = await conn.until_ready()
        rd = [p for t, p in msgs if t == b"T"]
        assert rd, "RowDescription expected for a join Describe"
        (ncols,) = struct.unpack_from(">H", rd[0], 0)
        assert ncols == 3
        # Field names parse out of the RowDescription.
        names = []
        off = 2
        for _ in range(ncols):
            end = rd[0].index(b"\x00", off)
            names.append(rd[0][off:end].decode())
            off = end + 1 + 18
        assert names == ["id", "name", "note"]

    run(_with_agent(SCHEMA, fn))


def test_copy_and_listen_fail_cleanly():
    """COPY/LISTEN/NOTIFY have no analogue: clean 0A000
    feature_not_supported, connection stays usable, and inside a txn the
    block fails like any other error."""

    async def fn(conn, a):
        m = await conn.send(
            QUERY("COPY t1 FROM STDIN")
        ) or await conn.until_ready()
        assert sqlstate(m) == "0A000" and ready_status(m) == b"I"
        m = await conn.send(QUERY("LISTEN foo")) or await conn.until_ready()
        assert sqlstate(m) == "0A000"
        m = await conn.send(QUERY("NOTIFY foo")) or await conn.until_ready()
        assert sqlstate(m) == "0A000"
        m = await conn.send(
            QUERY("DECLARE c CURSOR FOR SELECT 1")
        ) or await conn.until_ready()
        assert sqlstate(m) == "0A000"
        # Still usable.
        m = await conn.send(QUERY("SELECT 42")) or await conn.until_ready()
        assert data_rows(m) == [[b"42"]]
        # Inside a txn: the unsupported statement fails the block.
        m = await conn.send(QUERY("BEGIN")) or await conn.until_ready()
        m = await conn.send(
            QUERY("COPY t1 FROM STDIN")
        ) or await conn.until_ready()
        assert sqlstate(m) == "0A000" and ready_status(m) == b"E"
        m = await conn.send(QUERY("ROLLBACK")) or await conn.until_ready()
        assert ready_status(m) == b"I"

    run(_with_agent(SCHEMA, fn))


def test_cte_feeding_write_routes_to_write_path():
    """WITH ... INSERT must be classified as a WRITE (version assigned,
    replicated) — the head-word heuristic used to misroute it to the
    read pool, silently bypassing CRDT bookkeeping."""

    async def fn(conn, a):
        m = await conn.send(
            QUERY(
                "WITH src(id, name) AS (VALUES (7, 'cte'))"
                " INSERT INTO t1 (id, name) SELECT id, name FROM src"
            )
        ) or await conn.until_ready()
        assert sqlstate(m) is None
        m = await conn.send(
            QUERY("SELECT name FROM t1 WHERE id = 7")
        ) or await conn.until_ready()
        assert data_rows(m) == [[b"cte"]]
        # The write went through version assignment: bookkeeping moved.
        booked = a.agent.bookie.for_actor(a.agent.actor_id)
        assert (booked.last() or 0) >= 1

    run(_with_agent(SCHEMA, fn))


def test_ddl_then_dml_transaction_block():
    """The standard migration pattern (BEGIN; CREATE TABLE; INSERT INTO
    it; COMMIT) must not be failed by queue-time validation — the new
    table exists only inside the deferred batch."""

    async def fn(conn, a):
        m = await conn.send(QUERY("BEGIN")) or await conn.until_ready()
        m = await conn.send(
            QUERY("CREATE TABLE tmp (id INTEGER PRIMARY KEY)")
        ) or await conn.until_ready()
        assert sqlstate(m) is None
        assert "CREATE TABLE" in command_tags(m)
        m = await conn.send(
            QUERY("INSERT INTO tmp (id) VALUES (1)")
        ) or await conn.until_ready()
        assert sqlstate(m) is None and ready_status(m) == b"T"
        m = await conn.send(QUERY("COMMIT")) or await conn.until_ready()
        assert "COMMIT" in command_tags(m) and ready_status(m) == b"I"
        m = await conn.send(
            QUERY("SELECT count(*) FROM tmp")
        ) or await conn.until_ready()
        assert data_rows(m) == [[b"1"]]

    run(_with_agent(SCHEMA, fn))
