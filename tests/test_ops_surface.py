"""Ops/product surface: config, CLI, admin RPC, backup/restore, templates,
consul diffing, lock registry.

Mirrors the reference's CLI integration tests (integration-tests/tests/
cli_test.rs: run the binary, assert stdout) and the consul bridge unit test
(consul/sync.rs:560 basic_operations: hashing + statement generation).
"""

import asyncio
import json
import threading
import time

import pytest

from corrosion_tpu.agent.config import Config, parse_addr
from corrosion_tpu.cli import main as cli_main
from corrosion_tpu.agent.testing import launch_test_agent, poll_until


def run(coro):
    return asyncio.run(coro)


def test_config_toml_env_overlay(tmp_path):
    p = tmp_path / "corrosion.toml"
    p.write_text(
        """
[db]
path = "/data/state.db"
schema_paths = ["/etc/schema"]

[gossip]
addr = "0.0.0.0:4001"
bootstrap = ["seed:4001"]
max_transmissions = 9
"""
    )
    cfg = Config.load(str(p), env={"CORRO_API__ADDR": "0.0.0.0:9000",
                                   "CORRO_GOSSIP__MAX_TRANSMISSIONS": "3",
                                   "CORRO_CONSUL__ENABLED": "true"})
    assert cfg.db.path == "/data/state.db"
    assert cfg.gossip.bootstrap == ["seed:4001"]
    assert cfg.api.addr == "0.0.0.0:9000"  # env overrides
    assert cfg.gossip.max_transmissions == 3
    assert cfg.consul.enabled is True
    assert parse_addr(cfg.api.addr) == ("0.0.0.0", 9000)


def test_cli_help_and_query_exec(tmp_path, capsys):
    # cli_test.rs analogue: drive the CLI against a live agent.
    with pytest.raises(SystemExit):
        cli_main(["--help"])

    run(_setup_and_query(tmp_path, capsys))


async def _setup_and_query(tmp_path, capsys):
    a = await launch_test_agent(str(tmp_path / "a"))
    host, port = a.agent.api_addr
    try:
        # The CLI runs its own event loop, so call it from a thread.
        rc = await asyncio.to_thread(
            cli_main,
            ["--api-addr", f"{host}:{port}", "exec",
             "INSERT INTO tests (id, text) VALUES (7, 'cli')"],
        )
        assert rc == 0
        rc = await asyncio.to_thread(
            cli_main,
            ["--api-addr", f"{host}:{port}", "query", "--columns",
             "SELECT id, text FROM tests"],
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "id|text" in out and "7|cli" in out
    finally:
        await a.stop()


def test_admin_rpc_and_locks(tmp_path):
    async def main():
        uds = str(tmp_path / "admin.sock")
        a = await launch_test_agent(str(tmp_path / "a"), admin_uds=uds)
        try:
            from corrosion_tpu.agent.admin import AdminClient

            admin = AdminClient(uds)
            pong = await admin.call({"c": "ping"})
            assert pong[0]["pong"] and pong[0]["actor_id"] == a.agent.actor_id
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (1, 'x')"]]
            )
            sync = await admin.call({"c": "sync"})
            assert a.agent.actor_id in sync[0]["sync"]["heads"]
            locks = await admin.call({"c": "locks", "top": 5})
            assert isinstance(locks[0]["locks"], list)
            members = await admin.call({"c": "cluster"})
            assert any(
                m["actor_id"] == a.agent.actor_id for m in members[0]["members"]
            )
        finally:
            await a.stop()

    run(main())


def test_lock_registry_snapshot():
    from corrosion_tpu.utils.locks import LockRegistry

    reg = LockRegistry()
    lk = threading.Lock()
    with reg.acquire(lk, "write:test"):
        snap = reg.snapshot()
        assert snap[0]["label"] == "write:test"
        assert snap[0]["state"] == "locked"
    assert reg.snapshot() == []


def test_backup_restore_roundtrip(tmp_path):
    from corrosion_tpu.agent.backup import backup, restore
    from corrosion_tpu.agent.store import Store
    from corrosion_tpu.core.values import Statement

    s = Store(str(tmp_path / "a.db"), bytes([1] * 16))
    s.apply_schema(
        "CREATE TABLE t (id INTEGER NOT NULL PRIMARY KEY, v TEXT);"
    )
    s.execute_transaction(
        [Statement("INSERT INTO t (id, v) VALUES (1, 'keep')")]
    )
    s.close()
    backup(str(tmp_path / "a.db"), str(tmp_path / "snap.db"))
    # Restore as a fresh node: data survives, identity is re-assigned.
    site = restore(str(tmp_path / "snap.db"), str(tmp_path / "b.db"))
    assert site != bytes([1] * 16)
    s2 = Store(str(tmp_path / "b.db"), site)
    assert s2.query(Statement("SELECT v FROM t"))[1] == [("keep",)]
    s2.close()
    # Re-adoption keeps the original actor id (--self-actor-id).
    site2 = restore(
        str(tmp_path / "snap.db"), str(tmp_path / "c.db"), self_actor_id=True
    )
    assert site2 == bytes([1] * 16)


def test_template_render_and_watch(tmp_path):
    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        try:
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (1, 'svc-a')"],
                 ["INSERT INTO tests (id, text) VALUES (2, 'svc-b')"]]
            )
            tpl = tmp_path / "out.conf.tpl"
            tpl.write_text(
                "# generated on <%= hostname() %>\n"
                "<% for row in sql(\"SELECT id, text FROM tests ORDER BY id\"): %>"
                "server <%= row[0] %> <%= row[1] %>\n"
                "<% end %>"
                "count=<%= len(sql(\"SELECT id, text FROM tests ORDER BY id\")) %>\n"
            )
            from corrosion_tpu.tpl import TemplateState
            from corrosion_tpu.client import CorrosionApiClient

            host, port = a.agent.api_addr
            st = TemplateState(
                str(tpl), str(tmp_path / "out.conf"),
                CorrosionApiClient(host, port),
            )
            await st.write()
            out = (tmp_path / "out.conf").read_text()
            assert "server 1 svc-a" in out and "server 2 svc-b" in out
            assert "count=2" in out
            assert st.queries == ["SELECT id, text FROM tests ORDER BY id"]

            # Zero-row query must keep its real column names (to_csv header).
            tpl.write_text(
                "<%= sql(\"SELECT id, text FROM tests WHERE id > 99\").to_csv() %>"
            )
            await st.write()
            out = (tmp_path / "out.conf").read_text()
            assert out.strip() == "id,text"

            # Data-dependent NESTED sql(): the per-row query's text depends
            # on the outer query's rows. Single-pass direct execution
            # (corro-tpl lib.rs:447-613) fetches them live; the old
            # record-then-render double pass silently rendered them empty.
            tpl.write_text(
                "<% for row in sql(\"SELECT id FROM tests ORDER BY id\"): %>"
                "<%= sql(\"SELECT text FROM tests WHERE id = \""
                " + str(row[0])).rows[0][0] %>\n"
                "<% end %>"
            )
            await st.write()
            out = (tmp_path / "out.conf").read_text()
            assert out.splitlines() == ["svc-a", "svc-b"]
            # All three query texts (outer + one per row) were recorded for
            # watch mode.
            assert len(st.queries) == 3
        finally:
            await a.stop()

    run(main())


def test_template_watch_resubscribes_late_queries(tmp_path):
    """Watch mode must pick up queries DISCOVERED on a re-render: a new
    row makes the nested loop issue a new per-row query; a later change
    visible only through that query must still trigger a re-render."""

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"))
        try:
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (1, 'one')"]]
            )
            tpl = tmp_path / "w.conf.tpl"
            tpl.write_text(
                "<% for row in sql(\"SELECT id FROM tests ORDER BY id\"): %>"
                "<%= sql(\"SELECT text FROM tests2 WHERE id = \""
                " + str(row[0])).to_json() %>\n"
                "<% end %>"
            )
            from corrosion_tpu.client import CorrosionApiClient
            from corrosion_tpu.tpl import TemplateState, run_templates
            from corrosion_tpu.agent.config import Config

            host, port = a.agent.api_addr
            cfg = Config()
            cfg.api.addr = f"{host}:{port}"
            out_path = tmp_path / "w.conf"
            task = asyncio.create_task(
                run_templates(
                    [f"{tpl}:{out_path}"], cfg, watch=True
                )
            )
            try:
                async def rendered():
                    return out_path.exists()

                await poll_until(rendered)
                # New tests row -> re-render discovers the tests2 query for
                # id 2 and subscribes to it.
                await a.client.execute(
                    [["INSERT INTO tests (id, text) VALUES (2, 'two')"]]
                )

                async def saw_empty_two():
                    return (
                        out_path.exists()
                        and out_path.read_text().count("[]") >= 2
                    )

                await poll_until(saw_empty_two)
                # A change visible ONLY via the late-discovered tests2
                # query must still re-render.
                await a.client.execute(
                    [["INSERT INTO tests2 (id, text) VALUES (2, 'deep')"]]
                )

                async def saw_deep():
                    return "deep" in out_path.read_text()

                await poll_until(saw_deep)
                # Subscription set tracks the template: deleting row 2
                # drops its per-row query on the next render (reconcile
                # cancels the stale pump — the set never just grows), and
                # the output shrinks back to one line.
                await a.client.execute(
                    [["DELETE FROM tests WHERE id = 2"]]
                )

                async def shrunk():
                    return out_path.read_text().count("\n") == 1

                await poll_until(shrunk)
            finally:
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass
        finally:
            await a.stop()

    run(main())


def test_consul_diffing_basic_operations():
    # consul/sync.rs:560 basic_operations analogue: hash stability, upsert
    # generation, deletion, no-op on unchanged.
    from corrosion_tpu.integrations.consul import (
        diff_statements,
        hash_check,
        hash_service,
    )

    svc = {"ID": "web-1", "Service": "web", "Tags": ["a"], "Port": 80,
           "Address": "10.0.0.1"}
    chk = {"CheckID": "web-1-http", "ServiceID": "web-1", "Status": "passing",
           "Output": "ok"}
    assert hash_service(svc) == hash_service(dict(svc))
    assert hash_check(chk) == hash_check(dict(chk))

    stmts, svc_h, chk_h = diff_statements(
        "n1", {"web-1": svc}, {"web-1-http": chk}, {}, {}
    )
    assert len(stmts) == 2
    assert "INSERT INTO consul_services" in stmts[0][0]
    assert "INSERT INTO consul_checks" in stmts[1][0]
    # Unchanged -> no statements.
    stmts2, _, _ = diff_statements(
        "n1", {"web-1": svc}, {"web-1-http": chk}, svc_h, chk_h
    )
    assert stmts2 == []
    # Status flip changes the check hash only.
    chk2 = dict(chk, Status="critical")
    stmts3, _, _ = diff_statements(
        "n1", {"web-1": svc}, {"web-1-http": chk2}, svc_h, chk_h
    )
    assert len(stmts3) == 1 and "consul_checks" in stmts3[0][0]
    # Removal -> DELETE.
    stmts4, _, _ = diff_statements("n1", {}, {}, svc_h, chk_h)
    assert sorted(s[0].split()[0] + " " + s[0].split()[2] for s in stmts4) == [
        "DELETE consul_checks", "DELETE consul_services",
    ]


def test_consul_bridge_hashes_persist_across_restart(tmp_path):
    """A restarted bridge must NOT re-upsert unchanged services: the diff
    hashes persist in the node-local __corro_consul_* tables (the
    reference's setup + hash tables, consul/sync.rs:119-160). Re-upserts
    would bump updated_at and churn every subscription on consul_*."""
    import asyncio
    import json as _json

    from corrosion_tpu.agent.config import Config
    from corrosion_tpu.agent.testing import launch_test_agent
    from corrosion_tpu.integrations.consul import run_consul_sync

    SCHEMA = """
    CREATE TABLE consul_services (
      node TEXT NOT NULL, id TEXT NOT NULL, name TEXT NOT NULL DEFAULT '',
      tags TEXT NOT NULL DEFAULT '[]', meta TEXT NOT NULL DEFAULT '{}',
      port INTEGER NOT NULL DEFAULT 0, address TEXT NOT NULL DEFAULT '',
      updated_at INTEGER NOT NULL DEFAULT 0,
      PRIMARY KEY (node, id)
    );
    CREATE TABLE consul_checks (
      node TEXT NOT NULL, id TEXT NOT NULL,
      service_id TEXT NOT NULL DEFAULT '',
      service_name TEXT NOT NULL DEFAULT '', name TEXT NOT NULL DEFAULT '',
      status TEXT NOT NULL DEFAULT '', output TEXT NOT NULL DEFAULT '',
      updated_at INTEGER NOT NULL DEFAULT 0,
      PRIMARY KEY (node, id)
    );
    """

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"), schema=SCHEMA)

        # Fake Consul agent: fixed services/checks.
        async def on_conn(reader, writer):
            req = await reader.readline()
            while (await reader.readline()) not in (b"\r\n", b""):
                pass
            if b"/v1/agent/services" in req:
                body = _json.dumps(
                    {"web-1": {"ID": "web-1", "Service": "web",
                               "Tags": ["a"], "Port": 80,
                               "Address": "10.0.0.1"}}
                ).encode()
            else:
                body = _json.dumps(
                    {"web-1-http": {"CheckID": "web-1-http",
                                    "ServiceID": "web-1",
                                    "Status": "passing", "Output": "ok"}}
                ).encode()
            writer.write(
                b"HTTP/1.1 200 OK\r\ncontent-length: %d\r\n\r\n%s"
                % (len(body), body)
            )
            await writer.drain()
            writer.close()

        server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
        consul_port = server.sockets[0].getsockname()[1]
        try:
            cfg = Config()
            cfg.api.addr = "%s:%d" % a.agent.api_addr
            cfg.consul.address = f"127.0.0.1:{consul_port}"
            cfg.consul.interval_ms = 10

            await run_consul_sync(cfg, iterations=2)
            _, rows = await a.client.query(
                "SELECT id, updated_at FROM consul_services"
            )
            assert [r[0] for r in rows] == ["web-1"]
            first_seen = rows[0][1]
            head0 = a.agent.bookie.for_actor(a.agent.actor_id).last()

            # "Restart": a fresh bridge run with empty in-memory state must
            # find the persisted hashes and write NOTHING.
            await asyncio.sleep(1.1)  # updated_at has 1 s granularity
            await run_consul_sync(cfg, iterations=2)
            _, rows = await a.client.query(
                "SELECT updated_at FROM consul_services"
            )
            assert rows[0][0] == first_seen, "no re-upsert after restart"
            head1 = a.agent.bookie.for_actor(a.agent.actor_id).last()
            assert head1 == head0, "no replicated writes at all"
        finally:
            server.close()
            await a.stop()

    run(main())


def test_resolve_bootstrap_dns_syntax(monkeypatch):
    import socket

    from corrosion_tpu.agent.config import resolve_bootstrap

    # Deterministic resolver: no dependency on the host's DNS behavior
    # (NXDOMAIN-hijacking resolvers would wildcard-resolve anything).
    def fake_getaddrinfo(host, port, type=0):
        if host == "seed.example":
            return [
                (socket.AF_INET, type, 6, "", ("10.0.0.1", port)),
                (socket.AF_INET, type, 6, "", ("10.0.0.2", port)),
                (socket.AF_INET, type, 6, "", ("10.0.0.1", port)),  # dup
            ]
        raise socket.gaierror("NXDOMAIN")

    monkeypatch.setattr(socket, "getaddrinfo", fake_getaddrinfo)
    # Plain entries pass through untouched (no resolution at all).
    assert resolve_bootstrap(["10.0.0.9:8787"]) == [("10.0.0.9", 8787)]
    # @dns resolves the name to every distinct address.
    assert resolve_bootstrap(["seed.example:9999@dns"]) == [
        ("10.0.0.1", 9999), ("10.0.0.2", 9999),
    ]
    # Unresolvable names are skipped, not fatal (announce loop retries).
    assert resolve_bootstrap(["no.such.host.invalid:1@dns"]) == []
