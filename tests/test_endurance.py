"""Endurance observability plane tests (obs/series.py + obs/endurance.py).

Covers the corro-metric-series/1 recorder contract (rotation, resume,
replay, clock-less determinism, idempotent attach), snapshot consistency
under concurrent hammering, the label-cardinality cap, the detector
catalog (Theil–Sen leak fits, counter-reset classification, wedge and
stall runs, multi-window SLO burn rates) including POSITIVE CONTROLS —
an injected leak/wedge/slow-burn breach must be caught with the correct
verdict — the soak budget gate's never-tolerance-scaled rules, the
report diff, the kernel/agent install points with their zero-cost pins,
and the `obs soak` CLI exit codes.
"""

import asyncio
import json
import threading

import numpy as np
import pytest

from corrosion_tpu import models
from corrosion_tpu.obs import endurance as E
from corrosion_tpu.obs import series as S
from corrosion_tpu.sim import simulate
from corrosion_tpu.sim import telemetry as T
from corrosion_tpu.utils import metrics as M


def run(coro):
    return asyncio.run(coro)


# -- synthetic sample builders -----------------------------------------------


def mk_sample(t, counters=None, gauges=None, histograms=None):
    return {
        "kind": "sample", "t": float(t), "seq": int(t),
        "counters": dict(counters or {}),
        "gauges": dict(gauges or {}),
        "histograms": dict(histograms or {}),
    }


def mk_hist(le, counts, total=None, s=0.0):
    return {
        "le": list(le), "counts": list(counts),
        "count": total if total is not None else counts[-1], "sum": s,
    }


# -- robust trend fit --------------------------------------------------------


def test_theil_sen_recovers_seeded_noisy_slope():
    """Median-of-pairwise-slopes on a seeded noisy ramp lands on the
    true slope, and stays there when ~20% of points are outlier spikes
    (one compaction spike must not set the verdict)."""
    rng = np.random.default_rng(7)
    ts = list(np.arange(200, dtype=float))
    true = 3.5
    ys = [true * t + 40.0 + float(rng.normal(0, 2.0)) for t in ts]
    got = E.theil_sen(ts, ys)
    assert got == pytest.approx(true, rel=0.05)
    # Contaminate every 5th point with a huge spike: least squares would
    # be dragged far off; Theil-Sen barely moves.
    for i in range(0, 200, 5):
        ys[i] += 5000.0
    got = E.theil_sen(ts, ys)
    assert got == pytest.approx(true, rel=0.25)


def test_theil_sen_deterministic_and_degenerate():
    ts = list(np.arange(400, dtype=float))
    ys = [0.25 * t + ((t * 7919) % 13) for t in ts]
    # Thinned (n*(n-1)/2 >> max_pairs) but deterministic: same answer
    # twice, no RNG involved.
    a = E.theil_sen(ts, ys, max_pairs=500)
    assert a == E.theil_sen(ts, ys, max_pairs=500)
    assert E.theil_sen([1.0], [2.0]) is None
    assert E.theil_sen([], []) is None


# -- counter-reset classification --------------------------------------------


def test_rebase_counter_restart():
    """A relaunched agent drops its counters to ~0: classified restart,
    previous cumulative becomes the base, deltas stay meaningful."""
    rebased, events = E.rebase_counter([0.0, 10.0, 50.0, 2.0, 8.0])
    assert [e["kind"] for e in events] == ["restart"]
    assert rebased == [0.0, 10.0, 50.0, 52.0, 58.0]
    assert rebased == sorted(rebased)


def test_rebase_counter_wraparound():
    prev = 2.0 ** 32 - 10.0
    rebased, events = E.rebase_counter([prev, 5.0])
    assert [e["kind"] for e in events] == ["wraparound"]
    # The true delta (10 to the base + 5 past it) survives the wrap.
    assert rebased[1] - rebased[0] == pytest.approx(15.0)


def test_rebase_counter_genuine_decrease():
    """A small dip with no wrap base in reach is a monotonic-contract
    violation: the cumulative holds flat, never invents negative work."""
    rebased, events = E.rebase_counter([0.0, 100.0, 95.0, 97.0])
    assert [e["kind"] for e in events] == ["decrease"]
    assert rebased == [0.0, 100.0, 100.0, 102.0]


# -- recorder contract -------------------------------------------------------


def test_recorder_rotation_resume_replay(tmp_path):
    """Rotation past max_bytes rolls to path.N; replay merges segments
    oldest-first; mode="a" resumes the segment counter; mode="w" starts
    fresh and deletes stale segments."""
    path = str(tmp_path / "s.jsonl")
    reg = M.MetricsRegistry(max_labelsets=None)
    c = reg.counter("corro_x_total")
    rec = S.MetricSeriesRecorder(path, source="t", mode="w",
                                 max_bytes=600, clock=None)
    for i in range(12):
        c.inc()
        rec.sample(reg, t=float(i))
    rec.close()
    segs = S.series_segments(path)
    assert len(segs) > 1 and segs[-1] == path
    rep = S.replay_series(path)
    assert [s["t"] for s in rep["samples"]] == [float(i) for i in range(12)]
    ts, vals = S.series_values(rep["samples"], "corro_x_total")
    assert vals == [float(i + 1) for i in range(12)]

    # Append resumes: the segment counter continues past the rotated
    # chain instead of renaming the live file over an old segment.
    rec2 = S.MetricSeriesRecorder(path, source="t", mode="a",
                                  max_bytes=600, clock=None)
    for i in range(12, 18):
        c.inc()
        rec2.sample(reg, t=float(i))
    rec2.close()
    rep = S.replay_series(path)
    assert len(rep["samples"]) == 18
    assert len(rep["headers"]) >= 2  # one per open/rotation
    segments = [h["segment"] for h in rep["headers"]]
    assert segments == sorted(segments)

    # A truncating open kills the stale chain: replay sees ONLY the new
    # record, not a merge with the previous run's segments.
    rec3 = S.MetricSeriesRecorder(path, source="t", mode="w", clock=None)
    rec3.sample(reg, t=0.0)
    rec3.close()
    rep = S.replay_series(path)
    assert len(rep["samples"]) == 1
    assert S.series_segments(path) == [path]


def test_recorder_clockless_needs_explicit_t(tmp_path):
    rec = S.MetricSeriesRecorder(
        str(tmp_path / "d.jsonl"), clock=None, mode="w")
    reg = M.MetricsRegistry()
    with pytest.raises(ValueError):
        rec.sample(reg)
    rec.sample(reg, t=1.0)
    rec.close()
    with pytest.raises(ValueError):
        rec.sample(reg, t=2.0)


def test_recorder_event_reserved_kinds(tmp_path):
    rec = S.MetricSeriesRecorder(
        str(tmp_path / "e.jsonl"), clock=None, mode="w")
    with pytest.raises(ValueError):
        rec.record_event({"kind": "sample", "t": 0})
    rec.record_event({"kind": "phase", "name": "storm"})
    rec.close()
    rep = S.replay_series(str(tmp_path / "e.jsonl"))
    assert [e["kind"] for e in rep["events"]] == ["phase"]


def test_replay_skips_torn_tail(tmp_path):
    """A crash can tear at most the final in-flight line; replay keeps
    every whole line before it."""
    path = str(tmp_path / "torn.jsonl")
    reg = M.MetricsRegistry()
    rec = S.MetricSeriesRecorder(path, clock=None, mode="w")
    rec.sample(reg, t=0.0)
    rec.sample(reg, t=1.0)
    rec.close()
    with open(path, "a") as f:
        f.write('{"kind": "sample", "t": 2.0, "co')  # torn mid-write
    rep = S.replay_series(path)
    assert [s["t"] for s in rep["samples"]] == [0.0, 1.0]


def test_attach_is_idempotent_and_refcounted(tmp_path):
    """Two installs racing one path adopt ONE recorder (no duplicate
    header, no second handle); close is refcounted to match — the
    in-process relaunch contract (hostchaos kill_restart)."""
    path = str(tmp_path / "a.jsonl")
    r1 = S.MetricSeriesRecorder.attach(path, clock=None, mode="w")
    r2 = S.MetricSeriesRecorder.attach(path, clock=None, mode="w")
    assert r1 is r2
    reg = M.MetricsRegistry()
    r1.close()  # first release: still open for the second holder
    r2.sample(reg, t=0.0)
    r2.close()
    with pytest.raises(ValueError):
        r2.sample(reg, t=1.0)
    rep = S.replay_series(path)
    assert len(rep["headers"]) == 1
    # After full release a fresh attach opens a NEW recorder.
    r3 = S.MetricSeriesRecorder.attach(path, clock=None, mode="a")
    assert r3 is not r1
    r3.close()


def test_register_process_gauges_idempotent():
    reg = M.MetricsRegistry()
    a = M.register_process_gauges(reg)
    b = M.register_process_gauges(reg)
    assert all(x is y for x, y in zip(a, b))


# -- snapshot consistency + cardinality cap ----------------------------------


def test_snapshot_vs_scrape_under_hammering():
    """Whole-registry snapshots taken while writer threads hammer the
    metrics never tear: counters are monotone across samples, and each
    histogram's bucket/count trio is internally consistent (cumulative
    buckets, last bucket <= count)."""
    reg = M.MetricsRegistry(max_labelsets=None)
    c = reg.counter("corro_hammer_total")
    h = reg.histogram("corro_hammer_seconds")
    stop = threading.Event()

    def hammer():
        i = 0
        while not stop.is_set():
            c.inc(source="a")
            c.inc(source="b")
            h.observe(0.001 * (i % 50))
            i += 1

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    snaps = [reg.series_snapshot() for _ in range(200)]
    stop.set()
    for t in threads:
        t.join()

    prev = None
    for s in snaps:
        for name, v in s["counters"].items():
            if prev is not None and name in prev["counters"]:
                assert v >= prev["counters"][name], name
        for name, hist in s["histograms"].items():
            counts = hist["counts"]
            assert counts == sorted(counts)  # cumulative buckets
            assert counts[-1] <= hist["count"]
        prev = s
    # The render path agrees with the final snapshot's family split.
    text = reg.render()
    assert "corro_hammer_total" in text


def test_label_cardinality_cap_churn():
    """A labelset churn storm (every sample a new value) folds into the
    `other` overflow bucket past the cap: bounded snapshot size, the
    fold count on corro_metrics_labelsets_dropped_total, and the series
    keeps its label NAMES."""
    reg = M.MetricsRegistry(max_labelsets=8)
    c = reg.counter("corro_churn_total")
    for i in range(500):
        c.inc(peer=f"n{i}")
    snap = reg.series_snapshot()
    keys = [k for k in snap["counters"] if k.startswith("corro_churn")]
    assert len(keys) <= 9  # 8 admitted + the `other` bucket
    assert 'corro_churn_total{peer="other"}' in snap["counters"]
    assert snap["counters"]['corro_churn_total{peer="other"}'] == 492
    assert snap["counters"][
        "corro_metrics_labelsets_dropped_total"] == 492
    # Existing labelsets keep passing after the cap engaged.
    c.inc(peer="n0")
    assert reg.series_snapshot()["counters"][
        'corro_churn_total{peer="n0"}'] == 2


# -- detectors: positive controls --------------------------------------------


def _clean_samples(n=30):
    """A healthy host series: flat rss/fds, progress tracking offers,
    calm lag, one histogram entirely under threshold."""
    out = []
    for i in range(n):
        out.append(mk_sample(
            float(i),
            counters={
                "corro_changes_committed": 10.0 * i,
                "corro_changes_applied": 10.0 * i,
                "corro_gossip_member_removed": 0.0,
            },
            gauges={
                "corro_runtime_rss_bytes": 1e8 + (i % 3) * 1e5,
                "corro_runtime_open_fds": 40.0,
                "corro_sync_needs": 5.0,
                "corro_runtime_loop_lag_last_seconds": 0.01,
            },
            histograms={
                "corro_broadcast_recv_lag_seconds": mk_hist(
                    [0.1, 1.0, 10.0], [5 * i, 6 * i, 6 * i],
                    total=6 * i, s=0.05 * i),
            },
        ))
    return out


def test_clean_series_reports_ok_with_all_detectors_armed():
    rep = E.build_report(_clean_samples(), label="clean")
    assert rep["ok"] and rep["breaches"] == []
    assert all(rep["detectors_armed"].values()), rep["detectors_armed"]
    assert rep["schema"] == E.ENDURANCE_SCHEMA
    text = E.render_report(rep)
    assert "clean" in text and "BREACH" not in text


def test_positive_control_injected_fd_leak():
    """+5 fds per second = 18000/h against a 600/h ceiling: caught as a
    leak with the right stem and a units-per-hour verdict."""
    samples = _clean_samples()
    for i, s in enumerate(samples):
        s["gauges"]["corro_runtime_open_fds"] = 40.0 + 5.0 * i
    rep = E.build_report(samples, label="leaky")
    assert not rep["ok"]
    e = rep["leaks"]["corro_runtime_open_fds"]
    assert e["flagged"] and e["slope_per_hour"] == pytest.approx(
        18000.0, rel=0.01)
    assert any(
        b.startswith("leak: corro_runtime_open_fds")
        for b in rep["breaches"])
    assert "LEAK" in E.render_report(rep)


def test_positive_control_injected_wedge():
    """Commits keep arriving while applies go flat for the rest of the
    run: wedged, with the offered-work evidence in the verdict."""
    samples = _clean_samples()
    for i, s in enumerate(samples):
        if i >= 10:
            s["counters"]["corro_changes_applied"] = 100.0
    rep = E.build_report(samples, label="wedged")
    w = rep["wedges"]["corro_changes_committed->corro_changes_applied"]
    assert w["wedged"] and w["longest_run"]["offered"] > 0
    assert any(b.startswith("wedge:") for b in rep["breaches"])


def test_restart_does_not_fake_a_wedge_or_leak():
    """A mid-run agent relaunch (both progress counters drop to ~0) is
    classified as a restart and rebased: no wedge, no breach, and the
    reset is reported as relaunch evidence."""
    samples = _clean_samples()
    for i, s in enumerate(samples):
        if i >= 15:  # relaunched life recounting from zero
            s["counters"]["corro_changes_committed"] = 10.0 * (i - 15)
            s["counters"]["corro_changes_applied"] = 10.0 * (i - 15)
    rep = E.build_report(samples, label="relaunch")
    assert rep["ok"], rep["breaches"]
    assert rep["resets"]["corro_changes_committed"]["kinds"] == [
        "restart"]


def test_positive_control_slow_burn_slo():
    """A sustained staleness plateau above the SLO ceiling burns budget
    in BOTH windows -> breached; the same plateau confined to ancient
    history (recovered since) leaves the fast window clean -> no
    breach. The multi-window rule is what separates the two."""
    burn = _clean_samples()
    for s in burn:
        s["gauges"]["corro_sync_needs"] = 900.0  # above the 500 ceiling
    rep = E.build_report(burn, label="burning")
    slo = rep["slo"]["convergence_staleness"]
    assert slo["breached"]
    assert slo["windows"]["fast"]["burn"] >= 1.0
    assert slo["windows"]["slow"]["burn"] >= 1.0
    assert any(
        b.startswith("slo: convergence_staleness")
        for b in rep["breaches"])

    recovered = _clean_samples()
    for i, s in enumerate(recovered):
        if i < 10:  # bad past, clean tail
            s["gauges"]["corro_sync_needs"] = 900.0
    rep = E.build_report(recovered, label="recovered")
    slo = rep["slo"]["convergence_staleness"]
    assert slo["armed"] and not slo["breached"]
    assert slo["windows"]["fast"]["burn"] < 1.0


def test_counter_budget_slo_spans_restarts():
    """The false-alarm budget counts events on the REBASED cumulative,
    so a relaunch neither hides alarms nor invents them."""
    samples = _clean_samples()
    for i, s in enumerate(samples):
        # 2 removals per tick; agent restarts at i=20.
        v = 2.0 * (i if i < 20 else i - 20)
        s["counters"]["corro_gossip_member_removed"] = v
    rep = E.build_report(samples, label="flappy")
    slo = rep["slo"]["probe_false_alarm_budget"]
    # 2 events/s = 7200/h against the 720/h budget: burn ~10x.
    assert slo["breached"]
    assert slo["windows"]["slow"]["per_hour"] == pytest.approx(
        7200.0, rel=0.15)


def test_stall_runs_detected():
    samples = _clean_samples()
    for i, s in enumerate(samples):
        if 5 <= i < 10 or 20 <= i < 24:
            s["gauges"]["corro_runtime_loop_lag_last_seconds"] = 2.0
    rep = E.build_report(samples, label="stalled")
    assert rep["stalls"]["runs"] == 2
    assert rep["stalls"]["longest"] == 5
    assert any(b.startswith("stall:") for b in rep["breaches"])


# -- soak budget gate + diff -------------------------------------------------


def _soak_report(host_block, kernel_block=None, determinism=True):
    return {
        "schema": E.SOAK_SCHEMA,
        "platform": "cpu",
        "scenario": "soak_smoke",
        "wall_s": 10.0,
        "kernel": {
            "determinism_ok": determinism,
            "endurance": kernel_block or E.build_report(
                _clean_samples(), label="kernel"),
        },
        "host": {"endurance": {"agents": {"n0": host_block}}},
    }


def test_check_soak_budget_clean_and_ceilings():
    rep = _soak_report(E.build_report(_clean_samples(), label="n0"))
    budget = {
        "platform": "cpu", "scenario": "soak_smoke", "tolerance": 3.0,
        "leak_ceilings_per_hour": {
            "host:corro_runtime_rss_bytes": 1e9,
        },
        "require_detectors_armed": True,
        "require_determinism": True,
        "wall_ceiling_s": 60.0,
    }
    ok, breaches = E.check_soak_budget(rep, budget)
    assert ok, breaches

    # An exceeded leak ceiling breaches even under tolerance scaling.
    leaky = _clean_samples()
    for i, s in enumerate(leaky):
        s["gauges"]["corro_runtime_rss_bytes"] = 1e8 + 1e6 * i
    rep = _soak_report(E.build_report(leaky, label="n0"))
    budget["leak_ceilings_per_hour"][
        "host:corro_runtime_rss_bytes"] = 1e6
    ok, breaches = E.check_soak_budget(rep, budget)
    assert not ok
    assert any("corro_runtime_rss_bytes" in b for b in breaches)


def test_check_soak_budget_wedge_never_tolerance_scaled():
    wedged = _clean_samples()
    for i, s in enumerate(wedged):
        if i >= 10:
            s["counters"]["corro_changes_applied"] = 100.0
    rep = _soak_report(E.build_report(wedged, label="n0"))
    ok, breaches = E.check_soak_budget(
        rep, {"tolerance": 100.0, "wedge_max": 0})
    assert not ok
    assert any("wedge(s) > max 0" in b for b in breaches)


def test_check_soak_budget_harness_failure_on_unarmed_detectors():
    """The machinery-fired rule: a soak whose detectors never evaluated
    anything must FAIL as a harness failure, not pass green."""
    empty = E.build_report([], label="n0")
    assert empty["ok"]  # no breaches — but nothing was armed either
    rep = _soak_report(empty, kernel_block=empty)
    ok, breaches = E.check_soak_budget(
        rep, {"require_detectors_armed": True,
              "leak_ceilings_per_hour": {}})
    assert not ok
    assert any(b.startswith("test-harness failure") for b in breaches)


def test_check_soak_budget_coverage_hole_and_determinism():
    rep = _soak_report(E.build_report(_clean_samples(), label="n0"),
                       determinism=False)
    ok, breaches = E.check_soak_budget(rep, {
        "leak_ceilings_per_hour": {"host:corro_no_such_series": 1.0},
        "require_determinism": True,
    })
    assert not ok
    assert any("coverage hole" in b for b in breaches)
    assert any("not replay-deterministic" in b for b in breaches)


def test_diff_soak_flags_regressions_only():
    base = _soak_report(E.build_report(_clean_samples(), label="n0"))
    same = E.diff_soak(base, base)
    assert same["regressions"] == []
    assert all(r["ok"] for r in same["rows"])

    # Candidate grows a real fd leak: slope regression + new breach.
    leaky = _clean_samples()
    for i, s in enumerate(leaky):
        s["gauges"]["corro_runtime_open_fds"] = 40.0 + 5.0 * i
    cand = _soak_report(E.build_report(leaky, label="n0"))
    d = E.diff_soak(base, cand)
    assert any("corro_runtime_open_fds" in r for r in d["regressions"])
    assert any("new breaches" in r for r in d["regressions"])

    # Candidate loses detector coverage: never tolerated.
    lost = _soak_report(E.build_report([], label="n0"))
    d = E.diff_soak(base, lost)
    assert any("no longer armed" in r for r in d["regressions"])
    assert any("coverage collapsed" in r for r in d["regressions"])


# -- install points ----------------------------------------------------------


def test_kernel_series_chunked_deterministic_and_zero_cost(tmp_path):
    """The KernelTelemetry install: one sample per chunk at t = absolute
    round index, wall-clock histogram excluded, seeded reruns produce a
    byte-identical file — and running WITHOUT the series recorder leaves
    the curves bit-identical (zero-cost pin)."""
    cfg, topo, sched = models.merge_10k(n=32, rounds=24, samples=16)

    def run_with_series(path):
        reg = M.MetricsRegistry()
        rec = S.MetricSeriesRecorder(path, source="kernel", mode="w",
                                     clock=None)
        tele = T.KernelTelemetry(engine="dense", registry=reg,
                                 series=rec)
        final, curves = simulate(
            cfg, topo, sched, seed=5, max_chunk=8, telemetry=tele)
        rec.close()
        return curves

    p1, p2 = str(tmp_path / "k1.jsonl"), str(tmp_path / "k2.jsonl")
    curves = run_with_series(p1)
    run_with_series(p2)
    assert open(p1, "rb").read() == open(p2, "rb").read()

    rep = S.replay_series(p1)
    assert [s["t"] for s in rep["samples"]] == [8.0, 16.0, 24.0]
    names = S.series_names(rep["samples"], "histograms")
    assert not any("chunk_seconds" in n for n in names)
    # Convergence watermarks move through the series, not only at end.
    ts, vals = S.series_values(
        rep["samples"], 'corro_kernel_health_staleness_sum_last'
        '{engine="dense"}', family="gauges")
    assert len(ts) == 3

    # Zero-cost pin: identical curves without any series recorder.
    _, bare = simulate(cfg, topo, sched, seed=5, max_chunk=8,
                       telemetry=T.KernelTelemetry(engine="dense"))
    for k in curves:
        assert np.array_equal(
            np.asarray(curves[k]), np.asarray(bare[k])), k


def test_agent_runtime_series_install(tmp_path):
    """AgentConfig.metric_series_path wires the recorder into the
    runtime-metrics loop: samples appear, carry the process gauges, and
    the recorder closes with the agent."""
    from corrosion_tpu.agent.testing import launch_test_agent, poll_until

    path = str(tmp_path / "agent.series.jsonl")

    async def main():
        a = await launch_test_agent(
            str(tmp_path / "a"),
            metric_series_path=path,
            runtime_metrics_interval=0.05,
        )
        try:
            async def sampled():
                try:
                    rep = S.replay_series(path)
                except OSError:
                    return False
                return len(rep["samples"]) >= 3
            await poll_until(sampled, timeout=10.0)
        finally:
            await a.stop()

    run(main())
    rep = S.replay_series(path)
    assert rep["headers"][0]["source"].startswith("agent:")
    ts, vals = S.series_values(
        rep["samples"], "corro_runtime_rss_bytes", family="gauges")
    assert vals and all(v > 0 for v in vals)
    # Counters registered-at-boot are zero-seeded so budget SLOs arm
    # even on a clean soak.
    _, removed = S.series_values(
        rep["samples"], "corro_gossip_member_removed",
        family="counters")
    assert removed and removed[0] == 0.0
    # Stop released the recorder: the path is attachable fresh.
    import os
    assert os.path.abspath(path) not in S.MetricSeriesRecorder._live


# -- CLI ---------------------------------------------------------------------


def test_obs_soak_cli_report_and_diff(tmp_path, capsys):
    from corrosion_tpu import cli

    # A leaky series file -> exit 1 under a tight ceiling, 0 under a
    # generous one.
    path = str(tmp_path / "leaky.jsonl")
    reg = M.MetricsRegistry()
    fds = reg.gauge("corro_runtime_open_fds")
    rec = S.MetricSeriesRecorder(path, clock=None, mode="w")
    for i in range(20):
        fds.set(40.0 + 5.0 * i)
        rec.sample(reg, t=float(i))
    rec.close()
    assert cli.main([
        "obs", "soak", "report", path,
        "--leak-ceiling", "corro_runtime_open_fds=600",
    ]) == 1
    out = capsys.readouterr().out
    assert "LEAK" in out
    assert cli.main([
        "obs", "soak", "report", path,
        "--leak-ceiling", "corro_runtime_open_fds=50000",
    ]) == 0

    base_rep = E.build_report(_clean_samples(), label="n0")
    base = tmp_path / "base.json"
    base.write_text(json.dumps(_soak_report(base_rep)))
    assert cli.main([
        "obs", "soak", "diff", str(base), str(base)]) == 0

    leaky = _clean_samples()
    for i, s in enumerate(leaky):
        s["gauges"]["corro_runtime_open_fds"] = 40.0 + 5.0 * i
    cand = tmp_path / "cand.json"
    cand.write_text(json.dumps(
        _soak_report(E.build_report(leaky, label="n0"))))
    assert cli.main([
        "obs", "soak", "diff", str(base), str(cand)]) == 1
    assert "REGRESSION" in capsys.readouterr().out


def test_loadgen_soak_process_block_rides_the_recorder(tmp_path):
    """`loadgen soak` emits its process block through the series
    recorder (one sampling path): section-boundary samples in a
    corro-metric-series/1 record, start/end derived from its first/last
    samples."""
    from corrosion_tpu.loadgen.scenarios import intake_policy

    spath = str(tmp_path / "soak.series.jsonl")
    r = intake_policy(nodes=8, rounds=12, seed=0, series_path=spath)
    proc = r["process"]
    assert proc["samples"] == 3
    assert proc["series_path"] == spath
    rep = S.replay_series(spath)
    assert rep["headers"][0]["source"] == "loadgen-soak"
    ts, vals = S.series_values(
        rep["samples"], "corro_runtime_rss_bytes", family="gauges")
    assert vals[0] == proc["start"]["rss_bytes"]
    assert vals[-1] == proc["end"]["rss_bytes"]
    assert proc["rss_growth_bytes"] == vals[-1] - vals[0]
