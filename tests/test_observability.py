"""Tracing + metrics subsystem tests.

Covers the §5 observability surface: metric registry semantics, Prometheus
text exposition over HTTP, span parentage, W3C traceparent propagation
through a real 2-agent sync session (the SyncTraceContextV1 behavior,
sync.rs:32-67), the HLC-lag histogram, and the admin RPC metrics/trace
commands.
"""

import asyncio
import urllib.request

import pytest

from corrosion_tpu.utils import metrics as M
from corrosion_tpu.utils import tracing as T
from corrosion_tpu.agent.testing import launch_test_agent, poll_until
from corrosion_tpu.core.values import Statement


def run(coro):
    return asyncio.run(coro)


def test_counter_gauge_histogram_render():
    reg = M.MetricsRegistry()
    c = reg.counter("corro_test_total", "help text")
    c.inc()
    c.inc(2, source="sync")
    g = reg.gauge("corro_depth")
    g.set(3)
    g.add(2)
    h = reg.histogram("corro_lat_seconds")
    for v in (0.002, 0.02, 0.2, 2.0):
        h.observe(v)
    text = reg.render()
    assert "# TYPE corro_test_total counter" in text
    assert "corro_test_total 1" in text
    assert 'corro_test_total{source="sync"} 2' in text
    assert "corro_depth 5" in text
    assert 'corro_lat_seconds_bucket{le="0.0025"} 1' in text
    assert 'corro_lat_seconds_bucket{le="+Inf"} 4' in text
    assert "corro_lat_seconds_count 4" in text
    assert h.count() == 4
    assert h.quantile(0.5) <= 0.1
    # Same name returns the same metric (facade semantics).
    assert reg.counter("corro_test_total") is c


def test_prometheus_http_endpoint():
    async def main():
        reg = M.MetricsRegistry()
        reg.counter("corro_up").inc()
        server, (host, port) = await M.serve_prometheus(reg, "127.0.0.1", 0)
        try:
            body = await asyncio.to_thread(
                lambda: urllib.request.urlopen(
                    f"http://{host}:{port}/metrics"
                ).read().decode()
            )
            assert "corro_up 1" in body
        finally:
            server.close()

    run(main())


def test_prometheus_endpoint_404_on_other_paths():
    """The request line is parsed, not substring-matched: only GET
    /metrics (and /) serve the registry; any other URL — including ones
    merely CONTAINING "metrics" — is a 404."""

    async def main():
        reg = M.MetricsRegistry()
        reg.counter("corro_up").inc()
        server, (host, port) = await M.serve_prometheus(reg, "127.0.0.1", 0)

        def fetch_status(path):
            try:
                urllib.request.urlopen(f"http://{host}:{port}{path}")
                return 200
            except urllib.error.HTTPError as e:
                return e.code

        try:
            for path in ("/metricsfoo", "/not/metrics", "/favicon.ico",
                         "/x?y=/metrics"):
                status = await asyncio.to_thread(fetch_status, path)
                assert status == 404, path
            assert await asyncio.to_thread(fetch_status, "/metrics") == 200
            # Query strings on the real path still serve.
            assert (
                await asyncio.to_thread(fetch_status, "/metrics?x=1") == 200
            )
        finally:
            server.close()

    run(main())


def test_histogram_quantile_interpolates_within_bucket():
    h = M.Histogram("h", buckets=(1.0, 2.0, 4.0))
    for v in (1.2, 1.8):
        h.observe(v)
    # Both observations land in the (1, 2] bucket: the quantile must
    # interpolate inside it, not report the 2.0 upper bound.
    assert h.quantile(0.5) == pytest.approx(1.5)
    assert h.quantile(1.0) == pytest.approx(2.0)
    assert h.quantile(0.25) == pytest.approx(1.25)
    # Observations beyond the last bucket surface as +inf, not a bound.
    h.observe(100.0)
    assert h.quantile(0.99) == float("inf")
    # Empty histogram stays NaN.
    import math

    assert math.isnan(M.Histogram("e").quantile(0.5))


def test_span_parentage_and_traceparent():
    tr = T.Tracer()
    with tr.span("outer", kind="test") as outer:
        assert tr.current_traceparent() == outer.traceparent
        with tr.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
    spans = tr.recent()
    assert [s["name"] for s in spans] == ["inner", "outer"]
    assert spans[0]["attrs"] == {}
    assert spans[1]["attrs"] == {"kind": "test"}

    # Remote continuation via traceparent string.
    tp = outer.traceparent
    with tr.span("remote", traceparent=tp) as remote:
        assert remote.trace_id == outer.trace_id
        assert remote.parent_id == outer.span_id


def test_traceparent_parsing():
    ok = "00-" + "a" * 32 + "-" + "b" * 16 + "-01"
    assert T.parse_traceparent(ok) == ("a" * 32, "b" * 16)
    for bad in (
        "", "garbage", "00-short-span-01",
        "00-" + "0" * 32 + "-" + "b" * 16 + "-01",  # all-zero trace id
        "00-" + "z" * 32 + "-" + "b" * 16 + "-01",  # non-hex
    ):
        assert T.parse_traceparent(bad) is None


def test_span_records_errors():
    tr = T.Tracer()
    with pytest.raises(RuntimeError):
        with tr.span("failing"):
            raise RuntimeError("boom")
    (span,) = tr.recent()
    assert "boom" in span["attrs"]["error"]


def test_trace_export_file(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    tr = T.Tracer(export_path=path)
    with tr.span("exported"):
        pass
    tr.close()
    import json

    lines = [json.loads(x) for x in open(path)]
    assert lines[0]["name"] == "exported"
    assert lines[0]["duration_us"] >= 0


def test_agents_propagate_trace_and_count_metrics(tmp_path):
    """2-agent cluster: a sync session's server span must continue the
    client's trace (same trace_id); HLC-lag histogram and applied counters
    must tick; admin metrics/trace commands must serve them."""

    async def main():
        a = await launch_test_agent(
            str(tmp_path / "a"), admin_uds=str(tmp_path / "a.sock"),
            sync_interval=0.3,
        )
        b = await launch_test_agent(
            str(tmp_path / "b"), bootstrap=[a.gossip_addr],
            sync_interval=0.3,
        )
        try:
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (1, 'obs')"]]
            )

            async def converged():
                _, rows = b.agent.store.query(
                    Statement("SELECT count(*) FROM tests")
                )
                return rows[0][0] == 1

            await poll_until(converged, timeout=20)

            # Give at least one full sync session time to complete.
            async def has_server_span():
                return [
                    s for s in b.agent.tracer.recent(name="sync_server")
                ] or [s for s in a.agent.tracer.recent(name="sync_server")]

            server_spans = await poll_until(has_server_span, timeout=20)
            all_client = (
                a.agent.tracer.recent(name="sync_client")
                + b.agent.tracer.recent(name="sync_client")
            )
            client_traces = {s["trace_id"] for s in all_client}
            assert any(
                s["trace_id"] in client_traces and s["parent_id"]
                for s in server_spans
            ), "server sync span must continue a client trace"

            # HLC lag histogram observed the inbound changeset.
            snap = b.agent.metrics.snapshot()
            lag_keys = [
                k for k in snap
                if k.startswith("corro_broadcast_recv_lag_seconds_count")
            ]
            assert lag_keys and sum(snap[k] for k in lag_keys) >= 1
            assert any(
                k.startswith("corro_changes_applied") for k in snap
            )

            # Admin RPC surfaces.
            from corrosion_tpu.agent.admin import AdminClient

            cli = AdminClient(str(tmp_path / "a.sock"))
            (mframe,) = await cli.call({"c": "metrics"})
            assert isinstance(mframe["metrics"], dict)
            (tframe,) = await cli.call({"c": "trace", "limit": 5})
            assert isinstance(tframe["spans"], list)
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_wal_checkpoint_loop_truncates_and_times(tmp_path):
    """db_cleanup parity: the WAL checkpoint loop truncates the WAL on the
    background write tier and records its duration (agent.rs:1413-1435)."""

    async def main():
        a = await launch_test_agent(
            str(tmp_path / "a"), wal_checkpoint_interval=0.2
        )
        try:
            for i in range(20):
                await a.client.execute(
                    [["INSERT INTO tests (id, text) VALUES (?, 'w')", [i]]]
                )

            async def checkpointed():
                hist = a.agent.metrics.histogram(
                    "corro_db_wal_truncate_seconds", ""
                )
                return hist.count() >= 1

            from corrosion_tpu.agent.testing import poll_until

            await poll_until(checkpointed, timeout=10.0)
            # The WAL file is empty (truncated) right after a checkpoint
            # with no concurrent writers.
            import os

            wal = a.agent.store.path + "-wal"

            async def wal_empty():
                try:
                    return os.path.getsize(wal) == 0
                except OSError:
                    return True  # no WAL file at all

            await poll_until(wal_empty, timeout=10.0)
        finally:
            await a.stop()

    run(main())


def test_agent_prometheus_endpoint(tmp_path):
    async def main():
        a = await launch_test_agent(
            str(tmp_path / "a"), prometheus_addr="127.0.0.1:0",
            metrics_interval=0.25,  # test-speed sampling cadence
        )
        try:
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (1, 'm')"]]
            )
            host, port = a.agent.prometheus_addr

            async def fetch():
                return await asyncio.to_thread(
                    lambda: urllib.request.urlopen(
                        f"http://{host}:{port}/metrics"
                    ).read().decode()
                )

            body = await fetch()
            assert "corro_gossip_members" in body

            # collect_metrics parity: per-table row counts + pool queues
            # (agent.rs:1138-1187) — poll past the sampling cadence.
            async def sampled():
                body = await fetch()
                return (
                    'corro_db_table_rows{table="tests"} 1' in body
                    and "corro_sqlite_write_queue" in body
                    and "corro_gossip_cluster_size 1" in body
                    and "corro_db_buffered_changes_rows_total 0" in body
                )

            from corrosion_tpu.agent.testing import poll_until

            await poll_until(sampled, timeout=10.0)

            # Observability-parity series (doc/telemetry/prometheus.md →
            # docs/OBSERVABILITY.md audit): config/build gauges are set at
            # start; the commit counter moved with the INSERT above; the
            # pool histograms observed the write and the sampled reads.
            body = await fetch()
            for series in (
                "corro_build_info",
                "corro_gossip_config_max_transmissions",
                "corro_gossip_config_num_indirect_probes",
                "corro_broadcast_buffer_capacity",
                "corro_gossip_updates_backlog",
                "corro_changes_committed 1",
                "corro_sqlite_pool_read_connections 20",
                "corro_sqlite_pool_write_connections 1",
                "corro_sqlite_pool_execution_seconds_count",
                "corro_sqlite_pool_queue_seconds_count",
                "corro_gossip_member_added",
                "corro_gossip_member_removed",
                "corro_broadcast_recv_count",
                "corro_sync_attempts_count",
            ):
                assert series in body, f"missing series: {series}"
        finally:
            await a.stop()

    run(main())


def test_otlp_span_export_shape_and_post(tmp_path):
    """Spans batch-POST to an OTLP/HTTP collector as OTLP/JSON
    (main.rs:64-117's exporter role): a fake collector receives a valid
    ExportTraceServiceRequest."""
    import http.server
    import json as _json
    import threading
    import time as _time

    from corrosion_tpu.utils.tracing import Tracer, spans_to_otlp

    received = []

    class Collector(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            received.append((self.path, _json.loads(body)))
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), Collector)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        tracer = Tracer(
            service="corro-test",
            otlp_endpoint=f"http://127.0.0.1:{srv.server_port}",
        )
        tracer.OTLP_FLUSH_S = 0.0  # flush on every span for the test
        with tracer.span("sync_client", peer="abc"):
            pass
        deadline = _time.monotonic() + 5
        while not received and _time.monotonic() < deadline:
            _time.sleep(0.05)
        assert received, "collector never received spans"
        path, body = received[0]
        assert path == "/v1/traces"
        rs = body["resourceSpans"][0]
        attrs = {
            a["key"]: a["value"]["stringValue"]
            for a in rs["resource"]["attributes"]
        }
        assert attrs["service.name"] == "corro-test"
        span = rs["scopeSpans"][0]["spans"][0]
        assert span["name"] == "sync_client"
        assert len(span["traceId"]) == 32 and len(span["spanId"]) == 16
        assert int(span["endTimeUnixNano"]) >= int(span["startTimeUnixNano"])
        # The pure serializer is reusable for file-based pipelines too.
        again = spans_to_otlp("x", [tracer.recent(1)[0]])
        assert again["resourceSpans"][0]["scopeSpans"][0]["spans"]
    finally:
        srv.shutdown()


def test_runtime_metrics_exported(tmp_path):
    """The tokio-metrics analogue (command/agent.rs:87-213): loop lag,
    task counts, counted handles appear on /metrics."""
    import urllib.request

    async def main():
        a = await launch_test_agent(
            str(tmp_path / "a"), prometheus_addr="127.0.0.1:0",
        )
        try:
            host, port = a.agent.prometheus_addr

            async def sampled():
                body = await asyncio.to_thread(
                    lambda: urllib.request.urlopen(
                        f"http://{host}:{port}/metrics"
                    ).read().decode()
                )
                return (
                    "corro_runtime_loop_lag_seconds" in body
                    and "corro_runtime_tasks" in body
                    and "corro_runtime_counted_handles" in body
                )

            from corrosion_tpu.agent.testing import poll_until

            await poll_until(sampled, timeout=10.0)
        finally:
            await a.stop()

    run(main())


def test_log_format_selection(capsys):
    """LogConfig.format drives the process formatter (config.rs:318-326):
    json mode emits one JSON object per line; plaintext stays readable."""
    import json
    import logging

    from corrosion_tpu.utils.logfmt import setup_logging

    setup_logging(fmt="json")
    try:
        logging.getLogger("corro.test").warning("hello %s", "world")
        err = capsys.readouterr().err.strip().splitlines()[-1]
        obj = json.loads(err)
        assert obj["level"] == "WARNING"
        assert obj["msg"] == "hello world"
        assert obj["target"] == "corro.test"

        setup_logging(fmt="plaintext")
        logging.getLogger("corro.test").warning("plain line")
        err = capsys.readouterr().err.strip().splitlines()[-1]
        assert "WARNING corro.test: plain line" in err
    finally:
        # Leave no custom handlers behind for other tests.
        root = logging.getLogger()
        for h in list(root.handlers):
            if getattr(h, "_corro_log", False):
                root.removeHandler(h)


def test_tail_follow_survives_flight_rotation(tmp_path):
    """`obs tail --follow` regression (propagation-plane PR satellite):
    the size-capped recorder renames the live flight file at chunk
    boundaries; a follower holding the old handle must drain it, replay
    any rotated segments it missed, and resume on the fresh live file —
    every round record seen exactly once, in order, across multiple
    rotations. Driven single-threaded through the generator's own state
    machine: the writer rotates while the reader generator is suspended
    mid-iteration."""
    import numpy as np

    from corrosion_tpu.sim import health
    from corrosion_tpu.sim import telemetry as T2

    path = str(tmp_path / "flight.jsonl")
    # Cap sized so EVERY chunk overflows it: each record_chunk rotates,
    # leaving the follower multiple whole segments behind.
    rec = T2.FlightRecorder(path, engine="dense", mode="w", max_bytes=200)

    def chunk(start, n=3):
        rec.record_chunk(
            start,
            {"msgs": np.arange(start, start + n, dtype=np.uint32)},
        )

    gen = health.iter_flight(
        path, follow=True, poll_s=0.01, idle_timeout_s=0.4
    )
    # Attach the reader to the ORIGINAL live file (consume its header),
    # so every subsequent rotation happens under the open handle.
    first = next(gen)
    assert first.get("kind") == "flight" and first.get("segment") == 0
    seen = []
    chunk(0)
    chunk(3)
    chunk(6)
    rec.close()
    for obj in gen:  # drains the old handle, then replays the chain
        if obj.get("kind") == "round":
            seen.append(obj["round"])
    assert seen == list(range(9)), seen
    # The cap really rotated, repeatedly (else this test pins nothing).
    assert len(T2.flight_segments(path)) >= 4
    # And the offline reader agrees with the follower.
    curves, _chunks = T2.replay_flight(path)
    assert curves["round"].tolist() == list(range(9))


def test_metric_names_match_docs():
    """Metrics-name drift gate (propagation-plane PR satellite): the
    docs/OBSERVABILITY.md reference table must equal the set of series
    this codebase can actually register — literal registrations found
    statically plus the dynamically-built kernel names. A new metric
    (including the epidemic gauges) cannot land undocumented, and a
    documented row cannot outlive its series."""
    import os

    from corrosion_tpu.obs import metrics_ref

    docs = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "OBSERVABILITY.md",
    )
    documented = metrics_ref.documented_metric_names(docs)
    registered = metrics_ref.registered_metric_names()
    undocumented = sorted(registered - documented)
    stale = sorted(documented - registered)
    assert not undocumented and not stale, (
        f"metrics reference drift — undocumented: {undocumented}; "
        f"stale doc rows: {stale}. Regenerate the block between the "
        f"metrics-ref markers with obs/metrics_ref.render_reference()."
    )


def test_tail_missing_flight_path_raises():
    """A missing/typo'd flight path must raise, not read as a
    successful empty tail — only the mid-rotation absence of an
    already-followed live file is tolerated."""
    from corrosion_tpu.sim import health

    with pytest.raises(FileNotFoundError):
        next(health.iter_flight("/definitely/not/a/flight.jsonl"))
    with pytest.raises(FileNotFoundError):
        next(health.iter_flight(
            "/definitely/not/a/flight.jsonl", follow=True,
            idle_timeout_s=0.1,
        ))


def test_tail_follow_replays_segment_missed_by_probe_race(
    tmp_path, monkeypatch
):
    """The check-then-open race: the recorder rotates between the
    follower's exists() probe and its open of the live file, so the
    follower lands on a live file whose header segment is PAST the next
    unread one. It must redirect to the missed rotated segment (yielding
    nothing from the aborted visit) and only then resume — no record
    lost or duplicated. Simulated by failing the exists() probe once."""
    import json as _json
    import os

    from corrosion_tpu.sim import health

    path = str(tmp_path / "flight.jsonl")

    def seg_file(p, seg, rounds):
        with open(p, "w") as f:
            f.write(_json.dumps(
                {"kind": "flight", "schema": "corro-flight/1",
                 "version": 1, "engine": "dense", "segment": seg}
            ) + "\n")
            for r in rounds:
                f.write(_json.dumps({"kind": "round", "round": r}) + "\n")

    seg_file(path, 0, range(0, 3))
    gen = health.iter_flight(
        path, follow=True, poll_s=0.01, idle_timeout_s=0.4
    )
    seen = []
    for obj in gen:
        if obj.get("kind") == "round":
            seen.append(obj["round"])
        if len(seen) == 3:
            break
    # Two rotations happen "while" the follower is suspended; the probe
    # for the first missed segment is then made to fail exactly once,
    # modeling a third rotation landing between probe and open.
    os.replace(path, path + ".1")
    seg_file(path + ".2", 1, range(3, 6))
    seg_file(path, 2, range(6, 9))
    real_exists = os.path.exists
    missed_once = {"done": False}

    def flaky_exists(p):
        if p == path + ".2" and not missed_once["done"]:
            missed_once["done"] = True
            return False
        return real_exists(p)

    monkeypatch.setattr(os.path, "exists", flaky_exists)
    for obj in gen:
        if obj.get("kind") == "round":
            seen.append(obj["round"])
    assert seen == list(range(9)), seen
    assert missed_once["done"]  # the race path really ran
