"""Convergence health plane tests (sim/health.py + the extended
RoundCurves schema + the `obs` CLI).

The acceptance anchor: on a 512-node dense run WITH churn, `obs report`
derives time-to-convergence, staleness p99, a delivery-latency CDF, and
per-churn-event detection latency from the flight recording ALONE, and
the CDF agrees with the exact host-side recomputation
(``visibility_latencies``) to within one histogram bucket.
"""

import json

import numpy as np
import pytest

from corrosion_tpu.sim import health as H
from corrosion_tpu.sim import simulate, visibility_latencies
from corrosion_tpu.sim import telemetry as T
from corrosion_tpu.sim.engine import Schedule


def _churn_cluster(n=512, rounds=96, samples=64, seed=9):
    """The SAME scenario builder `obs record` and CI use — the tests must
    exercise the artifact pipeline's cluster, not a private twin."""
    return H.churned_demo_cluster(
        nodes=n, rounds=rounds, samples=samples, seed=seed
    )


@pytest.fixture(scope="module")
def churn_run(tmp_path_factory):
    """One 512-node churned run, flight-recorded, shared by the module's
    assertions (the run dominates the test wall)."""
    cfg, topo, sched, kill_rounds = _churn_cluster()
    path = str(tmp_path_factory.mktemp("flight") / "churn512.jsonl")
    tele = T.KernelTelemetry(
        engine="dense", recorder=T.FlightRecorder(path, engine="dense")
    )
    final, curves = simulate(
        cfg, topo, sched, seed=2, max_chunk=24, telemetry=tele
    )
    tele.recorder.close()
    return cfg, topo, sched, kill_rounds, path, final, curves


def test_report_from_flight_alone_derives_convergence(churn_run):
    """Acceptance: time-to-convergence, staleness p99, delivery CDF, and
    detection latency all come out of the JSONL flight record with no
    final state in sight."""
    cfg, topo, sched, kill_rounds, path, final, curves = churn_run
    rep = H.report_from_flight(
        path, round_ms=cfg.round_ms, kill_rounds=kill_rounds
    )
    assert rep.engine == "dense"
    assert rep.rounds == sched.rounds

    # The run must actually converge (drain tail sized for it), and the
    # report must see it strictly before the final round.
    assert rep.converged, (rep.need_last, rep.staleness_last)
    assert 0 < rep.converged_round < sched.rounds
    assert rep.ttc_s == rep.converged_round * cfg.round_ms / 1000.0
    # Ground truth: every round from converged_round on is all-quiet,
    # and the one before it is not.
    quiet = (
        (np.asarray(curves["need"]) == 0)
        & (np.asarray(curves["mismatches"]) == 0)
        & (np.asarray(curves["staleness_sum"]) == 0)
    )
    assert quiet[rep.converged_round:].all()
    assert not quiet[rep.converged_round - 1]

    # Staleness verdicts match the curves.
    assert rep.staleness_p99 == pytest.approx(
        float(np.percentile(np.asarray(curves["staleness_sum"]), 99))
    )
    assert rep.staleness_max_peak == float(
        np.asarray(curves["staleness_max"]).max()
    )
    assert rep.staleness_p99 > 0  # the churn run was not trivially quiet

    # Churn detection: the kill wave was detected, in bounded time.
    assert len(rep.detection_events) == 1
    det = rep.detection_events[0]["detected_rounds"]
    assert det is not None and 0 < det < sched.rounds
    assert rep.undetected_unresolved == 0
    # The SWIM plane actually saw the event.
    assert float(np.asarray(curves["swim_undetected_deaths"]).max()) > 0


def test_device_cdf_agrees_with_host_recomputation(churn_run):
    """Acceptance: the on-device delivery-latency histogram's p50/p99
    agree with the exact host-side visibility_latencies percentiles to
    within one histogram bucket."""
    cfg, topo, sched, kill_rounds, path, final, curves = churn_run
    rep = H.report_from_flight(path, round_ms=cfg.round_ms)
    lat = visibility_latencies(final, sched, cfg)

    # Full agreement on event counts: every (sample, node) visibility
    # event landed in exactly one bucket.
    assert rep.vis_total == int((np.asarray(final.vis_round) >= 0).sum())
    assert rep.vis_total == int(np.asarray(curves["vis_count"]).sum())
    assert lat["unseen"] == 0  # converged: nothing unseen

    rm = cfg.round_ms / 1000.0
    for q, got_bucket in (
        (50, rep.vis_p50_bucket), (99, rep.vis_p99_bucket),
    ):
        host_rounds = lat[f"p{q}_s"] / rm
        host_bucket = H.latency_bucket(host_rounds)
        assert abs(host_bucket - got_bucket) <= 1, (
            f"p{q}: host bucket {host_bucket} "
            f"({lat[f'p{q}_s']}s) vs device bucket {got_bucket}"
        )
    # CDF is a proper CDF.
    cdf = rep.vis_cdf
    assert all(a <= b + 1e-12 for a, b in zip(cdf, cdf[1:]))
    assert cdf[-1] == pytest.approx(1.0)


def test_backlog_and_flap_curves_behave(churn_run):
    cfg, topo, sched, kill_rounds, path, final, curves = churn_run
    backlog = np.asarray(curves["queue_backlog"])
    # Busy mid-run, fully drained once converged (budgets expire).
    assert backlog.max() > 0
    assert backlog[-1] == 0
    # False suspicions healed by the end (everyone revived).
    assert np.asarray(curves["swim_false_alarms"])[-1] == 0
    assert np.asarray(curves["swim_undetected_deaths"])[-1] == 0


def test_flight_recorder_streams_while_open(tmp_path):
    """Satellite: each record is flushed as written, so a reader (obs
    tail / tail -f) sees a chunk's rounds while the recorder is still
    open — not at close."""
    path = str(tmp_path / "live.jsonl")
    rec = T.FlightRecorder(path, engine="dense", mode="w")
    rec.record_chunk(0, {"msgs": np.asarray([3, 1, 4])}, wall_s=0.5)
    # Recorder still open: an independent reader must see everything.
    records = list(H.iter_flight(path, follow=False))
    kinds = [r["kind"] for r in records]
    assert kinds == ["flight", "round", "round", "round", "chunk"]
    assert [r["msgs"] for r in records if r["kind"] == "round"] == [3, 1, 4]

    # A torn partial line is held back, not yielded.
    rec._f.write('{"kind": "round", "round": 3, "msgs"')
    rec._f.flush()
    assert len(list(H.iter_flight(path, follow=False))) == 5
    rec.close()


@pytest.fixture(scope="module")
def small_churn():
    """One 64-node churned run with max_chunk=8: the sliced-schedule
    tests below re-run 8-round chunks of the SAME shapes and hit the jit
    cache instead of compiling their own scan lengths."""
    cfg, topo, sched, _ = _churn_cluster(n=64, rounds=72, samples=32,
                                         seed=1)
    final, curves = simulate(cfg, topo, sched, seed=1, max_chunk=8)
    return cfg, topo, sched, final, curves


def _slice_schedule(sched, rounds):
    return Schedule(
        writes=sched.writes[:rounds],
        kill=sched.kill[:rounds], revive=sched.revive[:rounds],
        sample_writer=sched.sample_writer, sample_ver=sched.sample_ver,
        sample_round=sched.sample_round,
    )


def test_visibility_latencies_all_dead_returns_nan(small_churn):
    """Satellite: alive_only=True with every node dead must yield NaN
    percentiles, not crash."""
    cfg, topo, sched, _final, _curves = small_churn
    short = _slice_schedule(sched, 8)
    final, _ = simulate(cfg, topo, short, seed=1, max_chunk=8)
    dead = final._replace(
        swim=final.swim._replace(
            alive=np.zeros_like(np.asarray(final.swim.alive))
        )
    )
    lat = visibility_latencies(dead, short, cfg, alive_only=True)
    assert np.isnan(lat["p50_s"]) and np.isnan(lat["p99_s"])
    assert lat["pairs"] == 0 and lat["unseen"] == 0


def test_visibility_latencies_reports_unseen(small_churn):
    """Satellite: a run cut short of convergence reports unseen > 0 and
    takes percentiles over the seen pairs only."""
    cfg, topo, sched, _final, _curves = small_churn
    short = _slice_schedule(sched, 16)
    final, _ = simulate(cfg, topo, short, seed=1, max_chunk=8)
    lat = visibility_latencies(final, short, cfg)
    assert lat["unseen"] > 0
    assert lat["pairs"] > 0
    assert np.isfinite(lat["p50_s"])  # seen pairs still yield percentiles


def test_visibility_hist_agreement_small_dense_run(small_churn):
    """Satellite: on a small dense run the on-device histogram and the
    host-side percentiles agree within one bucket (the cheap twin of the
    512-node acceptance check — and with zero unseen pairs the bucketed
    counts are exactly the host latencies' histogram)."""
    cfg, topo, sched, final, curves = small_churn
    lat = visibility_latencies(final, sched, cfg, alive_only=False)
    assert lat["unseen"] == 0
    hist = np.asarray([int(curves[k].sum()) for k in T.VIS_LAT_KEYS])
    vis = np.asarray(final.vis_round)
    lat_rounds = (vis - sched.sample_round[:, None])[vis >= 0]
    want = np.zeros(len(T.VIS_LAT_KEYS), np.int64)
    for lr in lat_rounds:
        want[H.latency_bucket(float(lr))] += 1
    np.testing.assert_array_equal(hist, want)


def test_detection_latencies_synthetic():
    u = np.asarray([0, 0, 3, 3, 1, 0, 0, 2, 2, 2])
    events = H.detection_latencies(u)
    assert events == [
        {"round": 2, "detected_rounds": 3},
        {"round": 7, "detected_rounds": None},  # unresolved at record end
    ]
    # Ground-truth kill rounds split overlapping events.
    events = H.detection_latencies(u, kill_rounds=[2, 3])
    assert events == [
        {"round": 2, "detected_rounds": 3},
        {"round": 3, "detected_rounds": 2},
    ]


def test_cdf_quantile_and_bucket_helpers():
    counts = np.zeros(len(T.VIS_LAT_KEYS))
    assert H.cdf_quantile(counts, 0.5) == (-1, float("nan")) or np.isnan(
        H.cdf_quantile(counts, 0.5)[1]
    )
    counts[1] = 9
    counts[3] = 1
    idx, edge = H.cdf_quantile(counts, 0.5)
    assert (idx, edge) == (1, 2.0)
    idx, edge = H.cdf_quantile(counts, 0.99)
    assert (idx, edge) == (3, 8.0)
    # Overflow bucket reports inf.
    counts[:] = 0
    counts[-1] = 5
    assert H.cdf_quantile(counts, 0.5)[1] == float("inf")
    # Host-side bucketize mirrors the on-device edges.
    assert H.latency_bucket(1) == 0
    assert H.latency_bucket(2) == 1
    assert H.latency_bucket(65) == len(T.VIS_LAT_EDGES)


def test_report_publish_and_diff_regression(churn_run):
    from corrosion_tpu.utils import metrics as M

    cfg, topo, sched, kill_rounds, path, final, curves = churn_run
    rep = H.report_from_curves(curves, engine="dense")
    reg = M.MetricsRegistry()
    H.publish_report(reg, rep)
    assert reg.gauge("corro_kernel_health_converged").get(
        engine="dense"
    ) == 1.0
    assert "corro_kernel_health_vis_p99_seconds" in reg.render()

    # Self-diff is clean; a degraded candidate flags regressions.
    assert H.diff_reports(rep, rep)["regressions"] == []
    worse = H.report_from_curves(curves, engine="dense")
    worse.vis_p99_s = rep.vis_p99_s * 2 + 1
    worse.converged_round = None  # also: never converged
    diff = H.diff_reports(rep, worse)
    assert any("vis_p99_s" in r for r in diff["regressions"])
    assert any("did not converge" in r for r in diff["regressions"])
    # A candidate regressing into the OVERFLOW bucket (inf) is the worst
    # case and must flag, not silently skip.
    overflow = H.report_from_curves(curves, engine="dense")
    overflow.vis_p99_s = float("inf")
    assert any(
        "vis_p99_s" in r
        for r in H.diff_reports(rep, overflow)["regressions"]
    )


def test_load_report_classifies_large_and_pretty_json(tmp_path, churn_run):
    """load_report must not mis-sniff a report JSON as a flight record:
    big reports (schema key past any fixed prefix) and pretty-printed
    ones both load as reports, and a flight JSONL still replays."""
    cfg, topo, sched, kill_rounds, path, final, curves = churn_run
    rep = H.report_from_curves(curves, engine="dense")
    # Pad detection_events so the serialized schema key sits far past 4k.
    rep.detection_events = [
        {"round": i, "detected_rounds": 3} for i in range(400)
    ]
    big = tmp_path / "big.json"
    big.write_text(json.dumps(rep.to_dict()))
    assert len(big.read_text()) > 4096
    loaded = H.load_report(str(big))
    assert loaded.rounds == rep.rounds
    assert len(loaded.detection_events) == 400
    pretty = tmp_path / "pretty.json"
    pretty.write_text(json.dumps(rep.to_dict(), indent=2))
    assert H.load_report(str(pretty)).rounds == rep.rounds
    assert H.load_report(path).rounds == sched.rounds  # flight unaffected


def test_obs_cli_report_tail_diff(churn_run, capsys, tmp_path):
    """The obs CLI end to end on a real flight record: report (text +
    json), tail summary, self-diff exit 0, regression diff exit 1."""
    from corrosion_tpu import cli

    cfg, topo, sched, kill_rounds, path, final, curves = churn_run
    assert cli.main(["obs", "report", path]) == 0
    text = capsys.readouterr().out
    assert "converged: yes at round" in text
    assert "delivery latency" in text

    assert cli.main(["obs", "report", path, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["schema"] == H.REPORT_SCHEMA
    assert rep["engine"] == "dense"
    report_json = tmp_path / "report.json"
    report_json.write_text(json.dumps(rep))

    assert cli.main(["obs", "tail", path]) == 0
    tail = capsys.readouterr().out
    assert "[flight] engine=dense" in tail
    assert f"[tail] {sched.rounds} round records" in tail

    # Self-diff (flight vs its own saved report) passes...
    assert cli.main(["obs", "diff", path, str(report_json)]) == 0
    capsys.readouterr()
    # ...and a doctored regression fails with exit 1.
    rep_bad = dict(rep)
    rep_bad["vis_p99_s"] = (rep["vis_p99_s"] or 1) * 10 + 5
    bad_json = tmp_path / "bad.json"
    bad_json.write_text(json.dumps(rep_bad))
    assert cli.main(["obs", "diff", path, str(bad_json)]) == 1


def test_report_tolerates_pre_health_flights(tmp_path):
    """Old flight files (PR 1 schema, no health keys) still replay into
    a report: health series read as zero, no crashes."""
    path = str(tmp_path / "old.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps(
            {"kind": "flight", "version": 1, "engine": "dense"}
        ) + "\n")
        for r in range(4):
            f.write(json.dumps(
                {"kind": "round", "round": r, "msgs": 5, "need": 0,
                 "mismatches": 0}
            ) + "\n")
    rep = H.report_from_flight(path)
    assert rep.rounds == 4
    assert rep.converged  # need/mismatches zero, health zero-filled
    assert rep.vis_total == 0
    assert np.isnan(rep.vis_p50_s)

    # The JSON encoding of a no-events report must be STRICT json (no
    # bare NaN/Infinity tokens), and load_report round-trips it.
    d = rep.to_dict()
    text = json.dumps(d)
    assert "NaN" not in text and "Infinity" not in text
    assert d["vis_p50_s"] is None
    saved = str(tmp_path / "rep.json")
    with open(saved, "w") as f:
        f.write(text)
    back = H.load_report(saved)
    assert np.isnan(back.vis_p50_s) and back.rounds == 4
    # inf (overflow bucket) round-trips as "inf" and still diffs as a
    # regression against a finite baseline.
    rep_inf = H.report_from_flight(path)
    rep_inf.vis_p99_s = float("inf")
    with open(saved, "w") as f:
        f.write(json.dumps(rep_inf.to_dict()))
    assert H.load_report(saved).vis_p99_s == float("inf")
    fin = H.report_from_flight(path)
    fin.vis_p99_s = 4.0
    assert any(
        "vis_p99_s" in r
        for r in H.diff_reports(fin, H.load_report(saved))["regressions"]
    )
