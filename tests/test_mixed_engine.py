"""Mixed chunk+version plane (VERDICT r4 missing #2): large multi-chunk
transactions and a version-granular write storm in ONE composite round.

- Convergence: watermarks cross the big versions only through chunk
  reassembly or whole-version sync grants; final state converges on
  watermarks AND CRDT cells against the serial-merge ground truth that
  includes the big versions.
- Differential: the kernel's per-(node, stream) seq coverage
  (ops/intervals) replayed against the host bookie's Partial gap
  tracking (core/bookkeeping.py) on identical chunk-arrival traces.
"""

import jax.numpy as jnp
import numpy as np

from corrosion_tpu.models.baselines import mixed_storm
from corrosion_tpu.ops import gossip, intervals
from corrosion_tpu.sim import mixed_engine


def _run_small(**kw):
    cfg, ccfg, topo, sched, spec = mixed_storm(
        n=kw.pop("n", 200), streams=kw.pop("streams", 4),
        last_seq=kw.pop("last_seq", 511), rounds=kw.pop("rounds", 160),
        samples=kw.pop("samples", 64), **kw,
    )
    final, curves = mixed_engine.simulate_mixed(
        cfg, ccfg, topo, sched, spec, seed=0
    )
    return cfg, ccfg, topo, sched, spec, final, curves


def test_mixed_workload_converges_with_big_versions():
    cfg, ccfg, topo, sched, spec, final, curves = _run_small()
    heads = np.asarray(final.data.head)
    # Big versions really occupy their slots in the writers' sequences.
    for s in range(len(spec.writer)):
        assert heads[spec.writer[s]] >= spec.version[s]
    # Convergence over watermarks INCLUDING the big versions.
    assert (np.asarray(final.data.contig) == heads[None, :]).all()
    assert int(gossip.total_need(final.data)) == 0
    # Every (node, stream) fully reassembled (directly or via sync
    # backfill).
    assert bool(np.asarray(final.applied_before).all())
    assert int(curves["streams_applied"][-1]) == cfg.n_nodes * len(
        spec.writer
    )
    # Converged end state shows in the health plane too.
    assert float(curves["staleness_sum"][-1]) == 0.0
    assert float(curves["need"][-1]) == 0.0
    # Sampled small writes all became visible everywhere.
    assert int((np.asarray(final.vis_round) < 0).sum()) == 0
    # The big versions' content moves on the chunk plane; the version
    # plane's queues must never have carried them. Final queues should
    # be drained anyway, but the stronger check: chunk traffic happened
    # AND big versions applied at nodes whose coverage came gap-free.
    # The canonical schema keeps the chunk plane separable from the
    # version-plane msgs/applied_sync exactly for this.
    assert int(curves["chunks_sent"].sum()) > 0
    assert int(curves["seqs_granted"].sum()) > 0
    # Cells: ground truth = serial merge over every version of every
    # writer, big ones included (they derive cells like any version).
    ref = gossip.serial_merge_reference(heads, cfg.gossip)
    pc = gossip.node_cells(final.data, cfg.gossip)
    assert bool(jnp.all(pc.cl == ref.cl[None, :]))
    assert bool(jnp.all(pc.col_version == ref.col_version[None, :]))
    assert bool(jnp.all(pc.value_rank == ref.value_rank[None, :]))


def test_mixed_engine_chunked_run_with_telemetry(tmp_path):
    """simulate_mixed(max_chunk=...) carries state across device
    executions (identical curves), and the flight recorder streams at
    each boundary under engine="mixed" — the PR 1 telemetry API the
    mixed engine was missing.

    Uses the same small config as test_kernel_telemetry's parity check
    deliberately: the unchunked baseline scan is then already in the jit
    cache and only the chunk-length scan compiles.
    """
    from corrosion_tpu.sim import telemetry as T
    from corrosion_tpu.utils import metrics as M

    cfg, ccfg, topo, sched, spec = mixed_storm(
        n=64, streams=2, last_seq=255, rounds=24, samples=16, n_cells=0
    )
    _, plain = mixed_engine.simulate_mixed(
        cfg, ccfg, topo, sched, spec, seed=0
    )

    path = str(tmp_path / "mixed.jsonl")
    reg = M.MetricsRegistry()
    tele = T.KernelTelemetry(
        engine="mixed",
        recorder=T.FlightRecorder(path, engine="mixed"),
        registry=reg,
    )
    _, chunked = mixed_engine.simulate_mixed(
        cfg, ccfg, topo, sched, spec, seed=0, max_chunk=8,
        telemetry=tele,
    )
    tele.recorder.close()

    for k in T.ROUND_CURVE_KEYS:
        np.testing.assert_array_equal(plain[k], chunked[k], err_msg=k)
    assert len(tele.chunk_walls) == 3
    rec, markers = T.replay_flight(path)
    assert rec["round"].tolist() == list(range(24))
    assert [m["start"] for m in markers] == [0, 8, 16]
    assert reg.counter("corro_kernel_msgs_total").get(
        engine="mixed"
    ) == float(chunked["msgs"].astype(np.float64).sum())
    assert reg.counter(
        "corro_kernel_health_chunks_sent_total"
    ).get(engine="mixed") == float(
        chunked["chunks_sent"].astype(np.float64).sum()
    )


def test_partial_coverage_differential_vs_bookie():
    from corrosion_tpu.core.bookkeeping import Partial
    from corrosion_tpu.core.intervals import RangeSet

    rng = np.random.default_rng(3)
    last_seq = 4095
    for trial in range(8):
        iv = intervals.IntervalSet(
            starts=jnp.full((16,), intervals.EMPTY, jnp.int32),
            ends=jnp.full((16,), intervals.EMPTY - 1, jnp.int32),
        )
        part = Partial(seqs=RangeSet(), last_seq=last_seq, ts=0)
        # Chunk arrivals: shuffled 256-seq chunks with duplicates and
        # overlap (the out-of-order buffering the reference gap-tracks,
        # agent.rs:2063-2151).
        chunks = [
            (s, min(s + 255 + int(rng.integers(0, 64)), last_seq))
            for s in range(0, last_seq + 1, 256)
        ]
        rng.shuffle(chunks)
        chunks = chunks + chunks[: len(chunks) // 3]  # duplicates
        for s, e in chunks:
            iv = intervals.insert(iv, jnp.int32(s), jnp.int32(e))
            part.seqs.insert(s, e)
            # Gap sets agree at every step.
            kg = intervals.gaps(iv, jnp.int32(0), jnp.int32(last_seq))
            ks, ke = np.asarray(kg.starts), np.asarray(kg.ends)
            kernel_gaps = [
                (int(a), int(b)) for a, b in zip(ks, ke) if a <= b
            ]
            host_gaps = list(part.seqs.gaps(0, last_seq))
            assert kernel_gaps == host_gaps, (
                f"trial {trial}: kernel {kernel_gaps} vs host {host_gaps}"
            )
            kernel_done = int(
                np.asarray(
                    intervals.contiguous_watermark(iv, jnp.int32(0))
                )
            ) >= last_seq
            assert kernel_done == part.is_complete()
        assert part.is_complete()
