"""Fidelity plane: round-model calibration + mixed-mode divergence.

Fast units pin the model math (derivation, ring occupancy, capacity
deferral, divergence metrics, budget gate) and the static-skip promise
(identity model => bit-identical engine traces). The live-cluster
mixed-mode comparisons are slow-marked out of the tier-1 lane and run
unfiltered in the `fidelity` CI job (docs/FIDELITY.md).
"""

import asyncio
import json

import numpy as np
import pytest

from corrosion_tpu.fidelity.calibrate import (
    MODEL_SCHEMA,
    RING_REPR_MS,
    RoundModel,
    derive_model,
    from_characterization,
    from_ring_occupancy,
    identity_model,
    trace_fingerprint,
)
from corrosion_tpu.fidelity.compare import (
    bucket_hist,
    divergence_verdict,
    hist_cdf,
)
from corrosion_tpu.fidelity.report import (
    check_fidelity_budget,
    emit_fidelity_report,
)
from corrosion_tpu.sim.engine import Schedule
from corrosion_tpu.sim.faults import axes_from_rates


def _model(**kw):
    base = dict(
        rtt_samples_by_pair={(0, 0): [1.0, 2.0, 4.0, 9.0]},
        flush_ms=50.0,
        apply_ms=50.0,
        apply_rate_per_s=100.0,
        probe_attempts=200,
        probe_timeouts=6,
        provenance={"source": "test"},
    )
    base.update(kw)
    return derive_model(**base)


# ---------------------------------------------------------------------------
# RoundModel derivation + serialization.


def test_derive_model_pins_round_and_miss():
    m = _model()
    # round = flush + apply + one-way p50 (p50 of rtts = 3 -> 1.5).
    assert m.round_ms == pytest.approx(101.5)
    # miss = E[min(one_way / round, 1)] over samples.
    expect = np.mean([x / 2.0 / 101.5 for x in (1.0, 2.0, 4.0, 9.0)])
    assert m.pair_miss[0][0] == pytest.approx(expect, abs=1e-5)
    assert m.probe_loss == pytest.approx(0.03)
    assert not m.is_identity


def test_model_json_roundtrip_with_provenance():
    m = _model()
    d = json.loads(m.to_json())
    assert d["schema"] == MODEL_SCHEMA
    m2 = RoundModel.from_json(m.to_json())
    assert m2.to_dict() == m.to_dict()
    # A model without provenance is rejected: a calibration whose
    # inputs are unstated cannot back a wall-clock claim.
    d["provenance"] = {}
    with pytest.raises(ValueError, match="provenance"):
        RoundModel.from_dict(d)
    d["schema"] = "bogus/9"
    with pytest.raises(ValueError, match="corro-round-model"):
        RoundModel.from_dict(d)


def test_compile_axes_bit_identical_across_calls():
    m = _model()
    a, b = m.compile_axes(24), m.compile_axes(24)
    for xa, xb in ((a.loss, b.loss), (a.probe_loss, b.probe_loss)):
        assert (xa is None) == (xb is None)
        if xa is not None:
            assert xa.dtype == xb.dtype and xa.shape == xb.shape
            assert (xa == xb).all(), "compile must be bit-deterministic"
    assert a.loss is not None and a.loss.shape == (24, 1)
    assert a.probe_loss is not None


def test_identity_model_compiles_to_absent_axes():
    ident = identity_model()
    assert ident.is_identity
    c = ident.compile_axes(8)
    assert c.loss is None and c.probe_loss is None
    sched = Schedule(writes=np.ones((8, 2), np.uint32)).make_samples(8)
    out = ident.apply(sched, n_nodes=4)
    assert out.loss is None and out.probe_loss is None
    assert (out.writes == sched.writes).all()


def test_identity_model_engine_trace_bit_identical():
    # The chaos plane's static-skip promise, re-pinned through the new
    # entry path: a fault-free (identity) model leaves engine traces
    # bit-identical to no-model runs.
    from corrosion_tpu.models.baselines import _cfg
    from corrosion_tpu.sim.engine import simulate

    cfg, topo = _cfg(12, writers=[0, 1], sync_interval=3, n_cells=0)
    writes = np.zeros((10, 2), np.uint32)
    writes[:4] = 1
    s0 = Schedule(writes=writes.copy()).make_samples(8)
    s1 = identity_model().apply(
        Schedule(writes=writes.copy()).make_samples(8), n_nodes=12
    )
    f0, c0 = simulate(cfg, topo, s0, seed=3)
    f1, c1 = simulate(cfg, topo, s1, seed=3)
    for k in c0:
        assert (np.asarray(c0[k]) == np.asarray(c1[k])).all(), k
    assert (np.asarray(f0.vis_round) == np.asarray(f1.vis_round)).all()
    assert (np.asarray(f0.data.contig) == np.asarray(f1.data.contig)).all()


def test_from_ring_occupancy_math():
    occ = np.zeros((2, 2, len(RING_REPR_MS)), np.int64)
    occ[0, 0, 0] = occ[1, 1, 0] = 4  # intra-region: ring 0
    occ[0, 1, 4] = occ[1, 0, 4] = 4  # cross-region: ring 4 (150 ms repr)
    m = from_ring_occupancy(occ, flush_ms=500.0)
    # one-way p50 = median over pair means (2.5, 150, 150, 2.5)/2.
    assert m.round_ms == pytest.approx(500.0 + np.median(
        [2.5, 150.0, 150.0, 2.5]
    ) / 2.0)
    assert m.pair_miss[0][1] == pytest.approx(
        min(75.0 / m.round_ms, 1.0), abs=1e-5
    )
    assert m.regions == 2
    # loss_by_region folds sources per receiver.
    lb = m.loss_by_region()
    assert lb.shape == (2,) and lb[0] == pytest.approx(
        (m.pair_miss[0][0] + m.pair_miss[0][1]) / 2.0, abs=1e-6
    )
    with pytest.raises(ValueError, match="ring sample"):
        from_ring_occupancy(
            np.zeros((2, 2, len(RING_REPR_MS))), flush_ms=500.0
        )


def test_from_characterization_requires_percentiles():
    m = from_characterization(
        {"probe_rtt_under_bulk_ms": {"p50": 1.0, "p99": 8.0},
         "probe_loss_under_bulk": 0.05},
        flush_ms=50.0,
    )
    assert m.probe_loss == pytest.approx(0.05)
    assert m.regions == 1 and m.flush_ms == 50.0
    with pytest.raises(ValueError, match="p50/p99"):
        from_characterization({}, flush_ms=50.0)


# ---------------------------------------------------------------------------
# Capacity deferral.


def test_defer_schedule_spreads_burst_keeps_samples():
    m = _model()  # 100/s at ~101.5 ms rounds -> ~10.15 writes/round
    writes = np.zeros((6, 2), np.uint32)
    writes[0] = (20, 10)  # 30-write burst in round 0
    sched = Schedule(writes=writes.copy()).make_samples(30)
    sample_round = sched.sample_round.copy()
    out = m.defer_schedule(sched)
    # Totals and per-writer order preserved; per-round admission capped.
    assert out.writes.sum(axis=0).tolist() == [20, 10]
    cap = m.apply_rate_per_s * m.round_ms / 1000.0
    assert out.writes.sum(axis=1).max() <= int(np.ceil(cap))
    # Samples untouched: latency still measures from true commit round.
    assert (out.sample_round == sample_round).all()
    assert (out.sample_ver == sched.sample_ver).all()
    # FIFO: the backlog drains in the earliest following rounds.
    assert out.writes[0].sum() > 0 and out.writes.sum() == 30


def test_defer_schedule_noop_under_capacity_and_unmeasured():
    m = _model()
    writes = np.ones((5, 2), np.uint32)  # 2/round << capacity
    sched = Schedule(writes=writes.copy()).make_samples(10)
    assert m.defer_schedule(sched) is sched
    m0 = _model(apply_rate_per_s=0.0)
    burst = Schedule(writes=np.full((2, 2), 50, np.uint32)).make_samples(8)
    assert m0.defer_schedule(burst) is burst


def test_defer_schedule_extends_rounds_for_deep_backlog():
    m = _model(apply_rate_per_s=20.0)  # ~2 writes/round capacity
    writes = np.zeros((2, 1), np.uint32)
    writes[0, 0] = 20
    sched = Schedule(writes=writes.copy()).make_samples(20)
    out = m.defer_schedule(sched)
    assert out.rounds > 2 and out.writes.sum() == 20
    # Extension with fault axes already attached must refuse (axes are
    # per-round; defer BEFORE applying plans).
    sched2 = Schedule(writes=writes.copy()).make_samples(20)
    sched2 = m.apply(sched2, n_nodes=2)
    with pytest.raises(ValueError, match="defer BEFORE"):
        m.defer_schedule(sched2)


# ---------------------------------------------------------------------------
# Divergence metrics + budget gate.


def test_axes_from_rates_accepts_per_round_matrix():
    loss = np.zeros((4, 2), np.float32)
    loss[1] = (0.5, 0.25)
    c = axes_from_rates(4, loss_by_region=loss)
    assert c.loss is not None and (c.loss == loss).all()
    with pytest.raises(ValueError, match="rows"):
        axes_from_rates(3, loss_by_region=loss)
    with pytest.raises(ValueError, match="0, 1"):
        axes_from_rates(2, loss_by_region=np.array([1.5]))
    assert axes_from_rates(4, loss_by_region=np.zeros(2)).loss is None


def test_divergence_verdict_emd_and_deltas():
    live = bucket_hist([0.5, 0.9, 1.5, 1.2, 0.4])
    near = bucket_hist([0.8, 1.1, 1.0, 1.6, 0.3])
    far = bucket_hist([9.0, 9.5, 10.0, 8.7, 9.9])
    v_near, v_far = (
        divergence_verdict(live, near), divergence_verdict(live, far)
    )
    # EMD = sum of |dCDF| = expected bucket displacement; a replay 4
    # buckets off for all its mass must never beat one within 1 bucket.
    assert v_near["cdf_distance"] < v_far["cdf_distance"]
    assert v_far["cdf_distance"] == pytest.approx(
        sum(v_far["per_bucket_cdf_diff"]), abs=1e-4
    )
    assert v_far["kolmogorov"] == max(v_far["per_bucket_cdf_diff"])
    assert v_near["p99_bucket_delta"] <= 1
    assert v_far["p50_bucket_delta"] >= 3
    cdf = hist_cdf(live)
    assert cdf[-1] == pytest.approx(1.0)
    with pytest.raises(ValueError, match="non-empty"):
        divergence_verdict([0] * 8, live)


def test_side_report_degrades_without_crashing_on_empty_hists():
    # A run where nothing delivered must still produce a report block
    # (the gate's unseen/missing-ceiling breaches flag it) — the
    # standing lane emits its artifact even for a broken run.
    from corrosion_tpu.fidelity.compare import _side_report

    live = {"lat_ms": [], "ttc_ms": None}
    rep = {
        "round_ms": 100.0, "rounds": 10, "pairs": 8, "unseen": 8,
        "lat_rounds": np.zeros(0), "vis_offset_rounds": 0.5,
        "ttc_ms": None,
    }
    out = _side_report(live, rep, cal_round_ms=100.0)
    assert out["unseen"] == 8 and "cdf_distance" not in out
    # And the healthy side against an empty live is equally tolerant.
    rep2 = dict(rep, lat_rounds=np.ones(4), unseen=0)
    out2 = _side_report(live, rep2, cal_round_ms=100.0)
    assert sum(out2["hist"]) == 4 and "cdf_distance" not in out2


def test_from_characterization_rejects_out_of_range_loss():
    with pytest.raises(ValueError, match="probe_loss"):
        from_characterization(
            {"probe_rtt_under_bulk_ms": {"p50": 1.0, "p99": 2.0},
             "probe_loss_under_bulk": 1.7},
            flush_ms=50.0,
        )


def _measured(**overrides):
    base = {
        "platform": "cpu",
        "scenario": "ci_smoke",
        "scenarios": {
            "steady": {
                "calibrated": {"cdf_distance": 0.4, "p99_bucket_delta": 1,
                               "unseen": 0},
                "uncalibrated": {"cdf_distance": 2.2},
                "calibrated_closer": True,
                "live": {"unseen": 0},
            },
            "dcn": {"invariants_ok": True, "recovery_delta_rounds": 1,
                    "calibrated": {"unseen": 0}},
        },
    }
    base.update(overrides)
    return base


def test_fidelity_budget_gate_units():
    budget = {
        "platform": "cpu", "scenario": "ci_smoke", "tolerance": 1.5,
        "ceilings": {"scenarios.steady.calibrated.cdf_distance": 0.5},
    }
    ok, br = check_fidelity_budget(_measured(), budget)
    assert ok and not br
    # Tolerance scales ceilings (0.4 <= 0.5*1.5) but NOT the ordering.
    tight = dict(budget, ceilings={
        "scenarios.steady.calibrated.cdf_distance": 0.2,
    })
    ok, br = check_fidelity_budget(_measured(), tight)
    assert not ok and "cdf_distance" in br[0]
    # A missing ceiling path is a breach (vanished surface).
    missing = dict(budget, ceilings={"scenarios.gone.cdf_distance": 1.0})
    ok, br = check_fidelity_budget(_measured(), missing)
    assert not ok and "missing" in br[0]
    # calibrated-beats-uncalibrated: never tolerance-scaled, any margin
    # of failure breaches even under a huge tolerance.
    m = _measured()
    m["scenarios"]["steady"]["calibrated_closer"] = False
    ok, br = check_fidelity_budget(m, {"tolerance": 1000.0})
    assert not ok and "strictly closer" in br[0]
    # DCN invariant cross-check: absolute.
    m = _measured()
    m["scenarios"]["dcn"]["invariants_ok"] = False
    ok, br = check_fidelity_budget(m, {})
    assert not ok and "invariant" in br[0]
    # unseen pairs: absolute.
    m = _measured()
    m["scenarios"]["steady"]["calibrated"]["unseen"] = 3
    ok, br = check_fidelity_budget(m, {})
    assert not ok and "unseen" in br[0]
    # Dimension mismatch names --update.
    ok, br = check_fidelity_budget(
        _measured(), {"platform": "axon"}
    )
    assert not ok and "--update" in br[0]


def test_emit_fidelity_report_requires_trace_fingerprint():
    good = {
        "platform": "cpu", "nodes": 3, "device_count": 1,
        "config_fingerprint": "ab12", "scenario": "x",
        "trace_fingerprint": trace_fingerprint([(0, "a", 1)]),
    }
    assert emit_fidelity_report(dict(good)) == good
    bad = dict(good)
    bad.pop("trace_fingerprint")
    with pytest.raises(ValueError, match="trace_fingerprint"):
        emit_fidelity_report(bad)


def test_trace_fingerprint_stable_and_order_free():
    a = [(1, "x", 1), (2, "y", 1)]
    assert trace_fingerprint(a) == trace_fingerprint(list(reversed(a)))
    assert trace_fingerprint(a) != trace_fingerprint(a[:1])


# ---------------------------------------------------------------------------
# WAN ring model + DCN scenario (kernel-side).


def test_wan_ring_model_shape_and_symmetry():
    from corrosion_tpu.fidelity.scenarios import wan_ring_model

    m = wan_ring_model()
    assert m.regions == 4 and not m.is_identity
    miss = np.asarray(m.pair_miss)
    assert (miss == miss.T).all(), "geo rings are symmetric"
    assert (np.diag(miss) < miss.max()).all(), "intra-region is nearest"
    c1, c2 = m.compile_axes(16), m.compile_axes(16)
    assert (c1.loss == c2.loss).all()


@pytest.mark.slow
def test_dcn_partition_scenario_invariant_cross_check():
    from corrosion_tpu.fidelity.scenarios import dcn_partition

    rep = dcn_partition(rounds=48, seed=0)
    assert rep["invariants_ok"], rep["invariant_violations"]
    assert rep["both_recovered"]
    assert rep["calibrated"]["unseen"] == 0
    assert rep["recovery_delta_rounds"] is not None


# ---------------------------------------------------------------------------
# Mixed-mode live-vs-kernel (slow: real agents over loopback).


@pytest.mark.slow
def test_mixed_mode_steady_calibrated_beats_uncalibrated(tmp_path):
    from corrosion_tpu.fidelity import scenarios

    rep = asyncio.run(
        scenarios.steady_load(str(tmp_path), writes=18, rate_hz=12.0)
    )
    assert rep["live"]["unseen"] == 0
    assert rep["calibrated"]["unseen"] == 0
    assert rep["calibrated_closer"], (
        rep["calibrated"]["cdf_distance"],
        rep["uncalibrated"]["cdf_distance"],
    )
    # The headline acceptance shape: within one bucket at p99.
    assert rep["calibrated"]["p99_bucket_delta"] <= 1
    # The model was measured, not assumed.
    m = rep["model"]
    assert m["provenance"]["source"] == "live"
    assert m["provenance"]["probe_attempts"] > 0
    assert 10.0 < m["round_ms"] < 500.0


@pytest.mark.slow
def test_mixed_mode_burst_calibrated_beats_uncalibrated(tmp_path):
    from corrosion_tpu.fidelity import scenarios

    rep = asyncio.run(scenarios.burst_drain(str(tmp_path), writes=18))
    assert rep["live"]["unseen"] == 0
    assert rep["calibrated_closer"], (
        rep["calibrated"]["cdf_distance"],
        rep["uncalibrated"]["cdf_distance"],
    )
    # Same trace on both sides, pinned by fingerprint.
    assert rep["trace_fingerprint"]


@pytest.mark.slow
def test_fidelity_cli_calibrate_and_replay(tmp_path, capsys):
    # calibrate -> model JSON on disk -> replay a saved trace under it.
    from corrosion_tpu.cli import main as cli_main
    from corrosion_tpu.sim.trace import Trace

    model_path = str(tmp_path / "model.json")
    rc = cli_main([
        "fidelity", "calibrate", "--agents", "2", "--probes", "8",
        "--out", model_path,
    ])
    assert rc == 0
    m = RoundModel.load(model_path)
    assert m.provenance["source"] == "live"

    trace_path = str(tmp_path / "trace.jsonl")
    Trace(events=[
        (0, "aa", 1), (40, "bb", 1), (80, "aa", 2), (400, "bb", 2),
    ]).save(trace_path)
    rc = cli_main([
        "fidelity", "replay", trace_path, "--model", model_path,
        "--observers", "1", "--json",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["unseen"] == 0 and sum(out["hist"]) > 0
