"""Sharded-vs-unsharded equivalence over the virtual 8-device CPU mesh.

The dryrun only proves the sharded path compiles and runs; this asserts the
placements in corrosion_tpu/parallel/mesh.py do not change semantics: the
same seed produces bit-identical final state sharded and unsharded (all
state is integer, so every reduction is order-independent).
"""

import jax
import numpy as np
import pytest

from corrosion_tpu import models, parallel
from corrosion_tpu.sim import engine, simulate

N, N_REGIONS = 64, 4


def _wan_setup():
    cfg, topo, sched = models.wan_100k(
        n=N, n_regions=N_REGIONS, n_writers=16, rounds=24, samples=16
    )
    sched.writes[:8, :] = 1
    sched = sched.make_samples(16)
    return cfg, topo, sched


def _run_sharded(cfg, topo, sched, mesh):
    topo_s = parallel.shard_topology(topo, mesh)
    state0 = engine.init_cluster(cfg, len(sched.sample_writer))
    state0 = parallel.shard_cluster_state(state0, mesh)
    return simulate(cfg, topo_s, sched, seed=5, state=state0)


def _assert_identical(final_u, final_s, curves_u=None, curves_s=None):
    for name in ("head", "contig", "seen", "q_writer", "q_ver", "q_tx"):
        np.testing.assert_array_equal(
            np.asarray(getattr(final_u.data, name)),
            np.asarray(getattr(final_s.data, name)),
            err_msg=name,
        )
    for name in ("cl", "col_version", "value_rank"):
        np.testing.assert_array_equal(
            np.asarray(getattr(final_u.data.cells, name)),
            np.asarray(getattr(final_s.data.cells, name)),
            err_msg=f"cells.{name}",
        )
    # wan_100k uses the sparse SWIM kernel; compare every membership leaf.
    for u_leaf, s_leaf in zip(
        jax.tree.leaves(final_u.swim), jax.tree.leaves(final_s.swim)
    ):
        np.testing.assert_array_equal(np.asarray(u_leaf), np.asarray(s_leaf))
    np.testing.assert_array_equal(
        np.asarray(final_u.vis_round), np.asarray(final_s.vis_round)
    )
    if curves_u is not None:
        for k in curves_u:
            np.testing.assert_array_equal(curves_u[k], curves_s[k], err_msg=k)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
@pytest.mark.slow  # tier-1 budget; the multichip CI job runs this file unfiltered
def test_sharded_run_is_bit_identical():
    cfg, topo, sched = _wan_setup()
    final_u, curves_u = simulate(cfg, topo, sched, seed=5)
    final_s, curves_s = _run_sharded(cfg, topo, sched, parallel.make_mesh(8))
    _assert_identical(final_u, final_s, curves_u, curves_s)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_wan_mesh_2d_bit_identical_and_region_blocked():
    """The (dcn, ici) WAN mesh must be semantics-preserving AND place every
    region's rows inside a single DCN group — the locality make_wan_mesh
    exists for (in-region gossip rides ICI; only cross-region crosses DCN).
    """
    cfg, topo, sched = _wan_setup()
    final_u, _ = simulate(cfg, topo, sched, seed=5)
    mesh = parallel.make_wan_mesh(n_dcn=2, n_ici=4)
    assert mesh.axis_names == ("dcn", "ici")
    final_s, _ = _run_sharded(cfg, topo, sched, mesh)
    _assert_identical(final_u, final_s)

    # Placement: device -> dcn coordinate from the mesh layout; every
    # shard's node rows must belong to ONE region, and every region's
    # shards must sit on devices of ONE dcn group.
    dcn_of_device = {}
    for d in range(mesh.devices.shape[0]):
        for j in range(mesh.devices.shape[1]):
            dcn_of_device[mesh.devices[d, j]] = d
    region_size = N // N_REGIONS
    regions_per_dcn = N_REGIONS // mesh.devices.shape[0]
    dcn_groups_of_region: dict[int, set[int]] = {}
    for shard in final_s.data.contig.addressable_shards:
        rows = range(*shard.index[0].indices(N))
        row_regions = {r // region_size for r in rows}
        assert len(row_regions) == 1, "a shard must not straddle regions"
        (region,) = row_regions
        dcn_groups_of_region.setdefault(region, set()).add(
            dcn_of_device[shard.device]
        )
    for region, groups in dcn_groups_of_region.items():
        assert groups == {region // regions_per_dcn}, (
            f"region {region} scattered across dcn groups {groups}"
        )


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_state_is_actually_distributed():
    cfg, topo, sched = models.wan_100k(
        n=N, n_regions=N_REGIONS, n_writers=16, rounds=4, samples=8
    )
    mesh = parallel.make_mesh(8)
    state0 = engine.init_cluster(cfg, len(sched.sample_writer))
    state0 = parallel.shard_cluster_state(state0, mesh)
    # contig is node-major: each device holds an 8-row slice, not a replica.
    sharding = state0.data.contig.sharding
    assert len(sharding.device_set) == 8
    shard_shapes = {s.data.shape for s in state0.data.contig.addressable_shards}
    assert shard_shapes == {(8, 16)}
    # The cell plane shards on the flat node-major axis too.
    cell_shards = {s.data.shape for s in state0.data.cells.cl.addressable_shards}
    assert cell_shards == {(64 * 256 // 8,)}


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
@pytest.mark.slow  # tier-1 budget; the multichip CI job runs this file unfiltered
def test_sparse_plane_sharded_bit_identical():
    """The round-5 sparse writer plane (rotation + deviation tables +
    cold sync) under the node-sharded mesh placement: bit-identical to
    the unsharded run, including a forced-demotion scenario so the
    deviation machinery runs sharded too."""
    from corrosion_tpu.models.baselines import anywrite_sparse
    from corrosion_tpu.sim import sparse_engine

    cfg, topo, sched = anywrite_sparse(
        n=64, w_hot=8, rounds=48, n_regions=4, epoch_rounds=8,
        cohort=4, burst_writes=2, samples=32, k_dev=16, partition=True,
    )
    final_u = sparse_engine.simulate_sparse(cfg, topo, sched, seed=2)

    mesh = parallel.make_mesh(8)
    resume = sparse_engine.initial_resume(cfg, len(sched.sample_writer))
    resume["sstate"] = parallel.shard_sparse_state(resume["sstate"], mesh)
    resume["swim"] = jax.tree.map(
        lambda x: jax.device_put(
            x,
            jax.sharding.NamedSharding(
                mesh,
                jax.sharding.PartitionSpec(
                    "nodes", *([None] * (x.ndim - 1))
                ),
            ),
        ),
        resume["swim"],
    )
    topo_s = parallel.shard_topology(topo, mesh)
    final_s = sparse_engine.simulate_sparse(
        cfg, topo_s, sched, seed=2, resume=resume
    )
    for name in ("head", "contig", "seen"):
        np.testing.assert_array_equal(
            np.asarray(getattr(final_u[0].data, name)),
            np.asarray(getattr(final_s[0].data, name)),
            err_msg=name,
        )
    np.testing.assert_array_equal(
        np.asarray(final_u[0].head_full), np.asarray(final_s[0].head_full)
    )
    np.testing.assert_array_equal(
        np.asarray(final_u[0].dev_writer), np.asarray(final_s[0].dev_writer)
    )
    for name in ("cl", "col_version", "value_rank"):
        np.testing.assert_array_equal(
            np.asarray(getattr(final_u[0].data.cells, name)),
            np.asarray(getattr(final_s[0].data.cells, name)),
            err_msg=f"cells.{name}",
        )
    np.testing.assert_array_equal(
        np.asarray(final_u[2]), np.asarray(final_s[2])
    )
