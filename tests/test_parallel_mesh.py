"""Sharded-vs-unsharded equivalence over the virtual 8-device CPU mesh.

The dryrun only proves the sharded path compiles and runs; this asserts the
placements in corrosion_tpu/parallel/mesh.py do not change semantics: the
same seed produces bit-identical final state sharded and unsharded (all
state is integer, so every reduction is order-independent).
"""

import jax
import numpy as np
import pytest

from corrosion_tpu import models, parallel
from corrosion_tpu.sim import engine, simulate


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_run_is_bit_identical():
    cfg, topo, sched = models.wan_100k(
        n=64, n_regions=4, n_writers=16, rounds=24, samples=16
    )
    sched.writes[:8, :] = 1
    sched = sched.make_samples(16)

    final_u, curves_u = simulate(cfg, topo, sched, seed=5)

    mesh = parallel.make_mesh(8)
    topo_s = parallel.shard_topology(topo, mesh)
    state0 = engine.init_cluster(cfg, len(sched.sample_writer))
    state0 = parallel.shard_cluster_state(state0, mesh)
    final_s, curves_s = simulate(cfg, topo_s, sched, seed=5, state=state0)

    for name in ("head", "contig", "seen", "q_writer", "q_ver", "q_tx"):
        np.testing.assert_array_equal(
            np.asarray(getattr(final_u.data, name)),
            np.asarray(getattr(final_s.data, name)),
            err_msg=name,
        )
    for name in ("cl", "col_version", "value_rank"):
        np.testing.assert_array_equal(
            np.asarray(getattr(final_u.data.cells, name)),
            np.asarray(getattr(final_s.data.cells, name)),
            err_msg=f"cells.{name}",
        )
    # wan_100k uses the sparse SWIM kernel; compare every membership leaf.
    for u_leaf, s_leaf in zip(
        jax.tree.leaves(final_u.swim), jax.tree.leaves(final_s.swim)
    ):
        np.testing.assert_array_equal(np.asarray(u_leaf), np.asarray(s_leaf))
    np.testing.assert_array_equal(
        np.asarray(final_u.vis_round), np.asarray(final_s.vis_round)
    )
    for k in curves_u:
        np.testing.assert_array_equal(curves_u[k], curves_s[k], err_msg=k)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_state_is_actually_distributed():
    cfg, topo, sched = models.wan_100k(
        n=64, n_regions=4, n_writers=16, rounds=4, samples=8
    )
    mesh = parallel.make_mesh(8)
    state0 = engine.init_cluster(cfg, len(sched.sample_writer))
    state0 = parallel.shard_cluster_state(state0, mesh)
    # contig is node-major: each device holds an 8-row slice, not a replica.
    sharding = state0.data.contig.sharding
    assert len(sharding.device_set) == 8
    shard_shapes = {s.data.shape for s in state0.data.contig.addressable_shards}
    assert shard_shapes == {(8, 16)}
    # The cell plane shards on the flat node-major axis too.
    cell_shards = {s.data.shape for s in state0.data.cells.cl.addressable_shards}
    assert cell_shards == {(64 * 256 // 8,)}
