"""Broadcast/sync hot-path overhaul: equivalence + safety pins.

Five families of guarantees from the perf passes (docs/PERFORMANCE.md):

1. **Batched anti-entropy pipeline**: the single tiled [R, C, W]
   candidate-scoring gather and the [R, S+1, W] union-pull are
   bit-identical to the original per-candidate/per-peer Python loops —
   peer selection AND post-sync DataState — in exact and digest scoring
   modes, on the cohort and non-cohort sync_round paths.
2. **Backend-native one-hot primitives**: the CPU scatter/gather forms
   of ops/onehot.py equal the dense one-hot forms bit-for-bit, at the
   primitive level (including out-of-range index handling) and through
   whole gossip rounds.
3. **Pallas kernel branch**: every kernel of the third backend — the
   per-primitive VMEM kernels, the fused delivery reductions
   (``delivery_reduce``), the fused window admission
   (``window_delivery``), and the native-u32 gathers
   (``rowgather_wide``, ``table_gather_u32``) — is bit-identical to the
   native and dense references under ``pallas_call(..., interpret=True)``
   on CPU, at the primitive level and through whole broadcast+sync
   rounds, in exact AND digest scoring modes, on the cohort and
   non-cohort sync paths.
4. **Digest quantization**: the int8/bf16 sync-scoring digest ranks
   candidate peers identically to the u32 digest below the saturation
   threshold (where the quantizer is provably the identity), and
   run-level selection/state are unchanged in that regime.
5. **Donation safety**: donated round/scan entry points return
   bit-identical results, actually release the donated input buffers,
   never read a donated buffer after the call in any engine driver, and
   keep the per-function compile-cache count at <= 1 (the CT031 retrace
   tripwire's invariant).

Plus the bench-report invariants (step_inner_ms <= step_ms;
sum(plane_ms) + residual == step_ms; provenance fields present) and the
bench-smoke budget gate with its platform/kernels shape checks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.ops import gossip, onehot
from corrosion_tpu.sim import telemetry
from corrosion_tpu.sim import benchlib


def mk(n, regions=None, writers=None, cohorts=False, **kw):
    regions = regions or [n]
    writers = writers if writers is not None else list(range(n))
    cfg = gossip.GossipConfig(n_nodes=n, n_writers=len(writers), **kw)
    topo = gossip.make_topology(
        regions, writers,
        sync_interval=cfg.sync_interval if cohorts else None,
    )
    return cfg, topo, gossip.init_data(cfg)


def run_rounds(cfg, topo, data, rounds, writes_fn=None, seed=0):
    """Broadcast+sync stepping loop; returns (final DataState, stats)."""
    n = cfg.n_nodes
    alive = jnp.ones(n, bool)
    part = jnp.zeros((int(jnp.max(topo.region)) + 1,) * 2, bool)
    key = jax.random.PRNGKey(seed)
    stats = []
    for r in range(rounds):
        key, k1, k2 = jax.random.split(key, 3)
        w = (
            writes_fn(r) if writes_fn
            else jnp.zeros(cfg.n_writers, jnp.uint32)
        )
        data, b = gossip.broadcast_round(data, topo, alive, part, w, k1, cfg)
        data, s = gossip.sync_round(
            data, topo, alive, part, jnp.int32(r), k2, cfg
        )
        stats.append((b, s))
    return data, stats


def assert_states_equal(a, b, msg=""):
    for name in a._fields:
        fa, fb = getattr(a, name), getattr(b, name)
        if name == "cells":
            for cn in fa._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(fa, cn)),
                    np.asarray(getattr(fb, cn)),
                    err_msg=f"{msg} cells.{cn}",
                )
        else:
            np.testing.assert_array_equal(
                np.asarray(fa), np.asarray(fb), err_msg=f"{msg} {name}"
            )


def _clear_round_caches():
    gossip.sync_round.clear_cache()
    gossip.sync_round_donated.clear_cache()
    gossip.broadcast_round.clear_cache()
    gossip.broadcast_round_donated.clear_cache()


def _clear_sync_caches():
    # The scoring flags (_BATCHED_SYNC/_EXACT_SCORE_MAX) only reach
    # sync_round's trace: broadcast stays cached across flips, which
    # keeps this module's wall time compile-light.
    gossip.sync_round.clear_cache()
    gossip.sync_round_donated.clear_cache()


# ---------------------------------------------------------------------------
# 1. Batched anti-entropy scoring/grants vs the looped reference


def _one_sync_run(cohorts, seed=0):
    cfg, topo, data = mk(
        24, regions=[6, 6, 6, 6], sync_interval=3, sync_budget=48,
        sync_chunk=8, sync_peers=3, sync_candidates=6, n_cells=32,
        cells_per_write=2, cohorts=cohorts,
    )
    w = jnp.zeros(24, jnp.uint32).at[3].set(2).at[17].set(1).at[9].set(3)
    data, stats = run_rounds(
        cfg, topo, data, 14,
        writes_fn=lambda r: w if r < 5 else jnp.zeros(24, jnp.uint32),
        seed=seed,
    )
    return data, stats


@pytest.mark.parametrize("cohorts", [False, True], ids=["phase", "cohort"])
@pytest.mark.parametrize("digest", [False, True], ids=["exact", "digest"])
def test_batched_scoring_bit_identical_to_looped(cohorts, digest):
    """Batched candidate scoring + grants == the looped reference:
    identical post-sync DataState (hence identical peer selection — a
    different selection changes what is granted) and identical per-round
    applied_sync/sessions stats, in both scoring modes, on both
    sync_round paths."""
    old_exact = gossip._EXACT_SCORE_MAX
    if digest:
        gossip._EXACT_SCORE_MAX = 0  # force the total-progress digest
    try:
        assert gossip._BATCHED_SYNC is True  # default under test
        _clear_sync_caches()
        batched, stats_b = _one_sync_run(cohorts)
        gossip._BATCHED_SYNC = False
        _clear_sync_caches()
        looped, stats_l = _one_sync_run(cohorts)
    finally:
        gossip._BATCHED_SYNC = True
        gossip._EXACT_SCORE_MAX = old_exact
        _clear_sync_caches()
    assert_states_equal(batched, looped, msg=f"cohorts={cohorts}")
    for r, ((_, sb), (_, sl)) in enumerate(zip(stats_b, stats_l)):
        for k in ("applied_sync", "sessions", "cell_merges"):
            assert int(sb[k]) == int(sl[k]), f"round {r} stat {k}"


def test_batched_scoring_converges_with_digest_mode():
    """Digest-mode selection still heals the cluster (the heuristic only
    affects which peers are pulled; grants recompute the real deficit)."""
    old_exact = gossip._EXACT_SCORE_MAX
    gossip._EXACT_SCORE_MAX = 0
    try:
        _clear_sync_caches()
        data, _ = _one_sync_run(cohorts=True)
    finally:
        gossip._EXACT_SCORE_MAX = old_exact
        _clear_sync_caches()
    heads = np.asarray(data.head)
    assert (np.asarray(data.contig) == heads[None, :]).all()


# ---------------------------------------------------------------------------
# 2. Native scatter/gather vs dense one-hot primitives


def _both_paths(fn):
    """Evaluate ``fn()`` under the native and dense onehot paths."""
    old = onehot._NATIVE_SCATTER
    try:
        onehot._NATIVE_SCATTER = True
        native = fn()
        onehot._NATIVE_SCATTER = False
        dense = fn()
    finally:
        onehot._NATIVE_SCATTER = old
    return native, dense


def test_onehot_primitives_native_equals_dense():
    k = jax.random.PRNGKey(0)
    r, m, w = 17, 23, 41
    # Indices deliberately include out-of-range values on both sides;
    # both paths must treat them as contributing nothing / yielding 0.
    idx = jax.random.randint(k, (r, m), -3, w + 4)
    val = jax.random.randint(
        jax.random.fold_in(k, 1), (r, m), 0, 1 << 30
    ).astype(jnp.uint32)
    mask = jax.random.bernoulli(jax.random.fold_in(k, 2), 0.7, (r, m))
    table = jax.random.randint(
        jax.random.fold_in(k, 3), (r, w), 0, 1 << 30
    ).astype(jnp.uint32)
    idx_in = jnp.clip(idx, 0, w - 1)

    for name, fn in {
        "rowmax": lambda: onehot.rowmax(idx, val, mask, w),
        "rowmax_nomask": lambda: onehot.rowmax(idx, val, None, w),
        "rowsum": lambda: onehot.rowsum(idx, val, mask, w),
        "rowgather": lambda: onehot.rowgather(table, idx),
        "rowgather_wide": lambda: onehot.rowgather_wide(table, idx_in),
        "table_gather": lambda: onehot.table_gather_u32(
            table[0], idx_in
        ),
    }.items():
        native, dense = _both_paths(fn)
        np.testing.assert_array_equal(
            np.asarray(native), np.asarray(dense), err_msg=name
        )


def test_gossip_rounds_native_equals_dense():
    """Whole broadcast+sync rounds (delivery, window, CRDT merge, grant
    enumeration, visibility) are bit-identical across the backend-native
    and dense one-hot paths."""

    def one():
        _clear_round_caches()
        cfg, topo, data = mk(
            24, regions=[8, 8, 8], sync_interval=3, n_cells=32,
            cells_per_write=2, loss_prob=0.2, cohorts=True,
        )
        w = jnp.zeros(24, jnp.uint32).at[5].set(3).at[20].set(2)
        data, _ = run_rounds(
            cfg, topo, data, 12,
            writes_fn=lambda r: w if r < 4 else jnp.zeros(24, jnp.uint32),
        )
        sw = jnp.asarray([5, 20], jnp.int32)
        sv = jnp.asarray([2, 1], jnp.uint32)
        vis = gossip.visibility(data, sw, sv)
        return data, np.asarray(vis)

    (d_nat, v_nat), (d_den, v_den) = _both_paths(one)
    _clear_round_caches()
    assert_states_equal(d_nat, d_den, msg="native vs dense")
    np.testing.assert_array_equal(v_nat, v_den)


# ---------------------------------------------------------------------------
# 2b. Pallas kernel branch: interpret-mode bit-equality vs native + dense


def _all_backends(fn):
    """Evaluate ``fn()`` once per onehot backend; returns {backend: out}.
    Off-TPU the pallas branch runs under interpret=True — identical
    kernel math, no Mosaic."""
    old = onehot._BACKEND
    out = {}
    try:
        for bk in onehot.BACKENDS:
            onehot._BACKEND = bk
            out[bk] = fn()
    finally:
        onehot._BACKEND = old
    return out


def _assert_backends_equal(outs, msg=""):
    ref = outs["native"]
    for bk in ("dense", "pallas"):
        np.testing.assert_array_equal(
            np.asarray(ref), np.asarray(outs[bk]), err_msg=f"{msg} {bk}"
        )


def test_pallas_primitives_bit_equal_interpret():
    """Every per-primitive pallas kernel == native == dense, including
    out-of-range/masked index handling, under interpret mode on CPU."""
    k = jax.random.PRNGKey(0)
    r, m, w = 17, 23, 41
    idx = jax.random.randint(k, (r, m), -3, w + 4)
    val = jax.random.randint(
        jax.random.fold_in(k, 1), (r, m), 0, 1 << 30
    ).astype(jnp.uint32)
    mask = jax.random.bernoulli(jax.random.fold_in(k, 2), 0.7, (r, m))
    table = jax.random.randint(
        jax.random.fold_in(k, 3), (r, w), 0, 1 << 30
    ).astype(jnp.uint32)
    idx_in = jnp.clip(idx, 0, w - 1)
    for name, fn in {
        "rowmax": lambda: onehot.rowmax(idx, val, mask, w),
        "rowmax_nomask": lambda: onehot.rowmax(idx, val, None, w),
        "rowsum": lambda: onehot.rowsum(idx, val, mask, w),
        "rowgather": lambda: onehot.rowgather(table, idx),
        "rowgather_wide": lambda: onehot.rowgather_wide(table, idx_in),
        "table_gather": lambda: onehot.table_gather_u32(
            table[0], idx_in
        ),
    }.items():
        _assert_backends_equal(_all_backends(fn), msg=name)


def test_fused_delivery_reduce_bit_equal():
    """The fused (advance, seen') kernel == the two-rowmax reference on
    every backend."""
    k = jax.random.PRNGKey(4)
    r, m, w = 19, 29, 37
    idx = jax.random.randint(k, (r, m), 0, w)
    d = jax.random.randint(
        jax.random.fold_in(k, 1), (r, m), 0, 60
    ).astype(jnp.uint32)
    v = d + jax.random.randint(
        jax.random.fold_in(k, 2), (r, m), 0, 1 << 20
    ).astype(jnp.uint32)
    valid = jax.random.bernoulli(jax.random.fold_in(k, 3), 0.8, (r, m))
    applied = valid & jax.random.bernoulli(
        jax.random.fold_in(k, 4), 0.6, (r, m)
    )
    seen = jax.random.randint(
        jax.random.fold_in(k, 5), (r, w), 0, 1 << 30
    ).astype(jnp.uint32)
    outs = _all_backends(
        lambda: onehot.delivery_reduce(idx, d, v, applied, valid, seen, w)
    )
    for part, pname in ((0, "adv"), (1, "seen")):
        _assert_backends_equal(
            {bk: o[part] for bk, o in outs.items()}, msg=pname
        )


def test_fused_window_delivery_bit_equal():
    """The fused window-admission kernel (gather + old-bit check + bit
    assembly in one VMEM pass) == the rowgather/rowsum reference, for
    1- and 2-word windows."""
    k = jax.random.PRNGKey(9)
    r, m, w = 13, 21, 33
    idx = jax.random.randint(k, (r, m), 0, w)
    valid = jax.random.bernoulli(jax.random.fold_in(k, 1), 0.7, (r, m))
    adv_m = jax.random.randint(
        jax.random.fold_in(k, 2), (r, m), 0, 10
    ).astype(jnp.uint32)
    for b_words, wk in ((1, 32), (2, 64)):
        d = jax.random.randint(
            jax.random.fold_in(k, 3 + b_words), (r, m), 0, wk + 16
        ).astype(jnp.uint32)
        oo = jax.random.randint(
            jax.random.fold_in(k, 5 + b_words), (b_words, r, w),
            0, 1 << 30,
        ).astype(jnp.uint32)
        outs = _all_backends(
            lambda: onehot.window_delivery(oo, idx, d, adv_m, valid, wk, w)
        )
        _assert_backends_equal(
            {bk: o[0] for bk, o in outs.items()}, msg=f"poss B={b_words}"
        )
        _assert_backends_equal(
            {bk: o[1] for bk, o in outs.items()}, msg=f"words B={b_words}"
        )


@pytest.mark.slow  # ~75 s of backend recompiles: runs in the bench-smoke
# CI kernel suite, outside the tier-1 870 s budget.
def test_window_admit_lambda_path_equals_window_delivery():
    """The admission math exists in two places — gossip._window_admit's
    legacy-lambda branch and onehot.window_delivery's reference branch.
    Pin them equal on identical inputs (same gather/assemble semantics)
    so a future edit to one copy cannot silently diverge the fast and
    legacy delivery paths."""
    k = jax.random.PRNGKey(21)
    r, m, w, wk = 11, 17, 29, 64
    idx = jax.random.randint(k, (r, m), 0, w)
    valid = jax.random.bernoulli(jax.random.fold_in(k, 1), 0.7, (r, m))
    adv_m = jax.random.randint(
        jax.random.fold_in(k, 2), (r, m), 0, 9
    ).astype(jnp.uint32)
    d = jax.random.randint(
        jax.random.fold_in(k, 3), (r, m), 0, wk + 12
    ).astype(jnp.uint32)
    # High bit deliberately set on some window words: the gather must
    # preserve u32 ordering (the Mosaic flip trick in the kernels).
    oo = jax.random.randint(
        jax.random.fold_in(k, 4), (2, r, w), 0, 1 << 30
    ).astype(jnp.uint32) | (jnp.uint32(1) << 31)
    contig_pre = jax.random.randint(
        jax.random.fold_in(k, 5), (r, w), 0, 1000
    ).astype(jnp.uint32)
    adv = jax.random.randint(
        jax.random.fold_in(k, 6), (r, w), 0, 5
    ).astype(jnp.uint32)
    via_lambdas = gossip._window_admit(
        oo, contig_pre, adv, adv_m, d, valid, wk,
        gather_word=lambda word: onehot.rowgather(word, idx),
        assemble_word=lambda contrib: onehot.rowsum(
            idx, contrib, None, w
        ),
    )
    via_fast = gossip._window_admit(
        oo, contig_pre, adv, adv_m, d, valid, wk,
        fast_idx=idx, width=w,
    )
    for xa, xb, name in zip(
        via_lambdas, via_fast, ("contig", "oo", "new_poss")
    ):
        np.testing.assert_array_equal(
            np.asarray(xa), np.asarray(xb), err_msg=name
        )


# One representative param stays in tier-1 as the round-level pin; the
# other three combinations are slow-marked (~20 s of backend recompiles
# each) and run in the bench-smoke CI kernel suite, outside the tier-1
# 870 s budget.
@pytest.mark.parametrize(
    "digest,cohorts",
    [
        pytest.param(False, True, id="exact-cohort"),
        pytest.param(
            False, False, id="exact-phase", marks=pytest.mark.slow
        ),
        pytest.param(
            True, True, id="digest-cohort", marks=pytest.mark.slow
        ),
        pytest.param(
            True, False, id="digest-phase", marks=pytest.mark.slow
        ),
    ],
)
def test_gossip_rounds_pallas_equals_native(cohorts, digest):
    """Whole broadcast+sync rounds (fused delivery chain, window
    admission, CRDT merge, grant enumeration, visibility) are
    bit-identical across all three backends, in exact and digest
    scoring modes, on the cohort and non-cohort sync paths — the
    tentpole acceptance pin for the pallas branch."""
    old_exact = gossip._EXACT_SCORE_MAX
    if digest:
        gossip._EXACT_SCORE_MAX = 0

    def one():
        _clear_round_caches()
        cfg, topo, data = mk(
            16, regions=[8, 8], sync_interval=3, n_cells=16,
            cells_per_write=1, loss_prob=0.25, cohorts=cohorts,
        )
        w = jnp.zeros(16, jnp.uint32).at[3].set(3).at[12].set(2)
        data, _ = run_rounds(
            cfg, topo, data, 8,
            writes_fn=lambda r: w if r < 3 else jnp.zeros(16, jnp.uint32),
        )
        vis = gossip.visibility(
            data, jnp.asarray([3, 12], jnp.int32),
            jnp.asarray([2, 1], jnp.uint32),
        )
        return data, np.asarray(vis)

    try:
        outs = _all_backends(one)
    finally:
        gossip._EXACT_SCORE_MAX = old_exact
        _clear_round_caches()
    for bk in ("dense", "pallas"):
        assert_states_equal(
            outs["native"][0], outs[bk][0], msg=f"native vs {bk}"
        )
        np.testing.assert_array_equal(
            outs["native"][1], outs[bk][1], err_msg=f"vis {bk}"
        )


@pytest.mark.slow  # full engine-scan compile under interpret mode: runs
# in the bench-smoke CI kernel suite, outside the tier-1 870 s budget.
def test_config_kernel_backend_plumbs_through_engine():
    """GossipConfig.kernel_backend reaches every delivery/sync/visibility
    primitive through the engine drivers: a pallas-backend simulate() is
    bit-identical to the auto (native-on-CPU) run."""
    import dataclasses

    from corrosion_tpu.sim.engine import simulate

    cfg, topo, sched = _tiny_cluster(rounds=9)
    final_a, curves_a = simulate(cfg, topo, sched, seed=0, max_chunk=3)
    cfg_p = dataclasses.replace(
        cfg, gossip=dataclasses.replace(cfg.gossip, kernel_backend="pallas")
    )
    final_b, curves_b = simulate(cfg_p, topo, sched, seed=0, max_chunk=3)
    assert_states_equal(final_a.data, final_b.data, msg="pallas engine")
    np.testing.assert_array_equal(
        np.asarray(final_a.vis_round), np.asarray(final_b.vis_round)
    )
    for k in curves_a:
        np.testing.assert_array_equal(curves_a[k], curves_b[k], err_msg=k)


def test_kernel_backend_validated():
    with pytest.raises(ValueError, match="kernel_backend"):
        gossip.GossipConfig(n_nodes=4, n_writers=4, kernel_backend="mxu")
    with pytest.raises(ValueError, match="backend"):
        onehot.resolve_backend("mxu")


# ---------------------------------------------------------------------------
# 2c. Digest quantization: rank preservation


def test_digest_quantization_rank_property():
    """Property: below saturation the quantized digest is the identity
    on the u32 deficit, so the packed need/ring score ranks candidates
    IDENTICALLY to the unclamped u32 path; at/above saturation the
    quantized digest equals the saturating clamp in u32 (ties decided by
    the ring term, deterministically) — for both u8 and bf16, across
    random deficit tensors straddling the threshold. Quantization only
    engages while sync_budget <= the dtype's saturation point (the
    provably-harmless regime); bigger budgets pass through as u32."""
    old = gossip._DIGEST_QUANT
    key = jax.random.PRNGKey(11)
    budget = 128  # <= every saturation point: quantization engages
    try:
        for mode, sat in (("u8", 255), ("bf16", 256)):
            for lo, hi in ((0, sat), (0, 4 * sat), (sat, 8 * sat)):
                key, k1 = jax.random.split(key)
                defc = jax.random.randint(
                    k1, (7, 9), lo, hi
                ).astype(jnp.uint32)
                gossip._DIGEST_QUANT = mode
                got = np.asarray(gossip._digest_score(defc, budget))
                clamped = np.minimum(np.asarray(defc), sat).astype(
                    np.int32
                )
                # The quantized score IS the saturating clamp, exactly.
                np.testing.assert_array_equal(got, clamped, err_msg=mode)
                # A budget past the saturation point must NOT quantize:
                # ranking among deep deficits still changes what a
                # session can drain there.
                np.testing.assert_array_equal(
                    np.asarray(gossip._digest_score(defc, sat + 1)),
                    np.asarray(defc).astype(np.int32),
                    err_msg=f"{mode} budget>{sat} passthrough",
                )
                if hi <= sat:
                    # Sub-saturation: identity on the u32 deficit ->
                    # identical packed-score ranking, provably.
                    gossip._DIGEST_QUANT = None
                    raw = np.asarray(gossip._digest_score(defc, budget))
                    np.testing.assert_array_equal(got, raw)
                    ring = np.asarray(
                        jax.random.randint(k1, (7, 9), 0, 6)
                    )
                    np.testing.assert_array_equal(
                        np.argsort(-(got * 8 + (5 - ring)), axis=1,
                                   kind="stable"),
                        np.argsort(-(raw * 8 + (5 - ring)), axis=1,
                                   kind="stable"),
                    )
    finally:
        gossip._DIGEST_QUANT = old


@pytest.mark.parametrize(
    "mode",
    ["u8", pytest.param("bf16", marks=pytest.mark.slow)],
)
def test_digest_quant_run_level_rank_identical(mode):
    """Across the exact<->digest threshold: with every deficit below the
    saturation bound (deficits here are tens of versions), digest-mode
    runs under the quantized digest select the same peers and land the
    same post-sync state as the unclamped u32 digest."""
    old_q, old_exact = gossip._DIGEST_QUANT, gossip._EXACT_SCORE_MAX
    gossip._EXACT_SCORE_MAX = 0  # force the digest side of the threshold
    try:
        gossip._DIGEST_QUANT = None
        _clear_sync_caches()
        ref, stats_r = _one_sync_run(cohorts=True)
        gossip._DIGEST_QUANT = mode
        _clear_sync_caches()
        got, stats_g = _one_sync_run(cohorts=True)
    finally:
        gossip._DIGEST_QUANT = old_q
        gossip._EXACT_SCORE_MAX = old_exact
        _clear_sync_caches()
    assert_states_equal(ref, got, msg=f"digest quant {mode}")
    for r, ((_, sr), (_, sg)) in enumerate(zip(stats_r, stats_g)):
        for k in ("applied_sync", "sessions"):
            assert int(sr[k]) == int(sg[k]), f"round {r} stat {k}"


# ---------------------------------------------------------------------------
# 2c'. Bucketed set-reconciliation sketch (cfg.sync_sketch_buckets)


def _sketch_sync_run(buckets, cohorts=True, seed=0):
    """The _one_sync_run scenario with the sketch scorer armed (a
    static config field, so each bucket count is its own trace)."""
    cfg, topo, data = mk(
        24, regions=[6, 6, 6, 6], sync_interval=3, sync_budget=48,
        sync_chunk=8, sync_peers=3, sync_candidates=6, n_cells=32,
        cells_per_write=2, cohorts=cohorts, sync_sketch_buckets=buckets,
    )
    w = jnp.zeros(24, jnp.uint32).at[3].set(2).at[17].set(1).at[9].set(3)
    data, stats = run_rounds(
        cfg, topo, data, 14,
        writes_fn=lambda r: w if r < 5 else jnp.zeros(24, jnp.uint32),
        seed=seed,
    )
    return data, stats


def test_bucket_sketch_bounds_and_dominance_property():
    """Property (the sketch's correctness contract, extending the
    digest rank family): on random progress tables the unquantized
    per-bucket one-sided deficit is sandwiched between the scalar
    total-progress digest (B=1, exactly) and the exact per-writer
    deficit — a strictly tighter lower bound as B grows — and EQUALS
    the exact deficit whenever the candidate dominates per writer, so
    ranking among genuinely-ahead candidates is preserved at any B."""
    key = jax.random.PRNGKey(7)
    w = 37  # no bucket count divides it: the padding path is covered
    budget = 1 << 20  # above every saturation point: no quantization
    for _ in range(4):
        key, k1, k2, k3 = jax.random.split(key, 4)
        self_c = jax.random.randint(k1, (1, w), 0, 50).astype(jnp.uint32)
        cands = jax.random.randint(k2, (9, w), 0, 50).astype(jnp.uint32)
        exact = np.sum(
            np.maximum(
                np.asarray(cands, np.int64)
                - np.asarray(self_c, np.int64),
                0,
            ),
            axis=1,
        )
        scalar = np.maximum(
            np.asarray(cands, np.int64).sum(axis=1)
            - np.asarray(self_c, np.int64).sum(axis=1),
            0,
        )
        for b in (1, 4, 8, 16):
            got = np.asarray(
                gossip._sketch_score(
                    gossip.bucket_sketch(cands, b),
                    gossip.bucket_sketch(self_c, b),
                    budget,
                ),
                np.int64,
            )
            assert (got >= scalar).all(), b
            assert (got <= exact).all(), b
            if b == 1:
                np.testing.assert_array_equal(got, scalar)
        # Per-writer dominating candidates: bucket sums telescope with
        # no cancellation, so the sketch equals the exact deficit at
        # every bucket count.
        dom = self_c + jax.random.randint(k3, (9, w), 0, 20).astype(
            jnp.uint32
        )
        exact_dom = np.sum(
            np.asarray(dom, np.int64) - np.asarray(self_c, np.int64),
            axis=1,
        )
        for b in (1, 4, 8, 16):
            np.testing.assert_array_equal(
                np.asarray(
                    gossip._sketch_score(
                        gossip.bucket_sketch(dom, b),
                        gossip.bucket_sketch(self_c, b),
                        budget,
                    ),
                    np.int64,
                ),
                exact_dom,
                err_msg=f"dominance B={b}",
            )


def test_bucket_sketch_quantizes_per_bucket():
    """The sketch rides the SAME saturating u8/bf16 quantization path
    as the scalar digest, applied per bucket: with quantization engaged
    (budget <= saturation) the score is the sum of per-bucket clamps;
    with a budget past the saturation point it passes through as the
    unclamped u32 sum (the digest gate, bucket-wise)."""
    old = gossip._DIGEST_QUANT
    contig = jnp.asarray(
        [[700, 0, 0, 0], [100, 100, 100, 100], [0, 0, 0, 0]], jnp.uint32
    )
    sk_self = gossip.bucket_sketch(contig[2:], 2)  # zeros
    skc = gossip.bucket_sketch(contig[:2], 2)  # [[700, 0], [200, 200]]
    try:
        gossip._DIGEST_QUANT = "bf16"  # saturation point 256
        got = np.asarray(gossip._sketch_score(skc, sk_self, 128))
        np.testing.assert_array_equal(got, [256, 200 + 200])
        got = np.asarray(gossip._sketch_score(skc, sk_self, 257))
        np.testing.assert_array_equal(got, [700, 400])
    finally:
        gossip._DIGEST_QUANT = old


def test_sketch_b1_run_level_bit_identical_to_scalar_digest():
    """B=1 degenerates to the legacy scalar digest, run level: forced
    into digest-scoring territory, a sync_sketch_buckets=1 run lands
    the bit-identical post-sync state and per-round stats as the
    legacy total-progress digest run."""
    old_exact = gossip._EXACT_SCORE_MAX
    gossip._EXACT_SCORE_MAX = 0  # force the digest/sketch scorer
    try:
        _clear_sync_caches()
        ref, stats_r = _sketch_sync_run(0)
        got, stats_g = _sketch_sync_run(1)
    finally:
        gossip._EXACT_SCORE_MAX = old_exact
        _clear_sync_caches()
    assert_states_equal(ref, got, msg="sketch B=1 vs scalar digest")
    for r, ((_, sr), (_, sg)) in enumerate(zip(stats_r, stats_g)):
        for k in ("applied_sync", "sessions", "cell_merges"):
            assert int(sr[k]) == int(sg[k]), f"round {r} stat {k}"


@pytest.mark.parametrize("batched", [True, False], ids=["batched", "looped"])
def test_sketch_mode_converges(batched):
    """Sketch-mode selection still heals the cluster (grants recompute
    the exact deficit; the sketch only picks peers), on both the
    batched and the looped reference scoring pipelines."""
    old_exact = gossip._EXACT_SCORE_MAX
    gossip._EXACT_SCORE_MAX = 0
    try:
        gossip._BATCHED_SYNC = batched
        _clear_sync_caches()
        data, stats = _sketch_sync_run(8)
    finally:
        gossip._BATCHED_SYNC = True
        gossip._EXACT_SCORE_MAX = old_exact
        _clear_sync_caches()
    heads = np.asarray(data.head)
    assert (np.asarray(data.contig) == heads[None, :]).all()


def test_sketch_batched_bit_identical_to_looped():
    """The batched [R, C, B] sketch gather == the per-candidate looped
    reference, post-sync state and stats (max/sum over candidates
    commute bucket-wise exactly as they do for the scalar digest)."""
    old_exact = gossip._EXACT_SCORE_MAX
    gossip._EXACT_SCORE_MAX = 0
    try:
        assert gossip._BATCHED_SYNC is True
        _clear_sync_caches()
        ref, stats_r = _sketch_sync_run(8)
        gossip._BATCHED_SYNC = False
        _clear_sync_caches()
        got, stats_g = _sketch_sync_run(8)
    finally:
        gossip._BATCHED_SYNC = True
        gossip._EXACT_SCORE_MAX = old_exact
        _clear_sync_caches()
    assert_states_equal(ref, got, msg="sketch batched vs looped")
    for r, ((_, sr), (_, sg)) in enumerate(zip(stats_r, stats_g)):
        for k in ("applied_sync", "sessions", "cell_merges"):
            assert int(sr[k]) == int(sg[k]), f"round {r} stat {k}"


# ---------------------------------------------------------------------------
# 2d. window_degraded dedup in the windowless branches (ADVICE r5)


@pytest.mark.parametrize(
    "fresh", [True, False], ids=["fast_path", "legacy_path"]
)
def test_window_degraded_dedup_windowless(fresh):
    """window_k=0: same-round duplicate copies of one (writer, version)
    degrade a single version per receiver, not one per copy — the
    windowed branches' first-copy adjacency dedup applied to the
    windowless counters on both delivery paths."""
    cfg = gossip.GossipConfig(
        n_nodes=4, n_writers=1, window_k=0, queue=4,
        fanout_near=0, fanout_far=8,
        rebroadcast_fresh_budget=fresh, rebroadcast_stale=False,
    )
    topo = gossip.make_topology([4], [0])
    data = gossip.init_data(cfg)
    # Nodes 1..3 each hold a queued copy of (writer 0, v5); every
    # receiver lacks v1..4 so the arrival can never apply in-order.
    qw = np.full((4, 4), -1, np.int32)
    qv = np.zeros((4, 4), np.uint32)
    qt = np.zeros((4, 4), np.int32)
    for nidx in (1, 2, 3):
        qw[nidx, 0] = 0
        qv[nidx, 0] = 5
        qt[nidx, 0] = 6
    data = data._replace(
        head=jnp.asarray([5], jnp.uint32),
        q_writer=jnp.asarray(qw), q_ver=jnp.asarray(qv),
        q_tx=jnp.asarray(qt),
    )
    alive = jnp.ones(4, bool)
    part = jnp.zeros((1, 1), bool)
    _, stats = gossip.broadcast_round(
        data, topo, alive, part, jnp.zeros(1, jnp.uint32),
        jax.random.PRNGKey(3), cfg,
    )
    # With fanout_far=8 every receiver (nodes 1..3; node 0 is the writer
    # and holds everything) pulls several duplicate copies (17 messages
    # land in total at this seed) — but exactly ONE distinct version
    # degrades per receiver.
    assert int(stats["msgs"]) > 3  # duplicates definitely arrived
    assert int(stats["window_degraded"]) == 3


def test_window_degraded_dedup_sentinel_versions():
    """Far-sentinel arrivals (delta clamped beyond max(kk, wk)) share a
    sort key, so the dedup must distinguish DISTINCT versions via the
    carried version operand: v40 copies collapse, v40 vs v41 do not."""
    cfg = gossip.GossipConfig(
        n_nodes=4, n_writers=1, window_k=0, queue=4,
        fanout_near=0, fanout_far=8,
    )
    topo = gossip.make_topology([4], [0])
    data = gossip.init_data(cfg)
    qw = np.full((4, 4), -1, np.int32)
    qv = np.zeros((4, 4), np.uint32)
    qt = np.zeros((4, 4), np.int32)
    # kk = fanout*queue = 32, so deltas 40/41 clamp to the far sentinel.
    for nidx, ver in ((1, 40), (2, 40), (3, 41)):
        qw[nidx, 0] = 0
        qv[nidx, 0] = ver
        qt[nidx, 0] = 6
    data = data._replace(
        head=jnp.asarray([41], jnp.uint32),
        q_writer=jnp.asarray(qw), q_ver=jnp.asarray(qv),
        q_tx=jnp.asarray(qt),
    )
    alive = jnp.ones(4, bool)
    part = jnp.zeros((1, 1), bool)
    _, stats = gossip.broadcast_round(
        data, topo, alive, part, jnp.zeros(1, jnp.uint32),
        jax.random.PRNGKey(3), cfg,
    )
    # At this seed the receivers hear 4 distinct (receiver, version)
    # degradations across 17 delivered copies.
    assert int(stats["msgs"]) > 4
    assert int(stats["window_degraded"]) == 4


# ---------------------------------------------------------------------------
# 3. Donation safety


def _tiny_cluster(rounds=9):
    """A lean ClusterConfig (small cell plane, default queue) — the
    donation contract is config-independent, and the flagship builder's
    1024-cell trace would quadruple this module's compile wall. Chunk
    length 3 is shared by every donation test below so the scan compiles
    once for the whole module."""
    import numpy as np

    from corrosion_tpu.ops.swim import SwimConfig
    from corrosion_tpu.sim.engine import ClusterConfig, Schedule

    n = 24
    g = gossip.GossipConfig(
        n_nodes=n, n_writers=n, sync_interval=3, n_cells=16,
        cells_per_write=1,
    )
    s = SwimConfig(
        n_nodes=n, max_transmissions=4, suspect_rounds=3, gossip_fanout=3
    )
    topo = gossip.make_topology(
        [n // 2, n // 2], list(range(n)), sync_interval=g.sync_interval
    )
    writes = np.zeros((rounds, n), np.uint32)
    writes[:3, :4] = 2
    sched = Schedule(writes=writes).make_samples(16)
    return ClusterConfig(swim=s, gossip=g), topo, sched


def test_donation_keeps_compile_cache_count_at_one():
    """A uniformly-chunked run compiles one donated scan executable (the
    ownership copy makes chunk 1 donatable too): every jitted entry in
    the engine module holds <= 1 compile-cache entry — the CT031 retrace
    tripwire invariant, donation included."""
    from corrosion_tpu.obs import ledger as ledger_mod
    from corrosion_tpu.sim import engine as engine_mod
    from corrosion_tpu.sim.engine import simulate

    cfg, topo, sched = _tiny_cluster(rounds=9)
    jax.clear_caches()
    simulate(cfg, topo, sched, seed=0, max_chunk=3)
    # The shared watched-fn registry (obs/ledger.py) — the same
    # discovery the sanitize CT030 tripwire and the runtime compile
    # ledger use, so this pin can never watch a different set.
    sizes = ledger_mod.cache_sizes(ledger_mod.jitted_functions(engine_mod))
    for name, size in sizes.items():
        assert size <= 1, (
            f"engine.{name} holds {size} compile-cache "
            f"entries — donation must not add cache entries"
        )
    # The donated scan actually ran and compiled exactly once.
    assert engine_mod._scan_rounds_donated._cache_size() == 1


@pytest.mark.slow  # tier-1 budget; the bench-smoke CI kernel suite runs it (-k donated)
def test_donated_round_entry_points_bit_identical_and_released():
    """broadcast/sync/cluster_round donated twins: same results as the
    plain entries from an identical input, and the donated input's
    buffers are actually released (reading them afterwards raises)."""
    from corrosion_tpu.sim import engine as engine_mod
    from corrosion_tpu.sim.engine import init_cluster

    cfg, topo, sched = _tiny_cluster()
    n_regions = int(np.asarray(topo.region).max()) + 1
    part = jnp.zeros((n_regions, n_regions), bool)
    kill = jnp.zeros((1,), bool)
    writes = jnp.asarray(sched.writes[0], jnp.uint32)
    s_w = jnp.asarray(sched.sample_writer)
    s_v = jnp.asarray(sched.sample_ver)
    s_r = jnp.asarray(sched.sample_round)
    key = jax.random.PRNGKey(7)

    # One plain round first: donation requires a device-execution output
    # (a fresh init may share constant buffers between zero leaves).
    state0 = init_cluster(cfg, len(sched.sample_writer))
    state1, _ = engine_mod.cluster_round(
        state0, topo, writes, part, kill, kill, s_w, s_v, s_r, key, cfg,
        False,
    )
    plain, _ = engine_mod.cluster_round(
        state1, topo, writes, part, kill, kill, s_w, s_v, s_r, key, cfg,
        False,
    )
    donated, _ = engine_mod.cluster_round_donated(
        state1, topo, writes, part, kill, kill, s_w, s_v, s_r, key, cfg,
        False,
    )
    for name in ("head", "contig", "seen"):
        np.testing.assert_array_equal(
            np.asarray(getattr(plain.data, name)),
            np.asarray(getattr(donated.data, name)),
            err_msg=name,
        )
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(state1.data.contig)

    # Leaf ops: same contract.
    g = cfg.gossip
    data1 = donated.data
    alive = donated.swim.alive
    b_plain, _ = gossip.broadcast_round(
        data1, topo, alive, part, writes, key, g
    )
    b_don, _ = gossip.broadcast_round_donated(
        data1, topo, alive, part, writes, key, g
    )
    assert_states_equal(b_plain, b_don, msg="broadcast donated")
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(data1.contig)
    s_plain, _ = gossip.sync_round(
        b_don, topo, alive, part, jnp.int32(3), key, g
    )
    s_don, _ = gossip.sync_round_donated(
        b_don, topo, alive, part, jnp.int32(3), key, g
    )
    assert_states_equal(s_plain, s_don, msg="sync donated")
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(b_don.contig)


def test_simulate_chunked_donation_bit_identical():
    """The chunked simulate path (every chunk through the donated scan)
    equals the unchunked path bit-for-bit — fault-free traces and final
    state unchanged by donation."""
    from corrosion_tpu.sim.engine import simulate

    cfg, topo, sched = _tiny_cluster(rounds=9)
    final_a, curves_a = simulate(cfg, topo, sched, seed=0)
    final_b, curves_b = simulate(cfg, topo, sched, seed=0, max_chunk=3)
    assert_states_equal(final_a.data, final_b.data, msg="chunked")
    np.testing.assert_array_equal(
        np.asarray(final_a.vis_round), np.asarray(final_b.vis_round)
    )
    for k in curves_a:
        np.testing.assert_array_equal(curves_a[k], curves_b[k], err_msg=k)


def test_caller_supplied_state_never_donated():
    """simulate() must not consume a caller's resume state: the snapshot
    stays readable and replays to the same result (checkpoint flows and
    the chaos suite re-read it). Chunk length 3 reuses the module's one
    compiled scan."""
    from corrosion_tpu.sim.engine import simulate

    cfg, topo, sched = _tiny_cluster(rounds=9)
    import dataclasses

    head = dataclasses.replace(sched, writes=sched.writes[:3])
    tail = dataclasses.replace(sched, writes=sched.writes[3:])
    snap, _ = simulate(cfg, topo, head, seed=0)
    out1, _ = simulate(cfg, topo, tail, seed=0, state=snap, max_chunk=3)
    out2, _ = simulate(cfg, topo, tail, seed=0, state=snap, max_chunk=3)
    np.asarray(snap.data.contig)  # still alive — never donated
    assert_states_equal(out1.data, out2.data, msg="resume replay")


# ---------------------------------------------------------------------------
# 4. Bench-report invariants + smoke budget gate


_PROVENANCE = {
    "platform": "cpu",
    "nodes": 128,
    "device_count": 1,
    "config_fingerprint": "deadbeefcafe0123",
}


def test_check_bench_invariants_accepts_consistent_report():
    plane = {"swim": 10.0, "broadcast": 50.0, "sync": 30.0}
    stage_costs = {
        k: {"flops": 1e6 * (i + 1), "bytes": 2e6 * (i + 1)}
        for i, k in enumerate(plane)
    }
    rep = {
        **_PROVENANCE,
        "step_ms": 100.0,
        "step_inner_ms": 90.0,
        "plane_ms": plane,
        "residual_ms": 10.0,
        # The device-cost plane: plane_ms now requires the matching
        # roofline block (derived from the same emitted numbers).
        "roofline": benchlib.roofline_report(stage_costs, plane),
        "step_ms_100k": 50.0,
        "step_inner_ms_100k": 49.0,
    }
    assert telemetry.check_bench_invariants(rep) is rep


def test_check_bench_invariants_requires_roofline_with_planes():
    """A plane_ms attribution without the flop/byte attribution is
    refused at the emit site, and a roofline whose achieved rate does
    not recompute from the emitted numbers is too."""
    plane = {"broadcast": 50.0}
    with pytest.raises(ValueError, match="roofline"):
        telemetry.check_bench_invariants(
            {**_PROVENANCE, "step_ms": 60.0, "plane_ms": plane,
             "residual_ms": 10.0}
        )
    bad = benchlib.roofline_report(
        {"broadcast": {"flops": 1e6, "bytes": 1e6}}, plane
    )
    bad["broadcast"]["flops_per_s"] = 123.0  # doctored achieved rate
    with pytest.raises(ValueError, match="flops_per_s"):
        telemetry.check_bench_invariants(
            {**_PROVENANCE, "step_ms": 60.0, "plane_ms": plane,
             "residual_ms": 10.0, "roofline": bad}
        )


def test_check_bench_invariants_compile_split_and_steady():
    """The ledger split must reconstruct the first-run blob, and a
    steady-state recompile count != 0 refuses to publish."""
    split = benchlib.compile_split_report(74.82, 61234.5)
    assert split["compile_ms"] + split["first_step_ms"] == pytest.approx(
        split["first_run_incl_compile_s"] * 1000.0
    )
    rep = {**_PROVENANCE, "step_ms": 10.0, **split, "steady_compiles": 0}
    assert telemetry.check_bench_invariants(rep) is rep
    with pytest.raises(ValueError, match="first_step_ms"):
        telemetry.check_bench_invariants(
            {**_PROVENANCE, "step_ms": 10.0, "compile_ms": 5.0}
        )
    with pytest.raises(ValueError, match="reconstruct"):
        telemetry.check_bench_invariants(
            {**_PROVENANCE, "step_ms": 10.0,
             "first_run_incl_compile_s": 10.0, "compile_ms": 5.0,
             "first_step_ms": 5.0}
        )
    with pytest.raises(ValueError, match="steady_compiles"):
        telemetry.check_bench_invariants(
            {**_PROVENANCE, "step_ms": 10.0, "steady_compiles": 2}
        )


def test_check_bench_invariants_rejects_r05_shape():
    # The BENCH_r05 anomaly: inner > step, planes summing to the raw
    # composite instead of partitioning step_ms. ValueError, not assert:
    # the guarantee must survive `python -O`.
    with pytest.raises(ValueError, match="step_inner_ms"):
        telemetry.check_bench_invariants(
            {**_PROVENANCE, "step_ms": 1189.1, "step_inner_ms": 1545.2}
        )
    with pytest.raises(ValueError, match="partition"):
        telemetry.check_bench_invariants(
            {
                **_PROVENANCE,
                "step_ms": 1189.1,
                "plane_ms": {"swim": 53.8, "broadcast": 807.6},
                "residual_ms": 0.2,
            }
        )


def test_check_bench_invariants_requires_provenance():
    """Every emitted bench JSON must be self-describing: a report
    missing platform/nodes/device_count/config_fingerprint — the shape
    under which a CPU-fallback run once passed as a TPU artifact — is
    refused at the emit site."""
    for missing in _PROVENANCE:
        rep = {
            **{k: v for k, v in _PROVENANCE.items() if k != missing},
            "step_ms": 10.0,
        }
        with pytest.raises(ValueError, match=missing):
            telemetry.check_bench_invariants(rep)


def test_bench_context_is_self_describing():
    ctx = benchlib.bench_context("cfg-repr", 128, 48)
    assert ctx["platform"] == "cpu"
    assert ctx["device_count"] >= 1
    assert len(ctx["config_fingerprint"]) == 16
    # Deterministic, and sensitive to every fingerprinted part.
    assert (
        ctx["config_fingerprint"]
        == benchlib.bench_context("cfg-repr", 128, 48)["config_fingerprint"]
    )
    assert (
        ctx["config_fingerprint"]
        != benchlib.bench_context("cfg-repr", 256, 48)["config_fingerprint"]
    )


def test_bench_budget_platform_mismatch_breaches():
    """Ceilings measured on one platform or kernel backend must refuse
    to gate a measurement from another — the budget analogue of the
    self-describing report rule."""
    budget = {
        "tolerance": 1.5, "platform": "cpu", "kernels": "native",
        "step_ms": 100.0,
    }
    ok, breaches = benchlib.check_budget(
        {"platform": "tpu", "kernels": "native", "step_ms": 1.0}, budget
    )
    assert not ok and "platform" in "\n".join(breaches)
    ok2, breaches2 = benchlib.check_budget(
        {"platform": "cpu", "kernels": "pallas", "step_ms": 1.0}, budget
    )
    assert not ok2 and "kernels" in "\n".join(breaches2)
    ok3, _ = benchlib.check_budget(
        {"platform": "cpu", "kernels": "native", "step_ms": 1.0}, budget
    )
    assert ok3


def test_bench_budget_gate():
    measured = {
        "step_ms": 100.0,
        "plane_ms": {"broadcast": 60.0, "sync": 30.0},
    }
    budget = {
        "tolerance": 1.5,
        "step_ms": 80.0,
        "plane_ms": {"broadcast": 50.0, "sync": 5.0, "track": 1.0},
    }
    ok, breaches = benchlib.check_budget(measured, budget)
    assert not ok
    joined = "\n".join(breaches)
    # step 100 <= 80*1.5 -> fine; broadcast 60 <= 75 fine; sync 30 > 7.5
    # breaches; track missing from the measurement breaches.
    assert "plane_ms.sync" in joined and "plane_ms.track" in joined
    assert "step_ms" not in joined and "broadcast" not in joined
    ok2, breaches2 = benchlib.check_budget(
        {"step_ms": 10.0, "plane_ms": {"broadcast": 1.0, "sync": 1.0,
                                       "track": 0.5}},
        budget,
    )
    assert ok2 and not breaches2
    # A bench-shape drift must breach: ceilings measured at one shape
    # cannot gate a differently-shaped measurement.
    ok3, breaches3 = benchlib.check_budget(
        {"nodes": 64, "rounds": 48, "step_ms": 10.0,
         "plane_ms": {"broadcast": 1.0, "sync": 1.0, "track": 0.5}},
        {**budget, "nodes": 128, "rounds": 48},
    )
    assert not ok3 and "rerun with --update" in "\n".join(breaches3)
