"""Broadcast/sync hot-path overhaul: equivalence + safety pins.

Three families of guarantees from the perf pass (docs/PERFORMANCE.md):

1. **Batched anti-entropy pipeline**: the single tiled [R, C, W]
   candidate-scoring gather and the [R, S+1, W] union-pull are
   bit-identical to the original per-candidate/per-peer Python loops —
   peer selection AND post-sync DataState — in exact and digest scoring
   modes, on the cohort and non-cohort sync_round paths.
2. **Backend-native one-hot primitives**: the CPU scatter/gather forms
   of ops/onehot.py equal the dense one-hot forms bit-for-bit, at the
   primitive level (including out-of-range index handling) and through
   whole gossip rounds.
3. **Donation safety**: donated round/scan entry points return
   bit-identical results, actually release the donated input buffers,
   never read a donated buffer after the call in any engine driver, and
   keep the per-function compile-cache count at <= 1 (the CT031 retrace
   tripwire's invariant).

Plus the bench-report invariants (step_inner_ms <= step_ms;
sum(plane_ms) + residual == step_ms) and the bench-smoke budget gate.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.ops import gossip, onehot
from corrosion_tpu.sim import telemetry
from corrosion_tpu.sim import benchlib


def mk(n, regions=None, writers=None, cohorts=False, **kw):
    regions = regions or [n]
    writers = writers if writers is not None else list(range(n))
    cfg = gossip.GossipConfig(n_nodes=n, n_writers=len(writers), **kw)
    topo = gossip.make_topology(
        regions, writers,
        sync_interval=cfg.sync_interval if cohorts else None,
    )
    return cfg, topo, gossip.init_data(cfg)


def run_rounds(cfg, topo, data, rounds, writes_fn=None, seed=0):
    """Broadcast+sync stepping loop; returns (final DataState, stats)."""
    n = cfg.n_nodes
    alive = jnp.ones(n, bool)
    part = jnp.zeros((int(jnp.max(topo.region)) + 1,) * 2, bool)
    key = jax.random.PRNGKey(seed)
    stats = []
    for r in range(rounds):
        key, k1, k2 = jax.random.split(key, 3)
        w = (
            writes_fn(r) if writes_fn
            else jnp.zeros(cfg.n_writers, jnp.uint32)
        )
        data, b = gossip.broadcast_round(data, topo, alive, part, w, k1, cfg)
        data, s = gossip.sync_round(
            data, topo, alive, part, jnp.int32(r), k2, cfg
        )
        stats.append((b, s))
    return data, stats


def assert_states_equal(a, b, msg=""):
    for name in a._fields:
        fa, fb = getattr(a, name), getattr(b, name)
        if name == "cells":
            for cn in fa._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(fa, cn)),
                    np.asarray(getattr(fb, cn)),
                    err_msg=f"{msg} cells.{cn}",
                )
        else:
            np.testing.assert_array_equal(
                np.asarray(fa), np.asarray(fb), err_msg=f"{msg} {name}"
            )


def _clear_round_caches():
    gossip.sync_round.clear_cache()
    gossip.sync_round_donated.clear_cache()
    gossip.broadcast_round.clear_cache()
    gossip.broadcast_round_donated.clear_cache()


def _clear_sync_caches():
    # The scoring flags (_BATCHED_SYNC/_EXACT_SCORE_MAX) only reach
    # sync_round's trace: broadcast stays cached across flips, which
    # keeps this module's wall time compile-light.
    gossip.sync_round.clear_cache()
    gossip.sync_round_donated.clear_cache()


# ---------------------------------------------------------------------------
# 1. Batched anti-entropy scoring/grants vs the looped reference


def _one_sync_run(cohorts, seed=0):
    cfg, topo, data = mk(
        24, regions=[6, 6, 6, 6], sync_interval=3, sync_budget=48,
        sync_chunk=8, sync_peers=3, sync_candidates=6, n_cells=32,
        cells_per_write=2, cohorts=cohorts,
    )
    w = jnp.zeros(24, jnp.uint32).at[3].set(2).at[17].set(1).at[9].set(3)
    data, stats = run_rounds(
        cfg, topo, data, 14,
        writes_fn=lambda r: w if r < 5 else jnp.zeros(24, jnp.uint32),
        seed=seed,
    )
    return data, stats


@pytest.mark.parametrize("cohorts", [False, True], ids=["phase", "cohort"])
@pytest.mark.parametrize("digest", [False, True], ids=["exact", "digest"])
def test_batched_scoring_bit_identical_to_looped(cohorts, digest):
    """Batched candidate scoring + grants == the looped reference:
    identical post-sync DataState (hence identical peer selection — a
    different selection changes what is granted) and identical per-round
    applied_sync/sessions stats, in both scoring modes, on both
    sync_round paths."""
    old_exact = gossip._EXACT_SCORE_MAX
    if digest:
        gossip._EXACT_SCORE_MAX = 0  # force the total-progress digest
    try:
        assert gossip._BATCHED_SYNC is True  # default under test
        _clear_sync_caches()
        batched, stats_b = _one_sync_run(cohorts)
        gossip._BATCHED_SYNC = False
        _clear_sync_caches()
        looped, stats_l = _one_sync_run(cohorts)
    finally:
        gossip._BATCHED_SYNC = True
        gossip._EXACT_SCORE_MAX = old_exact
        _clear_sync_caches()
    assert_states_equal(batched, looped, msg=f"cohorts={cohorts}")
    for r, ((_, sb), (_, sl)) in enumerate(zip(stats_b, stats_l)):
        for k in ("applied_sync", "sessions", "cell_merges"):
            assert int(sb[k]) == int(sl[k]), f"round {r} stat {k}"


def test_batched_scoring_converges_with_digest_mode():
    """Digest-mode selection still heals the cluster (the heuristic only
    affects which peers are pulled; grants recompute the real deficit)."""
    old_exact = gossip._EXACT_SCORE_MAX
    gossip._EXACT_SCORE_MAX = 0
    try:
        _clear_sync_caches()
        data, _ = _one_sync_run(cohorts=True)
    finally:
        gossip._EXACT_SCORE_MAX = old_exact
        _clear_sync_caches()
    heads = np.asarray(data.head)
    assert (np.asarray(data.contig) == heads[None, :]).all()


# ---------------------------------------------------------------------------
# 2. Native scatter/gather vs dense one-hot primitives


def _both_paths(fn):
    """Evaluate ``fn()`` under the native and dense onehot paths."""
    old = onehot._NATIVE_SCATTER
    try:
        onehot._NATIVE_SCATTER = True
        native = fn()
        onehot._NATIVE_SCATTER = False
        dense = fn()
    finally:
        onehot._NATIVE_SCATTER = old
    return native, dense


def test_onehot_primitives_native_equals_dense():
    k = jax.random.PRNGKey(0)
    r, m, w = 17, 23, 41
    # Indices deliberately include out-of-range values on both sides;
    # both paths must treat them as contributing nothing / yielding 0.
    idx = jax.random.randint(k, (r, m), -3, w + 4)
    val = jax.random.randint(
        jax.random.fold_in(k, 1), (r, m), 0, 1 << 30
    ).astype(jnp.uint32)
    mask = jax.random.bernoulli(jax.random.fold_in(k, 2), 0.7, (r, m))
    table = jax.random.randint(
        jax.random.fold_in(k, 3), (r, w), 0, 1 << 30
    ).astype(jnp.uint32)
    idx_in = jnp.clip(idx, 0, w - 1)

    for name, fn in {
        "rowmax": lambda: onehot.rowmax(idx, val, mask, w),
        "rowmax_nomask": lambda: onehot.rowmax(idx, val, None, w),
        "rowsum": lambda: onehot.rowsum(idx, val, mask, w),
        "rowgather": lambda: onehot.rowgather(table, idx),
        "rowgather_wide": lambda: onehot.rowgather_wide(table, idx_in),
        "table_gather": lambda: onehot.table_gather_u32(
            table[0], idx_in
        ),
    }.items():
        native, dense = _both_paths(fn)
        np.testing.assert_array_equal(
            np.asarray(native), np.asarray(dense), err_msg=name
        )


def test_gossip_rounds_native_equals_dense():
    """Whole broadcast+sync rounds (delivery, window, CRDT merge, grant
    enumeration, visibility) are bit-identical across the backend-native
    and dense one-hot paths."""

    def one():
        _clear_round_caches()
        cfg, topo, data = mk(
            24, regions=[8, 8, 8], sync_interval=3, n_cells=32,
            cells_per_write=2, loss_prob=0.2, cohorts=True,
        )
        w = jnp.zeros(24, jnp.uint32).at[5].set(3).at[20].set(2)
        data, _ = run_rounds(
            cfg, topo, data, 12,
            writes_fn=lambda r: w if r < 4 else jnp.zeros(24, jnp.uint32),
        )
        sw = jnp.asarray([5, 20], jnp.int32)
        sv = jnp.asarray([2, 1], jnp.uint32)
        vis = gossip.visibility(data, sw, sv)
        return data, np.asarray(vis)

    (d_nat, v_nat), (d_den, v_den) = _both_paths(one)
    _clear_round_caches()
    assert_states_equal(d_nat, d_den, msg="native vs dense")
    np.testing.assert_array_equal(v_nat, v_den)


# ---------------------------------------------------------------------------
# 3. Donation safety


def _tiny_cluster(rounds=9):
    """A lean ClusterConfig (small cell plane, default queue) — the
    donation contract is config-independent, and the flagship builder's
    1024-cell trace would quadruple this module's compile wall. Chunk
    length 3 is shared by every donation test below so the scan compiles
    once for the whole module."""
    import numpy as np

    from corrosion_tpu.ops.swim import SwimConfig
    from corrosion_tpu.sim.engine import ClusterConfig, Schedule

    n = 24
    g = gossip.GossipConfig(
        n_nodes=n, n_writers=n, sync_interval=3, n_cells=16,
        cells_per_write=1,
    )
    s = SwimConfig(
        n_nodes=n, max_transmissions=4, suspect_rounds=3, gossip_fanout=3
    )
    topo = gossip.make_topology(
        [n // 2, n // 2], list(range(n)), sync_interval=g.sync_interval
    )
    writes = np.zeros((rounds, n), np.uint32)
    writes[:3, :4] = 2
    sched = Schedule(writes=writes).make_samples(16)
    return ClusterConfig(swim=s, gossip=g), topo, sched


def test_donation_keeps_compile_cache_count_at_one():
    """A uniformly-chunked run compiles one donated scan executable (the
    ownership copy makes chunk 1 donatable too): every jitted entry in
    the engine module holds <= 1 compile-cache entry — the CT031 retrace
    tripwire invariant, donation included."""
    from corrosion_tpu.sim import engine as engine_mod
    from corrosion_tpu.sim.engine import simulate

    cfg, topo, sched = _tiny_cluster(rounds=9)
    jax.clear_caches()
    simulate(cfg, topo, sched, seed=0, max_chunk=3)
    for name in dir(engine_mod):
        fn = getattr(engine_mod, name, None)
        if callable(fn) and hasattr(fn, "_cache_size"):
            assert fn._cache_size() <= 1, (
                f"engine.{name} holds {fn._cache_size()} compile-cache "
                f"entries — donation must not add cache entries"
            )
    # The donated scan actually ran and compiled exactly once.
    assert engine_mod._scan_rounds_donated._cache_size() == 1


def test_donated_round_entry_points_bit_identical_and_released():
    """broadcast/sync/cluster_round donated twins: same results as the
    plain entries from an identical input, and the donated input's
    buffers are actually released (reading them afterwards raises)."""
    from corrosion_tpu.sim import engine as engine_mod
    from corrosion_tpu.sim.engine import init_cluster

    cfg, topo, sched = _tiny_cluster()
    n_regions = int(np.asarray(topo.region).max()) + 1
    part = jnp.zeros((n_regions, n_regions), bool)
    kill = jnp.zeros((1,), bool)
    writes = jnp.asarray(sched.writes[0], jnp.uint32)
    s_w = jnp.asarray(sched.sample_writer)
    s_v = jnp.asarray(sched.sample_ver)
    s_r = jnp.asarray(sched.sample_round)
    key = jax.random.PRNGKey(7)

    # One plain round first: donation requires a device-execution output
    # (a fresh init may share constant buffers between zero leaves).
    state0 = init_cluster(cfg, len(sched.sample_writer))
    state1, _ = engine_mod.cluster_round(
        state0, topo, writes, part, kill, kill, s_w, s_v, s_r, key, cfg,
        False,
    )
    plain, _ = engine_mod.cluster_round(
        state1, topo, writes, part, kill, kill, s_w, s_v, s_r, key, cfg,
        False,
    )
    donated, _ = engine_mod.cluster_round_donated(
        state1, topo, writes, part, kill, kill, s_w, s_v, s_r, key, cfg,
        False,
    )
    for name in ("head", "contig", "seen"):
        np.testing.assert_array_equal(
            np.asarray(getattr(plain.data, name)),
            np.asarray(getattr(donated.data, name)),
            err_msg=name,
        )
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(state1.data.contig)

    # Leaf ops: same contract.
    g = cfg.gossip
    data1 = donated.data
    alive = donated.swim.alive
    b_plain, _ = gossip.broadcast_round(
        data1, topo, alive, part, writes, key, g
    )
    b_don, _ = gossip.broadcast_round_donated(
        data1, topo, alive, part, writes, key, g
    )
    assert_states_equal(b_plain, b_don, msg="broadcast donated")
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(data1.contig)
    s_plain, _ = gossip.sync_round(
        b_don, topo, alive, part, jnp.int32(3), key, g
    )
    s_don, _ = gossip.sync_round_donated(
        b_don, topo, alive, part, jnp.int32(3), key, g
    )
    assert_states_equal(s_plain, s_don, msg="sync donated")
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(b_don.contig)


def test_simulate_chunked_donation_bit_identical():
    """The chunked simulate path (every chunk through the donated scan)
    equals the unchunked path bit-for-bit — fault-free traces and final
    state unchanged by donation."""
    from corrosion_tpu.sim.engine import simulate

    cfg, topo, sched = _tiny_cluster(rounds=9)
    final_a, curves_a = simulate(cfg, topo, sched, seed=0)
    final_b, curves_b = simulate(cfg, topo, sched, seed=0, max_chunk=3)
    assert_states_equal(final_a.data, final_b.data, msg="chunked")
    np.testing.assert_array_equal(
        np.asarray(final_a.vis_round), np.asarray(final_b.vis_round)
    )
    for k in curves_a:
        np.testing.assert_array_equal(curves_a[k], curves_b[k], err_msg=k)


def test_caller_supplied_state_never_donated():
    """simulate() must not consume a caller's resume state: the snapshot
    stays readable and replays to the same result (checkpoint flows and
    the chaos suite re-read it). Chunk length 3 reuses the module's one
    compiled scan."""
    from corrosion_tpu.sim.engine import simulate

    cfg, topo, sched = _tiny_cluster(rounds=9)
    import dataclasses

    head = dataclasses.replace(sched, writes=sched.writes[:3])
    tail = dataclasses.replace(sched, writes=sched.writes[3:])
    snap, _ = simulate(cfg, topo, head, seed=0)
    out1, _ = simulate(cfg, topo, tail, seed=0, state=snap, max_chunk=3)
    out2, _ = simulate(cfg, topo, tail, seed=0, state=snap, max_chunk=3)
    np.asarray(snap.data.contig)  # still alive — never donated
    assert_states_equal(out1.data, out2.data, msg="resume replay")


# ---------------------------------------------------------------------------
# 4. Bench-report invariants + smoke budget gate


def test_check_bench_invariants_accepts_consistent_report():
    rep = {
        "step_ms": 100.0,
        "step_inner_ms": 90.0,
        "plane_ms": {"swim": 10.0, "broadcast": 50.0, "sync": 30.0},
        "residual_ms": 10.0,
        "step_ms_100k": 50.0,
        "step_inner_ms_100k": 49.0,
    }
    assert telemetry.check_bench_invariants(rep) is rep


def test_check_bench_invariants_rejects_r05_shape():
    # The BENCH_r05 anomaly: inner > step, planes summing to the raw
    # composite instead of partitioning step_ms. ValueError, not assert:
    # the guarantee must survive `python -O`.
    with pytest.raises(ValueError, match="step_inner_ms"):
        telemetry.check_bench_invariants(
            {"step_ms": 1189.1, "step_inner_ms": 1545.2}
        )
    with pytest.raises(ValueError, match="partition"):
        telemetry.check_bench_invariants(
            {
                "step_ms": 1189.1,
                "plane_ms": {"swim": 53.8, "broadcast": 807.6},
                "residual_ms": 0.2,
            }
        )


def test_bench_budget_gate():
    measured = {
        "step_ms": 100.0,
        "plane_ms": {"broadcast": 60.0, "sync": 30.0},
    }
    budget = {
        "tolerance": 1.5,
        "step_ms": 80.0,
        "plane_ms": {"broadcast": 50.0, "sync": 5.0, "track": 1.0},
    }
    ok, breaches = benchlib.check_budget(measured, budget)
    assert not ok
    joined = "\n".join(breaches)
    # step 100 <= 80*1.5 -> fine; broadcast 60 <= 75 fine; sync 30 > 7.5
    # breaches; track missing from the measurement breaches.
    assert "plane_ms.sync" in joined and "plane_ms.track" in joined
    assert "step_ms" not in joined and "broadcast" not in joined
    ok2, breaches2 = benchlib.check_budget(
        {"step_ms": 10.0, "plane_ms": {"broadcast": 1.0, "sync": 1.0,
                                       "track": 0.5}},
        budget,
    )
    assert ok2 and not breaches2
    # A bench-shape drift must breach: ceilings measured at one shape
    # cannot gate a differently-shaped measurement.
    ok3, breaches3 = benchlib.check_budget(
        {"nodes": 64, "rounds": 48, "step_ms": 10.0,
         "plane_ms": {"broadcast": 1.0, "sync": 1.0, "track": 0.5}},
        {**budget, "nodes": 128, "rounds": 48},
    )
    assert not ok3 and "rerun with --update" in "\n".join(breaches3)
