"""Differential test: the host store's SQLite CRDT merge and the TPU
kernel's batched merge implement the SAME semantics (cr-sqlite's causal
length + LWW, doc/crdts.md:11-28) in two very different substrates. Drive
both with identical randomized change streams and require identical
winners.

Mapping: one sim cell = one (pk, column) register of a single-column
table. Values are non-negative integers, so the kernel's value_rank (u32,
bigger wins) and the store's SQLite value ordering (integers compare
numerically and all integers sort the same way) agree by construction.
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from corrosion_tpu.agent.store import Store  # noqa: E402
from corrosion_tpu.core.values import Change  # noqa: E402
from corrosion_tpu.ops import crdt  # noqa: E402

N_KEYS = 8


def make_store(tmp_path, name):
    store = Store(str(tmp_path / name), os.urandom(16))
    store.apply_schema(
        "CREATE TABLE cells (k INTEGER NOT NULL PRIMARY KEY, v INTEGER)"
    )
    return store


def random_changes(rng, n, site):
    """(key, cl, col_version, value) tuples; ~1/6 deletes (even cl)."""
    out = []
    for _ in range(n):
        key = int(rng.integers(0, N_KEYS))
        cl = int(rng.integers(1, 4))
        if rng.random() < 1 / 6:
            cl = cl * 2  # delete epoch
        else:
            cl = cl * 2 - 1  # live epoch
        cv = int(rng.integers(1, 6))
        val = int(rng.integers(0, 1000))
        out.append((key, cl, cv, val, site))
    return out


def apply_to_store(store, changes):
    pk = {}
    chs = []
    for i, (key, cl, cv, val, site) in enumerate(changes):
        from corrosion_tpu.core.values import pack_columns

        pk[key] = pack_columns([key])
        if cl % 2 == 0:
            ch = Change(table="cells", pk=pk[key], cid=Change.DELETE_CID,
                        val=None, col_version=1, db_version=i + 1, seq=0,
                        site_id=site, cl=cl)
        else:
            ch = Change(table="cells", pk=pk[key], cid="v", val=val,
                        col_version=cv, db_version=i + 1, seq=0,
                        site_id=site, cl=cl)
        chs.append(ch)
    store.apply_changes(chs)


def store_state(store):
    """(cl, col_version, value) per key from the clock + table."""
    out = {}
    for key in range(N_KEYS):
        from corrosion_tpu.core.values import pack_columns

        pk = pack_columns([key])
        row = store.conn.execute(
            'SELECT cl FROM "cells__crdt_rows" WHERE pk = ?', (pk,)
        ).fetchone()
        if row is None:
            continue
        cl = row[0]
        clock = store.conn.execute(
            'SELECT col_version FROM "cells__crdt_clock"'
            " WHERE pk = ? AND cid = 'v'",
            (pk,),
        ).fetchone()
        val = store.conn.execute(
            "SELECT v FROM cells WHERE k = ?", (key,)
        ).fetchone()
        out[key] = (
            cl,
            clock[0] if clock else 0,
            val[0] if val and val[0] is not None else None,
        )
    return out


def apply_to_kernel(changes):
    cells = crdt.make_cells(N_KEYS)
    key = jnp.asarray([c[0] for c in changes], jnp.int32)
    cl = jnp.asarray([c[1] for c in changes], jnp.uint32)
    cv = jnp.asarray(
        # Delete epochs carry no cell write: col_version 0 so live-epoch
        # writes at the same causal length never lose to a delete's row.
        [0 if c[1] % 2 == 0 else c[2] for c in changes], jnp.uint32
    )
    vr = jnp.asarray(
        [0 if c[1] % 2 == 0 else c[3] for c in changes], jnp.uint32
    )
    mask = jnp.ones((len(changes),), bool)
    batch = crdt.ChangeBatch(
        key=key, cl=cl, col_version=cv, value_rank=vr, mask=mask
    )
    return crdt.apply_changes(cells, batch)


def test_store_and_kernel_agree_on_random_streams(tmp_path):
    rng = np.random.default_rng(7)
    for trial in range(6):
        site_a, site_b = os.urandom(16), os.urandom(16)
        changes = random_changes(rng, 40, site_a) + random_changes(
            rng, 40, site_b
        )
        # The store applies in two different orders; the kernel in one
        # batch: all three must land on the same winners.
        s1 = make_store(tmp_path, f"s1_{trial}.db")
        apply_to_store(s1, changes)
        s2 = make_store(tmp_path, f"s2_{trial}.db")
        order = rng.permutation(len(changes))
        apply_to_store(s2, [changes[i] for i in order])
        st1, st2 = store_state(s1), store_state(s2)
        assert st1 == st2, f"trial {trial}: store order-dependent"

        cells = apply_to_kernel(changes)
        k_cl = np.asarray(cells.cl)
        k_cv = np.asarray(cells.col_version)
        k_vr = np.asarray(cells.value_rank)
        for key in range(N_KEYS):
            if key not in st1:
                assert k_cl[key] == 0, f"kernel has ghost cell {key}"
                continue
            cl, cv, val = st1[key]
            assert k_cl[key] == cl, (
                f"trial {trial} key {key}: kernel cl {k_cl[key]} vs "
                f"store {cl}"
            )
            if cl % 2 == 1:  # live: compare the LWW winner
                assert k_cv[key] == cv, (
                    f"trial {trial} key {key}: col_version "
                    f"{k_cv[key]} vs {cv}"
                )
                if val is not None:
                    assert k_vr[key] == val, (
                        f"trial {trial} key {key}: value {k_vr[key]} "
                        f"vs {val}"
                    )
        s1.close()
        s2.close()
