"""CRDT cell plane wired into the data plane.

The core property (VERDICT r1 item 2): after a cluster run converges, every
node's merged register state equals the order-independent serial merge of all
committed writes — the guarantee cr-sqlite's merge gives the reference
(doc/crdts.md:11-28), here enforced over actual delivered/synced batches with
loss, retransmission, and out-of-order arrival in play.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.ops import crdt, gossip


def mk(n, regions=None, writers=None, **kw):
    regions = regions or [n]
    writers = writers if writers is not None else list(range(n))
    cfg = gossip.GossipConfig(n_nodes=n, n_writers=len(writers), **kw)
    topo = gossip.make_topology(regions, writers)
    return cfg, topo, gossip.init_data(cfg)


def run(cfg, topo, data, rounds, writes_fn=None, seed=0, start=0):
    n = cfg.n_nodes
    alive = jnp.ones(n, bool)
    part = jnp.zeros((int(jnp.max(topo.region)) + 1,) * 2, bool)
    key = jax.random.PRNGKey(seed)
    merges = 0
    for r in range(start, start + rounds):
        key, k1, k2 = jax.random.split(key, 3)
        w = writes_fn(r) if writes_fn else jnp.zeros(cfg.n_writers, jnp.uint32)
        data, b = gossip.broadcast_round(data, topo, alive, part, w, k1, cfg)
        data, s = gossip.sync_round(data, topo, alive, part, jnp.int32(r), k2, cfg)
        merges += int(b["cell_merges"]) + int(s["cell_merges"])
    return data, merges


def assert_converged_to_serial_merge(data, cfg):
    heads = np.asarray(data.head)
    contig = np.asarray(data.contig)
    assert (contig == heads[None, :]).all(), "watermarks converged"
    assert bool(gossip.cells_agree(data, cfg)), "all nodes' cells identical"
    ref = gossip.serial_merge_reference(data.head, cfg)
    pc = gossip.node_cells(data, cfg)
    np.testing.assert_array_equal(np.asarray(pc.cl[0]), np.asarray(ref.cl))
    np.testing.assert_array_equal(
        np.asarray(pc.col_version[0]), np.asarray(ref.col_version)
    )
    np.testing.assert_array_equal(
        np.asarray(pc.value_rank[0]), np.asarray(ref.value_rank)
    )


def test_concurrent_writers_converge_to_serial_merge():
    # 12 nodes all writing into a small key space -> heavy LWW conflicts.
    cfg, topo, data = mk(12, n_cells=64, cells_per_write=2, sync_interval=4)
    rng = np.random.default_rng(0)
    w_sched = (rng.random((6, 12)) < 0.5).astype(np.uint32) * 2
    data, merges = run(
        cfg, topo, data, 6,
        writes_fn=lambda r: jnp.asarray(w_sched[r]),
    )
    data, m2 = run(cfg, topo, data, 30, start=6)
    assert merges > 0, "merges must execute during the write phase"
    assert_converged_to_serial_merge(data, cfg)


def test_lossy_delivery_still_converges_exactly():
    cfg, topo, data = mk(
        10, n_cells=32, cells_per_write=1, loss_prob=0.35, sync_interval=5
    )
    w = jnp.zeros(10, jnp.uint32).at[2].set(3).at[7].set(3)
    data, _ = run(cfg, topo, data, 5, writes_fn=lambda r: w)
    data, _ = run(cfg, topo, data, 45, start=5)
    assert_converged_to_serial_merge(data, cfg)


def test_sync_only_grants_materialize_cells():
    # fanout=0: every cell a non-writer holds arrived via sync enumeration.
    cfg, topo, data = mk(
        6, n_cells=32, fanout_near=0, fanout_far=0,
        sync_interval=1, sync_budget=16, sync_chunk=16,
    )
    w = jnp.zeros(6, jnp.uint32).at[0].set(3)
    data, _ = run(cfg, topo, data, 3, writes_fn=lambda r: w)
    data, merges = run(cfg, topo, data, 25, start=3)
    assert merges > 0, "sync plane must execute merges"
    assert_converged_to_serial_merge(data, cfg)


def test_delete_precedence_survives_dissemination():
    # derive_change marks ~1/16 writes as deletes (cl=2); with enough writes
    # at least one delete lands, and causal-length precedence must hold in
    # the converged state: any key with a delete shows even cl.
    cfg, topo, data = mk(8, n_cells=16, cells_per_write=2, sync_interval=3)
    w = jnp.ones(8, jnp.uint32) * 4
    data, _ = run(cfg, topo, data, 4, writes_fn=lambda r: w)
    data, _ = run(cfg, topo, data, 30, start=4)
    assert_converged_to_serial_merge(data, cfg)
    ref = gossip.serial_merge_reference(data.head, cfg)
    assert bool(jnp.any(ref.cl == 2)), "schedule produced at least one delete"


def test_disabled_cell_plane_has_empty_state():
    cfg, topo, data = mk(6)
    assert data.cells.cl.shape == (0,)
    w = jnp.zeros(6, jnp.uint32).at[0].set(1)
    data, merges = run(cfg, topo, data, 10, writes_fn=lambda r: w if r == 0 else jnp.zeros(6, jnp.uint32))
    assert merges == 0


def test_block_enumeration_forced_at_small_scale_matches_flat():
    """Force the block-decomposition enumeration at toy size (module
    threshold override, like _FAST_MAX_WRITERS) and check the merged
    cells equal a run through the flat path — the two implementations
    encode ONE enumeration."""
    def one_run():
        cfg, topo, data = mk(
            24, writers=list(range(24)), sync_interval=2, sync_budget=32,
            sync_chunk=8, n_cells=32, fanout_near=2, fanout_far=1,
        )
        w = jnp.zeros(24, jnp.uint32).at[3].set(2).at[17].set(1)
        data, _ = run(cfg, topo, data, 30,
                      writes_fn=lambda r: w if r < 6 else jnp.zeros(24, jnp.uint32))
        return cfg, data

    cfg_a, flat = one_run()
    old = gossip._BLOCK_ENUM_MIN_WRITERS
    gossip._BLOCK_ENUM_MIN_WRITERS = 1
    gossip.sync_round.clear_cache()
    gossip.broadcast_round.clear_cache()
    try:
        cfg_b, block = one_run()
    finally:
        gossip._BLOCK_ENUM_MIN_WRITERS = old
        gossip.sync_round.clear_cache()
        gossip.broadcast_round.clear_cache()
    for name in ("head", "contig", "seen"):
        assert (np.asarray(getattr(flat, name))
                == np.asarray(getattr(block, name))).all(), name
    for name in ("cl", "col_version", "value_rank"):
        assert (np.asarray(getattr(flat.cells, name))
                == np.asarray(getattr(block.cells, name))).all(), name
    assert_converged_to_serial_merge(block, cfg_b)


@pytest.mark.slow  # tier-1 budget; the chaos CI job runs this file unfiltered
def test_wide_writer_axis_sync_enumeration_matches_serial_merge():
    """n_writers >= 2048 routes the sync grant enumeration through the
    two-level block decomposition (MXU one-hot matmuls); the merged cell
    state must still equal the order-independent serial merge — the same
    ground truth the flat path is held to."""
    n = 2048
    cfg, topo, data = mk(
        n,
        writers=list(range(n)),
        fanout_near=2,
        fanout_far=2,
        queue=8,
        max_transmissions=5,
        sync_interval=2,
        sync_budget=128,
        sync_chunk=8,
        n_cells=64,
    )
    assert cfg.n_writers >= gossip._BLOCK_ENUM_MIN_WRITERS  # block path
    rng = np.random.default_rng(9)
    w_sched = (rng.random((6, n)) < 0.02).astype(np.uint32)

    def writes_fn(r):
        if r < 6:
            return jnp.asarray(w_sched[r])
        return jnp.zeros(n, jnp.uint32)

    data, merges = run(cfg, topo, data, 40, writes_fn=writes_fn)
    heads = np.asarray(data.head)
    assert heads.sum() > 0
    contig = np.asarray(data.contig)
    assert (contig == heads[None, :]).all(), "watermarks must converge"
    assert bool(gossip.cells_agree(data, cfg))
    assert_converged_to_serial_merge(data, cfg)
