"""Static-analysis plane v2 tests: asyncio race/lifecycle lints
(CT040-CT043), the engine-clone drift gate (CT050-CT052 + SEAM_MAP
round-trip), determinism taint (CT060-CT062), stale-suppression
detection (CT009), and the `lint --changed` CLI mode.

Positive/negative fixtures per rule, plus the corrupted-clone
acceptance pair: mutating one real engine copy fires CT050; a
refresh_seams (declared-seam edit) run is clean again.
"""

import os
import shutil
import subprocess
import textwrap

import pytest

from corrosion_tpu.analysis import lint_paths
from corrosion_tpu.analysis import clonemap

PKG = os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
) + "/corrosion_tpu"


def _lint_snippet(tmp_path, source, name="snippet.py", **kw):
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return lint_paths([str(p)], **kw)


def _rules(result):
    return [f.rule for f in result.findings]


# -- CT040: await-straddled state write ---------------------------------


def test_ct040_read_await_write_without_lock(tmp_path):
    res = _lint_snippet(tmp_path, """\
        import asyncio

        class Cache:
            async def refill(self, k):
                if k not in self._entries:
                    v = await self._fetch(k)
                    self._entries[k] = v
                return self._entries[k]
    """)
    assert _rules(res) == ["CT040"]
    assert "_entries" in res.findings[0].message


def test_ct040_lock_guarded_is_clean(tmp_path):
    res = _lint_snippet(tmp_path, """\
        import asyncio

        class Cache:
            async def refill(self, k):
                async with self._lock:
                    if k not in self._entries:
                        self._entries[k] = await self._fetch(k)
                    return self._entries[k]
    """)
    assert _rules(res) == []


def test_ct040_capture_and_swap_is_clean(tmp_path):
    # The write happens before the await: nothing to clobber after the
    # suspension point.
    res = _lint_snippet(tmp_path, """\
        import asyncio

        class Pump:
            async def stop(self):
                task, self._task = self._task, None
                if task is not None:
                    await task
    """)
    assert _rules(res) == []


# -- CT041: fire-and-forget tasks ---------------------------------------


def test_ct041_dropped_create_task(tmp_path):
    res = _lint_snippet(tmp_path, """\
        import asyncio

        def kick(loop):
            asyncio.create_task(work())
            _ = asyncio.ensure_future(other())
    """)
    assert _rules(res) == ["CT041", "CT041"]


def test_ct041_stored_or_grouped_is_clean(tmp_path):
    res = _lint_snippet(tmp_path, """\
        import asyncio

        async def kick(tg):
            t = asyncio.create_task(work())
            tasks.append(asyncio.create_task(other()))
            tg.create_task(third())
            await t
    """)
    assert _rules(res) == []


# -- CT042: blocking calls in async def ---------------------------------


def test_ct042_hard_blocking_fires_anywhere(tmp_path):
    res = _lint_snippet(tmp_path, """\
        import time

        async def tick():
            time.sleep(1.0)
    """)
    assert _rules(res) == ["CT042"]
    assert "time.sleep" in res.findings[0].message


def test_ct042_sqlite_fires_only_in_agent_modules(tmp_path):
    sql = textwrap.dedent("""\
        class H:
            async def load(self):
                return self.conn.execute("SELECT 1").fetchall()
    """)
    assert _rules(_lint_snippet(tmp_path, sql)) == []
    res = _lint_snippet(
        tmp_path, "# corro-lint: agent-module\n" + sql, name="hot.py"
    )
    assert _rules(res) == ["CT042"]


def test_ct042_cursor_local_resolution(tmp_path):
    res = _lint_snippet(tmp_path, """\
        # corro-lint: agent-module
        class H:
            async def load(self):
                c = self.store.conn
                return c.execute("SELECT 1").fetchall()
    """)
    assert _rules(res) == ["CT042"]


def test_ct042_sync_def_is_clean(tmp_path):
    res = _lint_snippet(tmp_path, """\
        import time

        def tick():
            time.sleep(1.0)
    """)
    assert _rules(res) == []


# -- CT043: swallowed CancelledError ------------------------------------


def test_ct043_swallow_variants(tmp_path):
    res = _lint_snippet(tmp_path, """\
        import asyncio

        async def a():
            try:
                await x()
            except asyncio.CancelledError:
                pass

        async def b():
            try:
                await x()
            except BaseException:
                log()
    """)
    assert _rules(res) == ["CT043", "CT043"]


def test_ct043_reraise_and_exception_are_clean(tmp_path):
    res = _lint_snippet(tmp_path, """\
        import asyncio

        async def a():
            try:
                await x()
            except asyncio.CancelledError:
                cleanup()
                raise
            except Exception:
                pass
    """)
    assert _rules(res) == []


def test_ct043_cancel_and_await_idiom_is_exempt(tmp_path):
    res = _lint_snippet(tmp_path, """\
        import asyncio

        async def close(task):
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
    """)
    assert _rules(res) == []


# -- CT060-CT062: determinism taint -------------------------------------


def test_ct060_wall_clock_in_traced_code(tmp_path):
    res = _lint_snippet(tmp_path, """\
        # corro-lint: kernel-module
        import jax
        import time

        @jax.jit
        def step(x):
            return x + time.time()
    """)
    assert "CT060" in _rules(res)


def test_ct060_host_helper_outside_kernel_is_clean(tmp_path):
    res = _lint_snippet(tmp_path, """\
        import time

        def stamp():
            return time.time()
    """)
    assert _rules(res) == []


def test_ct061_schedule_module_sources(tmp_path):
    res = _lint_snippet(tmp_path, """\
        # corro-lint: deterministic-module
        import random
        import numpy as np

        SEED_AT_IMPORT = random.random()

        def plan(regions):
            rng = np.random.default_rng()
            for r in set(regions):
                yield r, rng.random()
    """)
    rules = _rules(res)
    # import-time random.random, unseeded default_rng, set iteration
    assert rules.count("CT061") == 3
    msgs = " ".join(f.message for f in res.findings)
    assert "PYTHONHASHSEED" in msgs
    assert "unseeded" in msgs


def test_ct061_injected_and_seeded_are_clean(tmp_path):
    res = _lint_snippet(tmp_path, """\
        # corro-lint: deterministic-module
        import numpy as np
        import hashlib

        def plan(seed, regions, rng):
            g = np.random.default_rng(seed)
            h = hashlib.sha256(b"x").digest()
            for r in sorted(set(regions)):
                yield r, g.random(), rng.random(), h
    """)
    assert _rules(res) == []


def test_ct062_entropy_at_artifact_emit_site(tmp_path):
    src = """\
        import os

        def emit(path):
            return {"format": "corro-test-blob/1", "nonce": %s}
    """
    res = _lint_snippet(tmp_path, src % 'os.urandom(8).hex()')
    assert _rules(res) == ["CT062"]
    # Same entropy in a function with no artifact tag: not CT062's job.
    res = _lint_snippet(
        tmp_path,
        "import os\n\ndef emit(path):\n    return os.urandom(8).hex()\n",
    )
    assert _rules(res) == []


# -- CT050-CT052: engine-clone drift gate --------------------------------

_CLONE_A = """\
def round_a(state, key):
    a = mix(state, key)
    b = stir(a)
    return finish(b)
"""

_CLONE_B = """\
def round_b(st, key):
    a = mix(st, key)
    b = stir(a)
    b = extra_plane(b)
    return finish(b)
"""


def _clone_tree(tmp_path):
    (tmp_path / "sim").mkdir(parents=True, exist_ok=True)
    (tmp_path / "sim" / "a.py").write_text(_CLONE_A)
    (tmp_path / "sim" / "b.py").write_text(_CLONE_B)
    return {
        "format": "corro-seam-map/1",
        "clones": [{
            "name": "pair",
            "why": "test clones",
            "a": {"file": "sim/a.py", "func": "round_a"},
            "b": {"file": "sim/b.py", "func": "round_b"},
            "renames": {"round_b": "round_a", "st": "state"},
            "seams": [{
                "name": "extra-plane",
                "why": "b threads one more plane",
                "a": [],
                "b": ["    b = extra_plane(b)"],
            }],
        }],
        "partial_keys": {},
    }


def test_ct050_declared_seam_is_clean_and_drift_fires(tmp_path):
    smap = _clone_tree(tmp_path)
    assert clonemap.check_clones(smap, str(tmp_path)) == []
    # Drift outside the declared seam: mutate b's shared stanza.
    (tmp_path / "sim" / "b.py").write_text(
        _CLONE_B.replace("b = stir(a)", "b = stir(a, hard=True)")
    )
    found = clonemap.check_clones(smap, str(tmp_path))
    assert [f.rule for f in found] == ["CT050"]
    assert "pair" in found[0].message


def test_ct051_missing_function_and_file(tmp_path):
    smap = _clone_tree(tmp_path)
    (tmp_path / "sim" / "b.py").write_text("def other():\n    pass\n")
    found = clonemap.check_clones(smap, str(tmp_path))
    assert [f.rule for f in found] == ["CT051"]
    (tmp_path / "sim" / "b.py").unlink()
    found = clonemap.check_clones(smap, str(tmp_path))
    assert [f.rule for f in found] == ["CT051"]
    assert "file missing" in found[0].message


def test_seam_map_round_trip_and_refresh(tmp_path):
    smap = _clone_tree(tmp_path)
    path = str(tmp_path / "SEAM_MAP.json")
    clonemap.save_seam_map(smap, path)
    assert clonemap.load_seam_map(path) == smap
    # A legitimate new divergence — on a line NOT adjacent to the
    # existing seam, so its hunk survives unmerged: refresh declares it
    # (TODO why), the existing seam keeps its authored why, and the
    # gate is clean again.
    (tmp_path / "sim" / "b.py").write_text(
        _CLONE_B.replace("a = mix(st, key)", "a = mix(st, key, deep=True)")
    )
    assert clonemap.check_clones(smap, str(tmp_path)) != []
    refreshed, fresh = clonemap.refresh_seams(smap, str(tmp_path))
    assert fresh == 1
    assert clonemap.check_clones(refreshed, str(tmp_path)) == []
    whys = [s["why"] for s in refreshed["clones"][0]["seams"]]
    assert "b threads one more plane" in whys
    assert any("TODO" in w for w in whys)


def test_load_seam_map_rejects_wrong_format(tmp_path):
    path = str(tmp_path / "SEAM_MAP.json")
    (tmp_path / "SEAM_MAP.json").write_text('{"format": "nope/9"}')
    with pytest.raises(ValueError):
        clonemap.load_seam_map(path)


def test_ct052_partial_key_waivers():
    engines = {
        "engine": ["a", "b"],
        "sparse_engine": ["a"],
        "chunk_engine": ["a", "b"],
        "mixed_engine": ["a", "b"],
    }
    canonical = ("a", "b")
    # No waiver: fires. Exact waiver: clean. Stale waiver: fires.
    f = clonemap.check_partial_keys({"partial_keys": {}}, engines,
                                    canonical, "MAP")
    assert [x.rule for x in f] == ["CT052"]
    ok = {"partial_keys": {"b": {
        "engines": ["chunk_engine", "engine", "mixed_engine"],
        "why": "sparse has no b plane",
    }}}
    assert clonemap.check_partial_keys(ok, engines, canonical, "MAP") == []
    stale = {"partial_keys": {"b": {"engines": ["engine"], "why": "x"}}}
    f = clonemap.check_partial_keys(stale, engines, canonical, "MAP")
    assert [x.rule for x in f] == ["CT052"]
    assert "stale waiver" in f[0].message


def test_corrupted_real_engine_clone_fires_ct050(tmp_path):
    """Acceptance: deliberately editing one real engine copy outside
    its declared seams fails CT050; regenerating the seam map (the
    declared-seam edit flow) makes it clean again."""
    shutil.copytree(os.path.join(PKG, "sim"), str(tmp_path / "sim"))
    smap = clonemap.load_seam_map(
        os.path.join(PKG, "analysis", "SEAM_MAP.json")
    )
    assert clonemap.check_clones(smap, str(tmp_path)) == []
    eng = tmp_path / "sim" / "engine.py"
    text = eng.read_text()
    assert "round=state.round + 1" in text
    eng.write_text(text.replace(
        "round=state.round + 1", "round=state.round + 2", 1
    ))
    found = clonemap.check_clones(smap, str(tmp_path))
    assert "CT050" in [f.rule for f in found]
    refreshed, fresh = clonemap.refresh_seams(smap, str(tmp_path))
    assert fresh >= 1
    assert clonemap.check_clones(refreshed, str(tmp_path)) == []


def test_repo_seam_map_is_live_and_clean():
    """The committed map matches the engines at HEAD: no drift, no
    missing functions, waivers agree with measured key coverage."""
    res = lint_paths([os.path.join(PKG, "sim")])
    assert [f for f in res.findings
            if f.rule in ("CT050", "CT051", "CT052")] == []
    smap = clonemap.load_seam_map(
        os.path.join(PKG, "analysis", "SEAM_MAP.json")
    )
    assert smap["clones"], "map must declare clone pairs"
    assert smap["partial_keys"], "map must carry the measured waivers"
    for pair in smap["clones"]:
        for seam in pair["seams"]:
            assert "TODO" not in seam["why"], (pair["name"], seam["name"])


# -- CT009: stale suppressions ------------------------------------------


def test_stale_suppression_is_reported_non_gating(tmp_path):
    res = _lint_snippet(tmp_path, """\
        x = 1  # corro-lint: disable=CT001 reason=used to fire here
    """)
    assert _rules(res) == []  # non-gating
    assert [f.rule for f in res.stale] == ["CT009"]
    assert "CT001" in res.stale[0].message
    assert res.ok


def test_matching_suppression_is_not_stale(tmp_path):
    res = _lint_snippet(tmp_path, """\
        # corro-lint: kernel-module
        import jax.numpy as jnp

        def f():
            return jnp.zeros((4,))  # corro-lint: disable=CT003 reason=test
    """)
    assert res.stale == []
    assert [f.rule for f in res.suppressed] == ["CT003"]


def test_runtime_rule_suppressions_are_exempt_from_staleness(tmp_path):
    # CT03x is consumed by `lint --sanitize`, which a static run never
    # executes — calling those stale would force deleting live ones.
    res = _lint_snippet(tmp_path, """\
        x = 1  # corro-lint: disable=CT031 reason=sanitizer-time waiver
    """)
    assert res.stale == []


def test_rules_filter_limits_staleness_judgement(tmp_path):
    res = _lint_snippet(tmp_path, """\
        x = 1  # corro-lint: disable=CT001 reason=judged only when run
    """, rules={"CT020"})
    assert res.stale == []


# -- lint --changed CLI --------------------------------------------------


def _git(cwd, *args):
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=cwd, check=True, capture_output=True,
    )


def test_lint_changed_scopes_to_touched_files(tmp_path, capsys):
    from corrosion_tpu import cli

    repo = tmp_path / "repo"
    repo.mkdir()
    (repo / "clean.py").write_text("x = 1\n")
    (repo / "other.py").write_text("y = 2\n")
    _git(repo, "init", "-q")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-qm", "seed")
    (repo / "other.py").write_text(
        "import time\n\nasync def tick():\n    time.sleep(1)\n"
    )
    # Full run sees both files; --changed sees only the dirty one, and
    # exit codes are unchanged (findings still gate).
    assert cli.main(["lint", str(repo)]) == 1
    capsys.readouterr()
    assert cli.main(["lint", "--changed", "HEAD", str(repo)]) == 1
    out = capsys.readouterr().out
    assert "1 file(s)" in out
    # Reverting the dirty file: nothing changed vs HEAD, clean exit.
    (repo / "other.py").write_text("y = 2\n")
    assert cli.main(["lint", "--changed", "HEAD", str(repo)]) == 0
    out = capsys.readouterr().out
    assert "0 file(s)" in out
    # A ref git cannot resolve is a usage error.
    assert cli.main(["lint", "--changed", "no-such-ref",
                     str(repo)]) == 2
