"""TLS gossip-plane tests — the test_mutual_tls analogue (peer.rs:1730):
real TLS endpoints over loopback, certificate generation via agent/tls.py.
"""

import asyncio
import ssl

import pytest

# agent/tls.py generates the PKI with the cryptography package; without it
# the whole module (not just individual tests) fails to import, which
# pytest reports as a tier-1 COLLECTION error. Skip cleanly instead.
pytest.importorskip(
    "cryptography", reason="agent TLS plane needs the cryptography package"
)

from corrosion_tpu.agent import tls as tls_mod
from corrosion_tpu.agent.agent import AgentTls
from corrosion_tpu.agent.testing import launch_test_agent, poll_until
from corrosion_tpu.agent.transport import Transport
from corrosion_tpu.core.values import Statement


def run(coro):
    return asyncio.run(coro)


@pytest.fixture
def pki(tmp_path):
    ca_dir = str(tmp_path / "ca")
    tls_mod.generate_ca(ca_dir)
    server = tls_mod.generate_server_cert(
        str(tmp_path / "server"), ca_dir, "127.0.0.1"
    )
    client = tls_mod.generate_client_cert(str(tmp_path / "client"), ca_dir)
    return {
        "ca": str(tmp_path / "ca" / tls_mod.CA_CERT),
        "ca_dir": ca_dir,
        "server": server,
        "client": client,
    }


def _agent_tls(pki, mtls=True):
    return AgentTls(
        cert=pki["server"].cert,
        key=pki["server"].key,
        ca=pki["ca"],
        client_cert=pki["client"].cert,
        client_key=pki["client"].key,
        mtls=mtls,
    )


def test_transport_mutual_tls_roundtrip(pki):
    async def main():
        server_t = Transport(
            ssl_server=tls_mod.server_ssl_context(
                pki["server"].cert, pki["server"].key, pki["ca"],
                require_client_cert=True,
            )
        )
        got: list = []

        async def handler(session, msg):
            got.append(msg)
            await session.send({"echo": msg["n"]})

        host, port = await server_t.serve("127.0.0.1", 0, handler)

        client_t = Transport(
            ssl_client=tls_mod.client_ssl_context(
                pki["ca"], pki["client"].cert, pki["client"].key
            )
        )
        session = await client_t.open_session((host, port), {"n": 42})
        assert session is not None
        reply = await session.recv(timeout=5)
        assert reply == {"echo": 42}
        assert got and got[0]["n"] == 42

        # Without a client cert, the mTLS handshake must fail.
        bare = Transport(ssl_client=tls_mod.client_ssl_context(pki["ca"]))
        failed = await bare.open_session((host, port), {"n": 1}, timeout=5)
        if failed is not None:  # TLS 1.3: rejection can land on first read
            assert await failed.recv(timeout=5) is None
        client_t.close()
        bare.close()
        server_t.close()

    run(main())


def test_untrusted_server_rejected(pki, tmp_path):
    async def main():
        # A server with a cert from a DIFFERENT CA must be rejected.
        other_ca = str(tmp_path / "other_ca")
        tls_mod.generate_ca(other_ca)
        rogue = tls_mod.generate_server_cert(
            str(tmp_path / "rogue"), other_ca, "127.0.0.1"
        )
        server_t = Transport(
            ssl_server=tls_mod.server_ssl_context(rogue.cert, rogue.key)
        )

        async def handler(session, msg):
            pass

        host, port = await server_t.serve("127.0.0.1", 0, handler)
        client_t = Transport(
            ssl_client=tls_mod.client_ssl_context(pki["ca"])
        )
        session = await client_t.open_session((host, port), {"n": 1}, timeout=5)
        assert session is None
        # insecure=True (config `insecure = true`) skips verification.
        loose = Transport(
            ssl_client=tls_mod.client_ssl_context(insecure=True)
        )
        session = await loose.open_session((host, port), {"n": 1}, timeout=5)
        assert session is not None
        loose.close()
        client_t.close()
        server_t.close()

    run(main())


def test_two_agents_gossip_over_mtls(pki, tmp_path):
    async def main():
        a = await launch_test_agent(
            str(tmp_path / "a"), tls=_agent_tls(pki)
        )
        b = await launch_test_agent(
            str(tmp_path / "b"), bootstrap=[a.gossip_addr],
            tls=_agent_tls(pki),
        )
        try:
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (1, 'tls')"]]
            )

            async def converged():
                _, rows = b.agent.store.query(
                    Statement("SELECT id, text FROM tests")
                )
                return rows == [(1, "tls")]

            await poll_until(converged, timeout=20)
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_ssl_contexts_enforce_tls13(pki):
    ctx = tls_mod.server_ssl_context(pki["server"].cert, pki["server"].key)
    assert ctx.minimum_version == ssl.TLSVersion.TLSv1_3
    ctx = tls_mod.client_ssl_context(pki["ca"])
    assert ctx.minimum_version == ssl.TLSVersion.TLSv1_3
    with pytest.raises(ValueError):
        tls_mod.server_ssl_context(
            pki["server"].cert, pki["server"].key, None,
            require_client_cert=True,
        )
