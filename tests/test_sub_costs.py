"""Serving query-cost plane (docs/SERVING.md "Query-cost plane").

Pins the tentpole contracts: the pinned zero-cost disabled mode
(bit-identical events, no per-sub state), the query-plan classifier
(regex sweep + PK-injector ground truth), the per-sub fallback counter
riding the registry's cardinality cap, the heatmap join + exact mass
reconciliation (including the missing-ledger refusal and the
machinery-fired rule), ledger survival across ``?from=`` replay and
agent kill/relaunch, and the ``/v1/subs/costs`` endpoint.
"""

import asyncio
import json
import sqlite3

import pytest

from corrosion_tpu.agent.store import Store
from corrosion_tpu.agent.subs import MatcherHandle, classify_query
from corrosion_tpu.core.values import Statement

SCHEMA = """
CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT NOT NULL DEFAULT '');
CREATE TABLE tests2 (id INTEGER NOT NULL PRIMARY KEY, text TEXT NOT NULL DEFAULT '');
"""


def run(coro):
    return asyncio.run(coro)


def mk_store(tmp_path, n=0):
    s = Store(str(tmp_path / f"node{n}.db"), bytes([n + 1] * 16))
    s.apply_schema(SCHEMA)
    return s


def ins(s, i, text, table="tests"):
    _, _, _, changes = s.execute_transaction(
        [Statement(f"INSERT INTO {table} (id, text) VALUES (?, ?)",
                   params=[i, text])]
    )
    return changes


# -- classifier ---------------------------------------------------------------


def test_classifier_unit_vectors():
    """The regex sweep's class precedence (window > aggregate > join >
    simple) and feature flags on representative shapes."""
    cls, feats = classify_query(
        "SELECT id, min(id) OVER (PARTITION BY id) FROM tests"
    )
    assert cls == "window" and "window" in feats
    cls, feats = classify_query(
        "SELECT text, count(*) FROM tests GROUP BY text"
    )
    assert cls == "aggregate"
    assert "aggregate" in feats and "group_by" in feats
    cls, feats = classify_query(
        "SELECT t.id FROM tests t JOIN tests2 u ON t.id = u.id"
    )
    assert cls == "join" and "join" in feats
    cls, feats = classify_query(
        "SELECT t.id FROM tests t LEFT JOIN tests2 u ON t.id = u.id"
    )
    assert cls == "join" and "outer_join" in feats
    cls, feats = classify_query("SELECT id, text FROM tests WHERE id % 2 = 0")
    assert cls == "simple" and feats == []
    cls, feats = classify_query("SELECT DISTINCT id FROM tests LIMIT 5")
    assert cls == "simple"
    assert "distinct" in feats and "limit" in feats


def test_plan_record_uses_injector_ground_truth(tmp_path):
    """``fallback_bound`` comes from the PK injector's actual outcome,
    not the regex guess: a plain-predicate query is incremental, a
    window query (PK injection refused) is fallback-bound."""
    s = mk_store(tmp_path)
    try:
        h = MatcherHandle(s, "SELECT id, text FROM tests WHERE id % 2 = 0")
        assert h.plan["class"] == "simple"
        assert h.plan["incremental"] and not h.plan["fallback_bound"]
        w = MatcherHandle(
            s,
            "SELECT id, text, min(id) OVER (PARTITION BY id) AS w"
            " FROM tests",
        )
        assert w.plan["class"] == "window"
        assert w.plan["fallback_bound"] and not w.plan["incremental"]
        h.close()
        w.close()
    finally:
        s.close()


# -- zero-cost disabled pin ---------------------------------------------------


def test_disabled_mode_zero_cost_pin(tmp_path):
    """Disabled (the default) is pinned zero-cost: ``handle.cost`` stays
    None, the sub-db never grows a cost row, and the emitted event
    stream is bit-identical to an enabled handle's over the same
    writes."""
    s_off = mk_store(tmp_path, 0)
    s_on = mk_store(tmp_path, 1)
    try:
        sql = "SELECT id, text FROM tests WHERE id % 2 = 0"
        d_off = str(tmp_path / "subs_off")
        d_on = str(tmp_path / "subs_on")
        h_off = MatcherHandle(s_off, sql, db_dir=d_off)
        h_on = MatcherHandle(s_on, sql, db_dir=d_on)
        h_on.enable_cost()
        assert h_off.cost is None and h_on.cost is not None
        ev_off, ev_on = [], []
        for i in range(6):
            ev_off += h_off.process(ins(s_off, i, f"row{i}"))
            ev_on += h_on.process(ins(s_on, i, f"row{i}"))
        assert [e.to_json_obj() for e in ev_off] == \
               [e.to_json_obj() for e in ev_on]
        assert h_off.cost is None
        assert h_on.cost.snapshot()["candidate_evals"] > 0
        off_id, on_id = h_off.id, h_on.id
        h_off.close()
        h_on.close()
        db = sqlite3.connect(f"{d_off}/{off_id}.sqlite")
        assert db.execute(
            "SELECT v FROM meta WHERE k = 'cost'"
        ).fetchone() is None
        db.close()
        db = sqlite3.connect(f"{d_on}/{on_id}.sqlite")
        row = db.execute("SELECT v FROM meta WHERE k = 'cost'").fetchone()
        db.close()
        assert row is not None
        assert json.loads(row[0])["candidate_evals"] > 0
    finally:
        s_off.close()
        s_on.close()


def test_ledger_counts_fallback_and_candidate_kinds(tmp_path):
    """A fallback-bound handle books fallback evals + scanned rows; an
    incremental one books candidate evals — and the stage profiler
    decomposes a processed batch into the four stages."""
    s = mk_store(tmp_path)
    try:
        w = MatcherHandle(
            s,
            "SELECT id, text, min(id) OVER (PARTITION BY id) AS w"
            " FROM tests",
        )
        w.enable_cost()
        h = MatcherHandle(s, "SELECT id, text FROM tests")
        h.enable_cost()
        stages: list = []
        for i in range(4):
            changes = ins(s, i, f"r{i}")
            w.process(changes)
            h.process(changes, stages)
        cw, ch = w.cost.snapshot(), h.cost.snapshot()
        assert cw["fallback_evals"] >= 1 and cw["candidate_evals"] == 0
        assert cw["eval_seconds_fallback"] > 0 and cw["rows_scanned"] > 0
        assert ch["candidate_evals"] >= 1 and ch["fallback_evals"] == 0
        names = {name for name, _, _ in stages}
        assert names == {
            "candidate_extract", "sql_exec", "diff", "fanout_enqueue",
        }
        w.close()
        h.close()
    finally:
        s.close()


# -- cardinality cap ----------------------------------------------------------


def test_fallback_counter_cardinality_cap_under_ephemeral_subs():
    """5k ephemeral subscriptions' fallback counters must not explode
    /metrics: past ``max_labelsets`` the per-sub label folds into the
    `other` bucket and the registry counts the folded samples."""
    from corrosion_tpu.agent.subs import SubCost
    from corrosion_tpu.utils.metrics import MetricsRegistry

    reg = MetricsRegistry()
    fb = reg.counter("corro_subs_fallback_total")
    for i in range(5000):
        cost = SubCost(f"{i:08x}" * 4, fb_counter=fb)
        cost.note_eval("fallback", rows=1, seconds=0.001)
    assert len(fb._values) <= reg.max_labelsets + 1
    assert fb.get(sub="other") == 5000 - reg.max_labelsets
    assert reg._labelsets_dropped.get() == 5000 - reg.max_labelsets
    total = sum(fb._values.values())
    assert total == 5000  # folding loses cardinality, never mass


# -- heatmap join -------------------------------------------------------------


def _cost(**kw):
    from corrosion_tpu.agent.subs import SubCost

    base = {k: 0 for k in SubCost.COUNTERS}
    base["eval_seconds_candidate"] = 0.0
    base["eval_seconds_fallback"] = 0.0
    base.update(kw)
    base["eval_seconds_total"] = (
        base["eval_seconds_candidate"] + base["eval_seconds_fallback"]
    )
    return base


def _fake_run():
    plain, window = "a" * 32, "b" * 32
    return {
        "oracle": {"violations": 0, "delivered_changes": 16},
        "sub_costs": {
            "enabled": True,
            "ledger": {
                "kind": "corro-sub-cost", "version": 1, "enabled": True,
                "subs_total": 2,
                "totals": {},
                "subs": [
                    {
                        "sub_id": plain,
                        "sql": "SELECT id, text FROM tests",
                        "plan": {"class": "simple", "fallback_bound": False},
                        "cost": _cost(
                            candidate_evals=5, rows_scanned=10,
                            eval_seconds_candidate=0.010, fanout_events=10,
                        ),
                    },
                    {
                        "sub_id": window,
                        "sql": "SELECT min(id) OVER () FROM tests",
                        "plan": {"class": "window", "fallback_bound": True},
                        "cost": _cost(
                            fallback_evals=3, rows_scanned=30,
                            eval_seconds_fallback=0.030, fanout_events=6,
                        ),
                    },
                ],
            },
            "groups": {"0": plain, "1": window},
            "oracle_records": {
                "streams": [
                    {"sid": 0, "group": 0, "label": "s0",
                     "delivered_changes": 10, "delivered_snapshot": 0,
                     "reconnects": 0},
                    {"sid": 1, "group": 1, "label": "w0",
                     "delivered_changes": 6, "delivered_snapshot": 0,
                     "reconnects": 0},
                ],
                "writes": [
                    {"key": k, "group": g, "t_ack_mono": 100.0 + k}
                    for g in (0, 1) for k in range(2)
                ],
                "deliveries": [
                    {"kind": "change", "sid": g, "key": k,
                     "t_mono": 100.0 + k + 0.005}
                    for g in (0, 1) for k in range(2)
                ],
            },
        },
    }


def test_heatmap_join_attribution_and_reconciliation():
    from corrosion_tpu.obs import serving

    rep = serving.build_serving_report(_fake_run())
    assert rep["kind"] == "corro-serving-cost" and rep["streams"] == 2
    # Fallback share: 30ms of 40ms total eval burn.
    assert rep["fallback"]["share_of_eval_seconds"] == 0.75
    assert rep["fallback"]["bound_subs"] == 1
    assert rep["fallback"]["observed"] is True
    # Top-K orders by eval cost: the window sub burned 3x the plain one.
    assert rep["top"][0]["sub_id"] == "b" * 32
    assert rep["top"][0]["eval_ms"] == 30.0
    # Per-class lag percentiles from the (key, group) delivery join.
    assert rep["classes"]["window"]["lag_ms"]["p50"] == pytest.approx(
        5.0, abs=0.5
    )
    # Exact mass reconciliation: ledger fan-out == oracle delivered.
    assert rep["reconciliation"]["ok"]
    assert rep["reconciliation"]["checked"] == 2


def test_heatmap_join_flags_mass_mismatch():
    from corrosion_tpu.obs import serving

    run = _fake_run()
    run["sub_costs"]["oracle_records"]["streams"][1][
        "delivered_changes"
    ] = 7
    rep = serving.build_serving_report(run)
    assert not rep["reconciliation"]["ok"]
    assert "7" in rep["reconciliation"]["mismatches"][0]


def test_heatmap_refuses_run_without_ledger():
    """A heatmap without a ledger would silently attribute nothing —
    the builder refuses instead."""
    from corrosion_tpu.obs import serving

    with pytest.raises(ValueError, match="sub_costs ledger"):
        serving.build_serving_report({"oracle": {"violations": 0}})
    run = _fake_run()
    run["sub_costs"]["oracle_records"]["streams"] = []
    with pytest.raises(ValueError, match="stream records"):
        serving.build_serving_report(run)


def test_ledger_jsonl_roundtrip(tmp_path):
    from corrosion_tpu.obs import serving

    snap = _fake_run()["sub_costs"]["ledger"]
    path = str(tmp_path / "ledger.jsonl")
    serving.write_cost_ledger(path, snap, context={"scenario": "t"})
    back = serving.read_cost_ledger(path)
    assert back["kind"] == "corro-sub-cost" and back["version"] == 1
    assert [r["sub_id"] for r in back["subs"]] == \
           [r["sub_id"] for r in snap["subs"]]
    bad = str(tmp_path / "bad.jsonl")
    with open(bad, "w") as f:
        f.write(json.dumps({"kind": "corro-metric-series"}) + "\n")
    with pytest.raises(ValueError, match="kind"):
        serving.read_cost_ledger(bad)


# -- budget gate --------------------------------------------------------------


def _measured():
    from corrosion_tpu.obs import serving

    run = _fake_run()
    return {
        "platform": "cpu",
        "scenario": "t",
        "streams": 2,
        "run": run,
        "serving": serving.build_serving_report(run),
    }


def _budget(**over):
    return {
        "platform": "cpu", "scenario": "t", "streams": 2,
        "tolerance": 1.5,
        "ceilings_ms": {"serving.eval_ms.total": 100.0},
        "fallback_share_max": 0.9,
        "oracle_violations_max": 0,
        "require_fallback_observed": True,
        "require_mass_reconciled": True,
        **over,
    }


def test_budget_gate_green_on_clean_measurement():
    from corrosion_tpu.obs import serving

    ok, breaches = serving.check_serving_cost_budget(_measured(), _budget())
    assert ok and breaches == []


def test_budget_gate_machinery_fired_rule():
    """A storm where no fallback-bound subscription was ever observed
    evaluating is a HARNESS failure, not a pass."""
    from corrosion_tpu.obs import serving

    m = _measured()
    m["serving"]["fallback"]["observed"] = False
    ok, breaches = serving.check_serving_cost_budget(m, _budget())
    assert not ok
    assert any("test-harness failure" in b for b in breaches)


def test_budget_gate_absolute_rules():
    from corrosion_tpu.obs import serving

    m = _measured()
    m["serving"]["reconciliation"]["ok"] = False
    m["serving"]["reconciliation"]["mismatches"] = ["sub x: 5 != 6"]
    ok, breaches = serving.check_serving_cost_budget(m, _budget())
    assert not ok and any("reconciliation" in b for b in breaches)

    m = _measured()
    m["run"]["oracle"]["violations"] = 2
    ok, breaches = serving.check_serving_cost_budget(m, _budget())
    assert not ok and any("oracle violations" in b for b in breaches)

    m = _measured()
    ok, breaches = serving.check_serving_cost_budget(
        m, _budget(fallback_share_max=0.5)
    )
    assert not ok and any("fallback share" in b for b in breaches)

    m = _measured()
    ok, breaches = serving.check_serving_cost_budget(
        m, _budget(streams=512)
    )
    assert not ok and any("streams" in b for b in breaches)


def test_baseline_diff_regression():
    from corrosion_tpu.obs import serving

    base = _measured()["serving"]
    cand = json.loads(json.dumps(base))
    ok, rows = serving.diff_serving_reports(base, cand)
    assert ok
    cand["eval_ms"]["total"] = base["eval_ms"]["total"] * 10 + 100.0
    ok, rows = serving.diff_serving_reports(base, cand)
    assert not ok
    bad = [r for r in rows if not r["ok"]]
    assert bad and bad[0]["path"] == "eval_ms.total"


# -- live agent: endpoint, replay, kill/relaunch ------------------------------


def test_subs_costs_endpoint(tmp_path):
    """`GET /v1/subs/costs` serves the live corro-sub-cost/1 snapshot;
    bad top= values are a 400, not a 500."""
    from corrosion_tpu.agent.testing import launch_test_agent, poll_until

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"), sub_costs=True)
        try:
            assert a.agent.subs.costs_enabled
            h = a.agent.subs.subscribe(
                "SELECT id, text FROM tests WHERE id % 2 = 0"
            )
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (2, 'x')"]]
            )

            async def seen():
                return a.agent.subs.get(h.id).change_id >= 1

            await poll_until(seen, timeout=10)
            resp = await a.client._request("GET", "/v1/subs/costs?top=5")
            body = await resp.body()
            resp.close()
            assert resp.status == 200
            snap = json.loads(body)
            assert snap["kind"] == "corro-sub-cost" and snap["enabled"]
            rec = next(r for r in snap["subs"] if r["sub_id"] == h.id)
            assert rec["plan"]["class"] == "simple"
            assert rec["cost"]["candidate_evals"] >= 1
            resp = await a.client._request("GET", "/v1/subs/costs?top=zap")
            await resp.body()
            resp.close()
            assert resp.status == 400
            # The aggregates ride /metrics with the kind label.
            text = a.agent.metrics.render()
            assert "corro_subs_eval_seconds" in text
            assert 'kind="candidate"' in text
        finally:
            await a.stop()

    run(main())


def test_ledger_survives_reconnect_replay(tmp_path):
    """A ``?from=`` resume books its replayed rows into the ledger
    (replay mass is part of the reconciliation identity) and the
    counters accumulated before the reconnect survive it."""
    from corrosion_tpu.agent.testing import launch_test_agent, poll_until

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"), sub_costs=True)
        stream = None
        try:
            stream = await a.client.subscribe("SELECT id, text FROM tests")
            async for ev in stream:
                if "eoq" in ev:
                    break
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (1, 'one')"]]
            )
            ev = await stream.__anext__()
            assert "change" in ev
            h = a.agent.subs.get(stream.sub_id)
            pre = h.cost.snapshot()
            assert pre["fanout_events"] >= 1
            # Force a full replay: pretend we saw nothing.
            stream.last_change_id = 0
            await stream.reconnect()
            # The resume re-emits columns first, then the replayed change.
            async for ev in stream:
                if "change" in ev:
                    break
            else:
                raise AssertionError("no change replayed after reconnect")

            async def replayed():
                return h.cost.replay_rows >= 1

            await poll_until(replayed, timeout=10)
            post = h.cost.snapshot()
            assert post["replays"] >= 1
            assert post["fanout_events"] >= pre["fanout_events"]
            assert post["candidate_evals"] >= pre["candidate_evals"]
        finally:
            if stream is not None:
                stream.close()
            await a.stop()

    run(main())


def test_ledger_survives_kill_relaunch(tmp_path):
    """SIGKILL + relaunch adopts the persisted ledger: counters resume
    from what the previous life last persisted instead of zeroing (the
    hostchaos kill_restart scenario proves the same contract under
    storm traffic)."""
    from corrosion_tpu.agent.testing import (
        hard_kill,
        launch_test_agent,
        poll_until,
        relaunch_test_agent,
    )

    async def main():
        a = await launch_test_agent(str(tmp_path / "a"), sub_costs=True)
        b = None
        try:
            h = a.agent.subs.subscribe("SELECT id, text FROM tests")
            sub_id = h.id
            for i in range(3):
                await a.client.execute(
                    [[f"INSERT INTO tests (id, text) VALUES ({i}, 'r{i}')"]]
                )

            async def seen():
                return a.agent.subs.get(sub_id).change_id >= 3

            await poll_until(seen, timeout=10)
            pre = a.agent.subs.get(sub_id).cost.snapshot()
            assert pre["candidate_evals"] >= 1
            await hard_kill(a)
            b = await relaunch_test_agent(a)
            restored = b.agent.subs.get(sub_id)
            assert restored is not None and restored.cost is not None
            post = restored.cost.snapshot()
            # The relaunch re-adopts (>=: restore itself may process a
            # catch-up diff on top of the adopted counters).
            for k in ("candidate_evals", "rows_scanned", "diff_rows"):
                assert post[k] >= pre[k], (k, pre[k], post[k])
            # And keeps accumulating in the new life.
            await b.client.execute(
                [["INSERT INTO tests (id, text) VALUES (99, 'new')"]]
            )

            async def advanced():
                c = b.agent.subs.get(sub_id).cost
                return c.candidate_evals > post["candidate_evals"]

            await poll_until(advanced, timeout=10)
        finally:
            if b is not None:
                await b.stop()
            elif a is not None:
                await a.stop()

    run(main())
