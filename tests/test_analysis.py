"""Static-analysis plane tests (corrosion_tpu/analysis, docs/ANALYSIS.md).

Covers: one triggering fixture per CT0xx rule, a clean fixture with no
false positives, suppression-comment handling (line + scope + mandatory
reason), the static schema-parity check against both a corrupted engine
and the live telemetry module, the lock-order cycle detector, the repo
itself linting clean, and the lint CLI exit codes.
"""

import os
import textwrap

import pytest

from corrosion_tpu.analysis import lint_paths
from corrosion_tpu.analysis.findings import RULES
from corrosion_tpu.analysis.schema import extract_canonical

PKG = os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
) + "/corrosion_tpu"


def _lint_snippet(tmp_path, source, name="snippet.py", **kw):
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return lint_paths([str(p)], **kw)


def _rules(result):
    return [f.rule for f in result.findings]


# -- purity rules (kernel fixtures opt in via the marker comment) --------


def test_ct001_numpy_in_traced_code(tmp_path):
    res = _lint_snippet(tmp_path, """\
        # corro-lint: kernel-module
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return np.asarray(x) + 1
    """)
    assert _rules(res) == ["CT001"]
    assert "np.asarray" in res.findings[0].message


def test_ct002_local_numpy_import(tmp_path):
    res = _lint_snippet(tmp_path, """\
        # corro-lint: kernel-module
        def helper(x):
            import numpy as np
            return np.asarray(x)
    """)
    assert "CT002" in _rules(res)


def test_ct003_dtypeless_literal(tmp_path):
    res = _lint_snippet(tmp_path, """\
        # corro-lint: kernel-module
        import jax.numpy as jnp

        def make():
            a = jnp.zeros((4,))
            b = jnp.array([True])
            c = jnp.zeros((4,), jnp.uint32)  # explicit: fine
            d = jnp.full((4,), -1, jnp.int32)  # explicit: fine
            return a, b, c, d
    """)
    assert _rules(res) == ["CT003", "CT003"]


def test_ct004_traced_value_coercion(tmp_path):
    res = _lint_snippet(tmp_path, """\
        # corro-lint: kernel-module
        import jax

        def run(xs, carry):
            def body(c, x):
                v = float(x)
                return c + x.item(), v
            return jax.lax.scan(body, carry, xs)
    """)
    assert sorted(_rules(res)) == ["CT004", "CT004"]


def test_ct005_python_branch_on_traced_param(tmp_path):
    res = _lint_snippet(tmp_path, """\
        # corro-lint: kernel-module
        import jax

        def run(xs, carry):
            def body(c, x):
                if x > 0:
                    c = c + 1
                while c:
                    c = c - 1
                return c, ()
            return jax.lax.scan(body, carry, xs)
    """)
    assert sorted(_rules(res)) == ["CT005", "CT005"]


def test_ct005_exempts_static_argnames_shape_and_is_none(tmp_path):
    res = _lint_snippet(tmp_path, """\
        # corro-lint: kernel-module
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("cfg",))
        def step(x, topo, cfg):
            if cfg:
                x = x + 1
            if x.shape[0] == 0:
                x = x * 2
            if topo is None:
                x = x * 3
            return x
    """)
    assert _rules(res) == []


def test_clean_kernel_fixture_has_no_findings(tmp_path):
    res = _lint_snippet(tmp_path, """\
        # corro-lint: kernel-module
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            mask = jnp.zeros(x.shape, dtype=bool)
            return jnp.where(mask, x, jnp.uint32(0))

        def also_kernel(n):
            # presumed traced (kernel module) but violation-free
            return jnp.full((4,), n, jnp.int32)
    """)
    assert res.findings == []
    assert res.suppressed == []


# -- suppressions --------------------------------------------------------


def test_line_suppression_with_reason(tmp_path):
    res = _lint_snippet(tmp_path, """\
        # corro-lint: kernel-module
        import jax.numpy as jnp

        def make():
            return jnp.zeros((4,))  # corro-lint: disable=CT003 reason=legacy
    """)
    assert res.findings == []
    assert [f.rule for f in res.suppressed] == ["CT003"]
    assert res.suppressed[0].suppress_reason == "legacy"


def test_scope_suppression_covers_whole_function(tmp_path):
    res = _lint_snippet(tmp_path, """\
        # corro-lint: kernel-module
        import jax
        import numpy as np

        # corro-lint: disable=CT001,CT004 reason=host-side reference
        @jax.jit
        def ground_truth(x):
            a = np.asarray(x)
            return int(a.sum())
    """)
    assert res.findings == []
    assert sorted(f.rule for f in res.suppressed) == ["CT001", "CT004"]


def test_suppression_without_reason_is_ignored_and_flagged(tmp_path):
    res = _lint_snippet(tmp_path, """\
        # corro-lint: kernel-module
        import jax.numpy as jnp

        def make():
            return jnp.zeros((4,))  # corro-lint: disable=CT003
    """)
    assert sorted(_rules(res)) == ["CT000", "CT003"]


def test_suppression_with_unknown_rule_id_is_flagged(tmp_path):
    res = _lint_snippet(tmp_path, """\
        x = 1  # corro-lint: disable=CT999 reason=nope
    """)
    assert _rules(res) == ["CT000"]


# -- schema parity (CT010) ----------------------------------------------


def test_static_canonical_matches_runtime_telemetry():
    """The restricted evaluator must agree with the imported module —
    otherwise the parity lint silently checks a stale schema."""
    from corrosion_tpu.sim import telemetry as T

    canon = extract_canonical(os.path.join(PKG, "sim", "telemetry.py"))
    assert canon["ROUND_CURVE_KEYS"] == T.ROUND_CURVE_KEYS
    assert canon["VIS_LAT_KEYS"] == T.VIS_LAT_KEYS
    assert canon["HEALTH_CURVE_KEYS"] == T.HEALTH_CURVE_KEYS


def test_corrupting_an_engine_key_set_is_caught_statically(tmp_path):
    """The acceptance check: inject an off-schema key into a real
    engine's round_curves call and the lint must fail before any run."""
    src = open(os.path.join(PKG, "sim", "engine.py")).read()
    bad = src.replace("msgs=bstats[\"msgs\"],", "msgz=bstats[\"msgs\"],")
    assert bad != src
    res = _lint_snippet(tmp_path, bad, name="sim/engine.py")
    assert "CT010" in _rules(res)
    assert any("msgz" in f.message for f in res.findings)


def test_engine_module_without_round_curves_is_flagged(tmp_path):
    res = _lint_snippet(tmp_path, """\
        # corro-lint: engine-module
        def simulate():
            return {"msgs": 0}
    """)
    assert "CT010" in _rules(res)


def test_unresolvable_star_expansion_is_flagged(tmp_path):
    res = _lint_snippet(tmp_path, """\
        # corro-lint: engine-module
        from corrosion_tpu.sim import telemetry as T

        def simulate(mystery):
            return T.round_curves(msgs=1, **mystery)
    """)
    assert "CT010" in _rules(res)


@pytest.fixture(scope="module")
def repo_lint():
    """One lint walk of the package shared by the repo-wide tests."""
    return lint_paths([PKG])


def test_static_engine_key_sets_agree_with_runtime_parity(repo_lint):
    """All four engines' statically-extracted emissions are within the
    canonical set, every engine is seen, and — because round_curves
    zero-fills — the static check agrees with the runtime parity test
    (tests/test_kernel_telemetry.py) that the final key sets are
    identical."""
    res = repo_lint
    assert sorted(res.engines) == [
        "chunk_engine", "engine", "mixed_engine", "sparse_engine"
    ]
    canon = set(res.canonical_keys)
    assert canon
    for name, keys in res.engines.items():
        assert keys, name
        assert set(keys) <= canon, name
    # Delivery-latency histogram expansions resolved statically for all.
    for name in ("engine", "sparse_engine", "chunk_engine", "mixed_engine"):
        assert "vis_lat_b0" in res.engines[name], name


# -- concurrency (CT020/CT021) ------------------------------------------


def test_ct020_blocking_call_under_lock(tmp_path):
    res = _lint_snippet(tmp_path, """\
        import threading
        import time

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()

            def slow(self):
                with self._lock:
                    time.sleep(1.0)

            def fine(self):
                time.sleep(1.0)  # not under a lock
                with self._lock:
                    pass
    """)
    assert _rules(res) == ["CT020"]
    assert "Worker._lock" in res.findings[0].message


def test_ct021_lock_order_cycle(tmp_path):
    res = _lint_snippet(tmp_path, """\
        import threading

        class Pair:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def ab(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def ba(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
    """)
    assert _rules(res) == ["CT021"]
    assert "cycle" in res.findings[0].message


def test_ct021_one_hop_call_propagation(tmp_path):
    res = _lint_snippet(tmp_path, """\
        import threading

        class Pair:
            def ab(self):
                with self._a_lock:
                    self.take_b()

            def take_b(self):
                with self._b_lock:
                    pass

            def ba(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
    """)
    assert _rules(res) == ["CT021"]


def test_consistent_lock_order_is_clean(tmp_path):
    res = _lint_snippet(tmp_path, """\
        import threading

        class Pair:
            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def two(self):
                with self._a_lock:
                    with self._b_lock:
                        pass
    """)
    assert res.findings == []


# -- the repo itself -----------------------------------------------------


def test_repo_lints_clean(repo_lint):
    """Acceptance: `corrosion lint corrosion_tpu/` exits 0 at HEAD —
    every finding fixed or reason-suppressed."""
    res = repo_lint
    assert res.findings == [], "\n".join(f.render() for f in res.findings)
    # Suppressions must all carry reasons (CT000 would have fired above
    # otherwise); spot-check they exist where designed.
    assert any(f.path.endswith("ops/gossip.py") for f in res.suppressed)


def test_rule_registry_is_documented():
    doc = open(os.path.join(os.path.dirname(PKG), "docs", "ANALYSIS.md"))
    text = doc.read()
    for rid in RULES:
        assert rid in text, f"{rid} missing from docs/ANALYSIS.md"


# -- retrace tripwire plumbing ------------------------------------------


def test_retrace_tripwire_flags_multi_compile(monkeypatch):
    """Positive control for CT030 without paying for a real retrace:
    point the dense runner at a stub module whose jitted fn reports two
    cache entries."""
    from corrosion_tpu.analysis import sanitize as S

    class FakeJitted:
        def __call__(self):
            pass

        def _cache_size(self):
            return 2

    class FakeModule:
        __name__ = "fake_engine"
        scan = FakeJitted()

    monkeypatch.setitem(S._RUNNERS, "dense", lambda: FakeModule)
    findings = S.sanitize_engines(("dense",), strict_dtypes=False,
                                  check_nans=False)
    assert [f.rule for f in findings] == ["CT030"]
    assert "compiled 2 times" in findings[0].message


def test_sanitizer_classifies_non_promotion_failures_as_ct033(monkeypatch):
    """A crash that is not a TypePromotionError must not masquerade as a
    strict-dtype finding (CT031) — triage would chase phantom dtypes."""
    from corrosion_tpu.analysis import sanitize as S

    def broken_runner():
        raise ValueError("tiny config exploded")

    monkeypatch.setitem(S._RUNNERS, "dense", broken_runner)
    findings = S.sanitize_engines(("dense",), strict_dtypes=False,
                                  check_nans=False)
    assert [f.rule for f in findings] == ["CT033"]
    assert "tiny config exploded" in findings[0].message


# -- CLI ----------------------------------------------------------------


def test_lint_cli_exit_codes(tmp_path, capsys):
    from corrosion_tpu import cli

    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert cli.main(["lint", str(clean)]) == 0

    dirty = tmp_path / "dirty.py"
    dirty.write_text(
        "# corro-lint: kernel-module\n"
        "import jax.numpy as jnp\n"
        "def f():\n"
        "    return jnp.zeros((4,))\n"
    )
    assert cli.main(["lint", str(dirty)]) == 1
    assert cli.main(["lint", "--format=json", str(dirty)]) == 1
    out = capsys.readouterr().out
    assert '"CT003"' in out

    assert cli.main(["lint", "--list-rules"]) == 0
    assert cli.main(["lint", "--rules", "NOPE", str(clean)]) == 2
