"""Seq-granular chunk kernel: the large_tx_sync analogue.

Mirrors the reference's large-transaction tests (agent.rs:3340 large_tx_sync:
one 10k-row INSERT chunked into seq ranges, late/lossy receivers reassemble
via buffering + partial-need sync) and the buffering semantics of
agent.rs:2063-2151 (out-of-order chunks, gap tracking, apply when gap-free).
"""

import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.core.changes import chunk_changes
from corrosion_tpu.core.values import Change
from corrosion_tpu.ops import chunks


def run(cfg, origin, last_seq, rounds, seed=0, alive=None):
    state = chunks.init_chunks(cfg, origin, last_seq)
    alive = jnp.ones(cfg.n_nodes, bool) if alive is None else alive
    key = jax.random.PRNGKey(seed)
    stats = None
    for r in range(rounds):
        key, k = jax.random.split(key)
        state, stats = chunks.chunk_round(
            state, last_seq, alive, jnp.int32(r), k, cfg
        )
    return state, stats


def test_large_tx_reassembles_cluster_wide():
    # 10k-seq transaction from one origin; chunked gossip + partial sync.
    cfg = chunks.ChunkConfig(
        n_nodes=12, n_streams=1, chunk_len=512, fanout=3,
        sync_interval=3, gap_requests=6,
    )
    origin = jnp.array([0], jnp.int32)
    last_seq = jnp.array([9999], jnp.int32)
    state, _ = run(cfg, origin, last_seq, rounds=40)
    applied = np.asarray(chunks.applied_mask(state, last_seq, cfg))
    assert applied.all(), "every node reassembles the full 10k-seq tx"


def test_lossy_out_of_order_delivery_heals():
    cfg = chunks.ChunkConfig(
        n_nodes=10, n_streams=2, chunk_len=128, fanout=3,
        loss_prob=0.4, sync_interval=4, gap_requests=8,
    )
    origin = jnp.array([0, 7], jnp.int32)
    last_seq = jnp.array([4095, 2047], jnp.int32)
    state, _ = run(cfg, origin, last_seq, rounds=80, seed=3)
    applied = np.asarray(chunks.applied_mask(state, last_seq, cfg))
    assert applied.all(), "40% loss is healed by gap-request sync"


def test_partial_coverage_tracks_gaps_until_complete():
    cfg = chunks.ChunkConfig(
        n_nodes=6, n_streams=1, chunk_len=64, fanout=1,
        sync_interval=1000, gap_requests=0,  # no sync: broadcast only
    )
    origin = jnp.array([2], jnp.int32)
    last_seq = jnp.array([8191], jnp.int32)
    state, _ = run(cfg, origin, last_seq, rounds=3, seed=1)
    applied = np.asarray(chunks.applied_mask(state, last_seq, cfg))
    # Origin is complete by construction; 3 rounds of 64-seq chunks cannot
    # complete 8192 seqs anywhere else.
    assert applied[2, 0]
    assert applied.sum() == 1
    # But partial coverage exists somewhere beyond the origin.
    live = np.asarray(state.have.starts <= state.have.ends).reshape(6, 1, -1)
    assert live.any(axis=-1).sum() > 1


def test_dead_nodes_do_not_participate():
    cfg = chunks.ChunkConfig(
        n_nodes=8, n_streams=1, chunk_len=256, fanout=3, sync_interval=2,
    )
    origin = jnp.array([1], jnp.int32)
    last_seq = jnp.array([1023], jnp.int32)
    alive = jnp.ones(8, bool).at[5].set(False)
    state, _ = run(cfg, origin, last_seq, rounds=30, alive=alive)
    applied = np.asarray(chunks.applied_mask(state, last_seq, cfg))
    assert not applied[5, 0], "dead node receives nothing"
    assert applied[np.arange(8) != 5, 0].all()


def test_host_chunker_ranges_feed_kernel_semantics():
    # The host-side ChunkedChanges tiling produces exactly the seq ranges the
    # kernel models: tile a 10k-row tx, shuffle, insert into one coverage
    # set, and confirm gap-free completion — chunker/kernel agreement.
    rows = [
        Change(table="t", pk=b"k%d" % i, cid="c", val=i, col_version=1,
               db_version=1, seq=i, site_id=b"\x00" * 16, cl=1)
        for i in range(10_000)
    ]
    ranges = [rng for _, rng in chunk_changes(rows, last_seq=9999)]
    assert ranges[0][0] == 0 and ranges[-1][1] == 9999
    rng = np.random.default_rng(0)
    rng.shuffle(ranges)
    from corrosion_tpu.ops import intervals

    iv = intervals.make(64)
    for s, e in ranges:
        iv = intervals.insert(iv, jnp.int32(s), jnp.int32(e))
    assert int(intervals.contiguous_watermark(iv, jnp.int32(0))) == 9999


def test_chunk_engine_baseline_converges_small():
    """Config 3b at toy scale: multi-chunk transactions reassemble
    cluster-wide through chunk gossip + partial-need sync (the engine-scale
    driver, sim/chunk_engine.py)."""
    from corrosion_tpu.ops.chunks import ChunkConfig
    from corrosion_tpu.sim.chunk_engine import simulate_chunks

    cfg = ChunkConfig(
        n_nodes=48, n_streams=4, cap=16, chunk_len=64,
        fanout=3, k_in=6, sync_interval=4, gap_requests=4,
        sync_seq_budget=1024,
    )
    origin = [0, 11, 23, 40]
    last_seq = [1023, 1023, 511, 2047]
    _, m = simulate_chunks(cfg, origin, last_seq, rounds=200, seed=3)
    assert m["unapplied"] == 0, m
    assert m["p99_s"] <= 200 * 0.5
    assert m["seqs_granted"] > 0  # partial-need sync actually served gaps
