"""Device-cost observability plane (obs/costs.py, obs/ledger.py,
obs/trajectory.py).

Coverage, per the plane's contract:

- cost-model units: per-plane roofline flops/bytes are positive
  increments, entries key on config fingerprint, the donated scan twin
  costs the same flops as the plain one (donation changes aliasing,
  never arithmetic);
- predicted-vs-measured per-device byte reconciliation on the
  8-virtual-device CPU mesh — the spec-arithmetic prediction must equal
  the live addressable shards to the byte, and breaks RAISE;
- compile-ledger determinism: exactly one compile per config per scan
  entry, a second identical run compiles nothing even with the
  tripwire armed;
- a retrace-tripwire positive control (a fresh shape under an armed
  ledger must raise RetraceError);
- bench-trajectory provenance: cross-platform artifacts (the r05 CPU
  fallback shape) are mechanically flagged and deltas across the break
  are refused.

Heavy AOT lowerings (one full engine compile each) are slow-marked into
the bench-smoke CI job; the in-lane tests share the perf-plane's tiny
cluster config so the suite compiles its scan once.
"""

import json

import jax
import jax.numpy as jnp
import pytest

from corrosion_tpu.obs import costs, trajectory
from corrosion_tpu.obs import ledger as ledger_mod
from corrosion_tpu.sim import benchlib, telemetry

from test_perf_plane import _tiny_cluster  # shared compiled config


# ---------------------------------------------------------------------------
# Compile ledger


def test_ledger_one_compile_per_config_and_armed_second_run():
    """Determinism: a chunked run compiles its donated scan exactly
    once; an identical re-run adds zero compiles even with the
    steady-state tripwire ARMED (the live analogue of sanitize CT030)."""
    from corrosion_tpu.sim import engine as engine_mod
    from corrosion_tpu.sim.engine import simulate

    cfg, topo, sched = _tiny_cluster(rounds=9)
    # Targeted cache clear (not jax.clear_caches(): other modules'
    # compiles stay warm) so "exactly one compile" is exact regardless
    # of what ran earlier in the session.
    engine_mod._scan_rounds.clear_cache()
    engine_mod._scan_rounds_donated.clear_cache()
    led = ledger_mod.CompileLedger().watch(engine_mod).install()
    try:
        with led.window("warm") as w:
            simulate(cfg, topo, sched, seed=0, max_chunk=3)
        # Exactly one compiled executable per config per entry: the
        # uniform chunking reuses ONE donated scan — and the plain twin
        # must not have compiled alongside it.
        assert w.fns == {"_scan_rounds_donated": 1}
        assert w.compiles >= 1 and w.compile_ms > 0
        assert engine_mod._scan_rounds_donated._cache_size() == 1
        assert engine_mod._scan_rounds._cache_size() == 0
        led.arm("identical re-run must be compile-free")
        with led.window("steady") as w2:
            simulate(cfg, topo, sched, seed=0, max_chunk=3)
        assert w2.compiles == 0 and not w2.fns
        assert led.armed_compiles == 0
    finally:
        led.disarm()
        led.uninstall()


def test_nested_window_and_publish_count_each_compile_once():
    """The documented pattern — a KernelTelemetry per-chunk sink
    running INSIDE a caller's own ledger window, then a run-end
    publish() — must count every compile exactly once: nested windows
    are inert placeholders (no per-chunk re-count of the outer scope's
    cumulative totals, no premature flight records) and publish() skips
    windows a live sink already emitted."""
    from corrosion_tpu.sim import engine as engine_mod
    from corrosion_tpu.sim.engine import simulate
    from corrosion_tpu.utils.metrics import MetricsRegistry

    cfg, topo, sched = _tiny_cluster(rounds=9)
    # Guarantee the run compiles inside the window (non-vacuous even
    # when an earlier test warmed this config).
    engine_mod._scan_rounds_donated.clear_cache()
    led = ledger_mod.CompileLedger().watch(engine_mod).install()
    registry = MetricsRegistry()
    try:
        tele = telemetry.KernelTelemetry(
            engine="dense", registry=registry, ledger=led
        )
        with led.window("outer") as outer:
            simulate(cfg, topo, sched, seed=0, max_chunk=3, telemetry=tele)
        led.publish(registry, engine="dense")
        led.publish(registry, engine="dense")  # idempotent
    finally:
        led.uninstall()
    total = sum(
        registry.counter("corro_kernel_compiles_total").get(
            engine="dense", fn=fn
        )
        for fn in list(outer.fns) + ["(unwatched)"]
    )
    # Every backend compile of the run counted exactly once, all owned
    # by the outer window (the three chunk windows were inert).
    assert total == outer.compiles
    assert [w for w in led.windows if not w.nested] == [outer]
    ms = registry.counter("corro_kernel_compile_ms").get(engine="dense")
    assert ms == pytest.approx(outer.compile_ms)


def test_retrace_tripwire_positive_control():
    """An armed ledger must RAISE on a genuinely fresh compile."""
    f = jax.jit(lambda x: x * 3 + 1)
    f(jnp.ones(4))
    led = ledger_mod.CompileLedger().install()
    try:
        led.arm("positive control")
        f(jnp.ones(4))  # cached: fine
        with pytest.raises(ledger_mod.RetraceError, match="armed"):
            f(jnp.ones(5))  # fresh shape: compile under arms
        assert led.armed_compiles == 1
    finally:
        led.disarm()
        led.uninstall()


def test_ledger_shared_registry_matches_sanitize_discovery():
    """One registry: the ledger watches exactly the functions the
    sanitize CT030 tripwire inspects (anything with jax's _cache_size),
    donated twins included."""
    from corrosion_tpu.sim import engine as engine_mod

    fns = ledger_mod.jitted_functions(engine_mod)
    for name in ("cluster_round", "cluster_round_donated",
                 "_scan_rounds", "_scan_rounds_donated"):
        assert name in fns
    sizes = ledger_mod.cache_sizes(fns)
    assert set(sizes) == set(fns)


def test_ledger_compile_records_reach_flight_and_metrics(tmp_path):
    """The KernelTelemetry integration: a chunk that compiles writes a
    ``kind: "compile"`` flight record and counts into
    corro_kernel_compiles_total; replay_flight stays intact."""
    from corrosion_tpu.sim import engine as engine_mod
    from corrosion_tpu.sim.engine import simulate
    from corrosion_tpu.utils.metrics import MetricsRegistry

    cfg, topo, sched = _tiny_cluster(rounds=9)
    # A distinct chunk length forces one fresh scan compile so the
    # window has something to record even in a warm session.
    path = str(tmp_path / "flight.jsonl")
    led = ledger_mod.CompileLedger().watch(engine_mod).install()
    registry = MetricsRegistry()
    try:
        with telemetry.FlightRecorder(path, engine="dense") as rec:
            tele = telemetry.KernelTelemetry(
                engine="dense", recorder=rec, registry=registry,
                ledger=led,
            )
            simulate(cfg, topo, sched, seed=0, max_chunk=9, telemetry=tele)
    finally:
        led.uninstall()
    lines = [json.loads(x) for x in open(path) if x.strip()]
    compiles = [x for x in lines if x.get("kind") == "compile"]
    assert compiles, "the compiling chunk must leave a ledger record"
    assert compiles[0]["compiles"] >= 1
    assert compiles[0]["compile_ms"] > 0
    got = registry.counter("corro_kernel_compiles_total").get(
        engine="dense", fn="_scan_rounds_donated"
    )
    assert got >= 1
    # The out-of-band record must not disturb curve replay.
    curves, chunks = telemetry.replay_flight(path)
    assert len(curves["round"]) == 9 and len(chunks) == 1


def test_flight_record_event_refuses_reserved_kinds(tmp_path):
    path = str(tmp_path / "f.jsonl")
    with telemetry.FlightRecorder(path) as rec:
        rec.record_event({"kind": "compile", "compiles": 1})
        with pytest.raises(ValueError, match="reserved"):
            rec.record_event({"kind": "round", "round": 0})


# ---------------------------------------------------------------------------
# Roofline stage costs + report arithmetic


def test_roofline_stage_costs_are_positive_increments():
    """Cumulative-prefix cost extraction on a hand composite: each
    stage's flops/bytes are the increment of the single-step lowering,
    positive when the stage does work."""

    def composite(enabled):
        def step(carry, i):
            x, y = carry
            if "mul" in enabled:
                x = x * 2 + 1
            if "dot" in enabled:
                y = y + x @ x
            return x, y

        return step

    carry0 = (jnp.ones((16, 16), jnp.float32),
              jnp.zeros((16, 16), jnp.float32))
    sc = costs.roofline_stage_costs(composite, ("mul", "dot"), carry0)
    assert set(sc) == {"mul", "dot"}
    for s in sc.values():
        assert s["flops"] > 0 and s["bytes"] >= 0
    # The dot stage dominates flops and moves extra bytes (the
    # elementwise stage fuses into the carry copy: byte delta 0 is
    # legitimate for it — the identity prefix already moves the carry).
    assert sc["dot"]["flops"] > sc["mul"]["flops"]
    assert sc["dot"]["bytes"] > 0


def test_roofline_report_rates_derive_from_emitted_numbers():
    sc = {"broadcast": {"flops": 2e6, "bytes": 4e6}}
    roof = benchlib.roofline_report(sc, {"broadcast": 50.0})
    b = roof["broadcast"]
    assert b["flops_per_s"] == pytest.approx(2e6 / 0.05)
    assert b["bytes_per_s"] == pytest.approx(4e6 / 0.05)
    assert b["intensity"] == pytest.approx(0.5)
    # A zero-ms plane publishes null rates, not infinities.
    roof0 = benchlib.roofline_report(sc, {"broadcast": 0.0})
    assert roof0["broadcast"]["flops_per_s"] is None


# ---------------------------------------------------------------------------
# Cost model entries (heavy AOT lowerings -> bench-smoke CI job)


@pytest.mark.slow  # one full engine AOT compile per variant (~25 s)
def test_cost_entry_donated_twin_equals_plain_dense():
    """Donation changes buffer aliasing, never arithmetic: the donated
    scan's flops equal the plain twin's EXACTLY, bytes within the
    copy-elision margin, and the donated entry actually aliases."""
    plain = costs.cost_entry("dense", "plain", device_count=1)
    donated = costs.cost_entry("dense", "donated", device_count=1)
    assert plain["flops"] == donated["flops"]
    assert donated["bytes_accessed"] <= plain["bytes_accessed"] * 1.01
    assert donated["alias_bytes"] > 0 and plain["alias_bytes"] == 0
    assert plain["config_fingerprint"] == donated["config_fingerprint"]
    for e in (plain, donated):
        assert e["flops"] > 0 and e["bytes_accessed"] > 0
        assert e["peak_bytes"] > 0 and e["rounds"] > 0


@pytest.mark.slow  # four engine compiles on the virtual mesh (~60 s)
def test_cost_entries_all_engines_sharded_and_keyed():
    """Every engine lowers at D=8 on the (dcn, ici) mesh with positive
    flops/bytes, and entries key on distinct config fingerprints."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    entries = {
        eng: costs.cost_entry(eng, "plain", device_count=8)
        for eng in costs.ENGINES
    }
    fps = {e["config_fingerprint"] for e in entries.values()}
    assert len(fps) == len(entries), "fingerprints must key per config"
    for eng, e in entries.items():
        assert e["flops"] > 0 and e["bytes_accessed"] > 0, eng
        assert e["device_count"] == 8


@pytest.mark.slow  # engine-composite prefixes (~30 s of single-step AOT)
def test_engine_roofline_every_plane_positive():
    """The real plane composite: every timed stage does positive
    flops AND bytes — a zero plane means the prefix wiring broke."""
    from corrosion_tpu.sim.engine import simulate

    cfg, topo, sched = _tiny_cluster(rounds=9)
    final, _ = simulate(cfg, topo, sched, seed=0, max_chunk=3)
    composite, stages, carry0 = benchlib.plane_composite(
        cfg, topo, sched, final
    )
    sc = costs.roofline_stage_costs(composite, stages, carry0)
    assert set(sc) == set(benchlib.PLANE_STAGES)
    for name, s in sc.items():
        assert s["flops"] > 0, name
        assert s["bytes"] > 0, name


def test_cost_model_diff_gates_regressions_and_fingerprints():
    """The baseline diff: metric increases beyond tolerance breach,
    decreases are notes, missing entries breach, fingerprint drift
    breaches, cross-platform comparison is refused outright."""
    base = {
        "schema": costs.COST_SCHEMA, "platform": "cpu",
        "backend": "native", "jax_version": "x", "tolerance": 0.25,
        "entries": {
            "dense/plain/d1": {
                "config_fingerprint": "aa", "flops": 1000.0,
                "bytes_accessed": 2000.0, "peak_bytes": 300,
                "temp_bytes": 100,
            },
            "sparse/plain/d1": {
                "config_fingerprint": "bb", "flops": 10.0,
                "bytes_accessed": 10.0, "peak_bytes": 10,
                "temp_bytes": 1,
            },
        },
    }
    ok_cand = json.loads(json.dumps(base))
    ok, breaches, _ = costs.diff_cost_models(base, ok_cand)
    assert ok and not breaches
    # +50% flops on one entry breaches; -50% is a note.
    worse = json.loads(json.dumps(base))
    worse["entries"]["dense/plain/d1"]["flops"] = 1500.0
    worse["entries"]["sparse/plain/d1"]["flops"] = 5.0
    ok, breaches, notes = costs.diff_cost_models(base, worse)
    assert not ok and any("dense/plain/d1.flops" in b for b in breaches)
    assert any("improved" in n for n in notes)
    # Missing entry + fingerprint drift breach.
    drift = json.loads(json.dumps(base))
    del drift["entries"]["sparse/plain/d1"]
    drift["entries"]["dense/plain/d1"]["config_fingerprint"] = "zz"
    ok, breaches, _ = costs.diff_cost_models(base, drift)
    joined = "\n".join(breaches)
    assert "missing from measurement" in joined
    assert "fingerprint" in joined
    # Cross-platform refusal, the house provenance rule.
    tpu = json.loads(json.dumps(base))
    tpu["platform"] = "tpu"
    ok, breaches, _ = costs.diff_cost_models(base, tpu)
    assert not ok and "platform" in "\n".join(breaches)


# ---------------------------------------------------------------------------
# Per-device memory: prediction, watermarks, reconcile-or-fail


def test_predicted_per_device_bytes_exact_on_8dev_mesh():
    """The spec-arithmetic prediction equals the live addressable
    shards TO THE BYTE on the 8-virtual-device (dcn, ici) mesh, the
    watermark covers the state, and a doctored prediction FAILS the
    reconcile (break, not skew)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from corrosion_tpu import models, parallel
    from corrosion_tpu.parallel import mesh as mesh_mod
    from corrosion_tpu.sim import engine

    cfg, topo, sched = models.merge_10k(n=32, rounds=8, samples=8)
    mesh = benchlib.multichip_mesh(8)
    state = mesh_mod.shard_cluster_state(
        engine.init_cluster(cfg, len(sched.sample_writer)), mesh
    )
    predicted = costs.predicted_state_bytes(
        cfg, len(sched.sample_writer), mesh
    )
    measured = parallel.per_device_state_bytes(state)
    assert len(measured) == 8
    assert all(v == predicted for v in measured.values()), (
        predicted, sorted(measured.values())
    )
    wm = costs.MemoryWatermarks()
    wm.sample()
    rep = costs.reconcile_memory(
        state, watermarks=wm, predicted_per_device=predicted
    )
    assert rep["devices"] == 8
    assert rep["state_bytes_per_device_max"] == predicted
    with pytest.raises(ValueError, match="predicted"):
        costs.reconcile_memory(
            state, watermarks=wm,
            predicted_per_device=predicted + 10_000,
        )


def test_watermarks_sampled_at_chunk_boundaries_cover_state():
    """The KernelTelemetry integration on a real chunked run: the
    per-device live high-water mark sampled at chunk boundaries covers
    the final state's own bytes, and an UNSAMPLED watermark fails the
    reconcile rather than passing vacuously."""
    from corrosion_tpu.sim.engine import simulate

    cfg, topo, sched = _tiny_cluster(rounds=9)
    wm = costs.MemoryWatermarks()
    tele = telemetry.KernelTelemetry(engine="dense", watermarks=wm)
    final, _ = simulate(
        cfg, topo, sched, seed=0, max_chunk=3, telemetry=tele
    )
    assert wm.samples == 3  # one per chunk boundary
    rep = costs.reconcile_memory(final, watermarks=wm)
    assert rep["state_bytes_per_device_max"] > 0
    with pytest.raises(ValueError, match="never sampled"):
        costs.reconcile_memory(
            final, watermarks=costs.MemoryWatermarks()
        )


@pytest.mark.slow  # sharded engine run + sharded AOT entry (~45 s)
def test_sharded_run_reconciles_against_memory_analysis():
    """The full three-way reconcile on the 8-virtual-device mesh: live
    watermarks vs spec-arithmetic prediction vs the lowered entry's
    memory_analysis — all on the SAME tiny config the cost model
    fixes."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from corrosion_tpu import models, parallel

    cfg, topo, sched = models.merge_10k(n=32, rounds=8, samples=8)
    mesh = benchlib.multichip_mesh(8)
    wm = costs.MemoryWatermarks()
    tele = telemetry.KernelTelemetry(engine="dense", watermarks=wm)
    final, _ = parallel.simulate_sharded(
        cfg, topo, sched, mesh, seed=0, telemetry=tele
    )
    predicted = costs.predicted_state_bytes(
        cfg, len(sched.sample_writer), mesh
    )
    entry = costs.cost_entry("dense", "plain", device_count=8)
    rep = costs.reconcile_memory(
        final, watermarks=wm, predicted_per_device=predicted, cost=entry
    )
    assert rep["state_bytes_per_device_max"] == predicted
    # And the lowered entry's output really covers the state.
    assert entry["output_bytes"] >= predicted


# ---------------------------------------------------------------------------
# Capacity curve


def test_capacity_model_validates_both_measured_points():
    """The corro-capacity/1 artifact: the 512-node lane point must
    reconcile byte-exact against a live placement, the recorded 100k
    point within tolerance, and the curve must cover the ROADMAP
    500k-800k window with per-device bytes strictly increasing."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    model = costs.capacity_model()
    assert model["schema"] == costs.CAPACITY_SCHEMA
    assert model["validation"]["lane_512"]["exact"]
    assert model["validation"]["large_100k"]["relative_error"] < 0.05
    mib = [row["per_device_mib"] for row in model["curve"]]
    assert mib == sorted(mib) and len(set(mib)) == len(mib)
    nodes = [row["nodes"] for row in model["curve"]]
    assert any(n >= 800_000 for n in nodes)
    for row in model["curve"]:
        assert row["verdict"] in ("fits", "tight", "exceeds")
    # Marginal cluster-state bytes per node (the docs/SCALING.md
    # "Memory capacity" figure) rides the artifact.
    assert 1_000 < model["state_bytes_per_node"] < 50_000


def test_predicted_bytes_rejects_unplaceable_dimension():
    """A shape whose SHARDED DIMENSION does not divide the mesh factor
    is unplaceable (jax.device_put would refuse it) — the prediction
    must raise, even when the leaf's total BYTES happen to divide."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from jax.sharding import PartitionSpec as P

    from corrosion_tpu.parallel import mesh as mesh_mod

    mesh = benchlib.multichip_mesh(8)
    good = jax.ShapeDtypeStruct((16, 2), jnp.float32)
    assert mesh_mod.predicted_per_device_bytes(
        [good], [P(mesh_mod._node_axis(mesh, None), None)], mesh
    ) == 16 * 2 * 4 // 8
    bad = jax.ShapeDtypeStruct((12, 2), jnp.float32)  # 96 B divides 8...
    with pytest.raises(ValueError, match="not expressible"):
        mesh_mod.predicted_per_device_bytes(
            [bad], [P(mesh_mod._node_axis(mesh, None), None)], mesh
        )  # ...but dimension 0 (12) does not divide the mesh factor


def test_capacity_model_fails_on_contradicted_measurement(monkeypatch):
    """A model that contradicts its measured point must refuse to emit
    the artifact (reconcile-or-fail, not a skewed curve)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    monkeypatch.setitem(
        costs.MEASURED_100K, "per_device_bytes", 300.0 * 2**20
    )
    with pytest.raises(ValueError, match="100k point"):
        costs.capacity_model(node_counts=(100_352,))


# ---------------------------------------------------------------------------
# Bench trajectory


def _wrap(path, n, parsed, tail=""):
    path.write_text(json.dumps(
        {"n": n, "cmd": "bench", "rc": 0, "tail": tail, "parsed": parsed}
    ))


def test_trajectory_flags_platform_fallback_and_refuses_delta(tmp_path):
    """The r05 shape, mechanically: a TPU 10k artifact followed by a
    CPU 512 artifact under the same metric name is a comparability
    break — flagged, no delta computed across it — while matched
    artifacts get deltas."""
    _wrap(tmp_path / "BENCH_r01.json", 1, {
        "metric": "p99", "value": 7.0, "unit": "s", "step_ms": 500.0,
    }, tail='[bench] {"platform": "tpu", "nodes": 10000}\n')
    _wrap(tmp_path / "BENCH_r02.json", 2, {
        "metric": "p99", "value": 6.5, "unit": "s", "step_ms": 180.0,
    }, tail='[bench] {"platform": "tpu", "nodes": 10000}\n')
    _wrap(tmp_path / "BENCH_r03.json", 3, {
        "metric": "p99", "value": 2.5, "unit": "s", "step_ms": 1189.1,
        "platform": "cpu", "nodes": 512,
    })
    traj = trajectory.build_trajectory(str(tmp_path))
    r1, r2, r3 = traj["bench"]
    assert r2["comparable_with_prev"] is True
    assert r2["value_delta"] == pytest.approx(-0.5)
    assert r2["step_ms_delta"] == pytest.approx(-320.0)
    assert r3["comparable_with_prev"] is False
    assert any("platform tpu->cpu" in f for f in r3["flags"])
    assert any("nodes 10000->512" in f for f in r3["flags"])
    assert "value_delta" not in r3  # delta across the break is refused
    assert len(traj["comparability_breaks"]) == 1
    assert r1["provenance"] == "stderr" and r3["provenance"] == "emitted"
    text = trajectory.render_trajectory(traj)
    assert "not comparable: platform tpu->cpu" in text


def test_trajectory_reads_committed_artifacts_and_r05_break():
    """Against the REAL committed artifacts: the r04→r05 platform
    fallback must surface as a break (the VERDICT r5 caveat, now
    mechanical)."""
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    traj = trajectory.build_trajectory(root)
    assert len(traj["bench"]) >= 5
    r05 = next(r for r in traj["bench"] if "r05" in r["file"])
    assert r05["platform"] == "cpu" and r05["nodes"] == 512
    assert r05["comparable_with_prev"] is False
    assert any("platform tpu->cpu" in f for f in r05["flags"])
    assert traj["multichip"], "multichip lane artifacts must parse"
    for m in traj["multichip"]:
        assert m["device_count"] == 8


def test_trajectory_parses_prose_diag_line(tmp_path):
    """r01-era artifacts carry provenance only as stderr prose."""
    _wrap(tmp_path / "BENCH_r01.json", 1, {
        "metric": "tp", "value": 1.0, "unit": "c/s",
    }, tail="[bench] platform=tpu nodes=10000 rounds=120 wall=2s\n")
    row = trajectory.build_trajectory(str(tmp_path))["bench"][0]
    assert row["platform"] == "tpu" and row["nodes"] == 10000
    assert row["provenance"] == "stderr"


# ---------------------------------------------------------------------------
# Fingerprint keying (host-side)


def test_cost_entry_keys_and_fingerprints():
    assert costs.entry_key("dense", "plain", 1) == "dense/plain/d1"
    assert costs.entry_key("mixed", "donated", 8) == "mixed/donated/d8"
    a = benchlib.config_fingerprint("cfg", 8, 16)
    b = benchlib.config_fingerprint("cfg", 8, 32)
    assert a != b and a == benchlib.config_fingerprint("cfg", 8, 16)
