"""Version compaction: overwritten versions become Cleared ranges.

Mirrors the reference's compaction pipeline — find_cleared_db_versions
(agent.rs:1250-1299, unit-tested by test_in_memory_versions_compaction
agent.rs:3224), store_empty_changeset's range collapsing (agent.rs:1588-1664,
test_store_empty_changeset agent.rs:3603), and the clear_overwritten_versions
/ write_empties_loop pair (agent.rs:995-1126, 2522-2571) — on the host agent.
"""

import asyncio
import os
import sqlite3

from corrosion_tpu.agent.store import Store
from corrosion_tpu.agent.testing import launch_test_agent, poll_until
from corrosion_tpu.core.bookkeeping import Current
from corrosion_tpu.core.values import Statement


def run(coro):
    return asyncio.run(coro)


def make_store(tmp_path, name="s.db"):
    store = Store(str(tmp_path / name), os.urandom(16))
    store.apply_schema(
        "CREATE TABLE foo (a INTEGER NOT NULL PRIMARY KEY, b INTEGER);"
        "CREATE TABLE foo2 (a INTEGER NOT NULL PRIMARY KEY, b INTEGER);"
    )
    return store


def book(store, version, dbv, actor=None):
    store.conn.execute(
        "INSERT INTO __corro_bookkeeping VALUES (?, ?, NULL, ?, 0, 0)",
        (actor or store.site_id, version, dbv),
    )


def test_find_cleared_versions_reference_flow(tmp_path):
    """The exact scenario of test_in_memory_versions_compaction
    (agent.rs:3224): insert → delete keeps the tombstone's version live;
    resurrection retires it."""
    store = make_store(tmp_path)
    site = store.site_id

    _, dbv1, _, _ = store.execute_transaction(
        [Statement("INSERT INTO foo (a) VALUES (1)")]
    )
    book(store, 1, dbv1)
    _, dbv2, _, _ = store.execute_transaction([Statement("DELETE FROM foo")])
    book(store, 2, dbv2)

    to_clear = store.find_cleared_versions(site)
    assert dbv1 in to_clear, "overwritten insert version is clearable"
    assert dbv2 not in to_clear, "delete tombstone keeps its version live"

    store.store_empty_changeset(site, 1, 1)
    assert store.find_cleared_versions(site) == set()

    # A write to an unrelated table clears nothing.
    _, dbv3, _, _ = store.execute_transaction(
        [Statement("INSERT INTO foo2 (a) VALUES (2)")]
    )
    book(store, 3, dbv3)
    assert store.find_cleared_versions(site) == set()

    # Resurrecting the row retires the delete sentinel: now (and only now)
    # the delete's version is compactable.
    _, dbv4, _, _ = store.execute_transaction(
        [Statement("INSERT INTO foo (a) VALUES (1)")]
    )
    book(store, 4, dbv4)
    to_clear = store.find_cleared_versions(site)
    assert dbv2 in to_clear
    assert dbv3 not in to_clear and dbv4 not in to_clear

    store.store_empty_changeset(site, 2, 2)
    assert store.find_cleared_versions(site) == set()


def test_store_empty_changeset_collapses_ranges(tmp_path):
    """Range collapsing per the reference's overlap/adjacency clauses
    (agent.rs:1598-1614; test_store_empty_changeset agent.rs:3603)."""
    store = make_store(tmp_path)
    site = b"\x01" * 16
    c = store.conn
    for v in (1, 2, 3, 5):
        c.execute(
            "INSERT INTO __corro_bookkeeping VALUES (?, ?, NULL, ?, 0, 0)",
            (site, v, 100 + v),
        )
    c.execute(
        "INSERT INTO __corro_bookkeeping VALUES (?, 6, 8, NULL, NULL, NULL)",
        (site,),
    )
    # Change-log rows for the current versions, to verify pruning.
    for v in (1, 2, 3, 5):
        c.execute(
            "INSERT INTO __crdt_changes VALUES ('foo', X'00', 'b', NULL,"
            " 1, ?, 0, ?, 1)",
            (100 + v, site),
        )

    store.store_empty_changeset(site, 1, 2)
    rows = set(
        c.execute(
            "SELECT start_version, end_version, db_version"
            " FROM __corro_bookkeeping WHERE actor_id = ?",
            (site,),
        )
    )
    assert rows == {
        (1, 2, None), (3, None, 103), (5, None, 105), (6, 8, None),
    }

    # [3,5] swallows the single at 3 and 5, the left-adjacent cleared [1,2]
    # and the right-adjacent cleared [6,8] → one row [1,8].
    store.store_empty_changeset(site, 3, 5)
    rows = set(
        c.execute(
            "SELECT start_version, end_version, db_version"
            " FROM __corro_bookkeeping WHERE actor_id = ?",
            (site,),
        )
    )
    assert rows == {(1, 8, None)}
    # The cleared versions' change-log rows are pruned.
    left = c.execute(
        "SELECT count(*) FROM __crdt_changes WHERE site_id = ?", (site,)
    ).fetchone()[0]
    assert left == 0


def test_store_empty_changeset_straddles_start(tmp_path):
    """A persisted cleared range that straddles only the START of the new
    range must merge, not survive as an overlapping second row (hole in the
    reference's predicate, closed here)."""
    store = make_store(tmp_path)
    site = b"\x03" * 16
    store.store_empty_changeset(site, 1, 10)
    store.store_empty_changeset(site, 5, 20)
    rows = set(
        store.conn.execute(
            "SELECT start_version, end_version FROM __corro_bookkeeping"
            " WHERE actor_id = ?",
            (site,),
        )
    )
    assert rows == {(1, 20)}


def test_store_empty_changeset_noncontiguous_failsafe(tmp_path):
    store = make_store(tmp_path)
    site = b"\x02" * 16
    # Nothing adjacent: [10,10] over empty bookkeeping is fine...
    assert store.store_empty_changeset(site, 10, 10) == 1
    # ...and a second disjoint range stays a separate row (no failsafe trip).
    assert store.store_empty_changeset(site, 20, 20) == 1
    rows = set(
        store.conn.execute(
            "SELECT start_version, end_version FROM __corro_bookkeeping"
            " WHERE actor_id = ?",
            (site,),
        )
    )
    assert rows == {(10, 10), (20, 20)}


def test_agent_compacts_overwritten_versions(tmp_path):
    """clear_overwritten_versions end-to-end: repeated overwrites of one row
    collapse to a Cleared range in the bookie AND in persisted bookkeeping;
    a late joiner receives the cleared span + only the live data."""

    async def main():
        a = await launch_test_agent(
            str(tmp_path / "a"),
            compact_interval=0.25,
            empties_flush_interval=0.1,
        )
        try:
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (1, 'v0')"]]
            )
            for i in range(1, 6):
                await a.client.execute(
                    [["UPDATE tests SET text = ? WHERE id = 1", [f"v{i}"]]]
                )

            booked = a.agent.bookie.for_actor(a.agent.actor_id)
            assert booked.last() == 6

            async def compacted():
                return booked.cleared.contains_range(1, 5)

            await poll_until(compacted, timeout=10.0)
            assert isinstance(booked.get(6), Current), (
                "the live head version must survive compaction"
            )

            # Persisted: one collapsed NULL-db_version range row.
            async def persisted():
                rows = a.agent.store.conn.execute(
                    "SELECT start_version, end_version FROM"
                    " __corro_bookkeeping WHERE actor_id = ?"
                    " AND db_version IS NULL",
                    (a.agent.store.site_id,),
                ).fetchall()
                return (1, 5) in rows

            await poll_until(persisted, timeout=10.0)

            # Late joiner: gets the cleared span via sync, plus version 6's
            # data — and ends with the right row.
            b = await launch_test_agent(
                str(tmp_path / "b"), bootstrap=[a.gossip_addr]
            )
            try:
                async def b_caught_up():
                    _, rows = await b.client.query(
                        "SELECT text FROM tests WHERE id = 1"
                    )
                    return rows == [["v5"]]

                await poll_until(b_caught_up, timeout=20.0)
                bb = b.agent.bookie.get(a.agent.actor_id)
                assert bb is not None
                await poll_until(
                    lambda: _async(bb.cleared.contains_range(1, 5)),
                    timeout=10.0,
                )
            finally:
                await b.stop()
        finally:
            await a.stop()

    run(main())


async def _async(value):
    return value


def test_late_sync_after_delete_compaction(tmp_path):
    """The tombstone-correctness scenario: B holds a row, goes offline, A
    deletes it and compacts the INSERT version away. When B returns it must
    still learn the delete — the sentinel keeps the delete's version
    servable (cr-sqlite's __crsql_del clock row; find_cleared semantics
    agent.rs:1250-1299)."""

    async def main():
        a = await launch_test_agent(
            str(tmp_path / "a"),
            compact_interval=0.25,
            empties_flush_interval=0.1,
        )
        try:
            b = await launch_test_agent(
                str(tmp_path / "b"), bootstrap=[a.gossip_addr]
            )
            await a.client.execute(
                [["INSERT INTO tests (id, text) VALUES (9, 'doomed')"]]
            )

            async def on_b():
                _, rows = await b.client.query(
                    "SELECT count(*) FROM tests WHERE id = 9"
                )
                return rows[0][0] == 1

            await poll_until(on_b, timeout=20.0)
            await b.stop()

            await a.client.execute([["DELETE FROM tests WHERE id = 9"]])
            booked = a.agent.bookie.for_actor(a.agent.actor_id)

            async def insert_version_cleared():
                return booked.cleared.contains(1)

            await poll_until(insert_version_cleared, timeout=10.0)
            # The delete version itself must NOT be cleared.
            assert isinstance(booked.get(2), Current)

            # B restarts with its stale copy; sync must deliver the delete.
            b2 = await launch_test_agent(
                str(tmp_path / "b"), bootstrap=[a.gossip_addr],
                compact_interval=0.25, empties_flush_interval=0.1,
            )
            try:
                async def row_gone():
                    _, rows = await b2.client.query(
                        "SELECT count(*) FROM tests WHERE id = 9"
                    )
                    return rows[0][0] == 0

                await poll_until(row_gone, timeout=20.0)
                # B held v1 as Current, so no sync_cleared arrives for it;
                # B's OWN compaction notices the clock rows vanished when
                # the delete applied and clears v1 locally — compaction is
                # per-node, for every tracked actor (agent.rs:1005-1021).
                bb = b2.agent.bookie.get(a.agent.actor_id)
                assert bb is not None
                await poll_until(
                    lambda: _async(bb.cleared.contains(1)), timeout=10.0
                )
            finally:
                await b2.stop()
        finally:
            await a.stop()

    run(main())


def test_cleared_ranges_survive_restart(tmp_path):
    """Rehydration maps NULL-db_version bookkeeping rows back to Cleared
    (agent.rs:147-268): restart after compaction keeps the collapsed state
    and serves it to late joiners."""

    async def main():
        data = str(tmp_path / "a")
        a = await launch_test_agent(
            data, compact_interval=0.25, empties_flush_interval=0.1
        )
        actor = a.agent.actor_id
        await a.client.execute(
            [["INSERT INTO tests (id, text) VALUES (1, 'x')"]]
        )
        for i in range(4):
            await a.client.execute(
                [["UPDATE tests SET text = ? WHERE id = 1", [f"y{i}"]]]
            )
        booked = a.agent.bookie.for_actor(actor)
        await poll_until(
            lambda: _async(booked.cleared.contains_range(1, 4)), timeout=10.0
        )
        # Wait for the persisted collapse, then restart.
        db_path = a.agent.store.conn.execute("PRAGMA database_list").fetchall()[0][2]
        async def persisted():
            chk = sqlite3.connect(db_path)
            try:
                return chk.execute(
                    "SELECT count(*) FROM __corro_bookkeeping"
                    " WHERE db_version IS NULL AND start_version = 1"
                    " AND end_version = 4"
                ).fetchone()[0] == 1
            finally:
                chk.close()
        await poll_until(persisted, timeout=10.0)
        await a.stop()

        a2 = await launch_test_agent(data)
        try:
            assert a2.agent.actor_id == actor, "identity persists"
            booked = a2.agent.bookie.for_actor(actor)
            assert booked.cleared.contains_range(1, 4)
            assert isinstance(booked.get(5), Current)
        finally:
            await a2.stop()

    run(main())


def test_buffered_meta_reconcile_drops_orphaned_partials(tmp_path):
    """clear_buffered_meta_loop analogue (agent.rs:2575-2619): buffered
    partial data for a version cleared out-of-band (crash window between
    the bookkeeping write and the inline prune) is reconciled away, and
    the dead partial cannot resurrect at the next boot."""

    async def main():
        a = await launch_test_agent(
            str(tmp_path / "a"), buffered_meta_interval=3600.0
        )
        try:
            agent = a.agent
            actor = "ab" * 16
            site = bytes.fromhex(actor)
            # Simulate the crash window: buffered rows + seq bookkeeping
            # exist, and the bookie says the version is CLEARED (as a
            # rehydrate after an out-of-band empty would produce).
            with agent.store._wlock("test_seed"):
                agent.store.conn.execute(
                    "INSERT INTO __corro_buffered_changes VALUES"
                    " (?, 3, 'tests', x'00', 'text', 'v', 1, 1, 0, ?, 1)",
                    (site, site),
                )
                agent.store.conn.execute(
                    "INSERT INTO __corro_seq_bookkeeping VALUES"
                    " (?, 3, 0, 0, 5, 1)",
                    (site,),
                )
            from corrosion_tpu.core.bookkeeping import CLEARED, Partial
            from corrosion_tpu.core.intervals import RangeSet

            booked = agent.bookie.for_actor(actor)
            booked.partials[3] = Partial(
                seqs=RangeSet([(0, 0)]), last_seq=5, ts=1
            )
            booked.insert_many(3, 3, CLEARED)
            # insert_many(CLEARED) pops the partial itself; re-seed it to
            # model a rehydrated process whose in-memory partial came from
            # the orphaned seq rows.
            booked.partials[3] = Partial(
                seqs=RangeSet([(0, 0)]), last_seq=5, ts=1
            )

            await agent._clear_buffered_meta_once()

            assert 3 not in booked.partials
            assert agent.store.conn.execute(
                "SELECT count(*) FROM __corro_buffered_changes"
            ).fetchone()[0] == 0
            assert agent.store.conn.execute(
                "SELECT count(*) FROM __corro_seq_bookkeeping"
            ).fetchone()[0] == 0
        finally:
            await a.stop()

    run(main())
