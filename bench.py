"""Headline benchmark: 10k-node CRDT merge storm — p99 change visibility.

Runs BASELINE config 4 (10k virtual nodes, concurrent writers, live CRDT
cell plane) and reports the north-star metric: p99 change-visibility latency
in simulated seconds (target < 10 s, BASELINE.md). vs_baseline is
target / measured, so > 1.0 means the target is beaten.

Extra fields document the run honestly: convergence flag, cluster-wide
apply throughput, wall-clock per round after warm-up (the compile cache is
hit because the jitted scan is hoisted), and a per-plane step-time
breakdown (SWIM / broadcast / sync) from isolated timed executions.

Prints exactly one JSON line on stdout; diagnostics go to stderr.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def _time_plane(fn, *args, iters=5):
    out = fn(*args)  # compile
    jax.block_until_ready(jax.tree.leaves(out)[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(jax.tree.leaves(out)[0])
    return (time.perf_counter() - t0) / iters * 1000.0  # ms


def main() -> None:
    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)
    from corrosion_tpu import models
    from corrosion_tpu.ops import gossip as gossip_ops
    from corrosion_tpu.ops import swim as swim_ops
    from corrosion_tpu.sim import simulate, visibility_latencies

    if on_accel:
        n, rounds = 10_000, 120
    else:  # CPU smoke fallback so the script stays runnable anywhere
        n, rounds = 512, 60
    cfg, topo, sched = models.merge_10k(n=n, rounds=rounds, samples=256)

    chunk = 12  # bound single device executions (watchdog-safe)
    t0 = time.perf_counter()
    final, curves = simulate(cfg, topo, sched, seed=0, max_chunk=chunk)
    jax.block_until_ready(final.data.contig)
    compile_and_run = time.perf_counter() - t0

    t1 = time.perf_counter()
    final, curves = simulate(cfg, topo, sched, seed=1, max_chunk=chunk)
    jax.block_until_ready(final.data.contig)
    wall = time.perf_counter() - t1
    step_ms = wall / rounds * 1000.0

    applied = float(curves["applied_broadcast"].astype(np.float64).sum()
                    + curves["applied_sync"].astype(np.float64).sum())
    merges = float(curves["cell_merges"].astype(np.float64).sum())
    lat = visibility_latencies(final, sched, cfg)
    heads = np.asarray(final.data.head, dtype=np.float64)
    contig = np.asarray(final.data.contig, dtype=np.float64)
    converged = bool((contig == heads[None, :]).all())
    cells_ok = bool(gossip_ops.cells_agree(final.data, cfg.gossip))

    # Per-plane step-time breakdown on fresh state (isolated jitted calls).
    data = gossip_ops.init_data(cfg.gossip)
    sw = swim_ops.init_state(cfg.swim)
    alive = jnp.ones(cfg.n_nodes, bool)
    n_regions = int(np.asarray(topo.region).max()) + 1
    part = jnp.zeros((n_regions, n_regions), bool)
    writes = jnp.asarray(sched.writes[0], jnp.uint32)
    key = jax.random.PRNGKey(0)
    bcast_ms = _time_plane(
        lambda: gossip_ops.broadcast_round(
            data, topo, alive, part, writes, key, cfg.gossip
        )
    )
    sync_ms = _time_plane(
        lambda: gossip_ops.sync_round(
            data, topo, alive, part, jnp.int32(0), key, cfg.gossip
        )
    )
    swim_ms = _time_plane(
        lambda: swim_ops.swim_round(sw, key, jnp.int32(0), cfg.swim)
    )

    state_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(final.data)
    ) + sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(final.swim))

    diag = {
        "platform": platform,
        "nodes": n,
        "rounds": rounds,
        "wall_s": round(wall, 3),
        "first_run_incl_compile_s": round(compile_and_run, 1),
        "applied": applied,
        "cell_merges": merges,
        "state_mib": round(state_bytes / 2**20, 1),
    }
    print(f"[bench] {json.dumps(diag)}", file=sys.stderr)

    p99 = lat["p99_s"]
    print(
        json.dumps(
            {
                "metric": "p99_change_visibility_10k",
                "value": round(p99, 2),
                "unit": "s",
                # North-star target is p99 < 10 s (BASELINE.md); ratio > 1
                # beats it. The reference publishes no comparable number —
                # its only throughput figure is a 2-node log excerpt.
                "vs_baseline": round(10.0 / p99, 2) if p99 > 0 else None,
                "converged": converged,
                "cells_converged": cells_ok,
                "unseen_pairs": lat["unseen"],
                "p50_s": round(lat["p50_s"], 2),
                "throughput_changes_per_s": round(applied / wall, 1),
                "step_ms": round(step_ms, 1),
                "plane_ms": {
                    "swim": round(swim_ms, 1),
                    "broadcast": round(bcast_ms, 1),
                    "sync": round(sync_ms, 1),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
