"""Headline benchmark: 10k-node CRDT merge storm — p99 change visibility.

Runs BASELINE config 4 (10k virtual nodes, concurrent writers, live CRDT
cell plane) and reports the north-star metric: p99 change-visibility latency
in simulated seconds (target < 10 s, BASELINE.md). vs_baseline is
target / measured, so > 1.0 means the target is beaten.

Step-time fields (all per simulated round, warm — the compile cache is hit
because the jitted scan is hoisted):

- ``step_ms``: whole-run wall clock / rounds, INCLUDING host work between
  chunk executions (schedule slicing, dispatch, curve merging). The
  honest end-to-end number.
- ``step_inner_ms``: wall clock of the device chunk executions only,
  measured by the kernel-telemetry chunk timer (sim/telemetry.py) on the
  SAME timed run. A subset of step_ms's windows, so
  ``step_inner_ms <= step_ms`` holds structurally; the gap is host
  overhead.
- ``plane_ms`` / ``residual_ms``: step_ms attributed to the
  broadcast/swim/sync/track sub-steps by cumulative-prefix measurement
  (stages enabled one at a time in execution order on the run's final
  state; a stage's cost is the increment, which telescopes exactly —
  telemetry.PlaneAttribution asserts it) and projected onto step_ms, so
  ``sum(plane_ms) + residual_ms == step_ms`` by construction. The
  residual carries empty-scan overhead, host dispatch, and fusion slack
  — nothing can hide in unattributed time. (Earlier rounds reported the
  raw composite microbench as step_inner_ms; measured on the final state
  it can exceed the run's average round — the BENCH_r05 anomaly — so the
  composite now only supplies attribution FRACTIONS.)

Prints exactly one JSON line on stdout; diagnostics go to stderr.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import numpy as np


def main() -> None:
    if "--multichip" in sys.argv:
        # The standing sharded bench lane (ISSUE 7): dense + sparse under
        # the explicit shard_map round driver at device_count ∈ {1,2,4,8},
        # gated against bench_budget.json's `multichip` entry. ONE
        # implementation owns the lane — scripts/multichip_smoke.py — so
        # the headline bench and the CI gate can never measure different
        # things (the same rule benchlib enforces for the plane composite).
        from pathlib import Path

        sys.path.insert(0, str(Path(__file__).resolve().parent / "scripts"))
        import multichip_smoke

        argv = [a for a in sys.argv[1:] if a != "--multichip"]
        raise SystemExit(multichip_smoke.main(argv))

    from corrosion_tpu.utils.cache import (
        enable_persistent_cache,
        ensure_live_backend,
    )

    ensure_live_backend()  # dead tunnel → CPU smoke, never a hang
    enable_persistent_cache()
    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)
    from corrosion_tpu import models
    from corrosion_tpu.ops import gossip as gossip_ops
    from corrosion_tpu.sim import simulate, telemetry, visibility_latencies
    from corrosion_tpu.utils.metrics import MetricsRegistry

    if on_accel:
        n, rounds = 10_000, 120
    else:  # CPU smoke fallback so the script stays runnable anywhere
        n, rounds = 512, 60
    cfg, topo, sched = models.merge_10k(n=n, rounds=rounds, samples=256)

    # Compile ledger (obs/ledger.py): the first run's window splits the
    # opaque first-run blob into ledger-derived compile_ms +
    # first_step_ms, and ARMING it around the timed run turns any
    # steady-state recompile into a loud RetraceError instead of a
    # silently skewed measurement (the r04→r05 failure class).
    from corrosion_tpu.obs import costs as costs_mod
    from corrosion_tpu.obs import ledger as ledger_mod

    led = ledger_mod.CompileLedger().watch_engines(("dense",)).install()

    chunk = 24  # bound single device executions (watchdog-safe:
    # ~5 s per execution at current step times; dispatch to the remote
    # device costs tens of ms per chunk, so fewer chunks = honest wall)
    t0 = time.perf_counter()
    with led.window("first_run") as cold:
        final, curves = simulate(cfg, topo, sched, seed=0, max_chunk=chunk)
        jax.block_until_ready(final.data.contig)
    compile_and_run = time.perf_counter() - t0

    # The timed run carries the kernel telemetry plane: per-chunk device
    # execution walls (step_inner_ms), corro_kernel_* metric totals, the
    # armed compile ledger, and live per-device memory watermarks
    # sampled at every chunk boundary.
    registry = MetricsRegistry()
    watermarks = costs_mod.MemoryWatermarks()
    tele = telemetry.KernelTelemetry(
        engine="dense", registry=registry, ledger=led,
        watermarks=watermarks,
    )
    led.arm("bench timed run (seed 1, warmed by seed 0 at same shapes)")
    t1 = time.perf_counter()
    final, curves = simulate(
        cfg, topo, sched, seed=1, max_chunk=chunk, telemetry=tele
    )
    jax.block_until_ready(final.data.contig)
    wall = time.perf_counter() - t1
    led.disarm()
    led.publish(registry, engine="dense")
    step_ms = wall / rounds * 1000.0
    step_inner_ms = tele.device_step_ms
    assert step_inner_ms <= step_ms + 1e-6, (
        f"chunk-execution windows exceed the run wall: "
        f"{step_inner_ms} > {step_ms}"
    )
    # Metrics-bridge sanity: registry totals must equal the summed curves.
    for k in ("msgs", "applied_broadcast", "applied_sync"):
        got = registry.counter(f"corro_kernel_{k}_total").get(engine="dense")
        want = float(np.asarray(curves[k], dtype=np.float64).sum())
        assert got == want, f"corro_kernel_{k}_total {got} != {want}"

    applied = float(curves["applied_broadcast"].astype(np.float64).sum()
                    + curves["applied_sync"].astype(np.float64).sum())
    merges = float(curves["cell_merges"].astype(np.float64).sum())
    lat = visibility_latencies(final, sched, cfg)

    # Convergence health plane (sim/health.py): run-level protocol
    # verdicts from the SAME timed run's curves, published alongside the
    # corro_kernel_* series. The on-device delivery-latency histogram
    # must agree with the exact host-side percentiles to one bucket —
    # asserted here so the two measurement paths can never drift apart
    # silently.
    from corrosion_tpu.sim import health as health_mod

    rep = health_mod.report_from_curves(
        curves, engine="dense", round_ms=cfg.round_ms
    )
    health_mod.publish_report(registry, rep)
    if np.isfinite(lat["p50_s"]) and rep.vis_total:
        host_b = health_mod.latency_bucket(
            lat["p50_s"] / (cfg.round_ms / 1000.0)
        )
        assert abs(host_b - rep.vis_p50_bucket) <= 1, (
            f"on-device delivery-latency histogram disagrees with "
            f"host-side p50: bucket {rep.vis_p50_bucket} vs {host_b}"
        )
    heads = np.asarray(final.data.head, dtype=np.float64)
    contig = np.asarray(final.data.contig, dtype=np.float64)
    converged = bool((contig == heads[None, :]).all())
    cells_ok = bool(gossip_ops.cells_agree(final.data, cfg.gossip))

    # Per-plane attribution by CUMULATIVE PREFIX on the run's FINAL state
    # (fresh state would flatter sync — no deficits to score or grant):
    # telemetry.attribute_planes times the composite with stages enabled
    # one at a time in execution order; a stage's cost is the increment.
    # The composite's absolute numbers are a biased sample (end-of-run
    # state), so only its FRACTIONS are used — scaled onto the measured
    # step_ms, keeping sum(plane_ms) + residual_ms == step_ms exact.
    # (Isolated plane timings under-counted in-context costs by ~35%;
    # ablation timings over-counted overlap by ~20%.) The composite
    # builder is shared with the CI bench-smoke gate (sim/benchlib.py)
    # so the headline bench and the regression gate measure identically.
    from corrosion_tpu.sim import benchlib

    composite, stages, carry0 = benchlib.plane_composite(
        cfg, topo, sched, final
    )
    attr = telemetry.attribute_planes(composite, stages, carry0)
    plane, residual_ms = attr.scale(step_ms)
    # Roofline stage costs from the SAME composite prefixes (AOT
    # cost_analysis — lowering only, nothing re-executes): per-plane
    # flops/bytes joined with the measured plane split below.
    stage_costs = costs_mod.roofline_stage_costs(composite, stages, carry0)

    state_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(final.data)
    ) + sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(final.swim))

    # Memory reconcile-or-fail (obs/costs.py): the watermarks sampled at
    # every chunk boundary must cover the final state's own live bytes —
    # a silent sampling gap aborts the bench rather than publishing an
    # unverified watermark.
    mem = costs_mod.reconcile_memory(final, watermarks=watermarks)

    diag = {
        "platform": platform,
        "nodes": n,
        "rounds": rounds,
        "wall_s": round(wall, 3),
        "first_run_incl_compile_s": round(compile_and_run, 1),
        "compile_events": cold.compiles,
        "peak_live_mib": round(
            max(watermarks.peak.values(), default=0) / 2**20, 1
        ),
        "applied": applied,
        "cell_merges": merges,
        "state_mib": round(state_bytes / 2**20, 1),
        # Raw composite microbench (end-of-run state sample): supplies
        # the attribution fractions, not a headline step time.
        "attrib_composite_ms": round(attr.full_ms, 1),
        "attrib_overhead_ms": round(attr.overhead_ms, 2),
        "attrib_residual_ms": round(residual_ms, 1),
    }
    print(f"[bench] {json.dumps(diag)}", file=sys.stderr)

    # ---- 100k north star, driver-captured (VERDICT r4 weak #1) ------------
    # The wan_100k steady config (no partition: pure propagation) is the
    # BASELINE.md config-5 target metric. Run it here so the driver's
    # BENCH artifact carries the number instead of builder-reported prose.
    # Warm-step timing follows scripts/wan100k_smoke.py --steptime: first
    # chunk compiles, the remaining chunks re-run the same compiled scan.
    extra_100k = {}
    if on_accel:
        import dataclasses

        ck = 16
        rounds_1e5 = 160  # converges with an 80-round drain tail
        cfg5, topo5, sched5 = models.wan_100k(
            rounds=rounds_1e5, samples=256, partition=False
        )
        warm = dataclasses.replace(sched5, writes=sched5.writes[:ck])
        st5, _ = simulate(cfg5, topo5, warm, seed=0, max_chunk=ck)
        jax.block_until_ready(st5.data.contig)
        rest = dataclasses.replace(sched5, writes=sched5.writes[ck:])
        tele5 = telemetry.KernelTelemetry(
            engine="dense", progress=sys.stderr
        )
        t5 = time.perf_counter()
        st5, curves5 = simulate(
            cfg5, topo5, rest, seed=0, state=st5, max_chunk=ck,
            telemetry=tele5,
        )
        jax.block_until_ready(st5.data.contig)
        wall5 = time.perf_counter() - t5
        lat5 = visibility_latencies(st5, sched5, cfg5)
        heads5 = np.asarray(st5.data.head)
        conv5 = bool(
            (np.asarray(st5.data.contig) == heads5[None, :]).all()
        )
        p99_5 = lat5["p99_s"]
        rep5 = health_mod.report_from_curves(
            curves5, engine="dense", round_ms=cfg5.round_ms
        )
        extra_100k = {
            "p99_change_visibility_100k_s": round(p99_5, 2),
            # Health plane over the timed window (rounds ck..); the
            # converged round is relative to that window's start.
            "staleness_p99_100k": round(rep5.staleness_p99, 1),
            "vis_hist_p99_100k_s": rep5.to_dict()["vis_p99_s"],
            "queue_backlog_peak_100k": rep5.queue_backlog_peak,
            "p50_100k_s": round(lat5["p50_s"], 2),
            "vs_baseline_100k": (
                round(10.0 / p99_5, 2) if p99_5 > 0 else None
            ),
            "converged_100k": conv5,
            "cells_converged_100k": bool(
                gossip_ops.cells_agree(st5.data, cfg5.gossip)
            ),
            "unseen_pairs_100k": lat5["unseen"],
            "step_ms_100k": round(wall5 / (rounds_1e5 - ck) * 1000.0, 1),
            "step_inner_ms_100k": round(tele5.device_step_ms, 1),
            "window_degraded_100k": int(curves5["window_degraded"].sum()),
        }
        print(f"[bench] 100k: {json.dumps(extra_100k)}", file=sys.stderr)

    p99 = lat["p99_s"]
    from corrosion_tpu.ops import onehot

    step_rep = benchlib.rounded_step_report(step_ms, plane)
    report = {
        # Self-describing provenance (check_bench_invariants asserts the
        # presence of platform / nodes / device_count /
        # config_fingerprint): the r05 incident was a CPU-fallback run
        # published under the TPU metric name — with these fields a
        # fallback artifact is unmistakable from the JSON alone.
        **benchlib.bench_context(cfg, n, rounds, chunk),
        "nodes": n,
        "rounds": rounds,
        "kernels": onehot.resolve_backend(cfg.gossip.kernel_backend),
        "metric": "p99_change_visibility_10k",
        "value": round(p99, 2),
        "unit": "s",
        # North-star target is p99 < 10 s (BASELINE.md); ratio > 1
        # beats it. The reference publishes no comparable number —
        # its only throughput figure is a 2-node log excerpt.
        "vs_baseline": round(10.0 / p99, 2) if p99 > 0 else None,
        "converged": converged,
        "cells_converged": cells_ok,
        "unseen_pairs": lat["unseen"],
        "p50_s": round(lat["p50_s"], 2),
        "throughput_changes_per_s": round(applied / wall, 1),
        # Shared emit-site rounding (benchlib.rounded_step_report):
        # step_ms, plane_ms (step_ms attributed by measured stage
        # fractions), and a residual derived from the ROUNDED values so
        # sum(plane_ms) + residual_ms == step_ms holds exactly on the
        # published numbers (residual = scan overhead + host dispatch +
        # fusion slack, kept visible so regressions can't hide in
        # unattributed time). One implementation shared with the CI
        # bench-smoke gate.
        **step_rep,
        # Device chunk executions only (telemetry chunk timer) —
        # a subset of step_ms's wall, so <= step_ms always.
        "step_inner_ms": round(step_inner_ms, 1),
        # The ledger split of the first-run blob: compile wall vs
        # everything else, reconstructing first_run_incl_compile_s
        # exactly on the published numbers (check_bench_invariants).
        **benchlib.compile_split_report(compile_and_run, cold.compile_ms),
        # The armed timed run compiled nothing (a recompile would have
        # raised RetraceError before this line).
        "steady_compiles": led.armed_compiles,
        # Device-cost roofline per plane: composite flops/bytes joined
        # with the measured plane split — achieved FLOP/s, B/s, and
        # arithmetic intensity per plane, recomputable from the emitted
        # numbers (check_bench_invariants does).
        "roofline": benchlib.roofline_report(
            stage_costs, step_rep["plane_ms"]
        ),
        # Live per-device memory watermark, reconciled against the final
        # state's own bytes (reconcile_memory raised on any break).
        "peak_live_bytes_per_device": max(
            watermarks.peak.values(), default=0
        ),
        "state_bytes_per_device": mem["state_bytes_per_device_max"],
        # Convergence health plane (derived from the flight
        # curves alone; bucket-edge seconds, so >= the exact
        # percentiles above by construction).
        "converged_round": rep.converged_round,
        "staleness_p99": round(rep.staleness_p99, 1),
        "staleness_peak_node": rep.staleness_max_peak,
        # Through the report's JSON-safe serializer: overflow
        # percentiles become "inf", never a bare Infinity token.
        "vis_hist_p50_s": rep.to_dict()["vis_p50_s"],
        "vis_hist_p99_s": rep.to_dict()["vis_p99_s"],
        "queue_backlog_peak": rep.queue_backlog_peak,
        **extra_100k,
    }
    # Every reporting path funnels through the ONE emit site, and the
    # emitted dict itself — not intermediate variables — is what the
    # invariant check sees, so no path can bypass the normalization
    # again (the BENCH_r05 anomaly: a stale reporting path published the
    # raw composite microbench as step_inner_ms, violating both
    # documented invariants).
    print(json.dumps(telemetry.check_bench_invariants(report)))


if __name__ == "__main__":
    main()
