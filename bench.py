"""Headline benchmark: cluster-wide change propagation throughput.

Runs BASELINE config 4 (10k-node concurrent-writer CRDT merge storm) on the
available accelerator and reports how many change-version applications per
second the simulated cluster sustains (broadcast deliveries + anti-entropy
replay across all nodes).

vs_baseline: the only throughput number the reference publishes is the
2-node quick-start log excerpt, ≈156 changes/s (BASELINE.md; reference
doc/quick-start.md:119). The ratio is our simulated cluster-wide
apply throughput over that single-link figure.

Prints exactly one JSON line on stdout; diagnostics go to stderr.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import numpy as np


def main() -> None:
    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)
    from corrosion_tpu import models
    from corrosion_tpu.sim import simulate, visibility_latencies

    if on_accel:
        n, rounds = 10_000, 120
    else:  # CPU smoke fallback so the script stays runnable anywhere
        n, rounds = 512, 60
    cfg, topo, sched = models.merge_10k(n=n, rounds=rounds, samples=256)

    t0 = time.perf_counter()
    final, curves = simulate(cfg, topo, sched, seed=0)
    jax.block_until_ready(final.data.contig)
    compile_and_run = time.perf_counter() - t0

    t1 = time.perf_counter()
    final, curves = simulate(cfg, topo, sched, seed=1)
    jax.block_until_ready(final.data.contig)
    wall = time.perf_counter() - t1

    applied = float(curves["applied_broadcast"].astype(np.float64).sum()
                    + curves["applied_sync"].astype(np.float64).sum())
    throughput = applied / wall
    lat = visibility_latencies(final, sched, cfg)
    heads = np.asarray(final.data.head, dtype=np.float64)
    contig = np.asarray(final.data.contig, dtype=np.float64)
    converged = bool((contig == heads[None, :]).all())

    print(
        f"[bench] platform={platform} nodes={n} rounds={rounds} "
        f"wall={wall:.3f}s (first run incl. compile {compile_and_run:.1f}s) "
        f"applied={applied:.0f} converged={converged} "
        f"vis p50={lat['p50_s']:.2f}s p99={lat['p99_s']:.2f}s "
        f"unseen={lat['unseen']}",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "change_propagation_throughput",
                "value": round(throughput, 1),
                "unit": "changes/s",
                "vs_baseline": round(throughput / 156.0, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
