"""corrosion-tpu: a TPU-native rebuild of gossip-based distributed state.

Capabilities mirror spacekookie/corrosion (SWIM membership, CRDT changeset
broadcast, anti-entropy sync, SQLite materialization, HTTP API with streaming
SQL subscriptions) rebuilt from scratch in two cooperating halves:

- ``corrosion_tpu.sim`` + ``corrosion_tpu.parallel`` + ``corrosion_tpu.ops``:
  the JAX/XLA/pallas compute path — virtual Corrosion nodes sharded over a
  ``jax.sharding.Mesh``, with SWIM rounds, broadcast fanout, CRDT merge and
  anti-entropy as batched kernels.
- ``corrosion_tpu.agent`` + ``corrosion_tpu.client`` + ``corrosion_tpu.cli``:
  the host runtime — a real agent with SQLite CRR storage, datagram/stream
  transport, HTTP API, subscriptions, admin RPC, CLI.

Shared pure logic (version vectors, interval sets, sync-need computation, HLC,
wire codecs) lives in ``corrosion_tpu.core`` and is used by both halves.
"""

__version__ = "0.1.0"
