"""Metric time-series recorder — the endurance plane's temporal half.

Every observability plane so far is point-in-time or per-run: /metrics
is a live scrape and reports summarize one bounded run. This module
records how a :class:`~corrosion_tpu.utils.metrics.MetricsRegistry`
MOVES: periodic whole-registry snapshots (counters as monotonic
cumulatives, gauges as points, histograms as bucket vectors) streamed
to a self-describing ``corro-metric-series/1`` JSONL with the
FlightRecorder's rotation/resume contract (sim/telemetry.py):

- a ``{"kind": "series", "schema": ..., "segment": N}`` header per
  open, so a reader can refuse a future incompatible format;
- one flushed line per sample — a crashed run loses at most the
  in-flight line, and ``replay_series`` skips unparsable tails;
- rotation past ``max_bytes`` to ``path.N`` (oldest = ``.1``) with a
  resume-aware segment counter: ``mode="a"`` appends to an already-
  rotated record without renaming the live file over an old segment,
  ``mode="w"`` deletes stale segments so a fresh record never merges a
  previous run's chain into its replay.

Install points (both zero-cost when not installed — one ``is None``
branch, pinned like the chaos/prop axes):

- the agent runtime loop (``AgentConfig.metric_series_path``): one
  sample per runtime-metrics tick, wall-clock ``t``;
- ``KernelTelemetry`` chunk boundaries (``telemetry.series``): one
  sample per chunk with ``t`` = absolute round index, so a seeded run's
  series file is byte-reproducible.

Deliberately jax-free (like obs/timeline.py): ``obs soak`` over a
recorded JSONL and the agent runtime install must not pay the kernel
import. The detectors over recorded series live in
:mod:`corrosion_tpu.obs.endurance`.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time
from typing import IO

SERIES_SCHEMA = "corro-metric-series/1"

# Record kinds owned by the recorder itself; record_event refuses them
# so replay_series' row semantics cannot be spoofed (the FlightRecorder
# reserved-kind contract).
_RESERVED_KINDS = ("series", "sample")


def series_segments(path: str) -> list[str]:
    """Every file of a (possibly rotated) series record, oldest first:
    ``path.1``, ``path.2``, ..., then the live ``path``. Non-numeric
    suffixes are not segments. (The flight_segments contract, local so
    this module stays jax-free.)"""
    segs = []
    for p in glob.glob(path + ".*"):
        sfx = p[len(path) + 1:]
        if sfx.isdigit():
            segs.append((int(sfx), p))
    out = [p for _n, p in sorted(segs)]
    if os.path.exists(path):
        out.append(path)
    return out


class MetricSeriesRecorder:
    """Streams typed registry snapshots to a corro-metric-series/1 JSONL.

    ``clock`` stamps samples (and the header) with wall time by default;
    pass ``clock=None`` for a fully deterministic record — the header
    carries no timestamp and every ``sample()`` must supply an explicit
    ``t`` (the kernel plane passes the absolute round index, so a seeded
    rerun reproduces the file byte for byte).

    **Idempotent installs**: open through :meth:`attach` wherever two
    installs can race the same path in one process — an agent relaunched
    in-process (hostchaos ``kill_restart``) whose previous life was
    hard-killed without closing adopts the live recorder instead of
    opening a second handle (no raise, no duplicate header, no
    double-sampling). ``close()`` is refcounted to match.
    """

    _live: dict[str, "MetricSeriesRecorder"] = {}
    _live_lock = threading.Lock()

    def __init__(
        self, path: str, source: str = "", mode: str = "a",
        max_bytes: int | None = None, clock=time.time,
    ):
        self.path = path
        self.source = source
        self.max_bytes = max_bytes
        self.clock = clock
        self._refs = 1
        self._seq = 0
        self._lock = threading.Lock()
        existing = series_segments(path)
        if mode == "w":
            # A truncating open starts a FRESH record: stale rotated
            # segments from a previous capped run at the same path must
            # not survive to be merged into this record's replay.
            for p in existing:
                if p != path:
                    os.remove(p)
            self._segment = 0
        else:
            # Resume-aware segment counter: appending to an already-
            # rotated record must not rename the live file over an old
            # segment.
            self._segment = max(
                (
                    int(p[len(path) + 1:]) for p in existing
                    if p != path
                ),
                default=0,
            )
        self._f: IO[str] | None = open(path, mode)
        self._write_header()

    @classmethod
    def attach(cls, path: str, **kw) -> "MetricSeriesRecorder":
        """Idempotent open: adopt the live recorder already holding
        ``path`` in this process (bumping its refcount) or open a new
        one. The install path for anything that can be re-installed —
        the agent runtime loop on relaunch, repeated harness wiring."""
        key = os.path.abspath(path)
        with cls._live_lock:
            rec = cls._live.get(key)
            if rec is not None and rec._f is not None:
                rec._refs += 1
                return rec
            rec = cls(path, **kw)
            cls._live[key] = rec
            return rec

    def _write_header(self) -> None:
        hdr = {
            "kind": "series", "schema": SERIES_SCHEMA, "version": 1,
            "source": self.source, "segment": self._segment,
        }
        if self.clock is not None:
            hdr["t_unix"] = self.clock()
        self._write(hdr)

    def _write(self, obj: dict) -> None:
        # Flush every record: a live `tail -f` (and the soak harness
        # reading mid-run) sees whole lines; only the final in-flight
        # line of a crash can be torn, and replay_series skips it.
        self._f.write(json.dumps(obj) + "\n")
        self._f.flush()

    def sample(
        self, registry, t: float | None = None, exclude: tuple = (),
        extra: dict | None = None,
    ) -> dict:
        """Flush one whole-registry snapshot line. ``t`` defaults to
        ``clock()``; ``exclude`` drops series by NAME stem (labels
        ignored) — the kernel plane excludes its wall-clock chunk
        histogram so seeded reruns stay byte-identical. Returns the
        written record."""
        if self._f is None:
            raise ValueError("MetricSeriesRecorder is closed")
        if t is None:
            if self.clock is None:
                raise ValueError(
                    "clock-less (deterministic) recorder needs an "
                    "explicit t per sample"
                )
            t = self.clock()
        snap = registry.series_snapshot()
        if exclude:
            for fam in snap.values():
                for k in [
                    k for k in fam if k.split("{", 1)[0] in exclude
                ]:
                    del fam[k]
        with self._lock:
            obj = {"kind": "sample", "t": float(t), "seq": self._seq}
            obj.update(snap)
            if extra:
                obj["extra"] = extra
            self._seq += 1
            self._write(obj)
            if (
                self.max_bytes is not None
                and self._f.tell() >= self.max_bytes
            ):
                self._rotate()
        return obj

    def record_event(self, obj: dict) -> None:
        """Append one out-of-band event line (e.g. a scenario phase
        marker). The reserved kinds stay owned by the recorder."""
        if self._f is None:
            raise ValueError("MetricSeriesRecorder is closed")
        if obj.get("kind") in _RESERVED_KINDS:
            raise ValueError(
                f"record_event cannot write reserved kind "
                f"{obj.get('kind')!r}"
            )
        with self._lock:
            self._write(obj)

    def _rotate(self) -> None:
        """Roll the live file to ``path.N`` and open a fresh segment.
        Called only at sample boundaries (under the lock), so every
        segment holds whole samples and replays standalone."""
        self._f.close()
        self._segment += 1
        os.replace(self.path, f"{self.path}.{self._segment}")
        self._f = open(self.path, "w")
        self._write_header()

    def close(self) -> None:
        """Refcounted close: the file actually closes when the last
        attach() reference releases."""
        cls = type(self)
        with cls._live_lock:
            self._refs -= 1
            if self._refs > 0:
                return
            key = os.path.abspath(self.path)
            if cls._live.get(key) is self:
                del cls._live[key]
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self) -> "MetricSeriesRecorder":
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def replay_series(path: str) -> dict:
    """Rebuild ``{"headers", "samples", "events"}`` from a metric-series
    JSONL — including every rotated segment, oldest first. Crash-
    tolerant: unparsable lines (a write cut mid-line) are skipped. An
    incompatible schema raises instead of misparsing. Samples keep
    append order (the chain is chronological by construction)."""
    headers: list[dict] = []
    samples: list[dict] = []
    events: list[dict] = []
    segs = series_segments(path)
    if not segs:
        raise OSError(f"no metric-series record at {path}")
    for seg in segs:
        with open(seg) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue  # truncated tail from a crash — ignore
                kind = obj.get("kind")
                if kind == "series":
                    schema = obj.get("schema")
                    if schema != SERIES_SCHEMA:
                        raise ValueError(
                            f"{seg}: unsupported series schema "
                            f"{schema!r} (want {SERIES_SCHEMA})"
                        )
                    headers.append(obj)
                elif kind == "sample":
                    samples.append(obj)
                else:
                    events.append(obj)
    return {"headers": headers, "samples": samples, "events": events}


def series_values(
    samples: list[dict], name: str, family: str | None = None,
) -> tuple[list[float], list[float]]:
    """One series' ``(ts, values)`` by exact rendered name (labels
    included, e.g. ``corro_runtime_rss_bytes`` or
    ``corro_kernel_health_need_last{engine="dense"}``). Samples missing
    the series are skipped — a restarted life may register it late."""
    fams = (family,) if family else ("counters", "gauges")
    ts: list[float] = []
    vals: list[float] = []
    for s in samples:
        for fam in fams:
            v = s.get(fam, {}).get(name)
            if v is not None:
                ts.append(float(s["t"]))
                vals.append(float(v))
                break
    return ts, vals


def series_names(samples: list[dict], family: str) -> list[str]:
    """Every rendered name appearing in ``family`` across the samples,
    sorted (the detectors' discovery surface)."""
    names: set[str] = set()
    for s in samples:
        names.update(s.get(family, {}))
    return sorted(names)


def record_process_sample(
    recorder: MetricSeriesRecorder, registry, t: float | None = None,
    lag_s: float | None = None,
) -> None:
    """Set the process self-observability gauges from live /proc reads
    and flush one sample — the one sampling path `loadgen soak` and
    ad-hoc harnesses share with the agent runtime loop (which sets the
    same gauges each tick before sampling)."""
    from corrosion_tpu.utils.metrics import (
        process_open_fds,
        process_rss_bytes,
        register_process_gauges,
    )

    rss_g, fds_g, lag_g = register_process_gauges(registry)
    rss = process_rss_bytes()
    if rss is not None:
        rss_g.set(rss)
    fds = process_open_fds()
    if fds is not None:
        fds_g.set(fds)
    if lag_s is not None:
        lag_g.set(lag_s)
    recorder.sample(registry, t=t)
