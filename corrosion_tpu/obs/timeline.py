"""End-to-end causal write timelines — the cross-plane correlator.

For one acked write, where did the latency go? The three evidence
sources each see a part of the journey:

- the **load generator** (client side) knows when each write was sent,
  when its HTTP ack returned, and when every subscription stream
  delivered it (``loadgen/oracle.py`` delivery records, wall-clock
  stamped);
- the **agents** (server side) export causal spans — ``api_write`` at
  ingest (continuing the client's W3C traceparent), ``commit`` around
  the store transaction, ``ingest_apply`` per gossip hop on every relay,
  ``sub_fanout`` inside the matcher (``utils/tracing.py`` JSONL export);
- the **kernel plane** optionally contributes a replayed view of the
  same workload (:mod:`corrosion_tpu.obs.journey`).

``build_timeline`` joins them on the client-minted trace id (spans) and
write key (deliveries) into one ``corro-timeline/1`` artifact:

- per-write **stage decomposition**: ``send_wait`` (client send → server
  ingest), ``ingest`` (ingest → store transaction start, the
  admission/pool queue wait), ``commit`` (store transaction through
  bookkeeping), ``gossip`` (commit → last relevant remote hop's apply),
  ``fanout`` (→ last delivery or ack, whichever is later);
- a **latency budget**: p50/p99/mean/max per stage across all
  reconstructed writes, so a tail regression names the stage that moved;
- the **reconciliation invariant**: per write, the epoch-clock-derived
  stage sum must equal the wall latency measured on the MONOTONIC clock
  (oracle ``t_send_mono`` → last delivery/ack ``t_mono`` — a clock no
  span touches) within ``tolerance_ms``, and the span-derived cut
  points must be causally ordered against the oracle's timestamps
  (send ≤ api ≤ commit-start ≤ commit-end ≤ ack; no delivery before
  commit start). A broken join, a missing span, an epoch-clock step
  mid-run, or span-vs-oracle skew fails the reconcile — the
  provenance-chain property VERDICT r5 demanded of every headline
  number.

Clock domains: span times are ``time.time_ns()`` and client stamps
``time.time()`` (one epoch clock in-process — the stage CUTS live
there), while the reconciliation wall rides ``loop.time()``
(monotonic). The stage sum telescopes to the epoch window by
construction, so only the cross-domain comparison gives the sum check
teeth; records without monotonic stamps fall back to the epoch wall
and are counted out of ``reconcile.independent_walls`` (the ordering
check still applies to them).
"""

from __future__ import annotations

import json

from corrosion_tpu.utils.tracing import trace_sampled

TIMELINE_SCHEMA = "corro-timeline/1"

STAGES = ("send_wait", "ingest", "commit", "gossip", "fanout")

# Span names the host plane emits per traced write (agent/api.py,
# agent/agent.py, agent/subs.py).
SPAN_API = "api_write"
SPAN_COMMIT = "commit"
SPAN_HOP = "ingest_apply"
SPAN_FANOUT = "sub_fanout"


def load_spans(paths) -> list[dict]:
    """Read span-export JSONL files (unparsable lines skipped — a
    crashed agent's torn tail write must not sink the whole timeline).
    An UNOPENABLE file is a different failure class: it is warned about
    on stderr by name, because silently skipping it surfaces later only
    as a cryptic coverage shortfall (e.g. relative span paths resolved
    from the wrong cwd)."""
    import sys

    spans: list[dict] = []
    for path in paths:
        try:
            f = open(path)
        except OSError as e:
            print(
                f"obs timeline: cannot read span file {path!r}: {e} "
                f"(coverage will be judged without it)",
                file=sys.stderr,
            )
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    spans.append(json.loads(line))
                except ValueError:
                    continue
    return spans


def _pct(sorted_vals: list[float], q: float) -> float:
    """Exact nearest-rank percentile over a sorted sample."""
    if not sorted_vals:
        return float("nan")
    i = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[int(i)]


def _stage_stats(values: list[float]) -> dict:
    vals = sorted(values)
    if not vals:
        return {"count": 0}
    return {
        "count": len(vals),
        "p50": round(_pct(vals, 0.50), 3),
        "p99": round(_pct(vals, 0.99), 3),
        "mean": round(sum(vals) / len(vals), 3),
        "max": round(vals[-1], 3),
    }


def _span_times(span: dict) -> tuple[float, float]:
    """(start_s, end_s) of an exported span in epoch seconds."""
    start = span["start_ns"] / 1e9
    return start, start + span["duration_us"] / 1e6


def _hop_chain_depth(hops: list[dict]) -> int:
    """Longest parent-chain of ingest_apply spans (1 = single hop) —
    how deep the rebroadcast re-stamping carried the trace."""
    by_id = {h["span_id"]: h for h in hops}
    best = 0
    for h in hops:
        depth, cur, seen = 1, h, {h["span_id"]}
        while True:
            parent = by_id.get(cur.get("parent_id"))
            if parent is None or parent["span_id"] in seen:
                break
            seen.add(parent["span_id"])
            depth += 1
            cur = parent
        best = max(best, depth)
    return best


def build_timeline(
    spans: list[dict],
    oracle_records: dict,
    *,
    sample: float = 1.0,
    tolerance_ms: float = 100.0,
    max_writes_detail: int = 64,
) -> dict:
    """Join spans + oracle records into the ``corro-timeline/1`` dict.

    ``oracle_records`` is :meth:`FanoutOracle.delivery_records` output;
    ``sample`` is the trace-sampling rate the run used (reconstruction
    coverage is judged over the writes the sampler KEPT — an unsampled
    write has no spans by design, not by failure).
    """
    by_trace: dict[str, dict[str, list[dict]]] = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], {}).setdefault(
            s["name"], []
        ).append(s)

    deliveries_by_key: dict[object, list[dict]] = {}
    n_changes = n_snapshot = 0
    for d in oracle_records.get("deliveries", ()):
        deliveries_by_key.setdefault(d["key"], []).append(d)
        if d.get("kind") == "change":
            n_changes += 1
        else:
            n_snapshot += 1

    writes = oracle_records.get("writes", ())
    traced = [w for w in writes if w.get("trace_id")]
    expected = [
        w for w in traced if trace_sampled(w["trace_id"], sample)
    ]

    stage_vals: dict[str, list[float]] = {s: [] for s in STAGES}
    wall_vals: list[float] = []
    detail: list[dict] = []
    reconstructed = 0
    remote_hop_writes = 0
    max_depth = 0
    rec_checked = rec_ok = rec_independent = ordering_violations = 0
    max_abs_err_ms = 0.0

    for w in expected:
        tid = w["trace_id"]
        tspans = by_trace.get(tid, {})
        api = tspans.get(SPAN_API, [None])[0]
        commit = tspans.get(SPAN_COMMIT, [None])[0]
        hops = tspans.get(SPAN_HOP, [])
        dels = [
            d for d in deliveries_by_key.get(w["key"], ())
            if d.get("kind") == "change"
        ]
        t_send = w.get("t_send_wall")
        t_ack = w.get("t_ack_wall")
        if api is None or commit is None or t_send is None:
            continue  # not reconstructable end-to-end
        if not dels and not deliveries_by_key.get(w["key"]):
            # No delivery evidence at all (e.g. no matching stream):
            # the journey cannot be called end-to-end.
            continue
        api_start, _api_end = _span_times(api)
        commit_start, commit_end = _span_times(commit)
        t_delivery_last = max((d["t_wall"] for d in dels), default=None)
        ends = [v for v in (t_delivery_last, t_ack) if v is not None]
        if not ends:
            continue  # snapshot-only delivery with no ack stamp
        t_end = max(ends)
        reconstructed += 1
        if hops:
            max_depth = max(max_depth, _hop_chain_depth(hops))
        # The gossip stage counts only the hop that SERVED the
        # deliveries — the ingest_apply span containing the first
        # delivery (fan-out happens inside the serving agent's apply
        # flush). Other hops of the same trace (relays that hold no
        # matching stream) are real dissemination but not on this
        # write's delivery path: counting them would charge a
        # local-fan-out write for an unrelated relay that finished later.
        serving_hop = None
        if hops and dels:
            t_first = min(d["t_wall"] for d in dels)
            slack = tolerance_ms / 1e3
            cands = [
                h for h in hops
                if _span_times(h)[0] <= t_first
                <= _span_times(h)[1] + slack
            ]
            if cands:
                # Deepest qualifying hop = the serving agent's own apply.
                serving_hop = max(cands, key=lambda h: h["start_ns"])
        if serving_hop is not None:
            remote_hop_writes += 1
            c4 = max(commit_end, _span_times(serving_hop)[0])
        else:
            c4 = commit_end
        stages_ms = {
            "send_wait": (api_start - t_send) * 1e3,
            "ingest": (commit_start - api_start) * 1e3,
            "commit": (commit_end - commit_start) * 1e3,
            "gossip": (c4 - commit_end) * 1e3,
            "fanout": (t_end - c4) * 1e3,
        }
        # The wall the stage sum answers to. The stages telescope to
        # the EPOCH-clock window (t_end - t_send) by construction, so
        # comparing against that would be a tautology: whenever the
        # run recorded monotonic-clock endpoints too (oracle commit
        # t_send_mono/t_ack_mono + per-delivery t_mono — loop.time(),
        # a clock no span touches), the wall is measured THERE. An
        # epoch-clock step (NTP slew mid-run) or any mixed-clock
        # inconsistency then shows up as stage-sum error; span-vs-
        # oracle offset skew is caught by the ordering check below.
        t_send_m = w.get("t_send_mono")
        ends_mono = [
            d["t_mono"] for d in dels if d.get("t_mono") is not None
        ]
        if w.get("t_ack_mono") is not None:
            ends_mono.append(w["t_ack_mono"])
        independent = t_send_m is not None and bool(ends_mono)
        wall_ms = (
            (max(ends_mono) - t_send_m) * 1e3 if independent
            else (t_end - t_send) * 1e3
        )
        for k, v in stages_ms.items():
            stage_vals[k].append(v)
        wall_vals.append(wall_ms)

        # Reconciliation: the stage sum against the loadgen-measured
        # wall, plus the causal ordering of span cuts vs oracle stamps.
        rec_checked += 1
        if independent:
            rec_independent += 1
        err = abs(sum(stages_ms.values()) - wall_ms)
        max_abs_err_ms = max(max_abs_err_ms, err)
        tol_s = tolerance_ms / 1e3
        ordered = (
            t_send - tol_s <= api_start
            and api_start <= commit_start + tol_s
            and commit_start <= commit_end
            and (t_ack is None or commit_end <= t_ack + tol_s)
            and all(
                d["t_wall"] >= commit_start - tol_s for d in dels
            )
        )
        if not ordered:
            ordering_violations += 1
        if err <= tolerance_ms and ordered:
            rec_ok += 1
        if len(detail) < max_writes_detail:
            detail.append({
                "key": w["key"],
                "trace_id": tid,
                "wall_ms": round(wall_ms, 3),
                "stages_ms": {
                    k: round(v, 3) for k, v in stages_ms.items()
                },
                "deliveries": len(dels),
                "hops": len(hops),
                "reconciled": err <= tolerance_ms and ordered,
            })

    coverage = reconstructed / len(expected) if expected else 0.0
    return {
        "schema": TIMELINE_SCHEMA,
        "writes_acked": len(writes),
        "writes_traced": len(traced),
        "writes_expected": len(expected),
        "writes_reconstructed": reconstructed,
        "coverage": round(coverage, 5),
        "sample": sample,
        "spans_seen": len(spans),
        "deliveries": {"changes": n_changes, "snapshot": n_snapshot},
        "hops": {
            "writes_with_remote_hop": remote_hop_writes,
            "max_chain_depth": max_depth,
        },
        "stages_ms": {k: _stage_stats(v) for k, v in stage_vals.items()},
        "wall_ms": _stage_stats(wall_vals),
        "reconcile": {
            "tolerance_ms": tolerance_ms,
            "checked": rec_checked,
            "ok": rec_ok,
            # Writes whose wall came from the monotonic clock (a domain
            # no span touches) — only those stage-sum checks are
            # non-tautological; 0 here means the records carried no
            # monotonic stamps and only the ordering check had teeth.
            "independent_walls": rec_independent,
            "ordering_violations": ordering_violations,
            "max_abs_err_ms": round(max_abs_err_ms, 3),
        },
        "writes_detail": detail,
    }


def timeline_from_run(
    run: dict, *, tolerance_ms: float = 100.0,
    max_writes_detail: int = 64,
) -> dict:
    """Build the timeline from a traced ``loadgen run`` report block —
    the ``run`` dict returned by ``scenarios.fanout_storm(trace_dir=...)``
    (its ``trace`` sub-block carries span file paths + oracle records)."""
    trace = run.get("trace")
    if not trace:
        raise ValueError(
            "run has no trace block — rerun loadgen with tracing "
            "enabled (fanout_storm(trace_dir=...) / --trace-dir)"
        )
    return build_timeline(
        load_spans(trace["span_files"]),
        trace["oracle_records"],
        sample=float(trace.get("sample", 1.0)),
        tolerance_ms=tolerance_ms,
        max_writes_detail=max_writes_detail,
    )


def timeline_ok(
    timeline: dict, min_coverage: float = 0.99
) -> tuple[bool, list[str]]:
    """The timeline acceptance verdict: coverage over sampled acked
    writes, every reconciliation check green. Returns (ok, problems)."""
    problems: list[str] = []
    if timeline["writes_expected"] == 0:
        problems.append("no traced writes to reconstruct")
    if timeline["coverage"] < min_coverage:
        problems.append(
            f"coverage {timeline['coverage']:.4f} < {min_coverage} "
            f"({timeline['writes_reconstructed']}/"
            f"{timeline['writes_expected']} writes reconstructed)"
        )
    rec = timeline["reconcile"]
    if rec["ok"] < rec["checked"]:
        problems.append(
            f"reconciliation failed for {rec['checked'] - rec['ok']}/"
            f"{rec['checked']} writes (max err "
            f"{rec['max_abs_err_ms']} ms, "
            f"{rec['ordering_violations']} ordering violations)"
        )
    return not problems, problems
