"""Metrics-name drift check: the documented series set vs reality.

docs/OBSERVABILITY.md carries a reference table of every ``corro_*``
metric/gauge/histogram name this codebase can register (between the
``metrics-ref-begin``/``-end`` markers). This module computes the
ground-truth set two ways and unions them:

- **Static**: an AST walk over the package finds every
  ``registry.counter/gauge/histogram("literal", ...)`` call — the agent
  / transport / pool / loadgen planes register by literal name.
- **Runtime**: the kernel-side publishers build names with f-strings
  (``telemetry.publish_curves`` via ``series_name``,
  ``health.publish_report``, ``epidemic.publish_epidemic``), so those
  paths are exercised against a throwaway registry and the resulting
  names collected.

``tests/test_observability.py`` asserts documented == registered, so a
new metric — including this PR's epidemic gauges — cannot land
undocumented, and a doc row cannot outlive its series.
"""

from __future__ import annotations

import ast
import os
import re

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DOC_BEGIN = "<!-- metrics-ref-begin -->"
DOC_END = "<!-- metrics-ref-end -->"

_REG_METHODS = {"counter", "gauge", "histogram"}


def static_metric_names(root: str = _PKG_ROOT) -> set[str]:
    """Every literal first argument of a ``.counter()`` / ``.gauge()`` /
    ``.histogram()`` call in the package source that names a ``corro_*``
    series."""
    names: set[str] = set()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError:
                    continue
            for node in ast.walk(tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _REG_METHODS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    continue
                name = node.args[0].value
                if name.startswith("corro_"):
                    names.add(name)
    return names


def kernel_metric_names() -> set[str]:
    """Names the kernel-side publishers register dynamically: exercise
    ``publish_curves`` (full canonical key set), ``publish_report``,
    ``publish_epidemic``, and the process self-observability gauges
    against a fresh registry and collect what landed."""
    import numpy as np

    from corrosion_tpu.obs import epidemic
    from corrosion_tpu.sim import health
    from corrosion_tpu.sim import telemetry as T
    from corrosion_tpu.utils.metrics import (
        MetricsRegistry,
        register_process_gauges,
    )

    reg = MetricsRegistry()
    curves = {k: np.ones(2) for k in T.ROUND_CURVE_KEYS}
    T.publish_curves(reg, curves)
    health.publish_report(reg, health.ConvergenceReport())
    epidemic.publish_epidemic(reg, epidemic.build_report(curves))
    register_process_gauges(reg)
    return set(reg._metrics)


def registered_metric_names() -> set[str]:
    """The complete registrable series set (static literals + the
    dynamically-built kernel names)."""
    return static_metric_names() | kernel_metric_names()


def documented_metric_names(docs_path: str) -> set[str]:
    """Every ``corro_*`` token between the metrics-ref markers of
    docs/OBSERVABILITY.md. Raises when the markers are missing — a
    deleted table must fail the drift test loudly, not vacuously."""
    with open(docs_path, encoding="utf-8") as f:
        text = f.read()
    try:
        block = text.split(DOC_BEGIN, 1)[1].split(DOC_END, 1)[0]
    except IndexError:
        raise ValueError(
            f"{docs_path}: metrics reference markers "
            f"{DOC_BEGIN!r}/{DOC_END!r} not found"
        ) from None
    return set(re.findall(r"corro_[a-z0-9_]+", block))


def render_reference(names: set[str]) -> str:
    """The marker block body for docs/OBSERVABILITY.md — one backticked
    name per line, sorted (regenerate the docs table from this when a
    metric is added)."""
    return "\n".join(f"`{n}`" for n in sorted(names))
