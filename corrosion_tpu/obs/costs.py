"""Device-cost observability: XLA cost model, memory watermarks,
roofline attribution, and the HBM capacity curve.

SURVEY's premise makes gossip rounds batched sparse scatter/gather over
ICI — flops and bytes per round are this simulator's native currency —
yet until this plane the repo measured neither: the v5e capacity claim
in docs/SCALING.md was prose arithmetic, and the pallas-vs-dense
decision had no per-kernel flop/byte data. This module extracts what
XLA already knows at compile time and reconciles it against what the
runtime actually does:

- **Cost model** (``corro-cost-model/1``): AOT-lower every jitted plane
  entry of all four engine drivers — the plain AND donated scan twins,
  and the shard_map driver at device_count ∈ {1, 8} — at fixed tiny
  configs, and extract ``cost_analysis()`` (flops, bytes accessed) +
  ``memory_analysis()`` (argument/output/temp/alias bytes) per entry,
  keyed by config fingerprint + backend + device count. The committed
  ``COST_BASELINE.json`` is this artifact; CI diffs every PR against it
  (:func:`diff_cost_models`), so a cost regression — an accidental
  dense fallback, a widened dtype, a lost donation alias — fails the
  PR that introduces it.
- **Roofline stage costs**: the SAME cumulative-prefix composite the
  timing attribution uses (``benchlib.plane_composite``) is lowered one
  prefix at a time; a stage's flops/bytes are the increment, exactly
  mirroring how its milliseconds are measured. Joined with measured
  ``plane_ms``, every bench JSON carries achieved FLOP/s, B/s, and
  arithmetic intensity per plane (``benchlib.roofline_report``).
- **Memory watermarks** (:class:`MemoryWatermarks`): live per-device
  buffer bytes sampled at chunk/epoch boundaries (via
  ``KernelTelemetry``), reconciled — in the reconcile-or-fail style of
  the timeline plane — against the static spec-arithmetic prediction
  (``parallel.mesh.predicted_per_device_bytes``) and the measured
  ``parallel.per_device_state_bytes``: breaks raise, they do not skew.
- **Capacity curve** (``corro-capacity/1``): nodes → predicted
  per-device state bytes for the flagship sharded config, derived from
  ``jax.eval_shape`` + the one placement-spec source the shard helpers
  use, validated against the lane's measured 512-node point (live, to
  the byte) and the recorded 100,352-node run — then extrapolated to
  the 500k–800k ROADMAP targets against the v5e HBM budget. This
  replaces docs/SCALING.md's prose math.

Everything here is host-side AOT work: lowering never executes a round,
and ``.lower().compile()`` does not populate the jitted entries' call
caches (pinned in tests/test_cost_plane.py), so building the model
cannot trip the compile ledger's steady-state tripwire.
"""

from __future__ import annotations

import json
import math
import time

import jax
import jax.numpy as jnp
import numpy as np

COST_SCHEMA = "corro-cost-model/1"
CAPACITY_SCHEMA = "corro-capacity/1"

ENGINES = ("dense", "sparse", "chunk", "mixed")
VARIANTS = ("plain", "donated")
#: Device counts the model covers when the host has 8 devices: the
#: unsharded anchor and the standing 8-virtual-device CPU mesh lane.
DEVICE_COUNTS = (1, 8)

#: v5e per-chip HBM, the budget the capacity verdicts gate against.
HBM_BYTES_V5E = 16 * 2**30
#: Fraction of HBM the capacity verdict leaves for the round's transient
#: working set (XLA temps, donated round-trips, collectives). The
#: measured tiny-config ``temp_bytes / argument_bytes`` ratio rides the
#: artifact as context; this headroom is the conservative gate.
CAPACITY_HEADROOM = 0.5

#: Measured validation points for :func:`capacity_model`. The 512-node
#: point is re-measured LIVE on every run (device placement is cheap);
#: the 100k point is the recorded multichip ``--large`` run
#: (docs/SCALING.md "Multi-chip": 67.8 MiB max per-device state at
#: 100,352 nodes on the (dcn=2, ici=4) mesh).
MEASURED_100K = {
    "nodes": 100_352,
    "device_count": 8,
    "per_device_bytes": 67.8 * 2**20,
    "source": "multichip --large r07 (docs/SCALING.md Multi-chip)",
}


# ---------------------------------------------------------------------------
# cost_analysis / memory_analysis extraction


def _cost_dict(compiled) -> dict:
    """Normalize ``compiled.cost_analysis()`` across jax versions
    (list-of-dict vs dict) to ``{flops, bytes_accessed}``."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return {
        "flops": float(ca.get("flops", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
    }


def _mem_dict(compiled) -> dict:
    ma = compiled.memory_analysis()
    out = {
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
    }
    # One number for "live at once" regressions to gate on. XLA's CPU
    # backend reports no explicit peak, so this is the documented
    # arguments+outputs+temps upper envelope (aliased buffers counted
    # once — donation reuses them in place).
    out["peak_bytes"] = (
        out["argument_bytes"] + out["output_bytes"] + out["temp_bytes"]
        - out["alias_bytes"]
    )
    return out


def extract_entry(lowered, rounds: int, **meta) -> dict:
    """Compile a lowered computation and extract its cost entry."""
    t0 = time.perf_counter()
    compiled = lowered.compile()
    entry = {
        **meta,
        "rounds": int(rounds),
        **_cost_dict(compiled),
        **_mem_dict(compiled),
        "aot_compile_s": round(time.perf_counter() - t0, 2),
    }
    return entry


# ---------------------------------------------------------------------------
# Tiny fixed configs + concrete scan-entry arguments, per engine.
#
# Shapes mirror the sanitize pass's tiny instances (analysis/sanitize.py)
# so "the cost of a plane entry" means the same thing to both watchers;
# node counts divide 8 so the same configs lower on the virtual mesh.


def _tiny_dense():
    from corrosion_tpu import models

    return models.merge_10k(n=32, rounds=8, samples=8)


def _tiny_sparse():
    from corrosion_tpu import models

    return models.anywrite_sparse(
        n=96, w_hot=16, n_regions=4, rounds=16, cohort=8, epoch_rounds=8,
        k_dev=8, samples=16,
    )


def _tiny_chunk():
    from corrosion_tpu.ops.chunks import ChunkConfig

    cfg = ChunkConfig(
        n_nodes=16, n_streams=2, chunk_len=64, fanout=3, sync_interval=4,
        gap_requests=4,
    )
    return cfg, [0, 5], [511, 255], 8


def _tiny_mixed():
    from corrosion_tpu.models.baselines import mixed_storm

    return mixed_storm(
        n=64, streams=2, last_seq=255, rounds=8, samples=8, n_cells=0
    )


def _mesh_for(d: int):
    from corrosion_tpu.sim import benchlib

    if d <= 1:
        return None
    if len(jax.devices()) < d:
        raise ValueError(
            f"cost model at device_count={d} needs {d} devices, have "
            f"{len(jax.devices())} — set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={d}"
        )
    return benchlib.multichip_mesh(d)


def _bcast_for(mesh):
    from corrosion_tpu import parallel

    return None if mesh is None else parallel.make_sharded_broadcast(mesh)


def _lower_dense(variant: str, mesh) -> tuple[object, int, str]:
    from corrosion_tpu import parallel
    from corrosion_tpu.parallel import mesh as mesh_mod
    from corrosion_tpu.sim import benchlib, engine

    cfg, topo, sched = _tiny_dense()
    n_regions = int(np.asarray(topo.region).max()) + 1
    state = engine.init_cluster(cfg, len(sched.sample_writer))
    if mesh is not None:
        state = mesh_mod.shard_cluster_state(state, mesh)
        topo = parallel.replicate(topo, mesh)
    writes = jnp.asarray(sched.writes, dtype=jnp.uint32)
    kill = revive = jnp.zeros((sched.rounds, 1), dtype=bool)
    part = jnp.zeros((sched.rounds, n_regions, n_regions), dtype=bool)
    xs = (
        writes, part, kill, revive,
        jnp.arange(sched.rounds, dtype=jnp.int32), None, None, None,
    )
    fn = (
        engine._scan_rounds if variant == "plain"
        else engine._scan_rounds_donated
    )
    lowered = fn.lower(
        state, topo, xs, jnp.asarray(sched.sample_writer),
        jnp.asarray(sched.sample_ver), jnp.asarray(sched.sample_round),
        jax.random.PRNGKey(0), cfg, False, bcast_fn=_bcast_for(mesh),
    )
    return lowered, sched.rounds, benchlib.config_fingerprint(
        cfg, sched.rounds, len(sched.sample_writer)
    )


def _lower_sparse(variant: str, mesh) -> tuple[object, int, str]:
    from corrosion_tpu import parallel
    from corrosion_tpu.ops import sparse_writers as sw_ops
    from corrosion_tpu.ops import swim as swim_ops
    from corrosion_tpu.parallel import mesh as mesh_mod
    from corrosion_tpu.sim import benchlib, sparse_engine

    cfg, topo, sched = _tiny_sparse()
    sp = cfg.sparse
    n = cfg.n_nodes
    n_regions = int(np.asarray(topo.region).max()) + 1
    # The driver rebinds the writer arrays from the planner each epoch
    # (simulate_sparse); lowering only needs their shapes/dtypes.
    topo = topo._replace(
        writer_nodes=jnp.zeros(cfg.w_hot, jnp.int32),
        writer_of_node=jnp.full(n, -1, jnp.int32),
        writer_ids=jnp.zeros(cfg.w_hot, jnp.uint32),
    )
    sstate = sw_ops.init_sparse(cfg.gossip, sp)
    swim_state = swim_ops.impl(cfg.swim).init_state(cfg.swim)
    n_samples = len(sched.sample_writer)
    vis = jnp.full((n_samples, n), -1, jnp.int32)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        node = mesh_mod._node_axis(mesh, None)
        sstate = mesh_mod.shard_sparse_state(sstate, mesh)
        swim_state = mesh_mod.shard_node_major(swim_state, mesh, node)
        vis = jax.device_put(vis, NamedSharding(mesh, P(None, node)))
        topo = parallel.replicate(topo, mesh)
    el = sp.epoch_rounds
    writes_slots = jnp.zeros((el, cfg.w_hot), jnp.uint32)
    kill = revive = jnp.zeros((el, 1), bool)
    part = jnp.zeros((el, n_regions, n_regions), bool)
    s_slot = jnp.zeros((n_samples,), jnp.int32)
    ridx = jnp.arange(el, dtype=jnp.int32)
    fn = (
        sparse_engine._epoch_scan if variant == "plain"
        else sparse_engine._epoch_scan_donated
    )
    lowered = fn.lower(
        sstate, swim_state, vis, topo,
        (writes_slots, kill, revive, ridx, None, None), part,
        s_slot, jnp.asarray(sched.sample_ver),
        jnp.asarray(sched.sample_round), jax.random.PRNGKey(0),
        cfg, sp, False, bcast_fn=_bcast_for(mesh),
    )
    return lowered, el, benchlib.config_fingerprint(cfg, el, n_samples)


def _lower_chunk(variant: str, mesh) -> tuple[object, int, str]:
    from corrosion_tpu.ops import chunks as chunk_ops
    from corrosion_tpu.parallel import mesh as mesh_mod
    from corrosion_tpu.sim import benchlib, chunk_engine

    cfg, origin, last_seq, rounds = _tiny_chunk()
    origin = jnp.asarray(origin, jnp.int32)
    last_seq = jnp.asarray(last_seq, jnp.int32)
    state = chunk_ops.init_chunks(cfg, origin, last_seq)
    vis = jnp.full((cfg.n_nodes, cfg.n_streams), -1, jnp.int32)
    alive = jnp.ones((cfg.n_nodes,), bool)
    if mesh is not None:
        # The chunk plane is GSPMD-placed (no broadcast queue exchange
        # to stage explicitly) — same path as simulate_chunks_sharded.
        from jax.sharding import NamedSharding, PartitionSpec as P

        node = mesh_mod._node_axis(mesh, None)
        state = mesh_mod.shard_chunk_state(state, mesh)
        vis = jax.device_put(vis, NamedSharding(mesh, P(node, None)))
        last_seq = jax.device_put(last_seq, NamedSharding(mesh, P()))
    xs = (jnp.arange(rounds, dtype=jnp.int32), None, None, None)
    fn = (
        chunk_engine._scan if variant == "plain"
        else chunk_engine._scan_donated
    )
    lowered = fn.lower(
        state, vis, last_seq, alive, jax.random.PRNGKey(1), xs, cfg
    )
    return lowered, rounds, benchlib.config_fingerprint(cfg, rounds)


def _lower_mixed(variant: str, mesh) -> tuple[object, int, str]:
    from corrosion_tpu import parallel
    from corrosion_tpu.parallel import mesh as mesh_mod
    from corrosion_tpu.sim import benchlib, mixed_engine

    cfg, ccfg, topo, sched, spec = _tiny_mixed()
    state = mixed_engine.init_mixed_state(cfg, ccfg, topo, sched, spec)
    if mesh is not None:
        state = mesh_mod.shard_mixed_state(state, mesh)
        topo = parallel.replicate(topo, mesh)
    rounds = sched.rounds
    n_regions = topo.region_rtt.shape[0]
    writes = jnp.asarray(sched.writes, jnp.uint32)
    commit = np.zeros((rounds, len(spec.writer)), bool)
    for s, r in enumerate(spec.commit_round):
        if 0 <= r < rounds:
            commit[r, s] = True
    kill = revive = jnp.zeros((rounds, 1), dtype=bool)
    part = jnp.zeros((rounds, n_regions, n_regions), dtype=bool)
    xs = (
        writes, jnp.asarray(commit), part, kill, revive,
        jnp.arange(rounds, dtype=jnp.int32), None, None, None,
    )
    fn = (
        mixed_engine._scan_mixed if variant == "plain"
        else mixed_engine._scan_mixed_donated
    )
    lowered = fn.lower(
        state, topo, xs, jnp.asarray(spec.writer, jnp.int32),
        jnp.asarray(spec.version, jnp.uint32),
        jnp.asarray(spec.last_seq, jnp.int32),
        jnp.asarray(sched.sample_writer), jnp.asarray(sched.sample_ver),
        jnp.asarray(sched.sample_round), jax.random.PRNGKey(0),
        cfg, ccfg, False, bcast_fn=_bcast_for(mesh),
    )
    return lowered, rounds, benchlib.config_fingerprint(
        cfg, ccfg, rounds, len(sched.sample_writer)
    )


_LOWERERS = {
    "dense": _lower_dense,
    "sparse": _lower_sparse,
    "chunk": _lower_chunk,
    "mixed": _lower_mixed,
}

_ENTRY_NAMES = {
    "dense": "_scan_rounds",
    "sparse": "_epoch_scan",
    "chunk": "_scan",
    "mixed": "_scan_mixed",
}


def entry_key(engine: str, variant: str, device_count: int) -> str:
    return f"{engine}/{variant}/d{device_count}"


def cost_entry(engine: str, variant: str, device_count: int = 1) -> dict:
    """AOT-lower one engine's scan entry and extract its cost entry."""
    mesh = _mesh_for(device_count)
    lowered, rounds, fingerprint = _LOWERERS[engine](variant, mesh)
    name = _ENTRY_NAMES[engine] + ("_donated" if variant == "donated" else "")
    return extract_entry(
        lowered, rounds,
        engine=engine, entry=name, variant=variant,
        device_count=device_count, config_fingerprint=fingerprint,
    )


def build_cost_model(
    engines=ENGINES,
    variants=VARIANTS,
    device_counts=(1,),
    progress=None,
) -> dict:
    """The ``corro-cost-model/1`` artifact: one cost entry per
    engine × variant × device count, plus self-describing provenance.

    Sharded entries (device_count > 1) report PER-DEVICE numbers —
    that is what ``cost_analysis`` measures for an SPMD executable, and
    it is the per-chip roofline the capacity questions need.
    """
    from corrosion_tpu.ops import onehot

    entries: dict[str, dict] = {}
    for d in sorted(device_counts):
        for eng in engines:
            for var in variants:
                key = entry_key(eng, var, d)
                if progress is not None:
                    progress.write(f"[cost] lowering {key}\n")
                    progress.flush()
                entries[key] = cost_entry(eng, var, device_count=d)
    return {
        "schema": COST_SCHEMA,
        "platform": jax.devices()[0].platform,
        "device_count": len(jax.devices()),
        "backend": onehot.resolve_backend(None),
        "jax_version": jax.__version__,
        # The diff gate's relative-increase ceiling, committed with the
        # baseline so a hand-edited file never gates tighter than the
        # documented workflow (same rule as bench_budget.json).
        "tolerance": DEFAULT_COST_TOLERANCE,
        "engines": list(engines),
        "variants": list(variants),
        "device_counts": sorted(device_counts),
        "entries": entries,
    }


def save_model(model: dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(model, f, indent=2)
        f.write("\n")


def load_model(path: str) -> dict:
    with open(path) as f:
        model = json.load(f)
    if model.get("schema") != COST_SCHEMA:
        raise ValueError(
            f"{path}: schema {model.get('schema')!r} is not {COST_SCHEMA}"
        )
    return model


#: Metrics the baseline diff gates on (increase beyond tolerance fails).
GATED_METRICS = ("flops", "bytes_accessed", "peak_bytes", "temp_bytes")
#: Default relative-increase tolerance: the gate catches structural
#: regressions (a lost donation alias, a dense fallback, a widened
#: dtype — tens of percent to multi-×), not XLA-version scheduling
#: noise.
DEFAULT_COST_TOLERANCE = 0.25


def diff_cost_models(
    base: dict, cand: dict, tolerance: float | None = None
) -> tuple[bool, list[str], list[str]]:
    """Gate a freshly built model against the committed baseline.

    Returns ``(ok, breaches, notes)``. Breaches: cross-platform /
    cross-backend comparison (refused outright, the house provenance
    rule), entries missing from the candidate, config-fingerprint
    drift (the tiny shapes changed without a baseline refresh), and
    any gated metric increasing beyond ``tolerance`` (relative).
    Decreases are reported as notes — improvements land with a
    baseline refresh, they do not fail the gate.
    """
    tol = (
        float(base.get("tolerance", DEFAULT_COST_TOLERANCE))
        if tolerance is None else tolerance
    )
    breaches: list[str] = []
    notes: list[str] = []
    for dim in ("platform", "backend"):
        if base.get(dim) != cand.get(dim):
            breaches.append(
                f"{dim}: baseline {base.get(dim)!r} vs measured "
                f"{cand.get(dim)!r} — cost baselines do not compare "
                f"across {dim}s; rerun `obs cost show --out "
                f"COST_BASELINE.json` on the target {dim}"
            )
    if base.get("jax_version") != cand.get("jax_version"):
        notes.append(
            f"jax_version drift: baseline {base.get('jax_version')} vs "
            f"{cand.get('jax_version')} (tolerance absorbs codegen "
            f"movement; refresh the baseline on toolchain bumps)"
        )
    for key, b in base.get("entries", {}).items():
        c = cand.get("entries", {}).get(key)
        if c is None:
            breaches.append(f"{key}: missing from measurement")
            continue
        if b.get("config_fingerprint") != c.get("config_fingerprint"):
            breaches.append(
                f"{key}: config fingerprint "
                f"{c.get('config_fingerprint')} != baseline "
                f"{b.get('config_fingerprint')} — the fixed tiny shapes "
                f"changed; refresh COST_BASELINE.json with the change"
            )
            continue
        for m in GATED_METRICS:
            bv, cv = float(b.get(m, 0.0)), float(c.get(m, 0.0))
            if bv <= 0:
                continue
            rel = (cv - bv) / bv
            if rel > tol:
                breaches.append(
                    f"{key}.{m}: {cv:.0f} > baseline {bv:.0f} "
                    f"(+{rel:.0%}, tolerance {tol:.0%})"
                )
            elif rel < -tol:
                notes.append(
                    f"{key}.{m}: {cv:.0f} improved {rel:.0%} vs baseline "
                    f"— refresh COST_BASELINE.json to lock it in"
                )
    for key in cand.get("entries", {}):
        if key not in base.get("entries", {}):
            notes.append(f"{key}: new entry (not in baseline)")
    return not breaches, breaches, notes


# ---------------------------------------------------------------------------
# Roofline stage costs: the cumulative-prefix composite, in flops/bytes.


def roofline_stage_costs(composite, stages, carry0) -> dict:
    """Per-stage flops/bytes by lowering the SAME cumulative prefixes
    the timing attribution scans (``telemetry.attribute_planes``): a
    stage's cost is the increment of the single-round composite with it
    enabled. Increments telescope exactly like the wall-clock ones, so
    the flop/byte partition matches the millisecond partition stage for
    stage. Returns ``{stage: {flops, bytes}}`` (clamped at 0 — XLA may
    fuse a later stage into earlier work).

    Deliberately compiles its OWN single-step prefixes rather than
    reusing ``attribute_planes``'s scan-wrapped executables: a scan's
    ``cost_analysis`` counts the while-loop body once regardless of
    trip count and folds in scan plumbing, so it is not the per-round
    number — the extra N+1 single-step compiles (cheap relative to the
    scan compiles the timing pass already pays) buy an honest unit."""
    cum = []
    for k in range(len(stages) + 1):
        step = composite(tuple(stages[:k]))
        compiled = jax.jit(step).lower(carry0, jnp.int32(0)).compile()
        cum.append(_cost_dict(compiled))
    out = {}
    for k, s in enumerate(stages):
        out[s] = {
            "flops": max(cum[k + 1]["flops"] - cum[k]["flops"], 0.0),
            "bytes": max(
                cum[k + 1]["bytes_accessed"] - cum[k]["bytes_accessed"],
                0.0,
            ),
        }
    return out


# ---------------------------------------------------------------------------
# Live per-device memory watermarks + the reconcile-or-fail check.


def live_device_bytes() -> dict:
    """Live committed-buffer bytes per device, from the runtime's own
    array registry (``jax.live_arrays``) — works on backends with no
    allocator stats (CPU). Device allocator stats
    (``device.memory_stats``) ride alongside where the platform
    provides them (TPU ``bytes_in_use``/``peak_bytes_in_use``)."""
    out: dict = {}
    for arr in jax.live_arrays():
        try:
            shards = arr.addressable_shards
        except Exception:
            continue
        for s in shards:
            n = int(np.prod(s.data.shape or (1,))) * s.data.dtype.itemsize
            out[s.device] = out.get(s.device, 0) + n
    return out


class MemoryWatermarks:
    """Per-device live-byte high-water marks, sampled at chunk/epoch
    boundaries by ``KernelTelemetry`` (``watermarks=`` field)."""

    def __init__(self):
        self.peak: dict = {}
        self.allocator_peak: dict = {}
        self.samples = 0

    def sample(self) -> dict:
        live = live_device_bytes()
        for dev, n in live.items():
            if n > self.peak.get(dev, 0):
                self.peak[dev] = n
        for dev in jax.devices():
            stats = dev.memory_stats() or {}
            pk = stats.get("peak_bytes_in_use")
            if pk is not None and pk > self.allocator_peak.get(dev, 0):
                self.allocator_peak[dev] = pk
        self.samples += 1
        return live

    def to_dict(self) -> dict:
        return {
            "samples": self.samples,
            "peak_bytes": {
                str(dev): n for dev, n in sorted(
                    self.peak.items(), key=lambda kv: str(kv[0])
                )
            },
            "allocator_peak_bytes": {
                str(dev): n for dev, n in sorted(
                    self.allocator_peak.items(), key=lambda kv: str(kv[0])
                )
            },
        }


def reconcile_memory(
    final_state,
    watermarks: MemoryWatermarks | None = None,
    predicted_per_device: int | None = None,
    cost: dict | None = None,
    tol: float = 0.01,
) -> dict:
    """Reconcile the three views of per-device state memory; breaks
    raise ValueError (the house reconcile-or-fail rule), agreement
    returns the joined report.

    1. **measured vs predicted**: ``parallel.per_device_state_bytes``
       (live addressable shards) must equal the static spec-arithmetic
       prediction per device within ``tol`` — placement drift between
       the shard helpers and the capacity model is a break, not skew.
    2. **watermark covers state**: every device's live high-water mark
       must be at least its measured state bytes (the state was live
       when sampled; a smaller watermark means the sampler missed
       devices or the run freed state it still reports).
    3. **memory_analysis covers state**: when a cost entry for the
       entry point is supplied, its per-device ``output_bytes`` must
       cover the per-device state (the scan's output carries the state
       plus the stacked curves — a prediction below the state means
       the lowered entry and the run disagree about shapes).
    """
    from corrosion_tpu import parallel

    measured = parallel.per_device_state_bytes(final_state)
    if not measured:
        raise ValueError(
            "reconcile_memory: state has no addressable shards — was the "
            "final state deleted (donated) before reconciling?"
        )
    problems: list[str] = []
    per_dev = sorted(measured.values())
    if predicted_per_device is not None:
        for dev, got in sorted(measured.items(), key=lambda kv: str(kv[0])):
            if abs(got - predicted_per_device) > tol * max(
                predicted_per_device, 1
            ):
                problems.append(
                    f"{dev}: measured state {got} B != predicted "
                    f"{predicted_per_device} B (tol {tol:.0%})"
                )
    if watermarks is not None:
        if not watermarks.samples:
            problems.append("watermarks were never sampled")
        for dev, got in measured.items():
            wm = watermarks.peak.get(dev, 0)
            if wm + 1 < got:  # +1: exact integer domain, no fuzz needed
                problems.append(
                    f"{dev}: live watermark {wm} B below the device's own "
                    f"state bytes {got} B — the sampler missed this device"
                )
    if cost is not None:
        out_b = int(cost.get("output_bytes", 0))
        if out_b and out_b + 1 < max(per_dev):
            problems.append(
                f"memory_analysis output_bytes {out_b} B does not cover "
                f"the per-device state {max(per_dev)} B — the lowered "
                f"entry and the run disagree about state shapes"
            )
    if problems:
        raise ValueError(
            "per-device memory reconciliation failed:\n  "
            + "\n  ".join(problems)
        )
    return {
        "devices": len(measured),
        "state_bytes_per_device_max": max(per_dev),
        "state_bytes_per_device_min": min(per_dev),
        "predicted_per_device": predicted_per_device,
        "watermarks": None if watermarks is None else watermarks.to_dict(),
    }


# ---------------------------------------------------------------------------
# Capacity curve: nodes -> predicted per-device bytes, validated.


def flagship_cfg(n_nodes: int, samples: int = 16):
    """The flagship sharded config family (``benchlib._measure_large``'s
    exact shape): wan_100k at 8 regions, queue depth 16, writer count
    ``min(128, n/4)`` — the configuration the 100,352-node measured
    point ran, and the one the 500k–800k ROADMAP run will use."""
    from dataclasses import replace as dc_replace

    from corrosion_tpu import models

    n_writers = min(128, n_nodes // 4)
    cfg, topo, sched = models.wan_100k(
        n=n_nodes, n_regions=8, n_writers=n_writers, rounds=16,
        samples=samples, partition=False,
    )
    cfg = dc_replace(cfg, gossip=dc_replace(cfg.gossip, queue=16))
    return cfg, topo, sched


def predicted_state_bytes(cfg, n_samples: int, mesh) -> int:
    """Per-device state bytes for a dense ClusterState under the
    standard placement — pure ``eval_shape`` + spec arithmetic, no
    allocation (a 1M-node prediction costs microseconds)."""
    from corrosion_tpu.parallel import mesh as mesh_mod
    from corrosion_tpu.sim import engine

    shapes = jax.eval_shape(lambda: engine.init_cluster(cfg, n_samples))
    specs = mesh_mod.cluster_state_specs(shapes, mesh)
    return mesh_mod.predicted_per_device_bytes(shapes, specs, mesh)


#: The capacity curve's default node grid: the measured 100k anchor and
#: its multiples through the ROADMAP 500k-800k window to 1M, every
#: count divisible by 8 regions x 8 devices.
CAPACITY_NODE_GRID = (100_352, 250_880, 401_408, 501_760, 802_816, 1_003_520)


def capacity_model(
    node_counts=CAPACITY_NODE_GRID,
    device_count: int = 8,
    validate_live: bool = True,
    hbm_bytes: int = HBM_BYTES_V5E,
    tol: float = 0.05,
) -> dict:
    """The ``corro-capacity/1`` artifact: predicted per-device state
    bytes over ``node_counts`` for the flagship config on the standing
    (dcn, ici) mesh, validated against measured points, with a
    fits/exceeds verdict per count against the HBM budget.

    Validation (reconcile-or-fail — a failed point raises, the artifact
    is never emitted from a model that contradicts its measurements):

    - the **512-node lane point**, re-measured live on this host's mesh
      (the multichip lane's merge_10k shape): prediction must equal the
      measured ``per_device_state_bytes`` exactly;
    - the **100,352-node recorded point** (multichip ``--large``):
      prediction within ``tol``.
    """
    from corrosion_tpu.sim import benchlib

    mesh = _mesh_for(device_count)
    if mesh is None:
        raise ValueError("capacity_model needs device_count > 1")

    validation: dict = {}
    if validate_live:
        from corrosion_tpu import models, parallel
        from corrosion_tpu.parallel import mesh as mesh_mod
        from corrosion_tpu.sim import engine

        cfg512, _topo, sched512 = models.merge_10k(
            n=benchlib.MULTICHIP_NODES, rounds=8, samples=64
        )
        st = mesh_mod.shard_cluster_state(
            engine.init_cluster(cfg512, len(sched512.sample_writer)), mesh
        )
        measured512 = max(parallel.per_device_state_bytes(st).values())
        predicted512 = predicted_state_bytes(
            cfg512, len(sched512.sample_writer), mesh
        )
        if measured512 != predicted512:
            raise ValueError(
                f"capacity validation failed at the 512-node lane point: "
                f"predicted {predicted512} B != measured {measured512} B "
                f"per device — the placement specs and the shard helpers "
                f"have drifted"
            )
        validation["lane_512"] = {
            "nodes": benchlib.MULTICHIP_NODES,
            "predicted_bytes": predicted512,
            "measured_bytes": measured512,
            "exact": True,
        }

    cfg100k, _, sched100k = flagship_cfg(MEASURED_100K["nodes"])
    pred100k = predicted_state_bytes(
        cfg100k, len(sched100k.sample_writer), mesh
    )
    rec = MEASURED_100K["per_device_bytes"]
    rel = abs(pred100k - rec) / rec
    if rel > tol:
        raise ValueError(
            f"capacity validation failed at the recorded 100k point: "
            f"predicted {pred100k / 2**20:.1f} MiB vs measured "
            f"{rec / 2**20:.1f} MiB ({rel:.1%} > {tol:.0%}) — "
            f"{MEASURED_100K['source']}"
        )
    validation["large_100k"] = {
        **{k: v for k, v in MEASURED_100K.items()},
        "predicted_bytes": pred100k,
        "relative_error": round(rel, 4),
    }

    budget = int(hbm_bytes * (1 - CAPACITY_HEADROOM))
    curve = []
    for n in sorted(node_counts):
        cfg, _, sched = flagship_cfg(n)
        per_dev = predicted_state_bytes(cfg, len(sched.sample_writer), mesh)
        curve.append({
            "nodes": n,
            "per_device_bytes": per_dev,
            "per_device_mib": round(per_dev / 2**20, 1),
            "hbm_fraction": round(per_dev / hbm_bytes, 4),
            "verdict": (
                "fits" if per_dev <= budget
                else "tight" if per_dev <= hbm_bytes
                else "exceeds"
            ),
        })
    model = {
        "schema": CAPACITY_SCHEMA,
        "platform": jax.devices()[0].platform,
        "device_count": device_count,
        "mesh": {
            a: int(mesh.shape[a]) for a in mesh.axis_names
        },
        "engine": "dense",
        "config_family": "wan_100k(n_regions=8, queue=16, "
                         "n_writers=min(128, n/4))",
        "hbm_bytes": hbm_bytes,
        "hbm_headroom_fraction": CAPACITY_HEADROOM,
        "validation": validation,
        "curve": curve,
    }
    if len(curve) > 1:
        model["state_bytes_per_node"] = round(bytes_per_node(model), 1)
    return model


def bytes_per_node(model: dict) -> float:
    """Marginal per-device bytes per node from the capacity curve's
    endpoints (the replicated floor cancels)."""
    c = model["curve"]
    lo, hi = c[0], c[-1]
    d = math.prod(model["mesh"].values())
    return (
        (hi["per_device_bytes"] - lo["per_device_bytes"])
        / (hi["nodes"] - lo["nodes"])
        * d
    )
