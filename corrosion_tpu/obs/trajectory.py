"""Bench trajectory: the committed ``BENCH_r*.json`` /
``MULTICHIP_r*.json`` artifacts read as one provenance-checked series.

Every PR commits a driver-captured bench artifact, but until this tool
nothing read them AS A SERIES — which is exactly how the r04→r05
incident survived review: round 5's artifact was a 512-node CPU
fallback published under the 10k-TPU metric name, and only a human
diffing two JSON files could notice. The trajectory report makes that
class mechanical:

- Each artifact is parsed into one row: metric, value, step fields,
  and the provenance the emit sites now assert (platform / nodes /
  kernels / device_count). Pre-PR-6 artifacts carry no provenance in
  the emitted JSON, so the parser recovers it from the driver-captured
  stderr ``[bench]`` diagnostic line — recovered fields are labeled
  ``provenance: "stderr"``, never silently promoted to first-class.
- Consecutive rows are comparable ONLY when platform, nodes, and
  kernel backend all match; a mismatch is a **comparability break**:
  no delta is computed across it, and the row is flagged
  (``flags: ["platform tpu->cpu", "nodes 10000->512"]`` — the r05
  artifact, mechanically). The same refuse-to-compare rule the budget
  gate applies (``benchlib.check_budget`` shape dims), applied
  backwards over history.
- Multichip artifacts are a separate lane: per round, did the sharded
  dryrun run, at what device count, and did every plane converge.

``corrosion obs trajectory`` renders the report; the JSON form is
``corro-bench-trajectory/1``.
"""

from __future__ import annotations

import glob
import json
import os
import re

TRAJECTORY_SCHEMA = "corro-bench-trajectory/1"

#: Provenance dims that must match for two rows to be comparable — the
#: same dims ``benchlib.check_budget`` refuses to gate across.
COMPARABILITY_DIMS = ("platform", "nodes", "kernels")


def _diag_from_tail(tail: str) -> dict:
    """Recover provenance from the driver-captured stderr: the
    ``[bench] {json}`` diagnostic line (r02+) or the prose
    ``[bench] platform=tpu nodes=10000 ...`` form (r01)."""
    out: dict = {}
    for line in tail.splitlines():
        line = line.strip()
        if not line.startswith("[bench]"):
            continue
        body = line[len("[bench]"):].strip()
        if body.startswith("{"):
            try:
                out.update(json.loads(body))
                continue
            except ValueError:
                pass
        for key, cast in (("platform", str), ("nodes", int),
                          ("rounds", int)):
            m = re.search(rf"\b{key}=(\S+)", body)
            if m:
                try:
                    out.setdefault(key, cast(m.group(1)))
                except ValueError:
                    pass
    return out


def parse_bench_artifact(path: str) -> dict:
    """One trajectory row from a driver-captured BENCH_r*.json."""
    with open(path) as f:
        wrapper = json.load(f)
    parsed = wrapper.get("parsed") or {}
    row = {
        "file": os.path.basename(path),
        "round": wrapper.get("n"),
        "rc": wrapper.get("rc"),
        "metric": parsed.get("metric"),
        "value": parsed.get("value"),
        "unit": parsed.get("unit"),
        "step_ms": parsed.get("step_ms"),
        "step_inner_ms": parsed.get("step_inner_ms"),
        "converged": parsed.get("converged"),
        "throughput_changes_per_s": parsed.get("throughput_changes_per_s"),
        "compile_ms": parsed.get("compile_ms"),
    }
    # Self-describing artifacts (PR 6+) carry provenance in the emitted
    # JSON; older rounds only in the stderr diagnostics.
    diag = _diag_from_tail(wrapper.get("tail", ""))
    for dim in ("platform", "nodes", "kernels", "device_count"):
        if parsed.get(dim) is not None:
            row[dim] = parsed[dim]
            row.setdefault("provenance", "emitted")
        elif diag.get(dim) is not None:
            row[dim] = diag[dim]
            row["provenance"] = "stderr"
    row.setdefault("provenance", "missing")
    return row


def parse_multichip_artifact(path: str) -> dict:
    """One multichip-lane row from a driver-captured MULTICHIP_r*.json
    (dryrun prose tails in the committed rounds; JSON tails for the
    self-describing era)."""
    with open(path) as f:
        wrapper = json.load(f)
    m = re.search(r"MULTICHIP_r(\d+)", os.path.basename(path))
    row = {
        "file": os.path.basename(path),
        "round": int(m.group(1)) if m else None,
        "rc": wrapper.get("rc"),
        "ok": wrapper.get("ok"),
        "device_count": wrapper.get("n_devices"),
    }
    tail = wrapper.get("tail", "").strip()
    last = tail.splitlines()[-1].strip() if tail else ""
    if last.startswith("{"):
        try:
            parsed = json.loads(last)
            row["converged"] = all(
                p.get("converged") for p in parsed.get("planes", {}).values()
            ) if "planes" in parsed else parsed.get("converged")
            row["nodes"] = parsed.get("nodes")
            row["provenance"] = "emitted"
            return row
        except ValueError:
            pass
    nm = re.search(r"(\d+) nodes", last)
    row["nodes"] = int(nm.group(1)) if nm else None
    row["converged"] = (
        "need=0" in last or "converged=True" in last
    ) if last else None
    row["provenance"] = "stderr" if last else "missing"
    return row


def _compare(prev: dict, row: dict) -> tuple[bool, list[str], list[str]]:
    """Comparability verdict between consecutive rows of one metric.

    A dim breaks comparability only when KNOWN on both sides and
    different — the r05 shape (platform tpu→cpu, nodes 10000→512). A
    dim the era's artifacts never recorded (``kernels`` before PR 6)
    is a warning: the comparison is unverifiable on that axis, not
    provably wrong."""
    flags = []
    warnings = []
    for dim in COMPARABILITY_DIMS:
        a, b = prev.get(dim), row.get(dim)
        if a is not None and b is not None and a != b:
            flags.append(f"{dim} {a}->{b}")
        elif a is None or b is None:
            warnings.append(f"{dim} unverifiable (not recorded)")
    if prev.get("metric") != row.get("metric"):
        flags.append(f"metric {prev.get('metric')}->{row.get('metric')}")
    return not flags, flags, warnings


def build_trajectory(root: str = ".") -> dict:
    """Aggregate every committed bench/multichip artifact under
    ``root`` into the ``corro-bench-trajectory/1`` report."""
    bench = sorted(
        glob.glob(os.path.join(root, "BENCH_r*.json")),
        key=lambda p: p,
    )
    multi = sorted(glob.glob(os.path.join(root, "MULTICHIP_r*.json")))
    rows = [parse_bench_artifact(p) for p in bench]
    breaks = []
    prev = None
    for row in rows:
        if prev is None:
            row["comparable_with_prev"] = None
            row["flags"] = []
            row["warnings"] = []
        else:
            ok, flags, warnings = _compare(prev, row)
            row["comparable_with_prev"] = ok
            row["flags"] = flags
            row["warnings"] = warnings
            if ok and isinstance(prev.get("value"), (int, float)) and \
                    isinstance(row.get("value"), (int, float)):
                row["value_delta"] = round(row["value"] - prev["value"], 3)
                if isinstance(prev.get("step_ms"), (int, float)) and \
                        isinstance(row.get("step_ms"), (int, float)):
                    row["step_ms_delta"] = round(
                        row["step_ms"] - prev["step_ms"], 1
                    )
            elif not ok:
                breaks.append({
                    "from": prev["file"],
                    "to": row["file"],
                    "flags": flags,
                })
        prev = row
    mrows = [parse_multichip_artifact(p) for p in multi]
    return {
        "schema": TRAJECTORY_SCHEMA,
        "root": os.path.abspath(root),
        "bench": rows,
        "comparability_breaks": breaks,
        "multichip": mrows,
    }


def render_trajectory(traj: dict) -> str:
    lines = ["bench trajectory:"]
    for r in traj["bench"]:
        mark = (
            "    " if r["comparable_with_prev"] is None
            else " ok " if r["comparable_with_prev"] else "BRK "
        )
        step = f" step_ms={r['step_ms']}" if r.get("step_ms") else ""
        delta = (
            f" (Δ{r['value_delta']:+})" if "value_delta" in r else ""
        )
        lines.append(
            f"  [{mark}] {r['file']}: {r.get('metric')}="
            f"{r.get('value')}{r.get('unit') or ''}{delta}{step} "
            f"platform={r.get('platform')} nodes={r.get('nodes')} "
            f"kernels={r.get('kernels')} [{r.get('provenance')}]"
        )
        for f in r.get("flags", []):
            lines.append(f"         ! not comparable: {f}")
        for w in r.get("warnings", []):
            lines.append(f"         ~ {w}")
    if traj["comparability_breaks"]:
        lines.append(
            f"  {len(traj['comparability_breaks'])} comparability "
            f"break(s) — deltas across them are refused, not computed"
        )
    lines.append("multichip lane:")
    for r in traj["multichip"]:
        lines.append(
            f"  {r['file']}: devices={r.get('device_count')} "
            f"nodes={r.get('nodes')} converged={r.get('converged')} "
            f"ok={r.get('ok')} [{r.get('provenance')}]"
        )
    return "\n".join(lines)
