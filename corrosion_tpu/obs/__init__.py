"""Observability plane: causal write timelines + kernel write journeys.

The ``obs`` package owns the cross-plane observability logic the ``obs``
CLI group exposes (promoted out of ``cli.py``):

- :mod:`corrosion_tpu.obs.timeline` — the correlator that joins agent
  span exports (``utils/tracing.py`` JSONL), loadgen oracle delivery
  records (``loadgen/oracle.py``), and optionally a kernel write-journey
  reconstruction into one ``corro-timeline/1`` artifact with a
  latency-budget report: for one acked write, where did the latency go —
  send-wait / ingest-wait / commit / gossip-hops / fan-out — with every
  write's stage sum reconciled against the independently measured wall
  latency.
- :mod:`corrosion_tpu.obs.journey` — the kernel-plane reconstructor:
  given a flight JSONL and a recorded ``sim/trace.py`` workload, derive
  each write's commit round, delivery-round profile, and queue-dwell
  estimate from the existing round curves and delivery-latency buckets —
  no new traced code.
- :mod:`corrosion_tpu.obs.costs` — the device-cost plane: the AOT XLA
  cost model over every engine entry (``corro-cost-model/1``), roofline
  stage costs, live per-device memory watermarks with the
  reconcile-or-fail check, and the ``corro-capacity/1`` HBM curve.
- :mod:`corrosion_tpu.obs.ledger` — the runtime compile ledger: one
  registry of watched jitted functions (shared with the sanitize
  CT030-32 tripwire), per-chunk compile windows into the flight
  recorder and metrics, and the armable steady-state retrace tripwire.
- :mod:`corrosion_tpu.obs.trajectory` — the committed
  ``BENCH_r*``/``MULTICHIP_r*`` artifacts as one provenance-checked
  series (``corro-bench-trajectory/1``) that refuses cross-platform/
  kernel deltas.
- :mod:`corrosion_tpu.obs.epidemic` — the propagation-topology
  analyzer (``corro-epidemic/1``): coverage curves S(t) reconstructed
  from the rumor-age histogram, the SI/logistic spread-exponent fit vs
  push-gossip theory, region-pair traffic shares, redundancy, the
  traffic-model and host-oracle cross-validations, and the
  ``EPIDEMIC_BASELINE`` diff gate.
- :mod:`corrosion_tpu.obs.series` — the endurance plane's temporal
  half: periodic whole-registry snapshots (counters/gauges/histogram
  bucket vectors) streamed to a rotating ``corro-metric-series/1``
  JSONL, installable in the agent runtime loop and at KernelTelemetry
  chunk boundaries (byte-deterministic under a clock-less recorder).
- :mod:`corrosion_tpu.obs.endurance` — the detectors over recorded
  series (``corro-endurance/1``): Theil–Sen leak slopes in units/hour,
  counter-reset classification (restart/wraparound/decrease), wedge and
  loop-lag stall runs, multi-window SLO burn rates, plus the soak
  report diff and the bench_budget ``soak`` gate with its
  machinery-fired rule.
- :mod:`corrosion_tpu.obs.metrics_ref` — the metrics-name drift check:
  the documented ``corro_*`` series table vs every name the codebase
  can register (static literals + the dynamic kernel publishers).
- :mod:`corrosion_tpu.obs.commands` — the CLI entrypoints
  (``obs report|tail|diff|record|epidemic|timeline|cost|trajectory|soak``).

Everything host-side; ``journey``/``commands`` import jax transitively
through ``sim`` (``costs``/``ledger`` import jax directly),
``timeline``/``trajectory``/``series``/``endurance`` do not.
"""

from corrosion_tpu.obs.timeline import (  # noqa: F401
    TIMELINE_SCHEMA,
    build_timeline,
    load_spans,
    timeline_from_run,
)
