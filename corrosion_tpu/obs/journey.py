"""Kernel-plane write-journey reconstruction — no new traced code.

The kernel engines already emit everything needed to answer "where did
this write's simulated latency go" — they just emit it aggregated: the
per-round delivery-latency histogram (``vis_lat_b0..bN``, pairs that
became visible this round bucketed by commit-to-visible rounds), the
pending-queue backlog mass (``queue_backlog``), and the per-round
traffic (``msgs``). A recorded ``sim/trace.py`` workload says exactly
which writes committed in which round. This module inverts the two into
a per-write view:

- **commit round**: the write's trace-time bucket (the same arithmetic
  ``schedule_from_trace`` uses, so replayed schedules and reconstructed
  journeys can never disagree on round placement);
- **delivery-round profile**: visibility events at flight round ``t``
  in latency bucket ``b`` came from commits in rounds
  ``(t - hi_edge, t - lo_edge]``; each event mass is attributed across
  that window proportionally to how many writes the trace committed in
  each candidate round, then divided per write. Writes get an expected
  delivery count and a delivery-round distribution — a distributional
  reconstruction, exact in aggregate (the reconciliation total pins
  attributed + unattributed == observed events);
- **queue-dwell estimate**: Little's-law rounds of pending-queue
  residence at the write's commit round
  (``queue_backlog[r] / max(msgs[r], 1)``).

Unattributable mass (events whose whole commit window precedes the
trace, e.g. warm-up traffic) is reported, never silently dropped.
"""

from __future__ import annotations

JOURNEY_SCHEMA = "corro-write-journey/1"


def _trace_commit_rounds(trace, round_ms: float):
    """Per-event commit rounds + per-round write counts, using the exact
    ``schedule_from_trace`` bucketing arithmetic."""
    if not trace.events:
        raise ValueError("empty trace")
    if not round_ms > 0.0:
        raise ValueError(f"round_ms must be positive, got {round_ms}")
    events = sorted(trace.events)
    t0 = events[0][0]
    per_event = [
        (a, v, int((t - t0) // round_ms)) for t, a, v in events
    ]
    counts: dict[int, int] = {}
    for _a, _v, r in per_event:
        counts[r] = counts.get(r, 0) + 1
    return per_event, counts


def reconstruct_write_journeys(
    flight_path: str, trace, round_ms: float = 500.0,
    max_writes: int | None = None,
) -> dict:
    """Join a flight JSONL with a recorded write trace into the
    ``corro-write-journey/1`` dict. ``trace`` is a
    :class:`corrosion_tpu.sim.trace.Trace` (or anything with a
    compatible ``events`` list)."""
    from corrosion_tpu.sim.telemetry import (
        VIS_LAT_EDGES,
        VIS_LAT_KEYS,
        replay_flight,
    )

    curves, _chunks = replay_flight(flight_path)
    rounds = [int(r) for r in curves.get("round", [])]
    per_event, commit_counts = _trace_commit_rounds(trace, round_ms)

    def col(key):
        arr = curves.get(key)
        return (
            {r: float(arr[i]) for i, r in enumerate(rounds)}
            if arr is not None else {}
        )

    vis = {k: col(k) for k in VIS_LAT_KEYS if k in curves}
    backlog = col("queue_backlog")
    msgs = col("msgs")

    # Latency-bucket windows in rounds: bucket b covers commit-to-
    # visible latencies (lo_excl, hi] — b0 additionally admits latency 0
    # (visible the commit round itself).
    windows = []
    for b, _k in enumerate(VIS_LAT_KEYS):
        lo = 0 if b == 0 else VIS_LAT_EDGES[b - 1]
        hi = (
            VIS_LAT_EDGES[b]
            if b < len(VIS_LAT_EDGES)
            # Overflow: anything older, bounded by the record length.
            else (rounds[-1] + 1 if rounds else 0)
        )
        windows.append((lo, hi, b == 0))

    # Attribute each (visible-round, bucket) event mass across its
    # commit-round window, weighted by the trace's per-round write
    # counts. profile[c][t] = expected deliveries at round t for ALL
    # writes committed in round c.
    profile: dict[int, dict[int, float]] = {}
    total_events = attributed = 0.0
    for b, key in enumerate(VIS_LAT_KEYS):
        series = vis.get(key)
        if not series:
            continue
        lo, hi, incl_zero = windows[b]
        for t, count in series.items():
            if count <= 0:
                continue
            total_events += count
            c_lo = t - hi
            c_hi = t if incl_zero else t - lo - 1
            window = [
                c for c in range(c_lo, c_hi + 1) if commit_counts.get(c)
            ]
            weight = sum(commit_counts[c] for c in window)
            if weight <= 0:
                continue  # unattributable (pre-trace traffic)
            attributed += count
            for c in window:
                share = count * commit_counts[c] / weight
                profile.setdefault(c, {})[t] = (
                    profile.get(c, {}).get(t, 0.0) + share
                )

    writes_out = []
    for a, v, r in per_event[:max_writes] if max_writes else per_event:
        prof = profile.get(r, {})
        n_at_r = commit_counts[r]
        exp = sum(prof.values()) / n_at_r
        dist = {
            int(t): round(m / n_at_r, 4) for t, m in sorted(prof.items())
        }
        lat_mean = (
            sum((t - r) * m for t, m in prof.items())
            / sum(prof.values())
            if prof else None
        )
        writes_out.append({
            "actor": a[:8],
            "version": v,
            "commit_round": r,
            "expected_deliveries": round(exp, 4),
            "delivery_rounds": dist,
            "latency_rounds_mean": (
                round(lat_mean, 3) if lat_mean is not None else None
            ),
            "queue_dwell_rounds": round(
                backlog.get(r, 0.0) / max(msgs.get(r, 0.0), 1.0), 3
            ),
        })

    return {
        "schema": JOURNEY_SCHEMA,
        "round_ms": round_ms,
        "flight_rounds": len(rounds),
        "trace_writes": len(per_event),
        "writes": writes_out,
        "totals": {
            "vis_events": total_events,
            "attributed": round(attributed, 6),
            "unattributed": round(total_events - attributed, 6),
            "attribution_fraction": round(
                attributed / total_events, 5
            ) if total_events else None,
        },
    }
