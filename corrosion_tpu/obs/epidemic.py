"""Epidemic-model analyzer: propagation-plane curves → SI-fit verdicts.

The on-device propagation-topology plane (sim/telemetry.py
``PROP_CURVE_KEYS``, emitted by every engine's scan body when
``prop_observe`` is set) records the epidemic's *structure* per round:
which region pairs carried the broadcast load (``link_ij``), how many
delivered copies were productive vs redundant (``prop_useful_msgs`` /
``prop_dup_msgs``), and a rumor-age histogram — rounds-since-commit at
first delivery per tracked pair, on the fine ``RUMOR_AGE_EDGES``
buckets. This module is the host side (``corro-epidemic/1``):

- **Coverage curve S(t)**: the rumor-age histogram summed over the run
  IS the derivative of the commit-aligned mean coverage curve — bucket
  b counts pairs first reached at age ``edges[b-1] < t <= edges[b]``,
  so the bucket CDF is the fraction of pairs covered by age t. No
  per-write bookkeeping needed; the flight JSONL alone suffices.
- **SI / logistic fit**: push gossip with fanout F follows the SI model
  S(t) = N / (1 + (N - 1) e^(-beta t)) (Demers et al.; SURVEY
  §broadcast), i.e. logit(S/N) is LINEAR in t with slope beta. The fit
  regresses logit(CDF) on the bucket edges and reports the measured
  spread exponent, half-coverage age, and r² against the push-gossip
  prediction beta = ln(1 + F) for the config's fanout.
- **Traffic structure**: per-region-pair shares, same- vs cross-region
  split, ring-resolved shares under the synthetic geo geography, and
  the wasted-push (redundancy) ratio.
- **Conservation checks**: Σ link matrix == ``msgs`` and Σ rumor
  buckets == ``vis_count`` per round, ``useful + dup == msgs`` — the
  on-device accounting must partition exactly or the report refuses to
  stand (``checks_ok``).
- **Cross-validation**: :func:`xshard_model_check` pins a sharded run's
  measured exchange bytes against ``parallel.shard_driver.
  traffic_model`` per round, and :func:`oracle_coverage` builds the
  same age histogram from the HOST plane's loadgen oracle delivery
  records (wall-clock ages ÷ round length) so a mixed-mode run can
  compare kernel and live spread curves on one bucket axis
  (docs/FIDELITY.md).

``diff_reports`` flags regressions between two reports with BENCH-style
tolerances — the ``obs epidemic diff`` CI gate against the committed
``EPIDEMIC_BASELINE.json``.
"""

from __future__ import annotations

import json
import math

import numpy as np

from corrosion_tpu.sim.telemetry import (
    LINK_CURVE_KEYS,
    PROP_REGIONS,
    RUMOR_AGE_EDGES,
    RUMOR_AGE_KEYS,
    XSHARD_CURVE_KEYS,
    curve_array,
)

EPIDEMIC_SCHEMA = "corro-epidemic/1"

# Default fanout for theory comparison when the caller doesn't pass the
# config's: the reference-shaped 2 near + 2 far.
DEFAULT_FANOUT = 4


# Shared zero-fill curve accessor (telemetry.curve_array) — one fallback
# convention with sim/health.py's analyzers.
_arr = curve_array


def rumor_age_histogram(curves: dict) -> np.ndarray:
    """Run-total first-delivery counts per rumor-age bucket
    (len(RUMOR_AGE_KEYS); the last bucket is the overflow past the
    final edge)."""
    return np.asarray(
        [_arr(curves, k).sum() for k in RUMOR_AGE_KEYS], dtype=np.float64
    )


def link_matrix(curves: dict) -> np.ndarray:
    """Run-total [PROP_REGIONS, PROP_REGIONS] delivered-copies matrix
    (receiver region row, source region column)."""
    m = np.zeros((PROP_REGIONS, PROP_REGIONS), dtype=np.float64)
    for k in LINK_CURVE_KEYS:
        i, j = int(k[-2]), int(k[-1])
        m[i, j] = _arr(curves, k).sum()
    return m


def conservation_checks(curves: dict) -> tuple[bool, list[str]]:
    """The on-device accounting identities, per round: the link matrix's
    mass equals ``msgs``, the rumor buckets' mass equals ``vis_count``,
    and ``useful + dup == msgs``. A violation means the instrument is
    broken (or the flight predates the plane) — the report must not
    publish numbers it cannot reconcile."""
    problems: list[str] = []
    msgs = _arr(curves, "msgs")
    link = sum(_arr(curves, k) for k in LINK_CURVE_KEYS)
    if not np.array_equal(link, msgs):
        bad = int(np.sum(link != msgs))
        problems.append(
            f"link-matrix mass != msgs on {bad} round(s): the traffic "
            f"matrix must partition the delivered copies exactly"
        )
    rumor = sum(_arr(curves, k) for k in RUMOR_AGE_KEYS)
    vis = _arr(curves, "vis_count")
    if not np.array_equal(rumor, vis):
        bad = int(np.sum(rumor != vis))
        problems.append(
            f"rumor-age mass != vis_count on {bad} round(s): every first "
            f"delivery must land in exactly one age bucket"
        )
    useful = _arr(curves, "prop_useful_msgs")
    dup = _arr(curves, "prop_dup_msgs")
    if not np.array_equal(useful + dup, msgs):
        bad = int(np.sum(useful + dup != msgs))
        problems.append(
            f"useful + dup != msgs on {bad} round(s): the effective-"
            f"fanout split must partition the delivered copies"
        )
    return not problems, problems


def coverage_points(hist: np.ndarray) -> list[tuple[float, float]]:
    """(age upper edge, cumulative coverage fraction) per finite bucket
    — the reconstructed S(t)/N sampled at the bucket edges. The
    overflow bucket has no finite edge and is excluded (it still counts
    in the total, so its mass depresses the finite CDF — honest:
    never-finishing spread shows up as a curve that plateaus < 1)."""
    total = float(hist.sum())
    if total <= 0:
        return []
    cdf = np.cumsum(hist) / total
    return [
        (float(e), float(cdf[b])) for b, e in enumerate(RUMOR_AGE_EDGES)
    ]


def fit_si(points: list[tuple[float, float]]) -> dict:
    """Least-squares logit fit of the SI/logistic model to the coverage
    points: logit(S_frac) = intercept + beta * t. Points at 0 or 1
    carry no logit information and are dropped; with fewer than two
    interior points the fit abstains (``fitted: false``) rather than
    extrapolating from a degenerate curve.

    Returns measured ``spread_exponent`` (beta, per round),
    ``half_coverage_round`` (the fitted t where S = N/2), ``r2``, and
    the (t, frac, logit) triples used.
    """
    interior = [
        (t, f) for t, f in points if 1e-9 < f < 1.0 - 1e-9
    ]
    if len(interior) < 2:
        return {
            "fitted": False,
            "spread_exponent": None,
            "half_coverage_round": None,
            "r2": None,
            "points": [
                {"age": t, "coverage": f} for t, f in points
            ],
        }
    x = np.asarray([t for t, _ in interior], dtype=np.float64)
    y = np.asarray(
        [math.log(f / (1.0 - f)) for _, f in interior], dtype=np.float64
    )
    beta, intercept = np.polyfit(x, y, 1)
    pred = intercept + beta * x
    ss_res = float(np.sum((y - pred) ** 2))
    ss_tot = float(np.sum((y - y.mean()) ** 2))
    r2 = 1.0 - ss_res / ss_tot if ss_tot > 0 else 1.0
    half = -intercept / beta if beta != 0 else None
    return {
        "fitted": True,
        "spread_exponent": float(beta),
        "half_coverage_round": None if half is None else float(half),
        "r2": float(r2),
        "points": [
            {
                "age": t,
                "coverage": f,
                "logit": math.log(f / (1.0 - f))
                if 1e-9 < f < 1.0 - 1e-9 else None,
            }
            for t, f in points
        ],
    }


def push_gossip_theory(fanout: int, n_nodes: int | None) -> dict:
    """The SI-model prediction for push gossip with per-round fanout F:
    each informed node pushes F copies per round, so pre-saturation
    growth is (1 + F)^t — spread exponent beta = ln(1 + F) — and the
    logistic half-coverage from a single seed sits at
    t_half = ln(N - 1) / beta. Collisions and redundancy only slow the
    tail, so the measured exponent is expected AT OR BELOW theory;
    WAN rings, loss, and sparse writers push it further down — exactly
    the gap the diff gate watches."""
    beta = math.log(1.0 + fanout)
    return {
        "fanout": fanout,
        "spread_exponent": beta,
        "half_coverage_round": (
            math.log(max(n_nodes - 1, 2)) / beta
            if n_nodes is not None else None
        ),
    }


def geo_rings(n_regions: int) -> np.ndarray:
    """The synthetic circle geography's ring classes per region pair —
    the same arithmetic as ``ops.gossip.make_topology(region_rtt="geo")``
    so ring-resolved traffic shares need no topology file."""
    d = np.abs(
        np.arange(n_regions)[:, None] - np.arange(n_regions)[None, :]
    )
    d = np.minimum(d, n_regions - d)
    max_d = max(int(d.max()), 1)
    return np.ceil(d / max_d * 5).astype(np.int32)


def traffic_structure(curves: dict, geo_regions: int | None = None) -> dict:
    """Per-link traffic shares from the run-total link matrix: the raw
    [R, R] share matrix, the same- vs cross-region split, and — when
    ``geo_regions`` names the geo scenario's region count — per-RTT-ring
    shares under the deterministic circle geography."""
    m = link_matrix(curves)
    total = float(m.sum())
    used = [
        i for i in range(PROP_REGIONS)
        if m[i, :].sum() > 0 or m[:, i].sum() > 0
    ]
    r = (max(used) + 1) if used else 1
    shares = (m / total) if total > 0 else m
    out = {
        "total_copies": total,
        "regions": r,
        "matrix": [
            [float(m[i, j]) for j in range(r)] for i in range(r)
        ],
        "share_matrix": [
            [round(float(shares[i, j]), 6) for j in range(r)]
            for i in range(r)
        ],
        "same_region_share": (
            round(float(np.trace(m) / total), 6) if total > 0 else None
        ),
        "cross_region_share": (
            round(float((total - np.trace(m)) / total), 6)
            if total > 0 else None
        ),
    }
    if geo_regions:
        rings = geo_rings(geo_regions)
        ring_share: dict[int, float] = {}
        for i in range(min(geo_regions, PROP_REGIONS)):
            for j in range(min(geo_regions, PROP_REGIONS)):
                ring_share[int(rings[i, j])] = (
                    ring_share.get(int(rings[i, j]), 0.0) + float(m[i, j])
                )
        out["ring_shares"] = {
            str(k): round(v / total, 6) if total > 0 else 0.0
            for k, v in sorted(ring_share.items())
        }
    return out


def build_report(
    curves: dict,
    engine: str = "unknown",
    fanout: int = DEFAULT_FANOUT,
    nodes: int | None = None,
    round_ms: float = 500.0,
    geo_regions: int | None = None,
) -> dict:
    """The ``corro-epidemic/1`` artifact from per-round curves (any
    engine's output, or a ``replay_flight`` reconstruction)."""
    hist = rumor_age_histogram(curves)
    total = float(hist.sum())
    overflow = float(hist[-1])
    points = coverage_points(hist)
    fit = fit_si(points)
    theory = push_gossip_theory(fanout, nodes)
    msgs = float(_arr(curves, "msgs").sum())
    useful = float(_arr(curves, "prop_useful_msgs").sum())
    dup = float(_arr(curves, "prop_dup_msgs").sum())
    checks_ok, problems = conservation_checks(curves)
    beta = fit.get("spread_exponent")
    return {
        "schema": EPIDEMIC_SCHEMA,
        "engine": engine,
        "rounds": int(len(_arr(curves, "msgs"))),
        "round_ms": round_ms,
        "fanout": fanout,
        "nodes": nodes,
        # Coverage / fit
        "coverage_events": int(total),
        "coverage_overflow_events": int(overflow),
        "coverage_overflow_frac": (
            round(overflow / total, 6) if total > 0 else None
        ),
        "rumor_age_hist": hist.astype(np.int64).tolist(),
        "rumor_age_edges": list(RUMOR_AGE_EDGES),
        "fit": fit,
        "spread_exponent": beta,
        "half_coverage_round": fit.get("half_coverage_round"),
        "fit_r2": fit.get("r2"),
        "theory": theory,
        "spread_vs_theory": (
            round(beta / theory["spread_exponent"], 6)
            if beta is not None else None
        ),
        # Effective fanout / redundancy
        "msgs_total": msgs,
        "useful_msgs_total": useful,
        "dup_msgs_total": dup,
        "redundancy_ratio": round(dup / msgs, 6) if msgs > 0 else None,
        "effective_fanout": (
            round(fanout * useful / msgs, 6) if msgs > 0 else None
        ),
        # Traffic topology
        "traffic": traffic_structure(curves, geo_regions=geo_regions),
        # Conservation
        "checks_ok": checks_ok,
        "check_problems": problems,
    }


def report_from_flight(
    path: str,
    fanout: int = DEFAULT_FANOUT,
    nodes: int | None = None,
    round_ms: float = 500.0,
    geo_regions: int | None = None,
) -> dict:
    """corro-epidemic/1 from a flight JSONL alone (rotated segments
    included). Raises ValueError when the flight carries no propagation
    keys — the run was recorded with ``prop_observe`` off."""
    from corrosion_tpu.sim.health import flight_header
    from corrosion_tpu.sim.telemetry import replay_flight

    curves, _chunks = replay_flight(path)
    # The canonical schema zero-fills disabled planes, so key presence
    # alone cannot distinguish "plane off" from "plane on, quiet run" —
    # but a record with visibility events and NO rumor-age mass can
    # only be a disabled plane (the per-round conservation identity
    # rumor == vis_count holds whenever the plane ran).
    rumor = sum(_arr(curves, k).sum() for k in RUMOR_AGE_KEYS)
    vis = _arr(curves, "vis_count").sum()
    if rumor == 0 and vis > 0:
        raise ValueError(
            f"{path}: flight has visibility events but no rumor-age "
            f"mass — it was recorded with prop_observe off (obs record "
            f"--geo, or GossipConfig.prop_observe=True)"
        )
    engine = flight_header(path).get("engine", "unknown")
    return build_report(
        curves, engine=engine, fanout=fanout, nodes=nodes,
        round_ms=round_ms, geo_regions=geo_regions,
    )


def load_report(path: str, **kw) -> dict:
    """Load a saved corro-epidemic/1 JSON, or derive one from a flight
    JSONL — the ``obs epidemic diff`` input format."""
    with open(path) as f:
        first = f.readline().strip()
    obj = None
    try:
        obj = json.loads(first)
    except ValueError:
        try:
            with open(path) as f:
                obj = json.load(f)
        except ValueError:
            pass
    if isinstance(obj, dict) and "kind" not in obj:
        if obj.get("schema") != EPIDEMIC_SCHEMA:
            raise ValueError(
                f"{path}: not a flight JSONL or {EPIDEMIC_SCHEMA} report"
            )
        return obj
    return report_from_flight(path, **kw)


def render_report(rep: dict) -> str:
    """Human-readable report (the `obs epidemic report` default)."""
    rm = rep["round_ms"] / 1000.0

    def s(x, fmt="{:g}"):
        return "n/a" if x is None else fmt.format(x)

    fit = rep["fit"]
    th = rep["theory"]
    lines = [
        f"engine={rep['engine']} rounds={rep['rounds']} "
        f"round_ms={rep['round_ms']:g} fanout={rep['fanout']}"
        + (f" nodes={rep['nodes']}" if rep["nodes"] else ""),
        (
            f"spread: beta={s(rep['spread_exponent'], '{:.4f}')}/round "
            f"(theory ln(1+F)={th['spread_exponent']:.4f}, ratio "
            f"{s(rep['spread_vs_theory'], '{:.2f}')}) r2="
            f"{s(rep['fit_r2'], '{:.3f}')}"
            if fit["fitted"]
            else "spread: fit abstained (fewer than 2 interior coverage "
            "points)"
        ),
        f"half-coverage: {s(rep['half_coverage_round'], '{:.1f}')} rounds"
        + (
            f" ({rep['half_coverage_round'] * rm:.1f}s simulated; theory "
            f"{th['half_coverage_round']:.1f} rounds)"
            if rep["half_coverage_round"] is not None
            and th["half_coverage_round"] is not None
            else ""
        ),
        f"coverage: {rep['coverage_events']} first deliveries, "
        f"overflow>{RUMOR_AGE_EDGES[-1]} rounds: "
        f"{rep['coverage_overflow_events']} "
        f"({s(rep['coverage_overflow_frac'], '{:.1%}')})",
        f"redundancy: {s(rep['redundancy_ratio'], '{:.1%}')} of "
        f"{rep['msgs_total']:g} copies were wasted pushes "
        f"(effective fanout {s(rep['effective_fanout'], '{:.2f}')} "
        f"of {rep['fanout']})",
    ]
    tr = rep["traffic"]
    if tr["total_copies"] > 0:
        lines.append(
            f"traffic: same-region {tr['same_region_share']:.1%}, "
            f"cross-region {tr['cross_region_share']:.1%} over "
            f"{tr['regions']} region(s)"
        )
        if "ring_shares" in tr:
            lines.append(
                "  ring shares: " + " ".join(
                    f"ring{k}:{v:.1%}" for k, v in tr["ring_shares"].items()
                )
            )
    lines.append(
        "accounting: OK" if rep["checks_ok"]
        else "accounting: BROKEN — " + "; ".join(rep["check_problems"])
    )
    return "\n".join(lines)


# Metrics compared by `obs epidemic diff`: (field, larger-is-worse,
# absolute slack added to the relative tolerance band).
DIFF_METRICS = (
    # Slower spread = regression (smaller beta is worse).
    ("spread_exponent", False, 0.02),
    ("half_coverage_round", True, 1.0),
    # Redundancy gates through its monotone twin: effective_fanout =
    # F * useful / msgs. A redundancy fraction sitting near 1 (the
    # saturated steady state) has no relative headroom to regress
    # within, while the useful fraction scales cleanly.
    ("effective_fanout", False, 0.02),
    ("coverage_overflow_frac", True, 0.01),
    ("fit_r2", False, 0.05),
)


def diff_reports(base: dict, cand: dict, tolerance: float = 0.25) -> dict:
    """BENCH-style regression diff between two corro-epidemic/1 reports.

    A candidate whose accounting checks fail, or whose fit abstains
    where the baseline's fitted, is always a regression — tolerance
    never scales a broken instrument into passing."""
    rows = []
    regressions = []
    if not cand.get("checks_ok", False):
        regressions.append(
            "candidate conservation checks failed: "
            + "; ".join(cand.get("check_problems", ["(no detail)"]))
        )
    if base.get("fit", {}).get("fitted") and not cand.get("fit", {}).get(
        "fitted"
    ):
        regressions.append(
            "candidate SI fit abstained (baseline fitted) — the spread "
            "curve lost its interior"
        )
    for name, larger_worse, slack in DIFF_METRICS:
        a, b = base.get(name), cand.get(name)
        row = {"metric": name, "baseline": a, "candidate": b, "ok": True}
        if a is not None and b is not None:
            af, bf = float(a), float(b)
            if larger_worse:
                worse = bf > af * (1.0 + tolerance) + slack
            else:
                worse = bf < af * (1.0 - tolerance) - slack
            if worse:
                row["ok"] = False
                regressions.append(
                    f"{name}: {b} vs baseline {a} "
                    f"(tolerance {tolerance:.0%} + {slack:g})"
                )
        rows.append(row)
    return {"regressions": regressions, "rows": rows}


def publish_epidemic(registry, rep: dict, engine: str | None = None) -> None:
    """Fold the run-level epidemic verdicts into a MetricsRegistry as
    ``corro_kernel_epidemic_*`` gauges (-1 sentinels where the fit
    abstained or no traffic flowed)."""
    eng = engine or rep.get("engine", "unknown")

    def g(name: str, value, help_: str) -> None:
        registry.gauge(
            f"corro_kernel_epidemic_{name}",
            f"epidemic plane: {help_}",
        ).set(-1.0 if value is None else float(value), engine=eng)

    g("spread_exponent", rep.get("spread_exponent"),
      "fitted SI spread exponent, per round (-1 = fit abstained)")
    g("half_coverage_round", rep.get("half_coverage_round"),
      "fitted half-coverage age in rounds (-1 = fit abstained)")
    g("fit_r2", rep.get("fit_r2"), "logit-fit r² (-1 = fit abstained)")
    g("redundancy_ratio", rep.get("redundancy_ratio"),
      "wasted-push fraction of delivered copies (-1 = no traffic)")
    g("coverage_events", rep.get("coverage_events", 0),
      "first deliveries the rumor-age histogram bucketed")


def xshard_model_check(curves: dict, cfg_gossip, mesh) -> tuple[bool, list]:
    """Sharded-run cross-validation: the measured per-round exchange
    bytes must equal ``parallel.shard_driver.traffic_model``'s static
    arithmetic exactly, every round. Returns (ok, problems)."""
    from corrosion_tpu.parallel.shard_driver import traffic_model

    tm = traffic_model(cfg_gossip, mesh)
    problems = []
    for key in XSHARD_CURVE_KEYS:
        got = np.asarray(_arr(curves, key), dtype=np.float64)
        want = float(tm[key])
        if not np.array_equal(got, np.full_like(got, want)):
            problems.append(
                f"{key}: measured {got[got != want][:4].tolist()}... != "
                f"model {want}"
            )
    return not problems, problems


def oracle_coverage(records: dict, round_ms: float = 500.0) -> dict:
    """The host plane's view of the same spread curve: from loadgen
    oracle delivery records (``FanoutOracle.delivery_records`` with
    ``keep_deliveries``), bucket each change event's commit-ack-to-
    delivery wall age (in rounds of ``round_ms``) on the SAME
    ``RUMOR_AGE_EDGES`` axis and fit the SI model — the mixed-mode
    cross-validation path (docs/FIDELITY.md): kernel and live runs of
    one scenario land on one comparable bucket axis."""
    ack_by_key = {
        w["key"]: w.get("t_ack_wall")
        for w in records.get("writes", [])
        if w.get("t_ack_wall") is not None
    }
    hist = np.zeros(len(RUMOR_AGE_KEYS), dtype=np.float64)
    matched = 0
    for d in records.get("deliveries", []):
        if d.get("kind") != "change":
            continue
        ack = ack_by_key.get(d.get("key"))
        t = d.get("t_wall")
        if ack is None or t is None:
            continue
        age_rounds = max(t - ack, 0.0) / (round_ms / 1000.0)
        b = 0
        for e in RUMOR_AGE_EDGES:
            if age_rounds > e:
                b += 1
        hist[b] += 1
        matched += 1
    fit = fit_si(coverage_points(hist))
    return {
        "source": "loadgen-oracle",
        "round_ms": round_ms,
        "events": matched,
        "rumor_age_hist": hist.astype(np.int64).tolist(),
        "fit": fit,
        "spread_exponent": fit.get("spread_exponent"),
        "half_coverage_round": fit.get("half_coverage_round"),
    }
